import numpy as np
import pandas as pd
import pytest

# one namespace for all kernel entry points (module names are shadowed by the
# function re-exports in bqueryd_tpu.ops, so don't import submodules directly)
from bqueryd_tpu import ops as fz
from bqueryd_tpu import ops as gb
from bqueryd_tpu import ops as pred
from bqueryd_tpu.storage import ctable


def taxi_like_df(n=20_000, seed=1):
    rng = np.random.default_rng(seed)
    fare = rng.gamma(2.0, 7.0, n)
    fare[rng.random(n) < 0.01] = np.nan  # exercise NaN skipping
    return pd.DataFrame(
        {
            "VendorID": rng.integers(1, 3, n).astype(np.int64),
            "passenger_count": rng.integers(0, 7, n).astype(np.int64),
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "trip_distance": rng.exponential(3.0, n),
            "fare_amount": fare,
            "total_amount": rng.gamma(2.5, 8.0, n),
        }
    )


# ---------------------------------------------------------------------------
# factorize
# ---------------------------------------------------------------------------

def test_factorize_int_matches_pandas():
    values = np.array([5, 2, 5, 9, 2, 5, -3], dtype=np.int64)
    codes, uniques = fz.factorize(values)
    pd_codes, pd_uniques = pd.factorize(values)
    np.testing.assert_array_equal(codes, pd_codes)
    np.testing.assert_array_equal(uniques, pd_uniques)


def test_factorize_float():
    values = np.array([1.5, 0.5, 1.5, 2.5])
    codes, uniques = fz.factorize(values)
    np.testing.assert_array_equal(uniques[codes], values)
    assert uniques.tolist() == [1.5, 0.5, 2.5]


def test_factorize_device_fixed_capacity():
    import jax.numpy as jnp

    keys = jnp.array([7, 3, 7, 7, 1], dtype=jnp.int64)
    uniques, codes, n = fz.factorize_device(keys, capacity=8)
    assert int(n) == 3
    np.testing.assert_array_equal(np.asarray(uniques)[codes], np.asarray(keys))


def test_pack_unpack_codes_roundtrip():
    rng = np.random.default_rng(3)
    a = rng.integers(0, 5, 100).astype(np.int64)
    b = rng.integers(0, 7, 100).astype(np.int64)
    c = rng.integers(0, 3, 100).astype(np.int64)
    packed = fz.pack_codes([a, b, c], [5, 7, 3])
    ua, ub, uc = fz.unpack_codes(packed, [5, 7, 3])
    np.testing.assert_array_equal(ua, a)
    np.testing.assert_array_equal(ub, b)
    np.testing.assert_array_equal(uc, c)


def test_pack_codes_null_poisons():
    packed = fz.pack_codes(
        [np.array([0, -1, 2]), np.array([1, 1, -1])], [3, 2]
    )
    assert packed.tolist() == [1, -1, -1]


# ---------------------------------------------------------------------------
# groupby kernels vs pandas
# ---------------------------------------------------------------------------

def run_groupby(df, key, measure, op, mask=None):
    codes, uniques = fz.factorize(df[key].to_numpy())
    tables, rows = gb.groupby_aggregate(
        codes,
        (df[measure].to_numpy(),),
        (op,),
        n_groups=len(uniques),
        mask=None if mask is None else np.asarray(mask),
    )
    return uniques, np.asarray(tables[0]), np.asarray(rows)


@pytest.mark.parametrize("op,pandas_op", [
    ("sum", "sum"), ("mean", "mean"), ("count", "count"),
    ("min", "min"), ("max", "max"),
])
def test_groupby_matches_pandas(op, pandas_op):
    df = taxi_like_df()
    uniques, got, _rows = run_groupby(df, "payment_type", "fare_amount", op)
    expected = getattr(df.groupby("payment_type")["fare_amount"], pandas_op)()
    got_series = pd.Series(got, index=uniques).sort_index()
    pd.testing.assert_series_equal(
        got_series, expected.sort_index(), check_names=False,
        check_index_type=False, check_dtype=False,
    )


def test_groupby_int64_sum_bit_exact():
    """North-star criterion: int64 sums agree bit-for-bit with a CPU
    reference (numpy bincount accumulation)."""
    rng = np.random.default_rng(11)
    n = 100_000
    keys = rng.integers(0, 50, n).astype(np.int64)
    # large values to exercise 64-bit range (sums far beyond int32)
    values = rng.integers(-(2**40), 2**40, n).astype(np.int64)
    codes, uniques = fz.factorize(keys)
    tables, _ = gb.groupby_aggregate(codes, (values,), ("sum",), len(uniques))
    got = np.asarray(tables[0])
    expected = np.zeros(len(uniques), dtype=np.int64)
    np.add.at(expected, codes, values)
    assert got.dtype == np.int64
    np.testing.assert_array_equal(got, expected)


def _groupby_module():
    # module names are shadowed by the function re-exports in bqueryd_tpu.ops
    import sys

    import bqueryd_tpu.ops.groupby  # noqa: F401

    return sys.modules["bqueryd_tpu.ops.groupby"]


def test_highcard_bench_shape_stays_on_blocked_path():
    """Pin the chosen kernel route for BASELINE config 5 (10 M rows x 70,225
    groups): with the 64 Ki scatter blocks the bucket count stays inside
    ``_MAX_BLOCK_SEGMENTS``, so the exact int32 blocked scatter — not the
    emulated-s64 fallback that cost ~3 s in round 3 — handles it."""
    m = _groupby_module()
    n_blocks = -(-10_000_000 // m._SUM_BLOCK)
    assert n_blocks * 70_225 <= m._MAX_BLOCK_SEGMENTS


def test_groupby_highcard_int64_sum_bit_exact():
    """>=64k groups on the blocked-scatter path, full int64 range (block
    limb sums exercise the mod-2^32 wrap recovery)."""
    rng = np.random.default_rng(5)
    n, n_groups = 300_000, 70_000
    codes = rng.integers(0, n_groups, n).astype(np.int32)
    info = np.iinfo(np.int64)
    values = rng.integers(info.min // 4, info.max // 4, n).astype(np.int64)
    values[:100] = info.max
    values[100:200] = info.min
    m = _groupby_module()
    assert -(-n // m._SUM_BLOCK) * n_groups <= m._MAX_BLOCK_SEGMENTS
    tables, _ = gb.groupby_aggregate(codes, (values,), ("sum",), n_groups)
    expected = np.zeros(n_groups, dtype=np.int64)
    with np.errstate(over="ignore"):
        np.add.at(expected, codes, values)
    np.testing.assert_array_equal(np.asarray(tables[0]), expected)


def test_groupby_uint16_blocked_wrap_recovery(monkeypatch):
    """A 64 Ki block of max uint16 values sums to 2^32 - 2^16: the int32
    scatter wraps negative and the uint32 bitcast must recover it exactly.
    The MXU route is disabled so the blocked scatter actually runs (at
    n_groups=1 the matmul path would otherwise absorb this case)."""
    monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", "0")
    n = 70_000  # > one block
    codes = np.zeros(n, dtype=np.int32)
    values = np.full(n, np.iinfo(np.uint16).max, dtype=np.uint16)
    tables, _ = gb.groupby_aggregate(codes, (values,), ("sum",), 1)
    assert int(np.asarray(tables[0])[0]) == n * 65535


def test_sorted_segment_sum_bit_exact():
    """The extreme-cardinality sort-based path, directly and via the public
    API (forced by shrinking the bucket budget)."""
    import jax.numpy as jnp

    m = _groupby_module()
    rng = np.random.default_rng(17)
    n, n_groups = 50_000, 4_096
    codes = rng.integers(0, n_groups, n).astype(np.int32)
    info = np.iinfo(np.int64)
    values = rng.integers(info.min // 2, info.max // 2, n).astype(np.int64)
    expected = np.zeros(n_groups, dtype=np.int64)
    with np.errstate(over="ignore"):
        np.add.at(expected, codes, values)
    got = m._sorted_segment_sum(jnp.asarray(values), jnp.asarray(codes), n_groups)
    np.testing.assert_array_equal(np.asarray(got), expected)


def test_int64_segment_sum_routes_to_sorted_past_budget(monkeypatch):
    m = _groupby_module()
    # disable the MXU route (37 groups would otherwise take the matmul path
    # and never reach the scatter/sorted routing being pinned here) and
    # shrink the bucket budget so the sorted path must serve the query
    monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", "0")
    monkeypatch.setattr(m, "_MAX_BLOCK_SEGMENTS", 0)
    rng = np.random.default_rng(23)
    n, n_groups = 9_973, 37  # unique shape: avoids a stale jit cache entry
    codes = rng.integers(0, n_groups, n).astype(np.int32)
    values = rng.integers(-(2**60), 2**60, n).astype(np.int64)
    tables, rows = gb.groupby_aggregate(codes, (values,), ("sum",), n_groups)
    expected = np.zeros(n_groups, dtype=np.int64)
    np.add.at(expected, codes, values)
    np.testing.assert_array_equal(np.asarray(tables[0]), expected)
    np.testing.assert_array_equal(
        np.asarray(rows), np.bincount(codes, minlength=n_groups)
    )


def test_groupby_count_na():
    df = taxi_like_df()
    uniques, got, _ = run_groupby(df, "payment_type", "fare_amount", "count_na")
    expected = df["fare_amount"].isna().groupby(df["payment_type"]).sum()
    got_series = pd.Series(got, index=uniques).sort_index()
    pd.testing.assert_series_equal(
        got_series, expected.sort_index(), check_names=False,
        check_index_type=False, check_dtype=False,
    )


def test_groupby_multikey_via_packed_codes():
    df = taxi_like_df()
    c1, u1 = fz.factorize(df["VendorID"].to_numpy())
    c2, u2 = fz.factorize(df["payment_type"].to_numpy())
    packed = fz.pack_codes([c1, c2], [len(u1), len(u2)])
    dense, combos = fz.factorize(packed)
    tables, rows = gb.groupby_aggregate(
        dense, (df["total_amount"].to_numpy(),), ("sum",), len(combos)
    )
    got = {}
    for combo, value in zip(combos, np.asarray(tables[0])):
        i1, i2 = divmod(int(combo), len(u2))
        got[(u1[i1], u2[i2])] = value
    expected = df.groupby(["VendorID", "payment_type"])["total_amount"].sum()
    assert set(got) == set(expected.index)
    for key, value in expected.items():
        assert got[key] == pytest.approx(value)


def test_groupby_mask_pushdown_matches_filtered_pandas():
    df = taxi_like_df()
    mask = (df["trip_distance"] > 5.0).to_numpy()
    uniques, got, rows = run_groupby(df, "payment_type", "total_amount", "sum", mask)
    expected = df[mask].groupby("payment_type")["total_amount"].sum()
    got_series = pd.Series(got, index=uniques)[rows > 0].sort_index()
    pd.testing.assert_series_equal(
        got_series, expected.sort_index(), check_names=False,
        check_index_type=False, check_dtype=False,
    )


def test_groupby_negative_codes_dropped():
    codes = np.array([0, -1, 1, 0], dtype=np.int32)
    values = np.array([10.0, 99.0, 20.0, 30.0])
    tables, rows = gb.groupby_aggregate(codes, (values,), ("sum",), 2)
    assert np.asarray(tables[0]).tolist() == [40.0, 20.0]
    assert np.asarray(rows).tolist() == [2, 1]


def test_partials_merge_equals_full():
    """Merging per-shard partials must equal the unsharded result — the
    invariant the psum merge relies on (shard-vs-full equivalence, reference
    tests/test_simple_rpc.py:175-190)."""
    df = taxi_like_df(n=9_000)
    shards = [df.iloc[i::3] for i in range(3)]
    key_uniques = np.unique(df["payment_type"].to_numpy())
    n_groups = len(key_uniques)
    ops = ("sum", "mean", "count", "min", "max")

    def shard_partials(part):
        codes = np.searchsorted(key_uniques, part["payment_type"].to_numpy())
        measures = tuple(part["fare_amount"].to_numpy() for _ in ops)
        return gb.partial_tables(
            codes.astype(np.int32), measures, ops, n_groups
        )

    merged = shard_partials(shards[0])
    for s in shards[1:]:
        merged = gb.combine_partials(merged, shard_partials(s))
    merged_tables = gb.finalize(merged, ops)

    full = shard_partials(df)
    full_tables = gb.finalize(full, ops)
    for m, f in zip(merged_tables, full_tables):
        np.testing.assert_allclose(np.asarray(m), np.asarray(f), rtol=1e-12)


def test_weighted_mean_not_sum_of_means():
    """The reference merges shard means by summing them (reference
    bqueryd/rpc.py:171); the partial representation must produce the true
    weighted mean instead."""
    a = pd.DataFrame({"k": [1, 1, 1], "v": [1.0, 1.0, 1.0]})   # mean 1, n=3
    b = pd.DataFrame({"k": [1], "v": [5.0]})                    # mean 5, n=1
    ops = ("mean",)

    def partials(df):
        codes = np.zeros(len(df), dtype=np.int32)
        return gb.partial_tables(codes, (df["v"].to_numpy(),), ops, 1)

    merged = gb.combine_partials(partials(a), partials(b))
    mean = float(gb.finalize(merged, ops)[0][0])
    assert mean == pytest.approx(2.0)      # (3*1 + 5)/4, NOT 1+5=6


def test_count_distinct_matches_pandas():
    df = taxi_like_df()
    gcodes, guniques = fz.factorize(df["payment_type"].to_numpy())
    vcodes, vuniques = fz.factorize(df["passenger_count"].to_numpy())
    got = gb.groupby_count_distinct(
        gcodes, vcodes, n_groups=len(guniques), n_values=len(vuniques)
    )
    expected = df.groupby("payment_type")["passenger_count"].nunique()
    got_series = pd.Series(np.asarray(got), index=guniques).sort_index()
    pd.testing.assert_series_equal(
        got_series, expected.sort_index(), check_names=False,
        check_index_type=False, check_dtype=False,
    )


def test_sorted_count_distinct_on_sorted_data():
    df = taxi_like_df().sort_values(["payment_type", "passenger_count"])
    gcodes, guniques = fz.factorize(df["payment_type"].to_numpy())
    got = gb.groupby_sorted_count_distinct(
        gcodes, df["passenger_count"].to_numpy(), n_groups=len(guniques)
    )
    expected = df.groupby("payment_type")["passenger_count"].nunique()
    got_series = pd.Series(np.asarray(got), index=guniques).sort_index()
    pd.testing.assert_series_equal(
        got_series, expected.sort_index(), check_names=False,
        check_index_type=False, check_dtype=False,
    )


# ---------------------------------------------------------------------------
# predicates
# ---------------------------------------------------------------------------

@pytest.fixture
def taxi_table(tmp_path):
    df = taxi_like_df(n=5_000)
    df["store_and_fwd_flag"] = np.where(df["VendorID"] == 1, "Y", "N")
    root = str(tmp_path / "taxi.bcolz")
    ctable.fromdataframe(df, root)
    return df, ctable(root, mode="r")


@pytest.mark.parametrize("term,pandas_expr", [
    (("trip_distance", ">", 5.0), lambda d: d.trip_distance > 5.0),
    (("trip_distance", "<=", 1.0), lambda d: d.trip_distance <= 1.0),
    (("payment_type", "==", 2), lambda d: d.payment_type == 2),
    (("payment_type", "!=", 2), lambda d: d.payment_type != 2),
    (("payment_type", "in", [1, 3]), lambda d: d.payment_type.isin([1, 3])),
    (("payment_type", "not in", [1, 3]), lambda d: ~d.payment_type.isin([1, 3])),
    (("store_and_fwd_flag", "==", "Y"), lambda d: d.store_and_fwd_flag == "Y"),
])
def test_term_masks_match_pandas(taxi_table, term, pandas_expr):
    df, table = taxi_table
    mask = pred.build_mask(table, [term])
    np.testing.assert_array_equal(np.asarray(mask), pandas_expr(df).to_numpy())


def test_multi_term_conjunction(taxi_table):
    df, table = taxi_table
    mask = pred.build_mask(
        table, [("trip_distance", ">", 2.0), ("payment_type", "==", 1)]
    )
    expected = (df.trip_distance > 2.0) & (df.payment_type == 1)
    np.testing.assert_array_equal(np.asarray(mask), expected.to_numpy())


def test_unknown_dict_value_semantics(taxi_table):
    _df, table = taxi_table
    assert not np.asarray(
        pred.build_mask(table, [("store_and_fwd_flag", "==", "MISSING")])
    ).any()
    assert np.asarray(
        pred.build_mask(table, [("store_and_fwd_flag", "!=", "MISSING")])
    ).all()


def test_empty_terms_is_none(taxi_table):
    _df, table = taxi_table
    assert pred.build_mask(table, []) is None


def test_shard_can_match_pruning(taxi_table):
    _df, table = taxi_table
    # trip_distance >= 0 always; a > max(col) filter can never match
    hi = table.col_stats("trip_distance")[1]
    assert not pred.shard_can_match(table, [("trip_distance", ">", hi + 1)])
    assert pred.shard_can_match(table, [("trip_distance", ">", hi - 1)])
    assert not pred.shard_can_match(table, [("payment_type", "==", 99)])
    assert not pred.shard_can_match(
        table, [("store_and_fwd_flag", "==", "MISSING")]
    )
    assert pred.shard_can_match(table, [("store_and_fwd_flag", "==", "Y")])


def test_sorted_count_distinct_masked_run_leader():
    """A mask dropping the first row of a run must not hide the run
    (regression: boundary detection vs previous *valid* row)."""
    codes = np.array([0, 0], dtype=np.int32)
    values = np.array([5.0, 5.0])
    got = gb.groupby_sorted_count_distinct(
        codes, values, n_groups=1, mask=np.array([False, True])
    )
    assert int(got[0]) == 1


def test_unpack_codes_preserves_null():
    out = fz.unpack_codes(np.array([-1, 3]), [3, 2])
    assert out[0].tolist() == [-1, 1]
    assert out[1].tolist() == [-1, 1]


def test_in_with_set_on_numeric_column(tmp_path):
    df = pd.DataFrame({"payment_type": np.array([1, 2, 3, 4], dtype=np.int64)})
    root = str(tmp_path / "t.bcolz")
    ctable.fromdataframe(df, root)
    table = ctable(root, mode="r")
    mask = pred.build_mask(table, [("payment_type", "in", {1, 3})])
    assert np.asarray(mask).tolist() == [True, False, True, False]


def test_min_preserves_true_negative_infinity():
    codes = np.array([0, 0], dtype=np.int32)
    values = np.array([-np.inf, 1.0])
    (table,), rows = gb.groupby_aggregate(codes, (values,), ("min",), 1)
    assert np.isneginf(np.asarray(table)[0])


def test_nat_does_not_poison_datetime_stats(tmp_path):
    ts = pd.Series(pd.to_datetime(["2016-01-02", None, "2016-01-05"]))
    root = str(tmp_path / "t.bcolz")
    ctable.fromdataframe(pd.DataFrame({"t": ts}), root)
    table = ctable(root, mode="r")
    lo, hi = table.col_stats("t")
    assert lo == pd.Timestamp("2016-01-02").value
    assert hi == pd.Timestamp("2016-01-05").value


# ---------------------------------------------------------------------------
# host kernel (latency-aware routing)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op", ["sum", "mean", "count", "count_na", "min", "max"])
def test_host_partial_tables_matches_device(op):
    """ops.host_partial_tables is the numpy twin of partial_tables: same
    pytree, bit-exact ints, matching floats — the property that makes the
    latency-aware host route interchangeable with the device path."""
    import jax

    rng = np.random.default_rng(41)
    n, g = 30_000, 19
    codes = rng.integers(-1, g, n).astype(np.int32)
    mask = rng.random(n) < 0.85
    if op in ("count_na",):
        vals = (rng.random(n) * 100).astype(np.float64)
        vals[rng.random(n) < 0.04] = np.nan
    else:
        vals = rng.integers(-(2**60), 2**60, n).astype(np.int64)
    host = gb.host_partial_tables(codes, (vals,), (op,), g, mask=mask)
    dev = jax.device_get(gb.partial_tables(codes, (vals,), (op,), g, mask=mask))
    np.testing.assert_array_equal(host["rows"], dev["rows"])
    for key in dev["aggs"][0]:
        np.testing.assert_array_equal(
            np.asarray(host["aggs"][0][key]), np.asarray(dev["aggs"][0][key]),
            err_msg=f"op={op} partial={key}",
        )


def test_host_partial_tables_float_sum_close():
    import jax

    rng = np.random.default_rng(43)
    n, g = 20_000, 7
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = (rng.random(n) * 100 - 50).astype(np.float32)
    host = gb.host_partial_tables(codes, (vals,), ("mean",), g)
    dev = jax.device_get(gb.partial_tables(codes, (vals,), ("mean",), g))
    np.testing.assert_array_equal(
        host["aggs"][0]["count"], dev["aggs"][0]["count"]
    )
    np.testing.assert_allclose(
        host["aggs"][0]["sum"], dev["aggs"][0]["sum"], rtol=1e-5
    )


def test_float_matmul_split_uses_reduce_precision(monkeypatch):
    """The bf16 Dekker split on the MXU path must round via
    lax.reduce_precision, never an f32->bf16->f32 astype round-trip: on
    TPU the XLA excess-precision pass elides the round-trip, zeroing the
    mid/lo limbs (~0.9% relative error on float sums — caught on real
    hardware, TPU_VALIDATE_r5_prefix.json case5/case10).  The elision
    never happens on the CPU test backend, so pin the structural
    property instead: the traced program of a float-measure matmul
    groupby must contain reduce_precision ops."""
    import jax

    monkeypatch.setenv("BQUERYD_TPU_FORCE_MATMUL", "1")
    g = _groupby_module()
    rng = np.random.default_rng(3)
    n, ng = 4_096, 9
    codes = rng.integers(0, ng, n).astype(np.int32)
    vals = rng.standard_normal(n).astype(np.float32)
    jaxpr = jax.make_jaxpr(
        lambda c, v: g._partial_tables_mm(c, (v,), ("sum",), ng)
    )(codes, vals)
    assert "reduce_precision" in str(jaxpr), (
        "float matmul limbs no longer rounded via reduce_precision; "
        "the TPU excess-precision elision bug can return"
    )
    # and the split is still a lossless representation end-to-end
    out = jax.device_get(g.partial_tables(codes, (vals,), ("sum",), ng))
    expected = np.zeros(ng)
    np.add.at(expected, codes, vals.astype(np.float64))
    np.testing.assert_allclose(
        np.asarray(out["aggs"][0]["sum"], dtype=np.float64),
        expected,
        rtol=2e-6,
    )


def test_host_kernel_rows_env_and_cap(monkeypatch):
    from bqueryd_tpu.models import query as q

    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "12345")
    assert q.host_kernel_rows() == 12345
    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
    assert q.host_kernel_rows() == 0
    monkeypatch.delenv("BQUERYD_TPU_HOST_KERNEL_ROWS")
    monkeypatch.setattr(q, "_measured_floor", 10.0)  # pathological link
    assert q.host_kernel_rows() == q._HOST_ROUTE_CAP


def test_engine_routes_small_queries_to_host(monkeypatch, tmp_path):
    """Below the threshold execute_local must use the host kernel (no
    device dispatch); above, the device path."""
    import pandas as pd

    from bqueryd_tpu import ops as ops_pkg
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine

    df = pd.DataFrame(
        {
            "g": np.arange(500, dtype=np.int64) % 5,
            "v": np.arange(500, dtype=np.int64),
        }
    )
    root = str(tmp_path / "t.bcolz")
    ctable.fromdataframe(df, root)
    table = ctable(root)
    query = GroupByQuery(["g"], [["v", "sum", "s"]], [], aggregate=True)

    calls = {"host": 0}
    real = ops_pkg.host_partial_tables

    def spy(*a, **k):
        calls["host"] += 1
        return real(*a, **k)

    monkeypatch.setattr(ops_pkg, "host_partial_tables", spy)
    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "1000")
    payload_host = QueryEngine().execute_local(table, query)
    assert calls["host"] == 1
    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
    payload_dev = QueryEngine().execute_local(table, query)
    assert calls["host"] == 1  # unchanged: device path taken
    from bqueryd_tpu.parallel import hostmerge

    df_h = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload_host]))
    df_d = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload_dev]))
    pd.testing.assert_frame_equal(
        df_h.sort_values("g").reset_index(drop=True),
        df_d.sort_values("g").reset_index(drop=True),
        check_column_type=False,
    )


def test_matmul_route_auto_disables_on_cpu_backend(monkeypatch):
    """Without the force flag, a CPU backend must take the scatter path
    (the bf16 one-hot matmul emulates ~7x slower there)."""
    m = _groupby_module()
    monkeypatch.delenv("BQUERYD_TPU_FORCE_MATMUL", raising=False)
    assert not m._matmul_profitable(
        (np.ones(64, dtype=np.int64),), ("sum",), 64, 8
    )
    monkeypatch.setenv("BQUERYD_TPU_FORCE_MATMUL", "1")
    assert m._matmul_profitable(
        (np.ones(64, dtype=np.int64),), ("sum",), 64, 8
    )


def test_host_int_sum_fast_path_bit_exact():
    """Small-range int64 sums take the single-bincount fast path; values
    straddling the 2^53 partial-sum bound take the 16-bit-limb fallback.
    Both must equal the python-int ground truth (mod-2^64 semantics)."""
    rng = np.random.default_rng(44)
    n, g = 50_000, 13
    codes = rng.integers(0, g, n).astype(np.int32)
    for lo, hi in [(-20_000, 20_000), (-(2**62), 2**62)]:
        vals = rng.integers(lo, hi, n).astype(np.int64)
        out = gb.host_partial_tables(codes, (vals,), ("sum",), g)
        totals = [0] * g  # python ints: no overflow, wrap applied at the end
        for c, v in zip(codes, vals):
            totals[c] += int(v)
        expect = np.array(
            [(t % (1 << 64)) - (1 << 64) if (t % (1 << 64)) >= (1 << 63)
             else t % (1 << 64) for t in totals],
            dtype=np.int64,
        )
        np.testing.assert_array_equal(
            out["aggs"][0]["sum"], expect, err_msg=f"range=({lo},{hi})",
        )


def test_host_partial_tables_all_valid_fast_path():
    """No mask + no negative codes takes the unweighted-bincount fast path;
    results must match the masked general path run on the same data."""
    rng = np.random.default_rng(45)
    n, g = 40_000, 11
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = rng.integers(-(2**40), 2**40, n).astype(np.int64)
    fast = gb.host_partial_tables(codes, (vals,), ("mean",), g)
    general = gb.host_partial_tables(
        codes, (vals,), ("mean",), g, mask=np.ones(n, dtype=bool)
    )
    np.testing.assert_array_equal(fast["rows"], general["rows"])
    for key in fast["aggs"][0]:
        np.testing.assert_array_equal(
            fast["aggs"][0][key], general["aggs"][0][key]
        )


def test_count_na_int_measure_zero_on_all_paths(monkeypatch):
    """count_na over an integer measure is structurally zero (ints have no
    NaN); the scatter path, the forced-MXU path (zero_count plan — no
    matmul row spent), and the host kernel must all return zeros while
    float count_na still counts NaNs."""
    import jax

    rng = np.random.default_rng(46)
    n, g = 20_000, 7
    codes = rng.integers(-1, g, n).astype(np.int32)
    ivals = rng.integers(0, 100, n).astype(np.int64)
    fvals = rng.random(n).astype(np.float32)
    fvals[rng.random(n) < 0.1] = np.nan

    def run():
        return jax.device_get(
            gb.partial_tables(
                codes, (ivals, fvals), ("count_na", "count_na"), g
            )
        )

    scatter = run()
    monkeypatch.setenv("BQUERYD_TPU_FORCE_MATMUL", "1")
    mm = run()
    host = gb.host_partial_tables(
        codes, (ivals, fvals), ("count_na", "count_na"), g
    )
    for out, label in [(scatter, "scatter"), (mm, "mm"), (host, "host")]:
        np.testing.assert_array_equal(
            np.asarray(out["aggs"][0]["count"]), np.zeros(g, dtype=np.int64),
            err_msg=f"{label}: int count_na must be zero",
        )
        np.testing.assert_array_equal(
            np.asarray(out["aggs"][1]["count"]),
            np.asarray(scatter["aggs"][1]["count"]),
            err_msg=f"{label}: float count_na disagrees",
        )
    assert int(np.asarray(scatter["aggs"][1]["count"]).sum()) > 0


def test_host_ns_estimate_routes_slow_measures(tmp_path):
    """The routing cost estimate reads column metadata only: small-range
    int sums get the fast rate; min/max, stats-less columns, and int sums
    whose n x max|v| bound crosses 2^53 get the ~4x slow rate (so the
    derived row threshold shrinks instead of host-routing into the limb
    fallback)."""
    import os

    from bqueryd_tpu.models import query as qmod
    from bqueryd_tpu.storage.ctable import ctable as CT

    df = pd.DataFrame(
        {
            "small": np.array([1, -5, 9], dtype=np.int64),
            "huge": np.array([2**40, -(2**40), 7], dtype=np.int64),
            "f": np.array([0.5, 1.5, np.nan]),
            "u": np.array([1, 2, 3], dtype=np.uint64),
        }
    )
    root = str(tmp_path / "est.bcolz")
    CT.fromdataframe(df, root)
    ct = CT(root)

    fast = qmod._HOST_NS_PER_ROW
    slow = qmod._HOST_NS_PER_ROW_SLOW
    est = qmod._host_ns_estimate
    from bqueryd_tpu.storage import native as _native

    assert est(ct, [["small", "sum", "s"]], 1_000_000) == fast
    assert est(ct, [["f", "sum", "s"]], 1_000_000) == fast  # float: 1 bincount
    assert est(ct, [["small", "min", "s"]], 1_000) == slow  # ufunc.at
    # 2^40 bound x 150k rows >= 2^53 AND below the native row floor -> the
    # numpy limb fallback would run: slow rate
    assert est(ct, [["huge", "sum", "s"]], 150_000) == slow
    # same column, few rows -> partial sums stay exact, fast path
    assert est(ct, [["huge", "sum", "s"]], 1_000) == fast
    # above the native floor the C++ kernel sums exactly at any magnitude,
    # so the same huge-bound query rates fast (when the lib is built)
    if _native.groupby_available():
        assert est(ct, [["huge", "sum", "s"]], 1_048_576) == fast
    # extrema rate fast only when the DEDICATED min/max kernel will take
    # them; unsigned dtypes decline it (signed i64 accumulator) and must
    # keep the slow ufunc.at rate even above the native row floor
    if _native.groupby_available() and _native.groupby_minmax_available():
        assert est(ct, [["small", "min", "s"]], 1_048_576) == fast
        assert est(ct, [["u", "min", "s"]], 1_048_576) == slow
    # the slow estimate shrinks the derived threshold proportionally
    # (conftest pins BQUERYD_TPU_HOST_KERNEL_ROWS=0 for determinism, so
    # lift it here to exercise the derived-threshold path)
    qmod._measured_floor = 0.016  # low enough that the 4M cap never binds
    env_prior = os.environ.pop("BQUERYD_TPU_HOST_KERNEL_ROWS", None)
    try:
        assert qmod.host_kernel_rows(slow) * 3 < qmod.host_kernel_rows(fast)
    finally:
        qmod._measured_floor = None
        if env_prior is not None:
            os.environ["BQUERYD_TPU_HOST_KERNEL_ROWS"] = env_prior


def test_native_host_groupby_matches_numpy_paths(monkeypatch):
    """The striped C++ host kernels must agree with the numpy paths exactly:
    bit-equal int sums (any magnitude — the native path has no 2^53 bound),
    equal counts, allclose float sums with identical NaN-skip counts."""
    from bqueryd_tpu.storage import native

    if not native.groupby_available():
        pytest.skip("native groupby kernels not built")
    m = _groupby_module()
    rng = np.random.default_rng(48)
    n, g = 300_000, 37
    codes = rng.integers(-1, g, n).astype(np.int32)
    mask = rng.random(n) < 0.9
    ivals = rng.integers(-(2**62), 2**62, n).astype(np.int64)
    fvals = rng.random(n).astype(np.float64) * 100 - 50
    fvals[rng.random(n) < 0.05] = np.nan

    def run():
        return gb.host_partial_tables(
            codes,
            (ivals, fvals, ivals, fvals, ivals, fvals),
            ("sum", "mean", "count", "count_na", "min", "max"),
            g,
            mask=mask,
        )

    assert n >= m._NATIVE_GROUPBY_MIN_ROWS  # native path engages
    native_out = run()
    monkeypatch.setattr(m, "_NATIVE_GROUPBY_MIN_ROWS", n + 1)
    numpy_out = run()

    np.testing.assert_array_equal(native_out["rows"], numpy_out["rows"])
    for ai, (na, npy) in enumerate(
        zip(native_out["aggs"], numpy_out["aggs"])
    ):
        assert set(na) == set(npy), f"agg {ai} partial keys differ"
        for key in na:
            a, b = np.asarray(na[key]), np.asarray(npy[key])
            if a.dtype.kind in "iu":
                np.testing.assert_array_equal(a, b, err_msg=f"{ai}/{key}")
            else:
                np.testing.assert_allclose(
                    a, b, rtol=1e-12, err_msg=f"{ai}/{key}"
                )


def test_native_host_groupby_no_mask_fast_case(monkeypatch):
    """All-valid rows (mask=None, no negative codes) hit the native kernels
    with a null mask pointer; results still match numpy."""
    from bqueryd_tpu.storage import native

    if not native.groupby_available():
        pytest.skip("native groupby kernels not built")
    m = _groupby_module()
    rng = np.random.default_rng(49)
    n, g = 250_000, 11
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = rng.integers(-(2**40), 2**40, n).astype(np.int64)
    native_out = gb.host_partial_tables(codes, (vals,), ("sum",), g)
    monkeypatch.setattr(m, "_NATIVE_GROUPBY_MIN_ROWS", n + 1)
    numpy_out = gb.host_partial_tables(codes, (vals,), ("sum",), g)
    np.testing.assert_array_equal(native_out["rows"], numpy_out["rows"])
    np.testing.assert_array_equal(
        native_out["aggs"][0]["sum"], numpy_out["aggs"][0]["sum"]
    )


def test_native_minmax_unsigned_stays_on_numpy_path():
    """uint64 values above 2^63 would wrap in the signed i64 minmax kernel,
    so unsigned measures must keep the numpy ufunc.at path — results must
    stay correct at native-route row counts."""
    rng = np.random.default_rng(50)
    n, g = 250_000, 7
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = rng.integers(2**62, 2**64 - 1, n, dtype=np.uint64)
    out = gb.host_partial_tables(
        codes, (vals, vals), ("min", "max"), g
    )
    for gi in range(g):
        sel = codes == gi
        assert int(out["aggs"][0]["min"][gi]) == int(vals[sel].min()), gi
        assert int(out["aggs"][1]["max"][gi]) == int(vals[sel].max()), gi


def test_native_minmax_shares_one_pass(monkeypatch):
    """min and max over the SAME measure must issue one native kernel call."""
    from bqueryd_tpu.storage import native

    if not native.groupby_minmax_available():
        pytest.skip("native minmax kernels not built")
    calls = []
    real = native.groupby_minmax

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(native, "groupby_minmax", spy)
    rng = np.random.default_rng(51)
    n, g = 250_000, 5
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = rng.integers(-1000, 1000, n).astype(np.int64)
    out = gb.host_partial_tables(codes, (vals, vals), ("min", "max"), g)
    assert len(calls) == 1, f"expected one shared pass, saw {len(calls)}"
    for gi in range(g):
        sel = codes == gi
        assert int(out["aggs"][0]["min"][gi]) == vals[sel].min()
        assert int(out["aggs"][1]["max"][gi]) == vals[sel].max()


def test_compile_cache_platform_gating(tmp_path):
    """The persistent compile cache stays OFF on explicit CPU platforms
    (XLA:CPU AOT reload logs feature-mismatch errors / SIGILL risk) and an
    explicit path opts in anywhere.  Subprocesses: the config is process-
    wide and latched at ops import."""
    import os
    import subprocess
    import sys

    def probe(extra_env):
        env = dict(os.environ, JAX_PLATFORMS="cpu", **extra_env)
        for leak in (
            "_AXON_REGISTERED",
            "BQUERYD_TPU_PLATFORM",
            "BQUERYD_TPU_COMPILE_CACHE",
        ):
            if leak not in extra_env:
                env.pop(leak, None)
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, jax._src.xla_bridge as xb\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "xb._backend_factories.pop('axon', None)\n"
                "from bqueryd_tpu import ops\n"
                "print(repr(jax.config.jax_compilation_cache_dir))",
            ],
            capture_output=True,
            text=True,
            env=env,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr[-500:]
        return out.stdout.strip().splitlines()[-1]

    assert probe({}) == "None"
    opt_in = str(tmp_path / "cc")
    assert probe({"BQUERYD_TPU_COMPILE_CACHE": opt_in}) == repr(opt_in)


def test_pack_codes_refuses_int64_overflow():
    """A composite key space past 2^63 must raise CompositeOverflow (a
    wrapped radix pack silently merges unrelated groups) — computed in
    python ints so the check itself cannot wrap."""
    from bqueryd_tpu import ops

    small = np.zeros(3, dtype=np.int64)
    with pytest.raises(ops.CompositeOverflow, match="exceeds int64"):
        ops.pack_codes([small] * 4, [3_000_000] * 4)
    # just under the line is fine
    ops.pack_codes([small] * 2, [2**31, 2**31 - 1])


def test_engine_tuple_fallback_on_composite_overflow(tmp_path):
    """Four near-unique key columns overflow the radix space; the engine
    must serve the query exactly via tuple factorization (the reference's
    bquery factorized key tuples and never had this limit)."""
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.storage.ctable import ctable as CT

    rng = np.random.default_rng(5)
    n = 2_000
    df = pd.DataFrame(
        {f"k{i}": rng.integers(0, 10**9, n).astype(np.int64)
         for i in range(6)}
    )
    # duplicate some rows so real multi-row groups exist
    df = pd.concat([df, df.iloc[: n // 4]], ignore_index=True)
    df["v"] = rng.integers(-1000, 1000, len(df)).astype(np.int64)
    root = str(tmp_path / "of.bcolzs")
    CT.fromdataframe(df, root)
    ct = CT(root, mode="r")
    import math

    cards = [df[f"k{i}"].nunique() for i in range(6)]
    assert math.prod(cards) >= 2**63, "fixture no longer overflows"
    gcols = [f"k{i}" for i in range(6)]
    q = GroupByQuery(gcols, [["v", "sum", "s"]], [], aggregate=True)
    got = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([QueryEngine().execute_local(ct, q)])
    ).sort_values(gcols).reset_index(drop=True)
    exp = (
        df.groupby(gcols, as_index=False)["v"].sum()
        .rename(columns={"v": "s"})
        .sort_values(gcols).reset_index(drop=True)
    )
    assert len(got) == len(exp)
    for c in got.columns:
        np.testing.assert_array_equal(got[c].to_numpy(), exp[c].to_numpy())


def test_worker_degrades_mesh_overflow_to_engine(tmp_path, caplog):
    """The worker's routing: a psum-mergeable query whose key space
    overflows the mesh alignment's radix pack must degrade to the engine
    path and still answer exactly."""
    from bqueryd_tpu.models.query import GroupByQuery
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.storage.ctable import ctable as CT
    from bqueryd_tpu.utils.tracing import PhaseTimer
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(6)
    n = 2_000
    frames = []
    tables = []
    for s in range(2):
        df = pd.DataFrame(
            {f"k{i}": rng.integers(0, 10**9, n).astype(np.int64)
             for i in range(6)}
        )
        df["v"] = rng.integers(-100, 100, n).astype(np.int64)
        frames.append(df)
        root = str(tmp_path / f"of{s}.bcolzs")
        CT.fromdataframe(df, root)
        tables.append(CT(root, mode="r"))

    worker = WorkerNode.__new__(WorkerNode)  # routing only: no sockets
    worker._engine = None
    worker._mesh_executor = None
    worker._result_cache = None
    import logging as _logging

    worker.logger = _logging.getLogger("test-overflow")
    gcols = [f"k{i}" for i in range(6)]
    q = GroupByQuery(gcols, [["v", "sum", "s"]], [], aggregate=True)
    import logging as _logging2

    with caplog.at_level(_logging2.INFO, logger="test-overflow"):
        payload = worker._execute(tables, q, PhaseTimer())
    # the MESH path must have been attempted and degraded — not routed
    # around: otherwise this test silently stops covering the fallback
    assert any("composite key space" in r.message for r in caplog.records)
    got = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    ).sort_values(gcols).reset_index(drop=True)
    all_df = pd.concat(frames, ignore_index=True)
    exp = (
        all_df.groupby(gcols, as_index=False)["v"].sum()
        .rename(columns={"v": "s"})
        .sort_values(gcols).reset_index(drop=True)
    )
    assert len(got) == len(exp)
    for c in got.columns:
        np.testing.assert_array_equal(got[c].to_numpy(), exp[c].to_numpy())


def test_worker_degrades_mesh_runtime_error_to_engine(tmp_path, caplog):
    """A JaxRuntimeError out of the mesh executor (observed on hardware:
    flaky tunneled remote-compile HTTP 500s, TPU_VALIDATE_r5_prefix.json
    case7/case13) must degrade to the per-shard engine path and still
    answer exactly, not fail the query."""
    import jax

    from bqueryd_tpu.models.query import GroupByQuery
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.storage.ctable import ctable as CT
    from bqueryd_tpu.utils.tracing import PhaseTimer
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(8)
    n = 50_000  # large enough that routing picks the mesh path
    frames = []
    tables = []
    for s in range(2):
        df = pd.DataFrame(
            {
                "k": rng.integers(0, 9, n).astype(np.int64),
                "v": rng.integers(-100, 100, n).astype(np.int64),
            }
        )
        frames.append(df)
        root = str(tmp_path / f"rt{s}.bcolzs")
        CT.fromdataframe(df, root)
        tables.append(CT(root, mode="r"))

    worker = WorkerNode.__new__(WorkerNode)  # routing only: no sockets
    worker._engine = None
    worker._result_cache = None

    class _FailingMesh:
        timer = None

        def execute(self, tables, query, strategy=None):
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: remote_compile: HTTP 500: tpu_compile_helper "
                "subprocess exit code 1"
            )

    worker._mesh_executor = _FailingMesh()
    import logging as _logging

    worker.logger = _logging.getLogger("test-mesh-rt")
    q = GroupByQuery(["k"], [["v", "sum", "s"]], [], aggregate=True)
    with caplog.at_level(_logging.WARNING, logger="test-mesh-rt"):
        payload = worker._execute(tables, q, PhaseTimer())
    # the mesh path must have been attempted and degraded — not routed
    # around: otherwise this test silently stops covering the fallback
    assert any("mesh executor failed" in r.message for r in caplog.records)
    got = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    ).sort_values("k").reset_index(drop=True)
    all_df = pd.concat(frames, ignore_index=True)
    exp = (
        all_df.groupby("k", as_index=False)["v"].sum()
        .rename(columns={"v": "s"})
        .sort_values("k").reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["k"].to_numpy(), exp["k"].to_numpy())
    np.testing.assert_array_equal(got["s"].to_numpy(), exp["s"].to_numpy())


def test_hicard_pallas_path_bit_exact(monkeypatch):
    """The group-tiled Pallas MXU path (BQUERYD_TPU_PALLAS=1 past
    matmul_groups_limit) must agree bit-for-bit with numpy: int64 sums
    with negatives, unsigned means, null codes, and ragged padding in
    both the row-block and group-tile dimensions (40k rows -> 2 blocks;
    9k groups -> 5 group tiles of 2048)."""
    import jax

    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    g = _groupby_module()
    rng = np.random.default_rng(1)
    n, ng = 40_000, 9_000
    codes = rng.integers(-1, ng, n).astype(np.int64)
    v64 = rng.integers(-(2**40), 2**40, n).astype(np.int64)
    vu8 = rng.integers(0, 250, n).astype(np.uint8)
    assert g._hicard_matmul_profitable((v64, vu8), ("sum", "mean"), n, ng)
    out = jax.device_get(
        g.partial_tables(
            np.asarray(codes), (v64, vu8), ("sum", "mean"), n_groups=ng
        )
    )
    valid = codes >= 0
    truth_s = np.zeros(ng, dtype=np.int64)
    np.add.at(truth_s, codes[valid], v64[valid])
    got_s = np.asarray(out["aggs"][0]["sum"])
    assert got_s.dtype == np.int64
    np.testing.assert_array_equal(got_s, truth_s)
    truth_u = np.zeros(ng, dtype=np.uint64)
    np.add.at(truth_u, codes[valid], vu8[valid].astype(np.uint64))
    cnt = np.bincount(codes[valid], minlength=ng)
    np.testing.assert_array_equal(
        np.asarray(out["aggs"][1]["sum"]).astype(np.uint64), truth_u
    )
    np.testing.assert_array_equal(np.asarray(out["aggs"][1]["count"]), cnt)
    np.testing.assert_array_equal(np.asarray(out["rows"]), cnt)


def test_hicard_gate_declines_incompatible_queries(monkeypatch):
    """Floats (no wrap-free limb encoding), min/max (scatter anyway),
    out-of-range cardinalities, and the default flag state must all stay
    off the high-cardinality Pallas path."""
    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    g = _groupby_module()
    n, ng = 40_000, 9_000
    i64 = np.ones(n, dtype=np.int64)
    f32 = np.ones(n, dtype=np.float32)
    assert g._hicard_matmul_profitable((i64,), ("sum",), n, ng)
    assert not g._hicard_matmul_profitable((f32,), ("sum",), n, ng)
    assert not g._hicard_matmul_profitable((i64,), ("min",), n, ng)
    # inside matmul_groups_limit the classic path owns it
    assert not g._hicard_matmul_profitable((i64,), ("sum",), n, 100)
    # past the hicard ceiling the sort/scatter path owns it
    from bqueryd_tpu.ops import pallas_groupby as pg

    over = pg.hicard_groups_limit() + 1
    assert not g._hicard_matmul_profitable((i64,), ("sum",), n, over)
    # default flag state: off
    monkeypatch.delenv("BQUERYD_TPU_PALLAS")
    assert not g._hicard_matmul_profitable((i64,), ("sum",), n, ng)


def test_hicard_kernel_rejects_wrap_risk():
    """Past HICARD_MAX_ROWS a limb total could wrap uint32 twice; the
    kernel must refuse (and the dispatcher gate declines the same bound)."""
    import jax.numpy as jnp

    from bqueryd_tpu.ops import pallas_groupby as pg

    g = _groupby_module()
    fake_n = pg.HICARD_MAX_ROWS + 1
    assert not g._hicard_matmul_profitable(
        (np.ones(8, dtype=np.int64),), ("sum",), fake_n, 9_000
    )
    with pytest.raises(ValueError, match="HICARD_MAX_ROWS"):
        pg.onehot_rows_dot_hicard(
            jnp.zeros(fake_n, jnp.int32),
            jnp.zeros((1, fake_n), jnp.bfloat16),
            n_rows=1,
            n_groups=9_000,
            interpret=True,
        )


def test_count_distinct_refuses_composite_overflow():
    from bqueryd_tpu import ops

    with pytest.raises(ops.CompositeOverflow, match="exceeds int64"):
        ops.groupby_count_distinct(
            np.zeros(4, dtype=np.int32),
            np.zeros(4, dtype=np.int32),
            2**32,
            2**32,
        )


def test_host_sorted_count_distinct_matches_device():
    """The numpy run-leader twin must agree with the device kernel on
    adversarial layouts: masked rows bridging runs, null group codes,
    NaN values (NaN != NaN starts a new run), and empty input."""
    from bqueryd_tpu import ops

    rng = np.random.default_rng(17)
    n, g = 5_000, 37
    codes = rng.integers(-1, g, n).astype(np.int32)
    # sorted-ish values with repeats so real runs exist
    values = np.sort(rng.integers(0, 50, n)).astype(np.float64)
    values[rng.random(n) < 0.02] = np.nan
    mask = rng.random(n) < 0.8
    for m in (None, mask):
        dev = np.asarray(
            ops.groupby_sorted_count_distinct(codes, values, g, m)
        )
        host = ops.host_sorted_count_distinct(codes, values, g, m)
        np.testing.assert_array_equal(host, dev)
    # empty input
    np.testing.assert_array_equal(
        ops.host_sorted_count_distinct(
            np.empty(0, np.int32), np.empty(0), 5
        ),
        np.zeros(5, np.int64),
    )


def test_expand_mask_host_twin_out_of_range_parity(monkeypatch):
    """ADVICE r5 low #2: the wedged numpy twin of expand_mask_by_group must
    mirror the device twin's edge semantics for codes >= n_groups — the jit
    scatter silently DROPS out-of-range ids and the jit gather CLAMPS, where
    an unguarded fancy index raised IndexError instead."""
    from bqueryd_tpu.ops.groupby import _expand_mask_jit
    from bqueryd_tpu.utils import devicehealth

    n_groups = 4
    codes = np.array([0, 1, 7, 3, -1, 9, 3], dtype=np.int64)  # 7, 9 OOB
    mask = np.array([True, False, True, True, False, True, False])

    device = np.asarray(_expand_mask_jit(codes, mask, n_groups))
    monkeypatch.setattr(devicehealth, "backend_wedged", lambda **kw: True)
    host = np.asarray(gb.expand_mask_by_group(codes, mask, n_groups=n_groups))
    np.testing.assert_array_equal(host, device)
    # and the baseline in-range case still matches pandas-style semantics:
    # any selected row selects its whole group, null groups never selected
    codes2 = np.array([0, 0, 1, 2, -1, 2], dtype=np.int64)
    mask2 = np.array([True, False, False, False, True, True])
    host2 = np.asarray(
        gb.expand_mask_by_group(codes2, mask2, n_groups=3)
    )
    np.testing.assert_array_equal(
        host2, [True, True, False, True, False, True]
    )


def test_term_mask_wedged_rejects_device_arrays(monkeypatch):
    """ADVICE r5 low #1: the wedged branch must fail fast on a jax Array
    instead of np.asarray-ing it (a blocking device transfer — the exact
    hang the branch exists to avoid)."""
    import jax.numpy as jnp

    from bqueryd_tpu.utils import devicehealth

    monkeypatch.setattr(devicehealth, "backend_wedged", lambda **kw: True)
    host = pred.term_mask(np.array([1, 2, 3]), "==", 2)
    np.testing.assert_array_equal(np.asarray(host), [False, True, False])
    with pytest.raises(TypeError, match="wedged"):
        pred.term_mask(jnp.array([1, 2, 3]), "==", 2)
