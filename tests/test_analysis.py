"""Static-analysis suite tests: framework semantics, one injected violation
per analyzer family, the runtime lock-order recorder (ABBA fixture + real
pipeline/worker-path locks), and the tier-1 gate that the shipped tree is
clean."""

import json
import os
import subprocess
import sys
import threading

import pytest

from bqueryd_tpu.analysis import default_analyzers, run_suite
from bqueryd_tpu.analysis.concurrency import LockDisciplineAnalyzer
from bqueryd_tpu.analysis.configreg import (
    ENV_REGISTRY,
    ConfigRegistryAnalyzer,
    EnvVar,
    registry_markdown_rows,
)
from bqueryd_tpu.analysis.core import (
    Finding,
    Project,
    load_baseline,
    parse_suppressions,
    run_suite as core_run_suite,
)
from bqueryd_tpu.analysis.lockorder import (
    LockOrderError,
    LockOrderRecorder,
)
from bqueryd_tpu.analysis.metricslint import (
    MetricNameAnalyzer,
    MetricReadmeAnalyzer,
)
from bqueryd_tpu.analysis.purity import JitPurityAnalyzer
from bqueryd_tpu.analysis.wire import WireSchemaAnalyzer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_project(tmp_path, files, readme="(no config table)"):
    """A throwaway project tree: ``files`` maps package-relative paths to
    source text."""
    pkg = tmp_path / "bqueryd_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, text in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        if path.parent != pkg and not (
            path.parent / "__init__.py"
        ).exists():
            (path.parent / "__init__.py").write_text("")
        path.write_text(text)
    (tmp_path / "README.md").write_text(readme)
    return Project(str(tmp_path))


def rules_of(result):
    return {f.rule for f in result.new}


# -- framework ---------------------------------------------------------------

def test_pragma_requires_reason_and_rule():
    sups, problems = parse_suppressions(
        "x = 1  # bqtpu: allow[some-rule] measured, tolerable\n"
        "y = 2  # bqtpu: allow[other-rule]\n"
        "z = 3  # bqtpu: allow[]\n"
    )
    assert len(sups) == 1 and sups[0].rules == ("some-rule",)
    assert sups[0].reason == "measured, tolerable"
    assert len(problems) == 2


def test_pragma_in_docstring_is_not_a_pragma():
    sups, problems = parse_suppressions(
        '"""docs show the syntax: # bqtpu: allow[rule-id] reason"""\n'
    )
    assert sups == [] and problems == []


def test_pragma_suppresses_same_line_and_standalone_previous_line(tmp_path):
    project = make_project(tmp_path, {
        "mod.py": (
            "import os\n"
            "# bqtpu: allow[config-unregistered-env] test fixture var\n"
            'A = os.environ.get("BQUERYD_TPU_FIXTURE_ONLY")\n'
            'B = os.environ.get("BQUERYD_TPU_FIXTURE_TWO")'
            "  # bqtpu: allow[config-unregistered-env] also a fixture\n"
            'C = os.environ.get("BQUERYD_TPU_FIXTURE_THREE")\n'
        ),
    })
    reg = {
        v.name: v for v in [EnvVar(
            "BQUERYD_TPU_FIXTURE_THREE", "str", "-", "x")]
    }
    result = core_run_suite(
        project=project, analyzers=[ConfigRegistryAnalyzer(registry=reg)],
    )
    suppressed_rules = {f.rule for f, _reason in result.suppressed}
    assert "config-unregistered-env" in suppressed_rules
    assert len(result.suppressed) == 2
    # the third read is registered; remaining findings are doc/readme ones
    assert "config-unregistered-env" not in rules_of(result)


def test_unknown_rule_pragma_is_a_finding(tmp_path):
    project = make_project(tmp_path, {
        "mod.py": "x = 1  # bqtpu: allow[no-such-rule] because reasons\n",
    })
    result = core_run_suite(project=project, analyzers=[])
    assert "analysis-unknown-rule" in rules_of(result)


def test_baseline_grandfathers_and_stale_entries_flag(tmp_path):
    files = {
        "mod.py": 'import os\nA = os.environ.get("BQUERYD_TPU_LEGACY_X")\n',
    }
    project = make_project(tmp_path, files)
    analyzer = ConfigRegistryAnalyzer(registry={})
    result = core_run_suite(project=project, analyzers=[analyzer])
    (unmatched,) = [
        f for f in result.new if f.rule == "config-unregistered-env"
    ]

    baseline = tmp_path / "ANALYSIS_BASELINE.json"
    baseline.write_text(json.dumps({
        unmatched.fingerprint: "grandfathered: pre-registry legacy knob",
    }))
    result2 = core_run_suite(
        project=project, analyzers=[analyzer],
        baseline_path=str(baseline),
    )
    assert "config-unregistered-env" not in rules_of(result2)
    assert any(
        f.fingerprint == unmatched.fingerprint
        for f, _ in result2.baselined
    )

    # a baseline entry matching nothing is itself a finding
    baseline.write_text(json.dumps({"bogus:rule:path": "stale"}))
    result3 = core_run_suite(
        project=project, analyzers=[ConfigRegistryAnalyzer(registry={
            "BQUERYD_TPU_LEGACY_X": EnvVar(
                "BQUERYD_TPU_LEGACY_X", "str", "-", "x"),
        })],
        baseline_path=str(baseline),
    )
    assert "analysis-stale-baseline" in rules_of(result3)


def test_unused_pragma_is_a_finding(tmp_path):
    """A pragma whose finding was fixed must not linger (same only-shrinks
    contract as the baseline)."""
    project = make_project(tmp_path, {
        "mod.py": (
            "# bqtpu: allow[config-unregistered-env] nothing here anymore\n"
            "x = 1\n"
        ),
    })
    result = core_run_suite(
        project=project, analyzers=[ConfigRegistryAnalyzer(registry={})],
    )
    assert "analysis-unused-pragma" in rules_of(result)
    # but not when the family that owns the rule sat the run out
    result2 = core_run_suite(project=project, analyzers=[])
    assert "analysis-unused-pragma" not in rules_of(result2)


def test_fingerprint_is_line_independent():
    a = Finding("r", "p.py", 10, "msg", symbol="sym")
    b = Finding("r", "p.py", 99, "different msg", symbol="sym")
    assert a.fingerprint == b.fingerprint


# -- config registry ---------------------------------------------------------

def test_config_family_detects_each_violation(tmp_path):
    project = make_project(tmp_path, {
        "mod.py": (
            "import os\n"
            'A = os.environ.get("BQUERYD_TPU_UNKNOWN_KNOB")\n'     # unregistered
            'B = os.environ.get("SOMEONE_ELSES_VAR")\n'            # external
            'C = os.environ.get("BQUERYD_TPU_LIVE_KNOB")\n'        # import-read
            "def f(name):\n"
            "    return os.environ.get(name)\n"                    # dynamic
        ),
    }, readme="documents BQUERYD_TPU_GHOST_VAR only")
    registry = {v.name: v for v in [
        EnvVar("BQUERYD_TPU_LIVE_KNOB", "int", "1", "live", "call"),
        EnvVar("BQUERYD_TPU_DEAD_KNOB", "int", "1", "dead", "call"),
        EnvVar("BQUERYD_TPU_TRACE_THING", "int", "1", "a", "call"),
        EnvVar("BQUERYD_TPU_TRACE_THING_BYTES", "int", "1", "b", "call"),
    ]}
    result = core_run_suite(
        project=project,
        analyzers=[ConfigRegistryAnalyzer(registry=registry)],
    )
    got = rules_of(result)
    assert {
        "config-unregistered-env", "config-external-env",
        "config-import-time-read", "config-dynamic-env-key",
        "config-dead-var", "config-undocumented", "config-readme-unknown",
        "config-name-collision",
    } <= got


def test_config_doc_and_dead_checks_match_exact_tokens(tmp_path):
    """Substring matching would let FOO hide inside FOO_BYTES — the exact
    near-collision pairs the registry polices.  The README documenting (and
    the source referencing) only the longer sibling must still flag the
    shorter one."""
    project = make_project(tmp_path, {
        "mod.py": (
            "import os\n"
            'A = os.environ.get("BQUERYD_TPU_RING_BYTES")\n'
        ),
    }, readme="| `BQUERYD_TPU_RING_BYTES` | 16 MiB | byte cap |")
    registry = {v.name: v for v in [
        EnvVar("BQUERYD_TPU_RING", "int", "256", "entry cap", "call",
               related=("BQUERYD_TPU_RING_BYTES",)),
        EnvVar("BQUERYD_TPU_RING_BYTES", "int", "16 MiB", "byte cap",
               "call", related=("BQUERYD_TPU_RING",)),
    ]}
    result = core_run_suite(
        project=project,
        analyzers=[ConfigRegistryAnalyzer(registry=registry)],
    )
    undocumented = {
        f.symbol for f in result.new if f.rule == "config-undocumented"
    }
    dead = {f.symbol for f in result.new if f.rule == "config-dead-var"}
    assert undocumented == {"BQUERYD_TPU_RING"}
    assert dead == {"BQUERYD_TPU_RING"}


def test_registry_markdown_rows_cover_every_var():
    rows = registry_markdown_rows()
    assert len(rows) == len(ENV_REGISTRY)
    for name in ENV_REGISTRY:
        assert any(name in row for row in rows)


def test_trace_buffer_near_collision_is_reconciled():
    """The TRACE_BUFFER (entries) vs TRACE_BUFFER_BYTES near-collision: both
    registered, cross-referenced, with help text that distinguishes the
    entry cap from the byte cap."""
    entries = ENV_REGISTRY["BQUERYD_TPU_TRACE_BUFFER"]
    byts = ENV_REGISTRY["BQUERYD_TPU_TRACE_BUFFER_BYTES"]
    assert "BQUERYD_TPU_TRACE_BUFFER_BYTES" in entries.related
    assert "BQUERYD_TPU_TRACE_BUFFER" in byts.related
    assert "ENTRY-COUNT" in entries.help and "BYTE" in byts.help


# -- lock discipline ---------------------------------------------------------

LOCKED_CLASS = """
import threading


class Box:
    _bqtpu_guarded_ = {"_lock": ("_data", "_count")}

    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}
        self._count = 0

    def ok(self):
        with self._lock:
            self._count += 1
            return dict(self._data)

    def _drop_locked(self):
        self._data.clear()

    def racy(self):
        self._count += 1          # unguarded write

    def racy_helper(self):
        self._drop_locked()       # *_locked called lock-free
"""


def test_lock_discipline_flags_unguarded_and_helper(tmp_path):
    project = make_project(tmp_path, {"mod.py": LOCKED_CLASS})
    result = core_run_suite(
        project=project, analyzers=[LockDisciplineAnalyzer()],
    )
    by_rule = {}
    for f in result.new:
        by_rule.setdefault(f.rule, []).append(f)
    (unguarded,) = by_rule["lock-unguarded-attr"]
    assert unguarded.symbol == "Box.racy._count"
    (helper,) = by_rule["lock-helper-outside-lock"]
    assert "racy_helper" in helper.symbol


def test_lock_discipline_multi_item_with(tmp_path):
    """``with self._lock, ctx(self._data):`` holds the lock while the second
    context expression evaluates — no false finding."""
    project = make_project(tmp_path, {"mod.py": (
        "import threading\n"
        "class Box:\n"
        "    _bqtpu_guarded_ = {\"_lock\": (\"_data\",)}\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._data = {}\n"
        "    def both(self, ctx):\n"
        "        with self._lock, ctx(self._data):\n"
        "            return len(self._data)\n"
    )})
    result = core_run_suite(
        project=project, analyzers=[LockDisciplineAnalyzer()],
    )
    assert "lock-unguarded-attr" not in rules_of(result)


def test_lock_discipline_nonliteral_declaration_fails_loudly(tmp_path):
    """Refactoring the declaration into a computed value must be a finding,
    never a silent loss of checking for the whole class."""
    project = make_project(tmp_path, {"mod.py": (
        "ATTRS = (\"_x\",)\n"
        "class Box:\n"
        "    _bqtpu_guarded_ = {\"_lock\": ATTRS}\n"
        "    def racy(self):\n"
        "        return self._x\n"
    )})
    result = core_run_suite(
        project=project, analyzers=[LockDisciplineAnalyzer()],
    )
    assert "lock-bad-declaration" in rules_of(result)


def test_lock_discipline_missing_lock_attr(tmp_path):
    project = make_project(tmp_path, {"mod.py": (
        "class Odd:\n"
        "    _bqtpu_guarded_ = {\"_ghost_lock\": (\"_x\",)}\n"
        "    def get(self):\n"
        "        return 1\n"
    )})
    result = core_run_suite(
        project=project, analyzers=[LockDisciplineAnalyzer()],
    )
    assert "lock-missing-lock-attr" in rules_of(result)


# -- jit purity ---------------------------------------------------------------

IMPURE_JIT = """
import functools
import os
import time

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("n",))
def impure(x, n):
    t = time.time()
    e = os.environ.get("BQUERYD_TPU_METRICS")
    if x > 0:
        y = float(x)
    z = np.asarray(x)
    return x + n


def caller():
    return impure(1.0, n=[1, 2])


def outer():
    big = [1, 2, 3]

    @functools.lru_cache(maxsize=8)
    def closure_cache(k):
        return big[k]

    return closure_cache
"""

PURE_JIT = """
import functools

import jax
import jax.numpy as jnp

from bqueryd_tpu.obs import profile as _obsprofile


@functools.partial(jax.jit, static_argnames=("n_groups",))
def clean(codes, n_groups, mask=None):
    valid = codes >= 0
    if mask is not None:
        valid = valid & mask
    return jnp.where(valid, codes, 0).astype(jnp.int32)


clean = _obsprofile.instrument("ops.clean", clean)
"""


def test_purity_family_detects_each_violation(tmp_path):
    project = make_project(tmp_path, {"ops/kern.py": IMPURE_JIT})
    result = core_run_suite(project=project, analyzers=[JitPurityAnalyzer()])
    got = rules_of(result)
    assert {
        "jit-impure-time", "jit-impure-env", "jit-traced-branch",
        "jit-traced-coerce", "jit-host-numpy", "jit-nonhashable-static",
        "jit-lru-closure", "jit-uninstrumented",
    } <= got


def test_purity_clean_idioms_pass(tmp_path):
    """static-arg branches, `is None` structure checks, and instrumented
    entry points produce no findings."""
    project = make_project(tmp_path, {"ops/kern.py": PURE_JIT})
    result = core_run_suite(project=project, analyzers=[JitPurityAnalyzer()])
    assert rules_of(result) == set()


def test_purity_static_argnums_resolved_positionally(tmp_path):
    """Branching on a positionally-static parameter is legal; branching on
    the traced one still flags."""
    project = make_project(tmp_path, {"ops/kern.py": (
        "import functools\n"
        "import jax\n"
        "from bqueryd_tpu.obs import profile as _p\n"
        "@functools.partial(jax.jit, static_argnums=(1,))\n"
        "def f(x, n):\n"
        "    if n > 4:\n"          # static: fine
        "        return x\n"
        "    if x > 0:\n"          # traced: finding
        "        return x + n\n"
        "    return x\n"
        "f = _p.instrument('ops.f', f)\n"
    )})
    result = core_run_suite(project=project, analyzers=[JitPurityAnalyzer()])
    branches = [f for f in result.new if f.rule == "jit-traced-branch"]
    assert len(branches) == 1 and branches[0].symbol == "f.if.x"


# -- wire schema --------------------------------------------------------------

def test_wire_family_detects_each_violation(tmp_path):
    project = make_project(tmp_path, {
        "controller.py": (
            "def handle(msg):\n"
            "    msg[\"brand_new_key\"] = 1\n"       # undeclared
            "    msg[\"sole_shard\"] = True\n"       # written, never read here
            "    return msg.get(\"payload\")\n"
        ),
        "worker.py": "def noop(msg):\n    msg[\"payload\"] = \"ok\"\n",
        "rpc.py": "",
    })
    result = core_run_suite(project=project, analyzers=[WireSchemaAnalyzer()])
    got = rules_of(result)
    assert "wire-undeclared-key" in got
    assert "wire-one-sided-key" in got       # sole_shard written, never read
    assert "wire-dead-key" in got            # e.g. token: declared, untouched
    assert any(
        f.rule == "wire-undeclared-key" and f.symbol == "brand_new_key"
        for f in result.new
    )


def test_wire_result_envelope_anchored_on_pickle_dumps(tmp_path):
    """Bookkeeping dicts sharing a result-schema key ('busy', 'error') must
    NOT count as envelope writes; only the pickled dict does — and an
    undeclared key inside a pickled envelope is flagged."""
    project = make_project(tmp_path, {
        "controller.py": (
            "import pickle\n"
            "def bookkeeping():\n"
            "    info = {\"busy\": False, \"error\": None}\n"   # not wire
            "    return info\n"
            "def reply_ok(payloads):\n"
            "    return pickle.dumps({\"ok\": True, \"payloads\": payloads,"
            " \"timings\": {}, \"sneaky\": 1})\n"
        ),
        "worker.py": "",
        "rpc.py": (
            "import pickle\n"
            "def parse(raw):\n"
            "    envelope = pickle.loads(raw)\n"
            "    if envelope.get(\"busy\"):\n"
            "        raise RuntimeError(envelope.get(\"error\"))\n"
            "    return envelope[\"payloads\"], envelope.get(\"timings\")\n"
        ),
    })
    result = core_run_suite(project=project, analyzers=[WireSchemaAnalyzer()])
    assert any(
        f.rule == "wire-undeclared-key" and f.symbol == "sneaky"
        for f in result.new
    )
    one_sided = {
        f.symbol for f in result.new if f.rule == "wire-one-sided-key"
    }
    # 'busy'/'error' are READ here but their only "writes" are the
    # bookkeeping dict, which must not count -> one-sided reads; 'ok'
    # written-only likewise; payloads/timings are two-sided
    assert {"busy", "error", "ok"} <= one_sided
    assert "payloads" not in one_sided and "timings" not in one_sided


def test_wire_schema_covers_shipped_tree():
    """The real controller/worker/rpc trio against the declared schema: the
    gate that catches a one-sided key at review time."""
    project = Project(REPO_ROOT)
    result = core_run_suite(
        project=project, analyzers=[WireSchemaAnalyzer()],
        baseline_path=os.path.join(REPO_ROOT, "ANALYSIS_BASELINE.json"),
    )
    assert [f.render() for f in result.new] == []


# -- migrated metric lints ----------------------------------------------------

def test_metric_lints_detect_violations(tmp_path):
    project = make_project(tmp_path, {
        "m.py": (
            "def setup(reg):\n"
            "    reg.counter(\"Bad-Name\", \"help text\")\n"
            "    reg.gauge(\"bqueryd_tpu_thing\", \"\")\n"
        ),
    }, readme="no metrics table at all")
    result = core_run_suite(
        project=project,
        analyzers=[MetricNameAnalyzer(), MetricReadmeAnalyzer()],
    )
    got = rules_of(result)
    assert {
        "metric-name-format", "metric-missing-help",
        "metric-readme-coverage",
    } <= got


def test_runtime_metric_lint_entry_points_still_work():
    """The originals the analyzers migrated from keep their contracts."""
    from bqueryd_tpu.obs.metrics import (
        MetricsRegistry,
        readme_coverage_problems,
    )

    reg = MetricsRegistry()
    reg.counter("bqueryd_tpu_ok_total", "fine")
    assert reg.lint() == []
    assert readme_coverage_problems([reg], "bqueryd_tpu_ok_total") == []
    assert readme_coverage_problems([reg], "nothing here") != []


# -- lock-order recorder ------------------------------------------------------

def test_lockorder_abba_cycle_detected_with_sites():
    recorder = LockOrderRecorder()
    a = recorder.lock("A")
    b = recorder.lock("B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()

    cycles = recorder.cycles()
    assert cycles and set(cycles[0]) == {"A", "B"}
    report = recorder.report()
    # the report names BOTH acquisition sites of both edges
    assert "lock-order cycle: A -> B -> A" in report
    assert report.count(__file__) == 4
    assert "while holding" in report
    with pytest.raises(LockOrderError):
        recorder.assert_no_cycles()


def test_lockorder_reports_both_orientations_over_same_locks():
    """A->B->C->A and A->C->B->A are distinct deadlock orderings with
    distinct witness sites — node-set dedup would hide the second."""
    recorder = LockOrderRecorder()
    a, b, c = (recorder.lock(n) for n in "ABC")
    for first, second, third in ((a, b, c), (a, c, b)):
        with first:
            with second:
                with third:
                    pass
    # edges: A->B, A->C, B->C, C->B  =>  cycles B->C->B plus both
    # three-node orientations if closed; at minimum the 2-cycle plus
    # every distinct ordered cycle is present exactly once
    cycles = {tuple(cyc) for cyc in recorder.cycles()}
    assert ("B", "C") in cycles or ("C", "B") in cycles
    assert len(cycles) == len(recorder.cycles())  # no duplicates


def test_lockorder_consistent_order_is_clean():
    recorder = LockOrderRecorder()
    a = recorder.lock("A")
    b = recorder.lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert recorder.cycles() == []
    recorder.assert_no_cycles()


def test_lockorder_self_deadlock_raises():
    recorder = LockOrderRecorder()
    a = recorder.lock("A")
    with a:
        with pytest.raises(LockOrderError, match="self-deadlock"):
            a.acquire()


def test_lockorder_real_pipeline_and_worker_paths_run_clean():
    """Drive the PR-4 concurrency surface — shared caches, working set,
    stage clocks, metrics registry, flight ring — under instrumented locks
    from several threads and prove the acquisition graph is acyclic."""
    from bqueryd_tpu.obs.flightrec import FlightRecorder
    from bqueryd_tpu.obs.metrics import MetricsRegistry
    from bqueryd_tpu.ops.workingset import WorkingSet
    from bqueryd_tpu.parallel import pipeline
    from bqueryd_tpu.utils.cache import BytesCappedCache

    recorder = LockOrderRecorder()
    cache = BytesCappedCache(1 << 16, sizeof=len)
    ws = WorkingSet(budgets={"align": 1 << 14, "codes": 1 << 14,
                             "blocks": 1 << 14})
    registry = MetricsRegistry()
    counter = registry.counter("bqueryd_tpu_lockorder_test_total", "t")
    hist = registry.histogram("bqueryd_tpu_lockorder_test_seconds", "t")
    flight = FlightRecorder(node_id="t", capacity=64, max_bytes=1 << 14)
    clock = pipeline.StageClock()

    assert recorder.instrument_object(cache)
    recorder.instrument_object(ws)
    for name in ("align", "codes", "blocks"):
        recorder.instrument_object(ws.segment(name), prefix=f"ws.{name}")
    recorder.instrument_object(registry)
    recorder.instrument_object(counter, prefix="Counter")
    recorder.instrument_object(hist, prefix="Histogram")
    recorder.instrument_object(flight)
    recorder.instrument_object(clock, prefix="StageClock")

    sample = {"bytes_in_use": 10 * (1 << 14), "bytes_limit": 1 << 14}

    def storm(seed):
        for i in range(50):
            key = f"k{(seed * 50 + i) % 17}"
            cache.put(key, b"x" * 100)
            cache.get(key)
            cache.nbytes, len(cache)
            seg = ws.segment(("align", "codes", "blocks")[i % 3])
            seg.put((seed, i % 7), b"y" * 200, nbytes=200)
            seg.get((seed, i % 7))
            ws.stats()
            if i % 10 == 0:
                ws.evict_under_pressure(sample=sample)
            counter.inc()
            hist.observe(0.001 * i)
            registry.render()
            flight.record("rpc", verb="groupby", seq=i)
            flight.tail(8)
            len(flight), flight.nbytes, flight.evictions
            clock.add("decode", 0.001)
            clock.snapshot()

    threads = [threading.Thread(target=storm, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert recorder.acquisitions > 0
    assert recorder.cycles() == [], recorder.report()


def test_lockorder_instrumented_pipeline_map_ordered_clean():
    """The shared stage pool + busy clocks under instrumented module locks:
    the fallback multi-shard worker path's concurrency substrate."""
    from bqueryd_tpu.parallel import pipeline

    recorder = LockOrderRecorder()
    restore_pool = recorder.instrument_module_lock(pipeline, "_pool_lock")
    clock_wrapped = recorder.instrument_object(
        pipeline.clock(), prefix="StageClock"
    )
    try:
        assert clock_wrapped

        def work(i):
            with pipeline.stage("decode"):
                with pipeline.stage("kernel"):
                    return i * 2

        out = pipeline.map_ordered(work, range(32))
        assert out == [i * 2 for i in range(32)]
        assert recorder.cycles() == [], recorder.report()
    finally:
        restore_pool()


# -- root/readme robustness ---------------------------------------------------

def test_missing_readme_is_one_finding_not_sixty(tmp_path):
    pkg = tmp_path / "bqueryd_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("x = 1\n")
    project = Project(str(tmp_path))        # no README.md written
    result = core_run_suite(project=project)
    assert "analysis-missing-readme" in rules_of(result)
    assert "config-undocumented" not in rules_of(result)
    assert "metric-readme-coverage" not in rules_of(result)


def test_sourceless_root_fails_loudly(tmp_path):
    with pytest.raises(FileNotFoundError, match="source checkout"):
        Project(str(tmp_path))


def test_wire_schema_read_from_analyzed_tree_not_live_import(tmp_path):
    """--root must diff a checkout against ITS OWN messages.py schema."""
    project = make_project(tmp_path, {
        "messages.py": (
            'ENVELOPE_SCHEMA = {"payload": "verb", "custom_key": "theirs"}\n'
            "RESULT_ENVELOPE_SCHEMA = {}\n"
            "WIRE_ONE_SIDED_OK = {}\n"
        ),
        "controller.py": (
            "def handle(msg):\n"
            "    msg[\"custom_key\"] = 1\n"
            "    return msg.get(\"custom_key\"), msg.get(\"payload\"),"
            " msg.get(\"token\")\n"
        ),
        "worker.py": "def f(msg):\n    msg[\"payload\"] = 1\n",
        "rpc.py": "",
    })
    result = core_run_suite(project=project, analyzers=[WireSchemaAnalyzer()])
    # custom_key is declared in THIS tree's schema: no undeclared finding —
    # but 'token' (declared only in the live module) is undeclared here
    undeclared = {
        f.symbol for f in result.new if f.rule == "wire-undeclared-key"
    }
    assert "custom_key" not in undeclared
    assert "token" in undeclared


# -- suite + CLI on the shipped tree -----------------------------------------

def test_shipped_tree_has_zero_gating_findings():
    """THE tier-1 gate: the full suite over the real tree is clean (inline
    suppressions and the checked-in baseline are the only escapes, and the
    baseline must stay near-empty)."""
    result = run_suite(root=REPO_ROOT)
    assert [f.render() for f in result.gating] == []
    baseline = load_baseline(
        os.path.join(REPO_ROOT, "ANALYSIS_BASELINE.json")
    )
    assert len(baseline) <= 3, (
        "the suppression baseline must stay near-empty; fix findings "
        "instead of growing it"
    )
    # every analyzer family actually ran
    assert {
        "config-registry", "lock-discipline", "jit-purity", "wire-schema",
        "metric-lint", "metric-readme",
    } <= set(result.analyzers_run)


def test_suite_runtime_stays_fast():
    import time

    t0 = time.perf_counter()
    run_suite(root=REPO_ROOT)
    assert time.perf_counter() - t0 < 10.0


def test_cli_json_clean_and_violation_exit_codes(tmp_path, capsys):
    from bqueryd_tpu.analysis.__main__ import main

    rc = main(["--format", "json", "--root", REPO_ROOT])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    assert payload["schema"] == "bqueryd_tpu.analysis/1"
    assert payload["exit_code"] == 0
    assert payload["findings"] == []
    # counts_by_analyzer is RAW (pre-suppression): the two justified
    # dynamic-env-key pragma sites still show up as having been found
    assert payload["counts_by_analyzer"]["config-registry"] == len(
        payload["suppressed"]
    )

    # an injected violation flips the exit code
    make_project(tmp_path, {
        "mod.py": 'import os\nX = os.environ.get("BQUERYD_TPU_NEW_KNOB")\n',
    })
    rc = main(["--format", "json", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    assert payload["exit_code"] == 1
    assert any(
        f["rule"] == "config-unregistered-env" for f in payload["findings"]
    )


def test_cli_list_rules_and_unknown_analyzer(capsys):
    from bqueryd_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("config-unregistered-env", "lock-unguarded-attr",
                 "jit-impure-time", "wire-undeclared-key",
                 "metric-name-format", "analysis-stale-baseline"):
        assert rule in out
    assert main(["--analyzer", "no-such"]) == 2


def test_cli_subprocess_entry_point():
    """`python -m bqueryd_tpu.analysis` is the operator/CI surface."""
    proc = subprocess.run(
        [sys.executable, "-m", "bqueryd_tpu.analysis", "--format", "json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["exit_code"] == 0
