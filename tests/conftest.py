"""Test harness configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh: the env vars must
be set before JAX initializes its backends, which is why they live at conftest
import time rather than in a fixture.  Real-TPU runs happen in ``bench.py``.
"""

import os

# Force (not setdefault: the machine env pins JAX_PLATFORMS=axon, the real-TPU
# tunnel) the CPU platform with 8 virtual devices for hermetic sharding tests.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def pytest_configure(config):
    # The machine's sitecustomize imports jax at interpreter boot (TPU-tunnel
    # registration), so jax latched JAX_PLATFORMS=axon from the env before this
    # conftest could touch it: override the live config too, and drop the
    # tunnel backend factory so CPU-only tests can never touch (or hang on)
    # the tunnel.
    try:
        import jax
        import jax._src.xla_bridge as xb

        jax.config.update("jax_platforms", "cpu")
        xb._backend_factories.pop("axon", None)
    except Exception:
        pass

import time

import pytest


def wait_until(predicate, timeout=30.0, interval=0.05, desc="condition"):
    """Poll ``predicate`` until truthy; the framework-wide replacement for the
    reference's sleep-based test synchronization (SURVEY.md §4)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


@pytest.fixture(autouse=True)
def _disarmed_chaos():
    """Disarm fault injection and zero its counters between tests: an armed
    plan (or injected-fault stats) leaking out of one test must not fire
    inside another's cluster."""
    import sys

    yield
    if "bqueryd_tpu.chaos" in sys.modules:
        sys.modules["bqueryd_tpu.chaos"]._reset_for_tests()


@pytest.fixture(autouse=True)
def _fresh_calibration_store():
    """Reset the process-global measured-cost calibration store between
    tests: samples recorded by one test's executor runs must not tilt a
    later test's planner decisions (the cold-start contract under test is
    'no samples -> heuristic, bit for bit')."""
    import sys

    if "bqueryd_tpu.plan.calibrate" in sys.modules:
        sys.modules["bqueryd_tpu.plan.calibrate"]._reset_for_tests()
    yield


@pytest.fixture
def mem_store_url():
    """A fresh, flushed mem:// coordination store per test."""
    from bqueryd_tpu.coordination import coordination_store

    url = f"mem://test-{os.urandom(4).hex()}"
    store = coordination_store(url)
    store.flushdb()
    return url


# Semantic serving (PR 16) is heat-triggered: whether a repeated test query
# crosses the rollup materialization threshold depends on wall-clock cadence,
# which would make assertions about effective strategies / admission counters
# timing-dependent.  Pin it OFF suite-wide (the documented kill switch is
# bit-identical); tests/test_serving.py opts back in per test.
os.environ.setdefault("BQUERYD_TPU_SERVE", "0")

# Host-kernel routing is latency-adaptive (measured device floor); on the CPU
# test backend the floor is noisy enough to flip small fixtures between the
# host and device paths run-to-run.  Pin tests to the device path; dedicated
# host-kernel tests opt in explicitly.
os.environ.setdefault("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")

# The MXU one-hot matmul route auto-disables on CPU backends (it emulates
# far slower than the scatter there); pin it ON for the suite so the CPU
# test backend keeps exercising the MXU kernel paths (limb plans, Pallas).
os.environ.setdefault("BQUERYD_TPU_FORCE_MATMUL", "1")
