"""Test harness configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh: the env vars must
be set before JAX initializes its backends, which is why they live at conftest
import time rather than in a fixture.  Real-TPU runs happen in ``bench.py``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import time

import pytest


def wait_until(predicate, timeout=30.0, interval=0.05, desc="condition"):
    """Poll ``predicate`` until truthy; the framework-wide replacement for the
    reference's sleep-based test synchronization (SURVEY.md §4)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


@pytest.fixture
def mem_store_url():
    """A fresh, flushed mem:// coordination store per test."""
    from bqueryd_tpu.coordination import coordination_store

    url = f"mem://test-{os.urandom(4).hex()}"
    store = coordination_store(url)
    store.flushdb()
    return url
