import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine, ResultPayload
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.storage import ctable


def taxi_like_df(n=15_000, seed=2):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "VendorID": rng.integers(1, 3, n).astype(np.int64),
            "passenger_count": rng.integers(0, 7, n).astype(np.int64),
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "trip_distance": rng.exponential(3.0, n),
            "fare_amount": rng.gamma(2.0, 7.0, n),
            "total_amount": rng.gamma(2.5, 8.0, n),
            "flag": rng.choice(["Y", "N"], n),
            "basket_id": np.sort(rng.integers(0, n // 4, n)).astype(np.int64),
        }
    )


@pytest.fixture(scope="module")
def table(tmp_path_factory):
    df = taxi_like_df()
    root = str(tmp_path_factory.mktemp("qm") / "taxi.bcolz")
    ctable.fromdataframe(df, root)
    return df, ctable(root, mode="r")


def run_query(table, *args, **kw):
    df, ct = table
    query = GroupByQuery(*args, **kw)
    payload = QueryEngine().execute_local(ct, query)
    wire = ResultPayload.from_bytes(payload.to_bytes())  # exercise wire hop
    return df, hostmerge.payload_to_dataframe(hostmerge.merge_payloads([wire]))


def assert_frames_match(got, expected, key_cols):
    got = got.sort_values(key_cols).reset_index(drop=True)
    expected = expected.sort_values(key_cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, expected, check_dtype=False, check_column_type=False,
                                  check_index_type=False)


def test_single_key_sum(table):
    df, got = run_query(
        table, ["payment_type"], [["total_amount", "sum", "total_amount"]]
    )
    expected = df.groupby("payment_type")["total_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["payment_type"])


def test_multi_key_multi_agg(table):
    df, got = run_query(
        table,
        ["VendorID", "payment_type"],
        [
            ["fare_amount", "sum", "fare_sum"],
            ["fare_amount", "mean", "fare_mean"],
            ["passenger_count", "count", "n"],
        ],
    )
    g = df.groupby(["VendorID", "payment_type"])
    expected = pd.DataFrame(
        {
            "fare_sum": g["fare_amount"].sum(),
            "fare_mean": g["fare_amount"].mean(),
            "n": g["passenger_count"].count(),
        }
    ).reset_index()
    assert_frames_match(got, expected, ["VendorID", "payment_type"])


def test_string_key(table):
    df, got = run_query(table, ["flag"], [["fare_amount", "sum", "fare_amount"]])
    expected = df.groupby("flag")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["flag"])


def test_where_filter(table):
    df, got = run_query(
        table,
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        where_terms=[("trip_distance", ">", 4.0)],
    )
    expected = (
        df[df.trip_distance > 4.0]
        .groupby("payment_type")["total_amount"].sum().reset_index()
    )
    assert_frames_match(got, expected, ["payment_type"])


def test_unmatchable_filter_prunes_to_empty(table):
    df, got = run_query(
        table,
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        where_terms=[("payment_type", "==", 999)],
    )
    assert got.empty


def test_count_distinct(table):
    df, got = run_query(
        table,
        ["payment_type"],
        [["passenger_count", "count_distinct", "nuniq"]],
    )
    expected = (
        df.groupby("payment_type")["passenger_count"].nunique()
        .reset_index().rename(columns={"passenger_count": "nuniq"})
    )
    assert_frames_match(got, expected, ["payment_type"])


def test_count_distinct_sole_payload_device_kernel(table):
    """sole_payload=True routes count_distinct through the device sort
    kernel (final counts, no sets); results must match the sets path and
    pandas nunique, including under a filter and on a string column."""
    df, ct = table
    for value_col, where in [
        ("passenger_count", []),
        ("passenger_count", [("trip_distance", ">", 4.0)]),
        ("flag", []),
    ]:
        query = GroupByQuery(
            ["payment_type"],
            [[value_col, "count_distinct", "nuniq"]],
            where_terms=where,
            sole_payload=True,
        )
        payload = QueryEngine().execute_local(ct, query)
        # the device path ships counts, not value sets
        assert "distinct" in payload["aggs"][0]
        assert "distinct_offsets" not in payload["aggs"][0]
        got = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads([ResultPayload.from_bytes(payload.to_bytes())])
        )
        sub = df if not where else df[df.trip_distance > 4.0]
        expected = (
            sub.groupby("payment_type")[value_col].nunique()
            .reset_index().rename(columns={value_col: "nuniq"})
        )
        assert_frames_match(got, expected, ["payment_type"])


def test_distinct_values_payload_cap(table, monkeypatch):
    """The configurable cap rejects count_distinct payloads whose (group,
    value) pairs would exhaust memory, with an actionable error."""
    df, ct = table
    monkeypatch.setenv("BQUERYD_TPU_DISTINCT_VALUES_LIMIT", "3")
    query = GroupByQuery(
        ["payment_type"], [["passenger_count", "count_distinct", "nuniq"]]
    )
    with pytest.raises(ValueError, match="DISTINCT_VALUES_LIMIT"):
        QueryEngine().execute_local(ct, query)


def test_raw_rows_mode(table):
    df, got = run_query(
        table,
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        where_terms=[("trip_distance", ">", 8.0)],
        aggregate=False,
    )
    expected = df.loc[
        df.trip_distance > 8.0, ["payment_type", "total_amount"]
    ].reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got.reset_index(drop=True), expected, check_dtype=False,
        check_column_type=False,
    )


def test_basket_expansion(table):
    df, got = run_query(
        table,
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        where_terms=[("trip_distance", ">", 10.0)],
        expand_filter_column="basket_id",
    )
    hit_baskets = df.loc[df.trip_distance > 10.0, "basket_id"].unique()
    expanded = df[df.basket_id.isin(hit_baskets)]
    expected = expanded.groupby("payment_type")["total_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["payment_type"])


def test_cross_worker_merge_matches_full(table):
    """Payloads computed on disjoint row sets (as different workers would)
    must merge into exactly the unsharded result."""
    df, _ = table
    query = GroupByQuery(
        ["payment_type"],
        [
            ["fare_amount", "sum", "s"],
            ["fare_amount", "mean", "m"],
            ["fare_amount", "min", "lo"],
            ["fare_amount", "max", "hi"],
        ],
    )
    engine = QueryEngine()
    payloads = []
    import tempfile

    for i in range(3):
        part = df.iloc[i::3]
        root = tempfile.mkdtemp() + "/part.bcolzs"
        ctable.fromdataframe(part, root)
        payloads.append(engine.execute_local(ctable(root, "r"), query))
    merged = hostmerge.merge_payloads(payloads)
    got = hostmerge.payload_to_dataframe(merged)
    g = df.groupby("payment_type")["fare_amount"]
    expected = pd.DataFrame(
        {"s": g.sum(), "m": g.mean(), "lo": g.min(), "hi": g.max()}
    ).reset_index()
    assert_frames_match(got, expected, ["payment_type"])


def test_merge_empty_payloads():
    merged = hostmerge.merge_payloads([ResultPayload.empty(), ResultPayload.empty()])
    assert merged["kind"] == "empty"
    assert hostmerge.payload_to_dataframe(merged).empty


def test_agg_list_normalization():
    q = GroupByQuery(["k"], ["v", ["w", "mean"], ["x", "sum", "y"]])
    assert q.agg_list == [["v", "sum", "v"], ["w", "mean", "w"], ["x", "sum", "y"]]


def test_basket_expansion_null_baskets_are_one_group(tmp_path):
    """Dict-encoded basket columns with nulls: the null rows form ONE
    ordinary basket (the factorize runs over the physical codes, so -1 is
    a value like any other — the engine's long-standing semantics, kept
    when the factorize cache was introduced)."""
    from bqueryd_tpu.storage.ctable import ctable as CT

    df = pd.DataFrame(
        {
            "g": [1, 1, 2, 2, 1, 2],
            "v": [10, 20, 30, 40, 50, 60],
            "basket": ["a", None, None, "b", "a", None],
            "d": [0.0, 99.0, 0.0, 0.0, 0.0, 0.0],
        }
    )
    root = str(tmp_path / "nb.bcolz")
    CT.fromdataframe(df, root)
    query = GroupByQuery(
        ["g"],
        [["v", "sum", "s"]],
        [["d", ">", 50.0]],
        aggregate=True,
        expand_filter_column="basket",
    )
    payload = QueryEngine().execute_local(CT(root), query)
    from bqueryd_tpu.parallel import hostmerge

    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload]))
    # the matching row (d=99) has a NULL basket -> every null-basket row is
    # selected: rows v=20 (g=1), v=30 and v=60 (g=2)
    got = got.sort_values("g").reset_index(drop=True)
    assert got["g"].tolist() == [1, 2]
    assert got["s"].tolist() == [20, 90]


def test_null_dict_key_group_is_dropped(tmp_path):
    """A dict-encoded groupby key with nulls (code -1) must NOT produce a
    group: null-key rows vanish from the aggregation (pandas dropna
    semantics, and the mesh executor's convention).  Regression test — the
    old single-shard path re-factorized -1 into a real group whose collect
    then indexed key_values[-1], emitting a duplicate of the LAST key with
    the null rows' sum."""
    from bqueryd_tpu.storage.ctable import ctable as CT

    df = pd.DataFrame(
        {"k": ["a", None, "b", "a", None], "v": [1, 2, 3, 4, 5]}
    )
    root = str(tmp_path / "nullkey.bcolz")
    CT.fromdataframe(df, root)
    query = GroupByQuery(["k"], [["v", "sum", "s"]], [], aggregate=True)
    payload = QueryEngine().execute_local(CT(root), query)
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload]))
    got = got.sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == ["a", "b"]
    assert got["s"].tolist() == [5, 3]


def test_null_dict_key_multikey_both_paths(tmp_path, monkeypatch):
    """Multi-key composites poison null keys to -1; both the dense-combos
    path (small composite space) and the compaction path (forced via a
    zero cap) must drop them and agree with pandas."""
    from bqueryd_tpu.models import query as qmod
    from bqueryd_tpu.storage.ctable import ctable as CT

    df = pd.DataFrame(
        {
            "k": ["a", None, "b", "a", None, "b", "a"],
            "g": [1, 1, 2, 2, 1, 2, 1],
            "v": [1, 2, 3, 4, 5, 6, 7],
        }
    )
    root = str(tmp_path / "nullmk.bcolz")
    CT.fromdataframe(df, root)
    expected = (
        df.groupby(["k", "g"])["v"].sum().reset_index(name="s")
    )
    for cap in (qmod._DENSE_COMBO_CAP, 0):
        monkeypatch.setattr(qmod, "_DENSE_COMBO_CAP", cap)
        query = GroupByQuery(
            ["k", "g"], [["v", "sum", "s"]], [], aggregate=True
        )
        payload = QueryEngine().execute_local(CT(root), query)
        got = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads([payload])
        )
        got = got.sort_values(["k", "g"]).reset_index(drop=True)
        assert got["k"].tolist() == expected["k"].tolist(), f"cap={cap}"
        assert got["g"].tolist() == expected["g"].tolist(), f"cap={cap}"
        assert got["s"].tolist() == expected["s"].tolist(), f"cap={cap}"


def test_mixed_width_unsigned_shards_merge(tmp_path):
    """One shard stores a column as uint64, a sibling as uint32: the
    engine tags them 'uint64' and None respectively, and the merge must
    reconcile to the unsigned view instead of rejecting the payloads."""
    from bqueryd_tpu.storage.ctable import ctable as CT

    a = pd.DataFrame(
        {"g": [1, 2], "v": np.array([2**63, 7], dtype=np.uint64)}
    )
    b = pd.DataFrame(
        {"g": [1, 2], "v": np.array([5, 9], dtype=np.uint32)}
    )
    pa, pb = str(tmp_path / "a.bcolzs"), str(tmp_path / "b.bcolzs")
    CT.fromdataframe(a, pa)
    CT.fromdataframe(b, pb)
    query = GroupByQuery(
        ["g"],
        [["v", "sum", "s"], ["v", "min", "lo"], ["v", "max", "hi"]],
        [],
        aggregate=True,
    )
    engine = QueryEngine()
    payloads = [
        engine.execute_local(CT(p), query) for p in (pa, pb)
    ]
    for order in (payloads, payloads[::-1]):  # order independence
        got = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads(list(order))
        )
        got = got.sort_values("g").reset_index(drop=True)
        assert got["s"].tolist() == [2**63 + 5, 16]
        assert str(got["s"].dtype) == "uint64"
        # extrema must widen across payload dtypes, not truncate into
        # the narrower first payload's range
        assert got["lo"].tolist() == [5, 7]
        assert got["hi"].tolist() == [2**63, 9]

    # the same mixed-width shards on ONE worker (mesh executor) widen via
    # result_type and must tag the unsigned view the same way
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor

    q2 = GroupByQuery(["g"], [["v", "sum", "s"]], [], aggregate=True)
    payload = MeshQueryExecutor().execute([CT(pa), CT(pb)], q2)
    got3 = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    )
    got3 = got3.sort_values("g").reset_index(drop=True)
    assert got3["s"].tolist() == [2**63 + 5, 16]
    assert str(got3["s"].dtype) == "uint64"


def test_uint64_mixed_with_float_shard_is_refused(tmp_path):
    """A uint64 shard merging with a FLOAT sibling of the same column
    cannot keep the unsigned reinterpretation (the widened float total is
    not mod-2^64 bits); the merge must refuse loudly, not corrupt."""
    import pytest as _pytest

    from bqueryd_tpu.storage.ctable import ctable as CT

    a = pd.DataFrame(
        {"g": [1], "v": np.array([2**63], dtype=np.uint64)}
    )
    b = pd.DataFrame({"g": [1], "v": np.array([0.5], dtype=np.float64)})
    pa, pb = str(tmp_path / "a.bcolzs"), str(tmp_path / "b.bcolzs")
    CT.fromdataframe(a, pa)
    CT.fromdataframe(b, pb)
    query = GroupByQuery(["g"], [["v", "sum", "s"]], [], aggregate=True)
    engine = QueryEngine()
    payloads = [engine.execute_local(CT(p), query) for p in (pa, pb)]
    with _pytest.raises(ValueError, match="disagree"):
        hostmerge.merge_payloads(payloads)


def test_merge_tolerates_payload_without_value_kinds(tmp_path):
    """A payload missing ``value_kinds`` entirely (a worker still running a
    pre-kinds build during a rolling restart) must merge with a new-build
    payload for plain numeric measures — only genuinely incompatible kinds
    (uint64/datetime finalize next to kindless data) may refuse."""
    import pytest as _pytest

    from bqueryd_tpu.storage.ctable import ctable as CT

    a = pd.DataFrame({"g": [1, 2], "v": np.array([3, 4], dtype=np.int64)})
    b = pd.DataFrame({"g": [2, 3], "v": np.array([5, 6], dtype=np.int64)})
    pa, pb = str(tmp_path / "a.bcolzs"), str(tmp_path / "b.bcolzs")
    CT.fromdataframe(a, pa)
    CT.fromdataframe(b, pb)
    query = GroupByQuery(["g"], [["v", "sum", "s"]], [], aggregate=True)
    engine = QueryEngine()
    payloads = [engine.execute_local(CT(p), query) for p in (pa, pb)]
    assert "value_kinds" in payloads[0]
    del payloads[0]["value_kinds"]  # simulate the old-build worker
    for order in (payloads, payloads[::-1]):
        got = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads(list(order))
        ).sort_values("g").reset_index(drop=True)
        assert got["g"].tolist() == [1, 2, 3]
        assert got["s"].tolist() == [3, 9, 6]

    # but a uint64-kind payload next to a kindless one is ambiguous (the
    # kindless sum may be a wrapped int64): still refused
    u = pd.DataFrame(
        {"g": [1], "v": np.array([2**63 + 1], dtype=np.uint64)}
    )
    pu = str(tmp_path / "u.bcolzs")
    CT.fromdataframe(u, pu)
    p_old = engine.execute_local(CT(pa), query)
    del p_old["value_kinds"]
    p_new = engine.execute_local(CT(pu), query)
    assert "uint64" in p_new["value_kinds"]
    with _pytest.raises(ValueError, match="disagree"):
        hostmerge.merge_payloads([p_old, p_new])
