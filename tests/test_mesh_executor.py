"""Mesh executor: multi-shard queries merged on a virtual 8-device CPU mesh.

Covers SURVEY.md §7.2 step 5 (shard fan-out + mesh merge): equivalence of the
psum-merged result against both pandas ground truth and the per-shard
QueryEngine + host-merge path, for single/multi key, filters, string keys,
and shard counts above/below the device count.
"""

import os

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine, ResultPayload
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.parallel.executor import MeshQueryExecutor, make_mesh
from bqueryd_tpu.storage import ctable

N_SHARDS = 5


def taxi_like_df(n=12_000, seed=7):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "VendorID": rng.integers(1, 3, n).astype(np.int64),
            "passenger_count": rng.integers(0, 7, n).astype(np.int64),
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "trip_distance": rng.exponential(3.0, n),
            "fare_amount": rng.gamma(2.0, 7.0, n),
            "flag": rng.choice(["Y", "N", "M"], n),
            "PULocationID": rng.integers(1, 266, n).astype(np.int64),
            "DOLocationID": rng.integers(1, 266, n).astype(np.int64),
        }
    )


@pytest.fixture(scope="module")
def sharded(tmp_path_factory):
    """Unevenly sized shards (so bucket packing + padding is exercised)."""
    df = taxi_like_df()
    base = tmp_path_factory.mktemp("mesh")
    cuts = np.array([0, 1_000, 4_200, 6_000, 9_500, len(df)])
    tables = []
    for i in range(N_SHARDS):
        part = df.iloc[cuts[i] : cuts[i + 1]].reset_index(drop=True)
        root = str(base / f"taxi_{i}.bcolzs")
        ctable.fromdataframe(part, root)
        tables.append(ctable(root, mode="r"))
    return df, tables


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()  # all 8 virtual CPU devices


def mesh_result(tables, *args, **kw):
    query = GroupByQuery(*args, **kw)
    payload = MeshQueryExecutor(mesh=make_mesh()).execute(tables, query)
    wire = ResultPayload.from_bytes(payload.to_bytes())
    return hostmerge.payload_to_dataframe(hostmerge.merge_payloads([wire]))


def pershard_result(tables, *args, **kw):
    query = GroupByQuery(*args, **kw)
    engine = QueryEngine()
    payloads = [engine.execute_local(t, query) for t in tables]
    return hostmerge.payload_to_dataframe(hostmerge.merge_payloads(payloads))


def assert_frames_match(got, expected, key_cols, **kw):
    got = got.sort_values(key_cols).reset_index(drop=True)
    expected = expected.sort_values(key_cols).reset_index(drop=True)
    expected = expected[list(got.columns)]
    pd.testing.assert_frame_equal(
        got, expected, check_dtype=False, check_index_type=False,
        check_column_type=False, **kw
    )


def test_mesh_uses_all_devices(mesh):
    assert mesh.devices.size == 8


def test_single_key_sum_matches_pandas(sharded, mesh):
    df, tables = sharded
    got = mesh_result(
        tables, ["passenger_count"], [["fare_amount", "sum", "fare_amount"]]
    )
    expected = df.groupby("passenger_count")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["passenger_count"])


def test_int64_sum_bit_exact(sharded, mesh):
    """North-star bit-for-bit int64: sums of int64 columns across the psum
    merge equal pandas exactly (no tolerance)."""
    df, tables = sharded
    got = mesh_result(
        tables, ["VendorID"], [["passenger_count", "sum", "s"]]
    ).sort_values("VendorID").reset_index(drop=True)
    expected = (
        df.groupby("VendorID")["passenger_count"].sum().reset_index(name="s")
    )
    assert got["s"].dtype == np.int64
    assert (got["s"].to_numpy() == expected["s"].to_numpy()).all()


def test_multi_key_multi_agg(sharded, mesh):
    df, tables = sharded
    args = (
        ["VendorID", "payment_type"],
        [
            ["fare_amount", "sum", "fare_sum"],
            ["fare_amount", "mean", "fare_mean"],
            ["trip_distance", "max", "dist_max"],
            ["passenger_count", "count", "n"],
        ],
    )
    got = mesh_result(tables, *args)
    g = df.groupby(["VendorID", "payment_type"])
    expected = pd.DataFrame(
        {
            "fare_sum": g["fare_amount"].sum(),
            "fare_mean": g["fare_amount"].mean(),
            "dist_max": g["trip_distance"].max(),
            "n": g["passenger_count"].count(),
        }
    ).reset_index()
    assert_frames_match(got, expected, ["VendorID", "payment_type"])


def test_string_key_across_shard_dictionaries(sharded, mesh):
    """Dict-encoded key columns have *different* per-shard dictionaries;
    alignment must merge by value, not by local code."""
    df, tables = sharded
    got = mesh_result(tables, ["flag"], [["fare_amount", "sum", "fare_amount"]])
    expected = df.groupby("flag")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["flag"])


def test_where_filter_pushdown(sharded, mesh):
    df, tables = sharded
    where = [["trip_distance", ">", 2.0], ["payment_type", "!=", 1]]
    got = mesh_result(
        tables,
        ["payment_type"],
        [["fare_amount", "sum", "fare_amount"]],
        where,
    )
    sel = df[(df.trip_distance > 2.0) & (df.payment_type != 1)]
    expected = sel.groupby("payment_type")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["payment_type"])


def test_high_cardinality_two_key(sharded, mesh):
    """The BASELINE.json stress config: PULocationID x DOLocationID."""
    df, tables = sharded
    got = mesh_result(
        tables,
        ["PULocationID", "DOLocationID"],
        [["fare_amount", "sum", "fare_amount"]],
    )
    expected = (
        df.groupby(["PULocationID", "DOLocationID"])["fare_amount"]
        .sum()
        .reset_index()
    )
    assert_frames_match(got, expected, ["PULocationID", "DOLocationID"])


def test_matches_pershard_hostmerge_path(sharded, mesh):
    """Device psum merge and host value-keyed merge are the same function."""
    df, tables = sharded
    args = (
        ["payment_type"],
        [["fare_amount", "mean", "m"], ["fare_amount", "min", "lo"]],
    )
    got = mesh_result(tables, *args)
    expected = pershard_result(tables, *args)
    assert_frames_match(got, expected, ["payment_type"])


def test_fewer_shards_than_devices(sharded, mesh):
    df, tables = sharded
    got = mesh_result(
        tables[:2], ["VendorID"], [["fare_amount", "sum", "fare_amount"]]
    )
    expected = (
        pd.concat([t.todataframe() for t in tables[:2]])
        .groupby("VendorID")["fare_amount"]
        .sum()
        .reset_index()
    )
    assert_frames_match(got, expected, ["VendorID"])


def test_more_shards_than_devices(tmp_path, mesh):
    df = taxi_like_df(n=3_000, seed=11)
    bounds = np.linspace(0, len(df), 14, dtype=int)  # 13 shards > 8 devices
    parts = [df.iloc[a:b] for a, b in zip(bounds[:-1], bounds[1:])]
    tables = []
    for i, part in enumerate(parts):
        root = str(tmp_path / f"s{i}.bcolzs")
        ctable.fromdataframe(part.reset_index(drop=True), root)
        tables.append(ctable(root, mode="r"))
    got = mesh_result(
        tables, ["payment_type"], [["fare_amount", "sum", "fare_amount"]]
    )
    expected = df.groupby("payment_type")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["payment_type"])


def test_prunes_unmatchable_shards_to_empty(sharded, mesh):
    _df, tables = sharded
    payload = MeshQueryExecutor(mesh=mesh).execute(
        tables,
        GroupByQuery(
            ["VendorID"],
            [["fare_amount", "sum", "s"]],
            [["trip_distance", ">", 1e9]],
        ),
    )
    # min/max pruning drops every shard before any device work
    assert payload["kind"] == "empty"


def test_rejects_non_mergeable_ops(sharded, mesh):
    _df, tables = sharded
    with pytest.raises(ValueError, match="mergeable"):
        MeshQueryExecutor(mesh=mesh).execute(
            tables,
            GroupByQuery(["VendorID"], [["payment_type", "count_distinct", "d"]]),
        )
    assert not MeshQueryExecutor.supports(
        GroupByQuery(["VendorID"], [["fare_amount", "sum", "s"]], aggregate=False)
    )


def test_packed_fetch_matches_unpacked(tmp_path, monkeypatch):
    """The single-buffer packed fetch (bitcast-to-uint64 concat inside the
    mesh program) must be lossless for every partial dtype: int64 sums,
    float32/float64 sums, counts, and min/max carried on narrowed wire
    dtypes (int8/int16)."""
    import pandas as pd

    from bqueryd_tpu.models.query import GroupByQuery
    from bqueryd_tpu.parallel import executor as ex
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor
    from bqueryd_tpu.storage.ctable import ctable

    rng = np.random.default_rng(21)
    n = 4000
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 9, n).astype(np.int64),
            "big": rng.integers(-(2**60), 2**60, n).astype(np.int64),
            "small": rng.integers(-100, 100, n).astype(np.int64),  # int8 wire
            "f32": (rng.random(n) * 100).astype(np.float32),
            "f64": rng.random(n).astype(np.float64),
        }
    )
    tables = []
    for i in range(3):
        root = str(tmp_path / f"p{i}.bcolzs")
        ctable.fromdataframe(df.iloc[i::3], root)
        tables.append(ctable(root))
    query = GroupByQuery(
        ["g"],
        [
            ["big", "sum", "s"],
            ["small", "min", "lo"],
            ["small", "max", "hi"],
            ["f32", "mean", "m32"],
            ["f64", "sum", "s64"],
            ["big", "count", "n"],
        ],
        [],
        aggregate=True,
    )

    def run():
        ex._mesh_program.cache_clear()
        return MeshQueryExecutor().execute(tables, query)

    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "1")
    packed = run()
    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "0")
    unpacked = run()
    from bqueryd_tpu.parallel import hostmerge

    df_p = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([packed]))
    df_u = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([unpacked]))
    pd.testing.assert_frame_equal(
        df_p.sort_values("g").reset_index(drop=True),
        df_u.sort_values("g").reset_index(drop=True),
        check_column_type=False,
    )
    expect = df.groupby("g")["big"].sum().sort_index()
    np.testing.assert_array_equal(
        df_p.sort_values("g")["s"].to_numpy(), expect.to_numpy()
    )


def test_packed_fetch_spec_stable_across_kernel_routes(tmp_path, monkeypatch):
    """Two row counts can route the SAME query shape through different
    kernels (MXU vs scatter past BQUERYD_TPU_MATMUL_CELLS), whose float
    partial dtypes differ (f64 vs f32).  Each width must decode with its
    own trace's spec — re-running the small query after the large one must
    not corrupt its float aggregates (the shared-spec retrace bug)."""
    import pandas as pd

    from bqueryd_tpu.models.query import GroupByQuery
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor
    from bqueryd_tpu.storage.ctable import ctable

    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "1")
    # rows*groups above this forces the scatter route for the LARGE table
    monkeypatch.setenv("BQUERYD_TPU_MATMUL_CELLS", str(5000 * 7))

    rng = np.random.default_rng(31)

    def build(name, n):
        df = pd.DataFrame(
            {
                "g": rng.integers(0, 7, n).astype(np.int64),
                "v": (rng.random(n) * 100).astype(np.float32),
            }
        )
        root = str(tmp_path / name)
        ctable.fromdataframe(df, root)
        return df, [ctable(root)]

    df_small, small = build("small.bcolz", 2000)
    df_large, large = build("large.bcolz", 60_000)
    query = GroupByQuery(["g"], [["v", "mean", "m"]], [], aggregate=True)
    executor = MeshQueryExecutor()

    def result_means(tables):
        payload = executor.execute(tables, query)
        df = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads([payload])
        )
        return df.sort_values("g")["m"].to_numpy()

    def expect_means(df):
        return df.groupby("g")["v"].mean().sort_index().to_numpy()

    np.testing.assert_allclose(
        result_means(small), expect_means(df_small), rtol=1e-6
    )
    np.testing.assert_allclose(
        result_means(large), expect_means(df_large), rtol=1e-6
    )
    # the hazard: small again, after large's trace populated the cache
    np.testing.assert_allclose(
        result_means(small), expect_means(df_small), rtol=1e-6
    )


def test_cold_path_hits_disk_sidecars_and_matches(sharded, mesh):
    """Warm query -> clear every process cache (the bench's cold reset) ->
    re-query: the alignment must come back from the on-disk factorize /
    composite sidecars bit-identically, for both single- and multi-key."""
    from bqueryd_tpu.storage.ctable import free_cachemem

    df, tables = sharded
    ex = MeshQueryExecutor(mesh=make_mesh())
    for gcols in (["passenger_count"], ["VendorID", "payment_type"]):
        query = GroupByQuery(
            gcols, [["fare_amount", "sum", "s"]], [], aggregate=True
        )
        warm = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads([ex.execute(tables, query)])
        )
        # sidecars must exist next to the first shard now
        first = tables[0].rootdir
        assert os.path.isfile(
            os.path.join(first, "cols", gcols[0], "factor.npz")
        )
        ex.clear_caches()
        free_cachemem()
        # poison the factorizer: the cold query must be served entirely by
        # the sidecars, or an always-miss load regression could hide behind
        # a bit-identical recompute
        from bqueryd_tpu import ops as ops_mod

        real_factorize = ops_mod.factorize
        ops_mod.factorize = lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("cold align recomputed instead of sidecar hit")
        )
        try:
            cold = hostmerge.payload_to_dataframe(
                hostmerge.merge_payloads([ex.execute(tables, query)])
            )
        finally:
            ops_mod.factorize = real_factorize
        assert_frames_match(cold, warm, gcols)
        expected = (
            df.groupby(gcols, as_index=False)["fare_amount"]
            .sum()
            .rename(columns={"fare_amount": "s"})
        )
        assert_frames_match(cold, expected, gcols)


def test_program_bucket_properties():
    from bqueryd_tpu import ops

    for n in (1, 9, 16, 17, 100, 1000, 70225, 10_000_000):
        for fine in (False, True):
            b = ops.program_bucket(n, fine=fine)
            assert b >= n
            # bounded padding: <=12.5% coarse, <=3.2% fine (+1 step slack)
            limit = 1.032 if fine else 1.13
            assert n <= 16 or b <= int(n * limit) + 1, (n, fine, b)
            # stability: the whole step maps to one bucket
            assert ops.program_bucket(b, fine=fine) == b


def test_group_drift_reuses_compiled_program(tmp_path, mesh):
    """Two queries whose group counts differ but land in the same bucket
    must share one compiled mesh program — the point of shape bucketing
    (every exact cardinality was its own 20-40s compile on a tunneled
    backend)."""
    from bqueryd_tpu.parallel import executor as ex_mod

    dfs = []
    for n_vals in (900, 905):  # both bucket to the same grid point
        rng = np.random.default_rng(n_vals)
        dfs.append(
            pd.DataFrame(
                {
                    "g": rng.integers(0, n_vals, 20_000).astype(np.int64),
                    "v": rng.integers(-100, 100, 20_000).astype(np.int64),
                }
            )
        )
    tables = []
    for i, df in enumerate(dfs):
        root = str(tmp_path / f"drift_{i}.bcolzs")
        ctable.fromdataframe(df, root)
        tables.append(ctable(root, mode="r"))

    ex = MeshQueryExecutor(mesh=make_mesh())
    query = GroupByQuery(["g"], [["v", "sum", "s"]], [], aggregate=True)
    before = ex_mod._mesh_program.cache_info()
    for df, t in zip(dfs, tables):
        got = hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads([ex.execute([t], query)])
        ).sort_values("g").reset_index(drop=True)
        expected = (
            df.groupby("g", as_index=False)["v"].sum()
            .rename(columns={"v": "s"})
        )
        assert_frames_match(got, expected, ["g"])
    after = ex_mod._mesh_program.cache_info()
    assert after.misses == before.misses + 1, (
        "group-count drift within one bucket must not recompile "
        f"(before={before}, after={after})"
    )
    assert after.hits >= before.hits + 1


def test_threaded_alignment_matches_sequential(sharded, mesh, monkeypatch):
    """BQUERYD_TPU_ALIGN_THREADS>1 must produce the identical alignment as
    the sequential path (single-core CI degrades to sequential silently, so
    force the pool on)."""
    df, tables = sharded
    for gcols in (["passenger_count"], ["VendorID", "payment_type"]):
        query = GroupByQuery(
            gcols, [["fare_amount", "sum", "s"]], [], aggregate=True
        )
        monkeypatch.setenv("BQUERYD_TPU_ALIGN_THREADS", "1")
        seq = MeshQueryExecutor(mesh=make_mesh())._global_key_space(
            tables, query, QueryEngine()
        )
        monkeypatch.setenv("BQUERYD_TPU_ALIGN_THREADS", "4")
        par = MeshQueryExecutor(mesh=make_mesh())._global_key_space(
            tables, query, QueryEngine()
        )
        s_dense, s_combos, s_cards, s_vals = seq
        p_dense, p_combos, p_cards, p_vals = par
        assert s_cards == p_cards
        np.testing.assert_array_equal(s_combos, p_combos)
        for a, b in zip(s_dense, p_dense):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for col in s_vals:
            np.testing.assert_array_equal(s_vals[col], p_vals[col])


def test_transient_runtime_error_retried_once(sharded, mesh, monkeypatch):
    """One transient JaxRuntimeError out of the merged-program dispatch
    (tunneled backends surface flaky remote-compile INTERNAL errors) must
    be retried in place so the mesh path still answers; a second failure
    propagates (the worker then degrades to the engine path)."""
    import jax

    from bqueryd_tpu.parallel import executor as ex_mod

    df, tables = sharded
    real = ex_mod._mesh_partials
    calls = {"n": 0}

    def flaky(*args, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: remote_compile: HTTP 500"
            )
        return real(*args, **kw)

    monkeypatch.setattr(ex_mod, "_mesh_partials", flaky)
    got = mesh_result(
        tables, ["passenger_count"], [["fare_amount", "sum", "fare_amount"]]
    )
    assert calls["n"] == 2, "first failure must be retried exactly once"
    expected = df.groupby("passenger_count")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["passenger_count"])

    # persistent failure propagates after the single retry
    calls["n"] = 0

    def always_fail(*args, **kw):
        calls["n"] += 1
        raise jax.errors.JaxRuntimeError("INTERNAL: remote_compile: HTTP 500")

    monkeypatch.setattr(ex_mod, "_mesh_partials", always_fail)
    with pytest.raises(jax.errors.JaxRuntimeError):
        mesh_result(
            tables, ["VendorID"], [["fare_amount", "sum", "fare_amount"]]
        )
    assert calls["n"] == 2


def test_internal_error_does_not_latch_packed_fetch_off(
    sharded, mesh, monkeypatch
):
    """A transient INTERNAL JaxRuntimeError during the packed-fetch program
    must NOT set the process-lifetime _packed_fetch_broken latch (that
    would put every later query on per-leaf fetch — one transport
    round-trip per result leaf on tunneled devices); only a deterministic
    rejection (non-INTERNAL) is evidence against packing."""
    import jax

    from bqueryd_tpu.parallel import executor as ex_mod

    df, tables = sharded
    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "1")
    monkeypatch.setattr(ex_mod, "_packed_fetch_broken", False)
    monkeypatch.setattr(ex_mod, "_packed_transient_count", 0)
    real_program = ex_mod._mesh_program
    calls = {"n": 0}

    def flaky_program(*args, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: remote_compile: HTTP 500"
            )
        return real_program(*args, **kw)

    monkeypatch.setattr(ex_mod, "_mesh_program", flaky_program)
    got = mesh_result(
        tables, ["passenger_count"], [["fare_amount", "sum", "fare_amount"]]
    )
    assert not ex_mod._packed_fetch_broken, (
        "transient INTERNAL error must not disable packed fetch for the "
        "process"
    )
    expected = df.groupby("passenger_count")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["passenger_count"])

    # a deterministic rejection DOES latch (and the query still answers
    # via per-leaf fetch, not an engine degrade)
    monkeypatch.setattr(ex_mod, "_packed_fetch_broken", False)
    state = {"first": True}

    def rejecting_program(*args, **kw):
        # reject only the packed variant (pack flag is positional arg 6)
        if args[6] and state["first"]:
            state["first"] = False
            raise jax.errors.JaxRuntimeError(
                "INVALID_ARGUMENT: bitcast not supported"
            )
        return real_program(*args, **kw)

    monkeypatch.setattr(ex_mod, "_mesh_program", rejecting_program)
    got2 = mesh_result(
        tables, ["VendorID"], [["fare_amount", "sum", "fare_amount"]]
    )
    assert ex_mod._packed_fetch_broken, (
        "deterministic packed-program rejection must latch per-leaf fetch"
    )
    expected2 = df.groupby("VendorID")["fare_amount"].sum().reset_index()
    assert_frames_match(got2, expected2, ["VendorID"])


def test_repeated_transient_failures_latch_past_cap(
    sharded, mesh, monkeypatch
):
    """A deterministic failure that carries a transient status (an XLA
    lowering bug classed INTERNAL) must not dodge the per-leaf latch
    forever: past _PACKED_TRANSIENT_LIMIT consecutive packed failures the
    latch sets anyway and the query answers via per-leaf fetch."""
    import jax

    from bqueryd_tpu.parallel import executor as ex_mod

    df, tables = sharded
    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "1")
    monkeypatch.setattr(ex_mod, "_packed_fetch_broken", False)
    monkeypatch.setattr(ex_mod, "_packed_transient_count", 0)
    real_program = ex_mod._mesh_program

    def always_internal_on_packed(*args, **kw):
        if args[6]:  # the packed program variant
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: Mosaic lowering failed (deterministic)"
            )
        return real_program(*args, **kw)

    monkeypatch.setattr(ex_mod, "_mesh_program", always_internal_on_packed)
    # first query: both packed attempts raise transiently -> propagates
    with pytest.raises(jax.errors.JaxRuntimeError):
        mesh_result(
            tables, ["passenger_count"],
            [["fare_amount", "sum", "fare_amount"]],
        )
    # second query: cap reached -> latch sets, per-leaf fetch answers
    got = mesh_result(
        tables, ["VendorID"], [["fare_amount", "sum", "fare_amount"]]
    )
    assert ex_mod._packed_fetch_broken
    expected = df.groupby("VendorID")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["VendorID"])


def test_backend_outage_does_not_latch_packed_fetch(
    sharded, mesh, monkeypatch
):
    """When packed AND per-leaf both fail (whole backend down), the failure
    carries no packed-specific signal: the per-leaf latch must stay unset
    so packing resumes once the backend recovers."""
    import jax

    from bqueryd_tpu.parallel import executor as ex_mod

    df, tables = sharded
    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "1")
    monkeypatch.setattr(ex_mod, "_packed_fetch_broken", False)
    # at the cap: the next packed failure takes the latch-pending path
    monkeypatch.setattr(
        ex_mod, "_packed_transient_count", ex_mod._PACKED_TRANSIENT_LIMIT
    )
    real_program = ex_mod._mesh_program
    down = {"is": True}

    def outage_program(*args, **kw):
        if down["is"]:
            raise jax.errors.JaxRuntimeError("UNAVAILABLE: tunnel down")
        return real_program(*args, **kw)

    monkeypatch.setattr(ex_mod, "_mesh_program", outage_program)
    with pytest.raises(jax.errors.JaxRuntimeError):
        mesh_result(
            tables, ["passenger_count"],
            [["fare_amount", "sum", "fare_amount"]],
        )
    assert not ex_mod._packed_fetch_broken, (
        "an outage that also kills per-leaf fetch must not latch packing off"
    )
    # backend recovers: packed fetch resumes and the query answers
    down["is"] = False
    got = mesh_result(
        tables, ["VendorID"], [["fare_amount", "sum", "fare_amount"]]
    )
    assert not ex_mod._packed_fetch_broken
    expected = df.groupby("VendorID")["fare_amount"].sum().reset_index()
    assert_frames_match(got, expected, ["VendorID"])


def test_route_flag_flip_rebuilds_mesh_program(sharded, mesh, monkeypatch):
    """The kernel route is decided at TRACE time inside the cached mesh
    program: flipping a route flag (the bench's pallas variants, live
    re-tuning) must be a cache MISS that re-traces, not a silent hit that
    keeps serving the old route (the r4 bench's sharded_pallas number was
    exactly that sham, on the CPU side)."""
    from bqueryd_tpu.parallel import executor as ex_mod

    df, tables = sharded
    monkeypatch.delenv("BQUERYD_TPU_PALLAS", raising=False)
    args = (["passenger_count"], [["passenger_count", "sum", "s"]])
    mesh_result(tables, *args)
    before = ex_mod._mesh_program.cache_info()
    # same query, same flags: cache hit
    mesh_result(tables, *args)
    mid = ex_mod._mesh_program.cache_info()
    assert mid.misses == before.misses, "same-flags repeat must not re-trace"
    # flipped flag: cache miss (fresh trace through the dispatcher)
    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    got = mesh_result(tables, *args)
    after = ex_mod._mesh_program.cache_info()
    assert after.misses > mid.misses, "flag flip must rebuild the program"
    got = got.sort_values("passenger_count").reset_index(drop=True)
    truth = df.groupby("passenger_count")["passenger_count"].sum()
    np.testing.assert_array_equal(
        got["s"].to_numpy(), truth.sort_index().to_numpy()
    )


def test_hicard_pallas_route_through_mesh(tmp_path, monkeypatch):
    """The group-tiled hicard Pallas kernel inside the full mesh program
    (shard_map + psum + packed fetch) — the exact composition the TPU
    bench's highcard+pallas variant executes — must stay bit-exact."""
    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    rng = np.random.default_rng(31)
    n, ng = 30_000, 14_000  # observed uniques safely past matmul_groups_limit
    df = pd.DataFrame(
        {
            "k": rng.integers(0, ng, n).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, n).astype(np.int64),
        }
    )
    tables = []
    for i in range(3):
        root = str(tmp_path / f"hc{i}.bcolzs")
        ctable.fromdataframe(df.iloc[i::3].reset_index(drop=True), root)
        tables.append(ctable(root, mode="r"))
    from bqueryd_tpu.ops import groupby as gb

    # the executor routes on OBSERVED combos, not the fixture's nominal
    # cardinality: guard with the value the gate actually sees, so a
    # fixture drift below matmul_groups_limit cannot silently demote the
    # test to the non-Pallas route
    observed = df["k"].nunique()
    assert observed > gb.matmul_groups_limit(), (
        f"fixture drifted: {observed} observed groups no longer clears "
        f"matmul_groups_limit ({gb.matmul_groups_limit()})"
    )
    assert gb._hicard_matmul_profitable(
        (df["v"].to_numpy(),), ("sum",), n, observed
    ), "fixture must hit the hicard gate"
    got = mesh_result(tables, ["k"], [["v", "sum", "s"]])
    got = got.sort_values("k").reset_index(drop=True)
    exp = (
        df.groupby("k", as_index=False)["v"].sum()
        .rename(columns={"v": "s"})
        .sort_values("k").reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["k"].to_numpy(), exp["k"].to_numpy())
    np.testing.assert_array_equal(got["s"].to_numpy(), exp["s"].to_numpy())
