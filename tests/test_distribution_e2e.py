"""End-to-end dataset distribution over a real blob backend.

The reference exercised its full download path against localstack
(reference tests/test_download.py:95-141); the in-process cluster fixture in
tests/test_rpc_cluster.py fakes the fetch with a DummyDownloader.  These
tests run the REAL pipeline — ``zip_to_file`` → blob ``put`` →
``rpc.download(wait=True)`` → streamed ``download_file`` + unzip →
movebcolz two-phase activation → the new shard answers a groupby — using
:class:`bqueryd_tpu.blob.LocalFSBackend` as the object store, plus a
mid-flight cancellation case and a liveness check during a slow fetch (the
fetch runs on the downloader's thread pool, so WRM heartbeats continue).
"""

import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tests.conftest import wait_until


@pytest.fixture
def pipeline(tmp_path, mem_store_url):
    """Controller + calc worker + REAL downloader + mover sharing one
    serving dir, with a LocalFSBackend 'object store'."""
    from bqueryd_tpu.blob import LocalFSBackend
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import DownloaderNode, MoveBcolzNode, WorkerNode

    serving = tmp_path / "serving"
    blob_root = tmp_path / "blobs"
    serving.mkdir()
    blob_root.mkdir()
    backend = LocalFSBackend(root=str(blob_root))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    calc = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(serving),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    downloader = DownloaderNode(
        coordination_url=mem_store_url,
        data_dir=str(serving),
        loglevel=logging.WARNING,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    downloader.download_interval = 0.2
    downloader.blob_backend = backend
    mover = MoveBcolzNode(
        coordination_url=mem_store_url,
        data_dir=str(serving),
        loglevel=logging.WARNING,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    mover.download_interval = 0.2

    nodes = (controller, calc, downloader, mover)
    threads = [threading.Thread(target=n.go, daemon=True) for n in nodes]
    for t in threads:
        t.start()
    wait_until(
        lambda: len(controller.worker_map) >= 3, desc="nodes registered"
    )
    rpc = RPC(coordination_url=mem_store_url, timeout=60,
              loglevel=logging.WARNING)
    yield {
        "rpc": rpc,
        "controller": controller,
        "calc": calc,
        "downloader": downloader,
        "mover": mover,
        "serving": serving,
        "backend": backend,
    }
    for n in nodes:
        n.running = False
    for t in threads:
        t.join(timeout=5)


def test_full_distribution_pipeline(pipeline, tmp_path):
    """zip → put → download(wait=True) → real fetch/unzip → activation →
    the freshly distributed shard answers a groupby."""
    from bqueryd_tpu.download import METADATA_FILENAME
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.utils.net import zip_to_file

    df = pd.DataFrame(
        {
            "g": np.arange(500, dtype=np.int64) % 7,
            "v": np.arange(500, dtype=np.int64),
        }
    )
    build = tmp_path / "build"
    build.mkdir()
    src_root = build / "fresh.bcolzs"
    ctable.fromdataframe(df, str(src_root))
    zip_path, _crc = zip_to_file(str(src_root), str(build))
    pipeline["backend"].put("bcolz", "fresh.bcolzs.zip", zip_path)

    result = pipeline["rpc"].download(
        filenames=["fresh.bcolzs.zip"], bucket="bcolz", wait=True,
        scheme="localfs",
    )
    assert result == "DONE"

    # activation: shard dir swapped into serving with provenance metadata
    activated = pipeline["serving"] / "fresh.bcolzs"
    wait_until(activated.is_dir, desc="shard activated into serving dir")
    assert (activated / METADATA_FILENAME).is_file()

    # the calc worker's rescan picks it up and it answers queries
    wait_until(
        lambda: "fresh.bcolzs" in pipeline["controller"].files_map,
        desc="new shard advertised",
    )
    got = pipeline["rpc"].groupby(
        ["fresh.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
    )
    expect = df.groupby("g")["v"].sum().to_dict()
    assert dict(zip(got["g"].tolist(), got["v_sum"].tolist())) == expect


class SlowBackend:
    """Streams a small payload in many chunks with a delay per chunk, firing
    progress_cb between chunks so cancellation checks run."""

    def __init__(self, total_chunks=40, delay=0.1):
        self.total_chunks = total_chunks
        self.delay = delay
        self.started = threading.Event()
        self.finished = threading.Event()

    def fetch(self, bucket, key, dest_path, progress_cb=None):
        self.started.set()
        try:
            with open(dest_path, "wb") as f:
                for i in range(self.total_chunks):
                    f.write(b"x" * 128)
                    if progress_cb:
                        progress_cb((i + 1) * 128)
                    time.sleep(self.delay)
        finally:
            self.finished.set()


def test_heartbeats_continue_during_slow_fetch(pipeline):
    """The fetch runs on the download pool, so the downloader's liveness
    (WRM last_seen at the controller) keeps advancing while the blob stream
    crawls — the event-loop-blocking bug class from round 1."""
    slow = SlowBackend(total_chunks=40, delay=0.1)  # ~4s fetch
    pipeline["downloader"].blob_backend = slow
    controller = pipeline["controller"]
    downloader_id = pipeline["downloader"].worker_id

    ticket = pipeline["rpc"].download(
        filenames=["slow.bcolzs.zip"], bucket="bcolz", wait=False,
        scheme="localfs",
    )
    wait_until(slow.started.is_set, desc="fetch started")
    seen_before = controller.worker_map[downloader_id]["last_seen"]
    time.sleep(1.0)
    assert not slow.finished.is_set(), "fetch finished too fast to observe"
    seen_during = controller.worker_map[downloader_id]["last_seen"]
    assert seen_during > seen_before, (
        "downloader stopped heartbeating while fetching"
    )
    # let it finish; the fake payload isn't a zip, so the slot just goes DONE
    wait_until(slow.finished.is_set, timeout=15, desc="fetch finished")
    pipeline["rpc"].delete_download(ticket)


def test_midflight_cancellation_aborts_download(pipeline):
    """delete_download mid-fetch deletes the slots; the in-flight download
    observes the missing slot and aborts, removing its staging dir
    (reference bqueryd/worker.py:418-428)."""
    from bqueryd_tpu.download import incoming_dir

    slow = SlowBackend(total_chunks=200, delay=0.1)  # ~20s unless cancelled
    pipeline["downloader"].blob_backend = slow
    rpc = pipeline["rpc"]

    ticket = rpc.download(
        filenames=["cancelme.bcolzs.zip"], bucket="bcolz", wait=False,
        scheme="localfs",
    )
    wait_until(slow.started.is_set, desc="fetch started")
    assert rpc.delete_download(ticket) is True
    # CancelWatch polls every ~2s: the fetch must abort well before the
    # 20s it would otherwise take
    wait_until(slow.finished.is_set, timeout=10, desc="fetch aborted")
    staging = incoming_dir(pipeline["downloader"], ticket)
    wait_until(
        lambda: not os.path.exists(staging), desc="staging cleaned up"
    )
    # ticket record is gone: nothing to activate
    assert all(t != ticket for t, _ in rpc.downloads())


def test_downloads_shape_matches_reference(pipeline):
    """downloads() returns (ticket, "done/total") summary tuples and
    get_download_data() returns {full_store_key: {slot: value}} — the
    reference client's exact output shapes (reference bqueryd/rpc.py:181-199),
    so tooling written against the reference keeps working."""
    import re

    import bqueryd_tpu

    rpc = pipeline["rpc"]
    ticket = rpc.download(
        filenames=["shape1.bcolzs.zip", "shape2.bcolzs.zip"],
        bucket="bcolz", wait=False, scheme="localfs",
    )
    try:
        raw = rpc.get_download_data()
        key = bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + ticket
        assert key in raw
        assert isinstance(raw[key], dict) and len(raw[key]) == 2
        for slot, value in raw[key].items():
            assert "_" in slot and "_" in value  # "<node>_<url>" / "<ts>_<state>"

        summaries = dict(rpc.downloads())
        assert ticket in summaries
        assert re.fullmatch(r"\d+/2", summaries[ticket])

        rich = dict(rpc.download_progress())
        assert ticket in rich
        assert all(
            isinstance(k, tuple) and len(k) == 2 for k in rich[ticket]
        )
    finally:
        rpc.delete_download(ticket)


class FakeBoto3S3:
    """In-memory boto3 S3 client double covering the surface S3Backend uses:
    get_object (streaming Body) + upload_file.  ``fail_first`` get_object
    Bodies raise mid-stream to exercise download_file's retry loop — the
    failure-injection the reference's localstack tests couldn't do
    (reference tests/test_download.py:95-141)."""

    def __init__(self, fail_first=0):
        self.objects = {}  # (bucket, key) -> bytes
        self.fail_first = fail_first
        self.get_calls = 0

    def upload_file(self, src_path, bucket, key):
        with open(src_path, "rb") as f:
            self.objects[(bucket, key)] = f.read()

    def get_object(self, Bucket, Key):
        if (Bucket, Key) not in self.objects:
            raise KeyError(f"NoSuchKey: s3://{Bucket}/{Key}")
        self.get_calls += 1
        data = self.objects[(Bucket, Key)]
        explode = self.get_calls <= self.fail_first

        class Body:
            def __init__(self):
                self.pos = 0

            def read(self, n):
                if explode and self.pos >= len(data) // 2:
                    raise IOError("connection reset mid-stream")
                chunk = data[self.pos:self.pos + n]
                self.pos += len(chunk)
                return chunk

        return {"Body": Body()}


def test_s3_backend_streams_chunks_with_progress(tmp_path, monkeypatch):
    """S3Backend.fetch streams the object in CHUNK_SIZE pieces, firing
    progress_cb with CUMULATIVE byte counts after every chunk."""
    from bqueryd_tpu import blob as blob_mod
    from bqueryd_tpu.blob import S3Backend

    monkeypatch.setattr(blob_mod, "CHUNK_SIZE", 128)
    client = FakeBoto3S3()
    payload = bytes(range(256)) * 2  # 512 bytes -> 4 chunks of 128
    obj_path = tmp_path / "obj"
    obj_path.write_bytes(payload)
    client.upload_file(str(obj_path), "bcolz", "shard.zip")

    backend = S3Backend(client=client)
    seen = []
    dest = tmp_path / "out"
    backend.fetch("bcolz", "shard.zip", str(dest), progress_cb=seen.append)
    assert dest.read_bytes() == payload
    assert seen == [128, 256, 384, 512]


def test_s3_fetch_retry_after_midstream_failure(tmp_path, mem_store_url):
    """A connection reset mid-stream fails the first attempt;
    download_file's retry loop re-fetches and the second attempt lands the
    complete object."""
    from bqueryd_tpu.blob import S3Backend
    from bqueryd_tpu.download import download_file, set_progress
    from bqueryd_tpu.coordination import coordination_store

    client = FakeBoto3S3(fail_first=1)
    payload = os.urandom(4096)
    obj_path = tmp_path / "obj"
    obj_path.write_bytes(payload)
    client.upload_file(str(obj_path), "bcolz", "retry.bin")

    class WorkerDouble:
        node_name = "n1"
        data_dir = str(tmp_path / "serving")
        store = coordination_store(mem_store_url)
        blob_backend = S3Backend(client=client)

        class logger:
            info = warning = exception = staticmethod(
                lambda *a, **k: None
            )

    os.makedirs(WorkerDouble.data_dir, exist_ok=True)
    set_progress(WorkerDouble.store, "n1", "tk1", "s3://bcolz/retry.bin", -1)
    download_file(WorkerDouble(), "tk1", "s3://bcolz/retry.bin")
    assert client.get_calls == 2, "exactly one retry expected"
    staged = os.path.join(
        WorkerDouble.data_dir, "incoming", "tk1", "retry.bin"
    )
    assert open(staged, "rb").read() == payload
    state = WorkerDouble.store.hget(
        "bqueryd_download_ticket_tk1", "n1_s3://bcolz/retry.bin"
    )
    assert state.endswith("_DONE")


def test_full_distribution_pipeline_over_s3(pipeline, tmp_path):
    """The complete zip → put → download(wait=True) → unzip → two-phase
    activation → query flow with the REAL S3Backend code path (fake boto3
    client underneath) — the reference's localstack scenario (reference
    tests/test_download.py:95-141) without the docker dependency."""
    from bqueryd_tpu.blob import S3Backend
    from bqueryd_tpu.download import METADATA_FILENAME
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.utils.net import zip_to_file

    client = FakeBoto3S3()
    s3 = S3Backend(client=client)
    pipeline["downloader"].blob_backend = s3

    df = pd.DataFrame(
        {
            "g": np.arange(300, dtype=np.int64) % 5,
            "v": np.arange(300, dtype=np.int64),
        }
    )
    build = tmp_path / "build_s3"
    build.mkdir()
    src_root = build / "via_s3.bcolzs"
    ctable.fromdataframe(df, str(src_root))
    zip_path, _crc = zip_to_file(str(src_root), str(build))
    s3.put("bcolz", "via_s3.bcolzs.zip", zip_path)

    result = pipeline["rpc"].download(
        filenames=["via_s3.bcolzs.zip"], bucket="bcolz", wait=True,
        scheme="s3",
    )
    assert result == "DONE"
    activated = pipeline["serving"] / "via_s3.bcolzs"
    wait_until(activated.is_dir, desc="shard activated via s3 path")
    assert (activated / METADATA_FILENAME).is_file()
    wait_until(
        lambda: "via_s3.bcolzs" in pipeline["controller"].files_map,
        desc="s3-distributed shard advertised",
    )
    got = pipeline["rpc"].groupby(
        ["via_s3.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
    )
    expect = df.groupby("g")["v"].sum().to_dict()
    assert dict(zip(got["g"].tolist(), got["v_sum"].tolist())) == expect


class FakeAzureBlobService:
    """In-memory azure-storage-blob service double covering the surface
    AzureBackend uses: get_blob_client(container, blob) -> client with
    download_blob().chunks() and upload_blob() — mirroring FakeBoto3S3 so
    the azure scheme gets the same coverage the reference gave its cloud
    path via localstack (reference bqueryd/worker.py:519-556)."""

    def __init__(self, chunk_size=128):
        self.blobs = {}  # (container, blob) -> bytes
        self.chunk_size = chunk_size

    def get_blob_client(self, container, blob):
        service = self

        class BlobClient:
            def upload_blob(self, fobj, overwrite=False):
                key = (container, blob)
                if not overwrite and key in service.blobs:
                    raise ValueError(f"blob exists: {container}/{blob}")
                service.blobs[key] = fobj.read()

            def download_blob(self):
                if (container, blob) not in service.blobs:
                    raise KeyError(f"BlobNotFound: {container}/{blob}")
                data = service.blobs[(container, blob)]
                size = service.chunk_size

                class Stream:
                    @staticmethod
                    def chunks():
                        for i in range(0, len(data), size):
                            yield data[i:i + size]

                return Stream()

        return BlobClient()


def test_azure_backend_streams_chunks_with_progress(tmp_path):
    """AzureBackend.fetch iterates the download stream's chunks, firing
    progress_cb with CUMULATIVE byte counts after each one."""
    from bqueryd_tpu.blob import AzureBackend

    service = FakeAzureBlobService(chunk_size=128)
    backend = AzureBackend(service=service)
    payload = bytes(range(256)) * 2  # 512 bytes -> 4 chunks of 128
    src = tmp_path / "obj"
    src.write_bytes(payload)
    backend.put("container", "shard.zip", str(src))
    assert service.blobs[("container", "shard.zip")] == payload

    seen = []
    dest = tmp_path / "out"
    backend.fetch("container", "shard.zip", str(dest), progress_cb=seen.append)
    assert dest.read_bytes() == payload
    assert seen == [128, 256, 384, 512]


def test_full_distribution_pipeline_over_azure(pipeline, tmp_path):
    """zip → upload_blob → download(wait=True, scheme='azure') → unzip →
    two-phase activation → query, through the REAL AzureBackend code path
    (fake service underneath) — parity with the S3 pipeline test above and
    the reference's Azure downloader (reference bqueryd/worker.py:519-556)."""
    from bqueryd_tpu.blob import AzureBackend
    from bqueryd_tpu.download import METADATA_FILENAME
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.utils.net import zip_to_file

    azure = AzureBackend(service=FakeAzureBlobService(chunk_size=64 * 1024))
    pipeline["downloader"].blob_backend = azure

    df = pd.DataFrame(
        {
            "g": np.arange(300, dtype=np.int64) % 5,
            "v": np.arange(300, dtype=np.int64),
        }
    )
    build = tmp_path / "build_azure"
    build.mkdir()
    src_root = build / "via_azure.bcolzs"
    ctable.fromdataframe(df, str(src_root))
    zip_path, _crc = zip_to_file(str(src_root), str(build))
    azure.put("bcolz", "via_azure.bcolzs.zip", zip_path)

    result = pipeline["rpc"].download(
        filenames=["via_azure.bcolzs.zip"], bucket="bcolz", wait=True,
        scheme="azure",
    )
    assert result == "DONE"
    activated = pipeline["serving"] / "via_azure.bcolzs"
    wait_until(activated.is_dir, desc="shard activated via azure path")
    assert (activated / METADATA_FILENAME).is_file()
    wait_until(
        lambda: "via_azure.bcolzs" in pipeline["controller"].files_map,
        desc="azure-distributed shard advertised",
    )
    got = pipeline["rpc"].groupby(
        ["via_azure.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
    )
    expect = df.groupby("g")["v"].sum().to_dict()
    assert dict(zip(got["g"].tolist(), got["v_sum"].tolist())) == expect


def test_concurrent_tickets_activate_exactly_once(
    pipeline, tmp_path, mem_store_url
):
    """Three tickets in flight at once, eight shards each: every shard
    activates exactly once with its own ticket's provenance, no
    cross-ticket contamination, every shard queryable afterwards — the
    two-phase commit under the concurrency the reference never tested."""
    from bqueryd_tpu.download import METADATA_FILENAME
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.utils.net import zip_to_file

    build = tmp_path / "cbuild"
    build.mkdir()
    frames = {}
    tickets_files = []
    for t in range(3):
        files = []
        for s in range(8):
            name = f"ticket{t}_shard{s}.bcolzs"
            df = pd.DataFrame(
                {
                    "g": np.arange(200, dtype=np.int64) % 5,
                    "v": np.arange(200, dtype=np.int64) + 1000 * t + s,
                }
            )
            frames[name] = df
            src = build / name
            ctable.fromdataframe(df, str(src))
            zip_path, _ = zip_to_file(str(src), str(build))
            pipeline["backend"].put("bcolz", f"{name}.zip", zip_path)
            files.append(f"{name}.zip")
        tickets_files.append(files)

    # issue all three tickets concurrently — one RPC client per thread
    # (an RPC wraps a zmq REQ socket, which is single-thread lockstep by
    # design, exactly like the reference's client)
    from bqueryd_tpu.rpc import RPC

    results = {}
    threads = []

    def issue(i, files):
        client = RPC(
            coordination_url=mem_store_url,
            timeout=90,
            loglevel=logging.WARNING,
        )
        results[i] = client.download(
            filenames=files, bucket="bcolz", wait=True, scheme="localfs",
        )

    for i, files in enumerate(tickets_files):
        th = threading.Thread(target=issue, args=(i, files), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120)
        assert not th.is_alive(), "a ticket never completed"
    # a thread that raised never recorded its result: fail HERE, not as a
    # misleading activation timeout 30s later
    assert len(results) == 3, f"ticket thread died: {results}"
    assert all(r == "DONE" for r in results.values()), results

    import json as _json

    seen_tickets = {}
    for name, df in frames.items():
        activated = pipeline["serving"] / name
        wait_until(activated.is_dir, desc=f"{name} activated")
        meta = _json.loads((activated / METADATA_FILENAME).read_text())
        seen_tickets.setdefault(meta["ticket"], set()).add(name)
    # each ticket stamped exactly its own 8 shards
    assert sorted(len(v) for v in seen_tickets.values()) == [8, 8, 8]
    for tid, names in seen_tickets.items():
        prefixes = {n.split("_")[0] for n in names}
        assert len(prefixes) == 1, f"ticket {tid} mixed shards: {names}"

    # every shard serves its own data
    wait_until(
        lambda: all(
            n in pipeline["controller"].files_map for n in frames
        ),
        timeout=30,
        desc="all shards advertised",
    )
    for name, df in list(frames.items())[::7]:  # spot-check across tickets
        got = pipeline["rpc"].groupby(
            [name], ["g"], [["v", "sum", "s"]], []
        )
        assert dict(zip(got["g"], got["s"])) == (
            df.groupby("g")["v"].sum().to_dict()
        ), name
