"""End-to-end dataset distribution over a real blob backend.

The reference exercised its full download path against localstack
(reference tests/test_download.py:95-141); the in-process cluster fixture in
tests/test_rpc_cluster.py fakes the fetch with a DummyDownloader.  These
tests run the REAL pipeline — ``zip_to_file`` → blob ``put`` →
``rpc.download(wait=True)`` → streamed ``download_file`` + unzip →
movebcolz two-phase activation → the new shard answers a groupby — using
:class:`bqueryd_tpu.blob.LocalFSBackend` as the object store, plus a
mid-flight cancellation case and a liveness check during a slow fetch (the
fetch runs on the downloader's thread pool, so WRM heartbeats continue).
"""

import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tests.conftest import wait_until


@pytest.fixture
def pipeline(tmp_path, mem_store_url):
    """Controller + calc worker + REAL downloader + mover sharing one
    serving dir, with a LocalFSBackend 'object store'."""
    from bqueryd_tpu.blob import LocalFSBackend
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import DownloaderNode, MoveBcolzNode, WorkerNode

    serving = tmp_path / "serving"
    blob_root = tmp_path / "blobs"
    serving.mkdir()
    blob_root.mkdir()
    backend = LocalFSBackend(root=str(blob_root))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    calc = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(serving),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    downloader = DownloaderNode(
        coordination_url=mem_store_url,
        data_dir=str(serving),
        loglevel=logging.WARNING,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    downloader.download_interval = 0.2
    downloader.blob_backend = backend
    mover = MoveBcolzNode(
        coordination_url=mem_store_url,
        data_dir=str(serving),
        loglevel=logging.WARNING,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    mover.download_interval = 0.2

    nodes = (controller, calc, downloader, mover)
    threads = [threading.Thread(target=n.go, daemon=True) for n in nodes]
    for t in threads:
        t.start()
    wait_until(
        lambda: len(controller.worker_map) >= 3, desc="nodes registered"
    )
    rpc = RPC(coordination_url=mem_store_url, timeout=60,
              loglevel=logging.WARNING)
    yield {
        "rpc": rpc,
        "controller": controller,
        "calc": calc,
        "downloader": downloader,
        "mover": mover,
        "serving": serving,
        "backend": backend,
    }
    for n in nodes:
        n.running = False
    for t in threads:
        t.join(timeout=5)


def test_full_distribution_pipeline(pipeline, tmp_path):
    """zip → put → download(wait=True) → real fetch/unzip → activation →
    the freshly distributed shard answers a groupby."""
    from bqueryd_tpu.download import METADATA_FILENAME
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.utils.net import zip_to_file

    df = pd.DataFrame(
        {
            "g": np.arange(500, dtype=np.int64) % 7,
            "v": np.arange(500, dtype=np.int64),
        }
    )
    build = tmp_path / "build"
    build.mkdir()
    src_root = build / "fresh.bcolzs"
    ctable.fromdataframe(df, str(src_root))
    zip_path, _crc = zip_to_file(str(src_root), str(build))
    pipeline["backend"].put("bcolz", "fresh.bcolzs.zip", zip_path)

    result = pipeline["rpc"].download(
        filenames=["fresh.bcolzs.zip"], bucket="bcolz", wait=True,
        scheme="localfs",
    )
    assert result == "DONE"

    # activation: shard dir swapped into serving with provenance metadata
    activated = pipeline["serving"] / "fresh.bcolzs"
    wait_until(activated.is_dir, desc="shard activated into serving dir")
    assert (activated / METADATA_FILENAME).is_file()

    # the calc worker's rescan picks it up and it answers queries
    wait_until(
        lambda: "fresh.bcolzs" in pipeline["controller"].files_map,
        desc="new shard advertised",
    )
    got = pipeline["rpc"].groupby(
        ["fresh.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
    )
    expect = df.groupby("g")["v"].sum().to_dict()
    assert dict(zip(got["g"].tolist(), got["v_sum"].tolist())) == expect


class SlowBackend:
    """Streams a small payload in many chunks with a delay per chunk, firing
    progress_cb between chunks so cancellation checks run."""

    def __init__(self, total_chunks=40, delay=0.1):
        self.total_chunks = total_chunks
        self.delay = delay
        self.started = threading.Event()
        self.finished = threading.Event()

    def fetch(self, bucket, key, dest_path, progress_cb=None):
        self.started.set()
        try:
            with open(dest_path, "wb") as f:
                for i in range(self.total_chunks):
                    f.write(b"x" * 128)
                    if progress_cb:
                        progress_cb((i + 1) * 128)
                    time.sleep(self.delay)
        finally:
            self.finished.set()


def test_heartbeats_continue_during_slow_fetch(pipeline):
    """The fetch runs on the download pool, so the downloader's liveness
    (WRM last_seen at the controller) keeps advancing while the blob stream
    crawls — the event-loop-blocking bug class from round 1."""
    slow = SlowBackend(total_chunks=40, delay=0.1)  # ~4s fetch
    pipeline["downloader"].blob_backend = slow
    controller = pipeline["controller"]
    downloader_id = pipeline["downloader"].worker_id

    ticket = pipeline["rpc"].download(
        filenames=["slow.bcolzs.zip"], bucket="bcolz", wait=False,
        scheme="localfs",
    )
    wait_until(slow.started.is_set, desc="fetch started")
    seen_before = controller.worker_map[downloader_id]["last_seen"]
    time.sleep(1.0)
    assert not slow.finished.is_set(), "fetch finished too fast to observe"
    seen_during = controller.worker_map[downloader_id]["last_seen"]
    assert seen_during > seen_before, (
        "downloader stopped heartbeating while fetching"
    )
    # let it finish; the fake payload isn't a zip, so the slot just goes DONE
    wait_until(slow.finished.is_set, timeout=15, desc="fetch finished")
    pipeline["rpc"].delete_download(ticket)


def test_midflight_cancellation_aborts_download(pipeline):
    """delete_download mid-fetch deletes the slots; the in-flight download
    observes the missing slot and aborts, removing its staging dir
    (reference bqueryd/worker.py:418-428)."""
    from bqueryd_tpu.download import incoming_dir

    slow = SlowBackend(total_chunks=200, delay=0.1)  # ~20s unless cancelled
    pipeline["downloader"].blob_backend = slow
    rpc = pipeline["rpc"]

    ticket = rpc.download(
        filenames=["cancelme.bcolzs.zip"], bucket="bcolz", wait=False,
        scheme="localfs",
    )
    wait_until(slow.started.is_set, desc="fetch started")
    assert rpc.delete_download(ticket) is True
    # CancelWatch polls every ~2s: the fetch must abort well before the
    # 20s it would otherwise take
    wait_until(slow.finished.is_set, timeout=10, desc="fetch aborted")
    staging = incoming_dir(pipeline["downloader"], ticket)
    wait_until(
        lambda: not os.path.exists(staging), desc="staging cleaned up"
    )
    # ticket record is gone: nothing to activate
    assert all(t != ticket for t, _ in rpc.downloads())
