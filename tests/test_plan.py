"""Query planning & admission subsystem: plan compilation round-trips,
rewrite rules, stats-based shard pruning, cost-based kernel-strategy
selection, admission backpressure (BUSY), deadline propagation, and
multi-query shared dispatch."""

import logging
import os
import pickle
import time

import numpy as np
import pytest

from bqueryd_tpu import plan as planmod
from bqueryd_tpu.controller import ControllerNode
from bqueryd_tpu.messages import CalcMessage, Message, RPCMessage, msg_factory
from bqueryd_tpu.plan import (
    AdmissionController,
    LogicalPlan,
    compile_groupby,
    fragment_for,
    fragment_to_query,
    plan_groupby,
    stats_can_match,
)
from bqueryd_tpu.plan.strategy import choose_strategy, select_for_group


# -- logical plans -----------------------------------------------------------

def test_compile_normalizes_and_dedups():
    plan = plan_groupby(
        ["a.bcolzs", "a.bcolzs", "b.bcolzs"],
        ["k"],
        ["v", ["w", "count"], ["v", "mean", "m"]],
        [["x", ">", 5]],
    )
    assert plan.filenames == ["a.bcolzs", "b.bcolzs"]
    assert plan.physical_agg_list() == [
        ["v", "sum", "v"], ["w", "count", "w"], ["v", "mean", "m"],
    ]
    # predicate pushdown moved the filter into the scan node
    assert plan.scan.pushdown == [("x", ">", 5)]
    assert plan.filter.terms == []
    assert "predicate_pushdown" in plan.rewrites
    # every touched column appears exactly once in the scan
    assert plan.scan.columns == ["k", "v", "w", "x"]


def test_mean_decomposition_rewrite():
    plan = plan_groupby(
        ["a.bcolzs"], ["k"],
        [["v", "mean", "m"], ["v", "sum", "s"], ["v", "count", "n"]],
        [],
    )
    assert "mean_decomposition" in plan.rewrites
    # primitives are deduplicated: mean's sum+count share the explicit ones
    assert [(a[0], a[1]) for a in plan.aggregate.aggs] == [
        ("v", "sum"), ("v", "count"),
    ]
    exprs = dict(plan.project.exprs)
    assert exprs["m"][0] == "div"
    # physical reconstruction restores the original output list in order
    assert plan.physical_agg_list() == [
        ["v", "mean", "m"], ["v", "sum", "s"], ["v", "count", "n"],
    ]


def test_plan_wire_roundtrip():
    plan = plan_groupby(
        ["a.bcolzs"], ["k", "j"],
        [["v", "mean", "m"]],
        [["x", "in", [1, 2]]],
        aggregate=True,
        expand_filter_column="basket",
    )
    back = LogicalPlan.from_wire(plan.to_wire())
    assert back.physical_agg_list() == plan.physical_agg_list()
    assert back.where_terms == plan.where_terms
    assert back.signature() == plan.signature()
    assert "Scan" in back.explain()


def test_fragment_roundtrip_to_query():
    plan = plan_groupby(
        ["a.bcolzs", "b.bcolzs"], ["k"],
        [["v", "mean", "m"]], [["x", "<=", 9]],
    )
    frag = fragment_for(plan, ["a.bcolzs"], strategy="scatter", sole=True)
    query = fragment_to_query(frag)
    assert query.groupby_cols == ["k"]
    assert query.agg_list == [["v", "mean", "m"]]
    assert query.where_terms == [("x", "<=", 9)]
    assert query.sole_payload is True
    assert frag["strategy"] == "scatter"
    # fragments survive the message binary-field transport
    msg = CalcMessage({"payload": "groupby"})
    msg.add_as_binary("plan", frag)
    again = msg_factory(msg.to_json()).get_from_binary("plan")
    assert again == frag


def test_identical_plans_share_a_signature():
    a = plan_groupby(["a.bcolzs"], ["k"], [["v", "sum", "v"]], [["x", ">", 1]])
    b = plan_groupby(["b.bcolzs"], ["k"], [["v", "sum", "v"]], [["x", ">", 1]])
    c = plan_groupby(["a.bcolzs"], ["k"], [["v", "sum", "v"]], [["x", ">", 2]])
    assert a.signature() == b.signature()  # shard set is not part of it
    assert a.signature() != c.signature()


# -- stats pruning -----------------------------------------------------------

STATS = {
    "rows": 1000,
    "cols": {
        "x": {"kind": "numeric", "min": 10, "max": 20, "card": 11},
        "d": {"kind": "dict"},
    },
}


@pytest.mark.parametrize(
    "term,expected",
    [
        (("x", "==", 15), True),
        (("x", "==", 25), False),
        (("x", ">", 20), False),
        (("x", ">", 19), True),
        (("x", ">=", 21), False),
        (("x", "<", 10), False),
        (("x", "<=", 9), False),
        (("x", "<=", 10), True),
        (("x", "in", [1, 2, 3]), False),
        (("x", "in", [1, 15]), True),
        (("y", "==", 1), True),       # unknown column: conservative match
        (("d", "==", "blue"), True),  # dict column: no controller pruning
        (("x", "==", "oops"), True),  # non-numeric value: conservative
    ],
)
def test_stats_can_match(term, expected):
    assert stats_can_match(STATS, [term]) is expected


def test_stats_can_match_conjunction():
    assert not stats_can_match(STATS, [("x", ">", 12), ("x", ">", 99)])
    assert stats_can_match(STATS, [("x", ">", 12), ("x", "<", 19)])


def test_garbage_stats_never_prune_and_never_raise():
    """A version-skewed worker can advertise any shape; every consumer must
    degrade (conservative match / auto strategy), never raise mid-launch."""
    assert stats_can_match(5, [("x", ">", 1)]) is True
    assert stats_can_match({"cols": 3}, [("x", ">", 1)]) is True
    bad_bounds = {"cols": {"x": {"kind": "numeric", "min": "a", "max": "b"}}}
    assert stats_can_match(bad_bounds, [("x", ">", 1)]) is True
    garbage = {
        "x.b": {"rows": "many", "cols": {"k": {"kind": "numeric",
                                               "card": "lots"}}},
    }
    assert select_for_group(garbage, ["x.b"], ["k"])[0] == "auto"
    assert select_for_group({"x.b": 7}, ["x.b"], ["k"])[0] == "auto"


# -- strategy selection ------------------------------------------------------

def shard_stats(rows, cards, lo=0, hi=100):
    return {
        "rows": rows,
        "cols": {
            c: {"kind": "numeric", "min": lo, "max": hi, "card": k}
            for c, k in cards.items()
        },
    }


def test_choose_strategy_low_cardinality_is_matmul():
    assert choose_strategy(10_000_000, 9) == "matmul"


def test_choose_strategy_high_cardinality_is_scatter():
    assert choose_strategy(10_000_000, 70_000) == "scatter"


def test_choose_strategy_extreme_cardinality_is_sort():
    assert choose_strategy(10_000_000, 1_000_000) == "sort"


def test_choose_strategy_unknown_is_auto():
    assert choose_strategy(10_000_000, None) == "auto"
    assert choose_strategy(None, 9) == "auto"


def test_select_for_group_overlapping_ranges_use_max_card():
    # iid shards: same key domain -> global card ~ max per-shard card
    stats = {
        f"s{i}.bcolzs": shard_stats(1_000_000, {"a": 265, "b": 265})
        for i in range(10)
    }
    strat, est, rows = select_for_group(
        stats, list(stats), ["a", "b"]
    )
    assert rows == 10_000_000
    assert est == 265 * 265
    assert strat == "scatter"


def test_select_for_group_disjoint_ranges_sum_cards():
    # range-partitioned shards: per-shard domains are disjoint -> cards sum
    stats = {
        f"s{i}.bcolzs": shard_stats(
            100_000, {"a": 5000}, lo=i * 10_000, hi=i * 10_000 + 9_999
        )
        for i in range(4)
    }
    strat, est, _rows = select_for_group(stats, list(stats), ["a"])
    assert est == 20_000
    assert strat == "scatter"


def test_select_for_group_missing_stats_is_auto():
    stats = {"a.bcolzs": shard_stats(100, {"k": 5})}
    strat, est, rows = select_for_group(
        stats, ["a.bcolzs", "b.bcolzs"], ["k"]
    )
    assert (strat, est, rows) == ("auto", None, None)


def test_strategy_hints_are_bit_exact():
    """Every forced route computes the identical partial tables."""
    from bqueryd_tpu import ops

    rng = np.random.RandomState(7)
    codes = rng.randint(0, 37, 5000).astype(np.int32)
    vals = rng.randint(-(10**12), 10**12, 5000).astype(np.int64)
    fvals = rng.random(5000).astype(np.float64)
    mask = rng.random(5000) > 0.3

    def run(strategy):
        import jax

        out = jax.device_get(
            ops.partial_tables(
                codes, (vals, fvals), ("sum", "mean"), 37, mask,
                strategy=strategy,
            )
        )
        return out

    base = run(None)
    for strategy in ("scatter", "sort", "matmul", "auto"):
        got = run(strategy)
        assert np.array_equal(base["rows"], got["rows"])
        assert np.array_equal(base["aggs"][0]["sum"], got["aggs"][0]["sum"])
        np.testing.assert_allclose(
            base["aggs"][1]["sum"], got["aggs"][1]["sum"], rtol=1e-12
        )

    with pytest.raises(ValueError):
        run("warp-drive")


# -- admission controller ----------------------------------------------------

def test_admission_backpressure_and_release():
    adm = AdmissionController(max_active=1, queue_depth=1, client_quota=0)
    assert adm.submit("t1", "c1", payload="p1") == planmod.ADMIT
    assert adm.submit("t2", "c2", payload="p2") == planmod.QUEUED
    assert adm.submit("t3", "c3", payload="p3") == planmod.BUSY  # queue full
    assert adm.stats()["active"] == 1 and adm.stats()["queued"] == 1
    # resubmission of a live ticket is flagged, never double-counted or
    # re-launched (a client retry must not double the fan-out)
    assert adm.submit("t1", "c1", payload="p1") == planmod.DUPLICATE
    assert adm.submit("t2", "c2", payload="p2") == planmod.DUPLICATE
    assert adm.stats()["active"] == 1 and adm.stats()["queued"] == 1
    adm.release("t1")
    launch, expired = adm.pop_ready()
    assert launch == ["p2"] and expired == []


def test_admission_client_quota():
    adm = AdmissionController(max_active=8, queue_depth=8, client_quota=1)
    assert adm.submit("t1", "same", payload="p1") == planmod.ADMIT
    assert adm.submit("t2", "same", payload="p2") == planmod.BUSY
    assert adm.submit("t3", "other", payload="p3") == planmod.ADMIT
    adm.release("t1")
    assert adm.submit("t4", "same", payload="p4") == planmod.ADMIT


def test_admission_deadline_expiry_in_queue():
    adm = AdmissionController(max_active=1, queue_depth=4)
    assert adm.submit("t1", "c1", payload="p1") == planmod.ADMIT
    assert (
        adm.submit("t2", "c2", deadline=time.time() - 1, payload="p2")
        == planmod.QUEUED
    )
    launch, expired = adm.pop_ready()
    assert launch == [] and expired == ["p2"]
    adm.release("t1")
    assert adm.stats()["active"] == 0 and adm.stats()["queued"] == 0


def test_admission_priority_order():
    adm = AdmissionController(max_active=1, queue_depth=8)
    adm.submit("t0", "c", payload="p0")
    adm.submit("tlow", "c1", priority=5, payload="low")
    adm.submit("thigh", "c2", priority=1, payload="high")
    adm.release("t0")
    launch, _ = adm.pop_ready()
    assert launch == ["high"]


# -- deadline message helpers ------------------------------------------------

def test_message_deadline_helpers():
    msg = Message({"payload": "x"})
    assert msg.deadline_remaining() is None
    assert not msg.deadline_expired()
    msg.set_deadline(seconds=100)
    assert 99 < msg.deadline_remaining() <= 100
    assert not msg.deadline_expired()
    msg.set_deadline(at=time.time() - 1)
    assert msg.deadline_expired()
    # survives serialization and copy
    again = msg_factory(msg.to_json())
    assert again.deadline_expired()
    assert again.copy().deadline_expired()


def test_worker_refuses_expired_work(tmp_path):
    from bqueryd_tpu.worker import WorkerBase

    worker = WorkerBase(
        coordination_url=f"mem://plan-{os.urandom(4).hex()}",
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
    )
    sent = []
    worker.send = lambda addr, m: sent.append(m)
    worker.send_to_all = lambda m: None
    try:
        msg = CalcMessage({"payload": "sleep"})
        msg.set_args_kwargs([0.0], {})
        msg.set_deadline(at=time.time() - 5)
        worker.handle(msg, b"ctrl")
        (reply,) = sent
        assert reply["msg_type"] == "error"
        assert "deadline exceeded" in reply["payload"]
    finally:
        worker.socket.close()


# -- controller integration --------------------------------------------------

@pytest.fixture
def controller(tmp_path):
    node = ControllerNode(
        coordination_url=f"mem://plan-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
    )
    node._replies = []
    node.reply_rpc_raw = (
        lambda client_token, payload: node._replies.append(
            (client_token, payload)
        )
    )
    yield node
    node.socket.close()


def register(controller, worker_id, files, busy=True, stats=None):
    controller.worker_map[worker_id] = {
        "worker_id": worker_id,
        "workertype": "calc",
        "busy": busy,
        "last_seen": time.time(),
        "node": controller.node_name,
    }
    for f in files:
        controller.files_map.setdefault(f, set()).add(worker_id)
        if stats is not None:
            controller.shard_stats[f] = stats.get(f) or stats


def groupby_msg(filenames, where=None, token="00", deadline=None,
                client_id=None, **kwargs):
    msg = RPCMessage({"payload": "groupby", "token": token})
    msg.set_args_kwargs(
        [filenames, ["k"], [["v", "sum", "v"]], where or []], kwargs
    )
    if deadline is not None:
        msg["deadline"] = deadline
    if client_id is not None:
        msg["client_id"] = client_id
    return msg


def queued(controller):
    return [m for q in controller.worker_out_messages.values() for m in q]


def decode_reply(payload):
    return pickle.loads(payload)


def test_plan_time_pruning_skips_excluded_shards(controller):
    stats = {
        "a.bcolzs": shard_stats(100, {"k": 3}, lo=0, hi=50),
        "b.bcolzs": shard_stats(100, {"k": 3}, lo=1000, hi=2000),
    }
    register(
        controller, "w1", ["a.bcolzs", "b.bcolzs"], stats=stats
    )
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs", "b.bcolzs"], where=[["x", ">", 100]])
    )
    # x is unknown in stats -> no pruning on it; prune on k instead
    msgs = queued(controller)
    assert len(msgs) == 1  # batched: both shards still dispatched

    # now a term on k that b's range excludes but a's allows (fresh client
    # token: the first ticket is still active, a reuse would be a DUPLICATE)
    for q in controller.worker_out_messages.values():
        q.clear()
    controller.rpc_segments.clear()
    before = controller.counters["plan_pruned_shards"]
    controller.rpc_groupby(
        groupby_msg(
            ["a.bcolzs", "b.bcolzs"], where=[["k", "<", 60]], token="01"
        )
    )
    (msg,) = queued(controller)
    assert msg["filename"] == "a.bcolzs"  # b pruned, never dispatched
    assert controller.counters["plan_pruned_shards"] - before == 1
    (segment,) = controller.rpc_segments.values()
    assert segment["results"] == {("b.bcolzs",): b""}  # pre-filled empty


def test_all_shards_pruned_replies_immediately(controller):
    stats = {"a.bcolzs": shard_stats(100, {"k": 3}, lo=0, hi=50)}
    register(controller, "w1", ["a.bcolzs"], stats=stats)
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], where=[["k", ">", 99]], token="aa")
    )
    assert not queued(controller)
    assert not controller.rpc_segments  # completed instantly
    ((client, payload),) = controller._replies
    envelope = decode_reply(payload)
    assert envelope["ok"] is True
    assert envelope["payloads"] == [b""]  # one empty payload per shard


def test_planner_disabled_restores_static_fanout(controller, monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_PLANNER", "0")
    stats = {"a.bcolzs": shard_stats(100, {"k": 3}, lo=0, hi=50)}
    register(controller, "w1", ["a.bcolzs"], stats=stats)
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], where=[["k", ">", 99]])
    )
    (msg,) = queued(controller)  # no pruning: dispatched anyway
    frag = msg.get_from_binary("plan")
    assert frag["strategy"] is None


def test_strategy_hint_rides_the_fragment(controller):
    stats = {
        "a.bcolzs": shard_stats(10_000_000, {"k": 9}),
    }
    register(controller, "w1", ["a.bcolzs"], stats=stats)
    controller.rpc_groupby(groupby_msg(["a.bcolzs"]))
    (msg,) = queued(controller)
    frag = msg.get_from_binary("plan")
    assert frag["strategy"] == "matmul"
    assert controller.counters["plan_strategy_hints"] == 1
    assert frag["agg_list"] == [["v", "sum", "v"]]


def test_shared_dispatch_fuses_identical_queries(controller):
    register(controller, "w1", ["a.bcolzs"])
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="bb"))
    msgs = queued(controller)
    assert len(msgs) == 1  # second query joined the first's work unit
    assert controller.counters["plan_shared_dispatches"] == 1
    assert len(controller.rpc_segments) == 2
    token = msgs[0]["token"]
    assert len(controller._work_subscribers[token]) == 2

    # one worker result completes BOTH clients
    reply = CalcMessage(dict(msgs[0]))
    reply["data"] = b"payload-bytes"
    controller.process_worker_result(reply)
    assert not controller.rpc_segments
    clients = sorted(c for c, _ in controller._replies)
    assert clients == ["aa", "bb"]
    for _, payload in controller._replies:
        envelope = decode_reply(payload)
        assert envelope["ok"] and envelope["payloads"] == [b"payload-bytes"]
    assert not controller._work_subscribers and not controller._work_index


def test_client_resend_does_not_duplicate_fanout(controller):
    """A client retrying after its own timeout resends the same identity;
    the controller must not launch a second fan-out for the live ticket."""
    register(controller, "w1", ["a.bcolzs"])
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))  # resend
    assert len(queued(controller)) == 1
    assert len(controller.rpc_segments) == 1
    assert controller.admission.stats()["active"] == 1
    # the single run answers the identity once; completion frees the slot
    (msg,) = queued(controller)
    reply = CalcMessage(dict(msg))
    reply["data"] = b"x"
    controller.process_worker_result(reply)
    assert [c for c, _ in controller._replies] == ["aa"]
    assert controller.admission.stats()["active"] == 0
    # the NEXT query from that client admits fresh
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    assert controller.admission.stats()["active"] == 1


def test_retry_with_fresh_deadline_joins_inflight_run(controller):
    """An application-level retry restamps a fresh absolute deadline; it
    must still read as a RESEND (join the in-flight run), or every retry
    of a long query would cancel and restart it — a livelock."""
    register(controller, "w1", ["a.bcolzs"])
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], token="aa", deadline=time.time() + 60)
    )
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], token="aa", deadline=time.time() + 90)
    )
    assert controller.counters["admission_superseded"] == 0
    assert len(queued(controller)) == 1
    assert controller.admission.stats()["active"] == 1


def test_new_query_on_live_identity_supersedes(controller):
    """A DIFFERENT query arriving on a live identity means the client gave
    up on the old one (REQ is lockstep): the abandoned run is retired with
    no reply and the new query is admitted in its place."""
    register(controller, "w1", ["a.bcolzs", "b.bcolzs"])
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    old_msgs = queued(controller)
    controller.rpc_groupby(
        groupby_msg(["b.bcolzs"], where=[["k", ">", 1]], token="aa")
    )
    assert controller.counters["admission_superseded"] == 1
    # still exactly one live ticket for the identity, one live segment,
    # and the live segment is the NEW query's
    assert controller.admission.stats()["active"] == 1
    (segment,) = controller.rpc_segments.values()
    assert segment["filenames"] == ["b.bcolzs"]
    # the abandoned dispatch no longer owns a work unit; a late worker
    # reply for it must not reach the client
    for msg in old_msgs:
        assert msg["token"] not in controller._work_subscribers
    new_msg = next(
        m for m in queued(controller)
        if m["token"] in controller._work_subscribers
    )
    reply = CalcMessage(dict(new_msg))
    reply["data"] = b"x"
    controller.process_worker_result(reply)
    assert [c for c, _ in controller._replies] == ["aa"]


def test_different_deadlines_do_not_fuse(controller):
    """Fusing across deadlines would expire one client's work on another
    client's budget (or never enforce the tighter deadline at all)."""
    register(controller, "w1", ["a.bcolzs"])
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], token="aa", deadline=time.time() + 0.05)
    )
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="bb"))
    msgs = queued(controller)
    assert len(msgs) == 2
    assert controller.counters["plan_shared_dispatches"] == 0
    # the deadline-free query survives the other one's expiry
    time.sleep(0.1)
    controller.dispatch_pending()
    (remaining,) = queued(controller)
    assert remaining.get("deadline") is None
    ((client, payload),) = controller._replies
    assert client == "aa" and not decode_reply(payload)["ok"]


def test_different_queries_do_not_fuse(controller):
    register(controller, "w1", ["a.bcolzs"])
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], where=[["k", ">", 1]], token="bb")
    )
    assert len(queued(controller)) == 2
    assert controller.counters["plan_shared_dispatches"] == 0


def test_aborted_subscriber_does_not_kill_shared_work(controller):
    register(controller, "w1", ["a.bcolzs"])
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="bb"))
    (msg,) = queued(controller)
    # find aa's parent and abort it
    aa_parent = next(
        p for p, s in controller.rpc_segments.items()
        if s["client_token"] == "aa"
    )
    controller.abort_parent(aa_parent, "client gave up")
    assert queued(controller) == [msg]  # bb still owns the work unit
    reply = CalcMessage(dict(msg))
    reply["data"] = b"x"
    controller.process_worker_result(reply)
    done = {c: decode_reply(p) for c, p in controller._replies}
    assert done["aa"]["ok"] is False
    assert done["bb"]["ok"] is True


def test_malformed_stats_advertisement_is_quarantined(controller):
    """One bad WRM poisons at most its own shard's stats entry — and a
    well-shaped entry full of garbage still cannot fail a query."""
    register(controller, "w1", ["a.bcolzs"])
    controller._absorb_shard_stats({"shard_stats": 5})
    controller._absorb_shard_stats({"shard_stats": {"a.bcolzs": 7}})
    assert "a.bcolzs" not in controller.shard_stats
    controller._absorb_shard_stats({"shard_stats": {"a.bcolzs": {
        "rows": "many",
        "cols": {"k": {"kind": "numeric", "min": "lo", "max": 3}},
    }}})
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], where=[["k", ">", 1]], token="aa")
    )
    assert len(queued(controller)) == 1  # dispatched: not pruned, no raise
    assert controller.counters["plan_pruned_shards"] == 0


def test_failed_launch_leaves_no_zombie_segment(controller, monkeypatch):
    """If dispatch raises after SOME shard groups queued, the half-launched
    parent must be fully retired: a segment whose later groups never queued
    can never complete, and its queued work would burn worker time for a
    reply nobody can assemble."""
    register(controller, "w1", ["a.bcolzs", "b.bcolzs"])
    orig = controller._register_work
    calls = {"n": 0}

    def flaky(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("mid-launch failure")
        return orig(*args, **kwargs)

    monkeypatch.setattr(controller, "_register_work", flaky)
    with pytest.raises(RuntimeError):
        controller.rpc_groupby(
            groupby_msg(["a.bcolzs", "b.bcolzs"], token="aa", batch=False)
        )
    assert not controller.rpc_segments
    assert not controller._work_subscribers and not controller._work_index
    assert not queued(controller)
    assert controller.admission.stats()["active"] == 0


def test_admission_busy_reply(tmp_path):
    node = ControllerNode(
        coordination_url=f"mem://plan-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        admit_max_active=1,
        admit_queue_depth=1,
    )
    node._replies = []
    node.reply_rpc_raw = (
        lambda client_token, payload: node._replies.append(
            (client_token, payload)
        )
    )
    try:
        register(node, "w1", ["a.bcolzs"])
        node.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))  # active
        node.rpc_groupby(groupby_msg(["a.bcolzs"], token="bb"))  # queued
        node.rpc_groupby(groupby_msg(["a.bcolzs"], token="cc"))  # BUSY
        assert node.counters["admission_busy"] == 1
        assert node.counters["admission_queued"] == 1
        ((client, payload),) = node._replies
        assert client == "cc"
        envelope = decode_reply(payload)
        assert envelope["busy"] is True and envelope["ok"] is False
        # bb sat in the ADMISSION queue (not launched), so it could not
        # fuse with aa's in-flight work: completing aa frees the slot and
        # _admit_ready launches bb's own dispatch
        (msg,) = queued(node)
        node.worker_out_messages[None].clear()  # simulate the dispatch
        reply = CalcMessage(dict(msg))
        reply["data"] = b"x"
        node.process_worker_result(reply)
        assert {c for c, _ in node._replies} == {"aa", "cc"}
        (msg2,) = queued(node)  # bb launched into the freed capacity
        reply2 = CalcMessage(dict(msg2))
        reply2["data"] = b"y"
        node.process_worker_result(reply2)
        answered = {c for c, _ in node._replies}
        assert answered == {"aa", "bb", "cc"}
        assert node.admission.stats()["active"] == 0
    finally:
        node.socket.close()


def test_client_quota_binds_across_sockets(tmp_path):
    """Sockets declaring the same client_id share one quota bucket: the
    second concurrent query from the same application gets BUSY even
    though it arrives on a fresh REQ identity."""
    node = ControllerNode(
        coordination_url=f"mem://plan-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        admit_client_quota=1,
    )
    node._replies = []
    node.reply_rpc_raw = (
        lambda client_token, payload: node._replies.append(
            (client_token, payload)
        )
    )
    try:
        register(node, "w1", ["a.bcolzs"])
        node.rpc_groupby(
            groupby_msg(["a.bcolzs"], token="aa", client_id="app1")
        )
        node.rpc_groupby(
            groupby_msg(["a.bcolzs"], token="bb", client_id="app1")
        )
        assert node.counters["admission_busy"] == 1
        ((client, payload),) = node._replies
        assert client == "bb" and decode_reply(payload)["busy"] is True
        # a different application is not throttled by app1's quota
        node.rpc_groupby(
            groupby_msg(["a.bcolzs"], token="cc", client_id="app2")
        )
        assert node.counters["admission_busy"] == 1
    finally:
        node.socket.close()


def test_different_affinity_does_not_fuse(controller):
    """Fusing identical queries across affinity pins would silently run a
    pinned query on whichever worker the first query targeted."""
    register(controller, "w1", ["a.bcolzs"])
    register(controller, "w2", ["a.bcolzs"])
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], token="aa", affinity="w1")
    )
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], token="bb", affinity="w2")
    )
    assert controller.counters["plan_shared_dispatches"] == 0
    assert len(controller.worker_out_messages.get("w1", [])) == 1
    assert len(controller.worker_out_messages.get("w2", [])) == 1


def test_admission_queue_launches_after_release(tmp_path):
    node = ControllerNode(
        coordination_url=f"mem://plan-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        admit_max_active=1,
        admit_queue_depth=4,
    )
    node._replies = []
    node.reply_rpc_raw = (
        lambda client_token, payload: node._replies.append(
            (client_token, payload)
        )
    )
    try:
        register(node, "w1", ["a.bcolzs", "b.bcolzs"])
        node.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
        # different shard set -> not fused; waits in the admission queue
        node.rpc_groupby(groupby_msg(["b.bcolzs"], token="bb"))
        assert len(queued(node)) == 1  # only aa launched
        (msg,) = queued(node)
        reply = CalcMessage(dict(msg))
        reply["data"] = b"x"
        node.process_worker_result(reply)  # completes aa, admits bb
        msgs = queued(node)
        assert any(m["filename"] == "b.bcolzs" for m in msgs)
    finally:
        node.socket.close()


def test_queued_dispatch_expires_past_deadline(controller):
    register(controller, "w1", ["a.bcolzs"], busy=True)
    controller.rpc_groupby(
        groupby_msg(
            ["a.bcolzs"], token="aa", deadline=time.time() + 0.05
        )
    )
    (msg,) = queued(controller)
    assert msg.get("deadline") is not None  # propagated onto the shard
    time.sleep(0.1)
    controller.dispatch_pending()
    assert not queued(controller)
    assert controller.counters["deadline_expired"] == 1
    ((client, payload),) = controller._replies
    envelope = decode_reply(payload)
    assert not envelope["ok"] and "deadline" in envelope["error"]


def test_wrm_shard_stats_absorbed(controller):
    from bqueryd_tpu.messages import WorkerRegisterMessage

    wrm = WorkerRegisterMessage(
        {
            "worker_id": "w9",
            "workertype": "calc",
            "data_files": ["a.bcolzs"],
            "shard_stats": {"a.bcolzs": {"rows": 42, "cols": {}}},
        }
    )
    controller.handle_worker(b"w9", wrm)
    assert controller.shard_stats["a.bcolzs"]["rows"] == 42
    # un-advertising the file drops its stats
    wrm2 = WorkerRegisterMessage(
        {"worker_id": "w9", "workertype": "calc", "data_files": []}
    )
    controller.handle_worker(b"w9", wrm2)
    assert "a.bcolzs" not in controller.shard_stats


def test_supersede_drops_staged_window_plan(controller, monkeypatch):
    """A plan still STAGED in the admission micro-batch window when its
    identity sends a DIFFERENT query must be dropped before the flush can
    launch it — a launched stale run would queue a mis-pairing reply for
    the identity's next request (the same contract as superseding an
    active run, one stage earlier)."""
    register(controller, "w1", ["a.bcolzs", "b.bcolzs"])
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "60000")
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    assert len(controller._pending_window) == 1
    assert not controller.rpc_segments  # staged, not launched
    controller.rpc_groupby(
        groupby_msg(["b.bcolzs"], where=[["k", ">", 1]], token="aa")
    )
    assert controller.counters["admission_superseded"] == 1
    # only the NEW query remains staged, and the identity holds ONE ticket
    (staged_entry,) = controller._pending_window
    assert staged_entry[1].filenames == ["b.bcolzs"]
    assert controller.admission.stats()["active"] == 1
    controller._flush_window(force=True)
    (segment,) = controller.rpc_segments.values()
    assert segment["filenames"] == ["b.bcolzs"]
    # no reply was emitted for the abandoned staged plan
    assert controller._replies == []


def test_bundle_reply_without_members_aborts_not_misdelivers(
    controller, monkeypatch
):
    """A bundle answered WITHOUT bundle_members (a pre-PR-9 worker ran only
    the positional params) must abort every member with the mixed-version
    error — falling through to the shared-dispatch sink would hand one
    member's payload to every member as ok=True."""
    register(controller, "w1", ["a.bcolzs"])
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "60000")
    controller.rpc_groupby(groupby_msg(["a.bcolzs"], token="aa"))
    controller.rpc_groupby(
        groupby_msg(["a.bcolzs"], where=[["k", ">", 1]], token="bb")
    )
    controller._flush_window(force=True)
    assert controller.counters["plan_bundles"] == 1
    (msg,) = queued(controller)
    assert msg.get("bundle") and msg.get("_bundle_parents")
    reply = CalcMessage(dict(msg))
    reply["data"] = b"member0-payload"  # no bundle_members key
    controller.process_worker_result(reply)
    assert sorted(c for c, _ in controller._replies) == ["aa", "bb"]
    for _client, payload in controller._replies:
        envelope = pickle.loads(payload)
        assert envelope["ok"] is False
        assert "BQUERYD_TPU_BATCH_WINDOW_MS=0" in envelope["error"]
    assert not controller.rpc_segments
