"""Seeded differential fuzz: random queries vs pandas, both execution paths.

The reference's ground truth was always pandas (reference
tests/test_simple_rpc.py:139-172).  This suite generates deterministic
pseudo-random datasets exercising every storage kind at once — int64 (small
and >2^53-straddling magnitudes), float32 with NaNs, dict-encoded strings
with nulls, datetimes with NaT — shards them, and runs randomized groupby
queries through BOTH the per-shard engine + host merge path and the mesh
executor, comparing each against pandas (dropna group keys, skipna
aggregates).  Any divergence between the two framework paths, or between
either path and pandas, is a bug: this is the machine that caught the
null-dict-key wrapped-group defect.
"""

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.parallel.executor import MeshQueryExecutor
from bqueryd_tpu.storage.ctable import ctable

N_SHARDS = 3
ROWS_PER_SHARD = 4_000


def _dataset(seed):
    rng = np.random.default_rng(seed)
    frames = []
    for _ in range(N_SHARDS):
        n = ROWS_PER_SHARD
        k_str = rng.choice(["a", "b", "c", None], n, p=[0.4, 0.3, 0.2, 0.1])
        ts = pd.to_datetime(
            rng.integers(1_400_000_000, 1_500_000_000, n), unit="s"
        ).to_series().reset_index(drop=True)
        ts[pd.Series(rng.random(n) < 0.07)] = pd.NaT
        frames.append(
            pd.DataFrame(
                {
                    "k_int": rng.integers(0, 7, n).astype(np.int64),
                    "k_str": k_str,
                    "k_float": np.where(
                        rng.random(n) < 0.08,
                        np.nan,
                        rng.integers(0, 5, n).astype(np.float64) / 2.0,
                    ),
                    "t": ts.to_numpy(),
                    "k_wide": rng.integers(0, 500, n).astype(np.int64),
                    "v_small": rng.integers(-1000, 1000, n).astype(np.int64),
                    "v_big": rng.integers(
                        -(2**60), 2**60, n
                    ).astype(np.int64),
                    "v_float": np.where(
                        rng.random(n) < 0.05,
                        np.nan,
                        (rng.random(n) * 100 - 50),
                    ).astype(np.float32),
                    "v_bool": rng.random(n) < 0.3,
                    "v_u32": rng.integers(0, 2**32, n).astype(np.uint32),
                    "v_u64": rng.integers(
                        2**62, 2**64 - 1, n, dtype=np.uint64
                    ),
                    "basket": np.sort(
                        rng.integers(0, n // 8, n)
                    ).astype(np.int64),
                    "sel": rng.random(n).astype(np.float64),
                }
            )
        )
    return frames


# (groupby_cols, agg_list, where_terms) — each tuple is one fuzz case;
# ops/dtypes/filters drawn to cover every kernel branch
CASES = [
    (["k_int"], [["v_small", "sum", "s"]], []),
    (["k_int"], [["v_big", "sum", "s"]], []),  # limb/fallback magnitudes
    (["k_str"], [["v_small", "sum", "s"]], []),  # null dict keys drop
    (["k_str", "k_int"], [["v_small", "sum", "s"]], []),
    (["k_int", "k_wide"], [["v_small", "sum", "s"]], []),  # wide composite
    (
        ["k_int"],
        [
            ["v_small", "sum", "s"],
            ["v_float", "mean", "m"],
            ["v_small", "count", "n"],
        ],
        [],
    ),
    (["k_int"], [["v_float", "min", "lo"], ["v_float", "max", "hi"]], []),
    (["k_int"], [["v_small", "min", "lo"], ["v_big", "max", "hi"]], []),
    (["k_int"], [["v_float", "count_na", "na"]], []),
    (["k_int"], [["v_small", "sum", "s"]], [["sel", ">", 0.5]]),
    (["k_str"], [["v_float", "mean", "m"]], [["sel", "<=", 0.3]]),
    (
        ["k_int", "k_str"],
        [["v_big", "sum", "s"], ["v_float", "count", "n"]],
        [["sel", ">", 0.2]],
    ),
    (["k_wide"], [["v_small", "sum", "s"], ["v_small", "mean", "m"]], []),
    # datetime measures: NaT must vanish from counts/extrema (pandas skipna)
    (
        ["k_int"],
        [["t", "min", "lo"], ["t", "max", "hi"], ["t", "count", "n"]],
        [],
    ),
    (["k_str"], [["t", "count_na", "na"]], []),
    # null group keys beyond dict: float-NaN keys drop like pandas dropna
    (["k_float"], [["v_small", "sum", "s"]], []),
    (["k_float", "k_int"], [["v_small", "count", "n"]], [["sel", ">", 0.4]]),
    # distinct counts skip NaN/NaT values (pandas nunique), engine path
    # only — count_distinct partials are value sets, not psum-mergeable
    (["k_int"], [["v_float", "count_distinct", "nd"]], []),
    (["k_str"], [["t", "count_distinct", "nt"]], []),
    # remaining measure dtypes: bool sums count trues, unsigned sums stay
    # exact through the limb/native paths
    (["k_int"], [["v_bool", "sum", "s"], ["v_bool", "mean", "m"]], []),
    (["k_int"], [["v_u32", "sum", "s"], ["v_u32", "max", "hi"]], []),
    # uint64 above 2^63: sums stay unsigned mod 2^64 (pandas), extrema
    # keep the native unsigned ordering
    (["k_int"], [["v_u64", "sum", "s"], ["v_u64", "min", "lo"]], []),
    # integer MEANS accumulate float like pandas: group sums here exceed
    # 2^63/2^64, where dividing a wrapped int sum would corrupt the mean
    (["k_int"], [["v_big", "mean", "m"], ["v_u64", "mean", "mu"]], []),
    # bool extrema (any/all semantics) and the empty-group fill path
    (["k_int"], [["v_bool", "min", "lo"], ["v_bool", "max", "hi"]], []),
    # equality predicates, incl. on a dict column and a datetime bound
    (["k_int"], [["v_small", "sum", "s"]], [["k_str", "==", "b"]]),
    (["k_str"], [["v_small", "sum", "s"]], [["k_int", "!=", 3]]),
    (
        ["k_int"],
        [["v_small", "count", "n"]],
        [["t", ">", pd.Timestamp("2015-01-01")]],
    ),
]


def _filter_df(df, where):
    for col, op, val in where:
        if op == ">":
            df = df[df[col] > val]
        elif op == "<=":
            df = df[df[col] <= val]
        elif op == "==":
            df = df[df[col] == val]
        elif op == "!=":
            df = df[df[col] != val]
        elif op == "in":
            df = df[df[col].isin(val)]
        elif op == "not in":
            df = df[~df[col].isin(val)]
        else:
            raise NotImplementedError(op)
    return df


def _expected(frames, gcols, agg_list, where):
    df = _filter_df(pd.concat(frames, ignore_index=True), where)
    gb = df.groupby(gcols, dropna=True)
    out = {}
    for in_col, op, out_col in agg_list:
        if op == "sum":
            out[out_col] = gb[in_col].sum()
        elif op == "mean":
            out[out_col] = gb[in_col].mean()
        elif op == "count":
            out[out_col] = gb[in_col].count()
        elif op == "count_na":
            out[out_col] = gb[in_col].apply(lambda s: s.isna().sum())
        elif op == "min":
            out[out_col] = gb[in_col].min()
        elif op == "max":
            out[out_col] = gb[in_col].max()
        elif op == "count_distinct":
            out[out_col] = gb[in_col].nunique()
    return pd.DataFrame(out).reset_index()


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    frames = _dataset(seed=1234)
    root = tmp_path_factory.mktemp("fuzz")
    tables = []
    for i, df in enumerate(frames):
        p = str(root / f"shard_{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))
    return frames, tables


def _compare(got, expected, gcols, agg_list):
    got = got.sort_values(gcols).reset_index(drop=True)
    expected = expected.sort_values(gcols).reset_index(drop=True)
    assert len(got) == len(expected), (
        f"group count: got {len(got)} vs pandas {len(expected)}"
    )
    for col in gcols:
        assert got[col].astype(str).tolist() == (
            expected[col].astype(str).tolist()
        ), f"keys differ in {col}"
    for in_col, op, out_col in agg_list:
        g = got[out_col].to_numpy()
        e = expected[out_col].to_numpy()
        e_dt = np.asarray(e).dtype
        if np.issubdtype(e_dt, np.datetime64):
            np.testing.assert_array_equal(
                g.astype("datetime64[ns]"), e.astype("datetime64[ns]"),
                err_msg=f"{op}({in_col})",
            )
        elif op in (
            "sum", "count", "count_na", "min", "max", "count_distinct"
        ) and np.issubdtype(e_dt, np.integer):
            np.testing.assert_array_equal(g, e, err_msg=f"{op}({in_col})")
        else:
            np.testing.assert_allclose(
                g.astype(np.float64),
                e.astype(np.float64),
                rtol=2e-5,
                atol=1e-6,
                err_msg=f"{op}({in_col})",
            )


@pytest.mark.parametrize("case_i", range(len(CASES)))
def test_engine_hostmerge_matches_pandas(shards, case_i):
    frames, tables = shards
    gcols, agg_list, where = CASES[case_i]
    query = GroupByQuery(gcols, agg_list, where, aggregate=True)
    engine = QueryEngine()
    payloads = [engine.execute_local(t, query) for t in tables]
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads(payloads))
    _compare(got, _expected(frames, gcols, agg_list, where), gcols, agg_list)


@pytest.mark.parametrize("case_i", range(len(CASES)))
def test_mesh_executor_matches_pandas(shards, case_i):
    frames, tables = shards
    gcols, agg_list, where = CASES[case_i]
    query = GroupByQuery(gcols, agg_list, where, aggregate=True)
    if not MeshQueryExecutor.supports(query):
        pytest.skip("non-mergeable ops take the engine path")
    payload = MeshQueryExecutor().execute(tables, query)
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload]))
    _compare(got, _expected(frames, gcols, agg_list, where), gcols, agg_list)


# ---------------------------------------------------------------------------
# remaining query surfaces: raw rows, in/not-in predicates, basket expansion
# ---------------------------------------------------------------------------

RAW_CASES = [
    (["k_int"], ["v_small", "v_float"], [["sel", ">", 0.6]]),
    (["k_str"], ["v_small"], [["k_int", "in", [1, 3, 5]]]),
    (["k_int"], ["v_big"], [["k_int", "not in", [0, 2]], ["sel", "<=", 0.8]]),
]




@pytest.mark.parametrize("case_i", range(len(RAW_CASES)))
def test_raw_rows_match_pandas(shards, case_i):
    """aggregate=False: the filtered, selected rows concatenated across
    shards must equal pandas boolean filtering (compared as sorted
    multisets — cross-shard order is concatenation order by contract)."""
    frames, tables = shards
    gcols, in_cols, where = RAW_CASES[case_i]
    agg_list = [[c, "sum", c] for c in in_cols]
    query = GroupByQuery(gcols, agg_list, where, aggregate=False)
    engine = QueryEngine()
    payloads = [engine.execute_local(t, query) for t in tables]
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads(payloads))
    expected = _filter_df(pd.concat(frames, ignore_index=True), where)
    cols = list(dict.fromkeys(gcols + in_cols))
    expected = expected[cols]
    assert len(got) == len(expected)
    g = got.sort_values(cols).reset_index(drop=True)
    e = expected.sort_values(cols).reset_index(drop=True)
    for c in cols:
        if np.issubdtype(np.asarray(e[c]).dtype, np.floating):
            np.testing.assert_allclose(
                g[c].astype(np.float64), e[c].astype(np.float64),
                rtol=1e-6, equal_nan=True, err_msg=c,
            )
        else:
            assert g[c].astype(str).tolist() == e[c].astype(str).tolist(), c


@pytest.mark.parametrize(
    "where",
    [
        [["k_int", "in", [0, 2, 6]]],
        [["k_str", "in", ["a", "c"]]],
        [["k_str", "not in", ["b"]]],
        [["k_int", "not in", [1]], ["sel", ">", 0.5]],
    ],
)
def test_in_predicates_match_pandas(shards, where):
    """'in'/'not in' terms (incl. on dict columns, where membership is
    translated to physical codes) must agree with pandas isin on both
    execution paths.  NOTE pandas asymmetry: a null key row never matches
    'in', but ~isin() keeps nulls — the framework follows isin for the
    selection in both polarities, so expectation uses isin directly."""
    frames, tables = shards
    gcols, agg_list = ["k_int"], [["v_small", "sum", "s"]]
    query = GroupByQuery(gcols, agg_list, where, aggregate=True)
    engine = QueryEngine()
    payloads = [engine.execute_local(t, query) for t in tables]
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads(payloads))
    got_mesh = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads(
            [MeshQueryExecutor().execute(tables, query)]
        )
    )
    expected = _expected(frames, gcols, agg_list, where)
    _compare(got, expected, gcols, agg_list)
    _compare(got_mesh, expected, gcols, agg_list)


def test_sorted_count_distinct_on_basket_sorted_data(tmp_path):
    """sorted_count_distinct counts value runs within each group; on data
    sorted by (group, value) per shard — the basket layout the op exists
    for — the summed run counts equal pandas nunique per shard, and the
    cross-shard merge is additive by contract."""
    rng = np.random.default_rng(77)
    tables, frames = [], []
    for i in range(2):
        n = 3_000
        df = pd.DataFrame(
            {
                "g": np.sort(rng.integers(0, 5, n)).astype(np.int64),
                "v": rng.integers(0, 40, n).astype(np.int64),
            }
        ).sort_values(["g", "v"], kind="stable").reset_index(drop=True)
        p = str(tmp_path / f"s{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))
        frames.append(df)
    query = GroupByQuery(
        ["g"], [["v", "sorted_count_distinct", "nd"]], [], aggregate=True
    )
    engine = QueryEngine()
    payloads = [engine.execute_local(t, query) for t in tables]
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads(payloads))
    got = got.sort_values("g").reset_index(drop=True)
    expected = sum(
        df.groupby("g")["v"].nunique() for df in frames
    ).sort_index()
    assert got["g"].tolist() == expected.index.tolist()
    assert got["nd"].tolist() == expected.tolist()


def test_datetime_sum_mean_rejected(shards):
    """pandas-meaningless datetime sums/means raise on entry, on both
    execution paths (the README cites this suite for that behavior)."""
    frames, tables = shards
    for op in ("sum", "mean"):
        query = GroupByQuery(
            ["k_int"], [["t", op, "x"]], [], aggregate=True
        )
        with pytest.raises(ValueError, match="not defined for datetime"):
            QueryEngine().execute_local(tables[0], query)
        with pytest.raises(ValueError, match="not defined for datetime"):
            MeshQueryExecutor().execute(tables, query)



@pytest.mark.parametrize(
    "where",
    [
        [["sel", ">", 0.97]],
        [["v_small", ">", 900]],
    ],
)
def test_basket_expansion_matches_pandas(shards, where):
    """expand_filter_column widens a row filter to whole baskets PER SHARD
    (the reference's is_in_ordered_subgroups operates on each shard's
    ordered basket column): any matching row selects its entire basket.
    Ground truth: per-shard pandas transform-any, then a global groupby."""
    frames, tables = shards
    gcols, agg_list = ["k_int"], [["v_small", "sum", "s"]]
    query = GroupByQuery(
        gcols, agg_list, where, aggregate=True,
        expand_filter_column="basket",
    )
    engine = QueryEngine()
    payloads = [engine.execute_local(t, query) for t in tables]
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads(payloads))

    expanded = []
    for df in frames:
        hit = _filter_df(df, where).index
        keep = df["basket"].isin(df.loc[hit, "basket"].unique())
        expanded.append(df[keep])
    expected = _expected(expanded, gcols, agg_list, [])
    _compare(got, expected, gcols, agg_list)


# ---------------------------------------------------------------------------
# semantic serving (PR 16): randomized fold-served answers vs forced recompute
# ---------------------------------------------------------------------------

# every op here is hostmerge-mergeable, so the candidate rollup's partials
# can be re-aggregated; v_u64 sums stress the mod-2^64 limb path through
# the fold's collapse exactly like a cross-shard merge would
SERVE_AGG_POOL = [
    ["v_small", "sum", "s"],
    ["v_float", "mean", "m"],
    ["v_small", "count", "n"],
    ["v_float", "min", "lo"],
    ["v_big", "max", "hi"],
    ["v_u64", "sum", "su"],
]


@pytest.mark.parametrize("seed", range(10))
def test_serving_fold_matches_forced_recompute(shards, seed):
    """Randomized plan-subsumption serving (PR 16) at the engine level: a
    finer-keyed candidate rollup holding the full agg pool is matched
    against a random coarser query through the lattice, the resulting
    fold transform is applied per shard, and the hostmerged answer must
    agree with pandas (= the forced-recompute oracle) — bit-exact for
    integer aggregates, allclose for floats."""
    from bqueryd_tpu.models.query import ResultPayload
    from bqueryd_tpu.serve import subsume

    frames, tables = shards
    rng = np.random.default_rng(9000 + seed)
    droppable = ["k_int", "k_wide"]  # null-free int keys: fold-eligible
    cand_keys = list(droppable)
    if rng.random() < 0.5:
        cand_keys.append("k_str")  # dict key: must survive every fold
    drop = [k for k in droppable if rng.random() < 0.5]
    query_keys = [k for k in cand_keys if k not in drop]
    if not query_keys:
        query_keys = [cand_keys[0]]
    pick = sorted(
        rng.choice(
            len(SERVE_AGG_POOL),
            size=int(rng.integers(1, len(SERVE_AGG_POOL) + 1)),
            replace=False,
        )
    )
    query_aggs = [SERVE_AGG_POOL[i] for i in pick]

    def _view(keys, aggs):
        return {
            "filenames": ("all",),
            "keys": tuple(keys),
            "aggs": tuple(tuple(a) for a in aggs),
            "where": (),
            "aggregate_rows": True,
            "expand": None,
            "dag_sig": None,
        }

    meta = {
        "all": {
            k: {"kind": "int", "zones": None, "nulls": False}
            for k in droppable
        }
    }
    transform, why = subsume.match(
        _view(cand_keys, SERVE_AGG_POOL), _view(query_keys, query_aggs), meta
    )
    assert why is None, why

    cand_query = GroupByQuery(
        cand_keys, SERVE_AGG_POOL, [], aggregate=True
    )
    engine = QueryEngine()
    served = [
        ResultPayload(
            subsume.apply_transform(
                dict(engine.execute_local(t, cand_query)), transform
            )
        )
        for t in tables
    ]
    got = hostmerge.payload_to_dataframe(hostmerge.merge_payloads(served))
    _compare(
        got, _expected(frames, query_keys, query_aggs, []),
        query_keys, query_aggs,
    )
