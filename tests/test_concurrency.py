"""Shared-scan multi-query fusion (PR 9): the admission micro-batch window,
plan compatibility signatures, bundle execution parity, and per-member fault
isolation.

The executor-level tests prove the stacked-mask shared scan is bit-identical
to solo execution (the kernels fold a mask exactly like pre-folded codes);
the cluster tests prove the window end to end: distinct-but-compatible
concurrent queries fuse into one dispatch, every member keeps its own reply
identity, and a member's deadline expiry / quota rejection / shape error
never disturbs its bundle-mates.  Window 0 (the default) stages nothing.
"""

import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from conftest import wait_until

from bqueryd_tpu.models.query import GroupByQuery
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.parallel.executor import MeshQueryExecutor, make_mesh
from bqueryd_tpu.plan import bundle as bundlemod
from bqueryd_tpu.plan import plan_groupby
from bqueryd_tpu.storage import ctable

N_SHARDS = 3


def swarm_df(n=9_000, seed=23):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 8, n).astype(np.int64),
            "k2": rng.integers(0, 3, n).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, n).astype(np.int64),
            "w": rng.random(n) * 10.0,
        }
    )


@pytest.fixture(scope="module")
def sharded(tmp_path_factory):
    df = swarm_df()
    base = tmp_path_factory.mktemp("bundles")
    tables = []
    for i in range(N_SHARDS):
        root = str(base / f"b_{i}.bcolzs")
        ctable.fromdataframe(
            df.iloc[i::N_SHARDS].reset_index(drop=True), root
        )
        tables.append(ctable(root, mode="r"))
    return df, tables


def frame(payload):
    return hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    )


def assert_same(got, expected, key_cols, exact_ints=True):
    got = got.sort_values(key_cols).reset_index(drop=True)
    expected = expected.sort_values(key_cols).reset_index(drop=True)
    expected = expected[list(got.columns)]
    assert len(got) == len(expected)
    for col in got.columns:
        a, b = got[col].to_numpy(), expected[col].to_numpy()
        if a.dtype.kind in "iub" and exact_ints:
            assert np.array_equal(a, b), col
        else:
            np.testing.assert_allclose(
                a.astype(np.float64), b.astype(np.float64), rtol=1e-9
            )


# ---------------------------------------------------------------------------
# plan.bundle: compatibility signatures + fragments
# ---------------------------------------------------------------------------

def _plan(files, gcols, aggs, where=None, **kw):
    return plan_groupby(files, gcols, aggs, where or [], **kw)


def test_compat_key_fuses_across_measures_and_filters():
    keep = ["a.bcolzs", "b.bcolzs"]
    p1 = _plan(keep, ["k"], [["v", "sum", "v"]], [["w", ">", 1.0]])
    p2 = _plan(keep, ["k"], [["w", "mean", "m"]], [["w", "<", 9.0]])
    k1 = bundlemod.compat_key(p1, keep, {})
    k2 = bundlemod.compat_key(p2, keep, {})
    assert k1 is not None and k1 == k2


def test_compat_key_separates_incompatible_queries():
    keep = ["a.bcolzs", "b.bcolzs"]
    base = _plan(keep, ["k"], [["v", "sum", "v"]])
    key = bundlemod.compat_key(base, keep, {})
    # different group keys -> different signature
    other = _plan(keep, ["k2"], [["v", "sum", "v"]])
    assert bundlemod.compat_key(other, keep, {}) != key
    # different post-prune shard set -> different signature
    assert bundlemod.compat_key(base, keep[:1], {}) != key
    # raw-rows, basket expansion, non-mergeable aggs, batch=False and
    # fully-pruned plans cannot ride a bundle at all
    raw = _plan(keep, ["k"], [["v", "sum", "v"]], aggregate=False)
    assert bundlemod.compat_key(raw, keep, {}) is None
    basket = _plan(
        keep, ["k"], [["v", "sum", "v"]], expand_filter_column="k2"
    )
    assert bundlemod.compat_key(basket, keep, {}) is None
    distinct = _plan(keep, ["k"], [["v", "count_distinct", "nd"]])
    assert bundlemod.compat_key(distinct, keep, {}) is None
    assert bundlemod.compat_key(base, keep, {"batch": False}) is None
    assert bundlemod.compat_key(base, [], {}) is None
    # affinity is part of the identity (a pinned query must not fuse away)
    assert bundlemod.compat_key(base, keep, {"affinity": "w1"}) != key


def test_bundle_fragment_round_trip():
    keep = ["a.bcolzs"]
    p1 = _plan(keep, ["k"], [["v", "sum", "v"]], [["w", ">", 2.0]])
    p2 = _plan(keep, ["k"], [["v", "mean", "m"]])
    fragment = bundlemod.bundle_fragment(
        p1, keep, [("m1", p1, None), ("m2", p2, 123.0)], strategy="scatter",
    )
    members = bundlemod.bundle_to_queries(fragment)
    assert [m[0] for m in members] == ["m1", "m2"]
    assert members[0][1] is None and members[1][1] == 123.0
    q1, q2 = members[0][2], members[1][2]
    assert q1.where_terms == [("w", ">", 2.0)]
    assert q1.agg_list == [["v", "sum", "v"]]
    # mean decomposition round-trips through the physical form
    assert q2.ops == ("mean",)
    assert bundlemod.fragment_strategy(fragment) == "scatter"
    # the binding promotion ships as advisory matmul + flag (mixed-version
    # contract) and reconstructs only under an enabled calibration
    binding = bundlemod.bundle_fragment(
        p1, keep, [("m1", p1, None)], strategy="matmul!",
    )
    assert binding["strategy"] == "matmul"
    assert binding["strategy_binding"] is True
    assert bundlemod.fragment_strategy(binding) == "matmul!"
    with pytest.raises(ValueError):
        bundlemod.bundle_to_queries({"v": 99, "members": []})


def test_window_knobs_default_off(monkeypatch):
    monkeypatch.delenv("BQUERYD_TPU_BATCH_WINDOW_MS", raising=False)
    assert bundlemod.batch_window_ms() == 0.0
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "25.5")
    assert bundlemod.batch_window_ms() == 25.5
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "garbage")
    assert bundlemod.batch_window_ms() == 0.0
    monkeypatch.setenv("BQUERYD_TPU_BATCH_MAX", "1")
    assert bundlemod.batch_max() == 2  # floor: a bundle needs two members


# ---------------------------------------------------------------------------
# ops.bundle_partial_tables: stacked-mask emission vs solo kernels
# ---------------------------------------------------------------------------

def test_bundle_partial_tables_matches_solo_kernels():
    import jax.numpy as jnp

    from bqueryd_tpu import ops

    rng = np.random.default_rng(5)
    n, n_groups = 4096, 11
    codes = rng.integers(-1, n_groups, n).astype(np.int32)
    v = rng.integers(-1000, 1000, n).astype(np.int64)
    w = rng.random(n)
    mask_a = rng.random(n) > 0.4
    mask_b = rng.random(n) > 0.7
    member_specs = (
        (0, ((0, "sum"), (0, "count"))),   # masked by mask_a, over v
        (None, ((1, "mean"),)),            # unfiltered, over w
        (1, ((1, "min"), (0, "max"))),     # masked by mask_b, mixed cols
    )
    out = ops.bundle_partial_tables(
        jnp.asarray(codes),
        jnp.stack([jnp.asarray(mask_a), jnp.asarray(mask_b)]),
        (jnp.asarray(v), jnp.asarray(w)),
        member_specs,
        n_groups,
    )
    assert len(out) == 3
    solos = [
        ops.partial_tables(
            jnp.asarray(codes), (jnp.asarray(v), jnp.asarray(v)),
            ("sum", "count"), n_groups, mask=jnp.asarray(mask_a),
        ),
        ops.partial_tables(
            jnp.asarray(codes), (jnp.asarray(w),), ("mean",), n_groups,
        ),
        ops.partial_tables(
            jnp.asarray(codes), (jnp.asarray(w), jnp.asarray(v)),
            ("min", "max"), n_groups, mask=jnp.asarray(mask_b),
        ),
    ]
    import jax

    for got, want in zip(out, solos):
        got_leaves = jax.tree_util.tree_leaves(got)
        want_leaves = jax.tree_util.tree_leaves(want)
        assert len(got_leaves) == len(want_leaves)
        for g, w_ in zip(got_leaves, want_leaves):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w_))


# ---------------------------------------------------------------------------
# executor.execute_bundle: shared-scan parity on the 8-device mesh
# ---------------------------------------------------------------------------

def test_execute_bundle_matches_solo_execution(sharded):
    _df, tables = sharded
    ex = MeshQueryExecutor(mesh=make_mesh())
    queries = [
        GroupByQuery(["k"], [["v", "sum", "v_sum"]], [("w", ">", 6.0)]),
        GroupByQuery(["k"], [["v", "sum", "v_sum"]], [("w", ">", 1.5)]),
        GroupByQuery(["k"], [["w", "mean", "w_mean"]], []),
        GroupByQuery(
            ["k"], [["v", "min", "v_min"], ["v", "max", "v_max"]],
            [("w", "<", 8.0)],
        ),
        GroupByQuery(
            ["k"], [["v", "sum", "s"], ["v", "count", "n"],
                    ["w", "mean", "m"]],
            [("w", ">", 3.0)],
        ),
    ]
    bundled = ex.execute_bundle(tables, queries)
    assert len(bundled) == len(queries)
    for query, payload in zip(queries, bundled):
        solo = ex.execute(tables, query)
        assert_same(frame(payload), frame(solo), ["k"])


def test_execute_bundle_matches_pandas(sharded):
    df, tables = sharded
    ex = MeshQueryExecutor(mesh=make_mesh())
    queries = [
        GroupByQuery(["k"], [["v", "sum", "v_sum"]], [("w", ">", 5.0)]),
        GroupByQuery(["k"], [["v", "count", "n"]], [("w", "<", 5.0)]),
    ]
    got = [frame(p) for p in ex.execute_bundle(tables, queries)]
    exp0 = (
        df[df["w"] > 5.0].groupby("k")["v"].sum().reset_index()
        .rename(columns={"v": "v_sum"})
    )
    exp1 = (
        df[df["w"] < 5.0].groupby("k")["v"].count().reset_index()
        .rename(columns={"v": "n"})
    )
    assert_same(got[0], exp0, ["k"])
    assert_same(got[1], exp1, ["k"], exact_ints=False)


def test_execute_bundle_shares_scan_work(sharded):
    """The whole point: one alignment, one codes upload, one union measure
    upload for N members — solo repeats would multiply those misses."""
    _df, tables = sharded
    ex = MeshQueryExecutor(mesh=make_mesh())
    queries = [
        GroupByQuery(["k"], [["v", "sum", "a"]], [("w", ">", t)])
        for t in (1.0, 2.0, 3.0, 4.0)
    ]
    ex.execute_bundle(tables, queries)
    stats = ex.workingset.stats()
    assert stats["align"]["misses"] == 1
    assert stats["codes"]["misses"] == 1   # ONE unmasked codes entry
    assert stats["blocks"]["misses"] == 1  # v uploaded once for 4 members
    before = ex.workingset.stats()["codes"]["hits"]
    # a second bundle over the same tables is fully warm on the scan side
    ex.execute_bundle(tables, queries[:2])
    stats = ex.workingset.stats()
    assert stats["align"]["misses"] == 1
    assert stats["codes"]["hits"] > before
    # ... and the unmasked codes entry is the SAME one an unfiltered solo
    # query uses (shared key): no new codes miss
    ex.execute(tables, GroupByQuery(["k"], [["v", "sum", "a"]]))
    assert ex.workingset.stats()["codes"]["misses"] == 1


def test_execute_bundle_rejects_mixed_group_keys(sharded):
    _df, tables = sharded
    ex = MeshQueryExecutor(mesh=make_mesh())
    with pytest.raises(ValueError, match="group-key"):
        ex.execute_bundle(
            tables,
            [
                GroupByQuery(["k"], [["v", "sum", "a"]]),
                GroupByQuery(["k2"], [["v", "sum", "a"]]),
            ],
        )


# ---------------------------------------------------------------------------
# cluster: the admission window end to end
# ---------------------------------------------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture
def swarm_cluster(tmp_path, mem_store_url):
    """Controller + one calc worker serving two shards of swarm_df."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.worker import WorkerNode

    df = swarm_df(n=6_000, seed=31)
    shards = ["c_0.bcolzs", "c_1.bcolzs"]
    for i, name in enumerate(shards):
        ctable.fromdataframe(
            df.iloc[i::2].reset_index(drop=True), str(tmp_path / name)
        )
    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(
        lambda: all(name in controller.files_map for name in shards),
        desc="shards advertised",
    )
    yield {
        "controller": controller,
        "worker": worker,
        "df": df,
        "shards": shards,
        "url": mem_store_url,
    }
    _stop([controller, worker], threads)


def _concurrent_groupby(url, queries, timeout=60, client_ids=None):
    """One thread + one RPC socket per query; returns results/errors by
    index."""
    from bqueryd_tpu.rpc import RPC

    results, errors = {}, {}

    def run(i, query):
        try:
            rpc = RPC(
                coordination_url=url, timeout=timeout,
                loglevel=logging.WARNING,
                client_id=(client_ids or {}).get(i),
            )
            kwargs = {}
            if len(query) == 5:
                kwargs["deadline"] = query[4]
            results[i] = rpc.groupby(*query[:4], **kwargs)
        except Exception as exc:  # noqa: BLE001 - surfaced via errors dict
            errors[i] = exc

    threads = [
        threading.Thread(target=run, args=(i, q), daemon=True)
        for i, q in enumerate(queries)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 30)
    return results, errors


def test_window_zero_stages_nothing(swarm_cluster, monkeypatch):
    monkeypatch.delenv("BQUERYD_TPU_BATCH_WINDOW_MS", raising=False)
    cluster = swarm_cluster
    results, errors = _concurrent_groupby(
        cluster["url"],
        [(cluster["shards"], ["k"], [["v", "sum", "s"]], [])],
    )
    assert not errors
    assert cluster["controller"].counters["plan_bundles"] == 0
    assert not cluster["controller"]._pending_window


def test_window_fuses_compatible_queries_with_parity(
    swarm_cluster, monkeypatch
):
    """Distinct-but-compatible concurrent queries fuse into one bundle;
    every member's result is bit-identical to its window-0 run."""
    cluster = swarm_cluster
    df, shards, url = cluster["df"], cluster["shards"], cluster["url"]
    queries = [
        (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 7.0]]),
        (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 2.0]]),
        (shards, ["k"], [["w", "mean", "m"]], []),
    ]
    # window 0 reference first (and it must not bundle)
    monkeypatch.delenv("BQUERYD_TPU_BATCH_WINDOW_MS", raising=False)
    ref, errors = _concurrent_groupby(url, queries)
    assert not errors
    assert cluster["controller"].counters["plan_bundles"] == 0

    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "300")
    fused, errors = _concurrent_groupby(url, queries)
    assert not errors
    counters = cluster["controller"].counters
    assert counters["plan_bundles"] >= 1
    assert counters["plan_bundled_queries"] >= 3
    assert counters["plan_shared_dispatches"] >= 2
    for i in range(len(queries)):
        assert_same(fused[i], ref[i], ["k"])
    # pandas cross-check on one member (ints bit-exact end to end)
    expected = (
        df[df["w"] > 7.0].groupby("k")["v"].sum().reset_index()
        .rename(columns={"v": "s"})
    )
    assert_same(fused[0], expected, ["k"])


def test_window_keeps_incompatible_queries_separate(
    swarm_cluster, monkeypatch
):
    """One window, two signatures (different group keys): both complete
    correctly, unfused."""
    cluster = swarm_cluster
    df, shards, url = cluster["df"], cluster["shards"], cluster["url"]
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "300")
    before = cluster["controller"].counters["plan_bundles"]
    results, errors = _concurrent_groupby(
        url,
        [
            (shards, ["k"], [["v", "sum", "s"]], []),
            (shards, ["k2"], [["v", "sum", "s"]], []),
        ],
    )
    assert not errors
    assert cluster["controller"].counters["plan_bundles"] == before
    for i, gcol in enumerate(["k", "k2"]):
        expected = (
            df.groupby(gcol)["v"].sum().reset_index()
            .rename(columns={"v": "s"})
        )
        assert_same(results[i], expected, [gcol])


def test_bundle_member_deadline_isolation(swarm_cluster, monkeypatch):
    """A member whose deadline expires inside the window is dropped from
    the stack with ITS error; bundle-mates answer normally, and nothing is
    re-executed (one dispatch total)."""
    cluster = swarm_cluster
    df, shards, url = cluster["df"], cluster["shards"], cluster["url"]
    controller = cluster["controller"]
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "600")
    dispatched_before = controller.counters["dispatched_shards"]
    queries = [
        (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 4.0]]),
        # 0.1 s deadline expires inside the 0.6 s window
        (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 5.0]], 0.1),
        (shards, ["k"], [["v", "sum", "s"]], []),
    ]
    results, errors = _concurrent_groupby(url, queries)
    assert set(errors) == {1}
    assert "deadline" in str(errors[1]).lower()
    assert set(results) == {0, 2}
    for i, term in ((0, 4.0), (2, None)):
        sel = df if term is None else df[df["w"] > term]
        expected = (
            sel.groupby("k")["v"].sum().reset_index()
            .rename(columns={"v": "s"})
        )
        assert_same(results[i], expected, ["k"])
    # the expired member triggered no re-dispatch of its bundle-mates
    assert (
        controller.counters["dispatched_shards"] - dispatched_before == 1
    )
    wait_until(
        lambda: not controller.inflight and not controller.rpc_segments,
        desc="bundle fully settled",
    )


def test_bundle_member_quota_rejection_isolation(swarm_cluster, monkeypatch):
    """A quota-rejected query (client over BQUERYD_TPU_ADMIT_CLIENT_QUOTA
    while its first query sits staged) gets BUSY; the staged bundle
    completes undisturbed."""
    from bqueryd_tpu.rpc import RPCBusyError

    cluster = swarm_cluster
    df, shards, url = cluster["df"], cluster["shards"], cluster["url"]
    controller = cluster["controller"]
    controller.admission.client_quota = 1
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "800")
    try:
        queries = [
            (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 3.0]]),
            (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 6.0]]),
            # same client_id as 0: over quota while 0 is staged -> BUSY
            (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 1.0]]),
        ]

        def fire():
            # 0 and 1 (distinct quota buckets) land in the window; 2
            # shares client 0's bucket and must bounce without touching
            # the staged bundle
            results, errors = {}, {}

            def one(i, client_id, delay):
                from bqueryd_tpu.rpc import RPC

                time.sleep(delay)
                try:
                    rpc = RPC(
                        coordination_url=url, timeout=60,
                        loglevel=logging.WARNING, client_id=client_id,
                        retries=1,
                    )
                    results[i] = rpc.groupby(*queries[i])
                except Exception as exc:  # noqa: BLE001
                    errors[i] = exc

            threads = [
                threading.Thread(
                    target=one, args=(0, "app-a", 0.0), daemon=True
                ),
                threading.Thread(
                    target=one, args=(1, "app-b", 0.0), daemon=True
                ),
                threading.Thread(
                    target=one, args=(2, "app-a", 0.25), daemon=True
                ),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(90)
            return results, errors

        results, errors = fire()
        assert set(errors) == {2}
        assert isinstance(errors[2], RPCBusyError)
        assert set(results) == {0, 1}
        for i, term in ((0, 3.0), (1, 6.0)):
            expected = (
                df[df["w"] > term].groupby("k")["v"].sum().reset_index()
                .rename(columns={"v": "s"})
            )
            assert_same(results[i], expected, ["k"])
    finally:
        controller.admission.client_quota = 0


def test_bundle_member_error_isolation(swarm_cluster, monkeypatch):
    """A member whose query fails per-member (unknown column) errors alone;
    its bundle-mate completes."""
    cluster = swarm_cluster
    df, shards, url = cluster["df"], cluster["shards"], cluster["url"]
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "400")
    results, errors = _concurrent_groupby(
        url,
        [
            (shards, ["k"], [["v", "sum", "s"]], []),
            (shards, ["k"], [["no_such_column", "sum", "s"]], []),
        ],
    )
    assert set(errors) == {1}
    assert set(results) == {0}
    expected = (
        df.groupby("k")["v"].sum().reset_index().rename(columns={"v": "s"})
    )
    assert_same(results[0], expected, ["k"])


def test_identical_queries_share_dispatch_at_window_zero(swarm_cluster):
    """The PR-1 path the bench probe exercises: two concurrent IDENTICAL
    queries at window 0 fuse into one dispatch via the work-key index."""
    cluster = swarm_cluster
    df, shards, url = cluster["df"], cluster["shards"], cluster["url"]
    controller = cluster["controller"]
    os.environ.pop("BQUERYD_TPU_BATCH_WINDOW_MS", None)
    shared_before = controller.counters["plan_shared_dispatches"]
    dispatched_before = controller.counters["dispatched_shards"]
    query = (shards, ["k"], [["v", "sum", "s"]], [["w", ">", 4.44]])
    results, errors = _concurrent_groupby(url, [query, query])
    assert not errors
    assert (
        controller.counters["plan_shared_dispatches"] - shared_before >= 1
    )
    assert (
        controller.counters["dispatched_shards"] - dispatched_before == 1
    )
    expected = (
        df[df["w"] > 4.44].groupby("k")["v"].sum().reset_index()
        .rename(columns={"v": "s"})
    )
    for i in (0, 1):
        assert_same(results[i], expected, ["k"])
