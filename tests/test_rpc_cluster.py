"""Full-cluster integration tests: controller + workers + client in one
process (threads as nodes — the reference's own test topology, reference
tests/test_simple_rpc.py:42-74, with condition polling instead of sleeps)."""

import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tests.conftest import wait_until

NR_SHARDS = 5


def taxi_like_df(n=12_000, seed=4):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "passenger_count": rng.integers(0, 7, n).astype(np.int64),
            "trip_distance": rng.exponential(3.0, n),
            "total_amount": rng.gamma(2.5, 8.0, n),
        }
    )


@pytest.fixture(scope="module")
def taxi_df():
    return taxi_like_df()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory, taxi_df):
    from bqueryd_tpu.storage import ctable

    root = tmp_path_factory.mktemp("cluster_data")
    ctable.fromdataframe(taxi_df, str(root / "taxi.bcolz"))
    for i in range(NR_SHARDS):
        ctable.fromdataframe(
            taxi_df.iloc[i::NR_SHARDS], str(root / f"taxi-{i}.bcolzs")
        )
    return str(root)


@pytest.fixture(scope="module")
def cluster(data_dir):
    import bqueryd_tpu
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.worker import DownloaderNode, WorkerNode

    url = f"mem://cluster-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=data_dir,
        heartbeat_interval=0.2,
        dead_worker_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=url,
        data_dir=data_dir,
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.1,
    )

    class DummyDownloader(DownloaderNode):
        """Fakes the blob fetch but stages a real file so the movebcolz
        two-phase commit runs (the reference's DummyDownloader seam,
        reference tests/test_simple_rpc.py:36-39)."""

        def download_file(self, ticket, fileurl, lock=None):
            from bqueryd_tpu.download import incoming_dir

            staging = incoming_dir(self, ticket)
            name = os.path.basename(fileurl)
            os.makedirs(os.path.join(staging, name), exist_ok=True)
            self.file_downloader_progress(ticket, fileurl, "DONE")

    downloader = DummyDownloader(
        coordination_url=url,
        data_dir=data_dir,
        loglevel=logging.WARNING,
        heartbeat_interval=0.2,
        poll_timeout=0.1,
    )
    downloader.download_interval = 0.2

    from bqueryd_tpu.worker import MoveBcolzNode

    mover = MoveBcolzNode(
        coordination_url=url,
        data_dir=data_dir,
        loglevel=logging.WARNING,
        heartbeat_interval=0.2,
        poll_timeout=0.1,
    )
    mover.download_interval = 0.2

    threads = [
        threading.Thread(target=node.go, daemon=True)
        for node in (controller, worker, downloader, mover)
    ]
    for t in threads:
        t.start()

    wait_until(
        lambda: controller.files_map.get("taxi.bcolz"),
        desc="worker registration with data files",
    )
    wait_until(
        lambda: len(controller.worker_map) >= 3,
        desc="all workers registered",
    )
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(coordination_url=url, timeout=60, loglevel=logging.WARNING)
    yield {
        "rpc": rpc,
        "controller": controller,
        "worker": worker,
        "downloader": downloader,
        "mover": mover,
        "url": url,
    }
    for node in (controller, worker, downloader, mover):
        node.running = False
    for t in threads:
        t.join(timeout=5)


def test_ping(cluster):
    assert cluster["rpc"].ping() == "pong"


def test_info_shape(cluster):
    info = cluster["rpc"].info()
    assert info["address"] == cluster["controller"].address
    workers = info["workers"]
    types = sorted(w["workertype"] for w in workers.values())
    assert types == ["calc", "download", "movebcolz"]
    node_names = {w["node"] for w in workers.values()}
    assert node_names == {cluster["worker"].node_name}
    assert info["others"] == {}
    assert cluster["rpc"].last_call_duration is not None


def test_groupby_single_file_vs_pandas(cluster, taxi_df):
    rpc = cluster["rpc"]
    for op, pandas_fn in [("sum", "sum"), ("mean", "mean"), ("count", "count")]:
        got = rpc.groupby(
            ["taxi.bcolz"],
            ["payment_type"],
            [["total_amount", op, "total_amount"]],
            [],
        )
        got = got.sort_values("payment_type").reset_index(drop=True)
        expected = (
            getattr(taxi_df.groupby("payment_type")["total_amount"], pandas_fn)()
            .reset_index()
        )
        pd.testing.assert_frame_equal(got, expected, check_dtype=False, check_column_type=False)


def test_groupby_sharded_matches_full(cluster):
    rpc = cluster["rpc"]
    shard_names = [f"taxi-{i}.bcolzs" for i in range(NR_SHARDS)]
    full = rpc.groupby(
        ["taxi.bcolz"], ["payment_type"],
        [["passenger_count", "count", "passenger_count"]], [],
    )
    parts = rpc.groupby(
        shard_names, ["payment_type"],
        [["passenger_count", "count", "passenger_count"]], [],
    )
    full = full.sort_values("payment_type").reset_index(drop=True)
    parts = parts.sort_values("payment_type").reset_index(drop=True)
    pd.testing.assert_frame_equal(full, parts, check_dtype=False, check_column_type=False)


def test_groupby_with_filter(cluster, taxi_df):
    got = cluster["rpc"].groupby(
        ["taxi.bcolz"],
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        [("trip_distance", ">", 5.0)],
    )
    expected = (
        taxi_df[taxi_df.trip_distance > 5.0]
        .groupby("payment_type")["total_amount"].sum().reset_index()
    )
    got = got.sort_values("payment_type").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, expected, check_dtype=False, check_column_type=False)


def test_count_distinct_sharded(cluster, taxi_df):
    """Distinct counts can't psum-merge; the cluster must route them through
    the per-shard gather path and still agree with pandas nunique."""
    shard_names = [f"taxi-{i}.bcolzs" for i in range(NR_SHARDS)]
    got = cluster["rpc"].groupby(
        shard_names,
        ["payment_type"],
        [["passenger_count", "count_distinct", "nuniq"]],
        [],
    )
    expected = (
        taxi_df.groupby("payment_type")["passenger_count"]
        .nunique()
        .reset_index(name="nuniq")
    )
    got = got.sort_values("payment_type").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, expected, check_dtype=False, check_column_type=False)


def test_count_distinct_single_file_device_path(cluster, taxi_df):
    """A single-file count_distinct query gets the controller's sole-shard
    hint and finalizes on device (counts, no value sets) — same answer as
    pandas nunique."""
    got = cluster["rpc"].groupby(
        ["taxi.bcolz"],
        ["payment_type"],
        [["passenger_count", "count_distinct", "nuniq"]],
        [],
    )
    expected = (
        taxi_df.groupby("payment_type")["passenger_count"]
        .nunique()
        .reset_index(name="nuniq")
    )
    got = got.sort_values("payment_type").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, expected, check_dtype=False, check_column_type=False)


def test_count_distinct_string_column_across_shards(tmp_path, mem_store_url):
    """Per-shard dictionaries encode the same string with different codes;
    the distinct-set merge must union VALUES, not codes."""
    import threading

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage import ctable as storage_ctable
    from bqueryd_tpu.worker import WorkerNode

    # shard 0 sees 'cash' first, shard 1 sees 'credit' first -> code spaces
    # deliberately disagree
    s0 = pd.DataFrame({"g": [1, 1, 2], "pay": ["cash", "credit", "cash"]})
    s1 = pd.DataFrame({"g": [1, 2, 2], "pay": ["credit", "cash", "credit"]})
    storage_ctable.fromdataframe(s0, str(tmp_path / "p0.bcolzs"))
    storage_ctable.fromdataframe(s1, str(tmp_path / "p1.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.1,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url, data_dir=str(tmp_path),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.1, poll_timeout=0.05,
    )
    threads = [
        threading.Thread(target=n.go, daemon=True)
        for n in (controller, worker)
    ]
    for t in threads:
        t.start()
    try:
        wait_until(lambda: len(controller.files_map) >= 2, desc="shards")
        rpc = RPC(coordination_url=mem_store_url, timeout=30,
                  loglevel=logging.WARNING)
        got = rpc.groupby(
            ["p0.bcolzs", "p1.bcolzs"], ["g"],
            [["pay", "count_distinct", "nuniq"]], [],
        ).sort_values("g").reset_index(drop=True)
        full = pd.concat([s0, s1], ignore_index=True)
        exp = full.groupby("g")["pay"].nunique().reset_index(name="nuniq")
        pd.testing.assert_frame_equal(got, exp, check_dtype=False, check_column_type=False)
    finally:
        for n in (controller, worker):
            n.running = False
        for t in threads:
            t.join(timeout=5)


def test_raw_rows_mode_sharded(cluster, taxi_df):
    """aggregate=False returns the filtered rows themselves, concatenated
    across shards (reference bqueryd/worker.py:316-323 raw path)."""
    shard_names = [f"taxi-{i}.bcolzs" for i in range(NR_SHARDS)]
    got = cluster["rpc"].groupby(
        shard_names,
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        [("trip_distance", ">", 20.0)],
        aggregate=False,
    )
    expected = taxi_df[taxi_df.trip_distance > 20.0]
    assert len(got) == len(expected)
    # same multiset of rows (shard order differs from source order)
    got_s = got.sort_values(
        ["payment_type", "total_amount"]
    ).reset_index(drop=True)
    exp_s = expected[["payment_type", "total_amount"]].sort_values(
        ["payment_type", "total_amount"]
    ).reset_index(drop=True)
    pd.testing.assert_frame_equal(got_s, exp_s, check_dtype=False, check_column_type=False)


def test_groupby_unknown_file_errors(cluster):
    from bqueryd_tpu.rpc import RPCError

    with pytest.raises(RPCError, match="not found"):
        cluster["rpc"].groupby(["nope.bcolz"], ["payment_type"], [["x", "sum", "x"]], [])


def test_unknown_verb_errors(cluster):
    from bqueryd_tpu.rpc import RPCError

    with pytest.raises(RPCError, match="unknown verb"):
        cluster["rpc"].frobnicate()


def test_sleep_roundtrip(cluster):
    result = cluster["rpc"].sleep(0.01)
    assert "slept" in result


def test_download_ticket_registration(cluster):
    import bqueryd_tpu

    rpc = cluster["rpc"]
    ticket = rpc.download(filenames=["test_download.bcolz"], bucket="bcolz", wait=False)
    store = cluster["controller"].store
    entries = store.hgetall(bqueryd_tpu.REDIS_TICKET_KEY_PREFIX + ticket)
    assert len(entries) == 1
    ((slot, value),) = entries.items()
    assert slot.partition("_")[2] == "s3://bcolz/test_download.bcolz"
    # the cluster's dummy downloader may legitimately claim the ticket and
    # advance it between registration and this read — assert the slot value
    # is a well-formed progress state, not specifically the initial -1
    state = value.rpartition("_")[2]
    assert state == "-1" or state == "DONE" or state.isdigit()


def test_download_wait_released_by_dummy_downloader(cluster):
    result = cluster["rpc"].download(
        filenames=["some_file.newdata"], bucket="bcolz", wait=True
    )
    assert result == "DONE"


def test_worker_error_aborts_query(cluster, data_dir):
    """A shard whose table is corrupt must abort the whole query with the
    worker's error forwarded (reference bqueryd/controller.py:157-168)."""
    import shutil

    from bqueryd_tpu.rpc import RPCError

    from tests.conftest import wait_until

    bad = os.path.join(data_dir, "bad.bcolz")
    os.makedirs(bad, exist_ok=True)
    with open(os.path.join(bad, "meta.json"), "w") as f:
        f.write("{}")
    try:
        wait_until(
            lambda: "bad.bcolz" in cluster["controller"].files_map,
            desc="bad.bcolz discovery",
        )
        with pytest.raises(RPCError):
            cluster["rpc"].groupby(
                ["bad.bcolz"], ["payment_type"], [["x", "sum", "x"]], []
            )
    finally:
        shutil.rmtree(bad)


def test_loglevel_fanout(cluster):
    import bqueryd_tpu

    # the verb fans out asynchronously (controller applies it synchronously,
    # workers on their next poll tick), and every node shares this process's
    # root logger — poll until the last fan-out echo settles
    assert cluster["rpc"].loglevel("debug") == "OK"
    wait_until(
        lambda: bqueryd_tpu.logger.level == logging.DEBUG,
        desc="loglevel debug applied",
    )
    cluster["rpc"].loglevel("info")
    # stability, not a fixed sleep: every fan-out echo (controller + 3
    # worker roles) must have applied 'info' — poll until the level has
    # held INFO continuously for half a second
    stable_since = [None]

    def held_info():
        if bqueryd_tpu.logger.level != logging.INFO:
            stable_since[0] = None
            return False
        if stable_since[0] is None:
            stable_since[0] = time.time()
        return time.time() - stable_since[0] >= 0.5
    wait_until(held_info, desc="loglevel info applied and stable")


def test_batched_dispatch_merges_on_worker(cluster, taxi_df):
    """Co-located mergeable shards travel as ONE CalcMessage and come back as
    ONE psum-merged payload (the TPU redesign of per-shard fan-out)."""
    rpc = cluster["rpc"]
    shard_names = [f"taxi-{i}.bcolzs" for i in range(NR_SHARDS)]
    got = rpc.groupby(
        shard_names, ["payment_type"],
        [["total_amount", "mean", "m"], ["total_amount", "sum", "s"]], [],
    )
    # one timing entry covering all shards == one worker round-trip,
    # labelled compactly as "<first-file>+<n-1>more"
    assert len(rpc.last_call_timings) == 1
    (key,) = rpc.last_call_timings
    first, _, rest = key.partition("+")
    assert first in shard_names
    assert rest == f"{NR_SHARDS - 1}more"
    g = taxi_df.groupby("payment_type")["total_amount"]
    expected = pd.DataFrame({"m": g.mean(), "s": g.sum()}).reset_index()
    got = got.sort_values("payment_type").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, expected, check_dtype=False, check_column_type=False)


def test_batch_false_restores_pershard_dispatch(cluster):
    rpc = cluster["rpc"]
    shard_names = [f"taxi-{i}.bcolzs" for i in range(NR_SHARDS)]
    rpc.groupby(
        shard_names, ["payment_type"], [["total_amount", "sum", "s"]], [],
        batch=False,
    )
    assert len(rpc.last_call_timings) == NR_SHARDS


def test_legacy_merge_sum_of_shard_means(cluster, taxi_df):
    """legacy_merge reproduces the reference's sum-of-shard-means quirk
    (reference bqueryd/rpc.py:171), which requires per-shard payloads."""
    from bqueryd_tpu.rpc import RPC

    legacy = RPC(
        coordination_url=cluster["url"], timeout=60,
        loglevel=logging.WARNING, legacy_merge=True,
    )
    shard_names = [f"taxi-{i}.bcolzs" for i in range(NR_SHARDS)]
    got = legacy.groupby(
        shard_names, ["payment_type"], [["total_amount", "mean", "m"]], [],
    )
    assert len(legacy.last_call_timings) == NR_SHARDS  # batching disabled
    expected = sum(
        taxi_df.iloc[i::NR_SHARDS].groupby("payment_type")["total_amount"]
        .mean()
        for i in range(NR_SHARDS)
    ).reset_index(name="m")
    got = got.sort_values("payment_type").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, expected.rename(columns={"total_amount": "m"}),
        check_dtype=False, check_column_type=False,
    )


def test_readfile_returns_bytes(cluster, data_dir):
    """The reference's readfile verb (reference bqueryd/worker.py:216-220)
    end to end: client -> controller -> worker -> file bytes back."""
    with open(os.path.join(data_dir, "probe.txt"), "wb") as f:
        f.write(b"hello readfile")
    assert cluster["rpc"].readfile("probe.txt") == b"hello readfile"


def test_readfile_rejects_path_traversal(cluster):
    """The traversal guard is a deliberate behavior change vs the reference
    (which would serve any path joined under data_dir): escaping paths must
    error, not leak files."""
    from bqueryd_tpu.rpc import RPCError

    with pytest.raises(RPCError, match="escapes data_dir"):
        cluster["rpc"].readfile("../../etc/hostname")


def test_replacement_worker_first_query_rides_disk_sidecars(tmp_path):
    """A replacement worker's FIRST query on shards a previous worker served
    must come back exact and be answered from the on-disk factorize
    sidecars (bquery auto_cache parity across worker restarts): the
    sidecars' mtimes must not change — a store only happens on a load
    miss, so unchanged files mean the cold alignment truly loaded."""
    import glob

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage import ctable as CT
    from bqueryd_tpu.worker import WorkerNode

    df = taxi_like_df(n=6_000, seed=9)
    for i in range(3):
        CT.fromdataframe(
            df.iloc[i::3].reset_index(drop=True),
            str(tmp_path / f"side-{i}.bcolzs"),
        )
    url = f"mem://sidecar-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
        dead_worker_timeout=2.0,
    )

    def new_worker():
        return WorkerNode(
            coordination_url=url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.1,
            poll_timeout=0.05,
        )

    files = [f"side-{i}.bcolzs" for i in range(3)]
    expected = (
        df.groupby("payment_type")["total_amount"].sum().to_dict()
    )
    w1 = new_worker()
    nodes = [controller, w1]
    threads = [
        threading.Thread(target=n.go, daemon=True) for n in nodes
    ]
    for t in threads:
        t.start()
    try:
        wait_until(
            lambda: all(f in controller.files_map for f in files),
            desc="registration",
        )
        rpc = RPC(coordination_url=url, timeout=30,
                  loglevel=logging.WARNING)
        got = rpc.groupby(
            files, ["payment_type"], [["total_amount", "sum", "s"]], []
        )
        assert dict(
            zip(got["payment_type"], got["s"])
        ) == pytest.approx(expected)

        sidecars = sorted(
            glob.glob(str(tmp_path / "side-*" / "cols" / "*" / "*.npz"))
        )
        assert sidecars, "first worker must have persisted factorizations"
        stamps_before = [os.stat(p).st_mtime_ns for p in sidecars]

        # hard restart: silence + replacement (fresh engine, empty caches)
        w1.send = lambda *a, **k: None
        w1._hb_stop.set()
        w1.running = False
        w2 = new_worker()
        nodes.append(w2)
        t2 = threading.Thread(target=w2.go, daemon=True)
        threads.append(t2)
        t2.start()
        wait_until(
            lambda: w2.worker_id in controller.worker_map
            and w1.worker_id not in controller.worker_map,
            timeout=20,
            desc="replacement adopted, old culled",
        )
        # a DIFFERENT aggregation over the same key column: no result
        # cache anywhere can serve it, so it must run on the replacement —
        # while key alignment still rides the same factorize sidecars
        got2 = rpc.groupby(
            files, ["payment_type"], [["total_amount", "mean", "m"]], []
        )
        expected_mean = (
            df.groupby("payment_type")["total_amount"].mean().to_dict()
        )
        assert dict(
            zip(got2["payment_type"], got2["m"])
        ) == pytest.approx(expected_mean)
        stamps_after = [os.stat(p).st_mtime_ns for p in sidecars]
        assert stamps_after == stamps_before, (
            "replacement worker re-factorized instead of loading sidecars"
        )
    finally:
        for n in nodes:
            n.running = False
        for t in threads:
            t.join(timeout=5)
