"""Direct unit coverage of the host value-keyed merge — the fallback the
``BQUERYD_TPU_DEVICE_MERGE`` kill switch (and every non-mergeable route)
relies on (ISSUE 7 satellite).

``hostmerge._union_distinct_flat`` (packed-int fast path, overflow fallback,
string values, empty parts) and ``hostmerge._merge_partials`` (mixed
float32/float64 measure widening, count_distinct set union, value_kinds
reconciliation incl. pre-kinds payloads, shape disagreement) previously had
only end-to-end coverage.
"""

import numpy as np
import pytest

from bqueryd_tpu.models.query import ResultPayload
from bqueryd_tpu.parallel import hostmerge


# -- _union_distinct_flat -----------------------------------------------------

def _flat(mapping, n_groups):
    """{gid: [values...]} -> (local_map, values, offsets) part."""
    gids = sorted(mapping)
    values = np.concatenate(
        [np.asarray(mapping[g]) for g in gids]
    ) if gids else np.empty(0, dtype=np.int64)
    offsets = np.zeros(len(gids) + 1, dtype=np.int64)
    np.cumsum([len(mapping[g]) for g in gids], out=offsets[1:])
    return np.asarray(gids, dtype=np.int64), values, offsets


def _sets(values, offsets):
    return [
        set(np.asarray(values[offsets[g]:offsets[g + 1]]).tolist())
        for g in range(len(offsets) - 1)
    ]


def test_union_distinct_flat_int_fast_path():
    a = _flat({0: [1, 2], 2: [5]}, 3)
    b = _flat({1: [2], 2: [5, 7]}, 3)
    values, offsets = hostmerge._union_distinct_flat([a, b], 3)
    assert _sets(values, offsets) == [{1, 2}, {2}, {5, 7}]
    assert offsets.tolist() == [0, 2, 3, 5]


def test_union_distinct_flat_overflow_falls_back_to_unique():
    """Values near int64 max force the packed-range path off (span
    overflow); the np.unique fallback must union identically."""
    big = 1 << 62
    a = _flat({0: [-big, big], 1: [big]}, 2)
    b = _flat({0: [big], 1: [-big]}, 2)
    values, offsets = hostmerge._union_distinct_flat([a, b], 2)
    assert _sets(values, offsets) == [{-big, big}, {-big, big}]


def test_union_distinct_flat_string_values():
    a = (np.array([0, 1]), np.array(["x", "y"], dtype=object),
         np.array([0, 1, 2]))
    b = (np.array([0]), np.array(["y"], dtype=object), np.array([0, 1]))
    values, offsets = hostmerge._union_distinct_flat([a, b], 2)
    assert _sets(values, offsets) == [{"x", "y"}, {"y"}]


def test_union_distinct_flat_empty_parts():
    empty = (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64),
             np.zeros(1, dtype=np.int64))
    values, offsets = hostmerge._union_distinct_flat([empty], 4)
    assert len(values) == 0
    assert offsets.tolist() == [0, 0, 0, 0, 0]
    # one empty part beside a live one contributes nothing
    live = _flat({3: [9]}, 4)
    values, offsets = hostmerge._union_distinct_flat([empty, live], 4)
    assert _sets(values, offsets) == [set(), set(), set(), {9}]


def test_union_distinct_flat_spanning_values_counted_once():
    """The reference's forced-'sum' merge double-counted values spanning
    shards; the set union must not."""
    a = _flat({0: [7, 8]}, 1)
    b = _flat({0: [8, 9]}, 1)
    values, offsets = hostmerge._union_distinct_flat([a, b], 1)
    assert offsets[1] - offsets[0] == 3  # {7, 8, 9}, 8 counted once


# -- _merge_partials ----------------------------------------------------------

def _partials_payload(keys, rows, aggs, ops, out_cols, value_kinds=None,
                      key_col="g"):
    return ResultPayload.partials(
        key_cols=[key_col],
        keys={key_col: np.asarray(keys)},
        rows=np.asarray(rows, dtype=np.int64),
        aggs=aggs,
        ops=ops,
        out_cols=out_cols,
        value_kinds=value_kinds,
    )


def test_merge_partials_widens_mixed_float_dtypes():
    """A float32-summing shard merging with a float64 sibling must widen to
    float64 (np.result_type), not truncate into parts[0]'s dtype."""
    a = _partials_payload(
        [0, 1], [2, 1],
        [{"sum": np.array([1.5, 2.5], dtype=np.float32),
          "count": np.array([2, 1], dtype=np.int64)}],
        ["mean"], ["m"],
    )
    b = _partials_payload(
        [1, 2], [1, 3],
        [{"sum": np.array([0.25, 9.0], dtype=np.float64),
          "count": np.array([1, 3], dtype=np.int64)}],
        ["mean"], ["m"],
    )
    merged = hostmerge._merge_partials([a, b])
    assert merged["aggs"][0]["sum"].dtype == np.float64
    order, cols = hostmerge.finalize_table(merged)
    got = dict(zip(cols["g"].tolist(), cols["m"].tolist()))
    assert got[0] == pytest.approx(0.75)
    assert got[1] == pytest.approx((2.5 + 0.25) / 2)
    assert got[2] == pytest.approx(3.0)


def test_merge_partials_count_distinct_union_plus_float_measure():
    """The ISSUE's mixed case: count_distinct set parts merging by union
    NEXT TO a float measure in the same payload pair."""
    a = _partials_payload(
        [0, 1], [2, 1],
        [
            {"distinct_values": np.array([10, 11]),
             "distinct_offsets": np.array([0, 2, 2])},
            {"sum": np.array([1.0, 2.0], dtype=np.float32)},
        ],
        ["count_distinct", "sum"], ["nd", "s"],
    )
    b = _partials_payload(
        [0, 1], [1, 2],
        [
            {"distinct_values": np.array([11, 12, 13]),
             "distinct_offsets": np.array([0, 1, 3])},
            {"sum": np.array([0.5, 4.0], dtype=np.float64)},
        ],
        ["count_distinct", "sum"], ["nd", "s"],
    )
    merged = hostmerge._merge_partials([a, b])
    order, cols = hostmerge.finalize_table(merged)
    got_nd = dict(zip(cols["g"].tolist(), cols["nd"].tolist()))
    assert got_nd == {0: 2, 1: 2}  # {10,11} and {12,13}; 11 union'd once
    got_s = dict(zip(cols["g"].tolist(), cols["s"].tolist()))
    assert got_s[0] == pytest.approx(1.5)
    assert got_s[1] == pytest.approx(6.0)
    assert merged["aggs"][1]["sum"].dtype == np.float64


def test_merge_partials_value_kinds_reconciliation():
    """uint64 next to a narrower-unsigned sibling keeps the unsigned view;
    a payload with NO value_kinds (pre-kinds worker in a rolling restart)
    merges as all-None; uint64 next to a signed/float sibling refuses."""
    mk = lambda kinds: _partials_payload(
        [0], [1], [{"sum": np.array([5], dtype=np.int64)}], ["sum"], ["s"],
        value_kinds=kinds,
    )
    merged = hostmerge._merge_partials([mk(["uint64"]), mk(["uint"])])
    assert merged["value_kinds"] == ["uint64"]

    legacy = mk(None)
    del legacy["value_kinds"]
    merged = hostmerge._merge_partials([mk(["uint"]), legacy])
    assert merged["value_kinds"] == [None]

    with pytest.raises(ValueError, match="disagree"):
        hostmerge._merge_partials([mk(["uint64"]), mk([None])])


def test_merge_partials_rejects_shape_disagreement():
    a = _partials_payload(
        [0], [1], [{"sum": np.array([1], dtype=np.int64)}], ["sum"], ["s"],
    )
    b = _partials_payload(
        [0], [1], [{"count": np.array([1], dtype=np.int64)}], ["count"],
        ["n"],
    )
    with pytest.raises(ValueError, match="disagree"):
        hostmerge._merge_partials([a, b])


def test_merge_partials_min_max_extrema_fill_and_widening():
    """min/max across differently-widthed shards: result_type widening must
    not truncate a wider sibling's extrema into the fill range."""
    a = _partials_payload(
        [0, 1], [1, 1],
        [{"min": np.array([5, -100], dtype=np.int8),
          "count": np.array([1, 1], dtype=np.int64)}],
        ["min"], ["lo"],
    )
    b = _partials_payload(
        [0], [1],
        [{"min": np.array([-70_000], dtype=np.int32),
          "count": np.array([1], dtype=np.int64)}],
        ["min"], ["lo"],
    )
    merged = hostmerge._merge_partials([a, b])
    assert merged["aggs"][0]["min"].dtype == np.int32
    order, cols = hostmerge.finalize_table(merged)
    got = dict(zip(cols["g"].tolist(), cols["lo"].tolist()))
    assert got == {0: -70_000, 1: -100}
