"""Pipelined shard execution + the device-resident working-set cache.

Covers ISSUE 4: LRU byte-capped cache semantics (oversize reject, LRU
segment eviction, eviction counters), the bounded pipeline pool (ordered
results, serial degradation, stage busy clocks), bit-identical int64
aggregates between the pipelined and serial per-shard engine paths on the
differential-fuzz corpus, and the working-set layer's invalidation rules —
meta.json mtime bump, column-set change, and eviction-under-HBM-pressure
must all miss; a repeat query with a different measure column or aggregate
op must hit the codes/alignment segments with ZERO factorize calls.
"""

import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
from bqueryd_tpu.parallel import hostmerge, pipeline
from bqueryd_tpu.parallel.executor import MeshQueryExecutor, make_mesh
from bqueryd_tpu.storage.ctable import ctable
from bqueryd_tpu.utils.cache import BytesCappedCache


class _Blob:
    def __init__(self, nbytes):
        self.nbytes = nbytes


# -- LRU cache semantics (satellite: utils/cache.py fix) ---------------------

def test_cache_rejects_oversize_entry():
    cache = BytesCappedCache(100)
    cache.put("big", _Blob(101))
    assert "big" not in cache
    assert cache.nbytes == 0 and len(cache) == 0
    assert cache.rejected == 1
    # the budget-sized entry still fits exactly
    cache.put("fits", _Blob(100))
    assert "fits" in cache and cache.nbytes == 100


def test_cache_evicts_lru_not_wholesale():
    cache = BytesCappedCache(30)
    for key in ("a", "b", "c"):
        cache.put(key, _Blob(10))
    assert cache.get("a") is not None  # refresh recency: b is now LRU
    cache.put("d", _Blob(10))
    assert "b" not in cache, "LRU entry must go first"
    assert all(k in cache for k in ("a", "c", "d")), (
        "eviction must be segmented, not a wholesale clear"
    )
    assert cache.evictions == 1
    assert cache.nbytes == 30


def test_cache_never_ends_over_budget():
    cache = BytesCappedCache(25)
    for i in range(10):
        cache.put(i, _Blob(10))
        assert cache.nbytes <= 25
    assert len(cache) == 2  # two 10-byte entries fit a 25-byte budget
    assert cache.evictions == 8


def test_cache_stats_and_evict_bytes():
    cache = BytesCappedCache(100)
    for key in ("a", "b", "c"):
        cache.put(key, _Blob(20))
    cache.get("a")
    cache.get("zzz")
    stats = cache.stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    assert stats["entries"] == 3 and stats["bytes"] == 60
    freed, count = cache.evict_bytes(30)  # b then c are LRU after a's refresh
    assert freed == 40 and count == 2 and cache.evictions == 2
    assert "a" in cache and "b" not in cache


# -- pipeline pool -----------------------------------------------------------

def test_map_ordered_preserves_input_order(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "4")
    rng = np.random.RandomState(3)
    delays = rng.random(12) * 0.02

    def job(i):
        time.sleep(delays[i])
        return i * 10

    assert pipeline.map_ordered(job, range(12)) == [i * 10 for i in range(12)]


def test_map_ordered_serial_at_one_thread(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "1")
    seen = []

    def job(i):
        seen.append(threading.current_thread())
        return i

    assert pipeline.map_ordered(job, range(4)) == list(range(4))
    assert all(t is threading.current_thread() for t in seen), (
        "one-thread pipelines must run stages on the calling thread"
    )


def test_map_ordered_propagates_exceptions(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "3")

    def job(i):
        if i == 2:
            raise ValueError("boom")
        return i

    with pytest.raises(ValueError, match="boom"):
        pipeline.map_ordered(job, range(5))


def test_pipeline_threads_env_parsing(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "3")
    assert pipeline.pipeline_threads() == 3
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "garbage")
    assert pipeline.pipeline_threads() == pipeline._DEFAULT_THREADS
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "0")
    assert pipeline.pipeline_threads() == pipeline._DEFAULT_THREADS
    monkeypatch.delenv("BQUERYD_TPU_PIPELINE_THREADS")
    assert pipeline.pipeline_threads() == pipeline._DEFAULT_THREADS


def test_stage_clock_accumulates_busy_time():
    clock = pipeline.clock()
    before = clock.snapshot()["busy_seconds"].get("decode", 0.0)
    with pipeline.stage("decode"):
        time.sleep(0.01)
    snap = clock.snapshot()
    assert snap["busy_seconds"]["decode"] >= before + 0.009
    assert snap["calls"]["decode"] >= 1


# -- pipelined engine path == serial path, bit for bit -----------------------

N_SHARDS = 3


def _fuzz_shards(tmp_path, seed=5):
    """A slice of the differential-fuzz corpus: int64 at limb-straddling
    magnitudes, float32 NaNs, dict keys with nulls."""
    rng = np.random.default_rng(seed)
    frames, tables = [], []
    for i in range(N_SHARDS):
        n = 2_000
        frames.append(
            pd.DataFrame(
                {
                    "k_int": rng.integers(0, 7, n).astype(np.int64),
                    "k_str": rng.choice(
                        ["a", "b", "c", None], n, p=[0.4, 0.3, 0.2, 0.1]
                    ),
                    "v_small": rng.integers(-1000, 1000, n).astype(np.int64),
                    "v_big": rng.integers(
                        -(2**60), 2**60, n
                    ).astype(np.int64),
                    "v_float": np.where(
                        rng.random(n) < 0.05,
                        np.nan,
                        rng.random(n) * 100 - 50,
                    ).astype(np.float32),
                    "sel": rng.random(n).astype(np.float64),
                }
            )
        )
        root = str(tmp_path / f"fz{i}.bcolzs")
        ctable.fromdataframe(frames[-1], root)
        tables.append(ctable(root))
    return frames, tables


PIPELINE_CASES = [
    (["k_int"], [["v_big", "sum", "s"]], []),
    (["k_str"], [["v_small", "sum", "s"], ["v_float", "mean", "m"]], []),
    (["k_int"], [["v_small", "sum", "s"]], [["sel", ">", 0.5]]),
    # count_distinct is engine-path-only: exactly the worker fallback the
    # pipeline pool parallelizes
    (["k_int"], [["v_float", "count_distinct", "nd"]], []),
]


@pytest.mark.parametrize("case", range(len(PIPELINE_CASES)))
def test_pipelined_engine_path_bit_identical_to_serial(
    tmp_path, monkeypatch, case
):
    """The worker's per-shard engine fallback (pipeline.map_ordered over
    execute_local) must produce BIT-identical payload merges at any pool
    width — int64 aggregates compared with zero tolerance."""
    _frames, tables = _fuzz_shards(tmp_path)
    gcols, aggs, where = PIPELINE_CASES[case]
    query = GroupByQuery(gcols, aggs, where, aggregate=True)

    def run(threads):
        monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", str(threads))
        engine = QueryEngine()
        payloads = pipeline.map_ordered(
            lambda t: engine.execute_local(t, query), tables
        )
        return hostmerge.finalize_table(hostmerge.merge_payloads(payloads))

    order_s, cols_s = run(1)
    order_p, cols_p = run(4)
    assert order_s == order_p
    for col in order_s:
        a, b = np.asarray(cols_s[col]), np.asarray(cols_p[col])
        assert a.dtype == b.dtype
        # exact equality for EVERY dtype (assert_array_equal treats NaN as
        # equal to NaN): the pipelined path must be bit-identical, not close
        np.testing.assert_array_equal(a, b)


# -- working-set cache: hits without factorize, invalidation -----------------

@pytest.fixture
def ws_tables(tmp_path):
    rng = np.random.RandomState(11)
    frames, tables = [], []
    for i in range(3):
        df = pd.DataFrame(
            {
                "g": rng.randint(0, 6, 600).astype(np.int64),
                "h": rng.randint(0, 4, 600).astype(np.int64),
                "v": rng.randint(-30000, 30000, 600).astype(np.int64),
                "w": rng.randint(-500, 500, 600).astype(np.int64),
            }
        )
        root = str(tmp_path / f"ws{i}.bcolzs")
        ctable.fromdataframe(df, root)
        frames.append(df)
        tables.append(ctable(root))
    return frames, tables


def _poison_factorize(monkeypatch):
    """Make any factorize call an assertion failure (the engine and the
    mesh alignment both go through ``ops.factorize``)."""
    from bqueryd_tpu import ops as ops_mod

    def boom(*a, **k):
        raise AssertionError("factorize ran on what must be a cache hit")

    monkeypatch.setattr(ops_mod, "factorize", boom)


def test_different_measure_hits_codes_cache_no_factorize(
    ws_tables, monkeypatch
):
    """THE acceptance probe: a warm repeat query with a DIFFERENT measure
    column on unchanged shards performs zero factorize calls — the codes +
    alignment segments answer, only the new measure block is built."""
    frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    ex.execute(tables, GroupByQuery(["g"], [["v", "sum", "s"]]))
    stats0 = ex.workingset.stats()

    _poison_factorize(monkeypatch)
    r = ex.execute(tables, GroupByQuery(["g"], [["w", "sum", "s"]]))
    stats1 = ex.workingset.stats()
    assert stats1["codes"]["hits"] == stats0["codes"]["hits"] + 1
    assert stats1["align"]["hits"] == stats0["align"]["hits"] + 1
    # only the new measure column missed (decode+pack+H2D for `w` alone)
    assert stats1["blocks"]["entries"] == stats0["blocks"]["entries"] + 1

    full = pd.concat(frames, ignore_index=True)
    expect = full.groupby("g")["w"].sum()
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_array_equal(
        r["aggs"][0]["sum"][order], expect.sort_index().to_numpy()
    )


def test_different_agg_op_hits_codes_and_blocks(ws_tables, monkeypatch):
    """Same measure, different aggregate op: codes AND blocks both hit —
    the only new work is the (cached-program) kernel dispatch."""
    frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    ex.execute(tables, GroupByQuery(["g"], [["v", "sum", "s"]]))
    stats0 = ex.workingset.stats()

    _poison_factorize(monkeypatch)
    r = ex.execute(tables, GroupByQuery(["g"], [["v", "mean", "m"]]))
    stats1 = ex.workingset.stats()
    assert stats1["codes"]["hits"] == stats0["codes"]["hits"] + 1
    assert stats1["blocks"]["hits"] == stats0["blocks"]["hits"] + 1
    assert stats1["blocks"]["entries"] == stats0["blocks"]["entries"]

    full = pd.concat(frames, ignore_index=True)
    expect = full.groupby("g")["v"].mean().sort_index().to_numpy()
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_allclose(
        r["aggs"][0]["sum"][order] / r["aggs"][0]["count"][order],
        expect, rtol=1e-12,
    )


def test_meta_mtime_bump_invalidates_working_set(ws_tables):
    """Shard activation (meta.json rewrite) must MISS: the content key
    carries meta.json's inode+mtime."""
    _frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    query = GroupByQuery(["g"], [["v", "sum", "s"]])
    ex.execute(tables, query)
    stats0 = ex.workingset.stats()

    meta = os.path.join(tables[0].rootdir, "meta.json")
    st = os.stat(meta)
    os.utime(meta, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000_000))
    ex.execute(tables, query)
    stats1 = ex.workingset.stats()
    assert stats1["align"]["misses"] == stats0["align"]["misses"] + 1
    assert stats1["codes"]["misses"] == stats0["codes"]["misses"] + 1
    assert stats1["align"]["entries"] == 2  # old + new identity


def test_append_invalidates_working_set_and_serves_new_rows(ws_tables):
    """PR-14 satellite: the append path must invalidate like activation —
    content keys carry the table's row count + meta identity, and the
    decoded-column cache keys carry the committed chunk/row counts, so a
    grown shard can never serve stale cached bytes or stale aggregates."""
    frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    query = GroupByQuery(["g"], [["v", "sum", "s"]])
    before = ex.execute(tables, query)
    stats0 = ex.workingset.stats()

    extra = pd.DataFrame(
        {
            "g": np.array([0, 1], dtype=np.int64),
            "h": np.array([0, 1], dtype=np.int64),
            "v": np.array([10_000_000, -10_000_000], dtype=np.int64),
            "w": np.array([1, 2], dtype=np.int64),
        }
    )
    ctable(tables[0].rootdir, mode="a").append_dataframe(extra)
    grown = [ctable(t.rootdir) for t in tables]
    after = ex.execute(grown, query)
    stats1 = ex.workingset.stats()
    assert stats1["align"]["misses"] == stats0["align"]["misses"] + 1
    assert stats1["codes"]["misses"] == stats0["codes"]["misses"] + 1
    # the appended rows are IN the answer (no stale decode anywhere)
    def total(payload):
        return dict(
            zip(
                payload["keys"]["g"].tolist(),
                payload["aggs"][0]["sum"].tolist(),
            )
        )
    t0, t1 = total(before), total(after)
    assert t1[0] == t0[0] + 10_000_000
    assert t1[1] == t0[1] - 10_000_000


def test_column_set_change_misses(ws_tables, monkeypatch):
    """A different groupby column set is a different content key: align and
    codes must miss (and factorize the new key column)."""
    _frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    ex.execute(tables, GroupByQuery(["g"], [["v", "sum", "s"]]))
    stats0 = ex.workingset.stats()
    ex.execute(tables, GroupByQuery(["h"], [["v", "sum", "s"]]))
    stats1 = ex.workingset.stats()
    assert stats1["align"]["misses"] == stats0["align"]["misses"] + 1
    assert stats1["codes"]["misses"] == stats0["codes"]["misses"] + 1
    assert stats1["align"]["entries"] == 2


def test_eviction_under_pressure_forces_miss(ws_tables):
    """The HBM watermark policy sheds device segments (blocks before
    codes); the next query misses and rebuilds, and the shed is counted."""
    frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    query = GroupByQuery(["g"], [["v", "sum", "s"]])
    ex.execute(tables, query)
    assert len(ex._codes_cache) == 1 and len(ex._hbm_cache) == 1

    # target far above the cached bytes: everything device-side must go
    freed = ex.workingset.evict_under_pressure(
        sample={"bytes_in_use": 2 * 10**12, "bytes_limit": 10**12},
        watermark=0.5,
    )
    assert freed > 0
    assert ex.workingset.pressure_evictions >= 2
    assert len(ex._codes_cache) == 0 and len(ex._hbm_cache) == 0
    assert len(ex._align_cache) == 1, "host alignment is not device memory"

    stats0 = ex.workingset.stats()
    r = ex.execute(tables, query)  # rebuilds from the warm alignment
    stats1 = ex.workingset.stats()
    assert stats1["codes"]["misses"] == stats0["codes"]["misses"] + 1
    full = pd.concat(frames, ignore_index=True)
    expect = full.groupby("g")["v"].sum().sort_index().to_numpy()
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_array_equal(r["aggs"][0]["sum"][order], expect)


def test_pressure_eviction_noops_without_sample():
    from bqueryd_tpu.ops.workingset import WorkingSet

    ws = WorkingSet()
    assert ws.evict_under_pressure(sample=None, watermark=0.9) == 0
    assert ws.evict_under_pressure(
        sample={"bytes_in_use": 10, "bytes_limit": 100}, watermark=0.9
    ) == 0  # under the watermark
    assert ws.evict_under_pressure(
        sample={"bytes_in_use": 99, "bytes_limit": 100}, watermark=0
    ) == 0  # disabled


def test_fused_multiagg_uploads_one_block(ws_tables):
    """sum+count+mean over ONE column must upload ONE measure block (the
    fused gather: measure_index maps all three aggs to the same slot) and
    still match pandas."""
    frames, tables = ws_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    r = ex.execute(
        tables,
        GroupByQuery(
            ["g"],
            [["v", "sum", "s"], ["v", "count", "n"], ["v", "mean", "m"]],
        ),
    )
    assert ex.workingset.stats()["blocks"]["entries"] == 1
    full = pd.concat(frames, ignore_index=True)
    g = full.groupby("g")["v"]
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_array_equal(
        r["aggs"][0]["sum"][order], g.sum().sort_index().to_numpy()
    )
    np.testing.assert_array_equal(
        r["aggs"][1]["count"][order], g.count().sort_index().to_numpy()
    )
    np.testing.assert_allclose(
        r["aggs"][2]["sum"][order] / r["aggs"][2]["count"][order],
        g.mean().sort_index().to_numpy(),
        rtol=1e-12,
    )


def test_cold_align_prefetch_warms_build_decode(ws_tables, monkeypatch):
    """A fully COLD query (alignment + storage caches empty) must still fire
    the measure-column prefetch — deferred until the align fan-out releases
    the pool — and the prefetched chunks must land under the SAME content
    key the depth-2 column build probes: the build path then HITS instead of
    re-decoding (the 0.115 cold storage-decode hit rate)."""
    from bqueryd_tpu.storage.ctable import column_cache_stats, free_cachemem

    frames, tables = ws_tables
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "4")
    free_cachemem()
    ex = MeshQueryExecutor(mesh=make_mesh())
    s0 = column_cache_stats()
    r = ex.execute(tables, GroupByQuery(["g"], [["v", "sum", "s"]]))
    s1 = column_cache_stats()
    # the measure column was decoded once per shard by the prefetch (cache
    # misses) and then HIT by the build loop — a cold query on N shards
    # must therefore record >= N hits, where the un-prefetched cold path
    # recorded zero
    assert s1["hits"] - s0["hits"] >= len(tables), (
        "cold-path prefetch did not warm the content keys the build probes"
    )
    full = pd.concat(frames, ignore_index=True)
    expect = full.groupby("g")["v"].sum().sort_index().to_numpy()
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_array_equal(r["aggs"][0]["sum"][order], expect)


def test_storage_prefetch_warms_decode_cache(ws_tables, monkeypatch):
    """ctable.prefetch decodes on the pipeline pool into the process cache;
    the subsequent column_raw is a cache hit (same array object)."""
    from bqueryd_tpu.storage.ctable import free_cachemem

    _frames, tables = ws_tables
    monkeypatch.setenv("BQUERYD_TPU_PIPELINE_THREADS", "2")
    free_cachemem()
    futs = tables[0].prefetch(["v", "missing_column"])
    assert len(futs) == 1, "unknown columns are skipped, not errors"
    decoded = futs[0].result()
    assert tables[0].column_raw("v") is decoded, "prefetch must warm the cache"
