"""Measured-cost kernel-strategy calibration (plan.calibrate).

The contract under test, in the ISSUE's terms:

* COLD START — a store with no samples makes every decision bit-identical
  to the PR-5 heuristic (`select_for_group`), and `BQUERYD_TPU_CALIB=0`
  restores that behaviour even against a warm (or poisoned) store;
* MEASUREMENT — warm cells rank the legal candidates; a measured-best
  matmul is promoted to the binding-inside-guards `matmul!` form, which
  `ops.partial_tables` honours ONLY when the backend guard and the
  groups/cells value guards pass (the forced-matmul regression stays
  unreachable through any hint);
* PERSISTENCE & GOSSIP — save/load round-trips, WRM summaries absorb
  n-weighted into the controller's model, and malformed gossip is dropped
  cell by cell;
* FEEDBACK — the mesh executor and the engine record effective-route
  kernel walls into the process store and report `effective_strategy`.
"""

import logging
import os
import pickle
import time

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.plan import calibrate
from bqueryd_tpu.plan.strategy import (
    STRATEGY_MATMUL_BINDING,
    candidate_strategies,
    choose_strategy,
    select_calibrated,
    select_for_group,
)


def shard_stats(rows, cards, lo=0, hi=100):
    return {
        "rows": rows,
        "cols": {
            col: {"kind": "numeric", "min": lo, "max": hi, "card": card}
            for col, card in cards.items()
        },
    }


def warm(store, strategy, wall_s, rows=10_000_000, groups=9, dtype="int",
         backend="cpu", n=None):
    for _ in range(n if n is not None else calibrate.min_samples()):
        store.record(rows, groups, dtype, backend, strategy, wall_s)


# -- cold start ---------------------------------------------------------------

def test_cold_start_is_bit_identical_to_heuristic():
    store = calibrate.CalibrationStore()
    cases = [
        ({"a": shard_stats(10_000_000, {"k": 9})}, ["a"], ["k"]),
        ({"a": shard_stats(10_000_000, {"k": 70_000})}, ["a"], ["k"]),
        ({"a": shard_stats(10_000_000, {"k": 1_000_000})}, ["a"], ["k"]),
        ({"a": shard_stats(0, {"k": 5})}, ["a"], ["k"]),
        ({}, ["missing"], ["k"]),
    ]
    for stats, files, cols in cases:
        heuristic = select_for_group(stats, files, cols)
        calibrated = select_calibrated(stats, files, cols, calibration=store)
        assert calibrated[:3] == heuristic
        assert calibrated[3] == "cold"


def test_choose_cold_bucket_never_explores(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_CALIB_EPSILON", "1.0")
    store = calibrate.CalibrationStore()
    for _ in range(50):
        choice, reason = store.choose(
            10_000_000, 9, None, ("matmul", "scatter", "sort"), "matmul"
        )
        assert (choice, reason) == ("matmul", "cold")


def test_kill_switch_restores_heuristic_exactly(monkeypatch):
    store = calibrate.CalibrationStore()
    # poisoned model: scatter "measured" as 100x faster than anything
    warm(store, "scatter", 0.001)
    warm(store, "matmul", 1.0)
    stats = {"a": shard_stats(10_000_000, {"k": 9})}
    with_calib = select_calibrated(stats, ["a"], ["k"], calibration=store)
    assert with_calib[0] == "scatter"  # calibration is live before the flip
    monkeypatch.setenv("BQUERYD_TPU_CALIB", "0")
    killed = select_calibrated(stats, ["a"], ["k"], calibration=store)
    assert killed[:3] == select_for_group(stats, ["a"], ["k"])
    assert killed[3] == "cold"
    # recording and gossip shut off with the same switch
    store.record(10_000_000, 9, "int", "cpu", "scatter", 0.5)
    assert store.stats()["samples_total"] == 2 * calibrate.min_samples()
    assert calibrate.summary_for_wire() is None


# -- measured decisions -------------------------------------------------------

def test_measured_override_and_promotion():
    store = calibrate.CalibrationStore()
    stats = {"a": shard_stats(10_000_000, {"k": 9})}
    # heuristic says matmul at 9 groups; measurement says scatter wins
    warm(store, "scatter", 0.01)
    warm(store, "matmul", 0.10)
    strat, est, rows, reason = select_calibrated(
        stats, ["a"], ["k"], calibration=store
    )
    assert (strat, reason) == ("scatter", "measured")
    # ...and the other way around: measured-best matmul becomes BINDING
    store2 = calibrate.CalibrationStore()
    warm(store2, "scatter", 0.10)
    warm(store2, "matmul", 0.01)
    strat2, _est, _rows, reason2 = select_calibrated(
        stats, ["a"], ["k"], calibration=store2
    )
    assert strat2 == STRATEGY_MATMUL_BINDING
    assert reason2 in ("measured", "agree")


def test_agree_keeps_heuristic_within_hysteresis():
    store = calibrate.CalibrationStore()
    # scatter nominally faster, but within the 10% hysteresis band
    warm(store, "matmul", 0.100)
    warm(store, "scatter", 0.095)
    choice, reason = store.choose(
        10_000_000, 9, None, ("matmul", "scatter", "sort"), "matmul"
    )
    assert (choice, reason) == ("matmul", "agree")


def test_candidates_exclude_matmul_past_guards():
    assert "matmul" not in candidate_strategies(10_000_000, 70_000)
    assert "matmul" in candidate_strategies(10_000_000, 9)
    # the cells budget guard: rows x groups beyond 2^36
    assert "matmul" not in candidate_strategies(1 << 33, 8192)


def test_promotion_never_offered_outside_guards():
    """Even a poisoned store claiming matmul is instant cannot promote past
    the value guards: matmul is not a CANDIDATE there."""
    store = calibrate.CalibrationStore()
    warm(store, "matmul", 0.000001, groups=70_000)
    warm(store, "scatter", 10.0, groups=70_000)
    stats = {"a": shard_stats(10_000_000, {"k": 70_000})}
    strat, _est, _rows, _reason = select_calibrated(
        stats, ["a"], ["k"], calibration=store
    )
    assert strat in ("scatter", "sort")


def test_unmeasured_candidate_scored_by_analytic_prior():
    """sort is unmeasured; its analytic units at extreme cardinality are
    far below scatter's blocks x groups table, so the learned
    seconds-per-unit scale must rank it first."""
    store = calibrate.CalibrationStore()
    rows, groups = 10_000_000, 2_000_000
    warm(store, "scatter", 5.0, rows=rows, groups=groups)
    choice, reason = store.choose(
        rows, groups, None, ("scatter", "sort"), "scatter"
    )
    # prior-extrapolated winner: advisory-strength evidence only
    assert (choice, reason) == ("sort", "prior")


def test_prior_extrapolation_never_promotes_matmul():
    """A bucket with only scatter walls where the analytic prior ranks the
    (unmeasured) matmul cheaper must yield the ADVISORY matmul hint — the
    binding promotion requires real matmul measurements."""
    store = calibrate.CalibrationStore()
    rows, groups = 1_000_000, 4  # matmul units rows*4 << scatter rows*8
    warm(store, "scatter", 0.5, rows=rows, groups=groups)
    choice, reason = store.choose(
        rows, groups, None, ("matmul", "scatter", "sort"), "matmul"
    )
    assert (choice, reason) == ("matmul", "prior")
    stats = {"a": shard_stats(rows, {"k": groups})}
    strat, _e, _r, sreason = select_calibrated(
        stats, ["a"], ["k"], calibration=store
    )
    assert strat == "matmul"          # advisory, NOT "matmul!"
    assert sreason == "prior"


def test_binding_promotion_never_rides_the_wire():
    """Mixed-version safety: fragments ship the advisory 'matmul' plus a
    strategy_binding flag old workers ignore — never the 'matmul!' literal
    their KERNEL_STRATEGIES validation would reject."""
    from bqueryd_tpu.plan import fragment_for, plan_groupby

    plan = plan_groupby(["a.bcolzs"], ["k"], [["v", "sum", "v"]], [])
    fragment = fragment_for(plan, ["a.bcolzs"], strategy="matmul!")
    assert fragment["strategy"] == "matmul"
    assert fragment["strategy_binding"] is True
    advisory = fragment_for(plan, ["a.bcolzs"], strategy="matmul")
    assert advisory["strategy"] == "matmul"
    assert advisory["strategy_binding"] is False


def test_exploration_is_bounded_deterministic_and_advisory(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_CALIB_EPSILON", "0.5")
    store = calibrate.CalibrationStore()
    warm(store, "matmul", 0.01)  # heuristic route measured; others not
    stats = {"a": shard_stats(10_000_000, {"k": 9})}
    seen = []
    for _ in range(8):
        strat, _e, _r, reason = select_calibrated(
            stats, ["a"], ["k"], calibration=store
        )
        seen.append((strat, reason))
        assert strat != STRATEGY_MATMUL_BINDING or reason != "explore"
    explored = [s for s, r in seen if r == "explore"]
    assert explored, "eps=0.5 must explore within 8 warm decisions"
    assert len(explored) == 4  # deterministic every-2nd slot, not random
    assert set(explored) <= {"scatter", "sort"}
    monkeypatch.setenv("BQUERYD_TPU_CALIB_EPSILON", "0")
    post = [
        select_calibrated(stats, ["a"], ["k"], calibration=store)[3]
        for _ in range(4)
    ]
    assert "explore" not in post


# -- persistence & gossip -----------------------------------------------------

def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "calib.json")
    store = calibrate.CalibrationStore(path=path)
    warm(store, "scatter", 0.02, n=7)
    warm(store, "matmul", 0.01, n=4)
    assert store.save()
    reloaded = calibrate.CalibrationStore(path=path)
    assert reloaded.load() == 2
    assert reloaded.summary()["cells"] == store.summary()["cells"]
    # and the reloaded model decides like the original
    assert reloaded.choose(
        10_000_000, 9, "int", ("matmul", "scatter", "sort"), "scatter"
    )[0] == "matmul"


def test_load_missing_or_corrupt_is_cold(tmp_path):
    store = calibrate.CalibrationStore(path=str(tmp_path / "absent.json"))
    assert store.load() == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert calibrate.CalibrationStore(path=str(bad)).load() == 0


def test_absorb_merges_and_drops_garbage():
    a = calibrate.CalibrationStore()
    warm(a, "scatter", 0.04, n=5)
    wire = a.summary()
    # vandalize: malformed keys/cells must be dropped one by one
    wire["cells"]["not-a-key"] = {"n": 3, "ewma_s": 0.1}
    wire["cells"]["r23|g3|int|cpu|matmul"] = {"n": "nan", "ewma_s": "x"}
    wire["cells"]["r23|g3|int|cpu|sort"] = {"n": 2, "ewma_s": -1.0}
    b = calibrate.CalibrationStore()
    assert b.absorb(wire) == 1
    assert b.absorb("nonsense") == 0
    assert b.absorb({"cells": 7}) == 0
    merged = b.summary()["cells"]
    assert list(merged) == list(a.summary()["cells"])
    # n-weighted re-absorb accumulates counts (capped)
    assert b.absorb(wire) == 1
    (cell,) = b.summary()["cells"].values()
    assert cell["n"] == 10


def test_worker_summary_rides_the_wrm(monkeypatch):
    calibrate._reset_for_tests()
    assert calibrate.summary_for_wire() is None  # cold worker advertises nothing
    calibrate.record_sample(
        1_000_000, 16, [np.dtype(np.int64)], "cpu", "scatter", 0.02
    )
    wire = calibrate.summary_for_wire()
    assert wire and "r19|g4|int|cpu|scatter" in wire["cells"]


def test_controller_absorbs_calibration_gossip(tmp_path):
    from bqueryd_tpu.controller import ControllerNode

    node = ControllerNode(
        coordination_url=f"mem://calib-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
    )
    try:
        peer = calibrate.CalibrationStore()
        peer.record(10_000_000, 9, "int", "cpu", "scatter", 0.03)  # ONE wall
        wrm = {"worker_id": "w1", "calibration": peer.summary()}
        node._absorb_shard_stats(wrm)
        assert node.calibration.stats()["cells"] == 1
        # heartbeat re-gossip of the same cumulative summary must NOT
        # double-count: one measured wall stays one sample however many
        # WRMs repeat it, so it can never clear the min-samples floor by
        # repetition alone
        for _ in range(calibrate.min_samples() + 2):
            node._absorb_shard_stats(wrm)
        choice, reason = node.calibration.choose(
            10_000_000, 9, None, ("matmul", "scatter", "sort"), "matmul"
        )
        assert (choice, reason) == ("matmul", "cold")
        # malformed gossip is inert
        node._absorb_shard_stats({"worker_id": "w2", "calibration": "junk"})
        node._absorb_shard_stats(
            {"worker_id": "w2", "calibration": {"cells": ["x"]}}
        )
        assert node.calibration.stats()["cells"] == 1
        # two DISTINCT workers' samples do merge n-weighted
        peer2 = calibrate.CalibrationStore()
        warm(peer2, "scatter", 0.03, n=5)
        node._absorb_shard_stats(
            {"worker_id": "w2", "calibration": peer2.summary()}
        )
        assert node.calibration.stats()["sources"] == 2
        choice, reason = node.calibration.choose(
            10_000_000, 9, None, ("matmul", "scatter", "sort"), "matmul"
        )
        assert reason in ("measured", "prior")  # floor now genuinely met
    finally:
        node.socket.close()


# -- kernel guards under the binding hint ------------------------------------

@pytest.fixture
def mm_counter(monkeypatch):
    """Counts dispatches into the MXU path without changing results."""
    from bqueryd_tpu.ops import groupby as gb

    calls = {"n": 0}
    real = gb._partial_tables_mm

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(gb, "_partial_tables_mm", counting)
    return calls


def _run_partials(strategy, n=4096, groups=9, op="min"):
    from bqueryd_tpu import ops

    rng = np.random.default_rng(3)
    codes = rng.integers(0, groups, n).astype(np.int32)
    values = rng.integers(-50, 50, n).astype(np.int64)
    import jax

    return jax.device_get(
        ops.partial_tables(codes, (values,), (op,), groups,
                           strategy=strategy)
    )


def test_binding_matmul_bypasses_only_profitability(mm_counter):
    """A min-only query fails the op/dtype profitability heuristic (min
    scatters regardless), so auto and advisory 'matmul' both scatter —
    while 'matmul!' takes the MXU path, bit-identically."""
    auto = _run_partials(None)
    assert mm_counter["n"] == 0
    advisory = _run_partials("matmul")
    assert mm_counter["n"] == 0  # advisory == auto, by definition
    bound = _run_partials("matmul!")
    assert mm_counter["n"] == 1
    for a, b in zip(
        (auto["rows"], *auto["aggs"][0].values()),
        (bound["rows"], *bound["aggs"][0].values()),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(auto["aggs"][0]["min"]),
        np.asarray(advisory["aggs"][0]["min"]),
    )


def test_binding_matmul_demotes_past_group_ceiling(mm_counter, monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", "8")
    _run_partials("matmul!", groups=9)
    assert mm_counter["n"] == 0  # value guard stands under promotion


def test_binding_matmul_demotes_on_cpu_backend(mm_counter, monkeypatch):
    monkeypatch.delenv("BQUERYD_TPU_FORCE_MATMUL", raising=False)
    bound = _run_partials("matmul!", op="sum")
    assert mm_counter["n"] == 0  # backend guard stands under promotion
    ref = _run_partials("scatter", op="sum")
    np.testing.assert_array_equal(
        np.asarray(bound["aggs"][0]["sum"]),
        np.asarray(ref["aggs"][0]["sum"]),
    )


def test_kernel_route_predictions(monkeypatch):
    from bqueryd_tpu import ops

    ints = [np.zeros(8, np.int64)]
    assert ops.kernel_route("scatter", ints, ("sum",), 10_000, 9) == "scatter"
    assert ops.kernel_route("sort", ints, ("sum",), 10_000, 9) == "sort"
    assert ops.kernel_route(None, ints, ("sum",), 10_000, 9) == "matmul"
    assert ops.kernel_route(None, ints, ("min",), 10_000, 9) == "scatter"
    assert ops.kernel_route("matmul!", ints, ("min",), 10_000, 9) == "matmul"
    # past the blocks x groups budget the adaptive scatter sorts
    assert ops.kernel_route(
        None, ints, ("sum",), 10_000_000, 1_000_000
    ) == "sort"
    monkeypatch.delenv("BQUERYD_TPU_FORCE_MATMUL", raising=False)
    assert ops.kernel_route(
        "matmul!", ints, ("sum",), 10_000, 9
    ) == "scatter"  # backend guard


# -- feedback: executor + engine record and report ---------------------------

def taxi_like_df(n=9_000, seed=11):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "k": rng.integers(1, 7, n).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64),
        }
    )


@pytest.fixture
def shard_tables(tmp_path):
    from bqueryd_tpu.storage import ctable

    df = taxi_like_df()
    tables = []
    for i, part in enumerate(np.array_split(df, 3)):
        root = str(tmp_path / f"t{i}.bcolzs")
        ctable.fromdataframe(part.reset_index(drop=True), root)
        tables.append(ctable(root, mode="r"))
    return tables


def test_mesh_executor_reports_route_and_records_samples(shard_tables):
    from bqueryd_tpu.models.query import GroupByQuery
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor, make_mesh

    store = calibrate._reset_for_tests()
    executor = MeshQueryExecutor(mesh=make_mesh())
    query = GroupByQuery(["k"], [["v", "sum", "v"]])
    executor.execute(shard_tables, query)   # may compile: sample skipped
    executor.execute(shard_tables, query)   # warm: sample recorded
    assert executor.last_effective_strategy == "matmul"  # FORCE_MATMUL=1
    stats = store.stats()
    assert stats["samples_total"] >= 1
    key = calibrate.cell_key(
        calibrate.rows_bucket(sum(t.nrows for t in shard_tables)),
        calibrate.groups_bucket(6), "int", "cpu", "matmul",
    )
    assert key in store.summary(max_cells=512)["cells"]


def test_engine_reports_route(shard_tables):
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine

    engine = QueryEngine()
    query = GroupByQuery(["k"], [["v", "sum", "v"]])
    engine.execute_local(shard_tables[0], query)
    assert engine.last_effective_strategy == "matmul"
    engine.execute_local(shard_tables[0], query, strategy="host")
    assert engine.last_effective_strategy == "host"
    engine.execute_local(shard_tables[0], query, strategy="scatter")
    assert engine.last_effective_strategy == "scatter"


def test_effective_strategy_reaches_the_client_envelope(tmp_path):
    """Controller folds the workers' effective_strategy replies into the
    result envelope's `strategies` key (RESULT_ENVELOPE_SCHEMA)."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import CalcMessage, RPCMessage

    node = ControllerNode(
        coordination_url=f"mem://calib-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
    )
    replies = []
    node.reply_rpc_raw = lambda token, payload: replies.append(payload)
    try:
        node.worker_map["w1"] = {
            "worker_id": "w1", "workertype": "calc", "busy": False,
            "last_seen": time.time(), "node": node.node_name,
        }
        node.files_map["a.bcolzs"] = {"w1"}
        msg = RPCMessage({"payload": "groupby", "token": "00"})
        msg.set_args_kwargs(
            [["a.bcolzs"], ["k"], [["v", "sum", "v"]], []], {}
        )
        node.rpc_groupby(msg)
        (shard,) = [m for q in node.worker_out_messages.values() for m in q]
        reply = CalcMessage(dict(shard))
        reply["data"] = b"payload"
        reply["effective_strategy"] = "scatter"
        node.process_worker_result(reply)
        (payload,) = replies
        envelope = pickle.loads(payload)
        assert envelope["ok"]
        assert envelope["strategies"]["effective"] == {
            "a.bcolzs": "scatter"
        }
        assert "hints" in envelope["strategies"]
    finally:
        node.socket.close()
