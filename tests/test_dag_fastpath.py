"""DAG fast path (PR 15): batched mesh dispatch + device-resident merge.

Covers the acceptance criteria:

* device-vs-host merge parity of the extended part kinds across the fuzz
  surface — top-k largest/smallest x int/float/datetime-NaT ties, sketch
  zero/negative/clamp buckets, mixed classic+extended agg lists — ints,
  top-k multisets and sketch BUCKETS bit-identical, floats within
  reassociation ulps;
* the working-set sharing contract (join-probe gathers, window-bucket
  derived keys, folded composite codes content-keyed: a different-measure
  repeat skips the whole derivation);
* fallback routing: count_distinct / raw rows / over-budget sketch grids
  raise DagFastPathUnsupported (the worker then serves via the PR-13
  per-shard pipeline), query-shape validation errors raise identically on
  both routes;
* the BQUERYD_TPU_DAG_BATCH kill switch: batch gating at the plan layer
  and cluster-level bit-identity vs the per-shard PR-13 path.
"""

import logging
import threading

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import QueryEngine
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.parallel.executor import (
    DagFastPathUnsupported,
    MeshQueryExecutor,
)
from bqueryd_tpu.parallel.opexec import DagExecutor
from bqueryd_tpu.plan import dag as dagmod
from bqueryd_tpu.storage.ctable import ctable

from conftest import wait_until

N_SHARDS = 3
ROWS = 2_500
ALPHA = 0.01


def _dataset(seed=515):
    rng = np.random.default_rng(seed)
    frames = []
    for _ in range(N_SHARDS):
        n = ROWS
        ts = pd.to_datetime(
            rng.integers(1_400_000_000, 1_400_050_000, n), unit="s"
        ).to_series().reset_index(drop=True)
        ts[pd.Series(rng.random(n) < 0.07)] = pd.NaT
        frames.append(
            pd.DataFrame(
                {
                    "g": rng.integers(0, 6, n).astype(np.int64),
                    "cust": rng.integers(0, 40, n).astype(np.int64),
                    "k_str": rng.choice(["a", "b", "c"], n),
                    "t": ts.to_numpy(),
                    "v_int": rng.integers(-8, 8, n).astype(np.int64),
                    "v_big": rng.integers(-(2**50), 2**50, n),
                    "u64": rng.integers(0, 2**63, n).astype(np.uint64),
                    "v_float": np.where(
                        rng.random(n) < 0.08,
                        np.nan,
                        rng.random(n) * 200 - 100,
                    ),
                    # zero / negative / past-the-clamp magnitudes: the
                    # sketch's zero bucket, sign handling, and both clamp
                    # edges all get populated
                    "v_ext": np.where(
                        rng.random(n) < 0.2,
                        0.0,
                        np.where(
                            rng.random(n) < 0.5,
                            -rng.random(n) * 1e16,
                            rng.random(n) * 1e-14,
                        ),
                    ),
                }
            )
        )
    return frames


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    frames = _dataset()
    root = tmp_path_factory.mktemp("dagfast")
    tables = []
    for i, df in enumerate(frames):
        p = str(root / f"fp_{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))
    return frames, tables


def _dim():
    cust = np.arange(30, dtype=np.int64)
    return {
        "cust": cust,
        "region": np.array(["r%d" % (c % 4) for c in cust], dtype=object),
        "weight": (cust % 7).astype(np.int64),
    }


def _slow(tables, dag):
    """The PR-13 per-shard route (what BQUERYD_TPU_DAG_BATCH=0 restores)."""
    executor = DagExecutor(QueryEngine())
    payloads = [executor.execute_shard(t, dag) for t in tables]
    return hostmerge.merge_payloads(payloads)


def _fast(tables, dag, mex=None):
    mex = mex or MeshQueryExecutor()
    return dict(mex.execute_dag(tables, dag))


def _frames(payload_a, payload_b, sort_cols):
    a = hostmerge.payload_to_dataframe(payload_a)
    b = hostmerge.payload_to_dataframe(payload_b)
    return (
        a.sort_values(sort_cols).reset_index(drop=True),
        b.sort_values(sort_cols).reset_index(drop=True),
    )


# ---------------------------------------------------------------------------
# merge parity: device fast path vs the per-shard host route
# ---------------------------------------------------------------------------

def test_mixed_classic_and_extended_with_join_and_window(shards):
    """The full pipeline in one query — join + window + pushdown + post
    filter + classic + top-k + sketch: ints bit-exact, floats within
    reassociation, top-k lists identical, sketch estimates bit-equal."""
    _frames_src, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"],
        "groupby": ["g", {"window": {"on": "t", "every": "1h",
                                     "alias": "hr"}}],
        "aggs": [
            ["v_int", "sum", "s"],
            ["v_int", "min", "mn"],
            ["v_float", "mean", "m"],
            ["weight", "max", "wmax"],
            ["v_int", "topk", "t3", {"k": 3}],
            ["v_float", "quantile", "p50", {"q": 0.5, "alpha": ALPHA}],
        ],
        "where": [["v_int", ">", -7], ["weight", "<=", 5]],
        "join": {"table": _dim(), "on": "cust",
                 "select": ["region", "weight"]},
    })
    mex = MeshQueryExecutor()
    fast = _fast(tables, dag, mex)
    assert mex.last_merge_mode == "device"
    a, b = _frames(fast, _slow(tables, dag), ["g", "hr"])
    assert len(a) == len(b) and len(a) > 0
    for col in ("g", "hr", "s", "mn", "wmax"):
        assert a[col].tolist() == b[col].tolist(), col
    np.testing.assert_allclose(
        a["m"].to_numpy(), b["m"].to_numpy(), rtol=1e-12
    )
    for x, y in zip(a["t3"], b["t3"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    np.testing.assert_array_equal(
        a["p50"].to_numpy(), b["p50"].to_numpy()
    )


@pytest.mark.parametrize("col,largest", [
    ("v_int", True),      # heavy ties: multiset semantics
    ("v_int", False),
    ("v_big", True),
    ("v_float", False),   # NaN skipping + float sort key
    ("t", True),          # datetime: NaT sentinel + int64 bitwise-not sort
])
def test_topk_parity_matrix(shards, col, largest):
    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [[col, "topk", "tk", {"k": 5, "largest": largest}]],
    })
    a, b = _frames(_fast(tables, dag), _slow(tables, dag), ["g"])
    assert a["g"].tolist() == b["g"].tolist()
    for x, y in zip(a["tk"], b["tk"]):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype
        np.testing.assert_array_equal(xa, ya)


def test_sketch_buckets_bit_equal_including_clamps(shards):
    """The device-merged grid converts to EXACTLY the flat sketch part the
    host merge produces — zero bucket, negative keys, and both clamp
    edges included — so estimates are bit-equal, not just within alpha."""
    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [
            ["v_ext", "quantile", "q1", {"q": 0.1, "alpha": 0.02}],
            ["v_ext", "quantile", "q9", {"q": 0.9, "alpha": 0.02}],
        ],
    })
    fast, slow = _fast(tables, dag), _slow(tables, dag)
    # align groups by key value, then compare the flat sketch parts
    fast_order = np.argsort(np.asarray(fast["keys"]["g"]))
    slow_order = np.argsort(np.asarray(slow["keys"]["g"]))
    for ai in range(2):
        fa, sa = fast["aggs"][ai], slow["aggs"][ai]
        fo = np.asarray(fa["sketch_offsets"])
        so = np.asarray(sa["sketch_offsets"])
        for gf, gs in zip(fast_order, slow_order):
            np.testing.assert_array_equal(
                np.asarray(fa["sketch_keys"])[fo[gf]:fo[gf + 1]],
                np.asarray(sa["sketch_keys"])[so[gs]:so[gs + 1]],
            )
            np.testing.assert_array_equal(
                np.asarray(fa["sketch_counts"])[fo[gf]:fo[gf + 1]],
                np.asarray(sa["sketch_counts"])[so[gs]:so[gs + 1]],
            )
    a, b = _frames(fast, slow, ["g"])
    np.testing.assert_array_equal(a["q1"].to_numpy(), b["q1"].to_numpy())
    np.testing.assert_array_equal(a["q9"].to_numpy(), b["q9"].to_numpy())


def test_uint64_and_string_keys_parity(shards):
    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["k_str"],
        "aggs": [
            ["u64", "sum", "us"],
            ["u64", "max", "umax"],
            ["v_int", "topk", "tk", {"k": 2}],
        ],
    })
    a, b = _frames(_fast(tables, dag), _slow(tables, dag), ["k_str"])
    assert a["k_str"].tolist() == b["k_str"].tolist()
    assert a["us"].tolist() == b["us"].tolist()
    assert a["us"].dtype == b["us"].dtype  # mod-2^64 unsigned view kept
    assert a["umax"].tolist() == b["umax"].tolist()
    for x, y in zip(a["tk"], b["tk"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_topk_emission_routes_agree_directly():
    """All three dense emissions — matrix-argmax, segment k-pass, lexsort —
    produce the same flat partials as the numpy host twin (the k-pass and
    lexsort routes are only reachable via routing at >4096 groups / k >
    TOPK_KPASS_MAX_K, so they get direct coverage here)."""
    import jax
    import jax.numpy as jnp

    from bqueryd_tpu.ops import relops
    from bqueryd_tpu.parallel import opexec

    rng = np.random.default_rng(9)
    n, G, k = 3000, 7, 4
    codes = rng.integers(-1, G, n)
    for vals, drop_nan, float_neg in (
        (rng.integers(-5, 5, n).astype(np.int64), False, False),
        (np.where(rng.random(n) < 0.1, np.nan, rng.random(n)), True, True),
    ):
        for largest in (True, False):
            expected = opexec.topk_flat(codes, vals, k, largest, G)
            for emit in (
                relops.topk_matrix_block,
                relops.topk_kpass_block,
            ):
                dense, cnt = jax.device_get(
                    emit(
                        jnp.asarray(codes), jnp.asarray(vals), None,
                        k, largest, G, drop_nan, None,
                    )
                )
                got = opexec.dense_topk_to_flat(
                    np.asarray(dense), np.asarray(cnt)
                )
                np.testing.assert_array_equal(expected[1], got[1])
                np.testing.assert_array_equal(expected[0], got[0])
            dense, cnt = jax.device_get(
                relops.topk_dense_block(
                    jnp.asarray(codes), jnp.asarray(vals), None,
                    k, largest, G, drop_nan, None, float_neg,
                )
            )
            got = opexec.dense_topk_to_flat(
                np.asarray(dense), np.asarray(cnt)
            )
            np.testing.assert_array_equal(expected[1], got[1])
            np.testing.assert_array_equal(expected[0], got[0])


def test_topk_kpass_and_sort_routes_agree(shards):
    """The k-pass segment route (k <= TOPK_KPASS_MAX_K) and the lexsort
    route emit identical flat partials — both against each other (k
    straddling the crossover) and against the numpy host twin."""
    from bqueryd_tpu.ops import relops
    from bqueryd_tpu.parallel import opexec

    frames, _tables = shards
    rng = np.random.default_rng(3)
    codes = rng.integers(-1, 5, 4000)
    for col_vals in (
        rng.integers(-6, 6, 4000).astype(np.int64),        # ties
        np.where(rng.random(4000) < 0.1, np.nan, rng.random(4000)),
    ):
        for largest in (True, False):
            for k in (3, relops.TOPK_KPASS_MAX_K + 8):  # both routes
                host = opexec.topk_flat(
                    codes, col_vals, k, largest, 5
                )
                dev = relops.topk_partials(
                    codes, col_vals, k, largest, 5
                )
                np.testing.assert_array_equal(host[1], dev[1])
                np.testing.assert_array_equal(host[0], dev[0])


# ---------------------------------------------------------------------------
# the shared decode/align/H2D pass (working-set contract)
# ---------------------------------------------------------------------------

def test_different_measures_share_derivations(shards):
    """A second DAG query with DIFFERENT aggs over the same derivation
    pipeline (same join/window/filter/keys) hits the cached alignment and
    folded codes — the decode/align/H2D pass is shared, like folded group
    codes always were for classic queries."""
    _f, tables = shards
    mex = MeshQueryExecutor()
    base = {
        "table": ["x"],
        "groupby": ["g", {"window": {"on": "t", "every": "1h",
                                     "alias": "hr"}}],
        "where": [["v_int", ">", -7]],
        "join": {"table": _dim(), "on": "cust", "select": ["region"]},
    }
    _fast(tables, dagmod.compile_query(
        {**base, "aggs": [["v_int", "sum", "s"]]}
    ), mex)
    align_hits = mex.workingset.stats()["align"]["hits"]
    codes_hits = mex.workingset.stats()["codes"]["hits"]
    _fast(tables, dagmod.compile_query(
        {**base, "aggs": [["v_float", "mean", "m"],
                          ["v_float", "quantile", "p9", {"q": 0.9}]]}
    ), mex)
    stats = mex.workingset.stats()
    assert stats["align"]["hits"] > align_hits
    assert stats["codes"]["hits"] > codes_hits


# ---------------------------------------------------------------------------
# fallback routing + kill switch
# ---------------------------------------------------------------------------

def test_count_distinct_and_raw_rows_not_batchable():
    cd = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v", "count_distinct", "cd"]],
    })
    assert not dagmod.dag_batchable(cd)
    _plan, kw = dagmod.groupby_equivalent(cd)
    assert kw["batch"] is False
    ext = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v", "topk", "t", {"k": 2}]],
    })
    assert dagmod.dag_batchable(ext)
    _plan, kw = dagmod.groupby_equivalent(ext)
    assert kw["batch"] is True


def test_dag_batch_env_kill_switch(monkeypatch):
    ext = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v", "quantile", "q", {"q": 0.5}]],
    })
    monkeypatch.setenv("BQUERYD_TPU_DAG_BATCH", "0")
    assert not dagmod.dag_batchable(ext)
    _plan, kw = dagmod.groupby_equivalent(ext)
    assert kw["batch"] is False


def test_count_distinct_dag_raises_fast_path_unsupported(shards):
    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v_int", "count_distinct", "cd"],
                 ["v_int", "topk", "t", {"k": 2}]],
    })
    with pytest.raises(DagFastPathUnsupported):
        MeshQueryExecutor().execute_dag(tables, dag)


def test_sketch_grid_budget_falls_back(shards, monkeypatch):
    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v_float", "quantile", "p5", {"q": 0.5}]],
    })
    monkeypatch.setenv("BQUERYD_TPU_SKETCH_GRID_CELLS", "16")
    with pytest.raises(DagFastPathUnsupported):
        MeshQueryExecutor().execute_dag(tables, dag)


def test_validation_errors_identical_on_both_routes(shards):
    """A top-k over a dict (string) column raises the SAME DagValidationError
    on the fast path as on the per-shard route — the fast path never masks
    or reclassifies a query-shape error as a silent fallback."""
    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["k_str", "topk", "t", {"k": 2}]],
    })
    with pytest.raises(dagmod.DagValidationError) as fast_err:
        MeshQueryExecutor().execute_dag(tables, dag)
    with pytest.raises(dagmod.DagValidationError) as slow_err:
        DagExecutor(QueryEngine()).execute_shard(tables[0], dag)
    assert str(fast_err.value) == str(slow_err.value)


def test_worker_falls_back_when_unsupported(shards):
    """The worker-level router serves an ineligible DAG via the per-shard
    pipeline instead of failing the query."""
    from bqueryd_tpu.plan.dag import parse_op  # noqa: F401 - import check

    _f, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v_int", "count_distinct", "cd"]],
    })
    # dag_batchable is False -> the worker path goes straight per-shard;
    # emulate the routing condition the worker applies
    assert not dagmod.dag_batchable(dag)
    merged = _slow(tables, dag)
    df = hostmerge.payload_to_dataframe(merged)
    full = pd.concat(_f, ignore_index=True)
    exp = full.groupby("g")["v_int"].nunique().to_dict()
    assert dict(zip(df["g"], df["cd"])) == exp


# ---------------------------------------------------------------------------
# cluster e2e: batched dispatch + kill-switch bit-identity
# ---------------------------------------------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


@pytest.fixture
def fp_cluster(tmp_path, mem_store_url):
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    frames = _dataset(seed=77)[:2]
    for i, df in enumerate(frames):
        ctable.fromdataframe(df, str(tmp_path / f"fpc_{i}.bcolzs"))
    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.1,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url, data_dir=str(tmp_path),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.1, poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(
        lambda: all(
            controller.files_map.get(f"fpc_{i}.bcolzs") for i in range(2)
        ),
        desc="shards advertised",
    )
    rpc = RPC(
        coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
    )
    yield {
        "rpc": rpc, "controller": controller, "worker": worker,
        "frames": frames,
        "shards": [f"fpc_{i}.bcolzs" for i in range(2)],
    }
    controller.running = False
    worker.running = False
    for t in threads:
        t.join(timeout=5)


def test_cluster_batched_dag_dispatch_and_kill_switch(
    fp_cluster, monkeypatch
):
    """A batched DAG query ships ONE CalcMessage for the co-located shard
    group and replies merge_mode 'device'; under BQUERYD_TPU_DAG_BATCH=0
    the same spec dispatches per shard (PR-13 shape), merges host-side,
    and the answers are bit-identical (ints) across the two paths."""
    rpc = fp_cluster["rpc"]
    controller = fp_cluster["controller"]
    spec = {
        "table": fp_cluster["shards"], "groupby": ["g"],
        "aggs": [
            ["v_int", "sum", "s"],
            ["v_int", "topk", "t3", {"k": 3}],
            ["v_float", "quantile", "p50", {"q": 0.5, "alpha": ALPHA}],
        ],
        "where": [["v_int", ">", -7]],
    }
    before = controller.counters["dispatched_shards"]
    batched = rpc.query(spec)
    assert controller.counters["dispatched_shards"] - before == 1
    assert "device" in (rpc.last_call_merge_modes or {}).values()

    monkeypatch.setenv("BQUERYD_TPU_DAG_BATCH", "0")
    before = controller.counters["dispatched_shards"]
    per_shard = rpc.query(spec)
    assert controller.counters["dispatched_shards"] - before == 2
    modes = set((rpc.last_call_merge_modes or {}).values())
    assert "device" not in modes

    a = batched.sort_values("g").reset_index(drop=True)
    b = per_shard.sort_values("g").reset_index(drop=True)
    assert a["g"].tolist() == b["g"].tolist()
    assert a["s"].tolist() == b["s"].tolist()
    for x, y in zip(a["t3"], b["t3"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    np.testing.assert_array_equal(
        a["p50"].to_numpy(), b["p50"].to_numpy()
    )
