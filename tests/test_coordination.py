import threading

import pytest

from bqueryd_tpu.coordination import coordination_store


@pytest.fixture(params=["mem", "file"])
def store(request, tmp_path):
    if request.param == "mem":
        url = f"mem://coord-test-{id(request)}"
    else:
        url = f"file://{tmp_path}/store"
    s = coordination_store(url)
    s.flushdb()
    return s


def test_set_ops(store):
    store.sadd("controllers", "tcp://1.2.3.4:14300")
    store.sadd("controllers", "tcp://1.2.3.4:14301")
    store.sadd("controllers", "tcp://1.2.3.4:14300")
    assert store.smembers("controllers") == {
        "tcp://1.2.3.4:14300",
        "tcp://1.2.3.4:14301",
    }
    store.srem("controllers", "tcp://1.2.3.4:14300")
    assert store.smembers("controllers") == {"tcp://1.2.3.4:14301"}
    store.srem("controllers", "never-added")  # no-op


def test_hash_ops(store):
    store.hset("ticket_x", "node1_s3://b/f", "123_-1")
    store.hset("ticket_x", "node2_s3://b/f", "124_-1")
    store.hset("ticket_x", "node1_s3://b/f", "125_DONE")
    assert store.hget("ticket_x", "node1_s3://b/f") == "125_DONE"
    assert store.hgetall("ticket_x") == {
        "node1_s3://b/f": "125_DONE",
        "node2_s3://b/f": "124_-1",
    }
    store.hdel("ticket_x", "node1_s3://b/f")
    assert "node1_s3://b/f" not in store.hgetall("ticket_x")


def test_keys_pattern_and_delete(store):
    store.hset("bqueryd_download_ticket_aa", "f", "1")
    store.hset("bqueryd_download_ticket_bb", "f", "1")
    store.sadd("bqueryd_controllers", "x")
    tickets = sorted(store.keys("bqueryd_download_ticket_*"))
    assert tickets == ["bqueryd_download_ticket_aa", "bqueryd_download_ticket_bb"]
    store.delete("bqueryd_download_ticket_aa")
    assert store.keys("bqueryd_download_ticket_*") == ["bqueryd_download_ticket_bb"]


def test_lock_mutual_exclusion(store):
    l1 = store.lock("dl_lock", ttl=60)
    l2 = store.lock("dl_lock", ttl=60)
    assert l1.acquire(blocking=False)
    assert not l2.acquire(blocking=False)
    l1.release()
    assert l2.acquire(blocking=False)
    l2.release()


def test_lock_ttl_expiry(store, monkeypatch):
    import time as time_mod

    l1 = store.lock("dl_lock", ttl=0.05)
    assert l1.acquire(blocking=False)
    time_mod.sleep(0.1)
    l2 = store.lock("dl_lock", ttl=60)
    assert l2.acquire(blocking=False), "expired lock must be claimable"
    l2.release()


def test_mem_store_shared_by_url():
    a = coordination_store("mem://shared-url-test")
    b = coordination_store("mem://shared-url-test")
    a.flushdb()
    a.sadd("k", "v")
    assert b.smembers("k") == {"v"}


def test_concurrent_lock_single_winner(store):
    wins = []

    def contender():
        lock = store.lock("race", ttl=60)
        if lock.acquire(blocking=False):
            wins.append(1)

    threads = [threading.Thread(target=contender) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1


def test_bad_url_rejected():
    with pytest.raises(ValueError):
        coordination_store("carrier-pigeon://coop")


def test_lock_extend_keeps_claim_past_original_ttl(store):
    """A long-running holder (blob fetch outlasting the claim TTL) extends
    its lock from the progress path; the claim must stay exclusive past the
    original expiry so no duplicate concurrent download starts."""
    import time as time_mod

    l1 = store.lock("dl_lock", ttl=0.1)
    assert l1.acquire(blocking=False)
    assert l1.extend(30)
    time_mod.sleep(0.15)  # past the ORIGINAL ttl
    l2 = store.lock("dl_lock", ttl=60)
    assert not l2.acquire(blocking=False), "extended claim must hold"
    l1.release()
    assert l2.acquire(blocking=False)
    l2.release()


def test_cancel_watch_extends_lock_on_progress(store):
    """CancelWatch re-arms the claim lock from its throttled progress path
    once lock_ttl/3 has elapsed."""
    from bqueryd_tpu.download import CancelWatch, set_progress

    set_progress(store, "n1", "tick1", "s3://b/f", -1)

    class SpyLock:
        def __init__(self):
            self.extended = []

        def extend(self, ttl):
            self.extended.append(ttl)
            return True

    lock = SpyLock()
    watch = CancelWatch(
        store, "n1", "tick1", "s3://b/f", interval=0.0, lock=lock, lock_ttl=0.3
    )
    watch._last_extend -= 0.2  # cross the lock_ttl/3 threshold
    watch.maybe_write_progress(1024)
    assert lock.extended == [0.3]
    # inside the threshold: no second extend
    watch.maybe_write_progress(2048)
    assert lock.extended == [0.3]
