"""PR 3 observability tier: compile/device profiling, the flight recorder +
debug bundle, and worker health scoring fed back into dispatch — plus the
end-to-end acceptance path: a two-worker cluster where one worker's fake
wedge flips ``rpc.health()``, dispatch routes around it, and the pulled
``rpc.debug_bundle()`` carries the wedge event in the flight ring and a
compile-registry cache hit for the second identical query."""

import functools
import json
import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tests.conftest import wait_until

from bqueryd_tpu import obs
from bqueryd_tpu.obs import flightrec, health, profile


# -- flight recorder ----------------------------------------------------------

def test_flight_recorder_entry_bound_and_tail():
    ring = obs.FlightRecorder(capacity=4, max_bytes=1 << 20)
    for i in range(10):
        ring.record("tick", i=i)
    events = ring.events()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]  # oldest first
    assert ring.evictions == 6
    tail = ring.tail(limit=2)
    assert [e["i"] for e in tail] == [8, 9]
    # seq is monotonic across evictions
    assert events[-1]["seq"] == 10


def test_flight_recorder_byte_bound():
    ring = obs.FlightRecorder(capacity=10_000, max_bytes=2048)
    for i in range(100):
        ring.record("blob", data="x" * 200)
    assert ring.nbytes <= 2048
    assert ring.evictions > 0
    assert len(ring) >= 1  # never evicts down to empty


def test_flight_recorder_events_are_json_safe():
    ring = obs.FlightRecorder(capacity=8)
    ring.record("envelope", verb="groupby", token="abc", parent=None)
    json.dumps(ring.events())


def test_approx_json_bytes_tracks_size():
    small = flightrec.approx_json_bytes({"a": 1})
    big = flightrec.approx_json_bytes({"a": "x" * 1000, "b": list(range(50))})
    assert big > small
    assert big >= 1000


# -- redaction ----------------------------------------------------------------

def test_redact_paths_outside_data_root():
    obj = {
        "ok": "/srv/bcolz/taxi.bcolz",
        "bad": "traceback File \"/home/alice/secret/app.py\" line 1",
        "url": "tcp://10.1.2.3:14300",
        "rel": "taxi.bcolz",
        "nested": ["/usr/lib/python3.11/site.py", {"k": "/srv/bcolz/x"}],
    }
    out = flightrec.redact_paths(obj, ["/srv/bcolz"])
    assert out["ok"] == "/srv/bcolz/taxi.bcolz"
    assert "/home/alice" not in out["bad"]
    assert "<redacted>/app.py" in out["bad"]
    assert out["url"] == "tcp://10.1.2.3:14300"  # URLs are not paths
    assert out["rel"] == "taxi.bcolz"
    assert out["nested"][0] == "<redacted>/site.py"
    assert out["nested"][1]["k"] == "/srv/bcolz/x"


def test_redact_paths_redacts_dict_keys():
    out = flightrec.redact_paths({"/etc/passwd/shadow": 1}, [])
    assert out == {"<redacted>/shadow": 1}


def test_redact_paths_allows_prefix_not_substring():
    # /srv/bcolz-evil must NOT ride the /srv/bcolz allowance
    out = flightrec.redact_paths(
        {"a": "/srv/bcolz-evil/file.bin"}, ["/srv/bcolz"]
    )
    assert out["a"] == "<redacted>/file.bin"


# -- bundle assembly ----------------------------------------------------------

def test_build_bundle_schema_partial_and_roundtrip():
    now = 1000.0
    bundle = flightrec.build_bundle(
        {"address": "tcp://x", "flight": []},
        {
            "w-live": {"data": {"flight": []}, "ts": now - 1.0,
                       "registered": True},
            "w-stale": {"data": {"flight": []}, "ts": now - 500.0,
                        "registered": False},
            "w-silent": {"data": None, "ts": None, "registered": True},
        },
        trace_id="t1",
        now=now,
    )
    assert list(bundle) == [
        "schema", "generated_ts", "trace_id", "controller", "workers",
        "partial",
    ]
    assert bundle["schema"] == flightrec.BUNDLE_SCHEMA
    assert bundle["trace_id"] == "t1"
    assert bundle["partial"] == ["w-silent"]
    assert bundle["workers"]["w-live"]["stale"] is False
    assert bundle["workers"]["w-stale"]["stale"] is True
    assert bundle["workers"]["w-stale"]["registered"] is False
    assert bundle["workers"]["w-silent"]["snapshot"] is None
    # round-trips through json
    assert json.loads(json.dumps(bundle)) == bundle


def test_build_bundle_redacts_foreign_paths():
    bundle = flightrec.build_bundle(
        {"flight": [{"kind": "error", "error": "File \"/root/app/x.py\""}]},
        {},
        allowed_path_prefixes=["/srv/data"],
    )
    assert "/root/app" not in json.dumps(bundle)
    assert "<redacted>/x.py" in bundle["controller"]["flight"][0]["error"]


# -- compile profiling --------------------------------------------------------

def test_instrument_counts_hits_misses_and_cost():
    import jax
    import jax.numpy as jnp

    prof = profile._reset_for_tests()
    try:

        @functools.partial(jax.jit, static_argnames=("n",))
        def f(x, n):
            return x * n

        g = profile.instrument("test.scale", f)
        # explicit dtype: the suite enables x64 (ops import), which would
        # otherwise shift the default dtype and the signature under test
        g(jnp.zeros(16, dtype=jnp.float32), n=3)
        g(jnp.zeros(16, dtype=jnp.float32), n=3)
        g(jnp.zeros(32, dtype=jnp.float32), n=3)
        snap = prof.snapshot()
        assert snap["jit_cache_misses"] == 2
        assert snap["jit_cache_hits"] == 1
        assert sum(snap["compile_seconds"]["counts"]) == 2
        by_sig = {p["signature"]: p for p in snap["programs"]}
        entry = by_sig["test.scale(float32[16];3)"]
        assert entry["calls"] == 2
        assert entry["compiles"] == 1
        assert entry["jit_cache_hits"] == 1
        assert entry["flops"] is not None and entry["flops"] > 0
        assert entry["bytes_accessed"] is not None
        json.dumps(snap)
    finally:
        profile._reset_for_tests()


def test_instrument_respects_kill_switch_and_traces():
    import jax
    import jax.numpy as jnp

    prof = profile._reset_for_tests()
    try:
        g = profile.instrument("test.inc", jax.jit(lambda x: x + 1))
        obs.set_enabled(False)
        try:
            g(jnp.zeros(4))
        finally:
            obs.set_enabled(True)
        assert prof.snapshot()["programs_tracked"] == 0
        # under an outer trace the wrapper passes straight through
        outer = jax.jit(lambda x: g(x))
        outer(jnp.zeros(4))
        assert prof.snapshot()["programs_tracked"] == 0
        # a plain (non-jitted) callable is also a passthrough
        plain = profile.instrument("test.plain", lambda x: x)
        assert plain(5) == 5
    finally:
        profile._reset_for_tests()


def test_program_registry_evicts_least_recently_called(monkeypatch):
    """Past MAX_PROGRAMS the registry drops the LRU shape, not the one that
    just arrived (the regression would freeze it at the first 256 shapes)."""
    prof = profile._reset_for_tests()
    try:
        monkeypatch.setattr(profile, "MAX_PROGRAMS", 4)

        class FakeJit:
            def lower(self, *a, **k):
                raise RuntimeError("no cost analysis in this test")

        fake = FakeJit()
        for i in range(6):
            prof.record_call(
                f"prog{i}", fake, (), {}, compiled=True, duration_s=0.01
            )
        sigs = {p["name"] for p in prof.snapshot()["programs"]}
        assert sigs == {"prog2", "prog3", "prog4", "prog5"}
        assert prof.programs_evicted == 2
        # re-calling a survivor keeps it fresh; the next new shape evicts
        # the actual LRU instead
        prof.record_call("prog2", fake, (), {}, compiled=False,
                         duration_s=0.0)
        prof.record_call("prog6", fake, (), {}, compiled=True,
                         duration_s=0.01)
        sigs = {p["name"] for p in prof.snapshot()["programs"]}
        assert "prog2" in sigs and "prog3" not in sigs
    finally:
        profile._reset_for_tests()


def test_compile_cache_info_follows_env(monkeypatch, tmp_path):
    monkeypatch.setenv("BQUERYD_TPU_COMPILE_CACHE", str(tmp_path))
    info = profile.compile_cache_info()
    assert info["enabled"] is True
    assert info["path"] == str(tmp_path)
    assert info["writable"] is True
    monkeypatch.setenv("BQUERYD_TPU_COMPILE_CACHE", "0")
    assert profile.compile_cache_info()["enabled"] is False


def test_runtime_versions_reports_jax():
    import jax

    versions = profile.runtime_versions()
    assert versions["jax"] == jax.__version__
    assert "jaxlib" in versions


# -- health scoring -----------------------------------------------------------

def _snap(count, total):
    return {
        health.LATENCY_FAMILY: [
            {"labels": {}, "buckets": [1.0], "counts": [count], "sum": total}
        ]
    }


def test_health_scorer_error_rate_degrades():
    scorer = obs.HealthScorer(min_errors=3, error_rate_threshold=0.25)
    scorer.observe("w1", _snap(0, 0.0), errors=0, now=100.0)
    scorer.observe("w1", _snap(0, 0.0), errors=10, now=110.0)
    statuses = scorer.statuses()
    assert statuses["w1"]["status"] == obs.STATUS_DEGRADED
    assert "error rate" in statuses["w1"]["reason"]


def test_health_scorer_wedged_flag_wins():
    scorer = obs.HealthScorer()
    scorer.observe("w1", _snap(5, 0.1), wedged=True, now=100.0)
    assert scorer.status("w1") == obs.STATUS_WEDGED


def test_health_scorer_latency_outlier_vs_fleet():
    scorer = obs.HealthScorer(min_samples=5, latency_factor=3.0)
    for wid, per_query in (("fast1", 0.01), ("fast2", 0.012), ("slow", 0.5)):
        scorer.observe(wid, _snap(0, 0.0), now=100.0)
        scorer.observe(wid, _snap(10, 10 * per_query), now=110.0)
    statuses = scorer.statuses()
    assert statuses["fast1"]["status"] == obs.STATUS_OK
    assert statuses["slow"]["status"] == obs.STATUS_DEGRADED
    assert "fleet median" in statuses["slow"]["reason"]


def test_health_scorer_young_worker_is_ok_and_remove():
    scorer = obs.HealthScorer()
    scorer.observe("w1", _snap(1, 5.0), now=100.0)  # one sample: no window
    assert scorer.status("w1") == obs.STATUS_OK
    assert scorer.status("unknown") == obs.STATUS_OK
    scorer.remove("w1")
    assert scorer.statuses() == {}


def test_health_scorer_statuses_memo_invalidates_on_observe():
    """statuses() is memoized for the dispatch hot path; a new observation
    must invalidate the cache, not serve the stale verdict."""
    scorer = obs.HealthScorer()
    scorer.observe("w1", _snap(0, 0.0), now=100.0)
    first = scorer.statuses()
    assert scorer.statuses() is first  # cache hit between observations
    scorer.observe("w1", _snap(0, 0.0), wedged=True, now=110.0)
    assert scorer.statuses()["w1"]["status"] == obs.STATUS_WEDGED


def test_health_routing_env_gate(monkeypatch):
    monkeypatch.delenv("BQUERYD_TPU_HEALTH_ROUTING", raising=False)
    assert health.routing_enabled()
    monkeypatch.setenv("BQUERYD_TPU_HEALTH_ROUTING", "0")
    assert not health.routing_enabled()


# -- byte-bounded rings (satellite) -------------------------------------------

def test_trace_store_byte_bound_and_latest():
    store = obs.TraceStore(capacity=1000, max_bytes=4096)
    for i in range(50):
        store.put(f"t{i}", {"trace_id": f"t{i}", "pad": "x" * 300})
    assert store.nbytes <= 4096
    assert store.evictions > 0
    assert len(store) < 50
    assert store.get("t0") is None
    assert store.latest()["trace_id"] == "t49"


def test_trace_store_update_does_not_leak_bytes():
    store = obs.TraceStore(capacity=10, max_bytes=1 << 20)
    for _ in range(20):
        store.put("same", {"trace_id": "same", "pad": "x" * 100})
    assert len(store) == 1
    assert store.nbytes == flightrec.approx_json_bytes(store.get("same"))


def test_slow_query_log_byte_bound(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_SLOW_QUERY_MS", "0")
    log = obs.SlowQueryLog(capacity=1000, max_bytes=4096)
    for i in range(50):
        log.maybe_record(1.0, {"trace_id": f"t{i}", "pad": "y" * 300})
    assert log.nbytes <= 4096
    assert log.evictions > 0
    assert 1 <= len(log) < 50


# -- registry adoption + README-coverage lint (satellite) ---------------------

def test_registry_register_adopts_shared_instance():
    from bqueryd_tpu.obs.metrics import Histogram

    shared = Histogram("bqueryd_tpu_shared_seconds", "shared")
    reg_a, reg_b = obs.MetricsRegistry(), obs.MetricsRegistry()
    assert reg_a.register(shared) is shared
    assert reg_a.register(shared) is shared  # idempotent
    reg_b.register(shared)
    shared.observe(0.01)
    assert "bqueryd_tpu_shared_seconds_count 1" in reg_a.render()
    assert "bqueryd_tpu_shared_seconds_count 1" in reg_b.render()
    with pytest.raises(ValueError):
        reg_a.register(Histogram("bqueryd_tpu_shared_seconds", "other"))


def test_readme_coverage_lint_flags_undocumented():
    from bqueryd_tpu.obs.metrics import readme_coverage_problems

    reg = obs.MetricsRegistry()
    reg.counter("bqueryd_tpu_documented_total", "x")
    reg.counter("bqueryd_tpu_mystery_total", "x")
    problems = readme_coverage_problems(
        [reg], "docs mention `bqueryd_tpu_documented_total` only"
    )
    assert problems == [
        "bqueryd_tpu_mystery_total: registered but missing from the README "
        "metrics table"
    ]


# -- end-to-end: the acceptance path ------------------------------------------

NR_SHARDS = 3


def _taxi_df(n=3_000, seed=7):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "total_amount": rng.gamma(2.5, 8.0, n),
        }
    )


@pytest.fixture(scope="module")
def forensics_cluster(tmp_path_factory):
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = _taxi_df()
    root = tmp_path_factory.mktemp("forensics_cluster")
    ctable.fromdataframe(df, str(root / "taxi.bcolz"))
    for i in range(NR_SHARDS):
        ctable.fromdataframe(
            df.iloc[i::NR_SHARDS], str(root / f"taxi-{i}.bcolzs")
        )
    url = f"mem://forensics-{os.urandom(4).hex()}"
    # the result cache would serve the second identical query without any
    # kernel dispatch — the compile-registry acceptance check needs the
    # program to actually run twice
    old_cache = os.environ.get("BQUERYD_TPU_RESULT_CACHE_BYTES")
    os.environ["BQUERYD_TPU_RESULT_CACHE_BYTES"] = "0"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=str(root),
        heartbeat_interval=0.2,
        dead_worker_timeout=2.0,
    )
    workers = [
        WorkerNode(
            coordination_url=url,
            data_dir=str(root),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.1,
        )
        for _ in range(2)
    ]
    threads = [
        threading.Thread(target=node.go, daemon=True)
        for node in [controller] + workers
    ]
    for t in threads:
        t.start()
    wait_until(
        lambda: len(controller.files_map.get("taxi.bcolz", ())) == 2,
        desc="both workers advertising",
    )
    rpc = RPC(coordination_url=url, timeout=60, loglevel=logging.WARNING)
    yield {
        "rpc": rpc,
        "controller": controller,
        "workers": workers,
        "df": df,
    }
    for node in [controller] + workers:
        node.running = False
    for t in threads:
        t.join(timeout=5)
    if old_cache is None:
        os.environ.pop("BQUERYD_TPU_RESULT_CACHE_BYTES", None)
    else:
        os.environ["BQUERYD_TPU_RESULT_CACHE_BYTES"] = old_cache


def _groupby(rpc):
    return rpc.groupby(
        ["taxi.bcolz"],
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        [],
    )


def test_e2e_wedge_health_routing_and_debug_bundle(forensics_cluster):
    """ACCEPTANCE: wedge a fake worker -> rpc.health() flips it off ok ->
    dispatch routes around it -> the debug bundle carries the wedge event in
    the flight ring and a compile-registry cache hit for the second
    identical query."""
    rpc = forensics_cluster["rpc"]
    controller = forensics_cluster["controller"]
    healthy, wedged = forensics_cluster["workers"]

    # fake-wedge ONE worker of the in-process cluster: its WRMs advertise
    # the latch without touching the process-global devicehealth state
    wedged._backend_wedged = lambda: True

    def wedged_status():
        statuses = rpc.health()["workers"]
        return statuses.get(wedged.worker_id, {}).get("status")

    status = wait_until(
        lambda: wedged_status() in ("wedged", "degraded") and wedged_status(),
        desc="health scorer flags the wedged worker",
    )
    assert status == "wedged"
    assert rpc.health()["workers"][healthy.worker_id]["status"] == "ok"

    # the controller's flight ring logged the latch the moment the fleet
    # view learned it (never gated)
    assert any(
        e["kind"] == "worker_wedged" and e["worker"] == wedged.worker_id
        for e in controller.flight.events()
    )

    # dispatch routes around the wedged worker: sequential identical
    # queries all land on the healthy one
    base_healthy = healthy.groupby_queries.value
    base_wedged = wedged.groupby_queries.value
    snap_before = profile.profiler().snapshot(max_programs=1_000_000)
    hits_before = snap_before["jit_cache_hits"]
    # per-signature baseline: the process-global registry carries history
    # from every earlier test in this process; the acceptance claim is that
    # OUR second identical query registers as a hit on ITS program shape
    hits_by_sig = {
        p["signature"]: p["jit_cache_hits"] for p in snap_before["programs"]
    }
    expected = (
        forensics_cluster["df"]
        .groupby("payment_type")["total_amount"]
        .sum()
    )
    for _ in range(3):
        result = _groupby(rpc)
        got = result.set_index("payment_type")["total_amount"]
        assert np.allclose(got.sort_index(), expected.sort_index())
    trace_id = rpc.last_trace_id
    assert healthy.groupby_queries.value - base_healthy == 3
    assert wedged.groupby_queries.value == base_wedged
    assert controller.counters["health_avoided_dispatches"] >= 1

    # the repeat queries hit the jit cache (result cache is disabled in
    # this fixture, so the program really ran each time)
    assert profile.profiler().snapshot()["jit_cache_hits"] > hits_before

    # pull the bundle once the workers' WRM debug slices (with the fresh
    # compile registry numbers) have been absorbed
    def bundle_ready():
        bundle = rpc.debug_bundle(trace_id)
        workers = bundle["workers"]
        snap = (workers.get(healthy.worker_id) or {}).get("snapshot")
        if not snap:
            return None
        if snap["compile"]["jit_cache_hits"] <= hits_before:
            return None
        return bundle

    bundle = wait_until(bundle_ready, desc="bundle with fresh debug slices")
    assert bundle["schema"] == "bqueryd_tpu.debug_bundle/4"
    assert bundle["trace_id"] == trace_id
    # flight ring: the wedge event is in the artifact, alongside the
    # normal-flow envelope/dispatch/outcome events
    kinds = {
        (e["kind"], e.get("worker"))
        for e in bundle["controller"]["flight"]
    }
    assert ("worker_wedged", wedged.worker_id) in kinds
    bare_kinds = {k for k, _ in kinds}
    assert {"rpc", "dispatch", "query_done"} <= bare_kinds
    # compile registry: cache hit on the repeated identical query — some
    # program shape's hit count moved past its pre-query baseline
    compile_snap = bundle["workers"][healthy.worker_id]["snapshot"]["compile"]
    assert compile_snap["jit_cache_hits"] > hits_before
    assert any(
        p["jit_cache_hits"] > hits_by_sig.get(p["signature"], 0)
        for p in compile_snap["programs"]
    )
    # trace timeline rode along, spans intact
    assert bundle["controller"]["trace"]["trace_id"] == trace_id
    assert any(
        s["name"] == "kernel" for s in bundle["controller"]["trace"]["spans"]
    )
    # health section agrees with rpc.health()
    assert (
        bundle["controller"]["health"][wedged.worker_id]["status"] == "wedged"
    )
    # the whole artifact is one JSON file
    json.dumps(bundle)
    # both workers reported: nothing partial
    assert bundle["partial"] == []
    # worker flight rings carry the envelope/work events
    worker_kinds = {
        e["kind"]
        for e in bundle["workers"][healthy.worker_id]["snapshot"]["flight"]
    }
    assert {"envelope", "work_done"} <= worker_kinds


def test_e2e_info_reports_runtime_and_compile_cache(forensics_cluster):
    import jax

    rpc = forensics_cluster["rpc"]
    info = rpc.info()
    assert info["runtime"]["jax"] == jax.__version__
    assert set(info["compile_cache"]) == {"enabled", "path", "writable"}
    # per-worker versions gossiped via WRM debug slices
    wait_until(
        lambda: any(
            (v or {}).get("jax") == jax.__version__
            for v in rpc.info()["worker_runtime"].values()
        ),
        desc="worker runtime versions absorbed",
    )


def test_e2e_live_registries_pass_lints(forensics_cluster):
    """Registry lint + the README-coverage extension run clean on REAL node
    registries — every registered family is documented."""
    controller = forensics_cluster["controller"]
    workers = forensics_cluster["workers"]
    registries = [controller.metrics] + [w.metrics for w in workers]
    for registry in registries:
        assert registry.lint() == []
    from bqueryd_tpu.obs.metrics import readme_coverage_problems

    readme = open(
        os.path.join(os.path.dirname(__file__), "..", "README.md")
    ).read()
    assert readme_coverage_problems(registries, readme) == []


def test_e2e_trace_carries_device_memory_tags_when_available(
    forensics_cluster,
):
    """On backends with memory_stats (TPU) the calc root span is tagged with
    per-query device memory; on CPU the tags are simply absent — assert the
    span schema stays intact either way."""
    rpc = forensics_cluster["rpc"]
    _groupby(rpc)
    timeline = rpc.trace(rpc.last_trace_id)
    calc = next(s for s in timeline["spans"] if s["name"] == "calc")
    tags = calc.get("tags")
    if tags is not None and "device_hbm_watermark_bytes" in tags:
        assert tags["device_hbm_watermark_bytes"] >= 0
        assert tags["device_peak_delta_bytes"] >= 0


def test_e2e_sigusr1_dump_writes_bundle(forensics_cluster, tmp_path,
                                        monkeypatch):
    controller = forensics_cluster["controller"]
    monkeypatch.setenv("BQUERYD_TPU_DEBUG_DIR", str(tmp_path))
    controller._dump_debug_signal()
    dumps = list(tmp_path.glob("bqueryd_tpu_debug_controller_*.json"))
    assert len(dumps) == 1
    bundle = json.loads(dumps[0].read_text())
    assert bundle["schema"] == "bqueryd_tpu.debug_bundle/4"


def test_e2e_partial_bundle_after_worker_death(forensics_cluster):
    """A dead peer degrades the bundle, never fails it: its last absorbed
    snapshot still ships, marked unregistered."""
    rpc = forensics_cluster["rpc"]
    controller = forensics_cluster["controller"]
    wedged = forensics_cluster["workers"][1]
    wedged.running = False
    wait_until(
        lambda: wedged.worker_id not in controller.worker_map,
        desc="dead worker culled",
    )
    bundle = rpc.debug_bundle()
    entry = bundle["workers"][wedged.worker_id]
    assert entry["registered"] is False
    assert entry["snapshot"] is not None  # last words survive
    json.dumps(bundle)
