"""Operator-DAG executor: pandas-parity fuzz + merge parity + wire surface.

Covers the PR-13 acceptance criteria:

* plain filter->groupby queries compile THROUGH the DAG layer and stay
  bit-identical to the engine path (the fuzz corpus from
  test_differential_fuzz reused byte-for-byte);
* each new operator — broadcast hash join, per-group top-k, mergeable
  quantile sketch, time-window rollup — answers correctly under sharding
  vs pandas (ints bit-exact, floats within summation-order tolerance,
  quantiles within the documented sketch bound alpha);
* sharded-vs-single-shard merge parity (the flat partial forms merge
  associatively);
* device kernels (ops.relops) bit-identical to their host twins;
* spec validation and the structured UnsupportedOp error surface.
"""

import logging
import math
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
from bqueryd_tpu.parallel import hostmerge, opexec
from bqueryd_tpu.parallel.opexec import DagExecutor
from bqueryd_tpu.plan import dag as dagmod
from bqueryd_tpu.storage.ctable import ctable

from conftest import wait_until

N_SHARDS = 3
ROWS = 3_000
ALPHA = 0.01


def _dataset(seed=424):
    rng = np.random.default_rng(seed)
    frames = []
    for _ in range(N_SHARDS):
        n = ROWS
        ts = pd.to_datetime(
            rng.integers(1_400_000_000, 1_400_050_000, n), unit="s"
        ).to_series().reset_index(drop=True)
        ts[pd.Series(rng.random(n) < 0.06)] = pd.NaT
        frames.append(
            pd.DataFrame(
                {
                    "g": rng.integers(0, 6, n).astype(np.int64),
                    "cust": rng.integers(0, 40, n).astype(np.int64),
                    "k_str": rng.choice(
                        ["a", "b", "c", None], n, p=[0.4, 0.3, 0.2, 0.1]
                    ),
                    "t": ts.to_numpy(),
                    "v_int": rng.integers(-1000, 1000, n).astype(np.int64),
                    "v_big": rng.integers(-(2**60), 2**60, n),
                    "v_float": np.where(
                        rng.random(n) < 0.08,
                        np.nan,
                        rng.random(n) * 200 - 100,
                    ),
                    "sel": rng.random(n),
                }
            )
        )
    return frames


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    frames = _dataset()
    root = tmp_path_factory.mktemp("operators")
    tables = []
    for i, df in enumerate(frames):
        p = str(root / f"op_{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))
    return frames, tables


#: dimension table: deliberately MISSING cust ids >= 30 (absent join keys
#: must drop, inner-join semantics) and with a numeric attribute
def _dim():
    cust = np.arange(30, dtype=np.int64)
    return {
        "cust": cust,
        "region": np.array(
            ["r%d" % (c % 4) for c in cust], dtype=object
        ),
        "weight": (cust % 7).astype(np.int64),
    }


def _pandas_side(frames, dim=None, window=None, where=()):
    df = pd.concat(frames, ignore_index=True)
    if dim is not None:
        df = df.merge(pd.DataFrame(dim), on="cust", how="inner")
    if window is not None:
        col, every, alias = window
        df = df.copy()
        df[alias] = df[col].dt.floor(every)
    for col, op, val in where:
        if op == ">":
            df = df[df[col] > val]
        elif op == "==":
            df = df[df[col] == val]
        elif op == "!=":
            df = df[df[col] != val]
        elif op == "in":
            df = df[df[col].isin(val)]
        else:
            raise NotImplementedError(op)
    return df


def _run_dag(tables, dag):
    engine = QueryEngine()
    executor = DagExecutor(engine)
    payloads = [executor.execute_shard(t, dag) for t in tables]
    merged = hostmerge.merge_payloads(payloads)
    return hostmerge.payload_to_dataframe(merged)


# ---------------------------------------------------------------------------
# plain groupbys through the DAG layer: bit-identical (fuzz corpus)
# ---------------------------------------------------------------------------

def test_plain_dag_round_trip_is_field_exact_over_fuzz_corpus():
    """Every fuzz-corpus case round-trips GroupByQuery -> DAG ->
    GroupByQuery with an identical signature — the property that lets the
    worker compile every groupby through plan.dag while plain shapes
    execute on the unchanged engine."""
    from test_differential_fuzz import CASES

    for gcols, agg_list, where in CASES:
        q = GroupByQuery(gcols, agg_list, where, aggregate=True)
        dag = dagmod.dag_from_query(q, filenames=["x.bcolzs"])
        assert dag.is_plain()
        q2 = dag.plain_groupby_query()
        assert q2.signature() == q.signature()
        # and through the wire form too (what a CalcMessage carries)
        dag2 = dagmod.OperatorDAG.from_wire(dag.to_wire())
        assert dag2.plain_groupby_query().signature() == q.signature()


def test_plain_dag_payloads_bit_identical_to_engine(shards):
    """Executing the plain-DAG round-tripped query produces byte-identical
    payloads to the original query on every fuzz case (the engine path is
    shared, so this proves the round trip changes NOTHING)."""
    from test_differential_fuzz import CASES

    frames, tables = shards  # noqa: F841 - engine only needs tables
    engine = QueryEngine()
    for gcols, agg_list, where in CASES[:8]:
        gcols = [c for c in gcols if c in ("k_str",)] or ["g"]
        q = GroupByQuery(gcols, [["v_int", "sum", "s"]], [], aggregate=True)
        q2 = dagmod.dag_from_query(q).plain_groupby_query()
        a = engine.execute_local(tables[0], q).to_bytes()
        b = engine.execute_local(tables[0], q2).to_bytes()
        assert a == b


# ---------------------------------------------------------------------------
# broadcast hash join
# ---------------------------------------------------------------------------

def test_join_groupby_matches_pandas_inner(shards):
    frames, tables = shards
    dim = _dim()
    dag = dagmod.compile_query({
        "table": ["x"],
        "groupby": ["region"],
        "aggs": [
            ["v_int", "sum", "s"],
            ["v_float", "mean", "m"],
            ["weight", "sum", "w"],     # dimension column as a measure
        ],
        "join": {"table": dim, "on": "cust", "select": ["region", "weight"]},
    })
    got = _run_dag(tables, dag).sort_values("region").reset_index(drop=True)
    df = _pandas_side(frames, dim=dim)
    exp = df.groupby("region").agg(
        s=("v_int", "sum"), m=("v_float", "mean"), w=("weight", "sum")
    ).reset_index()
    assert got["region"].tolist() == exp["region"].tolist()
    np.testing.assert_array_equal(got["s"], exp["s"])   # int bit-exact
    np.testing.assert_array_equal(got["w"], exp["w"])
    np.testing.assert_allclose(got["m"], exp["m"], rtol=2e-12)


def test_join_keys_absent_from_dimension_table_drop(shards):
    """cust >= 30 has no dimension row: inner-join semantics drop those
    rows entirely (documented), so totals equal pandas' inner merge."""
    frames, tables = shards
    dim = _dim()
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["region"],
        "aggs": [["v_int", "count", "n"]],
        "join": {"table": dim, "on": "cust", "select": ["region"]},
    })
    got = _run_dag(tables, dag)
    df = _pandas_side(frames, dim=dim)
    assert int(got["n"].sum()) == len(df)
    # and strictly fewer rows than the unjoined fact side
    assert len(df) < sum(len(f) for f in frames)


def test_join_with_post_join_filter_and_fact_pushdown(shards):
    frames, tables = shards
    dim = _dim()
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v_int", "sum", "s"]],
        "where": [["sel", ">", 0.5], ["region", "in", ["r0", "r2"]]],
        "join": {"table": dim, "on": "cust", "select": ["region"]},
    })
    # the fact term pushed down; the dim term became the filter node
    assert dag.scan.pushdown == [("sel", ">", 0.5)]
    assert dag.filter.terms == [("region", "in", ["r0", "r2"])]
    got = _run_dag(tables, dag).sort_values("g").reset_index(drop=True)
    df = _pandas_side(
        frames, dim=dim,
        where=[("sel", ">", 0.5), ("region", "in", ["r0", "r2"])],
    )
    exp = df.groupby("g")["v_int"].sum().reset_index(name="s")
    assert got["g"].tolist() == exp["g"].tolist()
    np.testing.assert_array_equal(got["s"], exp["v_int"] if "v_int" in exp else exp["s"])


def test_join_validation_errors():
    dim = {"cust": np.array([1, 1, 2]), "x": np.array([1, 2, 3])}
    with pytest.raises(dagmod.DagValidationError, match="duplicate"):
        dagmod.compile_query({
            "table": ["x"], "groupby": ["x"],
            "aggs": [["v", "sum", "s"]],
            "join": {"table": dim, "on": "cust", "select": ["x"]},
        })
    big = {"cust": np.arange(10), "x": np.arange(10)}
    with pytest.raises(dagmod.DagValidationError, match="broadcast limit"):
        os.environ["BQUERYD_TPU_JOIN_BROADCAST_LIMIT"] = "5"
        try:
            dagmod.compile_query({
                "table": ["x"], "groupby": ["x"],
                "aggs": [["v", "sum", "s"]],
                "join": {"table": big, "on": "cust", "select": ["x"]},
            })
        finally:
            del os.environ["BQUERYD_TPU_JOIN_BROADCAST_LIMIT"]


# ---------------------------------------------------------------------------
# per-group top-k
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("largest", [True, False])
@pytest.mark.parametrize("col,k", [("v_int", 3), ("v_float", 5), ("v_big", 1)])
def test_topk_matches_pandas(shards, col, k, largest):
    frames, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [[col, "topk", "tk", {"k": k, "largest": largest}]],
    })
    got = _run_dag(tables, dag).sort_values("g").reset_index(drop=True)
    df = pd.concat(frames, ignore_index=True)
    exp = df.groupby("g")[col].apply(
        lambda s: np.sort(s.dropna().to_numpy())[::-1][:k]
        if largest else np.sort(s.dropna().to_numpy())[:k]
    )
    for i, g in enumerate(got["g"]):
        np.testing.assert_array_equal(np.asarray(got["tk"][i]), exp.loc[g])


def test_topk_ties_keep_duplicate_values():
    """k=3 over values with ties at the boundary: the selection keeps
    duplicated values (value multiset semantics, like nlargest)."""
    tmp_vals = np.array([5, 5, 5, 5, 1, 0], dtype=np.int64)
    codes = np.zeros(6, dtype=np.int64)
    vals, offsets = opexec.topk_flat(codes, tmp_vals, 3, True, 1)
    assert vals.tolist() == [5, 5, 5]
    assert offsets.tolist() == [0, 3]
    # smallest polarity
    vals, _ = opexec.topk_flat(codes, tmp_vals, 2, False, 1)
    assert vals.tolist() == [0, 1]


def test_topk_datetime_measure_skips_nat(shards):
    frames, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["t", "topk", "latest", {"k": 2}]],
    })
    got = _run_dag(tables, dag).sort_values("g").reset_index(drop=True)
    df = pd.concat(frames, ignore_index=True)
    exp = df.groupby("g")["t"].apply(
        lambda s: np.sort(s.dropna().to_numpy())[::-1][:2]
    )
    for i, g in enumerate(got["g"]):
        arr = np.asarray(got["latest"][i])
        assert arr.dtype.kind == "M"
        np.testing.assert_array_equal(arr, exp.loc[g])


def test_topk_sharded_vs_single_shard_parity(shards):
    """Merging per-shard top-k partials (k-way re-select) equals running
    top-k over the concatenated data in one shot."""
    frames, tables = shards
    df = pd.concat(frames, ignore_index=True)
    codes_all = df["g"].to_numpy()
    vals_all = df["v_int"].to_numpy()
    single_vals, single_offs = opexec.topk_flat(
        codes_all, vals_all, 4, True, 6
    )
    parts = []
    for f in frames:
        v, o = opexec.topk_flat(f["g"].to_numpy(), f["v_int"].to_numpy(),
                                4, True, 6)
        parts.append((np.arange(6), v, o))
    merged_vals, merged_offs = opexec.merge_topk_parts(parts, 4, True, 6)
    np.testing.assert_array_equal(merged_offs, single_offs)
    np.testing.assert_array_equal(merged_vals, single_vals)


def test_topk_k_limit_rejected():
    with pytest.raises(dagmod.DagValidationError) as err:
        dagmod.compile_query({
            "table": ["x"], "groupby": ["g"],
            "aggs": [["v", "topk", "t", {"k": 10**9}]],
        })
    assert err.value.error_class == "UnsupportedOp"


# ---------------------------------------------------------------------------
# mergeable quantile sketches
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("q", [0.1, 0.5, 0.9, 0.99])
def test_quantile_within_documented_bound(shards, q):
    frames, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["v_float", "quantile", "qq", {"q": q, "alpha": ALPHA}]],
    })
    got = _run_dag(tables, dag).sort_values("g").reset_index(drop=True)
    df = pd.concat(frames, ignore_index=True)
    exp = df.groupby("g")["v_float"].quantile(q, interpolation="lower")
    for i, g in enumerate(got["g"]):
        e = float(exp.loc[g])
        rel = abs(float(got["qq"][i]) - e) / max(abs(e), 1e-9)
        assert rel <= ALPHA + 1e-9, (g, got["qq"][i], e, rel)


def test_quantile_nan_and_all_nan_groups():
    """NaNs drop (pandas skipna); an all-NaN group estimates NaN."""
    codes = np.array([0, 0, 0, 1, 1], dtype=np.int64)
    vals = np.array([1.0, np.nan, 3.0, np.nan, np.nan])
    keys, counts, offsets = opexec.sketch_flat(codes, vals, 2, alpha=ALPHA)
    assert offsets.tolist()[-1] == int(counts.sum())
    est = opexec.sketch_quantiles(keys, counts, offsets, 0.5, ALPHA)
    assert abs(est[0] - 1.0) <= ALPHA * 1.0 + 1e-12  # lower stat of [1, 3]
    assert math.isnan(est[1])


def test_quantile_negative_zero_and_extreme_values():
    """Signed buckets: negatives mirror, zeros land in the zero bucket,
    magnitudes beyond the clamp still return finite estimates."""
    codes = np.zeros(7, dtype=np.int64)
    vals = np.array([-100.0, -1.0, 0.0, 0.0, 1.0, 100.0, 1e18])
    keys, counts, offsets = opexec.sketch_flat(codes, vals, 1, alpha=ALPHA)
    est0 = opexec.sketch_quantiles(keys, counts, offsets, 0.01, ALPHA)[0]
    assert abs(est0 - (-100.0)) <= 100.0 * ALPHA + 1e-9
    est_mid = opexec.sketch_quantiles(keys, counts, offsets, 0.5, ALPHA)[0]
    assert est_mid == 0.0
    assert np.isfinite(
        opexec.sketch_quantiles(keys, counts, offsets, 0.999, ALPHA)[0]
    )


def test_sketch_sharded_merge_is_bucket_addition(shards):
    """Sharded sketches merged by bucket addition are IDENTICAL to the
    single-pass sketch (same binning function everywhere), so sharded and
    single-shard quantile estimates are bit-equal."""
    frames, tables = shards
    df = pd.concat(frames, ignore_index=True)
    k1, c1, o1 = opexec.sketch_flat(
        df["g"].to_numpy(), df["v_float"].to_numpy(), 6, alpha=ALPHA
    )
    parts = []
    for f in frames:
        k, c, o = opexec.sketch_flat(
            f["g"].to_numpy(), f["v_float"].to_numpy(), 6, alpha=ALPHA
        )
        parts.append((np.arange(6), k, c, o))
    mk, mc, mo = opexec.merge_sketch_parts(parts, 6)
    np.testing.assert_array_equal(mk, k1)
    np.testing.assert_array_equal(mc, c1)
    np.testing.assert_array_equal(mo, o1)


def test_quantile_on_strings_rejected(shards):
    frames, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"], "groupby": ["g"],
        "aggs": [["k_str", "quantile", "qq", {"q": 0.5}]],
    })
    engine = QueryEngine()
    with pytest.raises(dagmod.DagValidationError, match="numeric"):
        DagExecutor(engine).execute_shard(tables[0], dag)


# ---------------------------------------------------------------------------
# time-window rollups
# ---------------------------------------------------------------------------

def test_window_rollup_matches_pandas_floor(shards):
    frames, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"],
        "groupby": [{"window": {"on": "t", "every": "1h", "alias": "hh"}}],
        "aggs": [["v_int", "sum", "s"], ["v_int", "count", "n"]],
    })
    got = _run_dag(tables, dag).sort_values("hh").reset_index(drop=True)
    df = _pandas_side(frames, window=("t", "1h", "hh"))
    exp = df.dropna(subset=["hh"]).groupby("hh").agg(
        s=("v_int", "sum"), n=("v_int", "count")
    ).reset_index()
    assert list(got["hh"].astype("datetime64[ns]")) == list(exp["hh"])
    np.testing.assert_array_equal(got["s"], exp["s"])
    np.testing.assert_array_equal(got["n"], exp["n"])


def test_window_boundaries_across_shard_edges(tmp_path):
    """A window straddling two shards (same bucket receives rows from
    both) merges into ONE output row with the exact combined total."""
    base = pd.Timestamp("2020-01-01 00:59:59")
    df0 = pd.DataFrame({
        "t": [base, base + pd.Timedelta(seconds=2)],
        "v": np.array([10, 20], dtype=np.int64),
    })
    df1 = pd.DataFrame({
        "t": [base + pd.Timedelta(seconds=1), base + pd.Timedelta(hours=2)],
        "v": np.array([100, 7], dtype=np.int64),
    })
    tables = []
    for i, df in enumerate((df0, df1)):
        p = str(tmp_path / f"w{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))
    dag = dagmod.compile_query({
        "table": ["x"],
        "groupby": [{"window": {"on": "t", "every": "1h", "alias": "hh"}}],
        "aggs": [["v", "sum", "s"]],
    })
    got = _run_dag(tables, dag).sort_values("hh").reset_index(drop=True)
    # buckets: 00:00 holds shard0's 00:59:59 row; 01:00 receives rows from
    # BOTH shards (01:00:01 in shard0, 01:00:00 in shard1) and must merge
    # into one output row; 02:00 holds shard1's tail row
    assert got["s"].tolist() == [10, 120, 7]
    assert list(got["hh"].astype("datetime64[ns]")) == [
        pd.Timestamp("2020-01-01 00:00:00"),
        pd.Timestamp("2020-01-01 01:00:00"),
        pd.Timestamp("2020-01-01 02:00:00"),
    ]


def test_window_plus_key_and_every_formats(shards):
    frames, tables = shards
    dag = dagmod.compile_query({
        "table": ["x"],
        "groupby": ["g", {"window": {"on": "t", "every": "30m",
                                     "alias": "hw"}}],
        "aggs": [["v_int", "sum", "s"]],
    })
    got = _run_dag(tables, dag)
    df = _pandas_side(frames, window=("t", "30min", "hw"))
    exp = df.dropna(subset=["hw"]).groupby(["g", "hw"])["v_int"].sum()
    assert len(got) == len(exp)
    got_map = {
        (g, pd.Timestamp(h)): s
        for g, h, s in zip(got["g"], got["hw"], got["s"])
    }
    assert got_map == exp.to_dict()
    # malformed every specs fail loudly at compile
    for bad in ("xyz", "-1h", 0):
        with pytest.raises(dagmod.DagValidationError):
            dagmod.parse_window_every(bad)


# ---------------------------------------------------------------------------
# combined DAG + device-kernel parity + spec surface
# ---------------------------------------------------------------------------

def test_combined_join_window_topk_quantile(shards):
    frames, tables = shards
    dim = _dim()
    dag = dagmod.compile_query({
        "table": ["x"],
        "groupby": ["region",
                    {"window": {"on": "t", "every": "4h", "alias": "w4"}}],
        "aggs": [
            ["v_int", "sum", "s"],
            ["v_int", "topk", "top2", {"k": 2}],
            ["v_float", "quantile", "med", {"q": 0.5, "alpha": ALPHA}],
        ],
        "where": [["sel", ">", 0.3]],
        "join": {"table": dim, "on": "cust", "select": ["region"]},
    })
    got = _run_dag(tables, dag)
    df = _pandas_side(
        frames, dim=dim, window=("t", "4h", "w4"), where=[("sel", ">", 0.3)]
    ).dropna(subset=["w4"])
    gb = df.groupby(["region", "w4"])
    exp_s = gb["v_int"].sum()
    exp_k = gb["v_int"].apply(lambda s: np.sort(s.to_numpy())[::-1][:2])
    exp_q = gb["v_float"].quantile(0.5, interpolation="lower")
    assert len(got) == len(exp_s)
    for i in range(len(got)):
        key = (got["region"][i], pd.Timestamp(got["w4"][i]))
        assert int(got["s"][i]) == int(exp_s.loc[key])
        np.testing.assert_array_equal(np.asarray(got["top2"][i]),
                                      exp_k.loc[key])
        e = float(exp_q.loc[key])
        assert abs(float(got["med"][i]) - e) <= abs(e) * ALPHA + 1e-9


def test_device_kernels_bit_identical_to_host_twins(monkeypatch):
    """With host routing disabled the executor takes the relops device
    kernels; results must be bit-identical to the host twins."""
    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
    from bqueryd_tpu.ops import relops

    rng = np.random.default_rng(11)
    n = 4000
    codes = rng.integers(-1, 9, n).astype(np.int64)
    mask = rng.random(n) < 0.8
    for vals in (
        rng.integers(-(2**60), 2**60, n),
        np.where(rng.random(n) < 0.1, np.nan, rng.random(n) * 100 - 50),
        rng.random(n) < 0.5,
    ):
        vals = np.asarray(vals)
        for largest in (True, False):
            hv, ho = opexec.topk_flat(codes, vals, 4, largest, 9, mask=mask)
            dv, do = relops.topk_partials(
                codes, vals, 4, largest, 9, mask=mask
            )
            np.testing.assert_array_equal(ho, do)
            np.testing.assert_array_equal(hv, dv)
    vals = rng.random(n) * 1e9 - 5e8
    np.testing.assert_array_equal(
        opexec.sketch_keys_host(vals, ALPHA), relops.sketch_bin(vals, ALPHA)
    )
    pos = rng.integers(-1, 50, 64)
    np.testing.assert_array_equal(
        relops.gather_positions(pos, codes % 64),
        np.where(codes % 64 >= 0, pos[np.maximum(codes % 64, 0)], -1),
    )


def test_spec_validation_surface():
    ok = {"table": ["x"], "groupby": ["g"], "aggs": [["v", "sum", "s"]]}
    assert dagmod.compile_query(ok).is_plain()
    cases = [
        ({**ok, "aggs": [["v", "median", "m"]]}, "UnsupportedOp"),
        ({**ok, "aggs": [["v", "quantile", "m", {"q": 1.5}]]},
         "UnsupportedOp"),
        ({**ok, "aggs": [["v", "topk", "m", {"k": 0}]]}, "UnsupportedOp"),
        ({**ok, "aggs": []}, "InvalidPlan"),
        ({**ok, "groupby": []}, "InvalidPlan"),
        ({**ok, "aggs": [["v", "sum", "g"]]}, "InvalidPlan"),  # collision
        ({**ok, "bogus": 1}, "InvalidPlan"),
        ({**ok, "table": []}, "InvalidPlan"),
    ]
    for spec, klass in cases:
        with pytest.raises(dagmod.DagValidationError) as err:
            dagmod.compile_query(spec)
        assert err.value.error_class == klass, spec


def test_dag_signature_stable_across_deserialization():
    """Object-dtype (string) dimension columns must freeze by VALUE, not
    by PyObject pointer bytes: two deserializations of the same wire DAG
    produce the SAME signature (the worker result-cache key), and a
    different dimension table produces a different one."""
    import pickle

    spec = {
        "table": ["x"], "groupby": ["zone"],
        "aggs": [["v", "sum", "s"]],
        "join": {
            "table": {
                "cust": np.arange(4, dtype=np.int64),
                "zone": np.array(["a", "b", "c", "d"], dtype=object),
            },
            "on": "cust", "select": ["zone"],
        },
    }
    wire = pickle.dumps(dagmod.compile_query(spec).to_wire())
    a = dagmod.OperatorDAG.from_wire(pickle.loads(wire))
    b = dagmod.OperatorDAG.from_wire(pickle.loads(wire))
    assert a.signature() == b.signature()
    other = dagmod.compile_query({
        **spec,
        "join": {
            "table": {
                "cust": np.arange(4, dtype=np.int64),
                "zone": np.array(["a", "b", "c", "e"], dtype=object),
            },
            "on": "cust", "select": ["zone"],
        },
    })
    assert other.signature() != a.signature()


def test_dag_signatures_distinguish_params():
    base = {"table": ["x"], "groupby": ["g"],
            "aggs": [["v", "topk", "t", {"k": 3}]]}
    a = dagmod.compile_query(base)
    b = dagmod.compile_query(
        {**base, "aggs": [["v", "topk", "t", {"k": 4}]]}
    )
    assert a.signature() != b.signature()
    # and a plain groupby never collides with a DAG of the same projection
    plain = dagmod.compile_query(
        {"table": ["x"], "groupby": ["g"], "aggs": [["v", "sum", "s"]]}
    )
    assert plain.signature() != a.signature()


# ---------------------------------------------------------------------------
# e2e: rpc.query over a live cluster + structured errors
# ---------------------------------------------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


@pytest.fixture
def op_cluster(tmp_path, mem_store_url):
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    frames = _dataset(seed=99)[:2]
    for i, df in enumerate(frames):
        ctable.fromdataframe(df, str(tmp_path / f"e2e_{i}.bcolzs"))
    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.1,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url, data_dir=str(tmp_path),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.1, poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(
        lambda: all(
            controller.files_map.get(f"e2e_{i}.bcolzs") for i in range(2)
        ),
        desc="shards advertised",
    )
    rpc = RPC(
        coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
    )
    yield {
        "rpc": rpc, "controller": controller, "worker": worker,
        "frames": frames,
        "shards": [f"e2e_{i}.bcolzs" for i in range(2)],
    }
    controller.running = False
    worker.running = False
    for t in threads:
        t.join(timeout=5)


def test_rpc_query_end_to_end(op_cluster):
    rpc = op_cluster["rpc"]
    frames = op_cluster["frames"]
    dim = _dim()
    df = rpc.query({
        "table": op_cluster["shards"],
        "groupby": ["region"],
        "aggs": [
            ["v_int", "sum", "s"],
            ["v_int", "topk", "t2", {"k": 2}],
            ["v_float", "quantile", "p90", {"q": 0.9, "alpha": ALPHA}],
        ],
        "join": {"table": dim, "on": "cust", "select": ["region"]},
    })
    full = pd.concat(frames).merge(pd.DataFrame(dim), on="cust")
    gb = full.groupby("region")
    exp_s = gb["v_int"].sum().to_dict()
    assert dict(zip(df["region"], df["s"])) == exp_s  # int bit-exact
    exp_k = gb["v_int"].apply(lambda s: sorted(s, reverse=True)[:2])
    exp_q = gb["v_float"].quantile(0.9, interpolation="lower")
    for i, r in enumerate(df["region"]):
        assert list(df["t2"][i]) == exp_k[r]
        e = float(exp_q[r])
        assert abs(float(df["p90"][i]) - e) <= abs(e) * ALPHA + 1e-9
    # DAG queries are autopsy-attributable from day one
    record = rpc.autopsy(rpc.last_trace_id)
    assert record and record["ok"] is True
    assert "join_probe" in "".join(record["segments"].keys()) or (
        record["coverage"] >= 0.5
    )


def test_rpc_query_window_end_to_end(op_cluster):
    rpc = op_cluster["rpc"]
    frames = op_cluster["frames"]
    df = rpc.query({
        "table": op_cluster["shards"],
        "groupby": [{"window": {"on": "t", "every": "1d", "alias": "day"}}],
        "aggs": [["v_int", "sum", "s"]],
    })
    full = pd.concat(frames, ignore_index=True).dropna(subset=["t"])
    exp = full.groupby(full["t"].dt.floor("1d"))["v_int"].sum()
    got = dict(zip(pd.to_datetime(df["day"]), df["s"]))
    assert got == exp.to_dict()


def test_rpc_query_spec_rejected_structured(op_cluster):
    from bqueryd_tpu.rpc import RPCError

    rpc = op_cluster["rpc"]
    # client-side validation fails without a round trip
    with pytest.raises(dagmod.DagValidationError):
        rpc.query({"table": op_cluster["shards"], "groupby": ["g"],
                   "aggs": [["v_int", "median", "m"]]})
    # a spec that passes the client but names an op the controller refuses
    # still comes back structured (drive the controller path directly)
    before = rpc.last_trace_id
    with pytest.raises(RPCError) as err:
        rpc.groupby(op_cluster["shards"], ["g"], [["v_int", "median", "m"]],
                    [])
    assert err.value.error_class == "UnsupportedOp"
    assert "rpc.query" in str(err.value)
    del before


def test_rpc_query_result_cache_hit(op_cluster):
    """An identical repeated DAG query serves from the worker result cache
    (keyed by the DAG signature)."""
    rpc = op_cluster["rpc"]
    spec = {
        "table": op_cluster["shards"], "groupby": ["g"],
        "aggs": [["v_int", "topk", "t", {"k": 3}]],
    }
    a = rpc.query(spec)
    worker = op_cluster["worker"]
    hits_before = worker.result_cache.hits if worker.result_cache else 0
    b = rpc.query(spec)
    assert len(a) == len(b)
    for x, y in zip(a["t"], b["t"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    if worker.result_cache is not None:
        assert worker.result_cache.hits > hits_before
