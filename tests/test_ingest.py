"""Streaming ingest (PR 14): append path, chunk-granular zone-map pruning,
and delta-maintained hot aggregates.

Three layers of coverage:

* storage — per-chunk zone maps, snapshot-consistent mid-append reads,
  torn-append repair, append-safe column-cache keys, ChunkView decode;
* engine/executor — chunk pruning parity (engine, mesh, raw rows, DAG
  pushdown) vs the unpruned path, gates and kill switches;
* cluster — ``rpc.append`` fan-out (replica dedup by (node, data_dir)),
  delta-refreshed repeat queries, incremental stats re-advertisement,
  structured errors (unknown file, disabled, mixed-version).
"""

import logging
import os
import threading

import numpy as np
import pandas as pd
import pytest

from conftest import wait_until

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
from bqueryd_tpu.ops import predicates
from bqueryd_tpu.ops.workingset import (
    DeltaAggCache,
    growth_since,
    table_growth_base,
)
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.plan.stats import (
    StatsCollector,
    gather_table_stats,
    zone_can_match,
)
from bqueryd_tpu.storage.ctable import ChunkView, ctable, table_cache_key


def _frame(n, seed=0, offset=0):
    rng = np.random.RandomState(seed)
    return pd.DataFrame(
        {
            "g": rng.randint(0, 5, n).astype(np.int64),
            "v": rng.randint(-100, 100, n).astype(np.int64),
            "f": rng.random(n).astype(np.float32),
            "s": (rng.randint(0, 3, n)).astype(str),
            "seq": np.arange(offset, offset + n, dtype=np.int64),
            "ts": (
                np.int64(1_700_000_000_000_000_000)
                + np.arange(offset, offset + n, dtype=np.int64)
                * np.int64(60_000_000_000)
            ).view("datetime64[ns]"),
        }
    )


def _finalize(payloads):
    return hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads(list(payloads))
    )


def _sorted(df, keys):
    return df.sort_values(keys).reset_index(drop=True)


# ---------------------------------------------------------------------------
# storage: zone maps, snapshots, cache keys, views
# ---------------------------------------------------------------------------

def test_append_writes_chunk_zone_maps(tmp_path):
    df = _frame(1000)
    t = ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"), chunklen=100)
    maps = t.chunk_zone_maps("seq")
    assert len(maps) == 10
    assert maps[0] == (0, 99) and maps[9] == (900, 999)
    # datetime zone maps are physical int64 ns
    ts_maps = t.chunk_zone_maps("ts")
    assert ts_maps[0][0] == int(df["ts"].iloc[0].value)
    # dict columns carry none
    assert t.chunk_zone_maps("s") is None
    # column-level stats agree with the folded zone maps
    assert t.col_stats("seq") == (0, 999)


def test_zone_maps_skip_nan_and_nat(tmp_path):
    df = pd.DataFrame(
        {
            "f": np.array([np.nan, 1.5, 2.5, np.nan], dtype=np.float64),
            "ts": pd.to_datetime(
                [None, "2024-01-01", "2024-01-02", None]
            ),
        }
    )
    t = ctable.fromdataframe(df, str(tmp_path / "n.bcolzs"), chunklen=2)
    assert t.chunk_zone_maps("f")[0] == (1.5, 1.5)
    # all-NaT chunk carries no zone map (conservatively matches)
    df2 = pd.DataFrame({"f": [np.nan, np.nan], "ts": pd.to_datetime([None, None])})
    ctable(str(tmp_path / "n.bcolzs"), mode="a").append_dataframe(df2)
    t2 = ctable(str(tmp_path / "n.bcolzs"))
    assert t2.chunk_zone_maps("ts")[-1] is None


def test_mid_append_reader_keeps_snapshot(tmp_path):
    """A reader opened mid-append (column index grown, meta.json not yet
    renamed) decodes exactly its committed row-count snapshot."""
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(300), root, chunklen=100)
    torn = ctable(root, mode="a")
    # simulate the torn window: chunk data + column meta written for one
    # column, meta.json row count NOT yet committed
    torn._append_physical("v", np.arange(50, dtype=np.int64))
    reader = ctable(root, mode="r")
    assert reader.nrows == 300
    assert len(reader.column_raw("v")) == 300
    assert len(reader.committed_chunks("v")) == 3


def test_torn_append_repaired_on_next_append(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(300), root, chunklen=100)
    torn = ctable(root, mode="a")
    torn._append_physical("v", np.arange(50, dtype=np.int64))
    # the next real append truncates the uncommitted index entries, so the
    # chunk grid stays synchronized across columns
    appender = ctable(root, mode="a")
    extra = _frame(40, seed=1, offset=300)
    appender.append_dataframe(extra)
    t = ctable(root)
    assert t.nrows == 340
    assert t.chunk_rows() is not None  # consistent grid
    np.testing.assert_array_equal(
        t.column_raw("v")[-40:], extra["v"].to_numpy()
    )
    # every column ends on the same chunk count
    counts = {len(t.committed_chunks(c)) for c in t.names}
    assert len(counts) == 1


def test_column_cache_never_serves_stale_after_append(tmp_path):
    """Satellite: content keys incorporate chunk/row counts, so a reader
    opened pre-append never poisons the cache for post-append readers (and
    vice versa) even though both stat the same grown data file."""
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(200), root, chunklen=100)
    old_reader = ctable(root)
    ctable(root, mode="a").append_dataframe(_frame(100, seed=2, offset=200))
    # the OLD instance decodes (and caches) its 200-row snapshot while the
    # file on disk already holds 300 rows
    assert len(old_reader.column_raw("v")) == 200
    new_reader = ctable(root)
    assert len(new_reader.column_raw("v")) == 300
    # and reading through the old instance again still yields its snapshot
    assert len(old_reader.column_raw("v")) == 200


def test_chunk_view_values_stats_and_identity(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    df = _frame(1000)
    t = ctable.fromdataframe(df, root, chunklen=100)
    view = t.chunk_view([2, 7])
    assert view.nrows == 200
    np.testing.assert_array_equal(
        view.column_raw("seq"),
        np.concatenate([np.arange(200, 300), np.arange(700, 800)]),
    )
    # zone-folded stats over the selection only
    assert view.col_stats("seq") == (200, 299) or view.col_stats("seq") == (
        200, 799,
    )
    assert view.col_stats("seq")[0] == 200
    # dict + datetime logical decode round-trips
    np.testing.assert_array_equal(
        view.column("s"), df["s"].to_numpy(dtype=object)[
            np.r_[200:300, 700:800]
        ],
    )
    assert view.column("ts").dtype == np.dtype("datetime64[ns]")
    # distinct cache identity per selection, parent, and parent growth
    k1 = table_cache_key(view)
    assert k1 != table_cache_key(t.chunk_view([2, 8]))
    assert k1 == table_cache_key(t.chunk_view([2, 7]))
    ctable(root, mode="a").append_dataframe(_frame(10, seed=3, offset=1000))
    t2 = ctable(root)
    assert table_cache_key(t2.chunk_view([2, 7])) != k1


def test_tail_view_boundaries(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(250), root, chunklen=100)
    ctable(root, mode="a").append_dataframe(_frame(70, seed=4, offset=250))
    t = ctable(root)
    tail = t.tail_view(250)
    assert tail is not None and tail.nrows == 70
    np.testing.assert_array_equal(
        tail.column_raw("seq"), np.arange(250, 320)
    )
    assert t.tail_view(240) is None       # not a chunk boundary
    assert t.tail_view(320).nrows == 0    # end-of-table tail is empty


# ---------------------------------------------------------------------------
# stats: zone_can_match + incremental gather
# ---------------------------------------------------------------------------

def test_zone_can_match_matrix():
    assert zone_can_match(10, 20, "==", 15)
    assert not zone_can_match(10, 20, "==", 25)
    assert zone_can_match(10, 20, ">", 15)
    assert not zone_can_match(10, 20, ">", 20)
    assert zone_can_match(10, 20, ">=", 20)
    assert not zone_can_match(10, 20, ">=", 21)
    assert zone_can_match(10, 20, "<", 11)
    assert not zone_can_match(10, 20, "<", 10)
    assert zone_can_match(10, 20, "<=", 10)
    assert not zone_can_match(10, 20, "<=", 9)
    assert zone_can_match(10, 20, "in", [1, 15])
    assert not zone_can_match(10, 20, "in", [1, 25])
    assert zone_can_match(10, 20, "in", [])            # conservative
    # != never prunes (NaN rows satisfy it but are invisible to zone maps)
    assert zone_can_match(10, 10, "!=", 10)
    # incomparable values conservatively match
    assert zone_can_match(10, 20, "==", "oops")


def test_gather_stats_incremental_on_append(tmp_path, monkeypatch):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(400), root, chunklen=100)
    t1 = ctable(root)
    prev = gather_table_stats(t1)
    assert prev["cols"]["v"]["chunks"] == 4
    assert prev["cols"]["s"]["card"] == 3
    ctable(root, mode="a").append_dataframe(
        pd.DataFrame(
            {
                "g": [1], "v": [5000], "f": [0.5], "s": ["zz"],
                "seq": [9999],
                "ts": _frame(1)["ts"],
            }
        )
    )
    t2 = ctable(root)
    # the incremental path must not re-probe unchanged sidecars
    import bqueryd_tpu.plan.stats as stats_mod

    calls = []
    real = stats_mod._sidecar_cardinality
    monkeypatch.setattr(
        stats_mod, "_sidecar_cardinality",
        lambda table, name: calls.append(name) or real(table, name),
    )
    fresh = gather_table_stats(t2, prev=prev)
    assert calls == [], "grown-only columns must skip the sidecar probe"
    assert fresh["rows"] == 401
    assert fresh["cols"]["v"]["max"] == 5000     # folded from the new chunk
    assert fresh["cols"]["v"]["min"] == prev["cols"]["v"]["min"]
    assert fresh["cols"]["v"]["chunks"] == 5
    assert fresh["cols"]["s"]["card"] == 4       # dictionary stays exact
    # parity with the full gather
    full = gather_table_stats(t2)
    assert fresh["cols"]["v"]["min"] == full["cols"]["v"]["min"]
    assert fresh["cols"]["v"]["max"] == full["cols"]["v"]["max"]


def test_gather_stats_rejects_in_place_replacement(tmp_path):
    """An in-place shard replacement with same-or-more chunks must NOT
    pass as an append: the per-column prefix fingerprint fails and the
    gather falls back to full stats — stale min/max folded into fresh
    advertisements would let the controller prune shards whose new rows
    match."""
    root = str(tmp_path / "t.bcolzs")
    old = _frame(400, seed=40)
    old["v"] += 100_000  # old bounds far from the replacement's
    ctable.fromdataframe(old, root, chunklen=100)
    prev = gather_table_stats(ctable(root))
    assert prev["cols"]["v"]["min"] >= 99_000
    # replace in place: same name, MORE chunks, completely different values
    ctable.fromdataframe(_frame(500, seed=41), root, chunklen=100)
    fresh = gather_table_stats(ctable(root), prev=prev)
    full = gather_table_stats(ctable(root))
    assert fresh["cols"]["v"]["min"] == full["cols"]["v"]["min"] < 0
    assert fresh["cols"]["v"]["max"] == full["cols"]["v"]["max"]
    assert fresh["cols"]["s"].get("card") == full["cols"]["s"].get("card")


def test_stats_collector_invalidate_drops_window(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(100), root)
    collector = StatsCollector(min_refresh_s=3600.0)
    first = collector.collect(str(tmp_path), ["t.bcolzs"])
    assert first["t.bcolzs"]["rows"] == 100
    ctable(root, mode="a").append_dataframe(_frame(20, seed=5, offset=100))
    # inside the refresh window: the stale snapshot object is returned
    assert collector.collect(str(tmp_path), ["t.bcolzs"]) is first
    collector.invalidate()
    fresh = collector.collect(str(tmp_path), ["t.bcolzs"])
    assert fresh["t.bcolzs"]["rows"] == 120


# ---------------------------------------------------------------------------
# pruning: selection, gates, parity
# ---------------------------------------------------------------------------

def test_chunk_selection_ops(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    df = _frame(1000)
    t = ctable.fromdataframe(df, root, chunklen=100)
    keep = predicates.chunk_selection(t, [["seq", ">", 850]])
    np.testing.assert_array_equal(keep, np.arange(10) >= 8)
    keep = predicates.chunk_selection(t, [["seq", "==", 250]])
    assert keep.sum() == 1 and keep[2]
    keep = predicates.chunk_selection(t, [["seq", "in", [50, 750]]])
    np.testing.assert_array_equal(np.flatnonzero(keep), [0, 7])
    # conjunction intersects
    keep = predicates.chunk_selection(
        t, [["seq", ">", 450], ["seq", "<=", 650]]
    )
    np.testing.assert_array_equal(np.flatnonzero(keep), [4, 5, 6])
    # datetime terms translate to ns before the zone compare
    cut = pd.Timestamp(df["ts"].iloc[900])
    keep = predicates.chunk_selection(t, [["ts", ">=", cut]])
    np.testing.assert_array_equal(np.flatnonzero(keep), [9])
    # dict columns and != contribute no pruning
    assert predicates.chunk_selection(t, [["s", "==", "1"]]) is None
    assert predicates.chunk_selection(t, [["seq", "!=", 5]]) is None
    # a non-selective term prunes nothing
    assert predicates.chunk_selection(t, [["seq", ">=", 0]]) is None


def test_chunk_pruned_table_gates(tmp_path, monkeypatch):
    root = str(tmp_path / "t.bcolzs")
    t = ctable.fromdataframe(_frame(1000), root, chunklen=100)
    terms = [["seq", ">", 850]]
    view, decoded, skipped = predicates.chunk_pruned_table(t, terms)
    assert isinstance(view, ChunkView) and (decoded, skipped) == (2, 8)
    # kill switch
    monkeypatch.setenv("BQUERYD_TPU_CHUNK_PRUNE", "0")
    same, decoded, skipped = predicates.chunk_pruned_table(t, terms)
    assert same is t and decoded == 0 and skipped == 0
    monkeypatch.delenv("BQUERYD_TPU_CHUNK_PRUNE")
    # selectivity floor: a near-full selection stays unpruned (counted)
    monkeypatch.setenv("BQUERYD_TPU_CHUNK_PRUNE_SELECTIVITY", "0.5")
    same, decoded, skipped = predicates.chunk_pruned_table(
        t, [["seq", ">", 150]]
    )
    assert same is t and (decoded, skipped) == (10, 0)
    # under the floor it prunes again
    view2, decoded, skipped = predicates.chunk_pruned_table(
        t, [["seq", ">", 850]]
    )
    assert isinstance(view2, ChunkView) and (decoded, skipped) == (2, 8)


@pytest.mark.parametrize(
    "terms",
    [
        [["seq", ">", 820]],
        [["seq", "<=", 120], ["v", ">", 0]],
        [["seq", "in", [10, 470, 980]]],
    ],
)
def test_engine_parity_with_chunk_pruning(tmp_path, terms):
    """Pruned execution is bit-identical to the full-table pass: zone maps
    are proofs, and surviving rows keep their order (float reductions see
    the same operand sequence)."""
    root = str(tmp_path / "t.bcolzs")
    df = _frame(2000, seed=7)
    t = ctable.fromdataframe(df, root, chunklen=128)
    query = GroupByQuery(
        ["g"],
        [
            ["v", "sum", "vs"], ["f", "mean", "fm"],
            ["v", "min", "vmin"], ["v", "max", "vmax"],
            ["f", "count", "n"],
        ],
        terms,
    )
    engine = QueryEngine()
    full = engine.execute_local(t, query, strategy="host")
    view, decoded, skipped = predicates.chunk_pruned_table(t, terms)
    assert skipped > 0
    pruned = engine.execute_local(view, query, strategy="host")
    a = _sorted(_finalize([full]), ["g"])
    b = _sorted(_finalize([pruned]), ["g"])
    pd.testing.assert_frame_equal(a, b)
    for col in ("vs", "vmin", "vmax", "n"):
        np.testing.assert_array_equal(
            a[col].to_numpy(), b[col].to_numpy()
        )


def test_raw_rows_chunk_prune_parity(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    df = _frame(1000, seed=8)
    t = ctable.fromdataframe(df, root, chunklen=100)
    terms = [["seq", ">=", 870]]
    query = GroupByQuery(["g"], [["v", "sum", "v"]], terms, aggregate=False)
    engine = QueryEngine()
    full = engine.execute_local(t, query)
    view, _, skipped = predicates.chunk_pruned_table(t, terms)
    assert skipped > 0
    pruned = engine.execute_local(view, query)
    for col in full["order"]:
        np.testing.assert_array_equal(
            np.asarray(full["columns"][col]),
            np.asarray(pruned["columns"][col]),
        )


def test_mesh_executor_accepts_chunk_views(tmp_path):
    """The mesh path runs over views: alignment, wire narrowing and the
    device caches key on the view's own identity."""
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor

    roots = []
    frames = []
    for i in range(2):
        df = _frame(600, seed=20 + i, offset=600 * i)
        root = str(tmp_path / f"s{i}.bcolzs")
        ctable.fromdataframe(df, root, chunklen=100)
        roots.append(root)
        frames.append(df)
    tables = [ctable(r) for r in roots]
    terms = [["seq", ">=", 1000]]
    query = GroupByQuery(
        ["g"], [["v", "sum", "vs"], ["f", "mean", "fm"]], terms
    )
    executor = MeshQueryExecutor()
    full = executor.execute(tables, query)
    pruned_tables = []
    skipped_total = 0
    for t in tables:
        view, _, skipped = predicates.chunk_pruned_table(t, terms)
        pruned_tables.append(view)
        skipped_total += skipped
    assert skipped_total > 0
    pruned = executor.execute(pruned_tables, query)
    a = _sorted(_finalize([full]), ["g"])
    b = _sorted(_finalize([pruned]), ["g"])
    np.testing.assert_array_equal(a["vs"].to_numpy(), b["vs"].to_numpy())
    np.testing.assert_allclose(
        a["fm"].to_numpy(), b["fm"].to_numpy(), rtol=1e-6
    )


# ---------------------------------------------------------------------------
# delta maintenance
# ---------------------------------------------------------------------------

def test_growth_since_validation(tmp_path):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(300), root, chunklen=100)
    base = table_growth_base(ctable(root))
    # no growth -> empty id list
    assert growth_since(base, ctable(root)) == []
    ctable(root, mode="a").append_dataframe(_frame(150, seed=9, offset=300))
    grown = ctable(root)
    assert growth_since(base, grown) == [3, 4]
    # a rewrite (same rows, different bytes) must NOT validate
    ctable.fromdataframe(
        pd.concat(
            [_frame(300, seed=31), _frame(150, seed=32, offset=300)],
            ignore_index=True,
        ),
        root, chunklen=100,
    )
    assert growth_since(base, ctable(root)) is None
    # shrink must not validate either
    small = str(tmp_path / "small.bcolzs")
    ctable.fromdataframe(_frame(100), small, chunklen=100)
    assert growth_since(base, ctable(small)) is None


def test_delta_cache_refresh_parity(tmp_path):
    """delta = merge(cached partial, tail partial) must equal the full
    recompute: ints bit-exact, float means within reassociation ulps."""
    from bqueryd_tpu.models.query import ResultPayload

    root = str(tmp_path / "t.bcolzs")
    df = _frame(2000, seed=11)
    ctable.fromdataframe(df, root, chunklen=256)
    query = GroupByQuery(
        ["g"],
        [
            ["v", "sum", "vs"], ["f", "mean", "fm"],
            ["v", "min", "vmin"], ["v", "max", "vmax"],
        ],
        [["v", ">", -50]],
    )
    engine = QueryEngine()
    t1 = ctable(root)
    base_payload = engine.execute_local(t1, query, strategy="host")
    cache = DeltaAggCache()
    key = ("k",)
    assert cache.store(key, [t1], ResultPayload(base_payload).to_bytes())
    extra = _frame(180, seed=12, offset=2000)
    ctable(root, mode="a").append_dataframe(extra)
    t2 = ctable(root)
    entry = cache.get(key)
    ids = cache.refresh_ids(entry, [t2])
    assert ids == [[8]]
    tail = t2.chunk_view(ids[0])
    assert tail.nrows == 180
    tail_payload = engine.execute_local(tail, query, strategy="host")
    merged = _sorted(
        _finalize(
            [ResultPayload.from_bytes(entry["data"]), tail_payload]
        ),
        ["g"],
    )
    expected_df = pd.concat([df, extra], ignore_index=True)
    expected_df = expected_df[expected_df["v"] > -50]
    expected = _sorted(
        expected_df.groupby("g", as_index=False).agg(
            vs=("v", "sum"), fm=("f", "mean"),
            vmin=("v", "min"), vmax=("v", "max"),
        ),
        ["g"],
    )
    for col in ("vs", "vmin", "vmax"):
        np.testing.assert_array_equal(
            merged[col].to_numpy(), expected[col].to_numpy()
        )
    np.testing.assert_allclose(
        merged["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
    )


def _worker_for(tmp_path, mem_store_url):
    from bqueryd_tpu.worker import WorkerNode

    return WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
    )


def _groupby_msg(filenames, aggs=None, where=None, payload="groupby"):
    from bqueryd_tpu.messages import CalcMessage

    msg = CalcMessage({"payload": payload, "token": "00"})
    msg.set_args_kwargs(
        [
            filenames, ["g"],
            aggs or [["v", "sum", "vs"], ["f", "mean", "fm"]],
            where or [],
        ],
        {},
    )
    return msg


def test_worker_delta_serves_after_append(tmp_path, mem_store_url):
    """The worker path end to end: fresh compute records the delta base; an
    append makes the repeat a delta refresh (effective_strategy 'delta'),
    bit-identical to a from-scratch recompute."""
    root = str(tmp_path / "t.bcolzs")
    df = _frame(1500, seed=13)
    ctable.fromdataframe(df, root, chunklen=256)
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        first = worker.handle_work(_groupby_msg(["t.bcolzs"]))
        assert first.get("effective_strategy") != "delta"
        extra = _frame(120, seed=14, offset=1500)
        ctable(root, mode="a").append_dataframe(extra)
        second = worker.handle_work(_groupby_msg(["t.bcolzs"]))
        assert second.get("effective_strategy") == "delta"
        assert worker.delta_refreshes_total.value == 1
        # parity vs recomputing with delta serving disabled
        os.environ["BQUERYD_TPU_DELTA_SERVE"] = "0"
        try:
            third = worker.handle_work(_groupby_msg(["t.bcolzs"]))
        finally:
            os.environ.pop("BQUERYD_TPU_DELTA_SERVE")
        from bqueryd_tpu.models.query import ResultPayload

        got = _sorted(
            _finalize([ResultPayload.from_bytes(second["data"])]), ["g"]
        )
        want = _sorted(
            _finalize([ResultPayload.from_bytes(third["data"])]), ["g"]
        )
        np.testing.assert_array_equal(
            got["vs"].to_numpy(), want["vs"].to_numpy()
        )
        np.testing.assert_allclose(
            got["fm"].to_numpy(), want["fm"].to_numpy(), rtol=1e-9
        )
    finally:
        worker.socket.close()


def test_worker_delta_ineligible_shapes_recompute(tmp_path, mem_store_url):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(800, seed=15), root, chunklen=128)
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        aggs = [["v", "count_distinct", "vd"]]
        worker.handle_work(_groupby_msg(["t.bcolzs"], aggs=aggs))
        ctable(root, mode="a").append_dataframe(
            _frame(50, seed=16, offset=800)
        )
        reply = worker.handle_work(_groupby_msg(["t.bcolzs"], aggs=aggs))
        assert reply.get("effective_strategy") != "delta"
        assert worker.delta_refreshes_total.value == 0
    finally:
        worker.socket.close()


def test_worker_chunk_prune_counters_and_span(tmp_path, mem_store_url):
    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(1200, seed=17), root, chunklen=100)
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        msg = _groupby_msg(["t.bcolzs"], where=[["seq", ">", 1050]])
        reply = worker.handle_work(msg)
        assert worker.chunks_skipped_total.value >= 9
        assert worker.chunks_decoded_total.value >= 1
        spans = reply.get("spans") or []
        prune = [s for s in spans if s.get("name") == "prune"]
        assert prune and prune[0]["tags"]["chunks_skipped"] >= 9
    finally:
        worker.socket.close()


# ---------------------------------------------------------------------------
# cluster: rpc.append fan-out + serving behaviour
# ---------------------------------------------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture
def ingest_cluster(tmp_path, mem_store_url):
    """Controller + one calc worker serving one chunked shard."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC

    df = _frame(3000, seed=18)
    ctable.fromdataframe(
        df, str(tmp_path / "t.bcolzs"), chunklen=256
    )
    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    worker = _worker_for(tmp_path, mem_store_url)
    worker.heartbeat_interval = 0.1
    worker.poll_timeout = 0.05
    threads = _start(controller, worker)
    wait_until(
        lambda: "t.bcolzs" in controller.files_map,
        desc="shard registration",
    )
    rpc = RPC(
        coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
    )
    yield {
        "rpc": rpc, "controller": controller, "worker": worker,
        "df": df, "tmp_path": tmp_path,
    }
    _stop([controller, worker], threads)


def test_rpc_append_end_to_end(ingest_cluster):
    rpc = ingest_cluster["rpc"]
    controller = ingest_cluster["controller"]
    worker = ingest_cluster["worker"]
    df = ingest_cluster["df"]
    q = (
        ["t.bcolzs"], ["g"],
        [["v", "sum", "vs"], ["f", "mean", "fm"], ["v", "min", "vmin"]],
        [],
    )
    r1 = rpc.groupby(*q)
    extra = _frame(240, seed=19, offset=3000)
    res = rpc.append("t.bcolzs", extra)
    assert res["appended"] == 240
    assert len(res["holders"]) == 1
    assert controller.counters["append_requests"] == 1
    assert controller.counters["append_dispatches"] == 1
    # the repeat query reflects the appended rows via a delta refresh
    r2 = rpc.groupby(*q)
    assert rpc.last_call_strategies["effective"]["t.bcolzs"] == "delta"
    assert worker.delta_refreshes_total.value == 1
    full = pd.concat([df, extra], ignore_index=True)
    expected = _sorted(
        full.groupby("g", as_index=False).agg(
            vs=("v", "sum"), fm=("f", "mean"), vmin=("v", "min")
        ),
        ["g"],
    )
    got = _sorted(r2, ["g"])
    np.testing.assert_array_equal(
        got["vs"].to_numpy(), expected["vs"].to_numpy()
    )
    np.testing.assert_allclose(
        got["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
    )
    assert len(r1) == len(r2)
    # fresh stats re-advertise with the grown row count
    wait_until(
        lambda: (controller.shard_stats.get("t.bcolzs") or {}).get("rows")
        == 3240,
        desc="post-append stats re-advertisement",
    )


def test_rpc_append_unknown_file(ingest_cluster):
    from bqueryd_tpu.rpc import RPCError

    with pytest.raises(RPCError, match="not served by any worker"):
        ingest_cluster["rpc"].append("nope.bcolzs", _frame(5))


def test_rpc_append_disabled_worker(ingest_cluster, monkeypatch):
    from bqueryd_tpu.rpc import RPCError

    monkeypatch.setenv("BQUERYD_TPU_APPEND", "0")
    with pytest.raises(RPCError, match="streaming append disabled"):
        ingest_cluster["rpc"].append("t.bcolzs", _frame(5))


def test_rpc_append_mixed_version_rejected(ingest_cluster, monkeypatch):
    """A pre-PR-14 worker rejects the verb with its base unhandled-payload
    traceback; the controller rewrites it into the structured
    UnsupportedVerb error."""
    from bqueryd_tpu.rpc import RPCError
    from bqueryd_tpu.worker import WorkerNode

    def legacy(self, msg):
        raise ValueError(
            f"unhandled message payload {msg.get('payload')!r}"
        )

    monkeypatch.setattr(WorkerNode, "_append_rows", legacy)
    with pytest.raises(RPCError, match="UnsupportedVerb"):
        ingest_cluster["rpc"].append("t.bcolzs", _frame(5))


def test_rpc_append_dedupes_shared_datadir(tmp_path, mem_store_url):
    """Two workers serving the SAME (node, data_dir) are one physical
    replica: the append applies once, not twice."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC

    root = str(tmp_path / "t.bcolzs")
    ctable.fromdataframe(_frame(500, seed=21), root, chunklen=100)
    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    w1 = _worker_for(tmp_path, mem_store_url)
    w2 = _worker_for(tmp_path, mem_store_url)
    for w in (w1, w2):
        w.heartbeat_interval = 0.1
        w.poll_timeout = 0.05
    threads = _start(controller, w1, w2)
    try:
        wait_until(
            lambda: len(controller.files_map.get("t.bcolzs") or ()) == 2,
            desc="both workers advertising",
        )
        rpc = RPC(
            coordination_url=mem_store_url, timeout=30,
            loglevel=logging.WARNING,
        )
        res = rpc.append("t.bcolzs", _frame(50, seed=22, offset=500))
        assert len(res["holders"]) == 1, "shared data_dir = one append"
        assert ctable(root).nrows == 550
    finally:
        _stop([controller, w1, w2], threads)


def test_dag_query_chunk_prune_parity(ingest_cluster):
    """Satellite: rpc.query pushdown predicates ride the same chunk mask;
    results match the unpruned path exactly."""
    rpc = ingest_cluster["rpc"]
    worker = ingest_cluster["worker"]
    spec = {
        "table": ["t.bcolzs"],
        "groupby": ["g"],
        "aggs": [["v", "sum", "vs"], ["v", "topk", "top2", {"k": 2}]],
        "where": [["seq", ">", 2700]],
    }
    before = worker.chunks_skipped_total.value
    pruned = rpc.query(spec)
    assert worker.chunks_skipped_total.value > before
    os.environ["BQUERYD_TPU_CHUNK_PRUNE"] = "0"
    try:
        full = rpc.query(spec)
    finally:
        os.environ.pop("BQUERYD_TPU_CHUNK_PRUNE")
    a = _sorted(pruned, ["g"])
    b = _sorted(full, ["g"])
    np.testing.assert_array_equal(
        a["vs"].to_numpy(), b["vs"].to_numpy()
    )
    for x, y in zip(a["top2"], b["top2"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
