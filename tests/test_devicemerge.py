"""Device-resident distributed merge over the ICI mesh (ISSUE 7).

Covers the span partitioner and bucketized partial emission, parity of the
span-owned reduce-scatter merge against the ``BQUERYD_TPU_DEVICE_MERGE=0``
hostmerge fallback across the fuzz-shaped dtype mix (limb-straddling int64,
narrow-wire min/max, float32 mean, float64 sum), the kill switch actually
routing through ``hostmerge.merge_payloads``, the D2H byte accounting, the
``merge_mode`` reply/envelope key end to end through a real cluster, and
the per-leaf (unpacked) fetch variant.
"""

import logging
import os
import threading

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery
from bqueryd_tpu.parallel import devicemerge, hostmerge
from bqueryd_tpu.parallel.executor import MeshQueryExecutor, make_mesh
from bqueryd_tpu.storage.ctable import ctable

N_SHARDS = 3


# -- span partitioner + bucketized emission ----------------------------------

def test_bucket_span_math():
    assert devicemerge.bucket_span(24, 8) == (3, 24)
    assert devicemerge.bucket_span(9, 8) == (2, 16)
    assert devicemerge.bucket_span(1, 8) == (1, 8)
    assert devicemerge.bucket_span(0, 8) == (1, 8)   # empty table: 1 slot
    assert devicemerge.bucket_span(7, 1) == (7, 7)
    # every group lands in exactly one device's contiguous span
    for n_groups, n_dev in ((9, 8), (70_225, 8), (5, 3)):
        span, padded = devicemerge.bucket_span(n_groups, n_dev)
        assert padded >= n_groups
        assert span * n_dev == padded
        owners = [g // span for g in range(n_groups)]
        assert max(owners) < n_dev


def test_bucketize_partials_pads_past_real_groups():
    from bqueryd_tpu import ops

    codes = np.array([0, 1, 2, 2, 4, 1], dtype=np.int32)
    vals = np.array([10, -3, 7, 1, 2, 5], dtype=np.int64)
    n_groups = 5
    padded, span = ops.bucketize_partials(
        ops.partial_tables(codes, (vals,), ("sum",), n_groups), n_groups, 8
    )
    assert span == 1
    rows = np.asarray(padded["rows"])
    assert rows.shape == (8,)
    np.testing.assert_array_equal(rows[:5], [1, 2, 2, 0, 1])
    np.testing.assert_array_equal(rows[5:], 0)  # pad tail: no real group
    sums = np.asarray(padded["aggs"][0]["sum"])
    np.testing.assert_array_equal(sums[:5], [10, 2, 8, 0, 2])
    np.testing.assert_array_equal(sums[5:], 0)


def test_partial_tables_bucketized_matches_flat_emission():
    from bqueryd_tpu import ops

    rng = np.random.default_rng(5)
    codes = rng.integers(-1, 11, 4_000).astype(np.int32)
    vals = rng.integers(-(2**60), 2**60, 4_000).astype(np.int64)
    flat = ops.partial_tables(codes, (vals,), ("sum",), 11)
    bucketized, span = ops.partial_tables_bucketized(
        codes, (vals,), ("sum",), 11, 8
    )
    assert span == 2
    np.testing.assert_array_equal(
        np.asarray(bucketized["aggs"][0]["sum"])[:11],
        np.asarray(flat["aggs"][0]["sum"]),
    )


# -- device merge vs host fallback parity ------------------------------------

@pytest.fixture(scope="module")
def merge_shards(tmp_path_factory):
    """Fuzz-shaped dtype mix: limb-straddling int64 sums, narrow-wire
    (int8) min/max, float32 NaN means, float64 sums, string keys."""
    rng = np.random.default_rng(17)
    n = 9_000
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 23, n).astype(np.int64),
            "k_str": rng.choice(["a", "b", "c", None], n,
                                p=[0.4, 0.3, 0.2, 0.1]),
            "big": rng.integers(-(2**60), 2**60, n).astype(np.int64),
            "small": rng.integers(-100, 100, n).astype(np.int64),
            "f32": np.where(
                rng.random(n) < 0.05, np.nan, rng.random(n) * 100
            ).astype(np.float32),
            "f64": rng.random(n).astype(np.float64),
            "sel": rng.random(n).astype(np.float64),
        }
    )
    base = tmp_path_factory.mktemp("devmerge")
    tables = []
    for i in range(N_SHARDS):
        root = str(base / f"dm{i}.bcolzs")
        ctable.fromdataframe(df.iloc[i::N_SHARDS].reset_index(drop=True), root)
        tables.append(ctable(root))
    return df, tables


MERGE_CASES = [
    (["g"], [["big", "sum", "s"]], []),
    (["g"], [["small", "min", "lo"], ["small", "max", "hi"],
             ["big", "count", "n"]], []),
    (["g"], [["f32", "mean", "m32"], ["f64", "sum", "s64"]], []),
    (["k_str"], [["big", "sum", "s"], ["f32", "mean", "m"]], []),
    (["g"], [["big", "sum", "s"]], [["sel", ">", 0.5]]),
]


def _run_mode(tables, query, enabled, monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "1" if enabled else "0")
    ex = MeshQueryExecutor(mesh=make_mesh())
    payload = ex.execute(tables, query)
    assert ex.last_merge_mode == ("device" if enabled else "host")
    df = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    )
    return df.sort_values(query.groupby_cols).reset_index(drop=True)


def _assert_mode_parity(dev, host, query):
    assert list(dev.columns) == list(host.columns)
    assert len(dev) == len(host)
    for col in dev.columns:
        a, b = dev[col].to_numpy(), host[col].to_numpy()
        if np.asarray(a).dtype.kind in "iub" or col in query.groupby_cols:
            # integer aggregates (the north-star axis) and keys: bit-exact
            np.testing.assert_array_equal(a, b)
        else:
            # float sums reassociate across the reduce-scatter vs the host
            # merge's sequential fold: equal to reassociation ulps
            np.testing.assert_allclose(
                np.asarray(a, dtype=np.float64),
                np.asarray(b, dtype=np.float64), rtol=1e-9, equal_nan=True,
            )


@pytest.mark.parametrize("case", range(len(MERGE_CASES)))
def test_device_merge_matches_host_fallback(merge_shards, monkeypatch, case):
    """The span-owned collective merge and the BQUERYD_TPU_DEVICE_MERGE=0
    hostmerge fallback must agree: bit-identical integers, reassociation
    ulps on floats — and both must match pandas."""
    df, tables = merge_shards
    gcols, aggs, where = MERGE_CASES[case]
    query = GroupByQuery(gcols, aggs, where, aggregate=True)
    dev = _run_mode(tables, query, True, monkeypatch)
    host = _run_mode(tables, query, False, monkeypatch)
    _assert_mode_parity(dev, host, query)

    sel = df
    for col, op, val in where:
        assert op == ">"
        sel = sel[sel[col] > val]
    g = sel.groupby(gcols[0], dropna=True)
    in_col, op, out_col = aggs[0]
    expect = getattr(g[in_col], {"sum": "sum", "min": "min", "max": "max",
                                 "mean": "mean", "count": "count"}[op])()
    got = dev.set_index(gcols[0])[out_col]
    if expect.dtype.kind in "iu" and op != "mean":
        np.testing.assert_array_equal(
            got.to_numpy(), expect.loc[got.index].to_numpy()
        )
    else:
        np.testing.assert_allclose(
            got.to_numpy(dtype=np.float64),
            expect.loc[got.index].to_numpy(dtype=np.float64),
            rtol=1e-5, equal_nan=True,
        )


def test_kill_switch_routes_through_hostmerge(merge_shards, monkeypatch):
    """=0 must actually call hostmerge.merge_payloads (per-device payloads);
    =1 must not touch it inside the executor."""
    _df, tables = merge_shards
    query = GroupByQuery(["g"], [["big", "sum", "s"]])
    calls = []
    real = hostmerge.merge_payloads

    def spy(payloads):
        calls.append(len(payloads))
        return real(payloads)

    monkeypatch.setattr(hostmerge, "merge_payloads", spy)

    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "1")
    MeshQueryExecutor(mesh=make_mesh()).execute(tables, query)
    assert calls == [], "device merge must not host-merge anything"

    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "0")
    MeshQueryExecutor(mesh=make_mesh()).execute(tables, query)
    assert calls and calls[0] == 8, (
        "kill switch must merge one payload per mesh device via hostmerge"
    )


def test_device_merge_byte_accounting(merge_shards, monkeypatch):
    """Device mode fetches a fraction of the host-gather bytes and records
    the saving; host mode fetches every device's full table."""
    _df, tables = merge_shards
    query = GroupByQuery(["g"], [["big", "sum", "s"]])
    stats = devicemerge.stats()

    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "1")
    before = stats.snapshot()
    MeshQueryExecutor(mesh=make_mesh()).execute(tables, query)
    mid = stats.snapshot()
    dev_fetched = (
        mid["bytes_fetched"]["device"] - before["bytes_fetched"]["device"]
    )
    dev_saved = mid["d2h_bytes_saved"] - before["d2h_bytes_saved"]
    assert mid["queries"]["device"] == before["queries"]["device"] + 1
    assert dev_fetched > 0
    assert dev_saved > 0, "an 8-device span merge must save per-device bytes"

    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "0")
    MeshQueryExecutor(mesh=make_mesh()).execute(tables, query)
    after = stats.snapshot()
    host_fetched = (
        after["bytes_fetched"]["host"] - mid["bytes_fetched"]["host"]
    )
    assert after["queries"]["host"] == mid["queries"]["host"] + 1
    # host-gather moves every device's table: ~n_dev x the span fetch
    assert host_fetched > 4 * dev_fetched


def test_device_merge_per_leaf_fetch(merge_shards, monkeypatch):
    """BQUERYD_TPU_PACKED_FETCH=0 (per-leaf device_get) under device merge
    must produce the identical table."""
    _df, tables = merge_shards
    query = GroupByQuery(
        ["g"], [["big", "sum", "s"], ["small", "min", "lo"]]
    )
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "1")
    packed = _run_mode(tables, query, True, monkeypatch)
    monkeypatch.setenv("BQUERYD_TPU_PACKED_FETCH", "0")
    unpacked = _run_mode(tables, query, True, monkeypatch)
    for col in packed.columns:
        np.testing.assert_array_equal(
            packed[col].to_numpy(), unpacked[col].to_numpy()
        )


def test_resolve_mode_contract(monkeypatch):
    monkeypatch.delenv("BQUERYD_TPU_DEVICE_MERGE", raising=False)
    assert devicemerge.resolve_mode() == devicemerge.MODE_DEVICE
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "0")
    assert devicemerge.resolve_mode() == devicemerge.MODE_HOST
    # multi-host pods pin the replicated-psum contract regardless
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "1")
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert devicemerge.resolve_mode() == devicemerge.MODE_PSUM


def test_merge_stats_thread_safety():
    stats = devicemerge.MergeStats()

    def pound():
        for _ in range(500):
            stats.record("device", 100, saved=700)
            stats.record("host", 800)

    threads = [threading.Thread(target=pound) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = stats.snapshot()
    assert snap["bytes_fetched"]["device"] == 4 * 500 * 100
    assert snap["bytes_fetched"]["host"] == 4 * 500 * 800
    assert snap["d2h_bytes_saved"] == 4 * 500 * 700
    stats.reset()
    assert stats.snapshot()["queries"] == {"device": 0, "host": 0}


# -- merge_mode on the wire, end to end --------------------------------------

@pytest.fixture(scope="module")
def merge_cluster(tmp_path_factory):
    """Controller + one calc worker over real zmq (the reference's own test
    topology), with a sharded table set."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    from tests.conftest import wait_until

    rng = np.random.default_rng(23)
    n = 6_000
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 9, n).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, n).astype(np.int64),
        }
    )
    root = tmp_path_factory.mktemp("devmerge_cluster")
    names = []
    for i in range(4):
        name = f"dm-{i}.bcolzs"
        ctable.fromdataframe(df.iloc[i::4].reset_index(drop=True),
                             str(root / name))
        names.append(name)

    url = f"mem://devmerge-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url, loglevel=logging.WARNING,
        runfile_dir=str(root), heartbeat_interval=0.2,
    )
    worker = WorkerNode(
        coordination_url=url, data_dir=str(root), loglevel=logging.WARNING,
        restart_check=False, heartbeat_interval=0.2, poll_timeout=0.1,
    )
    threads = [
        threading.Thread(target=node.go, daemon=True)
        for node in (controller, worker)
    ]
    for t in threads:
        t.start()
    wait_until(
        lambda: len(controller.files_map) >= len(names),
        desc="worker shard registration",
    )
    rpc = RPC(coordination_url=url, timeout=60, loglevel=logging.WARNING)
    yield df, names, rpc, controller, worker
    for node in (controller, worker):
        node.running = False
    for t in threads:
        t.join(timeout=5)


def test_merge_mode_rides_the_wire(merge_cluster, monkeypatch):
    """A batched groupby reports merge_mode=device per shard group; the
    kill switch flips every (now per-shard) reply to host/none, results
    stay identical, and the controller counts reply payload bytes."""
    df, names, rpc, controller, worker = merge_cluster
    monkeypatch.delenv("BQUERYD_TPU_DEVICE_MERGE", raising=False)
    expect = (
        df.groupby("g", as_index=False)["v"].sum()
        .rename(columns={"v": "s"})
    )

    got_dev = rpc.groupby(names, ["g"], [["v", "sum", "s"]], [])
    modes = rpc.last_call_merge_modes
    assert modes and all(m == "device" for m in modes.values()), modes
    got_dev = got_dev.sort_values("g").reset_index(drop=True)
    np.testing.assert_array_equal(
        got_dev["s"].to_numpy(), expect["s"].to_numpy()
    )

    bytes_before = controller.counters["reply_payload_bytes"]
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_MERGE", "0")
    got_host = rpc.groupby(names, ["g"], [["v", "sum", "s"]], [])
    host_bytes = controller.counters["reply_payload_bytes"] - bytes_before
    modes = rpc.last_call_merge_modes
    # the kill switch un-batches: one reply per shard, merged host-side
    assert modes and len(modes) == len(names), modes
    assert all(m in ("host", "none") for m in modes.values()), modes
    got_host = got_host.sort_values("g").reset_index(drop=True)
    np.testing.assert_array_equal(
        got_host["s"].to_numpy(), expect["s"].to_numpy()
    )
    assert host_bytes > 0
    # the worker-side histogram twin observed the same replies
    snap = worker.metrics.histogram_snapshot()["bqueryd_tpu_reply_bytes"]
    assert sum(sum(e["counts"]) for e in snap) >= len(names)
