"""Fault-plan semantics: the determinism contract the chaos bench leans on.

The cluster-level scenarios (die-after-ack failover, reply dedup, redis
partition) live in tests/test_cluster_resilience.py; these tests pin the
plan model itself — parsing, matching, trigger bookkeeping, seeded
determinism, the disarmed no-op path — plus the RPC backoff math and the
coordination-store partition seam.
"""

import json
import threading
import time

import pytest

from bqueryd_tpu import chaos
from bqueryd_tpu.chaos import plan as chaos_plan


def _plan(*faults, seed=0):
    return {"seed": seed, "faults": list(faults)}


# -- arming & parsing --------------------------------------------------------

def test_disarmed_fire_is_none_and_free():
    chaos._reset_for_tests()
    assert chaos.enabled() is False
    assert chaos.fire("worker.execute", verb="groupby") is None
    assert chaos.injected_total() == 0


def test_arm_from_dict_inline_json_and_path(tmp_path):
    spec = _plan({"site": "worker.execute", "action": "delay",
                  "args": {"seconds": 0}})
    for form in (
        spec,
        json.dumps(spec),
        str(tmp_path / "plan.json"),
    ):
        if isinstance(form, str) and not form.startswith("{"):
            (tmp_path / "plan.json").write_text(json.dumps(spec))
        plan = chaos.arm(form)
        assert isinstance(plan, chaos.FaultPlan)
        assert chaos.enabled()
        chaos.disarm()
    assert not chaos.enabled()


def test_maybe_arm_from_env(monkeypatch):
    spec = json.dumps(_plan(
        {"site": "rpc.call", "action": "delay", "args": {"seconds": 0}}
    ))
    monkeypatch.setenv("BQUERYD_TPU_FAULT_PLAN", spec)
    assert chaos.maybe_arm_from_env() is not None
    assert chaos.enabled()
    # unset leaves the armed plan alone (bench arms programmatically and
    # then constructs nodes, each of which calls maybe_arm_from_env)
    monkeypatch.delenv("BQUERYD_TPU_FAULT_PLAN")
    assert chaos.maybe_arm_from_env() is not None
    assert chaos.enabled()


@pytest.mark.parametrize("bad", [
    "not json {",
    {"faults": []},
    {"faults": "nope"},
    {"seed": 1},
    {"faults": [{"site": "no.such.site", "action": "delay"}]},
    {"faults": [{"site": "worker.execute", "action": "partition"}]},
    # 'raise' is interpreted by fire() but only LEGAL where the seam
    # catches it — at controller.dispatch it would lose the popped
    # message (never inflight, never requeued) instead of injecting
    {"faults": [{"site": "controller.dispatch", "action": "raise"}]},
    {"faults": [{"site": "controller.reply", "action": "raise"}]},
    {"faults": [{"site": "rpc.call", "action": "raise"}]},
    {"faults": [{"site": "coordination.store", "action": "raise"}]},
    {"faults": [{"site": "worker.execute", "action": "raise",
                 "banana": 1}]},
    {"faults": [{"site": "worker.execute"}]},
    {"typo_top_level": 1, "faults": [
        {"site": "worker.execute", "action": "raise"}]},
])
def test_malformed_plans_fail_loudly_at_arm_time(bad):
    with pytest.raises(chaos.FaultPlanError):
        chaos.arm(bad)
    # a missing plan file must not silently inject nothing either
    with pytest.raises(chaos.FaultPlanError):
        chaos.arm("/nonexistent/fault_plan.json")


# -- trigger semantics -------------------------------------------------------

def test_match_fnmatch_strings_and_equality():
    chaos.arm(_plan({
        "site": "worker.execute", "action": "wedge",
        "match": {"verb": "group*", "attempt": 2},
    }))
    assert chaos.fire("worker.execute", verb="groupby", attempt=1) is None
    assert chaos.fire("worker.execute", verb="sleep", attempt=2) is None
    # missing context key = no match (never a crash)
    assert chaos.fire("worker.execute", attempt=2) is None
    fault = chaos.fire("worker.execute", verb="groupby", attempt=2)
    assert fault is not None and fault.action == "wedge"


def test_times_after_every_counters():
    chaos.arm(_plan({
        "site": "controller.dispatch", "action": "drop",
        "after": 1, "every": 2, "times": 2,
    }))
    fired = [
        chaos.fire("controller.dispatch") is not None for _ in range(8)
    ]
    # skip 1, then every 2nd match, at most 2 fires
    assert fired == [False, True, False, True, False, False, False, False]


def test_seeded_probability_is_deterministic():
    def run(seed):
        chaos.arm(_plan(
            {"site": "rpc.call", "action": "timeout", "probability": 0.5},
            seed=seed,
        ))
        return tuple(
            chaos.fire("rpc.call") is not None for _ in range(32)
        )

    a, b = run(7), run(7)
    assert a == b, "same seed must replay the same decisions"
    assert any(a) and not all(a), "p=0.5 over 32 draws should mix"
    assert run(8) != a, "a different seed should decide differently"


def test_window_semantics_open_fire_exhaust():
    chaos.arm(_plan({
        "site": "coordination.store", "action": "partition",
        "window_s": 0.15,
    }))
    assert chaos.fire("coordination.store", op="smembers") is not None
    assert chaos.fire("coordination.store", op="sadd") is not None
    time.sleep(0.2)
    # window closed: exhausted for good, not re-opened
    assert chaos.fire("coordination.store", op="smembers") is None
    assert chaos.fire("coordination.store", op="smembers") is None


def test_window_honors_times_every_and_probability():
    """times/every/probability gate matches INSIDE an open window too — a
    windowed rule armed at 10% must not silently inject at 100%."""
    chaos.arm(_plan({
        "site": "coordination.store", "action": "partition",
        "window_s": 30.0, "times": 2,
    }))
    fired = [
        chaos.fire("coordination.store", op="smembers") is not None
        for _ in range(6)
    ]
    assert fired == [True, True, False, False, False, False]

    chaos.arm(_plan({
        "site": "coordination.store", "action": "partition",
        "window_s": 30.0, "every": 3,
    }))
    fired = [
        chaos.fire("coordination.store", op="smembers") is not None
        for _ in range(7)
    ]
    assert fired == [True, False, False, True, False, False, True]

    # probability inside the window: deterministic per seed, not all-fire
    def run(seed):
        chaos.arm(_plan(
            {"site": "coordination.store", "action": "partition",
             "window_s": 30.0, "probability": 0.5},
            seed=seed,
        ))
        return [
            chaos.fire("coordination.store", op="smembers") is not None
            for _ in range(32)
        ]

    a, b = run(7), run(7)
    assert a == b, "same seed must replay the same windowed decisions"
    assert any(a) and not all(a), "p=0.5 over 32 in-window draws should mix"


def test_site_patterns_and_first_match_wins():
    chaos.arm(_plan(
        {"site": "worker.*", "action": "delay", "args": {"seconds": 0},
         "match": {"verb": "sleep"}},
        {"site": "worker.execute", "action": "wedge"},
    ))
    # rule 0 matches (delay, handled inline -> None returned)
    assert chaos.fire("worker.execute", verb="sleep") is None
    # rule 0 mismatches, rule 1 fires
    fault = chaos.fire("worker.execute", verb="groupby")
    assert fault is not None and fault.action == "wedge"


def test_generic_raise_action_and_error_taxonomy():
    chaos.arm(_plan(
        {"site": "worker.device", "action": "raise",
         "args": {"error": "DeviceBusyError", "message": "busy!"}},
    ))
    with pytest.raises(chaos.DeviceBusyError, match="busy!"):
        chaos.fire("worker.device")
    assert issubclass(chaos.DeviceBusyError, chaos.TransientError)
    assert not issubclass(chaos.FaultInjected, chaos.TransientError)
    # unknown error name degrades to the non-transient FaultInjected
    chaos.arm(_plan(
        {"site": "worker.device", "action": "raise",
         "args": {"error": "NoSuchClass"}},
    ))
    with pytest.raises(chaos.FaultInjected):
        chaos.fire("worker.device")


def test_stats_count_injected_faults():
    chaos._reset_for_tests()
    chaos.arm(_plan(
        {"site": "worker.execute", "action": "wedge", "times": 2},
    ))
    chaos.fire("worker.execute")
    chaos.fire("worker.execute")
    chaos.fire("worker.execute")  # exhausted: not counted
    assert chaos.injected_total() == 2
    assert chaos.site_stats() == {"worker.execute": 2}
    assert chaos.plan_stats()[0]["fired"] == 2
    assert chaos.plan_stats()[0]["matched"] == 3
    chaos.disarm()
    # stats survive disarm (the bench reads them after a scenario)
    assert chaos.injected_total() == 2


def test_rule_counters_are_thread_safe():
    chaos.arm(_plan({
        "site": "controller.dispatch", "action": "drop", "times": 50,
    }))
    hits = []
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        for _ in range(25):
            if chaos.fire("controller.dispatch") is not None:
                hits.append(1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 50, "times cap must hold exactly under contention"


# -- the coordination partition seam ----------------------------------------

def test_chaos_store_partitions_one_node(mem_store_url):
    from bqueryd_tpu.coordination import (
        StorePartitioned,
        chaos_store,
        coordination_store,
    )

    victim = chaos_store(coordination_store(mem_store_url), node_id="w-a")
    bystander = chaos_store(coordination_store(mem_store_url), node_id="w-b")
    victim.sadd("k", "1")  # disarmed: plain delegation
    assert victim.smembers("k") == {"1"}
    chaos.arm(_plan({
        "site": "coordination.store", "action": "partition",
        "match": {"node": "w-a"}, "window_s": 30,
    }))
    with pytest.raises(StorePartitioned):
        victim.smembers("k")
    # the partition is PER NODE: the other store keeps working, as does
    # the victim's zmq plane (nothing here touches sockets)
    assert bystander.smembers("k") == {"1"}
    chaos.disarm()
    assert victim.smembers("k") == {"1"}


def test_chaos_store_partitions_inflight_locks(mem_store_url):
    """The ``lock`` factory hands back a proxy, not a bare StoreLock: a
    partition window must kill acquire/extend/release on a lock taken
    BEFORE the window opened — a real Redis partition takes in-flight
    locks, not just new ``store.lock(...)`` calls."""
    from bqueryd_tpu.coordination import (
        StorePartitioned,
        chaos_store,
        coordination_store,
    )

    victim = chaos_store(coordination_store(mem_store_url), node_id="w-a")
    lock = victim.lock("dl-ticket", ttl=30)
    assert lock.acquire(blocking=False)  # disarmed: plain delegation
    chaos.arm(_plan({
        "site": "coordination.store", "action": "partition",
        "match": {"node": "w-a"}, "window_s": 30,
    }))
    with pytest.raises(StorePartitioned):
        lock.extend(30)
    with pytest.raises(StorePartitioned):
        lock.release()
    with pytest.raises(StorePartitioned):
        victim.lock("dl-ticket-2", ttl=30).acquire(blocking=False)
    chaos.disarm()
    lock.release()


# -- RPC client backoff (satellite) -----------------------------------------

def test_rpc_backoff_delay_grows_caps_and_jitters_deterministically():
    from bqueryd_tpu.rpc import RPC

    client = RPC.__new__(RPC)  # no sockets: just the backoff math
    client.identity = "deadbeef00000000"
    delays = [client._backoff_delay(a) for a in range(1, 10)]
    # every delay is its exponential base stretched by at most 25% jitter
    # (jitter varies per attempt, so the raw sequence need not be strictly
    # monotonic once the cap flattens the base)
    base = RPC.BACKOFF_BASE_S
    cap = RPC.BACKOFF_CAP_S
    for attempt, delay in zip(range(1, 10), delays):
        expected = min(base * (2 ** (attempt - 1)), cap)
        assert expected <= delay <= expected * 1.25, (attempt, delay)
    # deterministic: same identity + attempt -> same delay
    assert delays == [client._backoff_delay(a) for a in range(1, 10)]
    # different identity -> different jitter stream (almost surely)
    other = RPC.__new__(RPC)
    other.identity = "feedface00000000"
    assert [other._backoff_delay(a) for a in range(1, 10)] != delays
