"""PR 10 observability tier: critical-path attribution (rpc.autopsy), SLO
accounting (per-class margin histograms + burn rates), the controller
timeline ring (rpc.timeline), per-member bundle shares, and the
span-coverage lint — plus the e2e acceptance path: a live cluster whose
queries autopsy with >= 95% coverage and whose client folds its own
deserialize wall into the fetched record."""

import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from conftest import wait_until

from bqueryd_tpu import obs
from bqueryd_tpu.obs import slo
from bqueryd_tpu.obs.metrics import MetricsRegistry, quantile_from_snapshot


def span(name, start, dur, tags=None, trace_id="t1"):
    return obs.make_span(trace_id, name, start, dur, tags=tags)


def timeline(spans, trace_id="t1", ok=True):
    return {"trace_id": trace_id, "ok": ok, "spans": spans}


def total_of(record):
    return sum(record["segments"].values()) + record["unattributed_s"]


# -- attribution sweep --------------------------------------------------------

def test_attribute_simple_decomposition():
    t0 = 1000.0
    record = slo.attribute(timeline([
        span("groupby", t0, 1.0),
        span("admission", t0, 0.1),
        span("dispatch", t0 + 0.1, 0.1),
        span("calc", t0 + 0.2, 0.7),
        span("h2d_transfer", t0 + 0.25, 0.05),
        span("kernel", t0 + 0.3, 0.3),
    ]))
    segments = record["segments"]
    assert record["wall_s"] == pytest.approx(1.0)
    assert segments["admission_wait"] == pytest.approx(0.1)
    assert segments["dispatch"] == pytest.approx(0.1)
    assert segments["h2d_transfer"] == pytest.approx(0.05)
    assert segments["kernel"] == pytest.approx(0.3)
    # calc residue outside its phases: 0.2-0.25 and 0.6-0.9
    assert segments["worker_other"] == pytest.approx(0.35)
    # 0.9-1.0 only the root is active
    assert record["unattributed_s"] == pytest.approx(0.1)
    assert record["coverage"] == pytest.approx(0.9)
    # the invariant the chaos tests re-assert: segments + unattributed
    # always sum to the wall (non-overlap by construction)
    assert total_of(record) == pytest.approx(record["wall_s"], abs=1e-5)


def test_attribute_overlapping_concurrent_shards_never_double_count():
    """Two concurrent shard executions overlap on the wall clock; the sweep
    charges each instant once (most-specific wins), so the total never
    exceeds the wall."""
    t0 = 50.0
    record = slo.attribute(timeline([
        span("groupby", t0, 1.0),
        span("calc", t0, 0.8),
        span("calc", t0 + 0.1, 0.9),
        span("kernel", t0 + 0.2, 0.4),
        span("kernel", t0 + 0.3, 0.5),   # overlaps the other kernel
    ]))
    assert record["segments"]["kernel"] == pytest.approx(0.6)  # union
    assert total_of(record) == pytest.approx(1.0, abs=1e-6)
    assert record["coverage"] == pytest.approx(1.0)


def test_attribute_splits_backoff_out_of_retry_dispatch():
    t0 = 10.0
    record = slo.attribute(timeline([
        span("groupby", t0, 2.0),
        span("dispatch", t0, 0.2, tags={"worker": "w1", "retries": 0}),
        span("dispatch", t0 + 0.2, 0.8,
             tags={"worker": "w1", "retries": 0,
                   "failed": "dispatch timeout"}),
        span("dispatch", t0 + 1.0, 0.5,
             tags={"worker": "w2", "retries": 1, "backoff_s": 0.3,
                   "excluded": ["w1"]}),
        span("calc", t0 + 1.5, 0.5),
    ]))
    segments = record["segments"]
    assert segments["retry_backoff"] == pytest.approx(0.3)
    # 0.2 first queue + 0.8 failed wait + 0.2 post-backoff queue
    assert segments["dispatch"] == pytest.approx(1.2)
    assert total_of(record) == pytest.approx(2.0, abs=1e-6)
    attempts = record["attempts"]
    # ONE entry per physical attempt: the failed in-flight span annotates
    # attempt 1 (failed reason + how long it sat) instead of listing twice
    assert len(attempts) == 2
    assert attempts[0]["failed"] == "dispatch timeout"
    assert attempts[0]["inflight_s"] == pytest.approx(0.8)
    assert attempts[1]["excluded"] == ["w1"]
    assert attempts[1]["backoff_s"] == pytest.approx(0.3)


def test_attribute_hedge_dispatch_tagged():
    """The controller emits a zero-length hedge MARKER at dispatch time
    (listed in attempts) plus the hedge-race window at reply time (tagged
    hedge+wait: a segment, not an attempt) — mirror both here."""
    t0 = 0.0
    record = slo.attribute(timeline([
        span("groupby", t0, 1.0),
        span("dispatch", t0, 0.4, tags={"worker": "w1"}),
        span("dispatch", t0 + 0.4, 0.0,
             tags={"worker": "w2", "hedge": True}),
        span("dispatch", t0 + 0.4, 0.2,
             tags={"worker": "w2", "hedge": True, "wait": True}),
        span("calc", t0 + 0.7, 0.3),
    ]))
    assert record["segments"]["hedge_dispatch"] == pytest.approx(0.2)
    hedges = [a for a in record["attempts"] if a["hedge"]]
    assert len(hedges) == 1 and hedges[0]["worker"] == "w2"


def test_attribute_bundle_share_reports_member_slice():
    t0 = 5.0
    record = slo.attribute(timeline([
        span("groupby", t0, 1.0),
        span("calc", t0, 1.0, tags={"bundle_share": 0.25}),
        span("kernel", t0 + 0.2, 0.8),
    ]))
    # true-wall segments stay untouched...
    assert record["segments"]["kernel"] == pytest.approx(0.8)
    # ...and the member's accountable slice is reported beside them
    assert record["bundle"]["share"] == pytest.approx(0.25)
    assert record["bundle"]["member_segments"]["kernel"] == pytest.approx(0.2)


def test_attribute_unknown_span_name_stays_visible():
    """An undeclared span name (the lint prevents shipping one, but a
    version-skewed worker may still send it) keeps its own segment instead
    of silently vanishing into unattributed."""
    record = slo.attribute(timeline([
        span("groupby", 0.0, 1.0),
        span("mystery_phase", 0.2, 0.5),
    ]))
    assert record["segments"]["mystery_phase"] == pytest.approx(0.5)
    assert record["coverage"] == pytest.approx(0.5)


def test_attribute_malformed_inputs_never_raise():
    assert slo.attribute(None)["wall_s"] == 0.0
    assert slo.attribute({})["coverage"] == 0.0
    record = slo.attribute(timeline([
        {"name": "kernel", "start_ts": "garbage", "duration_s": 1},
        {"not": "a span"},
        span("groupby", 0.0, 1.0),
    ]))
    assert record["wall_s"] == pytest.approx(1.0)


def test_attribute_without_root_uses_span_envelope():
    record = slo.attribute(timeline([
        span("calc", 10.0, 1.0),
        span("kernel", 10.2, 0.5),
    ]))
    assert record["wall_s"] == pytest.approx(1.0)
    assert record["segments"]["kernel"] == pytest.approx(0.5)


def test_summarize_compacts_record():
    record = slo.attribute(timeline([
        span("groupby", 0.0, 1.0),
        span("calc", 0.0, 0.9),
        span("kernel", 0.1, 0.6),
    ]))
    summary = slo.summarize(record, top=1)
    assert summary["segments"] == {"kernel": record["segments"]["kernel"]}
    assert summary["coverage"] == record["coverage"]
    assert slo.summarize(None) is None


def test_every_public_span_name_has_priority():
    """SPAN_CATEGORIES segments must all rank in SEGMENT_PRIORITY — an
    unranked segment would fall back to dispatch priority silently."""
    for segment in slo.SPAN_CATEGORIES.values():
        assert segment in slo.SEGMENT_PRIORITY
    for segment in slo.SYNTHETIC_SEGMENTS:
        assert segment in slo.SEGMENT_PRIORITY or segment == "unattributed"


# -- SLO tracker --------------------------------------------------------------

def test_parse_classes_formats_and_default():
    classes = slo.parse_classes("interactive:0.5:0.999,batch:30,junk:,bad:x")
    assert classes["interactive"] == {"target_s": 0.5, "objective": 0.999}
    assert classes["batch"]["target_s"] == 30.0
    assert classes["batch"]["objective"] == slo.DEFAULT_OBJECTIVE
    assert "junk" not in classes and "bad" not in classes
    assert "default" in classes
    assert slo.parse_classes("")["default"]["target_s"] == (
        slo.DEFAULT_TARGET_S
    )


def test_slo_tracker_records_margins_and_violations():
    registry = MetricsRegistry()
    tracker = slo.SLOTracker(
        registry, classes=slo.parse_classes("fast:0.5")
    )
    # on-target query: positive margin, no violation
    cls, violated = tracker.record("fast", wall_s=0.1)
    assert (cls, violated) == ("fast", False)
    # past-target query (no deadline): violation, margin clamps to 0
    cls, violated = tracker.record("fast", wall_s=0.9)
    assert violated
    # explicit deadline margin wins over the class target
    _, violated = tracker.record("fast", wall_s=0.1, margin_s=-0.2)
    assert violated
    # unknown class folds into default
    cls, _ = tracker.record("nope", wall_s=0.1)
    assert cls == "default"
    # errors violate regardless of wall
    _, violated = tracker.record("fast", wall_s=0.01, ok=False)
    assert violated
    hist = tracker._hist["fast"]
    assert hist.count == 4
    assert tracker._violations["fast"].value == 3
    assert tracker._queries["fast"].value == 4
    snapshot = tracker.snapshot()
    assert snapshot["fast"]["violations"] == 3
    assert snapshot["default"]["queries"] == 1


def test_slo_burn_rate_windows():
    registry = MetricsRegistry()
    tracker = slo.SLOTracker(
        registry, classes=slo.parse_classes("c:1.0:0.99")
    )
    now = 10_000.0
    # 2 of 4 violated inside the 5m window -> rate 0.5 over budget 0.01
    for offset, violated in ((-10, True), (-8, False), (-6, True), (-4, False)):
        tracker.record(
            "c", wall_s=2.0 if violated else 0.1, now=now + offset
        )
    assert tracker.burn_rate("c", 300.0, now=now) == pytest.approx(50.0)
    # nothing in a tiny window -> 0.0, not a division error
    assert tracker.burn_rate("c", 0.001, now=now + 100) == 0.0
    # gauges render without error and carry the labels
    text = registry.render()
    assert 'bqueryd_tpu_slo_burn_rate{slo_class="c",window="5m"}' in text
    assert registry.lint() == []


def test_slo_burn_window_survives_high_qps():
    """Burn bookkeeping is bucketed counts, not raw events: 50 minutes of
    heavy violations followed by a clean recovery must still dominate the
    1h rate at any QPS (a raw-event cap used to shrink the window to
    seconds under load), and memory stays bounded by bucket count."""
    tracker = slo.SLOTracker(
        MetricsRegistry(), classes=slo.parse_classes("c:1.0:0.99")
    )
    now = 100_000.0
    for i in range(5000):   # ~83 qpm for 50 minutes, all violating
        tracker.record("c", wall_s=2.0, now=now - 3600.0 + i * 0.6)
    for i in range(1000):   # clean last 10 minutes
        tracker.record("c", wall_s=0.1, now=now - 600.0 + i * 0.6)
    # 5000/6000 violated over the hour -> rate ~0.83 over budget 0.01
    assert tracker.burn_rate("c", 3600.0, now=now) == pytest.approx(
        83.3, rel=0.05
    )
    # the clean 5m window reads clean
    assert tracker.burn_rate("c", 300.0, now=now) == 0.0
    # memory: at most window/bucket + 1 buckets, regardless of QPS
    assert len(tracker._events["c"]) <= 3600.0 / slo._BURN_BUCKET_S + 2
    # buckets older than the largest window are trimmed on record
    tracker.record("c", wall_s=0.1, now=now + 7200.0)
    assert len(tracker._events["c"]) == 1


# -- timeline ring ------------------------------------------------------------

def test_snapshot_timeline_paces_and_bounds(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_TIMELINE_INTERVAL_S", "10")
    monkeypatch.setenv("BQUERYD_TPU_TIMELINE_ENTRIES", "3")
    ring = slo.SnapshotTimeline()
    taken = [
        ring.maybe_snapshot(lambda: {"n": i}, now=1000.0 + i * 6.0)
        for i in range(10)
    ]
    # 6 s apart at a 10 s interval: every other tick snapshots
    assert sum(taken) == 5
    entries = ring.entries()
    assert len(entries) == 3  # capacity trim, newest kept
    assert entries[-1]["n"] == 8 and "ts" in entries[-1]


def test_snapshot_timeline_disabled_and_builder_failure(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_TIMELINE_INTERVAL_S", "0")
    ring = slo.SnapshotTimeline()
    assert not ring.maybe_snapshot(lambda: {"x": 1}, now=1.0)
    monkeypatch.setenv("BQUERYD_TPU_TIMELINE_INTERVAL_S", "1")

    def boom():
        raise RuntimeError("builder broke")

    assert not ring.maybe_snapshot(boom, now=100.0)
    assert len(ring) == 0
    # the failure is counted (and logged), never invisible
    assert ring.failures == 1


def test_quantile_from_snapshot():
    from bqueryd_tpu.obs.metrics import Histogram

    h = Histogram("bqueryd_tpu_test_seconds", "t")
    for v in (0.001, 0.001, 0.04, 8.0):
        h.observe(v)
    snap = h.snapshot()
    assert quantile_from_snapshot(snap, 0.5) == pytest.approx(0.001)
    assert quantile_from_snapshot(snap, 0.99) == pytest.approx(10.0)
    assert quantile_from_snapshot({"buckets": [], "counts": []}, 0.5) is None
    assert quantile_from_snapshot({}, 0.5) is None


# -- bundle member shares -----------------------------------------------------

def test_member_shares_proportional_and_equal():
    from bqueryd_tpu.plan import bundle as bundlemod

    assert bundlemod.member_shares([]) == {}
    shares = bundlemod.member_shares(
        ["a", "b"], walls={"a": 0.3, "b": 0.1}
    )
    assert shares["a"] == pytest.approx(0.75)
    assert shares["b"] == pytest.approx(0.25)
    # missing/zero walls degrade to the equal split
    shares = bundlemod.member_shares(["a", "b"], walls={"a": 0.3})
    assert shares == {"a": 0.5, "b": 0.5}
    assert bundlemod.member_shares(["a", "b", "c"])["a"] == pytest.approx(
        1 / 3, abs=1e-4
    )


# -- span-coverage lint -------------------------------------------------------

def _span_project(tmp_path, extra_site="", schema_extra="", categories_extra=""):
    from tests.test_analysis import make_project

    return make_project(tmp_path, {
        "messages.py": (
            "SPAN_SCHEMA = {\n"
            "    'groupby': 'root',\n"
            "    'calc': 'worker root',\n"
            "    'open': 'raw name of storage_decode',\n"
            "    'storage_decode': 'decode',\n"
            f"{schema_extra}"
            "}\n"
        ),
        "obs/trace.py": (
            "PHASE_SPAN_NAMES = {'open': 'storage_decode'}\n"
        ),
        "obs/slo.py": (
            "SPAN_CATEGORIES = {\n"
            "    'groupby': 'query',\n"
            "    'calc': 'worker_other',\n"
            "    'storage_decode': 'storage_decode',\n"
            f"{categories_extra}"
            "}\n"
            "SYNTHETIC_SEGMENTS = ('unattributed',)\n"
        ),
        "worker.py": (
            "def handle(timer, recorder, make_span):\n"
            "    with timer.phase('open'):\n"
            "        pass\n"
            "    make_span('t', 'groupby', 0, 1)\n"
            "    SpanRecorder(root_name='calc')\n"
            f"{extra_site}"
            "def SpanRecorder(root_name=None):\n"
            "    return root_name\n"
        ),
    })


def _run_spans(project):
    from bqueryd_tpu.analysis.core import run_suite as core_run_suite
    from bqueryd_tpu.analysis.spans import SpanSchemaAnalyzer

    return core_run_suite(project=project, analyzers=[SpanSchemaAnalyzer()])


def test_span_lint_clean_project(tmp_path):
    result = _run_spans(_span_project(tmp_path))
    assert [f.render() for f in result.new] == []


def test_span_lint_flags_undeclared_site(tmp_path):
    result = _run_spans(_span_project(
        tmp_path, extra_site="    timer.phase('rogue_phase')\n"
    ))
    assert {
        (f.rule, f.symbol) for f in result.new
    } == {("span-undeclared-name", "rogue_phase")}


def test_span_lint_flags_unattributed_name(tmp_path):
    # declared + used, but no SPAN_CATEGORIES entry for its public form
    result = _run_spans(_span_project(
        tmp_path,
        extra_site="    timer.phase('warp')\n",
        schema_extra="    'warp': 'new phase',\n",
    ))
    assert {
        (f.rule, f.symbol) for f in result.new
    } == {("span-unattributed-name", "warp")}


def test_span_lint_flags_dead_name(tmp_path):
    result = _run_spans(_span_project(
        tmp_path, schema_extra="    'ghost': 'never recorded',\n",
        categories_extra="    'ghost': 'query',\n",
    ))
    assert {
        (f.rule, f.symbol) for f in result.new
    } == {("span-dead-name", "ghost")}


def test_span_lint_flags_unranked_segment(tmp_path):
    from tests.test_analysis import make_project

    project = make_project(tmp_path, {
        "messages.py": "SPAN_SCHEMA = {'groupby': 'root'}\n",
        "obs/trace.py": "PHASE_SPAN_NAMES = {}\n",
        "obs/slo.py": (
            "SPAN_CATEGORIES = {'groupby': 'query'}\n"
            "SYNTHETIC_SEGMENTS = ('retry_backoff', 'unattributed')\n"
            # 'retry_backoff' missing: the sweep would rank it silently
            "SEGMENT_PRIORITY = ('query',)\n"
        ),
        "worker.py": "def f(make_span):\n    make_span('t', 'groupby', 0, 1)\n",
    })
    result = _run_spans(project)
    assert {
        (f.rule, f.symbol) for f in result.new
    } == {("span-unranked-segment", "retry_backoff")}


def test_span_lint_raw_name_resolves_through_phase_map(tmp_path):
    # 'open' is used at a phase site and maps to storage_decode, which has
    # a category: no findings despite 'open' itself not being a category
    result = _run_spans(_span_project(tmp_path))
    assert not [f for f in result.new if f.symbol == "open"]


# -- e2e: cluster autopsy / timeline / slo ------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        if node is not None:
            node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture(scope="module")
def slo_cluster(tmp_path_factory):
    """Controller + one worker over two shards, with declared SLO classes,
    a fast timeline ring, and an everything-is-slow slow-query threshold."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.coordination import coordination_store
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    tmp_path = tmp_path_factory.mktemp("slo_cluster")
    url = "mem://slo_cluster"
    coordination_store(url).flushdb()
    env_overrides = {
        "BQUERYD_TPU_SLO_CLASSES": "interactive:0.5:0.999,batch:30",
        "BQUERYD_TPU_TIMELINE_INTERVAL_S": "0.2",
        "BQUERYD_TPU_SLOW_QUERY_MS": "0",
    }
    saved = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    rng = np.random.default_rng(23)
    df = pd.DataFrame({
        "g": rng.integers(0, 6, 4000).astype(np.int64),
        "v": rng.integers(-1000, 1000, 4000).astype(np.int64),
        "w": rng.random(4000),
    })
    shards = ["slo_0.bcolzs", "slo_1.bcolzs"]
    for i, name in enumerate(shards):
        ctable.fromdataframe(
            df.iloc[i::2].reset_index(drop=True), str(tmp_path / name)
        )
    controller = ControllerNode(
        coordination_url=url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.05,
    )
    worker = WorkerNode(
        coordination_url=url, data_dir=str(tmp_path),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.1, poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(
        lambda: all(name in controller.files_map for name in shards),
        desc="shards advertised",
    )
    yield {
        "controller": controller, "worker": worker, "df": df,
        "shards": shards, "url": url,
    }
    _stop([controller, worker], threads)
    for key, value in saved.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value


def test_autopsy_roundtrip_with_coverage(slo_cluster):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING,
    )
    rpc.groupby(slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [])
    # warm second query: the attribution the bench gates on
    rpc.groupby(slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [])
    trace_id = rpc.last_trace_id  # every rpc call re-mints last_trace_id
    record = rpc.autopsy(trace_id)
    assert record["trace_id"] == trace_id
    assert record["ok"] is True
    # a warm ~10 ms micro-query's coverage is dominated by the sub-ms
    # finalize tail (fixed cost); the >= 0.95 contract is gated on the
    # bench's 400k-row sharded config where walls are real
    assert record["coverage"] >= 0.8
    segments = record["segments"]
    assert "kernel" in segments or "worker_other" in segments
    # the client folded its own deserialize wall in
    assert "client_deserialize" in segments
    assert total_of(record) == pytest.approx(record["wall_s"], abs=1e-3)
    assert record["attempts"] and record["attempts"][0]["worker"]
    # SLOW_QUERY_MS=0 records everything: the ring entry rides along, with
    # the compact attribution summary
    assert record["slow_query"]["trace_id"] == trace_id
    assert record["slow_query"]["attribution"]["coverage"] >= 0.8
    # autopsy() with no trace id serves the newest timeline
    assert rpc.autopsy()["trace_id"] == trace_id


def test_autopsy_unknown_trace_returns_none(slo_cluster):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING,
    )
    assert rpc.autopsy("no_such_trace") is None


def test_slo_classes_and_margins_e2e(slo_cluster):
    from bqueryd_tpu.rpc import RPC

    controller = slo_cluster["controller"]
    before = controller.slo.snapshot()
    rpc = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING, slo_class="interactive",
    )
    rpc.groupby(
        slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [],
        deadline=30,
    )
    after = controller.slo.snapshot()
    assert after["interactive"]["queries"] == (
        before["interactive"]["queries"] + 1
    )
    # a 30 s deadline on a sub-second query: margin positive, no violation
    assert after["interactive"]["violations"] == (
        before["interactive"]["violations"]
    )
    hist = controller.slo._hist["interactive"]
    assert hist.count >= 1
    # the slow-query entry carries the resolved class
    entry = controller.slow_queries.entry_for(rpc.last_trace_id)
    assert entry["slo_class"] == "interactive"
    # undeclared classes fold into default (no accidental cardinality)
    rpc2 = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING, slo_class="not_a_class",
    )
    rpc2.groupby(slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [])
    assert controller.slo.snapshot()["default"]["queries"] > (
        before["default"]["queries"]
    )


def test_timeline_ring_e2e(slo_cluster):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING,
    )
    rpc.groupby(slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [])
    # wait for a snapshot taken AFTER the query completed (tests may run
    # in any order within the module)
    wait_until(
        lambda: len(slo_cluster["controller"].timeline_ring) >= 2
        and slo_cluster["controller"].timeline_ring.entries()[-1][
            "counters"
        ]["queries_completed"] >= 1,
        desc="timeline snapshot reflecting the completed query",
    )
    entries = rpc.timeline()
    assert len(entries) >= 2
    newest = entries[-1]
    assert newest["workers"] == 1
    assert newest["counters"]["queries_completed"] >= 1
    assert newest["groupby_p99_s"] is not None
    assert "default" in newest["slo"]
    assert entries[0]["ts"] <= newest["ts"]
    # PR 12: the ring doubles as capacity history — every entry carries
    # the fleet utilization/saturation slice
    capacity = newest["capacity"]
    for key in (
        "utilization", "state", "arrival_qps", "knee_qps",
        "headroom_qps", "model_drift",
    ):
        assert key in capacity
    assert capacity["state"] in ("ok", "warm", "saturated", "overloaded")


def test_debug_bundle_carries_new_sections(slo_cluster):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING,
    )
    rpc.groupby(slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [])
    trace_id = rpc.last_trace_id  # every rpc call re-mints last_trace_id
    bundle = rpc.debug_bundle(trace_id)
    assert bundle["schema"] == "bqueryd_tpu.debug_bundle/4"
    controller_section = bundle["controller"]
    # the autopsy of the bundled trace rides inline
    assert controller_section["autopsy"]["trace_id"] == trace_id
    # micro-query coverage (see test_autopsy_roundtrip_with_coverage):
    # the sub-ms finalize tail dominates a ~10 ms warm wall
    assert controller_section["autopsy"]["coverage"] >= 0.8
    # PR 6/8/9 surfaces the artifact previously omitted
    assert "samples_total" in controller_section["calibration"]
    assert controller_section["chaos"]["armed"] is False
    assert "injected_total" in controller_section["chaos"]
    # PR 12: the fleet capacity model rides the bundle, freshly evaluated
    capacity = controller_section["capacity"]
    assert capacity["enabled"] is True
    assert capacity["fleet"]["state"] in (
        "ok", "warm", "saturated", "overloaded"
    )
    assert "recommendations" in capacity
    assert "shards_by_holders" in controller_section["replication"]
    assert controller_section["batch_window"]["window_ms"] == 0
    assert "default" in controller_section["slo"]
    assert isinstance(controller_section["timeline_ring"], list)
    import json

    json.dumps(bundle, default=str)  # still one JSON-safe artifact


def test_bundle_member_shares_scale_slow_query_timings(
    slo_cluster, monkeypatch
):
    """A fused window's members land in the slow-query ring with
    share-scaled phase timings (not the whole bundle's wall) and their
    autopsies report the member slice."""
    from bqueryd_tpu.rpc import RPC

    controller = slo_cluster["controller"]
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "300")
    shards, url = slo_cluster["shards"], slo_cluster["url"]
    queries = [
        (shards, ["g"], [["v", "sum", "s"]], [["w", ">", 0.3]]),
        (shards, ["g"], [["v", "sum", "s"]], [["w", ">", 0.6]]),
    ]
    results, errors, trace_ids = {}, {}, {}

    def run(i, query):
        try:
            rpc = RPC(
                coordination_url=url, timeout=60, loglevel=logging.WARNING
            )
            results[i] = rpc.groupby(*query)
            trace_ids[i] = rpc.last_trace_id
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors[i] = exc

    bundles_before = controller.counters["plan_bundles"]
    threads = [
        threading.Thread(target=run, args=(i, q), daemon=True)
        for i, q in enumerate(queries)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(90)
    assert not errors
    assert controller.counters["plan_bundles"] > bundles_before
    shares = []
    for i in trace_ids:
        entry = controller.slow_queries.entry_for(trace_ids[i])
        assert entry is not None
        for timings in entry["phase_timings"].values():
            assert "_member_share" in timings
            shares.append(timings["_member_share"])
            # the scaled member wall is a fraction of the bundle wall
            assert timings["_total"] <= entry["wall_ms"] / 1000.0 + 1e-3
        record = controller.build_autopsy(trace_ids[i])
        assert record["bundle"]["share"] == pytest.approx(
            shares[-1], abs=1e-6
        )
        assert "bundle_demux" in record["segments"]
    # two executed members split the shared scan
    assert sum(shares) == pytest.approx(1.0, abs=1e-3)


def test_window_flight_events_recorded(slo_cluster, monkeypatch):
    """The flight ring explains staging decisions: window_open on first
    stage, window_flush with the fused-group census."""
    from bqueryd_tpu.rpc import RPC

    controller = slo_cluster["controller"]
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "100")
    rpc = RPC(
        coordination_url=slo_cluster["url"], timeout=60,
        loglevel=logging.WARNING,
    )
    rpc.groupby(slo_cluster["shards"], ["g"], [["v", "sum", "s"]], [])
    kinds = [e["kind"] for e in controller.flight.events()]
    assert "window_open" in kinds
    assert "window_flush" in kinds
    flush = [
        e for e in controller.flight.events() if e["kind"] == "window_flush"
    ][-1]
    assert flush["staged"] >= 1 and flush["groups"] >= 1
    # a solo flush fused nothing
    assert flush["fused"] == 0
    # the staged member's autopsy shows the window wait as its own segment
    record = controller.build_autopsy(rpc.last_trace_id)
    assert "batch_window_wait" in record["segments"]
