"""Observability subsystem tests: metrics registry + Prometheus rendering,
distributed tracing (TraceContext propagation, span recording, the
controller's timeline assembly), structured logging, the slow-query log —
and the end-to-end acceptance path: a groupby through an in-process
controller+worker cluster whose waterfall comes back via rpc.trace() and
whose metrics come back via rpc.metrics()."""

import json
import logging
import os
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tests.conftest import wait_until

from bqueryd_tpu import obs


# -- metrics primitives ------------------------------------------------------

def test_counter_and_gauge_render():
    reg = obs.MetricsRegistry()
    c = reg.counter("bqueryd_tpu_things_total", "things seen")
    c.inc()
    c.inc(2)
    reg.gauge("bqueryd_tpu_depth", "queue depth", fn=lambda: 7)
    text = reg.render()
    assert "# HELP bqueryd_tpu_things_total things seen" in text
    assert "# TYPE bqueryd_tpu_things_total counter" in text
    assert "bqueryd_tpu_things_total 3" in text
    assert "bqueryd_tpu_depth 7" in text


def test_gauge_callback_failure_is_nan_not_crash():
    reg = obs.MetricsRegistry()
    reg.gauge("bqueryd_tpu_broken", "always raises", fn=lambda: 1 / 0)
    assert "bqueryd_tpu_broken nan" in reg.render()


def test_histogram_buckets_cumulative_and_sum():
    reg = obs.MetricsRegistry()
    h = reg.histogram("bqueryd_tpu_lat_seconds", "latency")
    for v in (0.0002, 0.0002, 0.3, 1e9):  # two tiny, one mid, one overflow
        h.observe(v)
    text = reg.render()
    # cumulative counts: everything <= 0.5 except the overflow
    assert 'bqueryd_tpu_lat_seconds_bucket{le="0.5"} 3' in text
    assert 'bqueryd_tpu_lat_seconds_bucket{le="+Inf"} 4' in text
    assert "bqueryd_tpu_lat_seconds_count 4" in text
    assert h.count == 4
    # non-cumulative snapshot merges by vector add
    snap = h.snapshot()
    assert sum(snap["counts"]) == 4
    assert snap["buckets"] == list(obs.LATENCY_BUCKETS_S)


def test_histogram_family_labels():
    reg = obs.MetricsRegistry()
    reg.histogram(
        "bqueryd_tpu_phase_seconds", "per phase", labels={"phase": "kernel"}
    ).observe(0.01)
    reg.histogram(
        "bqueryd_tpu_phase_seconds", "per phase", labels={"phase": "merge"}
    ).observe(0.02)
    text = reg.render()
    assert text.count("# TYPE bqueryd_tpu_phase_seconds histogram") == 1
    assert 'phase="kernel"' in text and 'phase="merge"' in text


def test_merge_histogram_snapshots_vector_add():
    reg_a, reg_b = obs.MetricsRegistry(), obs.MetricsRegistry()
    for reg, values in ((reg_a, (0.001, 0.3)), (reg_b, (0.001,))):
        h = reg.histogram(
            "bqueryd_tpu_phase_seconds", "x", labels={"phase": "kernel"}
        )
        for v in values:
            h.observe(v)
    merged = obs.merge_histogram_snapshots(
        [reg_a.histogram_snapshot(), reg_b.histogram_snapshot()]
    )
    (entry,) = merged["bqueryd_tpu_phase_seconds"]
    assert sum(entry["counts"]) == 3
    assert entry["sum"] == pytest.approx(0.302)
    assert "_skipped" not in merged


def test_merge_histogram_snapshots_rejects_mismatched_buckets():
    good = {
        "bqueryd_tpu_x_seconds": [
            {"labels": {}, "buckets": [1.0, 2.0], "counts": [1, 0, 0], "sum": 0.5}
        ]
    }
    bad = {
        "bqueryd_tpu_x_seconds": [
            {"labels": {}, "buckets": [1.0, 5.0], "counts": [0, 1, 0], "sum": 3.0}
        ]
    }
    merged = obs.merge_histogram_snapshots([good, bad])
    (entry,) = merged["bqueryd_tpu_x_seconds"]
    assert entry["counts"] == [1, 0, 0]  # mismatch skipped, not mis-added
    assert merged["_skipped"] == ["bqueryd_tpu_x_seconds"]


def test_registry_counters_dict_compat():
    """The controller's counters surface: plain-dict reads/writes, every
    write mirrored into a typed Prometheus counter."""
    reg = obs.MetricsRegistry()
    counters = obs.RegistryCounters(reg, {"plan_pruned_shards": "help here"})
    assert counters["plan_pruned_shards"] == 0
    counters["plan_pruned_shards"] += 3
    assert counters["plan_pruned_shards"] == 3
    assert dict(counters) == {"plan_pruned_shards": 3}
    assert "bqueryd_tpu_plan_pruned_shards_total 3" in reg.render()


def test_registry_lint_clean_and_violations():
    reg = obs.MetricsRegistry()
    reg.counter("bqueryd_tpu_good_total", "fine")
    assert reg.lint() == []
    reg.counter("bqueryd_tpu_BAD", "casing")
    reg.gauge("bqueryd_tpu_nohelp", "")
    reg.histogram("bqueryd_tpu_odd_seconds", "buckets", buckets=(1.0, 2.0))
    problems = "\n".join(reg.lint())
    assert "bqueryd_tpu_BAD" in problems
    assert "missing help" in problems
    assert "merge precondition" in problems


# -- PhaseTimer satellites ---------------------------------------------------

def test_phase_timer_total_is_monotonic(monkeypatch):
    """total() must survive a wall-clock step backwards (NTP): both the
    anchor and the reading use perf_counter now."""
    from bqueryd_tpu.utils.tracing import PhaseTimer

    timer = PhaseTimer()
    with timer.phase("work"):
        pass
    # a wall-clock step back must not affect perf_counter-based totals
    monkeypatch.setattr(time, "time", lambda: 0.0)
    assert timer.total() >= 0.0
    assert timer.total() >= timer.timings["work"] - 1e-9


def test_phase_timer_total_key_never_collides():
    from bqueryd_tpu.utils.tracing import TOTAL_KEY, PhaseTimer

    timer = PhaseTimer()
    with timer.phase("total"):  # a REAL phase named "total"
        pass
    out = timer.as_dict()
    assert TOTAL_KEY == "_total"
    assert "total" in out and TOTAL_KEY in out
    assert out["total"] is not out[TOTAL_KEY]
    assert out[TOTAL_KEY] >= out["total"]


def test_phase_timer_records_spans_with_mapped_names():
    from bqueryd_tpu.utils.tracing import PhaseTimer

    recorder = obs.SpanRecorder(trace_id="t" * 32, node="w1")
    timer = PhaseTimer(recorder=recorder, span_names=obs.PHASE_SPAN_NAMES)
    with timer.phase("open"):
        pass
    with timer.phase("aggregate"):
        pass
    spans = recorder.export()
    names = [s["name"] for s in spans]
    assert names[0] == "calc"  # root first
    assert "storage_decode" in names and "kernel" in names
    for child in spans[1:]:
        assert child["parent_span_id"] == recorder.root_span_id
        assert child["trace_id"] == "t" * 32


# -- trace_span / profiler_trace env gating (satellite: zero tests imported
#    utils/tracing before) ---------------------------------------------------

def test_trace_span_noop_when_profile_unset(monkeypatch):
    from bqueryd_tpu.utils import tracing

    monkeypatch.delenv("BQUERYD_TPU_PROFILE", raising=False)
    entered = []
    monkeypatch.setitem(
        __import__("sys").modules, "jax.profiler", None
    )  # would raise if touched
    with tracing.trace_span("off"):
        entered.append(True)
    assert entered == [True]


def test_trace_span_enabled_with_jax(monkeypatch):
    from bqueryd_tpu.utils import tracing

    monkeypatch.setenv("BQUERYD_TPU_PROFILE", "1")
    with tracing.trace_span("on"):
        pass  # enters a real jax.profiler.TraceAnnotation


def test_trace_span_enabled_tags_trace_id(monkeypatch):
    import jax.profiler

    from bqueryd_tpu.utils import tracing

    seen = {}

    class FakeAnnotation:
        def __init__(self, name, **kwargs):
            seen["name"] = name
            seen.update(kwargs)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setenv("BQUERYD_TPU_PROFILE", "1")
    monkeypatch.setattr(jax.profiler, "TraceAnnotation", FakeAnnotation)
    ctx = obs.TraceContext.new_root()
    with obs.use_trace(ctx):
        with tracing.trace_span("kernel"):
            pass
    assert seen == {"name": "kernel", "trace_id": ctx.trace_id}


def test_trace_span_enabled_without_jax_is_noop(monkeypatch):
    """BQUERYD_TPU_PROFILE=1 but jax.profiler unimportable -> still a
    working no-op (downloader/controller processes without JAX)."""
    import sys

    from bqueryd_tpu.utils import tracing

    monkeypatch.setenv("BQUERYD_TPU_PROFILE", "1")
    monkeypatch.setitem(sys.modules, "jax.profiler", None)  # ImportError
    entered = []
    with tracing.trace_span("no-jax"):
        entered.append(True)
    assert entered == [True]


def test_profiler_trace_starts_and_stops(monkeypatch, tmp_path):
    import jax.profiler

    from bqueryd_tpu.utils import tracing

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop", None))
    )
    with tracing.profiler_trace(str(tmp_path)):
        pass
    assert calls == [("start", str(tmp_path)), ("stop", None)]


def test_profiler_trace_stops_on_error(monkeypatch, tmp_path):
    import jax.profiler

    from bqueryd_tpu.utils import tracing

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append("stop")
    )
    with pytest.raises(RuntimeError):
        with tracing.profiler_trace(str(tmp_path)):
            raise RuntimeError("boom")
    assert calls == ["stop"]


# -- trace model -------------------------------------------------------------

def test_trace_context_wire_roundtrip():
    ctx = obs.TraceContext.new_root()
    wire = ctx.to_wire()
    back = obs.TraceContext.from_wire(json.loads(json.dumps(wire)))
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    child = back.child()
    assert child.parent_span_id == back.span_id
    assert child.trace_id == back.trace_id
    assert obs.TraceContext.from_wire(None) is None
    assert obs.TraceContext.from_wire({"trace_id": 5}) is None


def test_trace_store_ring_eviction():
    store = obs.TraceStore(capacity=2)
    for i in range(3):
        store.put(f"t{i}", {"trace_id": f"t{i}"})
    assert store.get("t0") is None
    assert store.get("t2")["trace_id"] == "t2"
    assert len(store) == 2


# -- logs --------------------------------------------------------------------

def test_json_log_formatter_carries_context():
    formatter = obs.JsonLogFormatter(node_id="w-123")
    record = logging.LogRecord(
        "bqueryd_tpu.test", logging.INFO, __file__, 1, "hello %s", ("x",), None
    )
    with obs.bind_log_context(trace_id="abc", query_id="q1"):
        line = json.loads(formatter.format(record))
    assert line["msg"] == "hello x"
    assert line["node_id"] == "w-123"
    assert line["trace_id"] == "abc"
    assert line["query_id"] == "q1"
    # outside the bind, no correlation fields leak
    line2 = json.loads(formatter.format(record))
    assert "trace_id" not in line2


def test_slow_query_log_threshold_and_capacity(monkeypatch):
    log = obs.SlowQueryLog(capacity=2)
    monkeypatch.setenv("BQUERYD_TPU_SLOW_QUERY_MS", "100")
    assert not log.maybe_record(0.05, {"trace_id": "fast"})
    assert log.maybe_record(0.2, {"trace_id": "slow1"})
    assert log.maybe_record(0.2, {"trace_id": "slow2"})
    assert log.maybe_record(0.2, {"trace_id": "slow3"})
    entries = log.entries()
    assert [e["trace_id"] for e in entries] == ["slow2", "slow3"]
    assert entries[-1]["wall_ms"] == pytest.approx(200.0)


# -- /metrics HTTP endpoint --------------------------------------------------

def test_metrics_http_endpoint_serves_registry():
    from bqueryd_tpu.obs.http import MetricsServer

    reg = obs.MetricsRegistry()
    reg.counter("bqueryd_tpu_scraped_total", "scrapes").inc()
    server = MetricsServer(reg, port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(f"{base}/metrics", timeout=5).read()
        assert b"bqueryd_tpu_scraped_total 1" in body
        health = urllib.request.urlopen(f"{base}/healthz", timeout=5).read()
        assert health == b"ok\n"
    finally:
        server.close()


def test_metrics_http_maybe_start_off_by_default(monkeypatch):
    from bqueryd_tpu.obs import http as obs_http

    monkeypatch.delenv("BQUERYD_TPU_METRICS_PORT", raising=False)
    assert obs_http.maybe_start(obs.MetricsRegistry()) is None


# -- end-to-end: the acceptance path ----------------------------------------

NR_SHARDS = 3


def _taxi_df(n=3_000, seed=11):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "total_amount": rng.gamma(2.5, 8.0, n),
            "trip_distance": rng.exponential(3.0, n),
        }
    )


@pytest.fixture(scope="module")
def obs_cluster(tmp_path_factory):
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = _taxi_df()
    root = tmp_path_factory.mktemp("obs_cluster")
    ctable.fromdataframe(df, str(root / "taxi.bcolz"))
    for i in range(NR_SHARDS):
        ctable.fromdataframe(
            df.iloc[i::NR_SHARDS], str(root / f"taxi-{i}.bcolzs")
        )
    url = f"mem://obs-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=str(root),
        heartbeat_interval=0.2,
        dead_worker_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=url,
        data_dir=str(root),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.1,
    )
    threads = [
        threading.Thread(target=node.go, daemon=True)
        for node in (controller, worker)
    ]
    for t in threads:
        t.start()
    wait_until(
        lambda: controller.files_map.get("taxi.bcolz"),
        desc="worker registration",
    )
    rpc = RPC(coordination_url=url, timeout=60, loglevel=logging.WARNING)
    yield {
        "rpc": rpc,
        "controller": controller,
        "worker": worker,
        "df": df,
    }
    for node in (controller, worker):
        node.running = False
    for t in threads:
        t.join(timeout=5)


def _groupby(rpc):
    return rpc.groupby(
        ["taxi.bcolz"],
        ["payment_type"],
        [["total_amount", "sum", "total_amount"]],
        [],
    )


def test_trace_waterfall_covers_required_spans(obs_cluster):
    """ACCEPTANCE: groupby through controller+worker, then rpc.trace()
    returns a timeline covering admission, plan, dispatch, kernel, merge —
    with parent/child links intact."""
    rpc = obs_cluster["rpc"]
    _groupby(rpc)
    trace_id = rpc.last_trace_id
    assert trace_id
    timeline = rpc.trace(trace_id)
    assert timeline is not None
    assert timeline["trace_id"] == trace_id
    assert timeline["ok"] is True
    spans = timeline["spans"]
    names = {s["name"] for s in spans}
    assert {"admission", "plan", "dispatch", "kernel", "merge"} <= names, names
    # worker-side phases came along too
    assert {"calc", "storage_decode", "h2d_transfer"} <= names, names
    # parent/child links: every span's parent is another span in the
    # timeline, except the controller's root "groupby" span whose parent is
    # the CLIENT's root span (not part of the controller-held timeline)
    by_id = {s["span_id"]: s for s in spans}
    orphans = [
        s for s in spans if s["parent_span_id"] not in by_id
    ]
    assert [s["name"] for s in orphans] == ["groupby"]
    # chain: kernel -> calc -> dispatch -> groupby
    kernel = next(s for s in spans if s["name"] == "kernel")
    calc = by_id[kernel["parent_span_id"]]
    assert calc["name"] == "calc"
    dispatch = by_id[calc["parent_span_id"]]
    assert dispatch["name"] == "dispatch"
    assert by_id[dispatch["parent_span_id"]]["name"] == "groupby"
    for name in ("admission", "plan"):
        span = next(s for s in spans if s["name"] == name)
        assert by_id[span["parent_span_id"]]["name"] == "groupby"
    # every span is trace-consistent and non-negative
    for s in spans:
        assert s["trace_id"] == trace_id
        assert s["duration_s"] >= 0.0


def test_rpc_metrics_prometheus_exposition(obs_cluster):
    """ACCEPTANCE: rpc.metrics() returns valid Prometheus text including the
    migrated plan_pruned_shards counter and a latency histogram whose bucket
    counts sum to the query count."""
    rpc = obs_cluster["rpc"]
    controller = obs_cluster["controller"]
    _groupby(rpc)
    text = rpc.metrics()
    assert isinstance(text, str)
    assert "# TYPE bqueryd_tpu_plan_pruned_shards_total counter" in text
    assert "bqueryd_tpu_plan_pruned_shards_total" in text
    # the latency histogram: +Inf cumulative == _count == queries completed
    inf_line = next(
        line for line in text.splitlines()
        if line.startswith("bqueryd_tpu_groupby_seconds_bucket")
        and 'le="+Inf"' in line
    )
    count_line = next(
        line for line in text.splitlines()
        if line.startswith("bqueryd_tpu_groupby_seconds_count")
    )
    inf_value = int(float(inf_line.rsplit(" ", 1)[1]))
    count_value = int(float(count_line.rsplit(" ", 1)[1]))
    assert inf_value == count_value
    assert count_value == controller.counters["queries_completed"]
    assert count_value >= 1


def test_slow_query_log_over_rpc(obs_cluster):
    rpc = obs_cluster["rpc"]
    os.environ["BQUERYD_TPU_SLOW_QUERY_MS"] = "0"  # everything is slow
    try:
        _groupby(rpc)
        trace_id = rpc.last_trace_id
        entries = rpc.slow_queries()
    finally:
        os.environ.pop("BQUERYD_TPU_SLOW_QUERY_MS", None)
    assert entries, "threshold 0 must record every query"
    entry = next(e for e in entries if e["trace_id"] == trace_id)
    assert entry["ok"] is True
    assert entry["filenames"] == 1
    assert entry["plan_signature"]
    assert entry["wall_ms"] > 0
    # phase breakdown present, with the namespaced total key
    (timings,) = entry["phase_timings"].values()
    assert "_total" in timings


def test_worker_histograms_aggregate_into_info(obs_cluster):
    """Worker WRMs carry histogram snapshots; the controller merges them by
    bucket-vector addition into get_info."""
    rpc = obs_cluster["rpc"]
    worker = obs_cluster["worker"]
    _groupby(rpc)
    assert worker.groupby_queries.value >= 1

    def aggregated():
        info = obs_cluster["controller"].get_info()
        hists = info.get("worker_histograms", {})
        series = hists.get("bqueryd_tpu_worker_groupby_seconds")
        # snapshots ride periodic WRMs, and a pre-groupby WRM legitimately
        # carries the family with all-zero counts — wait for the heartbeat
        # that reflects the observation, not just for the family to exist
        if not series or sum(sum(e["counts"]) for e in series) < 1:
            return None
        return series

    series = wait_until(aggregated, desc="worker histogram snapshot in WRM")
    total = sum(sum(e["counts"]) for e in series)
    assert total >= 1
    # phase family made it too, with mapped span names as labels
    info = obs_cluster["controller"].get_info()
    phases = info["worker_histograms"]["bqueryd_tpu_query_phase_seconds"]
    labels = {e["labels"]["phase"] for e in phases}
    assert {"kernel", "storage_decode"} <= labels


def test_live_registries_pass_lint(obs_cluster):
    """Satellite: the registry self-check runs clean on REAL node
    registries (names, help text, identical bucket vectors)."""
    assert obs_cluster["controller"].metrics.lint() == []
    assert obs_cluster["worker"].metrics.lint() == []


def test_metrics_kill_switch_disables_hot_path(obs_cluster):
    rpc = obs_cluster["rpc"]
    controller = obs_cluster["controller"]
    before = controller.query_seconds.count
    obs.set_enabled(False)
    try:
        _groupby(rpc)
        trace_id = rpc.last_trace_id
    finally:
        obs.set_enabled(True)
    # no histogram observation, no timeline — but the query itself worked
    # and the logic counters still moved
    assert controller.query_seconds.count == before
    assert rpc.trace(trace_id) is None
    assert controller.counters["queries_completed"] >= 1


def test_last_call_duration_uses_perf_counter(obs_cluster):
    rpc = obs_cluster["rpc"]
    assert rpc.ping() == "pong"
    assert rpc.last_call_duration is not None
    assert rpc.last_call_duration >= 0.0
