"""Failure-path and control-plane coverage the reference never had.

SURVEY.md §4 lists the reference's test blind spots: multi-controller
peering, worker death/cull, execute_code, and the memory watchdog.  These
tests close them, using the same threads-as-nodes topology as
tests/test_rpc_cluster.py (the reference's own fixture style, reference
tests/test_simple_rpc.py:42-74) with condition polling instead of sleeps.
"""

import logging
import os
import threading

import pytest

from conftest import wait_until


def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        if node is not None:  # a test may fail before creating late nodes
            node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture
def small_cluster(tmp_path, mem_store_url):
    """One controller + one calc worker, fast heartbeats, no data files."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
        dead_worker_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(lambda: controller.worker_map, desc="worker registration")
    rpc = RPC(
        coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
    )
    yield {"rpc": rpc, "controller": controller, "worker": worker}
    _stop([controller, worker], threads)


def test_execute_code_roundtrip(small_cluster, monkeypatch):
    """The reference's deliberate remote-execution verb (reference
    bqueryd/worker.py:250-267) — here gated behind an explicit env flag."""
    monkeypatch.setenv("BQUERYD_TPU_ENABLE_EXECUTE_CODE", "1")
    result = small_cluster["rpc"].execute_code(
        function="math.gcd", args=[12, 18], wait=True
    )
    assert result == 6


def test_execute_code_direct_kwargs(small_cluster, monkeypatch):
    """Keywords other than function/args/kwargs/wait go to the function."""
    monkeypatch.setenv("BQUERYD_TPU_ENABLE_EXECUTE_CODE", "1")
    result = small_cluster["rpc"].execute_code(
        function="fnmatch.fnmatch", name="shard_3.bcolzs", pat="shard_*",
        wait=True,
    )
    assert result is True


def test_execute_code_disabled_by_default(small_cluster, monkeypatch):
    from bqueryd_tpu.rpc import RPCError

    monkeypatch.delenv("BQUERYD_TPU_ENABLE_EXECUTE_CODE", raising=False)
    with pytest.raises(RPCError, match="execute_code disabled"):
        small_cluster["rpc"].execute_code(
            function="math.gcd", args=[12, 18], wait=True
        )


def test_dead_worker_culled_and_rejoins(tmp_path, mem_store_url):
    """A worker that dies silently (no StopMessage) is culled after
    dead_worker_timeout and dropped from files_map (reference
    bqueryd/controller.py:548-552); a later heartbeat re-registers it."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame({"g": np.arange(10), "v": np.arange(10)})
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=0.5,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    try:
        wait_until(
            lambda: "t.bcolzs" in controller.files_map, desc="registration"
        )
        # crash the worker: no StopMessage, no heartbeats, just silence
        worker.stop = lambda: None
        worker.running = False
        wait_until(
            lambda: not controller.worker_map,
            timeout=10,
            desc="silent worker culled",
        )
        assert not controller.files_map.get("t.bcolzs")

        # a restarted worker (fresh identity, same files) is picked up again
        worker2 = WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.1,
            poll_timeout=0.05,
        )
        threads += _start(worker2)
        wait_until(
            lambda: "t.bcolzs" in controller.files_map
            and controller.files_map["t.bcolzs"],
            desc="replacement worker registered",
        )
    finally:
        _stop([controller, worker, locals().get("worker2")], threads)


def test_controller_peering_and_killall(tmp_path, mem_store_url):
    """Two controllers on one store discover each other via the membership
    set + gossip (reference bqueryd/controller.py:77-106) and killall fans
    out to peers (reference bqueryd/controller.py:510-516)."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC

    a = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "a"),
        heartbeat_interval=0.1,
    )
    b = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "b"),
        heartbeat_interval=0.1,
    )
    threads = _start(a, b)
    try:
        wait_until(
            lambda: b.address in a.others and a.address in b.others,
            desc="mutual peer discovery",
        )
        rpc = RPC(
            coordination_url=mem_store_url,
            address=a.address,
            timeout=30,
            loglevel=logging.WARNING,
        )
        info = rpc.info()
        assert b.address in info["others"]
        rpc.killall()
        wait_until(
            lambda: not a.running and not b.running,
            desc="killall reached both controllers",
        )
        # both unregistered from the membership set
        from bqueryd_tpu import REDIS_SET_KEY
        from bqueryd_tpu.coordination import coordination_store

        wait_until(
            lambda: not coordination_store(mem_store_url).smembers(
                REDIS_SET_KEY
            ),
            desc="membership set emptied",
        )
    finally:
        _stop([a, b], threads)


def test_busy_worker_outliving_dead_timeout_not_culled(tmp_path, mem_store_url):
    """Work that outlives dead_worker_timeout still completes: the liveness
    thread keeps heartbeating while handle_work blocks the event loop, so the
    controller must neither cull the busy worker nor drop its files_map
    entries mid-query (the round-1 benchmark failure: 'file(s) no longer on
    any worker')."""
    import time as time_mod

    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame(
        {"g": np.arange(20) % 4, "v": np.arange(20, dtype=np.int64)}
    )
    ctable.fromdataframe(df, str(tmp_path / "slow.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,   # far below the query's runtime
        dispatch_timeout=30.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.3,
        poll_timeout=0.05,
    )
    # make the query block the worker's event loop well past the cull timeout
    orig_handle_work = worker.handle_work

    def slow_handle_work(msg):
        time_mod.sleep(2.5)
        return orig_handle_work(msg)

    worker.handle_work = slow_handle_work

    threads = _start(controller, worker)
    try:
        wait_until(
            lambda: "slow.bcolzs" in controller.files_map, desc="registration"
        )
        rpc = RPC(
            coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
        )
        result = rpc.groupby(
            ["slow.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
        )
        got = dict(zip(result["g"].tolist(), result["v_sum"].tolist()))
        expect = df.groupby("g")["v"].sum().to_dict()
        assert got == expect
        # the worker survived: still registered, file still advertised
        assert worker.worker_id in controller.worker_map
        assert "slow.bcolzs" in controller.files_map
    finally:
        _stop([controller, worker], threads)


def test_shard_retry_lands_on_replacement_worker(tmp_path, mem_store_url):
    """A worker that dies mid-flight (work dispatched, no reply, silence)
    gets its shard requeued after dispatch_timeout and the retry completes on
    a replacement worker — the dispatch-tracking behaviour the reference left
    as a TODO (reference bqueryd/controller.py:265)."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame(
        {"g": np.arange(30) % 3, "v": np.arange(30, dtype=np.int64)}
    )
    ctable.fromdataframe(df, str(tmp_path / "r.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,
        dispatch_timeout=1.5,
    )
    worker_a = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    a_got_work = threading.Event()

    def crash_mid_work(msg):
        """Simulate a hard crash: stop heartbeating, never reply."""
        a_got_work.set()
        worker_a.stop = lambda: None       # no StopMessage: silent death
        worker_a._hb_stop.set()            # liveness thread dies too
        worker_a.running = False
        return None

    worker_a.handle_work = crash_mid_work

    worker_b = None
    threads = _start(controller, worker_a)
    try:
        wait_until(
            lambda: "r.bcolzs" in controller.files_map, desc="registration"
        )
        rpc = RPC(
            coordination_url=mem_store_url, timeout=45, loglevel=logging.WARNING
        )
        result_box = {}

        def ask():
            result_box["df"] = rpc.groupby(
                ["r.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
            )

        asker = threading.Thread(target=ask, daemon=True)
        asker.start()
        wait_until(a_got_work.is_set, desc="worker A received the shard")
        # bring up the replacement holding the same shard file
        worker_b = WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )
        threads += _start(worker_b)
        asker.join(timeout=40)
        assert not asker.is_alive(), "query never completed after retry"
        result = result_box["df"]
        got = dict(zip(result["g"].tolist(), result["v_sum"].tolist()))
        assert got == df.groupby("g")["v"].sum().to_dict()
        # the retry really happened on B: A is gone from the worker map
        wait_until(
            lambda: worker_a.worker_id not in controller.worker_map,
            desc="dead worker culled",
        )
        assert worker_b.worker_id in controller.worker_map
    finally:
        _stop([controller, worker_a, worker_b], threads)


def test_memory_watchdog_stops_over_limit_worker(tmp_path, mem_store_url):
    """RSS above the limit (and caches shed without relief) stops the loop so
    a supervisor can restart the process (reference bqueryd/worker.py:232-241,
    2 GB cap)."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=True,
        memory_limit_mb=1,  # any real process RSS exceeds this
    )
    worker.running = True
    worker._check_mem()
    assert worker.running is False
    worker.socket.close()


def test_memory_watchdog_unmeasurable_shed_still_stops(
    tmp_path, mem_store_url, monkeypatch
):
    """If the post-shed RSS read fails, the pre-shed over-limit reading wins
    and the worker still restarts (no silent disable of the safety net)."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=True,
        memory_limit_mb=1,
    )
    monkeypatch.setattr(worker, "_shed_caches", lambda: None)
    worker.running = True
    worker._check_mem()
    assert worker.running is False
    worker.socket.close()


def test_memory_watchdog_shed_recovery_keeps_running(
    tmp_path, mem_store_url, monkeypatch
):
    """If shedding caches brings RSS back under the limit, the worker keeps
    serving instead of restarting."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=True,
        memory_limit_mb=1,
    )
    monkeypatch.setattr(worker, "_shed_caches", lambda: 0.5)
    worker.running = True
    worker._check_mem()
    assert worker.running is True
    worker.socket.close()


def test_two_controllers_both_get_heartbeats_during_long_work(
    tmp_path, mem_store_url
):
    """Per-controller ADDRESSED heartbeat delivery: with two controllers and
    the worker's event loop blocked in a long handle_work, BOTH controllers'
    last_seen must keep refreshing (a single shared DEALER round-robins its
    sends across peers, making per-controller delivery probabilistic)."""
    import time as time_mod

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    a = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "a"),
        heartbeat_interval=0.05,
        dead_worker_timeout=10.0,
    )
    b = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "b"),
        heartbeat_interval=0.05,
        dead_worker_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(a, b, worker)
    try:
        wid = worker.worker_id
        wait_until(
            lambda: wid in a.worker_map and wid in b.worker_map,
            desc="worker registered on both controllers",
        )
        rpc = RPC(
            coordination_url=mem_store_url,
            address=a.address,
            timeout=30,
            loglevel=logging.WARNING,
        )
        done = threading.Event()

        def ask():
            rpc.sleep(2.0)
            done.set()

        threading.Thread(target=ask, daemon=True).start()
        wait_until(
            lambda: a.worker_map.get(wid, {}).get("busy"),
            desc="worker busy in long work",
        )
        # while the event loop is blocked, sample last_seen on BOTH
        seen_a0 = a.worker_map[wid]["last_seen"]
        seen_b0 = b.worker_map[wid]["last_seen"]
        time_mod.sleep(0.6)  # several heartbeat ticks
        assert not done.is_set(), "work finished too early to measure"
        assert a.worker_map[wid]["last_seen"] > seen_a0
        assert b.worker_map[wid]["last_seen"] > seen_b0
        wait_until(done.is_set, desc="sleep verb completed")
    finally:
        _stop([a, b, worker], threads)


def test_hb_only_adoption_is_busy_until_main_socket_speaks(mem_store_url):
    """A worker adopted from a liveness-only heartbeat (controller restarted
    while the worker is deep in handle_work) must not be dispatchable: the
    ROUTER may only hold a route for the '.hb' identity, and dispatching
    would EHOSTUNREACH -> remove -> re-adopt in a loop that burns the
    shard's retry budget.  The first main-socket WRM clears the flag."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import WorkerRegisterMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        wrm = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
                "liveness_only": True,
            }
        )
        controller.handle_worker(b"w1.hb", wrm)
        info = controller.worker_map["w1"]
        assert info["busy"] is True and info.get("hb_only")
        assert "s.bcolzs" in controller.files_map
        # not dispatchable while hb_only
        assert controller.find_free_worker(filename="s.bcolzs") is None

        # main-socket WRM proves the route: busy resets, flag clears
        full = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
            }
        )
        controller.handle_worker(b"w1", full)
        info = controller.worker_map["w1"]
        assert info["busy"] is False and not info.get("hb_only")
        assert controller.find_free_worker(filename="s.bcolzs") == "w1"
    finally:
        controller.socket.close()


def test_unroutable_dispatch_does_not_charge_retry_budget(mem_store_url):
    """An EHOSTUNREACH send (missing ROUTER route) requeues the shard WITHOUT
    incrementing _retries: routing facts are not evidence against the shard,
    and charging them aborts the query after MAX_DISPATCH_RETRIES re-adopts."""
    from bqueryd_tpu.controller import MAX_DISPATCH_RETRIES, ControllerNode
    from bqueryd_tpu.messages import CalcMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        msg = CalcMessage(
            {
                "payload": "groupby",
                "token": "t1",
                "parent_token": "p1",
                "filename": "s.bcolzs",
                "_retries": MAX_DISPATCH_RETRIES,  # budget already exhausted
            }
        )
        # no such route on the ROUTER -> ZMQError (ROUTER_MANDATORY) path
        controller._send_to_worker("no-such-worker", msg)
        queue = controller.worker_out_messages.get(None, [])
        assert [m.get("token") for m in queue] == ["t1"], (
            "shard must be requeued, not aborted"
        )
        assert queue[0].get("_retries") == MAX_DISPATCH_RETRIES
    finally:
        controller.socket.close()


def test_hb_only_adoption_expires_after_hard_timeout(mem_store_url):
    """A worker whose main loop is permanently wedged but whose heartbeat
    thread stays alive must not block its shards forever: the adoption
    expires after dispatch_hard_timeout and the worker is reclaimed, letting
    queries fail fast instead of hanging to the client timeout."""
    import time as time_mod

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import WorkerRegisterMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent", dispatch_hard_timeout=0.2,
        dispatch_timeout=0.1,
    )
    try:
        wrm = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
                "liveness_only": True,
            }
        )
        controller.handle_worker(b"w1.hb", wrm)
        assert "w1" in controller.worker_map
        time_mod.sleep(0.25)
        # heartbeats keep arriving (last_seen fresh) but main loop is silent
        controller.handle_worker(b"w1.hb", wrm.copy())
        controller.free_dead_workers()
        assert "w1" not in controller.worker_map
        assert "s.bcolzs" not in controller.files_map
        # the still-ticking heartbeat thread must NOT re-adopt it (quarantine)
        controller.handle_worker(b"w1.hb", wrm.copy())
        assert "w1" not in controller.worker_map
        # ...until the main socket proves the loop recovered
        full = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
            }
        )
        controller.handle_worker(b"w1", full)
        assert "w1" in controller.worker_map
        controller.handle_worker(b"w1.hb", wrm.copy())  # liveness works again
        assert controller.worker_map["w1"]["last_seen"]
    finally:
        controller.socket.close()


def test_stop_is_a_shutdown_request_and_deregisters(
    tmp_path, mem_store_url, monkeypatch
):
    """Calling stop() from OUTSIDE the node loop (tests, embedders,
    signal handlers) must end the loop promptly and deregister the
    controller from the coordination store — previously the loop kept
    polling the closed socket forever and external teardown hung on
    thread joins."""
    import logging
    import threading
    import time

    import bqueryd_tpu
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.coordination import coordination_store
    from bqueryd_tpu.worker import WorkerNode

    monkeypatch.setenv("BQUERYD_TPU_WARMUP", "0")
    url = mem_store_url
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    worker = WorkerNode(
        coordination_url=url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = [
        threading.Thread(target=n.go, daemon=True)
        for n in (controller, worker)
    ]
    for t in threads:
        t.start()
    store = coordination_store(url)
    wait_until(
        lambda: store.smembers(bqueryd_tpu.REDIS_SET_KEY),
        desc="controller registration",
    )
    # stop() before go() starts is a different race; wait the loops in
    wait_until(
        lambda: controller.running and worker.running, desc="loops running"
    )

    t0 = time.time()
    worker.stop()
    controller.stop()
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads), "node loops did not exit"
    assert time.time() - t0 < 5, "external stop() took too long"
    assert store.smembers(bqueryd_tpu.REDIS_SET_KEY) == set()


def test_groupby_through_either_controller(tmp_path, mem_store_url, monkeypatch):
    """A worker registers with every controller in the store; the same
    query asked through EACH controller must produce the same
    pandas-checked answer (the reference's operational model: clients
    may point at any controller, reference bqueryd/rpc.py:62-78)."""
    import logging
    import threading

    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    monkeypatch.setenv("BQUERYD_TPU_WARMUP", "0")
    rng = np.random.default_rng(21)
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 6, 4_000).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, 4_000).astype(np.int64),
        }
    )
    ctable.fromdataframe(df, str(tmp_path / "s0.bcolzs"))
    expected = df.groupby("g")["v"].sum()

    controllers = [
        ControllerNode(
            coordination_url=mem_store_url,
            loglevel=logging.WARNING,
            runfile_dir=str(tmp_path),
            heartbeat_interval=0.1,
        )
        for _ in range(2)
    ]
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    nodes = controllers + [worker]
    threads = [
        threading.Thread(target=n.go, daemon=True) for n in nodes
    ]
    for t in threads:
        t.start()
    try:
        for c in controllers:
            wait_until(
                lambda c=c: "s0.bcolzs" in c.files_map,
                desc=f"shard registered at {c.address}",
            )
        results = []
        for c in controllers:
            rpc = RPC(
                address=c.address,
                coordination_url=mem_store_url,
                loglevel=logging.WARNING,
                timeout=30,
            )
            got = rpc.groupby(
                ["s0.bcolzs"], ["g"], [["v", "sum", "s"]], []
            )
            got = got.sort_values("g").reset_index(drop=True)
            assert got["g"].tolist() == expected.index.tolist()
            assert got["s"].tolist() == expected.tolist()
            results.append(got)
        pd.testing.assert_frame_equal(results[0], results[1])
    finally:
        for n in nodes:
            n.stop()
        for t in threads:
            t.join(timeout=5)


def test_concurrent_clients_survive_worker_churn(tmp_path, mem_store_url):
    """N concurrent clients with mixed shard affinities keep getting exact
    answers while workers are hard-killed and replaced mid-stream — the
    redesign's dispatch tracking (tracked inflight + bounded retries +
    cull/requeue) under real concurrency, which the reference (retry TODO at
    reference bqueryd/controller.py:265) never attempted.

    Asserts: no lost replies (every call returns), bit-exact sums on every
    reply (any retry that re-merged, double-dispatched, or mixed stale
    partials into a result would corrupt them), bounded retries (every
    requeue stays under MAX_DISPATCH_RETRIES, none poisoned), churn really
    overlapped the query stream, and no leaked inflight entries once the
    stream drains."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import MAX_DISPATCH_RETRIES, ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(42)
    n_shards, rows = 6, 400
    frames = {}
    for i in range(n_shards):
        df = pd.DataFrame(
            {
                "g": rng.integers(0, 5, rows).astype(np.int64),
                "v": rng.integers(-(2**40), 2**40, rows).astype(np.int64),
            }
        )
        frames[f"churn_{i}.bcolzs"] = df
        ctable.fromdataframe(df, str(tmp_path / f"churn_{i}.bcolzs"))

    # mixed affinities: each client sticks to its own file subset
    subsets = [
        [f"churn_{i}.bcolzs" for i in idx]
        for idx in ([0, 1], [2, 3], [4, 5], [0, 2, 4], [1, 3, 5],
                    list(range(n_shards)))
    ]
    expected = {
        tuple(sub): pd.concat([frames[f] for f in sub])
        .groupby("g")["v"].sum().to_dict()
        for sub in map(tuple, subsets)
    }

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,
        dispatch_timeout=1.5,
    )
    requeues = []
    real_requeue = controller._requeue

    def counting_requeue(entry, charge_retry=True, **kw):
        requeues.append(entry.get("retries", 0))
        return real_requeue(entry, charge_retry=charge_retry, **kw)

    controller._requeue = counting_requeue

    def spawn_worker():
        return WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )

    workers = [spawn_worker() for _ in range(3)]
    threads = _start(controller, *workers)
    all_nodes = [controller] + list(workers)
    try:
        wait_until(
            lambda: len(controller.files_map.get("churn_0.bcolzs", ())) >= 1
            and len(controller.worker_map) >= 3,
            desc="initial registration",
        )

        stop_churn = threading.Event()
        errors = []
        results = []  # (subset, got_dict) — appended under a lock
        res_lock = threading.Lock()

        def client(sub, n_queries=4):
            try:
                rpc = RPC(
                    coordination_url=mem_store_url,
                    timeout=60,
                    loglevel=logging.WARNING,
                    retries=3,
                )
                for _ in range(n_queries):
                    df = rpc.groupby(
                        list(sub), ["g"], [["v", "sum", "s"]], []
                    )
                    got = dict(zip(df["g"].tolist(), df["s"].tolist()))
                    with res_lock:
                        results.append((tuple(sub), got))
            except Exception as exc:  # lost reply shows up here
                errors.append((sub, repr(exc)))

        kills_mid_stream = []

        def churn():
            """Hard-kill a worker mid-stream, start a replacement, twice."""
            try:
                for round_i in range(2):
                    if stop_churn.wait(0.6):
                        return
                    victim = workers[round_i]
                    # silent death: no goodbye StopMessage, no replies —
                    # but the loop thread still runs its own socket
                    # teardown on exit (stop() itself must stay intact)
                    victim.send = lambda *a, **k: None
                    victim._hb_stop.set()
                    victim.running = False
                    kills_mid_stream.append(
                        any(t.is_alive() for t in clients)
                    )
                    replacement = spawn_worker()
                    workers.append(replacement)
                    all_nodes.append(replacement)
                    threads.extend(_start(replacement))
            except Exception as exc:
                errors.append(("churn", repr(exc)))

        clients = [
            threading.Thread(target=client, args=(sub,), daemon=True)
            for sub in subsets
        ]
        churner = threading.Thread(target=churn, daemon=True)
        for t in clients:
            t.start()
        churner.start()
        for t in clients:
            t.join(timeout=120)
            assert not t.is_alive(), "client wedged: lost reply"
        stop_churn.set()
        churner.join(timeout=10)

        assert not errors, f"client/churn failures: {errors}"
        # the scenario must actually have happened: both kills landed while
        # clients were still querying (else this test silently stops
        # covering churn — tune the client/churn pacing if this fires)
        assert kills_mid_stream == [True, True], kills_mid_stream
        assert len(results) == len(subsets) * 4, "lost replies"
        for sub, got in results:
            assert got == expected[sub], f"wrong/duplicated sums for {sub}"
        # bounded retries: every requeue stayed under budget (none poisoned)
        assert all(r < MAX_DISPATCH_RETRIES for r in requeues), requeues
        # generous bound: kills can requeue at most the shards each victim
        # held inflight, twice, plus timeout-driven strays
        assert len(requeues) <= 4 * n_shards, requeues
        wait_until(
            lambda: not controller.inflight, desc="inflight drained"
        )
    finally:
        _stop(all_nodes, threads)


# ---------------------------------------------------------------------------
# fault-plan-driven chaos cases (PR 8): the failover paths exercised on
# purpose through bqueryd_tpu.chaos instead of hand-rolled monkeypatching
# ---------------------------------------------------------------------------

def _replica_cluster(tmp_path, mem_store_url, df_seed=11, n_workers=2,
                     dispatch_timeout=1.5, dispatch_hard_timeout=None,
                     shards=("rep_0.bcolzs", "rep_1.bcolzs")):
    """Controller + N workers ALL holding the same shard files (replica
    topology), small timeouts so failover happens in test time."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(df_seed)
    frames = {}
    for name in shards:
        df = pd.DataFrame(
            {
                "g": rng.integers(0, 4, 300).astype(np.int64),
                "v": rng.integers(-(2**40), 2**40, 300).astype(np.int64),
            }
        )
        frames[name] = df
        ctable.fromdataframe(df, str(tmp_path / name))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,
        dispatch_timeout=dispatch_timeout,
        dispatch_hard_timeout=dispatch_hard_timeout,
    )
    workers = [
        WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )
        for _ in range(n_workers)
    ]
    threads = _start(controller, *workers)
    wait_until(
        lambda: all(
            len(controller.files_map.get(name, ())) >= n_workers
            for name in shards
        ),
        desc="every shard advertised by every worker (replica topology)",
    )
    import pandas as pd

    expected = (
        pd.concat(frames.values()).groupby("g")["v"].sum().to_dict()
    )
    return controller, workers, threads, expected, list(shards)


def _ask_sum(mem_store_url, shards, timeout=45):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(
        coordination_url=mem_store_url, timeout=timeout,
        loglevel=logging.WARNING,
    )
    df = rpc.groupby(list(shards), ["g"], [["v", "sum", "s"]], [])
    return rpc, dict(zip(df["g"].tolist(), df["s"].tolist()))


def test_die_after_ack_fails_over_to_replica_holder(tmp_path, mem_store_url):
    """A worker that hard-crashes after accepting work (die_after_ack: Busy
    sent, then silence — no reply, no heartbeats) must not fail the query:
    the dispatch timeout re-queues the shard onto the OTHER holder, the
    result is bit-identical, and the failover counter proves the path ran."""
    from bqueryd_tpu import chaos

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    try:
        chaos.arm({
            "seed": 1,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected
        assert controller.counters["failover_dispatches"] >= 1
        assert chaos.injected_total() >= 1
        # exactly one worker died; the survivor still serves
        wait_until(
            lambda: len(controller.worker_map) == 1,
            desc="dead worker culled",
        )
        chaos.disarm()
        _, again = _ask_sum(mem_store_url, shards)
        assert again == expected
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_dag_topk_quantile_fails_over_under_kill_worker(
    tmp_path, mem_store_url
):
    """PR-13 acceptance: an operator-DAG query (top-k + quantile sketch)
    survives the PR-8 kill-worker chaos plan with ZERO failed queries —
    the DAG rides the same dispatch/failover machinery as plain groupbys,
    so the shard re-queues onto the replica holder and the merged answer
    matches the fault-free run exactly."""
    import numpy as np

    from bqueryd_tpu import chaos
    from bqueryd_tpu.rpc import RPC

    controller, workers, threads, _expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    spec = {
        "table": list(shards),
        "groupby": ["g"],
        "aggs": [
            ["v", "sum", "s"],
            ["v", "topk", "t3", {"k": 3}],
            ["v", "quantile", "p50", {"q": 0.5, "alpha": 0.01}],
        ],
    }
    try:
        rpc = RPC(
            coordination_url=mem_store_url, timeout=45,
            loglevel=logging.WARNING,
        )
        baseline = rpc.query(spec)  # fault-free reference run
        chaos.arm({
            "seed": 7,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        got = rpc.query(spec)
        assert chaos.injected_total() >= 1
        assert controller.counters["failover_dispatches"] >= 1
        # zero failed queries: the chaos run answered, and EXACTLY —
        # int sums bit-equal, top-k lists identical, sketch estimates
        # bit-equal (same buckets, same counts, whoever served the shard)
        assert got["g"].tolist() == baseline["g"].tolist()
        assert got["s"].tolist() == baseline["s"].tolist()
        for a, b in zip(got["t3"], baseline["t3"]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            got["p50"].to_numpy(), baseline["p50"].to_numpy()
        )
        wait_until(
            lambda: len(controller.worker_map) == 1,
            desc="dead worker culled",
        )
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_batched_dag_fails_over_as_whole_group_under_kill_worker(
    tmp_path, mem_store_url
):
    """PR-15 acceptance: a BATCHED DAG query (top-k + quantile sketch over
    one shard-group CalcMessage, the DAG fast path) survives the kill-worker
    chaos plan with ZERO failed queries — the whole group fails over to the
    replica holder (the PR-8/PR-9 bundle precedent), and the answer —
    including the sketch buckets behind the quantile estimates — is
    bit-equal to the fault-free baseline."""
    import numpy as np

    from bqueryd_tpu import chaos
    from bqueryd_tpu.rpc import RPC

    controller, workers, threads, _expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    spec = {
        "table": list(shards),
        "groupby": ["g"],
        "aggs": [
            ["v", "sum", "s"],
            ["v", "topk", "t3", {"k": 3}],
            ["v", "quantile", "p50", {"q": 0.5, "alpha": 0.01}],
        ],
    }
    try:
        rpc = RPC(
            coordination_url=mem_store_url, timeout=45,
            loglevel=logging.WARNING,
        )
        before = controller.counters["dispatched_shards"]
        baseline = rpc.query(spec)  # fault-free reference run
        # the whole replica-held shard set rode ONE batched CalcMessage
        assert controller.counters["dispatched_shards"] - before == 1
        assert "device" in (rpc.last_call_merge_modes or {}).values()
        chaos.arm({
            "seed": 17,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        got = rpc.query(spec)
        assert chaos.injected_total() >= 1
        assert controller.counters["failover_dispatches"] >= 1
        # zero failed queries, bit-equal to the fault-free run: int sums,
        # top-k lists, and sketch estimates (same buckets, same counts,
        # whichever holder served the whole group)
        assert got["g"].tolist() == baseline["g"].tolist()
        assert got["s"].tolist() == baseline["s"].tolist()
        for a, b in zip(got["t3"], baseline["t3"]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            got["p50"].to_numpy(), baseline["p50"].to_numpy()
        )
        wait_until(
            lambda: len(controller.worker_map) == 1,
            desc="dead worker culled",
        )
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_transient_device_fault_retries_on_other_holder(
    tmp_path, mem_store_url
):
    """A transient DeviceBusyError (wedge action: the worker latches
    backend_wedged and raises the transient class) triggers failover to the
    healthy replica holder — the query succeeds, nothing aborts, and the
    wedged worker is still alive (advertised wedged) afterwards."""
    from bqueryd_tpu import chaos

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    try:
        chaos.arm({
            "seed": 2,
            "faults": [{
                "site": "worker.execute",
                "action": "wedge",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected
        assert controller.counters["transient_faults"] >= 1
        assert controller.counters["failover_dispatches"] >= 1
        # both workers still registered: a transient fault must not cull
        assert len(controller.worker_map) == 2
        wedged = [w for w in workers if w._chaos_wedged]
        assert len(wedged) == 1
        # the wedge is advertised like the real device-health latch
        wait_until(
            lambda: any(
                controller._worker_wedged.get(w.worker_id)
                for w in wedged
            ),
            desc="wedge advertised in WRMs",
        )
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_autopsy_attributes_failover_backoff(tmp_path, mem_store_url):
    """A query that survives a transient device fault (wedge -> failover to
    the other holder) must autopsy with the recovery visible: a failed
    attempt, a retry whose backoff window appears as a retry_backoff
    segment, and segments that still sum consistently with the wall."""
    from bqueryd_tpu import chaos

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    try:
        chaos.arm({
            "seed": 2,
            "faults": [{
                "site": "worker.execute",
                "action": "wedge",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        rpc, got = _ask_sum(mem_store_url, shards)
        assert got == expected
        assert controller.counters["failover_dispatches"] >= 1
        record = rpc.autopsy(rpc.last_trace_id)
        assert record is not None and record["ok"] is True
        # the wedged attempt + the failover retry are both listed; the
        # retry excludes the faulted holder and charged a backoff window
        assert len(record["attempts"]) >= 2
        retries = [a for a in record["attempts"] if a["retries"] >= 1]
        assert retries and retries[0]["backoff_s"] > 0
        failed = [a for a in record["attempts"] if a.get("failed")]
        assert failed and failed[0]["worker"]
        assert record["segments"]["retry_backoff"] > 0
        # the non-overlap invariant holds under faults too
        total = sum(record["segments"].values()) + record["unattributed_s"]
        assert abs(total - record["wall_s"]) < 1e-3
        # recovery time is attributed, not mystery wall
        assert record["coverage"] >= 0.8
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_duplicated_reply_is_deduped_by_query_token(tmp_path, mem_store_url):
    """A reply the chaos plan duplicates at the controller must be counted
    (duplicate_replies) and not double-merged: sums stay bit-identical."""
    from bqueryd_tpu import chaos

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    try:
        chaos.arm({
            "seed": 3,
            "faults": [{
                "site": "controller.reply",
                "action": "duplicate",
            }],
        })
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected, "duplicated reply must not double-merge"
        assert controller.counters["duplicate_replies"] >= 1
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_dropped_reply_recovers_via_failover(tmp_path, mem_store_url):
    """A result lost on the wire (controller.reply drop) is recovered by
    the dispatch timeout + failover re-queue; the answer stays exact."""
    from bqueryd_tpu import chaos

    # the dropping worker stays alive and heartbeating, so recovery runs
    # through the HARD timeout (live-but-silent reclaim) — shrink it
    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url, dispatch_timeout=1.0,
        dispatch_hard_timeout=1.0,
    )
    try:
        chaos.arm({
            "seed": 4,
            "faults": [{
                "site": "controller.reply",
                "action": "drop",
                "times": 1,
            }],
        })
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected
        assert controller.counters["failover_dispatches"] >= 1
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_redis_partitioned_worker_is_culled_and_inflight_requeued(
    tmp_path, mem_store_url
):
    """The redis-partition scenario: ONE worker loses the coordination
    store (heartbeats stop — its WRM broadcast path reads the store every
    tick) while its zmq sockets stay up.  With its event loop also blocked
    mid-query, the controller must time the dispatch out, re-queue the
    in-flight shard onto the surviving holder, cull the silent worker, and
    answer exactly."""
    import time as time_mod

    from bqueryd_tpu import chaos

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url, dispatch_timeout=1.0
    )
    victim = workers[0]
    # pin the first dispatch onto the victim AND block it there long enough
    # for the partition + dispatch timeout to play out
    got_work = threading.Event()
    orig_handle_work = victim.handle_work

    def slow_handle_work(msg):
        got_work.set()
        time_mod.sleep(4.0)
        return orig_handle_work(msg)

    victim.handle_work = slow_handle_work
    # the other worker must not win the first dispatch: mark it busy until
    # the victim has the work
    survivor_id = workers[1].worker_id
    try:
        chaos.arm({
            "seed": 5,
            "faults": [{
                "site": "coordination.store",
                "action": "partition",
                "match": {"node": victim.worker_id},
                "window_s": 30.0,
            }],
        })
        wait_until(
            lambda: controller.worker_map.get(survivor_id) is not None,
            desc="survivor registered",
        )
        controller.worker_map[survivor_id]["busy"] = True
        result_box = {}

        def ask():
            _, result_box["got"] = _ask_sum(mem_store_url, shards)

        asker = threading.Thread(target=ask, daemon=True)
        asker.start()
        wait_until(got_work.is_set, desc="victim received the dispatch")
        controller.worker_map[survivor_id]["busy"] = False
        asker.join(timeout=40)
        assert not asker.is_alive(), "query never completed after partition"
        assert result_box["got"] == expected
        # the partitioned worker was culled (no heartbeats reached the
        # controller once the store access started raising StorePartitioned)
        wait_until(
            lambda: victim.worker_id not in controller.worker_map,
            timeout=15,
            desc="partitioned worker culled",
        )
        assert controller.counters["failover_dispatches"] >= 1
        assert chaos.site_stats().get("coordination.store", 0) >= 1
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_dispatch_exhaustion_returns_structured_error(
    tmp_path, mem_store_url
):
    """With every holder persistently faulting (transient raises, no
    replica left to absorb them), the retry budget exhausts and the client
    gets the STRUCTURED envelope: error_class DispatchExhausted + the
    per-attempt worker/fault history — not a blind timeout."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu import chaos
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC, RPCError
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame(
        {"g": np.arange(20) % 4, "v": np.arange(20, dtype=np.int64)}
    )
    ctable.fromdataframe(df, str(tmp_path / "x.bcolzs"))
    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=10.0,
        dispatch_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    try:
        wait_until(
            lambda: "x.bcolzs" in controller.files_map, desc="registration"
        )
        chaos.arm({
            "seed": 6,
            "faults": [{
                "site": "worker.execute",
                "action": "raise",
                "match": {"verb": "groupby"},
                "args": {"error": "DeviceBusyError"},
            }],
        })
        rpc = RPC(
            coordination_url=mem_store_url, timeout=30,
            loglevel=logging.WARNING,
        )
        with pytest.raises(RPCError) as excinfo:
            rpc.groupby(["x.bcolzs"], ["g"], [["v", "sum", "s"]], [])
        err = excinfo.value
        assert getattr(err, "error_class", None) == "DispatchExhausted"
        attempts = getattr(err, "attempts", [])
        assert len(attempts) >= 1
        assert all(a.get("worker") == worker.worker_id for a in attempts)
        assert any("DeviceBusyError" in str(a.get("reason")) for a in attempts)
        assert "DispatchExhausted" in str(err)
        # the sole holder was retried (never excluded outright) and the
        # abort is structural, not a client timeout
        assert controller.counters["transient_faults"] >= 1
    finally:
        chaos.disarm()
        _stop([controller, worker], threads)


def test_hedged_dispatch_first_reply_wins(tmp_path, mem_store_url):
    """BQUERYD_TPU_HEDGE_MS: a shard stuck on a slow holder past the
    threshold is duplicated onto the other holder; the fast duplicate's
    reply answers the query (hedge_wins), the slow original's late reply
    is deduplicated by token (duplicate_replies), sums stay exact."""
    import time as time_mod

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.worker import WorkerNode

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url, dispatch_timeout=30.0,
        shards=("hedge_0.bcolzs",),
    )
    controller.hedge_ms = 300.0
    slow = workers[0]
    orig_handle_work = slow.handle_work
    slowed = threading.Event()

    def slow_handle_work(msg):
        if msg.isa("groupby"):
            slowed.set()
            time_mod.sleep(2.0)
        return orig_handle_work(msg)

    slow.handle_work = slow_handle_work
    fast_id = workers[1].worker_id
    try:
        wait_until(
            lambda: controller.worker_map.get(fast_id) is not None,
            desc="fast worker registered",
        )
        # force the first dispatch onto the slow worker
        controller.worker_map[fast_id]["busy"] = True
        result_box = {}

        def ask():
            _, result_box["got"] = _ask_sum(mem_store_url, shards)

        asker = threading.Thread(target=ask, daemon=True)
        asker.start()
        wait_until(slowed.is_set, desc="slow worker holds the shard")
        controller.worker_map[fast_id]["busy"] = False
        asker.join(timeout=30)
        assert not asker.is_alive(), "hedged query never completed"
        assert result_box["got"] == expected
        assert controller.counters["hedged_dispatches"] >= 1
        assert controller.counters["hedge_wins"] >= 1
        # the slow original eventually replies too: deduped, not re-merged
        wait_until(
            lambda: controller.counters["duplicate_replies"] >= 1,
            desc="late original reply deduplicated",
        )
    finally:
        _stop([controller] + workers, threads)


def test_late_reply_from_superseded_worker_wins_and_keeps_reclaim_handle(
    tmp_path, mem_store_url
):
    """A worker hung past the hard timeout is removed and its shard
    re-queued onto the other holder; its LATE valid reply then wins (replica
    holders compute identical payloads) — and the controller must keep a
    hard-timeout reclaim handle on the superseded attempt's worker, which is
    still computing: without one, a wedged holder sits busy-and-advertised
    forever with no watchdog."""
    import time as time_mod

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url, dispatch_timeout=0.4,
        dispatch_hard_timeout=2.0, shards=("late_0.bcolzs",),
    )
    first, second = workers
    started = threading.Event()

    def wrap(worker, delay, evt=None):
        orig = worker.handle_work

        def wrapped(msg):
            if msg.isa("groupby"):
                if evt is not None:
                    evt.set()
                time_mod.sleep(delay)
            return orig(msg)

        worker.handle_work = wrapped

    # first: outlives the 2s hard timeout, replies at 3.5s; second picks up
    # the failover ~2.1-2.6s in and computes for 3s more — so the first
    # worker's late reply lands while the second is still mid-computation
    wrap(first, 3.5, started)
    wrap(second, 3.0)
    second_id = second.worker_id
    try:
        wait_until(
            lambda: controller.worker_map.get(second_id) is not None,
            desc="second worker registered",
        )
        # force the first dispatch onto the first worker
        controller.worker_map[second_id]["busy"] = True
        result_box = {}

        def ask():
            _, result_box["got"] = _ask_sum(mem_store_url, shards)

        asker = threading.Thread(target=ask, daemon=True)
        asker.start()
        wait_until(started.is_set, desc="first worker holds the shard")
        controller.worker_map[second_id]["busy"] = False
        asker.join(timeout=30)
        assert not asker.is_alive(), "query never completed"
        assert result_box["got"] == expected
        # the hard timeout really failed the shard over to the second holder
        assert controller.counters["failover_dispatches"] >= 1
        # ...and the first worker's late reply won while the second is still
        # computing: its reclaim handle must survive the inflight-entry pop
        assert any(
            second_id in rec["workers"]
            for rec in controller._hedge_losers.values()
        ), "no reclaim handle kept on the superseded attempt's worker"
        # the handle resolves: the loser answers (deduped by token) or is
        # reclaimed past the hard cap — either way tracking drains
        wait_until(
            lambda: not controller._hedge_losers,
            desc="superseded attempt deduplicated or reclaimed",
        )
    finally:
        _stop([controller] + workers, threads)


def test_requeue_of_hedged_entry_collapses_onto_surviving_duplicate(
    mem_store_url,
):
    """A hedged flight whose original side times out (or is culled) must
    NOT requeue a third execution — and must not leave the token in the
    hedge dedup ring, where the surviving duplicate's valid reply would be
    discarded as a 'duplicate' while the shard is still unanswered.  The
    inflight entry collapses onto the survivor with a rebased timeout
    clock and the failed side excluded."""
    import time

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import CalcMessage, WorkerRegisterMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent", dispatch_timeout=0.01,
        dispatch_hard_timeout=0.02,
    )
    try:
        for wid in ("wa", "wb"):
            controller.handle_worker(
                wid.encode(),
                WorkerRegisterMessage({
                    "worker_id": wid, "workertype": "calc",
                    "data_files": ["s.bcolzs"],
                }),
            )
        msg = CalcMessage({
            "payload": "groupby", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        now = time.time()
        controller.inflight["t1"] = {
            "worker": "wa", "sent_at": now - 60, "msg": msg,
            "parent": "p1", "retries": 0,
            "hedged": "wb", "hedged_at": now,
        }
        controller._hedged_tokens["t1"] = now
        controller.retry_stale_dispatches()
        entry = controller.inflight["t1"]
        assert entry["worker"] == "wb" and "hedged" not in entry
        assert entry["sent_at"] == now, "survivor clock rebased to the hedge"
        assert "t1" not in controller._hedged_tokens, (
            "dedup ring entry would discard the survivor's valid reply"
        )
        assert not any(controller.worker_out_messages.values()), (
            "redundant third execution queued"
        )
        assert msg.get("_excluded_workers") == ["wa"]
        # the hung-but-heartbeating original was reclaimed like any other
        # hung dispatch; the survivor's entry was left alone
        assert "wa" not in controller.worker_map
        assert controller.inflight["t1"]["worker"] == "wb"

        # cull of the HEDGE side: the original attempt stands alone again
        msg2 = CalcMessage({
            "payload": "groupby", "token": "t2", "parent_token": "p2",
            "filename": "s.bcolzs",
        })
        controller.inflight["t2"] = {
            "worker": "wb", "sent_at": now, "msg": msg2,
            "parent": "p2", "retries": 0,
            "hedged": "wc", "hedged_at": now,
        }
        controller._hedged_tokens["t2"] = now
        controller.remove_worker("wc")
        entry2 = controller.inflight["t2"]
        assert entry2["worker"] == "wb" and "hedged" not in entry2
        assert "t2" not in controller._hedged_tokens
        assert msg2.get("_excluded_workers") == ["wc"]
    finally:
        controller.socket.close()


def test_stale_replies_while_retry_parked_neither_abort_nor_reexecute(
    mem_store_url,
):
    """While a timed-out shard's retry is still parked in the dispatch
    queue (backoff window / no free holder), a late reply from the FAILED
    attempt must not abort the query — the parked retry stands for a
    stale ERROR — and a late VALID result wins outright, withdrawing the
    queued retry instead of burning a worker on a finished shard."""
    import time

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import CalcMessage, ErrorMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        aborted = []
        controller.abort_parent = (
            lambda parent, *a, **k: aborted.append(parent)
        )
        msg = CalcMessage({
            "payload": "groupby", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        entry = {
            "worker": "wa", "sent_at": time.time() - 60, "msg": msg,
            "parent": "p1", "retries": 0,
        }
        controller._requeue(entry, reason="test: dispatch timeout")
        assert "t1" in controller._requeued_tokens
        # late NON-transient error from the failed attempt: dropped, the
        # parked retry stands (the old path aborted the parent here)
        err = ErrorMessage({
            "payload": "boom", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        controller.handle_worker(b"wa", err)
        assert aborted == [], (
            "stale fault aborted a query with a healthy retry parked"
        )
        assert controller.counters["duplicate_replies"] == 1
        queued = controller.worker_out_messages.get(None, [])
        assert [m.get("token") for m in queued] == ["t1"]
        # late VALID result from the failed attempt: delivered (first
        # reply wins) and the queued retry is withdrawn
        reply = CalcMessage({
            "payload": "groupby", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        controller.handle_worker(b"wa", reply)
        assert aborted == []
        assert "t1" not in controller._requeued_tokens
        assert not any(controller.worker_out_messages.values()), (
            "answered shard left queued for a redundant execution"
        )
        # the win leaves a dedup-ring marker: ANOTHER superseded attempt
        # may still be computing the token, and its later non-transient
        # error must be counted and dropped — not reach the orphan
        # fall-through and abort the answered parent
        assert "t1" in controller._hedged_tokens
        late_err = ErrorMessage({
            "payload": "boom", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        dups_before = controller.counters["duplicate_replies"]
        controller.handle_worker(b"wb", late_err)
        assert aborted == [], (
            "late error from a second superseded attempt aborted the "
            "answered query"
        )
        assert controller.counters["duplicate_replies"] == dups_before + 1
    finally:
        controller.socket.close()


def test_orphan_loser_error_after_ring_eviction_does_not_abort(
    mem_store_url,
):
    """A late NON-transient ErrorMessage from a hedge loser whose
    dedup-ring marker was evicted by the 256-entry cap must not abort the
    parent: ``_hedge_losers`` outlives the ring and proves the token was
    already answered, so the reply is counted and dropped like the ring
    branch would have."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import ErrorMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        aborted = []
        controller.abort_parent = (
            lambda parent, *a, **k: aborted.append(parent)
        )
        # token answered long ago: the winning reply noted the loser, then
        # 256+ newer hedges evicted the ring marker
        controller._note_losers("t1", ["wa"])
        assert "t1" not in controller._hedged_tokens
        err = ErrorMessage({
            "payload": "shard file vanished", "token": "t1",
            "parent_token": "p1", "filename": "s.bcolzs",
        })
        controller.handle_worker(b"wa", err)
        assert aborted == [], (
            "orphan loser error aborted a query whose shard was merged"
        )
        assert controller.counters["duplicate_replies"] == 1
        assert "t1" not in controller._hedge_losers, (
            "answered loser left holding a hard-timeout reclaim handle"
        )
    finally:
        controller.socket.close()


def test_hedged_nontransient_error_defers_to_survivor(mem_store_url):
    """A NON-transient ErrorMessage from one side of a hedged pair must
    not abort the query (nor count a hedge win) while the other side is
    still computing: the inflight entry collapses onto the survivor, whose
    answer decides."""
    import time

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import CalcMessage, ErrorMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        aborted = []
        controller.abort_parent = (
            lambda parent, *a, **k: aborted.append(parent)
        )
        msg = CalcMessage({
            "payload": "groupby", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        now = time.time()
        controller.inflight["t1"] = {
            "worker": "wa", "sent_at": now, "msg": msg, "parent": "p1",
            "retries": 0, "hedged": "wb", "hedged_at": now,
        }
        controller._hedged_tokens["t1"] = now
        err = ErrorMessage({
            "payload": "corrupt shard copy", "token": "t1",
            "parent_token": "p1", "filename": "s.bcolzs",
        })
        controller.handle_worker(b"wb", err)
        assert aborted == [], (
            "hedge-side permanent error aborted a query whose original "
            "attempt is healthy and still computing"
        )
        entry = controller.inflight["t1"]
        assert entry["worker"] == "wa" and "hedged" not in entry
        assert controller.counters["hedge_wins"] == 0, (
            "an error reply counted as a hedge win"
        )
        assert controller.counters["transient_faults"] == 0
        assert "t1" not in controller._hedged_tokens, (
            "survivor's valid reply would be deduplicated away"
        )
        assert msg.get("_excluded_workers") == ["wb"]
    finally:
        controller.socket.close()


def test_segment_completion_tolerates_overlapping_batch_and_children(
    mem_store_url,
):
    """A re-split batch can leave BOTH the late batch payload and its
    per-shard children in a segment's results: overlapping keys must
    neither complete the segment early (sum-of-key-lengths said 4/4 with
    half the files uncovered) nor merge a shard's payload twice."""
    import pickle

    from bqueryd_tpu.controller import ControllerNode

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        replies = []
        controller.reply_rpc_raw = (
            lambda tok, data: replies.append((tok, data))
        )
        controller._finalize_query_obs = lambda *a, **k: None
        segment = {
            "client_token": "c1",
            "filenames": ["f1", "f2", "f3", "f4"],
            # children (f1,) (f2,) answered, then the original batch's
            # late valid reply was delivered too
            "results": {
                ("f1",): b"c1", ("f2",): b"c2", ("f1", "f2"): b"b12",
            },
            "timings": {},
            "admission_ticket": None,
            "pruned": [],
            "obs": None,
            "strategies": {},
            "effective": {},
        }
        controller.rpc_segments["p1"] = segment
        controller._maybe_complete_segment("p1")
        assert "p1" in controller.rpc_segments and not replies, (
            "overlapping keys double-counted into premature completion"
        )
        segment["results"][("f3", "f4")] = b"b34"
        controller._maybe_complete_segment("p1")
        assert "p1" not in controller.rpc_segments and replies
        payloads = pickle.loads(replies[0][1])["payloads"]
        assert payloads == [b"b12", b"b34"], (
            "per-shard children merged alongside their own batch payload"
        )
    finally:
        controller.socket.close()


def test_maybe_hedge_skips_entries_requeued_mid_loop(mem_store_url):
    """Culling a gone hedge target mid-loop requeues that worker's OTHER
    inflight entries: the stale snapshot items must be skipped, not
    hedged — a ring marker for a parked token would discard the retry's
    valid reply as a duplicate and burn a redundant execution."""
    import time

    import zmq as zmq_mod

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import CalcMessage, WorkerRegisterMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        controller.hedge_ms = 1.0
        for wid in ("wa", "wx", "wb"):
            controller.handle_worker(
                wid.encode(),
                WorkerRegisterMessage({
                    "worker_id": wid, "workertype": "calc",
                    "data_files": ["s.bcolzs"],
                }),
            )
        now = time.time()
        for token, worker in (("t1", "wa"), ("t2", "wx")):
            m = CalcMessage({
                "payload": "groupby", "token": token,
                "parent_token": f"p-{token}", "filename": "s.bcolzs",
            })
            controller.inflight[token] = {
                "worker": worker, "sent_at": now - 60, "msg": m,
                "parent": f"p-{token}", "retries": 0,
            }
        picks = iter(["wx", "wb"])
        controller.find_free_worker = (
            lambda *a, **k: next(picks)
        )

        def dead_route(target, msg):
            raise zmq_mod.ZMQError()

        controller._dispatch_wire = dead_route
        controller.maybe_hedge()
        # hedging t1 onto gone wx culled wx, requeueing t2 mid-loop: the
        # snapshot item for t2 must be skipped, not hedged
        assert "t2" in controller._requeued_tokens
        assert "t2" not in controller._hedged_tokens, (
            "parked token marked in the dedup ring — its retry's valid "
            "reply would be discarded as a duplicate"
        )
        assert controller.counters["hedged_dispatches"] == 0
    finally:
        controller.socket.close()


def test_replayed_transient_error_counts_once(mem_store_url):
    """A chaos-duplicated transient ErrorMessage must count ONE
    transient_fault: the replay enters process_worker_result with no
    inflight entry and is a duplicate of the fault, not a new one."""
    import time

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import CalcMessage, ErrorMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        msg = CalcMessage({
            "payload": "groupby", "token": "t1", "parent_token": "p1",
            "filename": "s.bcolzs",
        })
        entry = {
            "worker": "wa", "sent_at": time.time(), "msg": msg,
            "parent": "p1", "retries": 0,
        }
        err = ErrorMessage({
            "payload": "DeviceBusyError: chaos", "token": "t1",
            "parent_token": "p1", "filename": "s.bcolzs",
            "transient": True,
        })
        controller.process_worker_result(err, entry)   # the real fault
        controller.process_worker_result(err, None)    # the chaos replay
        assert controller.counters["transient_faults"] == 1, (
            "one injected duplicate inflated the transient-fault rate"
        )
        assert controller.counters["duplicate_replies"] == 1
    finally:
        controller.socket.close()


def test_hedged_transient_fault_defers_to_outstanding_duplicate(
    tmp_path, mem_store_url
):
    """A transient fault from one side of a hedged pair must NOT requeue or
    abort the shard while the duplicate is still computing: the inflight
    entry is re-keyed to the survivor, whose reply answers the query — no
    redundant third execution (failover_dispatches stays 0) and no
    DispatchExhausted abort with a correct answer in flight."""
    from bqueryd_tpu import chaos as chaos_mod

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url, dispatch_timeout=30.0,
        shards=("hedtr_0.bcolzs",),
    )
    controller.hedge_ms = 200.0
    faulty, steady = workers
    faulty_started = threading.Event()
    fault_now = threading.Event()
    steady_go = threading.Event()

    orig_faulty = faulty.handle_work

    def faulty_work(msg):
        if msg.isa("groupby"):
            faulty_started.set()
            fault_now.wait(timeout=20)
            raise chaos_mod.DeviceBusyError("injected: hedged-pair fault")
        return orig_faulty(msg)

    faulty.handle_work = faulty_work
    orig_steady = steady.handle_work

    def steady_work(msg):
        if msg.isa("groupby"):
            steady_go.wait(timeout=20)
        return orig_steady(msg)

    steady.handle_work = steady_work
    steady_id = steady.worker_id
    try:
        wait_until(
            lambda: controller.worker_map.get(steady_id) is not None,
            desc="steady worker registered",
        )
        # force the first dispatch onto the faulty worker
        controller.worker_map[steady_id]["busy"] = True
        result_box = {}

        def ask():
            _, result_box["got"] = _ask_sum(mem_store_url, shards)

        asker = threading.Thread(target=ask, daemon=True)
        asker.start()
        wait_until(faulty_started.is_set, desc="faulty worker holds the shard")
        controller.worker_map[steady_id]["busy"] = False
        wait_until(
            lambda: controller.counters["hedged_dispatches"] >= 1,
            desc="tail shard hedged onto the steady holder",
        )
        fault_now.set()
        wait_until(
            lambda: controller.counters["transient_faults"] >= 1,
            desc="transient fault from the hedged pair processed",
        )
        # no requeue happened: the entry now rides the surviving duplicate
        assert controller.counters["failover_dispatches"] == 0
        assert [
            e["worker"] for e in controller.inflight.values()
        ] == [steady_id]
        steady_go.set()
        asker.join(timeout=30)
        assert not asker.is_alive(), "query never completed"
        assert result_box["got"] == expected
        assert controller.counters["failover_dispatches"] == 0
        assert not controller.inflight
    finally:
        _stop([controller] + workers, threads)


def test_bundle_shared_scan_fails_over_as_one_unit(
    tmp_path, mem_store_url, monkeypatch
):
    """Bundle x PR-8 failover: two distinct-but-compatible concurrent
    queries fuse into ONE shared-scan bundle inside the admission window;
    a chaos transient fault (wedge -> DeviceBusyError) on the first holder
    fails the WHOLE bundle over to the replica holder — both members get
    bit-exact answers, neither aborts, and no member is executed twice for
    one successful attempt (one bundle token end to end)."""
    from bqueryd_tpu import chaos
    from bqueryd_tpu.rpc import RPC

    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url, df_seed=17
    )
    monkeypatch.setenv("BQUERYD_TPU_BATCH_WINDOW_MS", "400")
    try:
        chaos.arm({
            "seed": 9,
            "faults": [{
                "site": "worker.execute",
                "action": "wedge",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        # distinct signatures (different filter conjunctions over the full
        # value range), identical answers: both cover every row
        lo = -(2**41)
        queries = [
            (list(shards), ["g"], [["v", "sum", "s"]], [["v", ">", lo]]),
            (list(shards), ["g"], [["v", "sum", "s"]], [["v", ">=", lo]]),
        ]
        results, errors = {}, {}

        def ask(i):
            try:
                rpc = RPC(
                    coordination_url=mem_store_url, timeout=60,
                    loglevel=logging.WARNING,
                )
                df = rpc.groupby(*queries[i])
                results[i] = dict(zip(df["g"].tolist(), df["s"].tolist()))
            except Exception as exc:  # noqa: BLE001
                errors[i] = exc

        askers = [
            threading.Thread(target=ask, args=(i,), daemon=True)
            for i in range(2)
        ]
        for t in askers:
            t.start()
        for t in askers:
            t.join(90)
        assert not errors, errors
        assert results[0] == expected
        assert results[1] == expected
        # the two queries rode ONE bundle...
        assert controller.counters["plan_bundles"] >= 1
        assert controller.counters["plan_bundled_queries"] >= 2
        # ...which failed over as one unit on the transient fault
        assert controller.counters["transient_faults"] >= 1
        assert controller.counters["failover_dispatches"] >= 1
        # a transient fault never culls: both holders still registered,
        # exactly one latched its chaos wedge
        assert len(controller.worker_map) == 2
        assert sum(1 for w in workers if w._chaos_wedged) == 1
        wait_until(
            lambda: not controller.inflight and not controller.rpc_segments,
            desc="bundle settled after failover",
        )
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def _warm_capacity_model(controller, workers, mem_store_url, shards,
                         expected, max_queries=20):
    """Query until every worker has a measured μ in the capacity model
    (random dispatch placement reaches both holders within a few tries)."""
    import time as _time

    def measured():
        ws = controller.capacity.evaluate().get("workers", {})
        return all(
            ws.get(w.worker_id, {}).get("mu") is not None for w in workers
        )

    deadline = _time.time() + 30
    for _ in range(max_queries):
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected
        if measured():
            return
        if _time.time() > deadline:
            break
        _time.sleep(0.2)  # let a WRM carry the bumped totals
    wait_until(measured, desc="every worker measured by the capacity model")


def test_capacity_kill_worker_shrinks_fleet_mu_and_advises_scale_up(
    tmp_path, mem_store_url, monkeypatch
):
    """PR-8 kill-worker chaos under the PR-12 capacity model: the dead
    worker's μ leaves the fleet aggregate, no query fails (replica
    failover), and with load still arriving the shadow advisor flips to
    scale_up.  Thresholds are pinned low so the micro-queries' utilization
    registers — the test targets the mechanism, not the default knobs."""
    from bqueryd_tpu import chaos

    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_HYSTERESIS_S", "0")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_WINDOW_S", "20")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_SATURATED", "0.005")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_WARM", "0.002")
    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    try:
        _warm_capacity_model(
            controller, workers, mem_store_url, shards, expected
        )
        before = controller.capacity.evaluate()["fleet"]
        assert before["measured_workers"] == 2
        chaos.arm({
            "seed": 5,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected  # failover: ZERO failed queries
        chaos.disarm()
        wait_until(
            lambda: len(controller.worker_map) == 1,
            desc="dead worker culled",
        )
        # keep load arriving so the advisor has evidence post-kill
        for _ in range(3):
            _, again = _ask_sum(mem_store_url, shards)
            assert again == expected
        result = controller.capacity.evaluate()
        fleet = result["fleet"]
        assert fleet["workers"] == 1
        assert fleet["measured_workers"] == 1
        # the dead worker's μ left the aggregate: the model dropped it
        # entirely, and fleet capacity is now the survivor's μ alone (the
        # raw sum comparison would race the survivor's own EWMA drifting
        # as warm micro-queries speed up)
        dead = [w for w in workers if w.worker_id not in
                controller.worker_map]
        assert len(dead) == 1
        assert dead[0].worker_id not in result["workers"]
        survivor_mu = [
            w["mu"] for wid, w in result["workers"].items()
        ]
        assert len(survivor_mu) == 1 and survivor_mu[0] is not None
        assert fleet["mu_dispatches_per_s"] == pytest.approx(
            survivor_mu[0], rel=0.01
        )
        actions = [r["action"] for r in result["recommendations"]]
        assert "scale_up" in actions, result["recommendations"]
        assert controller.counters["capacity_scale_up_advised"] >= 1
        assert controller.counters["failover_dispatches"] >= 1
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_capacity_wedge_device_shrinks_fleet_mu_and_advises_scale_up(
    tmp_path, mem_store_url, monkeypatch
):
    """Wedge-device chaos: the wedged worker stays registered (transient
    failover serves its queries from the replica holder) but its
    advertised latch excludes its μ from fleet capacity — fleet μ shrinks,
    queries keep succeeding, and the advisor flips to scale_up."""
    from bqueryd_tpu import chaos

    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_HYSTERESIS_S", "0")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_WINDOW_S", "20")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_SATURATED", "0.005")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_WARM", "0.002")
    controller, workers, threads, expected, shards = _replica_cluster(
        tmp_path, mem_store_url
    )
    try:
        _warm_capacity_model(
            controller, workers, mem_store_url, shards, expected
        )
        before = controller.capacity.evaluate()["fleet"]
        assert before["measured_workers"] == 2
        chaos.arm({
            "seed": 6,
            "faults": [{
                "site": "worker.execute",
                "action": "wedge",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        _, got = _ask_sum(mem_store_url, shards)
        assert got == expected  # transient failover: ZERO failed queries
        chaos.disarm()
        wedged = [w for w in workers if w._chaos_wedged]
        assert len(wedged) == 1
        # the latch must ride a WRM into the capacity model
        wait_until(
            lambda: controller.capacity.evaluate()
            .get("workers", {})
            .get(wedged[0].worker_id, {})
            .get("wedged") is True,
            desc="wedge latch absorbed by the capacity model",
        )
        for _ in range(3):
            _, again = _ask_sum(mem_store_url, shards)
            assert again == expected
        result = controller.capacity.evaluate()
        fleet = result["fleet"]
        # both workers still registered — but the wedged one is no longer
        # counted as capacity: fleet μ is the healthy worker's alone (the
        # raw before/after sum comparison would race the healthy worker's
        # own EWMA drift on warm micro-queries)
        assert len(controller.worker_map) == 2
        assert fleet["workers"] == 2
        assert fleet["measured_workers"] == 1
        healthy_mu = [
            w["mu"] for w in result["workers"].values()
            if not w["wedged"] and w["mu"] is not None
        ]
        assert len(healthy_mu) == 1
        assert fleet["mu_dispatches_per_s"] == pytest.approx(
            healthy_mu[0], rel=0.01
        )
        actions = [r["action"] for r in result["recommendations"]]
        assert "scale_up" in actions, result["recommendations"]
        assert controller.counters["transient_faults"] >= 1
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)


def test_append_delta_failover_chaos(tmp_path, mem_store_url):
    """PR-14 acceptance: append + kill-worker during a delta-refresh burst
    leaves ZERO failed queries with results bit-exact vs a full recompute.

    True replica topology (each worker owns its own data_dir copy of the
    shard): rpc.append fans the batch to BOTH holders; a die_after_ack
    chaos kill mid-burst fails the in-flight query over to the surviving
    replica; post-cull appends route to the survivor alone and its repeat
    queries keep being served by delta refreshes."""
    import shutil

    import numpy as np
    import pandas as pd

    from bqueryd_tpu import chaos
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(77)

    def batch(n, offset):
        return pd.DataFrame(
            {
                "g": rng.integers(0, 4, n).astype(np.int64),
                "v": rng.integers(-(2**40), 2**40, n).astype(np.int64),
                "seq": np.arange(offset, offset + n, dtype=np.int64),
            }
        )

    frame = batch(1200, 0)
    dirs = [tmp_path / "a", tmp_path / "b"]
    dirs[0].mkdir()
    ctable.fromdataframe(
        frame, str(dirs[0] / "t.bcolzs"), chunklen=256
    )
    shutil.copytree(str(dirs[0] / "t.bcolzs"), str(tmp_path / "b" / "t.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,
        dispatch_timeout=1.5,
    )
    workers = [
        WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(d),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )
        for d in dirs
    ]
    threads = _start(controller, *workers)
    q = (["t.bcolzs"], ["g"], [["v", "sum", "s"]], [])

    def expect(df):
        return df.groupby("g")["v"].sum().to_dict()

    try:
        wait_until(
            lambda: len(controller.files_map.get("t.bcolzs", ())) == 2,
            desc="both replica holders advertising",
        )
        rpc = RPC(
            coordination_url=mem_store_url, timeout=45,
            loglevel=logging.WARNING,
        )
        got = rpc.groupby(*q)
        assert dict(zip(got["g"], got["s"])) == expect(frame)

        # append #1 lands on BOTH replicas
        extra1 = batch(150, 1200)
        res = rpc.append("t.bcolzs", extra1)
        assert res["appended"] == 150 and len(res["holders"]) == 2
        assert all(
            ctable(str(d / "t.bcolzs")).nrows == 1350 for d in dirs
        )
        frame = pd.concat([frame, extra1], ignore_index=True)
        got = rpc.groupby(*q)
        assert dict(zip(got["g"], got["s"])) == expect(frame)
        # (which holder serves each query is a scheduling choice, so the
        # "delta" route is asserted deterministically below, once a single
        # survivor serves everything)

        # kill one holder mid-burst: the in-flight query fails over
        chaos.arm({
            "seed": 5,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        extra2 = batch(150, 1350)
        # the dying side may or may not have applied extra2 before the
        # kill fires on the next groupby — the SURVIVOR's state is what
        # queries answer from, so append first, then query through chaos
        failed = 0
        try:
            rpc.append("t.bcolzs", extra2, deadline=20)
        except Exception:
            # a holder that died mid-append reports a structured error;
            # the surviving replica applied it (asserted via parity below)
            pass
        frame = pd.concat([frame, extra2], ignore_index=True)
        try:
            got = rpc.groupby(*q)
        except Exception:
            failed += 1
        assert failed == 0, "chaos burst must leave zero failed queries"
        assert dict(zip(got["g"], got["s"])) == expect(frame)
        assert chaos.injected_total() >= 1
        chaos.disarm()

        wait_until(
            lambda: len(controller.worker_map) == 1,
            desc="dead worker culled",
        )
        survivor = [
            w for w in workers
            if w.worker_id in controller.worker_map
        ][0]

        # the survivor serves everything now: establish its delta base,
        # append (routes to it alone), and the repeat MUST delta-refresh
        got = rpc.groupby(*q)
        assert dict(zip(got["g"], got["s"])) == expect(frame)
        extra3 = batch(100, 1500)
        res = rpc.append("t.bcolzs", extra3)
        assert len(res["holders"]) == 1
        frame = pd.concat([frame, extra3], ignore_index=True)
        refreshes_before = survivor.delta_refreshes_total.value
        got = rpc.groupby(*q)
        assert dict(zip(got["g"], got["s"])) == expect(frame)
        assert survivor.delta_refreshes_total.value > refreshes_before
        assert (
            rpc.last_call_strategies["effective"]["t.bcolzs"] == "delta"
        )
        assert controller.counters["failover_dispatches"] >= 1
    finally:
        chaos.disarm()
        _stop([controller] + workers, threads)
