"""Failure-path and control-plane coverage the reference never had.

SURVEY.md §4 lists the reference's test blind spots: multi-controller
peering, worker death/cull, execute_code, and the memory watchdog.  These
tests close them, using the same threads-as-nodes topology as
tests/test_rpc_cluster.py (the reference's own fixture style, reference
tests/test_simple_rpc.py:42-74) with condition polling instead of sleeps.
"""

import logging
import os
import threading

import pytest

from conftest import wait_until


def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        if node is not None:  # a test may fail before creating late nodes
            node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture
def small_cluster(tmp_path, mem_store_url):
    """One controller + one calc worker, fast heartbeats, no data files."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
        dead_worker_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(lambda: controller.worker_map, desc="worker registration")
    rpc = RPC(
        coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
    )
    yield {"rpc": rpc, "controller": controller, "worker": worker}
    _stop([controller, worker], threads)


def test_execute_code_roundtrip(small_cluster, monkeypatch):
    """The reference's deliberate remote-execution verb (reference
    bqueryd/worker.py:250-267) — here gated behind an explicit env flag."""
    monkeypatch.setenv("BQUERYD_TPU_ENABLE_EXECUTE_CODE", "1")
    result = small_cluster["rpc"].execute_code(
        function="math.gcd", args=[12, 18], wait=True
    )
    assert result == 6


def test_execute_code_direct_kwargs(small_cluster, monkeypatch):
    """Keywords other than function/args/kwargs/wait go to the function."""
    monkeypatch.setenv("BQUERYD_TPU_ENABLE_EXECUTE_CODE", "1")
    result = small_cluster["rpc"].execute_code(
        function="fnmatch.fnmatch", name="shard_3.bcolzs", pat="shard_*",
        wait=True,
    )
    assert result is True


def test_execute_code_disabled_by_default(small_cluster, monkeypatch):
    from bqueryd_tpu.rpc import RPCError

    monkeypatch.delenv("BQUERYD_TPU_ENABLE_EXECUTE_CODE", raising=False)
    with pytest.raises(RPCError, match="execute_code disabled"):
        small_cluster["rpc"].execute_code(
            function="math.gcd", args=[12, 18], wait=True
        )


def test_dead_worker_culled_and_rejoins(tmp_path, mem_store_url):
    """A worker that dies silently (no StopMessage) is culled after
    dead_worker_timeout and dropped from files_map (reference
    bqueryd/controller.py:548-552); a later heartbeat re-registers it."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame({"g": np.arange(10), "v": np.arange(10)})
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=0.5,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    try:
        wait_until(
            lambda: "t.bcolzs" in controller.files_map, desc="registration"
        )
        # crash the worker: no StopMessage, no heartbeats, just silence
        worker.stop = lambda: None
        worker.running = False
        wait_until(
            lambda: not controller.worker_map,
            timeout=10,
            desc="silent worker culled",
        )
        assert not controller.files_map.get("t.bcolzs")

        # a restarted worker (fresh identity, same files) is picked up again
        worker2 = WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.1,
            poll_timeout=0.05,
        )
        threads += _start(worker2)
        wait_until(
            lambda: "t.bcolzs" in controller.files_map
            and controller.files_map["t.bcolzs"],
            desc="replacement worker registered",
        )
    finally:
        _stop([controller, worker, locals().get("worker2")], threads)


def test_controller_peering_and_killall(tmp_path, mem_store_url):
    """Two controllers on one store discover each other via the membership
    set + gossip (reference bqueryd/controller.py:77-106) and killall fans
    out to peers (reference bqueryd/controller.py:510-516)."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC

    a = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "a"),
        heartbeat_interval=0.1,
    )
    b = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "b"),
        heartbeat_interval=0.1,
    )
    threads = _start(a, b)
    try:
        wait_until(
            lambda: b.address in a.others and a.address in b.others,
            desc="mutual peer discovery",
        )
        rpc = RPC(
            coordination_url=mem_store_url,
            address=a.address,
            timeout=30,
            loglevel=logging.WARNING,
        )
        info = rpc.info()
        assert b.address in info["others"]
        rpc.killall()
        wait_until(
            lambda: not a.running and not b.running,
            desc="killall reached both controllers",
        )
        # both unregistered from the membership set
        from bqueryd_tpu import REDIS_SET_KEY
        from bqueryd_tpu.coordination import coordination_store

        wait_until(
            lambda: not coordination_store(mem_store_url).smembers(
                REDIS_SET_KEY
            ),
            desc="membership set emptied",
        )
    finally:
        _stop([a, b], threads)


def test_busy_worker_outliving_dead_timeout_not_culled(tmp_path, mem_store_url):
    """Work that outlives dead_worker_timeout still completes: the liveness
    thread keeps heartbeating while handle_work blocks the event loop, so the
    controller must neither cull the busy worker nor drop its files_map
    entries mid-query (the round-1 benchmark failure: 'file(s) no longer on
    any worker')."""
    import time as time_mod

    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame(
        {"g": np.arange(20) % 4, "v": np.arange(20, dtype=np.int64)}
    )
    ctable.fromdataframe(df, str(tmp_path / "slow.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,   # far below the query's runtime
        dispatch_timeout=30.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.3,
        poll_timeout=0.05,
    )
    # make the query block the worker's event loop well past the cull timeout
    orig_handle_work = worker.handle_work

    def slow_handle_work(msg):
        time_mod.sleep(2.5)
        return orig_handle_work(msg)

    worker.handle_work = slow_handle_work

    threads = _start(controller, worker)
    try:
        wait_until(
            lambda: "slow.bcolzs" in controller.files_map, desc="registration"
        )
        rpc = RPC(
            coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
        )
        result = rpc.groupby(
            ["slow.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
        )
        got = dict(zip(result["g"].tolist(), result["v_sum"].tolist()))
        expect = df.groupby("g")["v"].sum().to_dict()
        assert got == expect
        # the worker survived: still registered, file still advertised
        assert worker.worker_id in controller.worker_map
        assert "slow.bcolzs" in controller.files_map
    finally:
        _stop([controller, worker], threads)


def test_shard_retry_lands_on_replacement_worker(tmp_path, mem_store_url):
    """A worker that dies mid-flight (work dispatched, no reply, silence)
    gets its shard requeued after dispatch_timeout and the retry completes on
    a replacement worker — the dispatch-tracking behaviour the reference left
    as a TODO (reference bqueryd/controller.py:265)."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    df = pd.DataFrame(
        {"g": np.arange(30) % 3, "v": np.arange(30, dtype=np.int64)}
    )
    ctable.fromdataframe(df, str(tmp_path / "r.bcolzs"))

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,
        dispatch_timeout=1.5,
    )
    worker_a = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.05,
    )
    a_got_work = threading.Event()

    def crash_mid_work(msg):
        """Simulate a hard crash: stop heartbeating, never reply."""
        a_got_work.set()
        worker_a.stop = lambda: None       # no StopMessage: silent death
        worker_a._hb_stop.set()            # liveness thread dies too
        worker_a.running = False
        return None

    worker_a.handle_work = crash_mid_work

    worker_b = None
    threads = _start(controller, worker_a)
    try:
        wait_until(
            lambda: "r.bcolzs" in controller.files_map, desc="registration"
        )
        rpc = RPC(
            coordination_url=mem_store_url, timeout=45, loglevel=logging.WARNING
        )
        result_box = {}

        def ask():
            result_box["df"] = rpc.groupby(
                ["r.bcolzs"], ["g"], [["v", "sum", "v_sum"]], []
            )

        asker = threading.Thread(target=ask, daemon=True)
        asker.start()
        wait_until(a_got_work.is_set, desc="worker A received the shard")
        # bring up the replacement holding the same shard file
        worker_b = WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )
        threads += _start(worker_b)
        asker.join(timeout=40)
        assert not asker.is_alive(), "query never completed after retry"
        result = result_box["df"]
        got = dict(zip(result["g"].tolist(), result["v_sum"].tolist()))
        assert got == df.groupby("g")["v"].sum().to_dict()
        # the retry really happened on B: A is gone from the worker map
        wait_until(
            lambda: worker_a.worker_id not in controller.worker_map,
            desc="dead worker culled",
        )
        assert worker_b.worker_id in controller.worker_map
    finally:
        _stop([controller, worker_a, worker_b], threads)


def test_memory_watchdog_stops_over_limit_worker(tmp_path, mem_store_url):
    """RSS above the limit (and caches shed without relief) stops the loop so
    a supervisor can restart the process (reference bqueryd/worker.py:232-241,
    2 GB cap)."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=True,
        memory_limit_mb=1,  # any real process RSS exceeds this
    )
    worker.running = True
    worker._check_mem()
    assert worker.running is False
    worker.socket.close()


def test_memory_watchdog_unmeasurable_shed_still_stops(
    tmp_path, mem_store_url, monkeypatch
):
    """If the post-shed RSS read fails, the pre-shed over-limit reading wins
    and the worker still restarts (no silent disable of the safety net)."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=True,
        memory_limit_mb=1,
    )
    monkeypatch.setattr(worker, "_shed_caches", lambda: None)
    worker.running = True
    worker._check_mem()
    assert worker.running is False
    worker.socket.close()


def test_memory_watchdog_shed_recovery_keeps_running(
    tmp_path, mem_store_url, monkeypatch
):
    """If shedding caches brings RSS back under the limit, the worker keeps
    serving instead of restarting."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=True,
        memory_limit_mb=1,
    )
    monkeypatch.setattr(worker, "_shed_caches", lambda: 0.5)
    worker.running = True
    worker._check_mem()
    assert worker.running is True
    worker.socket.close()


def test_two_controllers_both_get_heartbeats_during_long_work(
    tmp_path, mem_store_url
):
    """Per-controller ADDRESSED heartbeat delivery: with two controllers and
    the worker's event loop blocked in a long handle_work, BOTH controllers'
    last_seen must keep refreshing (a single shared DEALER round-robins its
    sends across peers, making per-controller delivery probabilistic)."""
    import time as time_mod

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    a = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "a"),
        heartbeat_interval=0.05,
        dead_worker_timeout=10.0,
    )
    b = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path / "b"),
        heartbeat_interval=0.05,
        dead_worker_timeout=10.0,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = _start(a, b, worker)
    try:
        wid = worker.worker_id
        wait_until(
            lambda: wid in a.worker_map and wid in b.worker_map,
            desc="worker registered on both controllers",
        )
        rpc = RPC(
            coordination_url=mem_store_url,
            address=a.address,
            timeout=30,
            loglevel=logging.WARNING,
        )
        done = threading.Event()

        def ask():
            rpc.sleep(2.0)
            done.set()

        threading.Thread(target=ask, daemon=True).start()
        wait_until(
            lambda: a.worker_map.get(wid, {}).get("busy"),
            desc="worker busy in long work",
        )
        # while the event loop is blocked, sample last_seen on BOTH
        seen_a0 = a.worker_map[wid]["last_seen"]
        seen_b0 = b.worker_map[wid]["last_seen"]
        time_mod.sleep(0.6)  # several heartbeat ticks
        assert not done.is_set(), "work finished too early to measure"
        assert a.worker_map[wid]["last_seen"] > seen_a0
        assert b.worker_map[wid]["last_seen"] > seen_b0
        wait_until(done.is_set, desc="sleep verb completed")
    finally:
        _stop([a, b, worker], threads)


def test_hb_only_adoption_is_busy_until_main_socket_speaks(mem_store_url):
    """A worker adopted from a liveness-only heartbeat (controller restarted
    while the worker is deep in handle_work) must not be dispatchable: the
    ROUTER may only hold a route for the '.hb' identity, and dispatching
    would EHOSTUNREACH -> remove -> re-adopt in a loop that burns the
    shard's retry budget.  The first main-socket WRM clears the flag."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import WorkerRegisterMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        wrm = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
                "liveness_only": True,
            }
        )
        controller.handle_worker(b"w1.hb", wrm)
        info = controller.worker_map["w1"]
        assert info["busy"] is True and info.get("hb_only")
        assert "s.bcolzs" in controller.files_map
        # not dispatchable while hb_only
        assert controller.find_free_worker(filename="s.bcolzs") is None

        # main-socket WRM proves the route: busy resets, flag clears
        full = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
            }
        )
        controller.handle_worker(b"w1", full)
        info = controller.worker_map["w1"]
        assert info["busy"] is False and not info.get("hb_only")
        assert controller.find_free_worker(filename="s.bcolzs") == "w1"
    finally:
        controller.socket.close()


def test_unroutable_dispatch_does_not_charge_retry_budget(mem_store_url):
    """An EHOSTUNREACH send (missing ROUTER route) requeues the shard WITHOUT
    incrementing _retries: routing facts are not evidence against the shard,
    and charging them aborts the query after MAX_DISPATCH_RETRIES re-adopts."""
    from bqueryd_tpu.controller import MAX_DISPATCH_RETRIES, ControllerNode
    from bqueryd_tpu.messages import CalcMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent",
    )
    try:
        msg = CalcMessage(
            {
                "payload": "groupby",
                "token": "t1",
                "parent_token": "p1",
                "filename": "s.bcolzs",
                "_retries": MAX_DISPATCH_RETRIES,  # budget already exhausted
            }
        )
        # no such route on the ROUTER -> ZMQError (ROUTER_MANDATORY) path
        controller._send_to_worker("no-such-worker", msg)
        queue = controller.worker_out_messages.get(None, [])
        assert [m.get("token") for m in queue] == ["t1"], (
            "shard must be requeued, not aborted"
        )
        assert queue[0].get("_retries") == MAX_DISPATCH_RETRIES
    finally:
        controller.socket.close()


def test_hb_only_adoption_expires_after_hard_timeout(mem_store_url):
    """A worker whose main loop is permanently wedged but whose heartbeat
    thread stays alive must not block its shards forever: the adoption
    expires after dispatch_hard_timeout and the worker is reclaimed, letting
    queries fail fast instead of hanging to the client timeout."""
    import time as time_mod

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.messages import WorkerRegisterMessage

    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir="/nonexistent", dispatch_hard_timeout=0.2,
        dispatch_timeout=0.1,
    )
    try:
        wrm = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
                "liveness_only": True,
            }
        )
        controller.handle_worker(b"w1.hb", wrm)
        assert "w1" in controller.worker_map
        time_mod.sleep(0.25)
        # heartbeats keep arriving (last_seen fresh) but main loop is silent
        controller.handle_worker(b"w1.hb", wrm.copy())
        controller.free_dead_workers()
        assert "w1" not in controller.worker_map
        assert "s.bcolzs" not in controller.files_map
        # the still-ticking heartbeat thread must NOT re-adopt it (quarantine)
        controller.handle_worker(b"w1.hb", wrm.copy())
        assert "w1" not in controller.worker_map
        # ...until the main socket proves the loop recovered
        full = WorkerRegisterMessage(
            {
                "worker_id": "w1",
                "workertype": "calc",
                "data_files": ["s.bcolzs"],
            }
        )
        controller.handle_worker(b"w1", full)
        assert "w1" in controller.worker_map
        controller.handle_worker(b"w1.hb", wrm.copy())  # liveness works again
        assert controller.worker_map["w1"]["last_seen"]
    finally:
        controller.socket.close()


def test_stop_is_a_shutdown_request_and_deregisters(
    tmp_path, mem_store_url, monkeypatch
):
    """Calling stop() from OUTSIDE the node loop (tests, embedders,
    signal handlers) must end the loop promptly and deregister the
    controller from the coordination store — previously the loop kept
    polling the closed socket forever and external teardown hung on
    thread joins."""
    import logging
    import threading
    import time

    import bqueryd_tpu
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.coordination import coordination_store
    from bqueryd_tpu.worker import WorkerNode

    monkeypatch.setenv("BQUERYD_TPU_WARMUP", "0")
    url = mem_store_url
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    worker = WorkerNode(
        coordination_url=url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    threads = [
        threading.Thread(target=n.go, daemon=True)
        for n in (controller, worker)
    ]
    for t in threads:
        t.start()
    store = coordination_store(url)
    wait_until(
        lambda: store.smembers(bqueryd_tpu.REDIS_SET_KEY),
        desc="controller registration",
    )
    # stop() before go() starts is a different race; wait the loops in
    wait_until(
        lambda: controller.running and worker.running, desc="loops running"
    )

    t0 = time.time()
    worker.stop()
    controller.stop()
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads), "node loops did not exit"
    assert time.time() - t0 < 5, "external stop() took too long"
    assert store.smembers(bqueryd_tpu.REDIS_SET_KEY) == set()


def test_groupby_through_either_controller(tmp_path, mem_store_url, monkeypatch):
    """A worker registers with every controller in the store; the same
    query asked through EACH controller must produce the same
    pandas-checked answer (the reference's operational model: clients
    may point at any controller, reference bqueryd/rpc.py:62-78)."""
    import logging
    import threading

    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    monkeypatch.setenv("BQUERYD_TPU_WARMUP", "0")
    rng = np.random.default_rng(21)
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 6, 4_000).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, 4_000).astype(np.int64),
        }
    )
    ctable.fromdataframe(df, str(tmp_path / "s0.bcolzs"))
    expected = df.groupby("g")["v"].sum()

    controllers = [
        ControllerNode(
            coordination_url=mem_store_url,
            loglevel=logging.WARNING,
            runfile_dir=str(tmp_path),
            heartbeat_interval=0.1,
        )
        for _ in range(2)
    ]
    worker = WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.1,
        poll_timeout=0.05,
    )
    nodes = controllers + [worker]
    threads = [
        threading.Thread(target=n.go, daemon=True) for n in nodes
    ]
    for t in threads:
        t.start()
    try:
        for c in controllers:
            wait_until(
                lambda c=c: "s0.bcolzs" in c.files_map,
                desc=f"shard registered at {c.address}",
            )
        results = []
        for c in controllers:
            rpc = RPC(
                address=c.address,
                coordination_url=mem_store_url,
                loglevel=logging.WARNING,
                timeout=30,
            )
            got = rpc.groupby(
                ["s0.bcolzs"], ["g"], [["v", "sum", "s"]], []
            )
            got = got.sort_values("g").reset_index(drop=True)
            assert got["g"].tolist() == expected.index.tolist()
            assert got["s"].tolist() == expected.tolist()
            results.append(got)
        pd.testing.assert_frame_equal(results[0], results[1])
    finally:
        for n in nodes:
            n.stop()
        for t in threads:
            t.join(timeout=5)


def test_concurrent_clients_survive_worker_churn(tmp_path, mem_store_url):
    """N concurrent clients with mixed shard affinities keep getting exact
    answers while workers are hard-killed and replaced mid-stream — the
    redesign's dispatch tracking (tracked inflight + bounded retries +
    cull/requeue) under real concurrency, which the reference (retry TODO at
    reference bqueryd/controller.py:265) never attempted.

    Asserts: no lost replies (every call returns), bit-exact sums on every
    reply (any retry that re-merged, double-dispatched, or mixed stale
    partials into a result would corrupt them), bounded retries (every
    requeue stays under MAX_DISPATCH_RETRIES, none poisoned), churn really
    overlapped the query stream, and no leaked inflight entries once the
    stream drains."""
    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import MAX_DISPATCH_RETRIES, ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(42)
    n_shards, rows = 6, 400
    frames = {}
    for i in range(n_shards):
        df = pd.DataFrame(
            {
                "g": rng.integers(0, 5, rows).astype(np.int64),
                "v": rng.integers(-(2**40), 2**40, rows).astype(np.int64),
            }
        )
        frames[f"churn_{i}.bcolzs"] = df
        ctable.fromdataframe(df, str(tmp_path / f"churn_{i}.bcolzs"))

    # mixed affinities: each client sticks to its own file subset
    subsets = [
        [f"churn_{i}.bcolzs" for i in idx]
        for idx in ([0, 1], [2, 3], [4, 5], [0, 2, 4], [1, 3, 5],
                    list(range(n_shards)))
    ]
    expected = {
        tuple(sub): pd.concat([frames[f] for f in sub])
        .groupby("g")["v"].sum().to_dict()
        for sub in map(tuple, subsets)
    }

    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.05,
        dead_worker_timeout=1.0,
        dispatch_timeout=1.5,
    )
    requeues = []
    real_requeue = controller._requeue

    def counting_requeue(entry, charge_retry=True):
        requeues.append(entry.get("retries", 0))
        return real_requeue(entry, charge_retry=charge_retry)

    controller._requeue = counting_requeue

    def spawn_worker():
        return WorkerNode(
            coordination_url=mem_store_url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )

    workers = [spawn_worker() for _ in range(3)]
    threads = _start(controller, *workers)
    all_nodes = [controller] + list(workers)
    try:
        wait_until(
            lambda: len(controller.files_map.get("churn_0.bcolzs", ())) >= 1
            and len(controller.worker_map) >= 3,
            desc="initial registration",
        )

        stop_churn = threading.Event()
        errors = []
        results = []  # (subset, got_dict) — appended under a lock
        res_lock = threading.Lock()

        def client(sub, n_queries=4):
            try:
                rpc = RPC(
                    coordination_url=mem_store_url,
                    timeout=60,
                    loglevel=logging.WARNING,
                    retries=3,
                )
                for _ in range(n_queries):
                    df = rpc.groupby(
                        list(sub), ["g"], [["v", "sum", "s"]], []
                    )
                    got = dict(zip(df["g"].tolist(), df["s"].tolist()))
                    with res_lock:
                        results.append((tuple(sub), got))
            except Exception as exc:  # lost reply shows up here
                errors.append((sub, repr(exc)))

        kills_mid_stream = []

        def churn():
            """Hard-kill a worker mid-stream, start a replacement, twice."""
            try:
                for round_i in range(2):
                    if stop_churn.wait(0.6):
                        return
                    victim = workers[round_i]
                    # silent death: no goodbye StopMessage, no replies —
                    # but the loop thread still runs its own socket
                    # teardown on exit (stop() itself must stay intact)
                    victim.send = lambda *a, **k: None
                    victim._hb_stop.set()
                    victim.running = False
                    kills_mid_stream.append(
                        any(t.is_alive() for t in clients)
                    )
                    replacement = spawn_worker()
                    workers.append(replacement)
                    all_nodes.append(replacement)
                    threads.extend(_start(replacement))
            except Exception as exc:
                errors.append(("churn", repr(exc)))

        clients = [
            threading.Thread(target=client, args=(sub,), daemon=True)
            for sub in subsets
        ]
        churner = threading.Thread(target=churn, daemon=True)
        for t in clients:
            t.start()
        churner.start()
        for t in clients:
            t.join(timeout=120)
            assert not t.is_alive(), "client wedged: lost reply"
        stop_churn.set()
        churner.join(timeout=10)

        assert not errors, f"client/churn failures: {errors}"
        # the scenario must actually have happened: both kills landed while
        # clients were still querying (else this test silently stops
        # covering churn — tune the client/churn pacing if this fires)
        assert kills_mid_stream == [True, True], kills_mid_stream
        assert len(results) == len(subsets) * 4, "lost replies"
        for sub, got in results:
            assert got == expected[sub], f"wrong/duplicated sums for {sub}"
        # bounded retries: every requeue stayed under budget (none poisoned)
        assert all(r < MAX_DISPATCH_RETRIES for r in requeues), requeues
        # generous bound: kills can requeue at most the shards each victim
        # held inflight, twice, plus timeout-driven strays
        assert len(requeues) <= 4 * n_shards, requeues
        wait_until(
            lambda: not controller.inflight, desc="inflight drained"
        )
    finally:
        _stop(all_nodes, threads)
