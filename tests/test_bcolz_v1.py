"""Legacy bcolz v1 ingest: Blosc chunk decoding + carray/ctable readers +
the ``bqueryd-tpu import`` conversion path.

The fixture writer below emits the REAL bcolz v1 on-disk layout (carray dirs
with meta/sizes + meta/storage JSON and Blosc v1 ``.blp`` chunks — the format
served by the reference at reference bqueryd/worker.py:291).  Chunk payloads
are produced three ways so the decoder is exercised on every container
variant: memcpyed chunks, shuffled+split blosclz chunks, and unsplit chunks.
Decoder correctness against the PUBLIC format (not just round-trip through
our own compressor) is pinned by the hand-crafted byte-stream vectors in
TestBloscLZVectors.
"""

import json
import os
import struct

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.storage import bcolz_v1
from bqueryd_tpu.storage import native


# ---------------------------------------------------------------------------
# minimal blosclz COMPRESSOR (literal runs + RLE matches) for fixtures
# ---------------------------------------------------------------------------

def blosclz_compress_simple(data):
    """Valid blosclz stream built from literal runs and distance-1 RLE
    matches — enough to exercise the decoder's literal, match and RLE paths
    on real fixture data (a full match-searching compressor is not needed
    for ingest, only decode)."""
    out = bytearray()
    n = len(data)
    i = 0

    def emit_literals(chunk):
        for s in range(0, len(chunk), 32):
            piece = chunk[s:s + 32]
            out.append(len(piece) - 1)
            out.extend(piece)

    lit_start = 0
    while i < n:
        # find an RLE run of >= 4 identical bytes (first byte stays literal)
        run = 1
        while i + run < n and data[i + run] == data[i] and run < 3 + 6 + 255 * 3:
            run += 1
        if run >= 4 and i >= lit_start:
            emit_literals(data[lit_start:i + 1])  # include the seed byte
            copy_len = run - 1  # bytes reproduced by the match
            len_field = copy_len - 3
            if len_field < 6:
                out.append(((len_field + 1) << 5) | 0)
                out.append(0)
            else:
                out.append((7 << 5) | 0)
                rest = len_field - 6
                while rest >= 255:
                    out.append(255)
                    rest -= 255
                out.append(rest)
                out.append(0)
            i += run
            lit_start = i
        else:
            i += 1
    emit_literals(data[lit_start:n])
    return bytes(out)


# ---------------------------------------------------------------------------
# Blosc v1 chunk builder (fixture side)
# ---------------------------------------------------------------------------

def _shuffle(data, typesize):
    arr = np.frombuffer(data, dtype=np.uint8)
    nelems = len(data) // typesize
    head = arr[: nelems * typesize].reshape(nelems, typesize).T.reshape(-1)
    return head.tobytes() + bytes(arr[nelems * typesize:])


def _trans_bit_8x8(x):
    """Hacker's Delight transpose8 — the TRANS_BIT_8X8 macro of the
    bitshuffle library (public algorithm; this port exists so the fixture
    encoder is INDEPENDENT of the numpy production code it validates)."""
    m = 0xFFFFFFFFFFFFFFFF
    t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AA
    x = x ^ t ^ ((t << 7) & m)
    t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCC
    x = x ^ t ^ ((t << 14) & m)
    t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0
    x = x ^ t ^ ((t << 28) & m)
    return x & m


def scalar_bitshuffle_reference(data, typesize):
    """Direct port of the bitshuffle library's scalar pipeline
    (``bshuf_trans_byte_elem`` -> ``bshuf_trans_bit_byte`` ->
    ``bshuf_trans_bitrow_eight``) wrapped with c-blosc shuffle.c's
    ``bitshuffle()`` truncation rule: elements truncated to a multiple of
    8, trailing bytes copied through."""
    nelems = (len(data) // typesize) & ~7
    cut = nelems * typesize
    if nelems == 0:
        return bytes(data)
    src = np.frombuffer(data[:cut], np.uint8)
    # stage 1: transpose bytes within elements
    s1 = src.reshape(nelems, typesize).T.copy().reshape(-1)
    # stage 2: transpose bits within bytes (8 bytes -> 8 bit-planes)
    nbyte = cut
    nbr = nbyte // 8
    s2 = np.zeros(nbyte, np.uint8)
    for ii in range(0, nbyte, 8):
        x = _trans_bit_8x8(int.from_bytes(s1[ii:ii + 8].tobytes(), "little"))
        for kk in range(8):
            s2[kk * nbr + ii // 8] = (x >> (8 * kk)) & 0xFF
    # stage 3: regroup bit-rows per byte-of-element
    row = nelems // 8
    s3 = np.zeros(nbyte, np.uint8)
    for jj in range(typesize):
        for kk in range(8):
            dst_off = (jj * 8 + kk) * row
            src_off = (kk * typesize + jj) * row
            s3[dst_off:dst_off + row] = s2[src_off:src_off + row]
    return s3.tobytes() + data[cut:]


def build_blosc_chunk(data, typesize, mode="blosclz", blocksize=None,
                      bitshuffle=False):
    """One Blosc v1 chunk: 16-byte header + bstarts + split streams."""
    nbytes = len(data)
    if mode == "memcpy":
        header = struct.pack(
            "<BBBBiii", 2, 1, 0x2, typesize, nbytes, nbytes, 16 + nbytes
        )
        return header + data
    blocksize = blocksize or max(typesize, min(nbytes, 4096))
    if blocksize % typesize:
        blocksize += typesize - blocksize % typesize
    if bitshuffle:
        flags = 0x4  # bit-shuffle (applies at any typesize)
    else:
        flags = 0x1 if typesize > 1 else 0  # byte-shuffle
    nblocks = -(-nbytes // blocksize)
    streams = []
    for b in range(nblocks):
        raw = data[b * blocksize:(b + 1) * blocksize]
        leftover = len(raw) != blocksize
        if bitshuffle:
            raw = scalar_bitshuffle_reference(raw, typesize)
        elif typesize > 1:
            raw = _shuffle(raw, typesize)
        splittable = (
            not leftover
            and 1 < typesize <= 16
            and len(raw) % typesize == 0
            and len(raw) // typesize >= 128
        )
        nsplits = typesize if splittable else 1
        neblock = len(raw) // nsplits
        parts = bytearray()
        for s in range(nsplits):
            piece = raw[s * neblock:(s + 1) * neblock]
            comp = blosclz_compress_simple(piece)
            if len(comp) < neblock:
                parts += struct.pack("<i", len(comp)) + comp
            else:
                parts += struct.pack("<i", neblock) + piece  # stored raw
        streams.append(bytes(parts))
    bstarts = []
    pos = 16 + 4 * nblocks
    for s in streams:
        bstarts.append(pos)
        pos += len(s)
    body = b"".join(streams)
    cbytes = 16 + 4 * nblocks + len(body)
    header = struct.pack(
        "<BBBBiii", 2, 1, flags, typesize, nbytes, blocksize, cbytes
    )
    return header + b"".join(struct.pack("<i", b) for b in bstarts) + body


# ---------------------------------------------------------------------------
# bcolz v1 directory fixture writer
# ---------------------------------------------------------------------------

def write_bcolz_v1_carray(rootdir, values, chunklen=1000, mode="blosclz",
                          raw_leftover=False, bitshuffle=False):
    values = np.ascontiguousarray(values)
    os.makedirs(os.path.join(rootdir, "meta"))
    os.makedirs(os.path.join(rootdir, "data"))
    typesize = values.dtype.itemsize
    with open(os.path.join(rootdir, "meta", "sizes"), "w") as f:
        json.dump(
            {"shape": [len(values)], "nbytes": values.nbytes, "cbytes": -1}, f
        )
    with open(os.path.join(rootdir, "meta", "storage"), "w") as f:
        json.dump(
            {
                "dtype": str(values.dtype.str),
                "cparams": {
                    "clevel": 5,
                    # bcolz constants: 1 = SHUFFLE, 2 = BITSHUFFLE
                    "shuffle": 2 if bitshuffle else 1,
                    "cname": "blosclz",
                },
                "chunklen": chunklen,
                "dflt": 0,
                "expectedlen": len(values),
            },
            f,
        )
    nfull = len(values) // chunklen
    for i in range(nfull):
        chunk = values[i * chunklen:(i + 1) * chunklen].tobytes()
        with open(os.path.join(rootdir, "data", f"__{i}.blp"), "wb") as f:
            f.write(
                build_blosc_chunk(
                    chunk, typesize, mode=mode, bitshuffle=bitshuffle
                )
            )
    left = values[nfull * chunklen:]
    if len(left):
        path = os.path.join(rootdir, "data", "__leftover.blp")
        with open(path, "wb") as f:
            if raw_leftover:
                f.write(left.tobytes())
            else:
                f.write(
                    build_blosc_chunk(
                        left.tobytes(), typesize, mode=mode,
                        bitshuffle=bitshuffle,
                    )
                )


def write_bcolz_v1_ctable(rootdir, frame, chunklen=1000, mode="blosclz"):
    os.makedirs(rootdir)
    with open(os.path.join(rootdir, "__attrs__"), "w") as f:
        json.dump({"origin": "fixture"}, f)
    with open(os.path.join(rootdir, "__cols__"), "w") as f:
        json.dump({"names": list(frame.keys())}, f)
    for name, values in frame.items():
        write_bcolz_v1_carray(
            os.path.join(rootdir, name), values, chunklen=chunklen, mode=mode
        )


# ---------------------------------------------------------------------------
# hand-crafted blosclz streams: pin the decoder to the public format
# ---------------------------------------------------------------------------

def _decoders():
    out = [("py", bcolz_v1._blosclz_decompress_py)]
    if native.blosc_available():
        def native_blosclz(src, usize):
            # route through a 1-block unsplit chunk so the native stream
            # decoder is reachable from public API: header, one bstart at
            # offset 20, then the int32-framed split stream
            header = struct.pack(
                "<BBBBiii", 2, 1, 0, 1, usize, usize, 16 + 4 + 4 + len(src)
            )
            chunk = (
                header
                + struct.pack("<i", 20)
                + struct.pack("<i", len(src))
                + bytes(src)
            )
            return native.blosc_decode(chunk, usize)
        out.append(("native", native_blosclz))
    return out


@pytest.mark.parametrize("name,decode", _decoders())
class TestBloscLZVectors:
    def test_literal_run(self, name, decode):
        stream = bytes([4]) + b"hello"
        assert decode(stream, 5) == b"hello"

    def test_rle_match(self, name, decode):
        # 'a' then a distance-1 match of 6 bytes: ctrl len field 3 -> 3+3=6
        stream = bytes([0]) + b"a" + bytes([(4 << 5) | 0, 0])
        assert decode(stream, 7) == b"aaaaaaa"

    def test_overlapping_match(self, name, decode):
        # "ab" then match dist 2 len 6 -> "abababab"
        stream = bytes([1]) + b"ab" + bytes([(4 << 5) | 0, 1])
        assert decode(stream, 8) == b"abababab"

    def test_extended_length(self, name, decode):
        # literal 'x' + RLE of 6+7+3 = 16 bytes: len field saturated (7),
        # extension byte 7
        stream = bytes([0]) + b"x" + bytes([(7 << 5) | 0, 7, 0])
        assert decode(stream, 17) == b"x" * 17

    def test_far_distance(self, name, decode):
        # 9000 distinct-ish literal bytes, then a far match (dist > 8191+255)
        body = bytes(range(256)) * 36  # 9216 bytes
        body = body[:9000]
        stream = bytearray()
        for s in range(0, 9000, 32):
            piece = body[s:s + 32]
            stream.append(len(piece) - 1)
            stream += piece
        dist = 8500
        extra = dist - bcolz_v1._MAX_DISTANCE - 1  # = 308
        # copy length = ((ctrl>>5) - 1) + 3 = 5 for a length field of 3
        stream += bytes([(3 << 5) | 31, 255, extra >> 8, extra & 0xFF])
        expect = body + body[9000 - dist:9000 - dist + 5]
        assert decode(bytes(stream), 9005) == bytes(expect)


def test_python_and_native_chunk_decoders_agree():
    rng = np.random.default_rng(7)
    values = rng.integers(0, 50, 4096).astype(np.int64)
    chunk = build_blosc_chunk(values.tobytes(), 8)
    got_py = bcolz_v1._blosc_decode_chunk_py(chunk)
    assert got_py == values.tobytes()
    if native.blosc_available():
        nbytes, typesize, flags = native.blosc_info(chunk)
        assert (nbytes, typesize) == (values.nbytes, 8)
        assert native.blosc_decode(chunk, nbytes) == values.tobytes()


def test_bitshuffle_codec_matches_scalar_reference():
    """The production numpy bit-(un)shuffle must match the independent
    direct port of the bitshuffle library's scalar pipeline for every
    typesize class, including the non-multiple-of-8-elements tail that
    c-blosc copies through unshuffled."""
    from bqueryd_tpu.storage.codec import _bitshuffle, _bitunshuffle

    rng = np.random.default_rng(11)
    for typesize in (1, 2, 3, 4, 8, 16):
        for nelems in (8, 64, 133):  # 133: 5-element unshuffled tail
            data = rng.integers(
                0, 256, nelems * typesize, dtype=np.uint8
            ).tobytes() + b"\x7f" * (typesize // 2)  # ragged byte tail
            ref = scalar_bitshuffle_reference(data, typesize)
            assert _bitshuffle(data, typesize) == ref, (
                f"forward layout diverges at typesize={typesize}"
            )
            assert _bitunshuffle(ref, typesize) == data, (
                f"inverse does not recover at typesize={typesize}"
            )


def test_bitshuffled_chunk_decoders_agree():
    """A bit-shuffled chunk (flag 0x4) decodes identically through the
    Python and native paths — including the split-stream framing, which
    c-blosc applies independently of the shuffle filter."""
    rng = np.random.default_rng(13)
    for typesize, values in (
        (8, rng.integers(0, 50, 4096).astype(np.int64)),
        (1, (rng.random(4096) < 0.2)),  # bools: bitshuffle's home turf
        (4, rng.normal(size=2048).astype(np.float32)),
    ):
        chunk = build_blosc_chunk(
            values.tobytes(), typesize, bitshuffle=True
        )
        assert bcolz_v1._blosc_decode_chunk_py(chunk) == values.tobytes()
        if native.blosc_available():
            nbytes, _ts, flags = native.blosc_info(chunk)
            assert flags & 0x4
            assert native.blosc_decode(chunk, nbytes) == values.tobytes()


def test_read_carray_bitshuffle_roundtrip(tmp_path):
    """A bcolz v1 carray written with shuffle=bcolz.BITSHUFFLE reads back
    exactly, leftover chunk (non-multiple-of-8 elements) included."""
    rng = np.random.default_rng(17)
    values = rng.integers(-(2**30), 2**30, 2513).astype(np.int64)
    write_bcolz_v1_carray(
        str(tmp_path / "c"), values, chunklen=1000, bitshuffle=True
    )
    got = bcolz_v1.read_carray(str(tmp_path / "c"))
    np.testing.assert_array_equal(got, values)


def test_memcpyed_chunk():
    data = os.urandom(512)
    chunk = build_blosc_chunk(data, 8, mode="memcpy")
    assert bcolz_v1.decode_chunk(chunk) == data


def test_unsplit_typesize_above_16():
    # |S24 strings: typesize 24 > MAX_SPLITS -> single split stream
    values = np.array(
        [f"name-{i % 9:019d}".encode() for i in range(600)], dtype="|S24"
    )
    chunk = build_blosc_chunk(values.tobytes(), 24)
    assert bcolz_v1.decode_chunk(chunk) == values.tobytes()


# ---------------------------------------------------------------------------
# carray / ctable readers + import
# ---------------------------------------------------------------------------

def test_read_carray_roundtrip(tmp_path):
    rng = np.random.default_rng(3)
    values = rng.integers(-(2**40), 2**40, 2500).astype(np.int64)
    write_bcolz_v1_carray(str(tmp_path / "c"), values, chunklen=1000)
    got = bcolz_v1.read_carray(str(tmp_path / "c"))
    np.testing.assert_array_equal(got, values)


def test_read_carray_raw_leftover(tmp_path):
    values = np.arange(1234, dtype=np.int32)
    write_bcolz_v1_carray(
        str(tmp_path / "c"), values, chunklen=1000, raw_leftover=True
    )
    got = bcolz_v1.read_carray(str(tmp_path / "c"))
    np.testing.assert_array_equal(got, values)


def _taxi_frame(n=3210):
    rng = np.random.default_rng(11)
    return {
        "passenger_count": rng.integers(1, 9, n).astype(np.int64),
        "fare_cents": rng.integers(250, 20000, n).astype(np.int64),
        "trip_distance": (rng.random(n) * 30).astype(np.float64),
        "vendor": np.array(
            [("CMT", "VTS", "DDS")[i % 3] for i in range(n)], dtype="|S3"
        ),
    }


def test_read_ctable_matches_pandas(tmp_path):
    frame = _taxi_frame()
    src = str(tmp_path / "legacy.bcolz")
    write_bcolz_v1_ctable(src, frame)
    columns, attrs = bcolz_v1.read_ctable(src)
    assert list(columns) == list(frame)  # __cols__ order preserved
    assert attrs == {"origin": "fixture"}
    for name in frame:
        np.testing.assert_array_equal(columns[name], frame[name])


def test_import_ctable_end_to_end(tmp_path):
    """The VERDICT's done-bar: convert a legacy rootdir and assert
    logical-value equality against a pandas load of the source data,
    through the converted table's own query surface."""
    from bqueryd_tpu.storage.ctable import ctable

    frame = _taxi_frame()
    src = str(tmp_path / "legacy.bcolz")
    dst = str(tmp_path / "converted.bcolz")
    write_bcolz_v1_ctable(src, frame)

    rows = bcolz_v1.import_ctable(src, dst)
    assert rows == len(frame["fare_cents"])

    t = ctable(dst)
    source_df = pd.DataFrame(
        {
            k: (np.char.decode(v, "utf-8") if v.dtype.kind == "S" else v)
            for k, v in frame.items()
        }
    )
    for name in frame:
        np.testing.assert_array_equal(
            np.asarray(t.column(name)), source_df[name].to_numpy()
        )
    # converted data answers queries bit-exactly vs pandas
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
    from bqueryd_tpu.parallel import hostmerge

    q = GroupByQuery(
        ["passenger_count"], [["fare_cents", "sum", "s"]], [], aggregate=True
    )
    payload = QueryEngine().execute_local(t, q)
    df = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    ).sort_values("passenger_count")
    expect = (
        source_df.groupby("passenger_count")["fare_cents"].sum().sort_index()
    )
    np.testing.assert_array_equal(
        df["s"].to_numpy(), expect.to_numpy()
    )
    assert t.attrs.get("bcolz_v1_attrs") == {"origin": "fixture"}


def test_cli_import(tmp_path):
    from bqueryd_tpu.node import main

    frame = {"a": np.arange(50, dtype=np.int64)}
    src = str(tmp_path / "legacy.bcolz")
    dst = str(tmp_path / "out.bcolz")
    write_bcolz_v1_ctable(src, frame, chunklen=16)
    assert main(["import", src, dst]) == 0
    from bqueryd_tpu.storage.ctable import ctable

    np.testing.assert_array_equal(
        np.asarray(ctable(dst).column("a")), frame["a"]
    )


def test_read_carray_datetime_and_float(tmp_path):
    """datetime64[ns] and float32 columns round-trip through the Blosc
    decode (dtype strings as bcolz stores them, e.g. '<M8[ns]')."""
    stamps = np.array(
        ["2016-01-01T00:00:00", "2016-01-02T12:34:56"] * 700,
        dtype="datetime64[ns]",
    )
    write_bcolz_v1_carray(str(tmp_path / "dt"), stamps.view(np.int64))
    # dtype metadata says datetime: rewrite storage meta accordingly
    storage = json.load(open(tmp_path / "dt" / "meta" / "storage"))
    storage["dtype"] = "<M8[ns]"
    json.dump(storage, open(tmp_path / "dt" / "meta" / "storage", "w"))
    got = bcolz_v1.read_carray(str(tmp_path / "dt"))
    assert got.dtype == np.dtype("<M8[ns]")
    np.testing.assert_array_equal(got, stamps)

    floats = (np.random.default_rng(2).random(1500) * 7).astype(np.float32)
    write_bcolz_v1_carray(str(tmp_path / "f"), floats, chunklen=512)
    np.testing.assert_array_equal(
        bcolz_v1.read_carray(str(tmp_path / "f")), floats
    )


@pytest.mark.parametrize("decoder", ["py", "native"])
def test_chunk_decoders_survive_corrupt_input(decoder):
    """The decoders face untrusted legacy bytes: random garbage and
    bit-flipped valid chunks must fail cleanly (ValueError / 0-return),
    never crash or return oversized output (seeded, bounded)."""
    if decoder == "native" and not native.blosc_available():
        pytest.skip("native lib without blosc symbols")
    rng = np.random.default_rng(99)
    values = rng.integers(0, 1000, 2048).astype(np.int64)
    valid = build_blosc_chunk(values.tobytes(), 8)

    def attempt(buf):
        if decoder == "py":
            try:
                out = bcolz_v1._blosc_decode_chunk_py(buf)
            except bcolz_v1._DECODE_ERRORS:
                return None
            return out
        try:
            nbytes, _t, _f = native.blosc_info(bytes(buf))
        except ValueError:
            return None
        if not 0 <= nbytes <= (64 << 20):
            return None
        try:
            return native.blosc_decode(bytes(buf), nbytes)
        except ValueError:
            return None

    # pure garbage
    for n in (0, 1, 15, 16, 17, 64, 300):
        for _ in range(12):
            attempt(bytes(rng.integers(0, 256, n, dtype=np.uint8)))
    # bit-flipped valid chunks: either clean failure or SOME bytes back
    arr = np.frombuffer(valid, dtype=np.uint8).copy()
    for _ in range(150):
        mutated = arr.copy()
        for _ in range(int(rng.integers(1, 4))):
            mutated[rng.integers(0, len(mutated))] ^= 1 << int(
                rng.integers(0, 8)
            )
        attempt(mutated.tobytes())
    # truncations
    for cut in rng.integers(0, len(valid), 25):
        attempt(valid[: int(cut)])
