"""Multi-host execution path, simulated with two OS processes on CPU.

The mesh executor claims to scale to multi-host pods (``maybe_init_distributed``
+ the ``make_array_from_callback`` placement in ``executor._put``) the way the
reference scales by adding worker boxes (reference misc/supervisor.conf:19-20,
README.md:125).  Until a real pod exists, this is the executable evidence:
two ``jax.distributed``-joined CPU processes (4 virtual devices each → one
8-device global mesh) run the same groupby through MeshQueryExecutor and must
both produce the psum-merged global answer, bit-exact vs pandas.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

_WORKER_SCRIPT = r"""
import json, os, sys
proc_id, data_dir, out_path = int(sys.argv[1]), sys.argv[2], sys.argv[3]

from bqueryd_tpu import ops
assert ops.maybe_init_distributed() is True, "distributed init did not run"
import jax
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, jax.devices()

from bqueryd_tpu.models.query import GroupByQuery
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.parallel.executor import MeshQueryExecutor
from bqueryd_tpu.storage.ctable import ctable

names = sorted(n for n in os.listdir(data_dir) if n.endswith(".bcolzs"))
tables = [ctable(os.path.join(data_dir, n)) for n in names]
query = GroupByQuery(["g"], [["v", "sum", "s"]], [], aggregate=True)
executor = MeshQueryExecutor()
payload = executor.execute(tables, query)
df = hostmerge.payload_to_dataframe(hostmerge.merge_payloads([payload]))
df = df.sort_values("g").reset_index(drop=True)
with open(f"{out_path}.{proc_id}", "w") as f:
    json.dump({"g": df["g"].tolist(), "s": df["s"].tolist()}, f)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_psum_merge(tmp_path):
    # bounded by the communicate(timeout=240) below
    import jax

    if not hasattr(jax, "shard_map"):
        # pre-0.6 jax: XLA:CPU rejects cross-process computations outright
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"), so the two-process simulation cannot run — the
        # multi-host path is still exercised single-process by
        # test_mesh_executor on the 8-device virtual mesh
        pytest.skip("multiprocess CPU collectives unsupported on this jax")
    from bqueryd_tpu.storage.ctable import ctable

    rng = np.random.default_rng(9)
    frames = []
    for i in range(4):
        df = pd.DataFrame(
            {
                "g": rng.integers(0, 11, 5_000).astype(np.int64),
                "v": rng.integers(-(2**50), 2**50, 5_000).astype(np.int64),
            }
        )
        frames.append(df)
        ctable.fromdataframe(df, str(tmp_path / f"shard_{i}.bcolzs"))
    expect = (
        pd.concat(frames).groupby("g")["v"].sum().sort_index()
    )

    script = tmp_path / "worker.py"
    script.write_text(_WORKER_SCRIPT)
    out_path = str(tmp_path / "result.json")
    port = _free_port()
    import bqueryd_tpu

    pkg_root = os.path.dirname(os.path.dirname(bqueryd_tpu.__file__))
    env = dict(os.environ)
    env.update(
        {
            # the worker script lives in tmp_path, so the package root must
            # be importable explicitly — python puts the script's directory
            # on sys.path, not the parent's cwd
            "PYTHONPATH": os.pathsep.join(
                p for p in (pkg_root, env.get("PYTHONPATH")) if p
            ),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "BQUERYD_TPU_DIST_COORDINATOR": f"127.0.0.1:{port}",
            "BQUERYD_TPU_DIST_NPROCS": "2",
        }
    )
    procs = []
    for proc_id in (0, 1):
        penv = dict(env, BQUERYD_TPU_DIST_PROC_ID=str(proc_id))
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), str(proc_id), str(tmp_path),
                 out_path],
                env=penv,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
            )
        )
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out.decode(errors="replace"))
    finally:
        for p in procs:  # a hung barrier must not leak into later tests
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"worker process failed:\n{out}"

    for proc_id in (0, 1):
        with open(f"{out_path}.{proc_id}") as f:
            got = json.load(f)
        assert got["g"] == expect.index.tolist()
        assert got["s"] == expect.tolist()
