"""Wire narrowing, exact int64 limb sums, and executor cache behavior."""

import logging

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.models.query import GroupByQuery, freeze_value as _freeze
from bqueryd_tpu.parallel.executor import (
    MeshQueryExecutor,
    _codes_dtype,
    _where_signature,
    _wire_dtype,
    make_mesh,
)
from bqueryd_tpu.storage.ctable import ctable


@pytest.fixture
def shard_tables(tmp_path):
    rng = np.random.RandomState(9)
    frames, tables = [], []
    for i in range(3):
        df = pd.DataFrame(
            {
                "g": rng.randint(0, 6, 500).astype(np.int64),
                "v": rng.randint(-30000, 30000, 500).astype(np.int64),
                "big": rng.randint(-(2**62), 2**62, 500).astype(np.int64),
                "f": rng.random(500).astype(np.float32),
            }
        )
        root = str(tmp_path / f"s{i}.bcolzs")
        ctable.fromdataframe(df, root)
        frames.append(df)
        tables.append(ctable(root))
    return frames, tables


@pytest.mark.parametrize("path", ["mxu_matmul", "scatter"])
def test_int64_sum_bit_exact_full_range(path, monkeypatch):
    """Exact int64 sums across the full value range on BOTH kernel paths:
    the 8-bit-limb MXU matmul (default) and the 16-bit-limb blocked scatter
    (high-cardinality fallback, forced via BQUERYD_TPU_MATMUL_GROUPS=0)."""
    import jax

    from bqueryd_tpu import ops

    if path == "scatter":
        monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", "0")
    rng = np.random.RandomState(0)
    for dtype in (np.int8, np.int16, np.int32, np.int64):
        info = np.iinfo(dtype)
        vals = rng.randint(
            info.min, info.max, 5000, dtype=np.int64
        ).astype(dtype)
        codes = rng.randint(0, 7, 5000).astype(np.int32)
        out = jax.device_get(
            ops.partial_tables(codes, (vals,), ("sum",), 7)
        )["aggs"][0]["sum"]
        expect = np.zeros(7, dtype=np.int64)
        np.add.at(expect, codes, vals.astype(np.int64))
        np.testing.assert_array_equal(np.asarray(out), expect)


@pytest.mark.parametrize("op", ["sum", "mean", "count", "count_na", "min", "max"])
def test_mm_and_scatter_paths_agree(op, monkeypatch):
    """The MXU and scatter kernels must be interchangeable: identical results
    for every mergeable op, with nulls, masks and negative (dropped) codes."""
    import jax

    from bqueryd_tpu import ops

    rng = np.random.RandomState(5)
    n, g = 20_000, 23
    codes = rng.randint(-1, g, n).astype(np.int32)
    mask = rng.random(n) < 0.8
    vals = (rng.random(n) * 1000 - 500).astype(np.float32)
    vals[rng.random(n) < 0.05] = np.nan

    def run():
        return jax.device_get(
            ops.partial_tables(codes, (vals,), (op,), g, mask=mask)
        )

    mm = run()
    monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", "0")
    scatter = run()
    np.testing.assert_array_equal(mm["rows"], scatter["rows"])
    # float32 sums cancel heavily here (values in ±500, group sums ~1e2), so
    # compare with an absolute floor scaled to the summed magnitude instead of
    # pure rtol: both kernels carry ~1e-7 relative accumulation noise.
    atol = 1e-6 * float(np.nansum(np.abs(vals)))
    for key in scatter["aggs"][0]:
        np.testing.assert_allclose(
            mm["aggs"][0][key], scatter["aggs"][0][key], rtol=1e-4, atol=atol,
            err_msg=f"op={op} partial={key}",
        )


@pytest.mark.parametrize("op,g", [
    ("sum", 150),        # small-G: 2048 tile
    ("sum", 1300),       # pads to 1408 lanes: non-pow2 G, 1024 tile, two
                         # blocks — the shapes that once truncated the block
                         # loop when tiles weren't forced to divide BLOCK_K
    ("mean", 150),
    ("count_na", 150),
])
def test_pallas_kernel_matches_xla_path(op, g, monkeypatch):
    """BQUERYD_TPU_PALLAS=1 routes the one-hot contraction through the Pallas
    kernel (interpreted off-TPU); results must be bit-identical to the XLA
    path, which shares the limb plan and differs only in who forms the
    one-hot.  The flag is a static jit arg read per call in the un-jitted
    dispatcher, so the two runs trace distinct executables."""
    import jax

    from bqueryd_tpu import ops
    from bqueryd_tpu.ops import pallas_groupby

    if g == 1300:  # regression guard: this landing must use a dividing tile
        assert pallas_groupby.BLOCK_K % pallas_groupby._tile_k(1408) == 0

    rng = np.random.RandomState(9)
    n = 40_000  # pads to two 32768 blocks
    codes = rng.randint(-1, g, n).astype(np.int32)
    mask = rng.random(n) < 0.9
    ivals = rng.randint(-(2**40), 2**40, n).astype(np.int64)
    fvals = (rng.random(n) * 100).astype(np.float32)
    fvals[rng.random(n) < 0.03] = np.nan
    vals = fvals if op == "count_na" else ivals

    def run():
        return jax.device_get(
            ops.partial_tables(codes, (vals,), (op,), g, mask=mask)
        )

    monkeypatch.delenv("BQUERYD_TPU_PALLAS", raising=False)
    xla = run()
    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    pallas = run()
    np.testing.assert_array_equal(xla["rows"], pallas["rows"])
    for key in xla["aggs"][0]:
        np.testing.assert_array_equal(
            xla["aggs"][0][key], pallas["aggs"][0][key],
            err_msg=f"op={op} partial={key}",
        )


def test_pallas_high_cardinality_tile_shrinks(monkeypatch):
    """Above 8192 groups the one-hot tile must shrink to _MIN_TILE instead of
    overflowing the VMEM budget (the round-3 hole: _tile_k bottomed at 256,
    so raising BQUERYD_TPU_MATMUL_GROUPS past ~8k overflowed ~4 MB)."""
    import jax

    from bqueryd_tpu import ops
    from bqueryd_tpu.ops import pallas_groupby as pg

    g = 12_289  # > the old 8k ceiling, <= pallas_groups_limit()
    assert g <= pg.pallas_groups_limit()
    tile = pg._tile_k(g)
    assert tile == pg._MIN_TILE
    assert tile * g <= pg._ONEHOT_BUDGET
    assert pg.BLOCK_K % tile == 0

    monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", "16384")
    rng = np.random.RandomState(3)
    n = pg.BLOCK_K  # one grid block keeps interpret mode fast
    codes = rng.randint(-1, g, n).astype(np.int32)
    vals = rng.randint(-(2**40), 2**40, n).astype(np.int64)

    def run():
        return jax.device_get(ops.partial_tables(codes, (vals,), ("sum",), g))

    monkeypatch.delenv("BQUERYD_TPU_PALLAS", raising=False)
    xla = run()
    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    pallas = run()
    np.testing.assert_array_equal(xla["rows"], pallas["rows"])
    np.testing.assert_array_equal(
        xla["aggs"][0]["sum"], pallas["aggs"][0]["sum"]
    )


def test_pallas_route_capped_at_groups_limit(monkeypatch):
    """Past pallas_groups_limit() the dispatcher must keep the XLA dot even
    with BQUERYD_TPU_PALLAS=1 (no VMEM-overflowing kernel launch)."""
    from bqueryd_tpu.ops import groupby as gbm
    from bqueryd_tpu.ops import pallas_groupby as pg

    g = pg.pallas_groups_limit() + 1
    seen = {}
    real = gbm._partial_tables_mm

    def spy(codes, measures, ops_, n_groups, mask=None, use_pallas=False,
            **kw):
        seen["use_pallas"] = use_pallas
        return real(codes, measures, ops_, n_groups, mask,
                    use_pallas=use_pallas, **kw)

    monkeypatch.setattr(gbm, "_partial_tables_mm", spy)
    monkeypatch.setenv("BQUERYD_TPU_PALLAS", "1")
    monkeypatch.setenv("BQUERYD_TPU_MATMUL_GROUPS", str(g))
    codes = np.arange(64, dtype=np.int32) % g
    vals = np.ones(64, dtype=np.int64)
    gbm.partial_tables(codes, (vals,), ("sum",), g)
    assert seen["use_pallas"] is False


def _worker_for(tmp_path, mem_store_url):
    from bqueryd_tpu.worker import WorkerNode

    return WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
    )


def _calc_msg(filenames):
    from bqueryd_tpu.messages import CalcMessage

    msg = CalcMessage({"payload": "groupby", "token": "00"})
    msg.set_args_kwargs(
        [filenames, ["g"], [["v", "sum", "v"]], []], {}
    )
    return msg


def test_result_cache_hit_and_activation_invalidation(
    tmp_path, mem_store_url, monkeypatch
):
    """A repeated identical query is served from the worker's result cache
    (no engine execution); rewriting the shard (two-phase activation bumps
    meta.json's mtime) invalidates the entry."""
    df = pd.DataFrame({"g": np.arange(20) % 3, "v": np.arange(20)})
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"))
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        calls = []
        real_execute = worker._execute
        monkeypatch.setattr(
            worker, "_execute",
            lambda *a, **kw: calls.append(1) or real_execute(*a, **kw),
        )
        first = worker.handle_work(_calc_msg(["t.bcolzs"]))
        second = worker.handle_work(_calc_msg(["t.bcolzs"]))
        assert calls == [1], "second query must be served from cache"
        assert first["data"] == second["data"]

        # activation rewrites the table: meta.json is written atomically
        # (temp + rename), so the table identity changes via the fresh inode
        # even within filesystem timestamp granularity — no mtime bump needed
        df2 = pd.DataFrame({"g": np.arange(20) % 3, "v": np.arange(20) * 10})
        import shutil

        shutil.rmtree(str(tmp_path / "t.bcolzs"))
        ctable.fromdataframe(df2, str(tmp_path / "t.bcolzs"))
        third = worker.handle_work(_calc_msg(["t.bcolzs"]))
        assert calls == [1, 1], "rewritten shard must recompute"
        assert third["data"] != first["data"]
    finally:
        worker.socket.close()


def test_result_cache_disabled_by_env(tmp_path, mem_store_url, monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_RESULT_CACHE_BYTES", "0")
    df = pd.DataFrame({"g": np.arange(6) % 2, "v": np.arange(6)})
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"))
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        calls = []
        real_execute = worker._execute
        monkeypatch.setattr(
            worker, "_execute",
            lambda *a, **kw: calls.append(1) or real_execute(*a, **kw),
        )
        worker.handle_work(_calc_msg(["t.bcolzs"]))
        worker.handle_work(_calc_msg(["t.bcolzs"]))
        assert calls == [1, 1], "cache disabled: every query executes"
    finally:
        worker.socket.close()


def test_wire_dtype_narrows_by_stats(shard_tables):
    _, tables = shard_tables
    assert _wire_dtype(tables, "v") == np.dtype(np.int16)
    assert _wire_dtype(tables, "big") is None  # full-range int64 can't narrow
    assert _wire_dtype(tables, "f") is None    # floats ship as stored
    assert _codes_dtype(6) == np.dtype(np.int8)
    assert _codes_dtype(1000) == np.dtype(np.int16)
    assert _codes_dtype(100_000) == np.dtype(np.int32)


def test_narrowed_query_matches_pandas(shard_tables):
    frames, tables = shard_tables
    q = GroupByQuery(
        ["g"],
        [["v", "sum", "vs"], ["v", "min", "vmin"], ["big", "sum", "bs"],
         ["f", "mean", "fm"]],
    )
    ex = MeshQueryExecutor(mesh=make_mesh())
    r = ex.execute(tables, q)
    full = pd.concat(frames, ignore_index=True)
    expect = full.groupby("g").agg(
        vs=("v", "sum"), vmin=("v", "min"), bs=("big", "sum"),
        fm=("f", "mean"),
    )
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_array_equal(
        r["aggs"][0]["sum"][order], expect["vs"].to_numpy()
    )
    got_min = r["aggs"][1]["min"][order]
    assert got_min.dtype == np.int64  # restored to the stored dtype
    np.testing.assert_array_equal(got_min, expect["vmin"].to_numpy())
    # int64 sums wrap mod 2^64 exactly like numpy; compare against numpy
    np.testing.assert_array_equal(
        r["aggs"][2]["sum"][order], expect["bs"].to_numpy()
    )
    np.testing.assert_allclose(
        r["aggs"][3]["sum"][order] / r["aggs"][3]["count"][order],
        expect["fm"].to_numpy(),
        rtol=1e-6,
    )


def test_set_and_array_where_terms_cacheable(shard_tables):
    frames, tables = shard_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    q = GroupByQuery(
        ["g"], [["v", "sum", "vs"]], where_terms=[["g", "in", {1, 2}]]
    )
    r = ex.execute(tables, q)  # must not crash on the set-valued term
    full = pd.concat(frames, ignore_index=True)
    expect = full[full["g"].isin([1, 2])].groupby("g")["v"].sum()
    order = np.argsort(r["keys"]["g"])
    np.testing.assert_array_equal(
        r["aggs"][0]["sum"][order], expect.to_numpy()
    )
    # distinct arrays with identical truncated reprs must not collide
    a = np.arange(2000)
    b = a.copy()
    b[1000] = -1
    sig_a = _freeze(a)
    sig_b = _freeze(b)
    assert sig_a != sig_b
    assert _freeze({1, 2}) == _freeze({2, 1})


def test_repeat_query_hits_caches(shard_tables):
    frames, tables = shard_tables
    ex = MeshQueryExecutor(mesh=make_mesh())
    q = GroupByQuery(["g"], [["v", "sum", "vs"]])
    ex.execute(tables, q)
    assert len(ex._codes_cache) == 1  # folded group codes
    assert len(ex._hbm_cache) == 1    # one measure block
    assert len(ex._align_cache) == 1
    before = (len(ex._codes_cache), len(ex._hbm_cache))
    ex.execute(tables, q)
    # no new blocks on repeat
    assert (len(ex._codes_cache), len(ex._hbm_cache)) == before
    ex.clear_caches()
    assert len(ex._hbm_cache) == 0 and ex._hbm_cache.nbytes == 0
    assert len(ex._codes_cache) == 0 and len(ex._align_cache) == 0


def test_where_signature_distinguishes_filters():
    q1 = GroupByQuery(["g"], [["v", "sum", "v"]], where_terms=[["v", ">", 1]])
    q2 = GroupByQuery(["g"], [["v", "sum", "v"]], where_terms=[["v", ">", 2]])
    assert _where_signature(q1) != _where_signature(q2)
