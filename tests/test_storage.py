import os

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.storage import codec as codec_mod
from bqueryd_tpu.storage import ctable, native


def taxi_like_df(n=10_000, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "VendorID": rng.integers(1, 3, n).astype(np.int64),
            "passenger_count": rng.integers(0, 7, n).astype(np.int64),
            "payment_type": rng.integers(1, 5, n).astype(np.int64),
            "trip_distance": rng.exponential(3.0, n),
            "fare_amount": rng.gamma(2.0, 7.0, n),
            "total_amount": rng.gamma(2.5, 8.0, n),
            "store_and_fwd_flag": rng.choice(["Y", "N"], n),
            "tpep_pickup_datetime": pd.Timestamp("2016-01-01")
            + pd.to_timedelta(rng.integers(0, 31 * 24 * 3600, n), unit="s"),
        }
    )


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec_id", [codec_mod.RAW, codec_mod.LZ4, codec_mod.ZLIB])
@pytest.mark.parametrize("elem_size", [1, 4, 8])
def test_codec_roundtrip(codec_id, elem_size):
    rng = np.random.default_rng(42)
    # compressible typed data: small-range ints in wide dtypes
    arr = rng.integers(0, 50, 10_000)
    payload = arr.astype(f"<i{elem_size}" if elem_size > 1 else "u1").tobytes()
    used, buf = codec_mod.encode_chunk(payload, elem_size, codec_id)
    out = codec_mod.decode_chunk(buf, len(payload), elem_size, used)
    assert out == payload
    if used != codec_mod.RAW and elem_size > 1:
        # shuffle makes the high bytes of small-range wide ints runs of zeros
        assert len(buf) < len(payload), "typed data should compress"


def test_codec_python_lz4_decoder_matches_native():
    if not native.available():
        pytest.skip("native lib not built")
    rng = np.random.default_rng(7)
    payload = rng.integers(0, 9, 50_000).astype(np.int64).tobytes()
    _, buf = codec_mod.encode_chunk(payload, 8, codec_mod.LZ4)
    # native-encoded LZ4 chunk must be readable by the pure-Python fallback
    shuffled = codec_mod._lz4_decompress_py(buf, len(payload))
    assert codec_mod._unshuffle(shuffled, 8) == payload


def test_codec_corrupt_chunk_raises():
    payload = np.arange(1000, dtype=np.int64).tobytes()
    used, buf = codec_mod.encode_chunk(payload, 8, codec_mod.LZ4)
    bad = bytes([buf[0] ^ 0xFF]) + buf[1:]
    with pytest.raises(Exception):
        codec_mod.decode_chunk(bad, len(payload), 8, used)


def test_factorize_i64_first_seen_order():
    values = np.array([30, 10, 30, 20, 10, 30], dtype=np.int64)
    codes, uniques = codec_mod.factorize_i64(values)
    assert uniques.tolist() == [30, 10, 20]
    assert codes.tolist() == [0, 1, 0, 2, 1, 0]


# ---------------------------------------------------------------------------
# ctable
# ---------------------------------------------------------------------------

def test_ctable_roundtrip_dataframe(tmp_path):
    df = taxi_like_df()
    root = str(tmp_path / "taxi.bcolz")
    ct = ctable.fromdataframe(df, rootdir=root)
    assert len(ct) == len(df)
    assert ct.names == list(df.columns)

    ct2 = ctable(root, mode="r")
    out = ct2.todataframe()
    pd.testing.assert_frame_equal(
        out, df.astype({"store_and_fwd_flag": object}), check_dtype=False,
        check_column_type=False,
    )


def test_ctable_dict_column_physical_codes(tmp_path):
    df = pd.DataFrame({"flag": ["N", "Y", "N", "N", "Y"]})
    ct = ctable.fromdataframe(df, rootdir=str(tmp_path / "t.bcolz"))
    codes = ct.column_raw("flag")
    assert codes.dtype == np.int32
    assert ct.dictionary("flag") == ["N", "Y"]
    assert codes.tolist() == [0, 1, 0, 0, 1]


def test_ctable_datetime_roundtrip(tmp_path):
    ts = pd.date_range("2016-01-01", periods=5, freq="h")
    df = pd.DataFrame({"t": ts})
    ct = ctable.fromdataframe(df, rootdir=str(tmp_path / "t.bcolz"))
    assert ct.column_raw("t").dtype == np.int64
    np.testing.assert_array_equal(ct.column("t"), ts.to_numpy())


def test_ctable_append_extends_dictionary(tmp_path):
    root = str(tmp_path / "t.bcolz")
    ct = ctable.fromdataframe(pd.DataFrame({"c": ["a", "b"], "x": [1, 2]}), root)
    ct2 = ctable(root, mode="a")
    ct2.append_dataframe(pd.DataFrame({"c": ["b", "z"], "x": [3, 4]}))
    ct3 = ctable(root, mode="r")
    assert len(ct3) == 4
    assert ct3.column("c").tolist() == ["a", "b", "b", "z"]
    assert ct3.column("x").tolist() == [1, 2, 3, 4]
    assert ct3.dictionary("c") == ["a", "b", "z"]


def test_ctable_multi_chunk(tmp_path):
    df = pd.DataFrame({"x": np.arange(10_000, dtype=np.int64)})
    ct = ctable.fromdataframe(df, rootdir=str(tmp_path / "t.bcolz"), chunklen=1024)
    ct2 = ctable(str(tmp_path / "t.bcolz"), mode="r")
    np.testing.assert_array_equal(ct2.column("x"), df["x"].to_numpy())
    assert len(ct2._columns["x"].chunks) == 10


def test_ctable_attrs(tmp_path):
    root = str(tmp_path / "t.bcolz")
    ct = ctable.fromdataframe(pd.DataFrame({"x": [1]}), root)
    ct.set_attrs(ticket="abc123", timestamp=1234.5)
    assert ctable(root, mode="r").attrs == {"ticket": "abc123", "timestamp": 1234.5}


def test_ctable_open_missing_raises(tmp_path):
    with pytest.raises(IOError):
        ctable(str(tmp_path / "nope.bcolz"), mode="r")


def test_ctable_column_cache_identity(tmp_path):
    root = str(tmp_path / "t.bcolz")
    ctable.fromdataframe(pd.DataFrame({"x": np.arange(100)}), root)
    ct = ctable(root, mode="r", auto_cache=True)
    a = ct.column_raw("x")
    b = ct.column_raw("x")
    assert a is b, "cache should return the same array object"
    assert not a.flags.writeable


def test_native_lib_is_available():
    # The image has g++/cmake; the native path must be active so the bench
    # measures the real decoder, not the fallback.
    assert native.available()


def test_ctable_mixed_codec_append_readable(tmp_path, monkeypatch):
    """A table written with the native LZ4 codec then appended on a host
    without the native lib (zlib fallback) must stay fully readable."""
    root = str(tmp_path / "mixed.bcolz")
    ctable.fromdataframe(pd.DataFrame({"x": np.arange(100, dtype=np.int64)}), root)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_searched", True)
    ct = ctable(root, mode="a")
    ct.append_dataframe(pd.DataFrame({"x": np.arange(100, 200, dtype=np.int64)}))
    monkeypatch.undo()
    assert ctable(root, mode="r").column("x").tolist() == list(range(200))


def test_ctable_corrupt_chunk_detected(tmp_path):
    import glob

    root = str(tmp_path / "c.bcolz")
    ctable.fromdataframe(pd.DataFrame({"x": np.arange(50_000, dtype=np.int64)}), root)
    data = glob.glob(root + "/cols/x/data.tpc")[0]
    buf = bytearray(open(data, "rb").read())
    buf[len(buf) // 2] ^= 0xFF
    open(data, "wb").write(bytes(buf))
    with pytest.raises(Exception):
        ctable(root, mode="r", auto_cache=False).column("x")


def test_ctable_inconsistent_meta_rejected(tmp_path):
    """Chunk index disagreeing with table nrows must error, not overflow."""
    import json

    root = str(tmp_path / "bad.bcolz")
    ctable.fromdataframe(pd.DataFrame({"x": np.arange(100, dtype=np.int64)}), root)
    meta = json.load(open(root + "/meta.json"))
    meta["nrows"] = 50
    json.dump(meta, open(root + "/meta.json", "w"))
    with pytest.raises(IOError):
        ctable(root, mode="r", auto_cache=False).column_raw("x")


def test_factor_cache_sidecar_roundtrip_and_invalidation(tmp_path):
    """The on-disk factorize sidecar (bquery auto_cache parity) round-trips,
    is skipped when disabled, and invalidates when the column data changes."""
    import pandas as pd

    from bqueryd_tpu.models.query import QueryEngine

    root = str(tmp_path / "t.bcolzs")
    values = np.array([5, 5, 9, -3, 9, 5], dtype=np.int64)
    ctable.fromdataframe(pd.DataFrame({"k": values}), root)
    ct = ctable(root, mode="r")

    engine = QueryEngine()
    codes, uniques = engine._key_codes(ct, "k")
    sidecar = os.path.join(root, "cols", "k", "factor.npz")
    assert os.path.isfile(sidecar), "factorize must persist next to the shard"

    # a cold engine (fresh process analogue) loads the SAME factorization
    # from disk without decoding the column.  Poison the factorizer so a
    # silent load-path regression (always-miss) cannot hide behind a
    # recompute that yields identical output.
    from bqueryd_tpu import ops as ops_mod

    real_factorize = ops_mod.factorize
    ops_mod.factorize = lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("cold path recomputed instead of hitting the sidecar")
    )
    try:
        cold = QueryEngine()
        c2, u2 = cold._key_codes(ctable(root, mode="r"), "k")
    finally:
        ops_mod.factorize = real_factorize
    np.testing.assert_array_equal(np.asarray(c2), np.asarray(codes))
    np.testing.assert_array_equal(u2, uniques)

    # appending rewrites the data file -> stamp mismatch -> fresh factorize
    ct_w = ctable(root, mode="a")
    ct_w.append_dataframe(pd.DataFrame({"k": np.array([7], dtype=np.int64)}))
    ct_w.flush()
    c3, u3 = QueryEngine()._key_codes(ctable(root, mode="r"), "k")
    assert len(c3) == 7 and 7 in np.asarray(u3)

    # kill switch
    os.environ["BQUERYD_TPU_DISK_FACTOR_CACHE"] = "0"
    try:
        assert ctable(root, mode="r").factor_cache_load("k") is None
    finally:
        del os.environ["BQUERYD_TPU_DISK_FACTOR_CACHE"]


def test_factor_cache_stores_post_poison_codes(tmp_path):
    """Null keys (NaN) are poisoned to -1 BEFORE the sidecar is written, so
    a disk load must not resurrect them as live groups."""
    import pandas as pd

    from bqueryd_tpu.models.query import QueryEngine

    root = str(tmp_path / "f.bcolzs")
    vals = np.array([1.5, np.nan, 2.5, np.nan, 1.5])
    ctable.fromdataframe(pd.DataFrame({"k": vals}), root)
    codes, _ = QueryEngine()._key_codes(ctable(root, mode="r"), "k")
    c2, u2 = QueryEngine()._key_codes(ctable(root, mode="r"), "k")  # disk hit
    np.testing.assert_array_equal(np.asarray(c2), np.asarray(codes))
    assert (np.asarray(c2)[[1, 3]] == -1).all()
    assert not np.isnan(np.asarray(u2)[np.asarray(c2)[[0, 2, 4]]]).any()


def test_composite_cache_digest_guards_shard_set(tmp_path):
    """The composite sidecar must refuse a hit when the global-dictionary
    digest changes (same shard, different shard SET)."""
    import pandas as pd

    root = str(tmp_path / "c.bcolzs")
    ctable.fromdataframe(
        pd.DataFrame(
            {
                "a": np.array([0, 1, 0, 1], dtype=np.int64),
                "b": np.array([2, 3, 3, 2], dtype=np.int64),
            }
        ),
        root,
    )
    ct = ctable(root, mode="r")
    codes = np.array([0, 3, 1, 2], dtype=np.int32)
    uniq = np.array([0, 5, 7, 3], dtype=np.int64)
    ct.composite_cache_store(
        ["a", "b"], b"digest-one", codes, uniq,
        stamp=ct.composite_stamp(["a", "b"]),
    )
    hit = ct.composite_cache_load(["a", "b"], b"digest-one")
    assert hit is not None
    np.testing.assert_array_equal(hit[0], codes)
    np.testing.assert_array_equal(hit[1], uniq)
    assert ct.composite_cache_load(["a", "b"], b"digest-two") is None
    assert ct.composite_cache_load(["b", "a"], b"digest-one") is None
