"""Fleet capacity telemetry (PR 12): the queueing-model saturation
accounting in obs.capacity — μ/λ/ρ estimation, WRM-reset robustness, state
hysteresis, the M/G/1 prediction + drift, shard heat / skew detection, the
shadow advisor, the rpc.capacity() verb, timeline-ring capacity fields, and
the worker-restart-mid-burst regression."""

import json
import logging
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.obs import capacity
from tests.conftest import wait_until


def svc_snapshot(count, total, buckets=(0.05, 0.1, 0.25, 0.5),
                 counts=None):
    """A WRM histogram snapshot with the given cumulative service totals."""
    if counts is None:
        counts = [count] + [0] * len(buckets)
    return {
        capacity.SERVICE_FAMILY: [
            {"buckets": list(buckets), "counts": counts, "sum": total}
        ]
    }


@pytest.fixture()
def fast_knobs(monkeypatch):
    """No hysteresis, a short window: unit tests exercise transitions
    without wall-clock waits."""
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_HYSTERESIS_S", "0")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_WINDOW_S", "30")


# -- service-rate estimation ---------------------------------------------------

def test_service_totals_parses_and_defends():
    count, total, bounds, counts = capacity.service_totals(
        svc_snapshot(7, 1.4)
    )
    assert (count, total) == (7, 1.4)
    assert bounds and len(counts) == len(bounds) + 1
    assert capacity.service_totals({})[0] == 0
    assert capacity.service_totals({capacity.SERVICE_FAMILY: "junk"})[0] == 0
    assert capacity.service_totals(None if False else {"x": 1})[0] == 0


def test_mu_from_histogram_deltas(fast_knobs):
    m = capacity.CapacityModel()
    now = time.time()
    m.absorb_worker("w", svc_snapshot(0, 0.0), now=now)
    # 10 completions per beat, 0.1 s each -> mu = 10/s
    for i in range(1, 4):
        m.absorb_worker("w", svc_snapshot(i * 10, i * 1.0), now=now + i)
    result = m.evaluate(now=now + 4)
    w = result["workers"]["w"]
    assert w["mu"] == pytest.approx(10.0, rel=0.01)
    assert w["mean_service_s"] == pytest.approx(0.1, rel=0.01)
    assert w["samples"] == 30
    assert w["resets"] == 0


def test_restart_reset_rebases_not_poisons(fast_knobs):
    m = capacity.CapacityModel()
    now = time.time()
    m.absorb_worker("w", svc_snapshot(0, 0.0), now=now)
    m.absorb_worker("w", svc_snapshot(40, 4.0), now=now + 1)
    mu_before = m.evaluate(now=now + 1)["workers"]["w"]["mu"]
    # the worker process restarts under the same node id: totals near zero
    m.absorb_worker("w", svc_snapshot(2, 0.2), now=now + 2)
    result = m.evaluate(now=now + 2)
    w = result["workers"]["w"]
    assert w["resets"] == 1
    assert m.worker_resets() == 1
    # μ survives the restart untouched (EWMA kept, baseline rebased)
    assert w["mu"] == pytest.approx(mu_before, rel=0.01)
    # post-restart beats resume measuring from the rebased baseline
    m.absorb_worker("w", svc_snapshot(12, 1.2), now=now + 3)
    assert m.evaluate(now=now + 3)["workers"]["w"]["samples"] == 50


def test_out_of_order_snapshot_is_not_a_restart(fast_knobs):
    """The worker's two WRM streams can deliver snapshots slightly out of
    order; a barely-backwards total is a stale sample to drop, not a
    restart to rebase on."""
    m = capacity.CapacityModel()
    now = time.time()
    m.absorb_worker("w", svc_snapshot(0, 0.0), now=now)
    m.absorb_worker("w", svc_snapshot(40, 4.0), now=now + 1)
    m.absorb_worker("w", svc_snapshot(39, 3.9), now=now + 1.01)  # stale
    assert m.evaluate(now=now + 2)["workers"]["w"]["resets"] == 0
    # the baseline stayed at 40: the next real beat's delta is 10, not 11
    m.absorb_worker("w", svc_snapshot(50, 5.0), now=now + 2)
    assert m.evaluate(now=now + 2)["workers"]["w"]["samples"] == 50


def test_idle_heartbeats_leave_moments_alone(fast_knobs):
    m = capacity.CapacityModel()
    now = time.time()
    m.absorb_worker("w", svc_snapshot(0, 0.0), now=now)
    m.absorb_worker("w", svc_snapshot(10, 1.0), now=now + 1)
    mean = m.evaluate(now=now + 1)["workers"]["w"]["mean_service_s"]
    for i in range(2, 5):
        m.absorb_worker("w", svc_snapshot(10, 1.0), now=now + i)
    assert m.evaluate(now=now + 5)["workers"]["w"][
        "mean_service_s"
    ] == mean


def test_cv2_from_bucket_spread(fast_knobs):
    m = capacity.CapacityModel()
    now = time.time()
    buckets = (0.01, 0.1, 1.0)
    m.absorb_worker(
        "w", svc_snapshot(0, 0.0, buckets, [0, 0, 0, 0]), now=now
    )
    # half the completions fast, half slow: high dispersion
    m.absorb_worker(
        "w", svc_snapshot(10, 1.5, buckets, [5, 0, 5, 0]), now=now + 1
    )
    w = m.evaluate(now=now + 1)["workers"]["w"]
    assert w["cv2"] > 0.5


def test_pipeline_busy_bottleneck_and_reset_guard(fast_knobs):
    m = capacity.CapacityModel()
    now = time.time()
    m.absorb_worker(
        "w", svc_snapshot(0, 0.0),
        pipeline_busy={"busy_seconds": {"kernel": 1.0, "decode": 0.2}},
        now=now,
    )
    m.absorb_worker(
        "w", svc_snapshot(5, 1.0),
        pipeline_busy={"busy_seconds": {"kernel": 3.0, "decode": 0.4}},
        now=now + 1,
    )
    assert m.evaluate(now=now + 1)["workers"]["w"][
        "bottleneck_stage"
    ] == "kernel"
    # stage clocks reset (restart): the window delta is dropped, never
    # negative
    m.absorb_worker(
        "w", svc_snapshot(1, 0.2),
        pipeline_busy={"busy_seconds": {"kernel": 0.1, "decode": 0.9}},
        now=now + 2,
    )
    m.absorb_worker(
        "w", svc_snapshot(2, 0.4),
        pipeline_busy={"busy_seconds": {"kernel": 0.2, "decode": 2.0}},
        now=now + 3,
    )
    assert m.evaluate(now=now + 3)["workers"]["w"][
        "bottleneck_stage"
    ] == "decode"
    # a slightly-backwards stage total (stale snapshot from the worker's
    # other WRM stream) is dropped, not treated as a restart: the EWMA and
    # baseline survive and the label holds
    m.absorb_worker(
        "w", svc_snapshot(3, 0.6),
        pipeline_busy={"busy_seconds": {"kernel": 0.19, "decode": 1.99}},
        now=now + 4,
    )
    assert m.evaluate(now=now + 4)["workers"]["w"][
        "bottleneck_stage"
    ] == "decode"


# -- windows, states, hysteresis ----------------------------------------------

def test_rate_window_cold_start_and_trim():
    w = capacity._RateWindow(bucket_s=1.0)
    now = 1000.0
    for i in range(4):
        w.add(now + i, 2)
    # 8 events over ~4 s of observed life, not diluted over the horizon
    assert w.rate(now + 3.5, 60.0) == pytest.approx(8 / 3.5, rel=0.05)
    # far in the future everything expires (and trims)
    assert w.rate(now + 1000, 60.0) == 0.0
    assert w.buckets == {}


def test_classify_thresholds(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_WARM", "0.5")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_SATURATED", "0.8")
    assert capacity.classify(None) == "ok"
    assert capacity.classify(0.3) == "ok"
    assert capacity.classify(0.6) == "warm"
    assert capacity.classify(0.9) == "saturated"
    assert capacity.classify(1.2) == "overloaded"


def test_hysteresis_holds_then_flips():
    h = capacity._Hysteresis()
    now = 100.0
    assert h.update("saturated", now, hold_s=5.0) == "ok"
    assert h.update("saturated", now + 3, hold_s=5.0) == "ok"
    # a flap back resets the pending clock
    assert h.update("ok", now + 4, hold_s=5.0) == "ok"
    assert h.update("saturated", now + 5, hold_s=5.0) == "ok"
    assert h.update("saturated", now + 10.1, hold_s=5.0) == "saturated"
    # hold 0 flips immediately
    assert h.update("ok", now + 11, hold_s=0.0) == "ok"


# -- fleet derivation ----------------------------------------------------------

def _warm_model(m, now, qps=8, mu_per_worker=10, workers=("w1", "w2"),
                beats=5, shards=("s0", "s1")):
    """Drive a synthetic steady state: the fleet receives ``qps``
    arrivals/s split across workers/shards; each worker COMPLETES its
    share at mean service 1/μ (so its busy fraction tracks its load —
    serving 4/s at μ=10 is 40% busy, not flat out)."""
    served = max(qps // len(workers), 1)
    for w in workers:
        m.absorb_worker(w, svc_snapshot(0, 0.0), now=now)
    for i in range(1, beats + 1):
        t = now + i
        for w in workers:
            m.absorb_worker(
                w,
                svc_snapshot(i * served, i * served / mu_per_worker),
                now=t,
            )
        for q in range(qps):
            m.observe_arrival("default", now=t)
            m.observe_launch(now=t)
            m.observe_dispatch(
                workers[q % len(workers)], [shards[q % len(shards)]],
                now=t,
            )
    return now + beats


def test_fleet_knee_headroom_and_coverage(fast_knobs):
    m = capacity.CapacityModel()
    t = _warm_model(m, time.time(), qps=8, mu_per_worker=10)
    fleet = m.evaluate(now=t)["fleet"]
    assert fleet["coverage"] == 1.0
    assert fleet["arrival_qps"] == pytest.approx(8.0, rel=0.2)
    assert fleet["shards_per_query"] == pytest.approx(1.0, rel=0.05)
    # knee = Σμ / spq = 20 qps; headroom = knee * target_rho - λ
    assert fleet["knee_qps"] == pytest.approx(20.0, rel=0.05)
    expected_headroom = 20.0 * capacity.target_rho() - fleet["arrival_qps"]
    assert fleet["headroom_qps"] == pytest.approx(
        expected_headroom, rel=0.1
    )
    assert fleet["mu_dispatches_per_s"] == pytest.approx(20.0, rel=0.05)


def test_mg1_prediction_measured_and_drift(fast_knobs):
    m = capacity.CapacityModel()
    t = _warm_model(m, time.time(), qps=8, mu_per_worker=10)
    for _ in range(4):
        m.observe_queue_wait(0.02)
    fleet = m.evaluate(now=t)["fleet"]
    assert fleet["predicted_queue_delay_s"] is not None
    assert fleet["predicted_queue_delay_s"] > 0
    assert fleet["measured_queue_delay_s"] == pytest.approx(0.02, rel=0.05)
    assert fleet["model_drift"] is not None
    assert -1.0 <= fleet["model_drift"] <= 1.0


def test_remove_worker_shrinks_fleet_mu(fast_knobs):
    m = capacity.CapacityModel()
    t = _warm_model(m, time.time(), qps=4, mu_per_worker=10)
    before = m.evaluate(now=t)["fleet"]["mu_dispatches_per_s"]
    m.remove_worker("w2")
    after = m.evaluate(now=t)["fleet"]
    assert after["mu_dispatches_per_s"] == pytest.approx(
        before / 2, rel=0.05
    )
    assert after["workers"] == 1


# -- the shadow advisor --------------------------------------------------------

def test_advisor_scale_up_at_saturation(fast_knobs):
    m = capacity.CapacityModel()
    # λ 16/s against fleet μ 8/s: overloaded
    t = _warm_model(
        m, time.time(), qps=16, mu_per_worker=4, workers=("w1", "w2")
    )
    result = m.evaluate(now=t)
    assert result["fleet"]["state"] == "overloaded"
    recs = result["recommendations"]
    assert recs and recs[0]["action"] == "scale_up"
    # 16 dispatches/s at μ=4 per worker and target ρ 0.7 needs ~6 workers
    assert recs[0]["n"] >= 3
    assert recs[0]["evidence"]["workers"] == 2


def test_advisor_silent_when_idle_and_at_low_load(fast_knobs):
    m = capacity.CapacityModel()
    now = time.time()
    # workers present, zero traffic: no evidence, no advice (and
    # especially no scale_down loop on an idle cluster)
    for w in ("w1", "w2"):
        m.absorb_worker(w, svc_snapshot(0, 0.0), now=now)
        m.absorb_worker(w, svc_snapshot(10, 1.0), now=now + 1)
    assert m.evaluate(now=now + 1)["recommendations"] == []
    # light load on ONE worker: ok state, nothing to advise
    m2 = capacity.CapacityModel()
    t = _warm_model(
        m2, now, qps=2, mu_per_worker=10, workers=("w1",), shards=("s0",)
    )
    result = m2.evaluate(now=t)
    assert result["fleet"]["state"] == "ok"
    assert result["recommendations"] == []


def test_advisor_scale_down_when_overprovisioned(fast_knobs):
    m = capacity.CapacityModel()
    t = _warm_model(
        m, time.time(), qps=2, mu_per_worker=10,
        workers=("w1", "w2", "w3", "w4"),
    )
    result = m.evaluate(now=t)
    assert result["fleet"]["state"] == "ok"
    recs = result["recommendations"]
    assert recs and recs[0]["action"] == "scale_down"
    assert 1 <= recs[0]["n"] <= 3
    assert recs[0]["evidence"]["workers_needed"] >= 1


def test_advisor_rebalance_on_shard_skew(fast_knobs, monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_RHO_SATURATED", "0.6")
    m = capacity.CapacityModel()
    now = time.time()
    for w in ("hot", "cool"):
        m.absorb_worker(w, svc_snapshot(0, 0.0), now=now)
    for i in range(1, 6):
        t = now + i
        # hot serves 10/s flat out; cool serves 1/s with idle room
        m.absorb_worker("hot", svc_snapshot(i * 10, i * 1.0), now=t)
        m.absorb_worker("cool", svc_snapshot(i * 1, i * 0.02), now=t)
        for q in range(10):
            m.observe_arrival("default", now=t)
            m.observe_dispatch("hot", ["s_hot"], now=t)
        m.observe_dispatch("cool", ["s_a"], now=t)
        # cold shards exist so the skew has a uniform share to beat
        for shard in ("s_b", "s_c"):
            m.observe_dispatch("cool", [shard], now=t)
    result = m.evaluate(now=now + 5)
    actions = {r["action"]: r for r in result["recommendations"]}
    assert "rebalance" in actions
    reb = actions["rebalance"]
    assert reb["shard"] == "s_hot"
    assert reb["to_worker"] == "cool"
    assert reb["evidence"]["skew"] >= capacity.SHARD_SKEW_FACTOR
    heat = result["shard_heat"]
    assert heat[0]["shard"] == "s_hot" and heat[0]["share"] > 0.5


def test_advice_emitted_once_per_change_and_counted(fast_knobs):
    emitted = []
    m = capacity.CapacityModel(on_advice=emitted.append)
    t = _warm_model(
        m, time.time(), qps=16, mu_per_worker=4, workers=("w1", "w2")
    )
    m.evaluate(now=t)
    m.evaluate(now=t + 0.1)     # unchanged advice: no re-emit
    assert len(emitted) == 1
    assert emitted[0]["action"] == "scale_up"
    assert m.advice_count("scale_up") == 1
    assert m.evaluate(now=t)["advice_counts"]["scale_up"] == 1
    # a still-standing scale_up whose sizing `n` flaps (ceil quantization
    # near a boundary) must NOT re-emit: more load arrives, n grows, the
    # recommendation stands — one emission total
    for i in range(10):
        m.observe_arrival("default", now=t)
        m.observe_dispatch("w1", ["s0"], now=t)
    result = m.evaluate(now=t + 0.2)
    assert result["recommendations"][0]["action"] == "scale_up"
    assert len(emitted) == 1
    assert m.advice_count("scale_up") == 1


def test_shed_offers_do_not_inflate_the_knee(fast_knobs):
    """Offers that never launch (BUSY shed, queued-then-expired,
    superseded) count toward λ (offered load) but not toward the
    shards-per-query denominator — shedding must not make the knee read
    higher exactly when the cluster is saturated."""
    m = capacity.CapacityModel()
    t = _warm_model(m, time.time(), qps=8, mu_per_worker=10)
    knee_before = m.evaluate(now=t)["fleet"]["knee_qps"]
    # a burst of shed offers: arrivals with no launch behind them
    for _ in range(40):
        m.observe_arrival("default", now=t)
    fleet = m.evaluate(now=t)["fleet"]
    assert fleet["arrival_qps"] > fleet["launched_qps"]
    assert fleet["knee_qps"] == pytest.approx(knee_before, rel=0.01)


def test_pid_change_is_an_exact_restart_signal(fast_knobs):
    """A restart the halving heuristic would miss (the old count was
    small, the new one already past half) still rebases when the WRM's
    advertised pid changed — no cross-restart delta ever reaches μ."""
    m = capacity.CapacityModel()
    now = time.time()
    m.absorb_worker("w", svc_snapshot(0, 0.0), pid=100, now=now)
    m.absorb_worker("w", svc_snapshot(4, 0.4), pid=100, now=now + 1)
    # restarted process already served 3 (3 > 4//2: heuristic blind)
    m.absorb_worker("w", svc_snapshot(3, 9.0), pid=200, now=now + 2)
    w = m.evaluate(now=now + 2)["workers"]["w"]
    assert w["resets"] == 1
    # the 9.0s cross-restart sum never poisoned the mean (still 0.1)
    assert w["mean_service_s"] == pytest.approx(0.1, rel=0.01)
    # post-restart deltas measure from the rebased baseline
    m.absorb_worker("w", svc_snapshot(13, 10.0), pid=200, now=now + 3)
    assert m.evaluate(now=now + 3)["workers"]["w"]["samples"] == 14


def test_advisor_sizes_against_usable_workers(fast_knobs):
    """scale_up sizing counts only usable (measured, non-wedged) workers:
    2 of 4 wedged means the gap is measured from 2, not 4."""
    m = capacity.CapacityModel()
    now = time.time()
    workers = ("w1", "w2", "w3", "w4")
    for w in workers:
        m.absorb_worker(w, svc_snapshot(0, 0.0), now=now)
    for i in range(1, 6):
        t = now + i
        for w in workers:
            m.absorb_worker(
                w, svc_snapshot(i * 4, i * 1.0),
                wedged=w in ("w3", "w4"), now=t,
            )
        for q in range(14):
            m.observe_arrival("default", now=t)
            m.observe_launch(now=t)
            m.observe_dispatch(workers[q % 2], ["s0"], now=t)
    result = m.evaluate(now=now + 5)
    assert result["fleet"]["workers"] == 4
    assert result["fleet"]["measured_workers"] == 2
    recs = [r for r in result["recommendations"]
            if r["action"] == "scale_up"]
    assert recs, result["recommendations"]
    # λ=14 dispatches/s at μ=4/worker, target 0.7: needs ceil(5) = 5
    # usable workers; with 2 usable the ask is 3, not 1
    assert recs[0]["n"] == 3
    assert recs[0]["evidence"]["usable_workers"] == 2


# -- kill switch + surfaces ----------------------------------------------------

def test_kill_switch_disables_taps_and_evaluate(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY", "0")
    m = capacity.CapacityModel()
    m.absorb_worker("w", svc_snapshot(10, 1.0))
    m.observe_arrival()
    m.observe_dispatch("w", ["s"])
    m.observe_queue_wait(1.0)
    assert m.evaluate() == {}
    snap = m.snapshot()
    assert snap["enabled"] is False
    assert "workers" not in snap or not snap.get("workers")


def test_fleet_gauges_and_snapshot_json_safe(fast_knobs):
    m = capacity.CapacityModel()
    t = _warm_model(m, time.time(), qps=16, mu_per_worker=4)
    m.evaluate(now=t)
    assert m.fleet_gauge("state") == capacity.STATE_CODES["overloaded"]
    assert m.fleet_gauge("utilization") > 1.0
    assert m.fleet_gauge("headroom_qps") == 0.0
    json.dumps(m.snapshot())  # must be JSON-safe end to end


# -- health scorer restart regression (satellite) ------------------------------

def test_health_scorer_rebases_on_worker_restart():
    from bqueryd_tpu.obs.health import HealthScorer

    scorer = HealthScorer(window_s=300.0)
    now = time.time()

    def snap(count, total):
        return {
            "bqueryd_tpu_worker_groupby_seconds": [
                {"counts": [count], "sum": total}
            ]
        }

    scorer.observe("w", snapshot=snap(0, 0.0), errors=0, now=now)
    scorer.observe("w", snapshot=snap(40, 4.0), errors=2, now=now + 1)
    assert scorer.statuses()["w"]["queries"] == 40
    # restart: totals reset to zero; the window must rebase, and the next
    # delta must reflect the restarted process's real throughput instead
    # of clamping to 0 until the pre-restart samples age out
    scorer.observe("w", snapshot=snap(0, 0.0), errors=0, now=now + 2)
    scorer.observe("w", snapshot=snap(30, 9.0), errors=0, now=now + 3)
    stats = scorer.statuses()["w"]
    assert stats["queries"] == 30
    assert stats["mean_latency_s"] == pytest.approx(0.3, rel=0.01)
    # a slightly out-of-order snapshot is NOT a restart: the window keeps
    # its post-restart baseline (a one-off ±1 in the first-vs-last delta,
    # not a rebase to an empty window)
    scorer.observe("w", snapshot=snap(29, 8.7), errors=0, now=now + 3.01)
    assert scorer.statuses()["w"]["queries"] >= 29


# -- e2e: cluster --------------------------------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        if node is not None:
            node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture()
def capacity_cluster(tmp_path, mem_store_url, monkeypatch):
    """Controller + one worker over two shards with fast capacity knobs."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_HYSTERESIS_S", "0")
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY_WINDOW_S", "30")
    rng = np.random.default_rng(7)
    df = pd.DataFrame({
        "g": rng.integers(0, 5, 2000).astype(np.int64),
        "v": rng.integers(-1000, 1000, 2000).astype(np.int64),
    })
    shards = ["cap_0.bcolzs", "cap_1.bcolzs"]
    for i, name in enumerate(shards):
        ctable.fromdataframe(
            df.iloc[i::2].reset_index(drop=True), str(tmp_path / name)
        )
    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.05,
    )
    worker = WorkerNode(
        coordination_url=mem_store_url, data_dir=str(tmp_path),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.1, poll_timeout=0.05,
    )
    threads = _start(controller, worker)
    wait_until(
        lambda: all(name in controller.files_map for name in shards),
        desc="shards advertised",
    )
    expected = df.groupby("g")["v"].sum().to_dict()
    yield {
        "controller": controller, "worker": worker, "shards": shards,
        "url": mem_store_url, "tmp_path": tmp_path, "expected": expected,
    }
    _stop([controller, worker], threads)


def _ask(url, shards, timeout=45):
    from bqueryd_tpu.rpc import RPC

    rpc = RPC(coordination_url=url, timeout=timeout,
              loglevel=logging.WARNING)
    df = rpc.groupby(list(shards), ["g"], [["v", "sum", "s"]], [])
    got = dict(zip(df["g"].tolist(), df["s"].tolist()))
    return rpc, got


def test_rpc_capacity_e2e(capacity_cluster):
    controller = capacity_cluster["controller"]
    rpc, got = _ask(
        capacity_cluster["url"], capacity_cluster["shards"]
    )
    assert got == capacity_cluster["expected"]
    for _ in range(3):
        rpc.groupby(
            capacity_cluster["shards"], ["g"], [["v", "sum", "s"]], []
        )
    worker_id = capacity_cluster["worker"].worker_id
    # the WRM-fed μ needs heartbeats carrying all 4 completions' totals
    wait_until(
        lambda: controller.capacity.evaluate().get("workers", {})
        .get(worker_id, {}).get("samples", 0) >= 4,
        desc="capacity model absorbed every completion",
    )
    snap = rpc.capacity()
    assert snap["enabled"] is True
    fleet = snap["fleet"]
    assert fleet["workers"] == 1
    assert fleet["coverage"] == 1.0
    assert fleet["arrival_qps"] > 0
    assert fleet["knee_qps"] is not None and fleet["knee_qps"] > 0
    assert fleet["state"] in ("ok", "warm", "saturated", "overloaded")
    w = snap["workers"][worker_id]
    assert w["mu"] > 0 and w["samples"] >= 4
    # the pipeline busy clocks rode the WRM: a bottleneck stage is named
    assert w["bottleneck_stage"] is not None
    # both shards appear on the heat map via the batched group dispatch
    heat_shards = {h["shard"] for h in snap["shard_heat"]}
    assert set(capacity_cluster["shards"]) <= heat_shards
    # measured admission/dispatch waits flowed from finished autopsies
    assert fleet["measured_wait_samples"] > 0


def test_worker_restart_mid_burst_rebases_model(capacity_cluster):
    """The satellite regression: a worker process restarting under the
    same node id resets its cumulative WRM counters; the capacity model
    must rebase (resets counter), μ must stay finite/positive, and the
    health window must rebuild instead of reporting zero throughput."""
    from bqueryd_tpu.worker import WorkerNode

    controller = capacity_cluster["controller"]
    worker = capacity_cluster["worker"]
    worker_id = worker.worker_id
    rpc, got = _ask(capacity_cluster["url"], capacity_cluster["shards"])
    assert got == capacity_cluster["expected"]
    for _ in range(3):
        rpc.groupby(
            capacity_cluster["shards"], ["g"], [["v", "sum", "s"]], []
        )
    wait_until(
        lambda: controller.capacity.evaluate().get("workers", {})
        .get(worker_id, {}).get("samples", 0) >= 4,
        desc="pre-restart μ measured",
    )
    # crash the worker (no StopMessage — a graceful stop would deregister
    # it and drop the model's baseline, which is NOT the restart scenario)
    # and restart a fresh process-equivalent under the SAME node id
    # (fresh registries: cumulative totals restart at 0)
    worker.controllers.clear()
    worker.running = False
    wait_until(lambda: worker.socket.closed, desc="old worker stopped")
    worker2 = WorkerNode(
        coordination_url=capacity_cluster["url"],
        data_dir=str(capacity_cluster["tmp_path"]),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.1, poll_timeout=0.05,
    )
    worker2.worker_id = worker_id
    worker2.socket.identity = worker_id.encode()
    threads2 = _start(worker2)
    try:
        wait_until(
            lambda: controller.worker_map.get(worker_id, {}).get(
                "uptime", 1e9
            ) < 30,
            desc="restarted worker re-registered under the same id",
        )
        # mid-burst continues against the restarted worker
        rpc2, got2 = _ask(
            capacity_cluster["url"], capacity_cluster["shards"]
        )
        assert got2 == capacity_cluster["expected"]
        for _ in range(2):
            rpc2.groupby(
                capacity_cluster["shards"], ["g"], [["v", "sum", "s"]], []
            )
        wait_until(
            lambda: controller.capacity.worker_resets() >= 1,
            desc="capacity model detected the counter reset",
        )
        result = controller.capacity.evaluate()
        w = result["workers"][worker_id]
        assert w["mu"] is not None and w["mu"] > 0
        assert w["resets"] >= 1
        # the health scorer rebased too: the window reports the restarted
        # process's own (positive) throughput, not a clamped zero
        wait_until(
            lambda: controller.health.statuses().get(worker_id, {}).get(
                "queries", 0
            ) > 0,
            desc="health window rebuilt after restart",
        )
    finally:
        _stop([worker2], threads2)


def test_capacity_disabled_serves_stub(capacity_cluster, monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_CAPACITY", "0")
    rpc, _ = _ask(capacity_cluster["url"], capacity_cluster["shards"])
    snap = rpc.capacity()
    assert snap["enabled"] is False
