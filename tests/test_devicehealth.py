"""Accelerator-backend wedge detection and host-served degraded mode.

The failure mode is real on this project's dev backend: the tunneled TPU
stops answering and any dispatch blocks forever inside native code (no
signal can interrupt it).  These tests simulate the wedge through the
probe seam — no real hangs — and pin that the cluster keeps serving
exact results from the host kernels while latched, and resumes device
routing when a probe succeeds.
"""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from bqueryd_tpu.utils import devicehealth


@pytest.fixture(autouse=True)
def _reset_latch():
    devicehealth.force_state(False)
    yield
    devicehealth.force_state(False)


def test_latch_flips_when_probe_overdue_and_recovers_without_release(
    monkeypatch,
):
    """An in-flight probe past the deadline latches wedged without the
    caller ever blocking.  Recovery must NOT require the hung thread to
    return (a real wedge never does): the overdue probe is written off and
    a FRESH probe launched on the interval clock unlatches."""
    hang_forever = threading.Event()  # never set: a true wedge
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        if calls["n"] == 1:
            hang_forever.wait(5)  # parked (bounded for test hygiene)

    monkeypatch.setattr(devicehealth, "_probe_fn", probe)
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_PROBE_TIMEOUT_S", "0.05")
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_PROBE_INTERVAL_S", "0.05")
    # arrange a fresh probe launch
    devicehealth._last_probe_start = 0.0
    t0 = time.perf_counter()
    assert devicehealth.backend_wedged() is False  # probe just launched
    assert time.perf_counter() - t0 < 1.0, "must never block"
    time.sleep(0.1)
    assert devicehealth.backend_wedged() is True  # overdue -> latched
    # the hung probe is written off; the interval clock launches probe #2
    # ("tunnel recovered": it succeeds) and the latch clears
    deadline = time.time() + 5
    while devicehealth.backend_wedged() and time.time() < deadline:
        time.sleep(0.02)
    assert devicehealth.backend_wedged() is False
    assert calls["n"] >= 2, "a fresh probe must have been launched"
    hang_forever.set()


def test_probe_error_latches_and_recovers(monkeypatch):
    """A probe that ERRORS (backend dead but answering) latches too."""
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_PROBE_INTERVAL_S", "0.05")
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("backend gone")

    monkeypatch.setattr(devicehealth, "_probe_fn", flaky)
    devicehealth._last_probe_start = 0.0
    devicehealth.backend_wedged()  # launches the erroring probe
    deadline = time.time() + 5
    while not devicehealth.backend_wedged() and time.time() < deadline:
        time.sleep(0.02)
    assert devicehealth.backend_wedged() is True
    # the interval clock keeps re-probes coming; the second succeeds
    deadline = time.time() + 5
    while devicehealth.backend_wedged() and time.time() < deadline:
        time.sleep(0.05)
    assert devicehealth.backend_wedged() is False


def test_run_with_deadline_abandons_hung_fn():
    ev = threading.Event()
    t0 = time.perf_counter()
    done, result = devicehealth.run_with_deadline(ev.wait, 0.05)
    assert not done and result is None
    assert time.perf_counter() - t0 < 1.0
    ev.set()  # release the parked thread
    done, result = devicehealth.run_with_deadline(lambda: 41 + 1, 5)
    assert done and result == 42


def test_host_kernel_rows_wedged_overrides_env(monkeypatch):
    """While latched, host routing is unbounded — even over an operator
    device-only pin (survival beats performance)."""
    from bqueryd_tpu.models import query as q

    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
    assert q.host_kernel_rows() == 0
    devicehealth.force_state(True)
    assert q.host_kernel_rows() == 1 << 62


def test_dispatch_floor_deadline_miss_latches(monkeypatch):
    from bqueryd_tpu.models import query as q

    monkeypatch.setattr(q, "_measured_floor", None)
    monkeypatch.setattr(
        devicehealth, "run_with_deadline", lambda fn, t: (False, None)
    )
    floor = q.device_dispatch_floor(remeasure=True)
    assert floor == devicehealth.probe_timeout_s()
    assert devicehealth.backend_wedged() is True
    # the garbage floor is NOT cached: recovery remeasures
    assert q._measured_floor is None


def test_wedged_engine_serves_exact_results(monkeypatch, tmp_path):
    """With the backend latched, a mergeable groupby, a count_distinct,
    and a basket filter all answer exactly from the host kernels."""
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.storage.ctable import ctable

    # make sure the engine would OTHERWISE route to the device
    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
    rng = np.random.default_rng(5)
    n = 30_000
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 9, n).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, n).astype(np.int64),
            "basket": rng.integers(0, 500, n).astype(np.int64),
        }
    )
    root = str(tmp_path / "w.bcolzs")
    ctable.fromdataframe(df, root)
    tbl = ctable(root, mode="r")
    devicehealth.force_state(True)
    engine = QueryEngine()

    def run(query):
        payload = engine.execute_local(tbl, query)
        return hostmerge.payload_to_dataframe(
            hostmerge.merge_payloads([payload])
        ).sort_values(query.groupby_cols).reset_index(drop=True)

    got = run(GroupByQuery(["k"], [["v", "sum", "s"]], [], aggregate=True))
    exp = (
        df.groupby("k", as_index=False)["v"].sum()
        .rename(columns={"v": "s"})
    )
    np.testing.assert_array_equal(got["s"].to_numpy(), exp["s"].to_numpy())

    # WITH a where filter: the mask must compute on host while wedged
    # (this was the gap a review pass caught — term_mask dispatched jnp)
    got = run(
        GroupByQuery(
            ["k"], [["v", "sum", "s"]], [["v", ">", 0]], aggregate=True
        )
    )
    sel = df[df["v"] > 0]
    exp = sel.groupby("k", as_index=False)["v"].sum()
    np.testing.assert_array_equal(got["s"].to_numpy(), exp["v"].to_numpy())

    # formerly the one device-only op: the numpy run-leader twin serves it
    got = run(
        GroupByQuery(
            ["k"],
            [["basket", "sorted_count_distinct", "d"]],
            [],
            aggregate=True,
        )
    )
    b = df["basket"].to_numpy()
    k = df["k"].to_numpy()
    # run-leader ground truth: a row starts a run unless the ADJACENT
    # previous row has the same (group, value) — the kernel's semantics
    prev_same = np.concatenate(
        [[False], (b[1:] == b[:-1]) & (k[1:] == k[:-1])]
    )
    exp = (
        pd.DataFrame({"k": k, "new": ~prev_same})
        .groupby("k")["new"].sum().sort_index()
    )
    np.testing.assert_array_equal(got["d"].to_numpy(), exp.to_numpy())

    got = run(
        GroupByQuery(
            ["k"], [["basket", "count_distinct", "d"]], [], aggregate=True
        )
    )
    exp = df.groupby("k")["basket"].nunique()
    np.testing.assert_array_equal(
        got["d"].to_numpy(), exp.sort_index().to_numpy()
    )

    # basket expansion path (expand_mask_by_group host fallback)
    from bqueryd_tpu import ops

    codes = df["basket"].to_numpy()
    mask = df["v"].to_numpy() > 0
    got_mask = np.asarray(
        ops.expand_mask_by_group(codes, mask, n_groups=500)
    )
    sel_groups = set(codes[mask])
    exp_mask = np.array([c in sel_groups for c in codes])
    np.testing.assert_array_equal(got_mask, exp_mask)


def test_wedged_worker_routes_around_mesh(monkeypatch, tmp_path):
    """The worker must not touch the mesh executor while latched."""
    from bqueryd_tpu.models.query import GroupByQuery
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.utils.tracing import PhaseTimer
    from bqueryd_tpu.worker import WorkerNode

    monkeypatch.setenv("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
    rng = np.random.default_rng(6)
    n = 60_000
    frames, tables = [], []
    for s in range(2):
        df = pd.DataFrame(
            {
                "k": rng.integers(0, 9, n).astype(np.int64),
                "v": rng.integers(-100, 100, n).astype(np.int64),
            }
        )
        frames.append(df)
        root = str(tmp_path / f"wm{s}.bcolzs")
        ctable.fromdataframe(df, root)
        tables.append(ctable(root, mode="r"))

    worker = WorkerNode.__new__(WorkerNode)
    worker._engine = None
    worker._result_cache = None

    class _MustNotRun:
        timer = None

        def execute(self, tables, query):
            raise AssertionError("mesh executor touched while wedged")

    worker._mesh_executor = _MustNotRun()
    import logging

    worker.logger = logging.getLogger("test-wedge")
    devicehealth.force_state(True)
    q = GroupByQuery(["k"], [["v", "sum", "s"]], [], aggregate=True)
    payload = worker._execute(tables, q, PhaseTimer())
    got = hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads([payload])
    ).sort_values("k").reset_index(drop=True)
    all_df = pd.concat(frames, ignore_index=True)
    exp = all_df.groupby("k")["v"].sum()
    np.testing.assert_array_equal(
        got["s"].to_numpy(), exp.sort_index().to_numpy()
    )


def test_prepare_wrm_carries_backend_wedged():
    """The worker's register/heartbeat message surfaces the latch so
    rpc.info() gives operators degraded-mode visibility."""
    from bqueryd_tpu.worker import WorkerNode

    worker = WorkerNode.__new__(WorkerNode)
    worker.worker_id = "w1"
    worker.node_name = "n1"
    worker.data_dir = "/tmp"
    worker.data_files = []
    worker.workertype = "calc"
    worker.start_time = time.time()
    worker.msg_count = 0
    devicehealth.force_state(False)
    assert worker.prepare_wrm()["backend_wedged"] is False
    devicehealth.force_state(True)
    assert worker.prepare_wrm()["backend_wedged"] is True


def test_wedged_cluster_serves_via_rpc(tmp_path, monkeypatch):
    """Full-stack degraded mode: a live (threads-as-nodes) cluster with the
    backend latched answers an RPC groupby exactly, and rpc.info() shows
    the worker advertising backend_wedged."""
    import logging
    import os

    # the JAX warmup daemon thread is pointless here (the backend is
    # latched) and a thread mid-compile at this short session's interpreter
    # exit aborts pthread teardown ("FATAL: exception not rethrown" —
    # the known gotcha; same pin as test_cluster_resilience)
    monkeypatch.setenv("BQUERYD_TPU_WARMUP", "0")

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode
    from tests.conftest import wait_until

    rng = np.random.default_rng(9)
    n = 40_000
    df = pd.DataFrame(
        {
            "k": rng.integers(0, 9, n).astype(np.int64),
            "v": rng.integers(-(2**40), 2**40, n).astype(np.int64),
        }
    )
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"))
    url = f"mem://wedge-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.2,
    )
    worker = WorkerNode(
        coordination_url=url, data_dir=str(tmp_path),
        loglevel=logging.WARNING, restart_check=False,
        heartbeat_interval=0.2, poll_timeout=0.1,
    )
    threads = [
        threading.Thread(target=controller.go, daemon=True),
        threading.Thread(target=worker.go, daemon=True),
    ]
    for t in threads:
        t.start()
    try:
        devicehealth.force_state(True)
        wait_until(lambda: controller.worker_map, desc="worker registration")
        rpc = RPC(coordination_url=url, timeout=30,
                  loglevel=logging.WARNING)
        wait_until(
            lambda: any(
                w.get("data_files")
                for w in rpc.info().get("workers", {}).values()
            ),
            desc="worker registered",
        )
        got = rpc.groupby(
            ["t.bcolzs"], ["k"], [["v", "sum", "s"]], []
        ).sort_values("k").reset_index(drop=True)
        exp = df.groupby("k")["v"].sum()
        np.testing.assert_array_equal(
            got["s"].to_numpy(), exp.sort_index().to_numpy()
        )
        # heartbeats advertise the latch within an interval
        wait_until(
            lambda: any(
                w.get("backend_wedged")
                for w in rpc.info().get("workers", {}).values()
            ),
            desc="wedged flag visible in info()",
        )
    finally:
        devicehealth.force_state(False)
        worker.stop()
        controller.stop()
        for t in threads:
            t.join(timeout=10)


def test_wedge_marker_catches_transient_wedge():
    """A wedge that latches and recovers INSIDE a window must dirty the
    window even though both endpoint reads say not-wedged."""
    clean_start = devicehealth.wedge_marker()
    assert not devicehealth.window_dirty(clean_start)
    devicehealth.latch_wedged()
    devicehealth.force_state(False)  # recovered before the end read
    assert devicehealth.backend_wedged(launch=False) is False
    assert devicehealth.window_dirty(clean_start), (
        "transient wedge inside the window must dirty it"
    )


def test_detection_disabled_by_zero_timeout(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_DEVICE_PROBE_TIMEOUT_S", "0")
    # even a forced latch reads False while disabled, and no probe launches
    devicehealth.force_state(True)
    assert devicehealth.backend_wedged() is False
