"""Controller scheduling edge cases: batched-group re-split when placement
changes, fail-fast on vanished files, duplicate-filename dedup, and the
download-failure path of the two-phase commit."""

import logging
import os
import time

import pytest

import bqueryd_tpu
from bqueryd_tpu.controller import ControllerNode
from bqueryd_tpu.messages import RPCMessage


@pytest.fixture
def controller(tmp_path):
    node = ControllerNode(
        coordination_url=f"mem://sched-{os.urandom(4).hex()}",
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
    )
    yield node
    node.socket.close()


def register(controller, worker_id, files, busy=True):
    controller.worker_map[worker_id] = {
        "worker_id": worker_id,
        "workertype": "calc",
        "busy": busy,
        "last_seen": time.time(),
        "node": controller.node_name,
    }
    for f in files:
        controller.files_map.setdefault(f, set()).add(worker_id)


def enqueue_groupby(controller, filenames):
    msg = RPCMessage({"payload": "groupby", "token": "00"})
    msg.set_args_kwargs(
        [filenames, ["k"], [["v", "sum", "v"]], []], {}
    )
    controller.rpc_groupby(msg)
    return msg


def queued(controller):
    return [m for q in controller.worker_out_messages.values() for m in q]


def test_colocated_shards_batch_into_one_message(controller):
    register(controller, "w1", ["a.bcolzs", "b.bcolzs", "c.bcolzs"])
    enqueue_groupby(controller, ["a.bcolzs", "b.bcolzs", "c.bcolzs"])
    msgs = queued(controller)
    assert len(msgs) == 1
    assert msgs[0]["filename"] == ["a.bcolzs", "b.bcolzs", "c.bcolzs"]


def test_split_placement_batches_per_worker_set(controller):
    register(controller, "w1", ["a.bcolzs", "b.bcolzs"])
    register(controller, "w2", ["c.bcolzs"])
    enqueue_groupby(controller, ["a.bcolzs", "b.bcolzs", "c.bcolzs"])
    names = sorted(
        str(m["filename"]) for m in queued(controller)
    )
    assert names == ["['a.bcolzs', 'b.bcolzs']", "c.bcolzs"]


def test_duplicate_filenames_deduplicated(controller):
    register(controller, "w1", ["a.bcolzs"])
    enqueue_groupby(controller, ["a.bcolzs", "a.bcolzs", "a.bcolzs"])
    (msg,) = queued(controller)
    assert msg["filename"] == "a.bcolzs"
    (segment,) = controller.rpc_segments.values()
    assert segment["filenames"] == ["a.bcolzs"]


def test_unservable_batch_resplits_to_per_shard(controller):
    register(controller, "w1", ["a.bcolzs", "b.bcolzs"])
    enqueue_groupby(controller, ["a.bcolzs", "b.bcolzs"])
    # placement changes: the co-locating worker dies, two new (busy) workers
    # each hold one shard
    controller.remove_worker("w1")
    register(controller, "w2", ["a.bcolzs"], busy=True)
    register(controller, "w3", ["b.bcolzs"], busy=True)
    controller.dispatch_pending()
    msgs = queued(controller)
    assert sorted(m["filename"] for m in msgs) == ["a.bcolzs", "b.bcolzs"]
    parent_tokens = {m["parent_token"] for m in msgs}
    assert len(parent_tokens) == 1  # still the same query
    assert len({m["token"] for m in msgs}) == 2  # fresh per-shard tokens


def test_vanished_file_aborts_parent_fast(controller):
    register(controller, "w1", ["a.bcolzs"])
    enqueue_groupby(controller, ["a.bcolzs"])
    controller.remove_worker("w1")  # file gone from every worker
    assert controller.rpc_segments
    controller.dispatch_pending()
    assert not queued(controller)
    assert not controller.rpc_segments  # aborted, client answered


def test_batch_respects_non_mergeable_ops(controller):
    register(controller, "w1", ["a.bcolzs", "b.bcolzs"])
    msg = RPCMessage({"payload": "groupby", "token": "00"})
    msg.set_args_kwargs(
        [["a.bcolzs", "b.bcolzs"], ["k"], [["v", "count_distinct", "v"]], []],
        {},
    )
    controller.rpc_groupby(msg)
    assert sorted(m["filename"] for m in queued(controller)) == [
        "a.bcolzs", "b.bcolzs",
    ]


# -- download failure path --------------------------------------------------


class _Worker:
    """Minimal downloader-shaped stand-in for download.py functions."""

    def __init__(self, store, data_dir):
        self.store = store
        self.data_dir = data_dir
        self.node_name = "testnode"
        self.failed = []
        import logging as _l

        self.logger = _l.getLogger("test.download")

    def download_file(self, ticket, fileurl):
        raise IOError("bucket on fire")

    def run_download(self, ticket, fileurl, lock):
        """Synchronous version of DownloaderNode.run_download (no pool)."""
        try:
            self.download_file(ticket, fileurl)
        except Exception as exc:
            self.fail_ticket(ticket, fileurl, str(exc))
        finally:
            lock.release()

    def fail_ticket(self, ticket, fileurl, error):
        from bqueryd_tpu import download

        download.fail_ticket(self, ticket, fileurl, error)
        self.failed.append((ticket, fileurl, error))


def test_failed_download_poisons_ticket(tmp_path, mem_store_url):
    from bqueryd_tpu import download
    from bqueryd_tpu.coordination import coordination_store

    store = coordination_store(mem_store_url)
    worker = _Worker(store, str(tmp_path))
    ticket = "t1"
    download.set_progress(store, "testnode", ticket, "s3://b/f.zip", -1)
    download.set_progress(store, "othernode", ticket, "s3://b/f.zip", "DONE")

    download.check_downloads(worker)
    assert worker.failed and worker.failed[0][0] == ticket
    err = download.ticket_error(store, ticket)
    assert err and err.startswith("ERROR")
    # slots survive (observable state), underscore-free reason parses cleanly
    assert "_" not in err.partition(":")[2]

    # movebcolz must NOT activate and must clear its staging
    staging = download.incoming_dir(worker, ticket)
    os.makedirs(os.path.join(staging, "f.bcolz"), exist_ok=True)
    download.check_moves(worker)
    assert not os.path.exists(staging)
    assert not os.path.exists(os.path.join(worker.data_dir, "f.bcolz"))

    # a second poll cycle skips the ERROR slot instead of retrying forever
    worker.failed.clear()
    download.check_downloads(worker)
    assert not worker.failed


def test_ticket_error_released_to_waiting_client(controller):
    """TicketDoneMessage with an error must answer wait=True clients with the
    failure, not DONE."""
    from bqueryd_tpu.messages import TicketDoneMessage

    controller.rpc_segments["ticket_t9"] = {
        "client_token": "00",
        "msg": RPCMessage({"payload": "download", "token": "00"}),
        "created": time.time(),
    }
    sent = []
    controller.reply_rpc_message = lambda tok, m: sent.append((tok, m))
    controller.release_ticket_waiters("t9", "bucket on fire")
    ((_tok, reply),) = sent
    assert reply["msg_type"] == "error"
    assert "bucket on fire" in reply["payload"]
    assert "ticket_t9" not in controller.rpc_segments


def test_concurrent_clients_all_get_correct_results(tmp_path, mem_store_url):
    """Four client threads interleaving two query shapes against a
    two-worker cluster: every reply must be the bit-correct answer for ITS
    query (exercises the affinity queues, busy/done flow control, and sink
    bookkeeping under real concurrency)."""
    import logging
    import threading

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode
    from tests.conftest import wait_until

    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(0)
    df = pd.DataFrame(
        {
            "g": rng.integers(0, 9, 20_000).astype(np.int64),
            "h": rng.integers(0, 4, 20_000).astype(np.int64),
            "v": rng.integers(-(10**10), 10**10, 20_000).astype(np.int64),
            "f": rng.random(20_000).astype(np.float32) * 50,
        }
    )
    for i in range(4):
        ctable.fromdataframe(
            df.iloc[i::4], str(tmp_path / f"s{i}.bcolzs")
        )
    controller = ControllerNode(
        coordination_url=mem_store_url, loglevel=logging.WARNING,
        runfile_dir=str(tmp_path), heartbeat_interval=0.2,
    )
    workers = [
        WorkerNode(
            coordination_url=mem_store_url, data_dir=str(tmp_path),
            loglevel=logging.WARNING, restart_check=False,
            heartbeat_interval=0.2, poll_timeout=0.1,
        )
        for _ in range(2)
    ]
    nodes = [controller] + workers
    threads = [threading.Thread(target=n.go, daemon=True) for n in nodes]
    for t in threads:
        t.start()
    try:
        wait_until(
            lambda: len(controller.files_map) >= 4, desc="shards registered"
        )
        shards = [f"s{i}.bcolzs" for i in range(4)]
        exp_sum = df.groupby("g")["v"].sum().sort_index().tolist()
        exp_multi = (
            df[df.f > 25].groupby(["g", "h"])["v"].sum().sort_index().tolist()
        )
        errors = []

        def client(ci):
            try:
                rpc = RPC(
                    coordination_url=mem_store_url, timeout=60,
                    loglevel=logging.WARNING,
                )
                for q in range(8):
                    if (ci + q) % 2 == 0:
                        got = rpc.groupby(
                            shards, ["g"], [["v", "sum", "s"]], []
                        ).sort_values("g")
                        assert got["s"].tolist() == exp_sum
                    else:
                        got = rpc.groupby(
                            shards, ["g", "h"], [["v", "sum", "s"]],
                            [["f", ">", 25.0]],
                        ).sort_values(["g", "h"])
                        assert got["s"].tolist() == exp_multi
            except Exception as exc:  # surfaced below with client id
                errors.append(f"client {ci}: {exc!r}")

        cts = [
            threading.Thread(target=client, args=(i,)) for i in range(4)
        ]
        for t in cts:
            t.start()
        for t in cts:
            t.join(timeout=120)
        assert not errors, errors
    finally:
        for n in nodes:
            n.running = False
        for t in threads:
            t.join(timeout=5)


def test_wide_fanout_64_shards_two_workers(tmp_path, mem_store_url):
    from tests.conftest import wait_until

    """Scale check on the fan-out machinery: 64 shards served by 2 workers
    through one query must batch into shard groups, keep the sink's
    bookkeeping straight, and produce the pandas answer — the widest
    shard count in the suite (the bench uses 10)."""
    import logging
    import os
    import threading
    import time

    import numpy as np
    import pandas as pd

    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.storage.ctable import ctable
    from bqueryd_tpu.worker import WorkerNode

    rng = np.random.default_rng(11)
    frames = []
    for i in range(64):
        df = pd.DataFrame(
            {
                "g": rng.integers(0, 9, 500).astype(np.int64),
                "v": rng.integers(-(2**45), 2**45, 500).astype(np.int64),
            }
        )
        frames.append(df)
        ctable.fromdataframe(df, str(tmp_path / f"w_{i:02d}.bcolzs"))
    names = [f"w_{i:02d}.bcolzs" for i in range(64)]

    url = mem_store_url
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.2,
    )
    workers = [
        WorkerNode(
            coordination_url=url,
            data_dir=str(tmp_path),
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.2,
            poll_timeout=0.05,
        )
        for _ in range(2)
    ]
    threads = [
        threading.Thread(target=n.go, daemon=True)
        for n in [controller] + workers
    ]
    for t in threads:
        t.start()
    try:
        wait_until(
            lambda: len(controller.files_map) >= 64,
            timeout=60,
            desc="64 shards registered",
        )
        rpc = RPC(
            coordination_url=url, timeout=120, loglevel=logging.WARNING
        )
        got = rpc.groupby(names, ["g"], [["v", "sum", "s"]], [])
        got = got.sort_values("g").reset_index(drop=True)
        expected = (
            pd.concat(frames).groupby("g")["v"].sum().reset_index(name="s")
        )
        assert got["g"].tolist() == expected["g"].tolist()
        assert got["s"].tolist() == expected["s"].tolist()
    finally:
        for n in [controller] + workers:
            n.stop()
        for t in threads:
            t.join(timeout=10)
