import os
import zipfile

from bqueryd_tpu.utils import (
    get_my_ip,
    mkdir_p,
    rm_file_or_dir,
    tree_checksum,
    zip_to_file,
)


def test_get_my_ip_returns_ipv4():
    ip = get_my_ip()
    parts = ip.split(".")
    assert len(parts) == 4
    assert all(0 <= int(p) <= 255 for p in parts)


def test_mkdir_p_idempotent(tmp_path):
    target = tmp_path / "a" / "b" / "c"
    mkdir_p(str(target))
    mkdir_p(str(target))
    assert target.is_dir()


def test_rm_file_or_dir(tmp_path):
    f = tmp_path / "f.txt"
    f.write_text("x")
    d = tmp_path / "d"
    (d / "sub").mkdir(parents=True)
    link = tmp_path / "lnk"
    os.symlink(str(d), str(link))

    rm_file_or_dir(str(link))
    assert not link.exists() and d.exists()
    rm_file_or_dir(str(f))
    rm_file_or_dir(str(d))
    rm_file_or_dir(str(tmp_path / "never-existed"))
    assert not f.exists() and not d.exists()


def test_zip_to_file_dir_roundtrip(tmp_path):
    src = tmp_path / "shard.bcolz"
    (src / "col").mkdir(parents=True)
    (src / "col" / "chunk0").write_bytes(b"\x01\x02\x03")
    (src / "meta.json").write_text("{}")

    dest = tmp_path / "out"
    dest.mkdir()
    zip_name, checksum = zip_to_file(str(src), str(dest))
    assert checksum.startswith("0x")
    with zipfile.ZipFile(zip_name) as zf:
        names = set(zf.namelist())
    assert names == {"col/chunk0", "meta.json"}


def test_tree_checksum_changes_with_structure(tmp_path):
    (tmp_path / "a").write_text("1")
    c1 = tree_checksum(str(tmp_path))
    (tmp_path / "b").write_text("2")
    c2 = tree_checksum(str(tmp_path))
    assert c1 != c2
    assert tree_checksum(str(tmp_path)) == c2


def test_trace_span_emits_profiler_annotation(monkeypatch):
    """trace_span is live under BQUERYD_TPU_PROFILE=1 (it wraps every
    executor phase via MeshQueryExecutor._phase) — exercise the enabled
    path so the jax.profiler.TraceAnnotation import/enter/exit runs."""
    from bqueryd_tpu.utils import tracing

    monkeypatch.setenv("BQUERYD_TPU_PROFILE", "1")
    with tracing.trace_span("unit-test-span"):
        pass


def test_executor_phase_wraps_timer_and_trace(monkeypatch):
    """MeshQueryExecutor._phase must enter BOTH the PhaseTimer phase and the
    profiler span (the round-3 verdict flagged trace_span as dead code)."""
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor
    from bqueryd_tpu.utils import tracing
    from bqueryd_tpu.utils.tracing import PhaseTimer

    seen = []
    import contextlib

    @contextlib.contextmanager
    def fake_span(name):
        seen.append(name)
        yield

    monkeypatch.setattr(tracing, "trace_span", fake_span)
    ex = MeshQueryExecutor(timer=PhaseTimer())
    with ex._phase("decode"):
        pass
    assert seen == ["decode"]
    assert "decode" in ex.timer.timings
