import json

import pytest

from bqueryd_tpu import messages


def test_factory_dispatch_all_types():
    for name, cls in messages.MSG_MAPPING.items():
        if name is None:
            continue
        msg = messages.msg_factory(json.dumps({"msg_type": name}))
        assert isinstance(msg, cls)
        assert msg["msg_type"] == name


def test_factory_accepts_bytes_dict_and_none():
    assert isinstance(messages.msg_factory(b'{"msg_type": "busy"}'), messages.BusyMessage)
    assert isinstance(messages.msg_factory({"msg_type": "done"}), messages.DoneMessage)
    assert type(messages.msg_factory(None)) is messages.Message
    assert type(messages.msg_factory({})) is messages.Message


def test_factory_unknown_type_degrades_to_base():
    msg = messages.msg_factory({"msg_type": "from-the-future"})
    assert type(msg) is messages.Message


def test_factory_strict_raises_on_garbage():
    with pytest.raises(messages.MalformedMessage):
        messages.msg_factory("not json {{{")
    assert type(messages.msg_factory("not json {{{", strict=False)) is messages.Message


def test_wire_roundtrip_preserves_params():
    msg = messages.RPCMessage({"payload": "groupby", "token": "abcd"})
    args = (["file.bcolz"], ["payment_type"], [["total_amount", "sum", "total_amount"]], [])
    kwargs = {"aggregate": True}
    msg.set_args_kwargs(args, kwargs)

    wire = msg.to_json()
    parsed = messages.msg_factory(wire)
    assert isinstance(parsed, messages.RPCMessage)
    got_args, got_kwargs = parsed.get_args_kwargs()
    assert list(got_args) == list(args)
    assert got_kwargs == kwargs
    assert parsed["token"] == "abcd"


def test_wire_format_shape():
    """The JSON envelope keeps the reference's field contract: msg_type,
    payload, version, created at top level; params is a base64 string."""
    msg = messages.CalcMessage({"payload": "groupby"})
    msg.set_args_kwargs([1], {})
    d = json.loads(msg.to_json())
    assert d["msg_type"] == "calc"
    assert d["payload"] == "groupby"
    assert d["version"] == 1
    assert isinstance(d["created"], float)
    assert isinstance(d["params"], str)  # base64 text, JSON-safe


def test_isa_matches_class_and_payload():
    msg = messages.RPCMessage({"payload": "info"})
    assert msg.isa(messages.RPCMessage)
    assert msg.isa("info")
    assert not msg.isa(messages.CalcMessage)
    assert not msg.isa("groupby")


def test_copy_preserves_class():
    msg = messages.CalcMessage({"payload": "groupby"})
    clone = msg.copy()
    assert isinstance(clone, messages.CalcMessage)
    clone["payload"] = "other"
    assert msg["payload"] == "groupby"


def test_binary_field_roundtrip():
    msg = messages.Message()
    payload = {"arr": [1, 2, 3], "nested": {"x": b"\x00\xff"}}
    msg.add_as_binary("data", payload)
    assert messages.msg_factory(msg.to_json()).get_from_binary("data") == payload
    assert msg.get_from_binary("absent", "fallback") == "fallback"
