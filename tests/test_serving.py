"""Semantic serving layer (PR 16): plan subsumption + materialized rollups.

Three layers of coverage:

* lattice — eligibility refusals, exact / key-fold / window-fold / zone-proof
  matching, transform application parity vs pandas, the calibrated source
  choice (tiny tables refuse on cost);
* manager — heat threshold decay, build/absorb lifecycle, append-epoch
  staleness (including an append racing a build), delta refresh, retention
  sweeps (count cap, byte cap, build timeout);
* worker + cluster — the ``rollup`` verb end to end (build, delta refresh,
  census), rollup/subsume answers through ``rpc.groupby`` with provenance
  on the result envelope, append invalidation (never serve stale), the
  ``BQUERYD_TPU_SERVE=0`` kill switch, mixed-version worker rejection, and
  the debug-bundle ``serving`` section + flight events.
"""

import logging
import os
import pickle
import threading

import numpy as np
import pandas as pd
import pytest

from conftest import wait_until

from bqueryd_tpu.models.query import GroupByQuery, QueryEngine, ResultPayload
from bqueryd_tpu.parallel import hostmerge
from bqueryd_tpu.serve import rollup as rollupmod
from bqueryd_tpu.serve import subsume
from bqueryd_tpu.storage.ctable import ctable


def _frame(n, seed=0, offset=0):
    rng = np.random.RandomState(seed)
    return pd.DataFrame(
        {
            "g": rng.randint(0, 5, n).astype(np.int64),
            "g2": rng.randint(0, 3, n).astype(np.int64),
            "v": rng.randint(-100, 100, n).astype(np.int64),
            "f": rng.random(n).astype(np.float32),
            "s": (rng.randint(0, 3, n)).astype(str),
            "seq": np.arange(offset, offset + n, dtype=np.int64),
        }
    )


def _finalize(payloads):
    return hostmerge.payload_to_dataframe(
        hostmerge.merge_payloads(list(payloads))
    )


def _sorted(df, keys):
    return df.sort_values(keys).reset_index(drop=True)


def _view(keys=("g",), aggs=(("v", "sum", "vs"),), where=(),
          filenames=("t.bcolzs",), dag_sig=None, aggregate=True, expand=None):
    return {
        "filenames": tuple(filenames),
        "keys": tuple(keys),
        "aggs": tuple(tuple(a) for a in aggs),
        "where": tuple(subsume._freeze_term(t) for t in where),
        "aggregate_rows": aggregate,
        "expand": expand,
        "dag_sig": dag_sig,
    }


def _census(**cols):
    """{col: (kind, zones)} -> one file's census dict."""
    return {
        name: {"kind": kind, "zones": zones, "nulls": kind != "int"}
        for name, (kind, zones) in cols.items()
    }


# ---------------------------------------------------------------------------
# lattice: eligibility
# ---------------------------------------------------------------------------

def test_plan_eligibility_refusals():
    ok, why = subsume.plan_eligible(_view())
    assert ok and why is None
    assert subsume.plan_eligible(_view(aggregate=False)) == (False, "raw-rows")
    assert subsume.plan_eligible(_view(expand="basket")) == (
        False, "expand-filter"
    )
    assert subsume.plan_eligible(
        _view(aggs=(("v", "count_distinct", "vd"),))
    ) == (False, "op:count_distinct")
    assert subsume.plan_eligible(
        _view(aggs=(("v", "top_k", "t"),))
    ) == (False, "op:top_k")
    # joins never serve; plain DAGs pass (exact-only), windowed DAGs pass
    sig = [0] * 8
    sig[subsume._DAG_JOIN_IDX] = ("j",)
    sig[subsume._DAG_WINDOW_IDX] = None
    assert subsume.plan_eligible(_view(dag_sig=tuple(sig))) == (False, "join")
    sig[subsume._DAG_JOIN_IDX] = None
    assert subsume.plan_eligible(_view(dag_sig=tuple(sig)))[0]


def test_plan_view_and_key_from_logical_plan(tmp_path):
    from bqueryd_tpu import plan as planmod

    plan = planmod.plan_groupby(
        ["t.bcolzs"], ["g"], [["v", "sum", "vs"]], [["seq", ">", 5]],
        aggregate=True, expand_filter_column=None,
    )
    view = subsume.plan_view(plan)
    assert view["keys"] == ("g",)
    assert view["where"] == (("seq", ">", 5),)
    key = subsume.view_key(view)
    assert key.startswith("rollup:g:")
    # deterministic, and sensitive to the filter
    assert key == subsume.view_key(subsume.plan_view(plan))
    plan2 = planmod.plan_groupby(
        ["t.bcolzs"], ["g"], [["v", "sum", "vs"]], [],
        aggregate=True, expand_filter_column=None,
    )
    assert subsume.view_key(subsume.plan_view(plan2)) != key


# ---------------------------------------------------------------------------
# lattice: matching
# ---------------------------------------------------------------------------

def test_match_exact_and_filename_refusal():
    v = _view()
    t, why = subsume.match(v, dict(v))
    assert t == {"kind": "exact"} and why is None
    t, why = subsume.match(v, _view(filenames=("other.bcolzs",)))
    assert t is None and why == "filenames"
    t, why = subsume.match(v, _view(aggregate=False))
    assert t is None and why == "shape"


def test_key_fold_match_and_null_refusal():
    cand = _view(keys=("g", "g2"))
    query = _view(keys=("g",))
    meta = {"t.bcolzs": _census(g2=("int", [(0, 2)]))}
    t, why = subsume.match(cand, query, meta)
    assert why is None and t == {"kind": "fold", "keys": ("g",)}
    # the dropped key column must be proven null-free: float/dict refuse
    for kind in ("float", "dict", "datetime"):
        bad = {"t.bcolzs": _census(g2=(kind, None))}
        t, why = subsume.match(cand, query, bad)
        assert t is None and why == "key-nullable:g2"
    # a query keyed outside the candidate refuses
    t, why = subsume.match(cand, _view(keys=("s",)), meta)
    assert t is None and why == "keys"


def test_agg_projection_and_missing_agg():
    cand = _view(aggs=(("v", "sum", "vs"), ("f", "mean", "fm")))
    query = _view(aggs=(("f", "mean", "fm"),))
    t, why = subsume.match(cand, query, {})
    assert why is None and t == {"kind": "fold", "aggs": (1,)}
    t, why = subsume.match(
        cand, _view(aggs=(("v", "max", "vx"),)), {}
    )
    assert t is None and why == "agg-missing:vx"


def test_zone_proof_filter_match_and_partial_refusal():
    cand = _view()
    meta = {"t.bcolzs": _census(seq=("int", [(0, 255), (256, 511)]))}
    # full-select proof on every chunk: serve the stored bytes verbatim
    t, why = subsume.match(cand, _view(where=((("seq", ">=", 0)),)), meta)
    assert why is None and t == {"kind": "zone"}
    # partial chunk overlap: chunk (0, 255) is not wholly selected
    t, why = subsume.match(cand, _view(where=(("seq", ">", 100),)), meta)
    assert t is None and why == "filter-partial:seq"
    # a float column can never prove full selection (NaNs skip zone maps)
    fmeta = {"t.bcolzs": _census(f=("float", [(0.0, 1.0)]))}
    t, why = subsume.match(cand, _view(where=(("f", ">=", 0.0),)), fmeta)
    assert t is None and why == "filter-partial:f"
    # candidate filtered more strictly than the query can never serve it
    t, why = subsume.match(_view(where=(("seq", ">", 5),)), _view(), meta)
    assert t is None and why == "filter-weaker"


def test_zone_full_select_table():
    cases = [
        ((5, 5), "==", 5, True), ((4, 5), "==", 5, False),
        ((4, 9), "!=", 10, True), ((4, 9), "!=", 5, False),
        ((6, 9), ">", 5, True), ((5, 9), ">", 5, False),
        ((5, 9), ">=", 5, True), ((4, 9), ">=", 5, False),
        ((1, 4), "<", 5, True), ((1, 5), "<", 5, False),
        ((1, 5), "<=", 5, True), ((1, 6), "<=", 5, False),
        ((3, 3), "in", [3, 7], True), ((3, 4), "in", [3, 4], False),
    ]
    for zone, op, value, want in cases:
        assert subsume.zone_full_select(zone[0], zone[1], op, value) is want, (
            zone, op, value
        )
    # incomparable values are a conservative refusal, not a crash
    assert subsume.zone_full_select(1, 5, ">", None) is False
    # a chunk with no zone map (all-null) refuses
    meta = _census(seq=("int", [(0, 9), None]))
    assert not subsume.term_full_selects(meta, ("seq", ">=", 0))


def test_window_fold_alignment_rules():
    def sig(every, origin=0, col="ts", alias="w"):
        s = ["node"] * 8
        s[subsume._DAG_JOIN_IDX] = None
        s[subsume._DAG_WINDOW_IDX] = (col, every, alias, origin)
        return tuple(s)

    minute, hour = 60_000_000_000, 3_600_000_000_000
    cand, query = _view(dag_sig=sig(minute)), _view(dag_sig=sig(hour))
    t, why = subsume.match(cand, query)
    assert why is None
    assert t == {"kind": "fold", "window": ("w", hour, 0)}
    # coarse grid not a multiple of the fine one
    t, why = subsume.match(cand, _view(dag_sig=sig(90_000_000_000)))
    assert t is None and why == "window-misaligned"
    # origins incongruent modulo the fine width
    t, why = subsume.match(cand, _view(dag_sig=sig(hour, origin=30)))
    assert t is None and why == "window-origin"
    # a different window column (or alias) never folds
    t, why = subsume.match(cand, _view(dag_sig=sig(hour, col="ts2")))
    assert t is None and why == "window-column"
    # any other DAG node difference refuses
    other = list(sig(hour))
    other[0] = "different"
    t, why = subsume.match(cand, _view(dag_sig=tuple(other)))
    assert t is None and why == "dag-shape"
    # the fine rollup can never be answered FROM the coarse one
    t, why = subsume.match(_view(dag_sig=sig(hour)), _view(dag_sig=sig(minute)))
    assert t is None and why == "window-misaligned"


# ---------------------------------------------------------------------------
# lattice: transform application parity
# ---------------------------------------------------------------------------

def _partials(tmp_path, df, keys, aggs, name="p.bcolzs"):
    t = ctable.fromdataframe(df, str(tmp_path / name), chunklen=256)
    query = GroupByQuery(list(keys), [list(a) for a in aggs], [],
                         aggregate=True)
    return dict(QueryEngine().execute_local(t, query))


def test_apply_transform_key_fold_parity(tmp_path):
    df = _frame(2000, seed=3)
    aggs = [["v", "sum", "vs"], ["f", "mean", "fm"], ["v", "min", "vmin"]]
    fine = _partials(tmp_path, df, ["g", "g2"], aggs)
    folded = subsume.apply_transform(
        fine, {"kind": "fold", "keys": ("g",)}
    )
    got = _sorted(_finalize([ResultPayload(folded)]), ["g"])
    expected = _sorted(
        df.groupby("g", as_index=False).agg(
            vs=("v", "sum"), fm=("f", "mean"), vmin=("v", "min")
        ),
        ["g"],
    )
    np.testing.assert_array_equal(got["g"], expected["g"])
    np.testing.assert_array_equal(got["vs"], expected["vs"])
    np.testing.assert_array_equal(got["vmin"], expected["vmin"])
    np.testing.assert_allclose(
        got["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
    )


def test_apply_transform_agg_projection_parity(tmp_path):
    df = _frame(1200, seed=4)
    fine = _partials(
        tmp_path, df, ["g"],
        [["v", "sum", "vs"], ["f", "mean", "fm"], ["v", "count", "n"]],
    )
    # project out the middle slot only (fm), no re-keying
    sliced = subsume.apply_transform(fine, {"kind": "fold", "aggs": (1,)})
    got = _sorted(_finalize([ResultPayload(sliced)]), ["g"])
    assert list(got.columns) == ["g", "fm"]
    expected = _sorted(
        df.groupby("g", as_index=False).agg(fm=("f", "mean")), ["g"]
    )
    np.testing.assert_allclose(
        got["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
    )


def test_apply_transform_window_refloor_parity(tmp_path):
    minute, hour = 60_000_000_000, 3_600_000_000_000
    n = 1500
    rng = np.random.RandomState(7)
    df = pd.DataFrame(
        {
            "b": (np.arange(n, dtype=np.int64) * minute // 7) // minute
            * minute,
            "v": rng.randint(-50, 50, n).astype(np.int64),
        }
    )
    fine = _partials(tmp_path, df, ["b"], [["v", "sum", "vs"]])
    # re-key the minute buckets onto the hour grid and collapse
    folded = subsume.apply_transform(
        fine, {"kind": "fold", "window": ("b", hour, 0)}
    )
    got = _sorted(_finalize([ResultPayload(folded)]), ["b"])
    expected = _sorted(
        df.assign(b=(df["b"] // hour) * hour)
        .groupby("b", as_index=False).agg(vs=("v", "sum")),
        ["b"],
    )
    np.testing.assert_array_equal(got["b"], expected["b"])
    np.testing.assert_array_equal(got["vs"], expected["vs"])


def test_apply_transform_window_preserves_datetime_dtype(tmp_path):
    minute, hour = 60_000_000_000, 3_600_000_000_000
    df = pd.DataFrame(
        {
            "b": np.arange(0, 360, 3, dtype=np.int64) * minute,
            "v": np.ones(120, dtype=np.int64),
        }
    )
    fine = _partials(tmp_path, df, ["b"], [["v", "sum", "vs"]])
    fine["keys"] = dict(fine["keys"])
    fine["keys"]["b"] = np.asarray(
        fine["keys"]["b"], dtype=np.int64
    ).view("datetime64[ns]")
    folded = subsume.apply_transform(
        fine, {"kind": "fold", "window": ("b", hour, 0)}
    )
    out = np.asarray(folded["keys"]["b"])
    assert out.dtype == np.dtype("datetime64[ns]")
    want = np.sort(
        pd.Series(
            df["b"].to_numpy().view("datetime64[ns]")
        ).dt.floor("h").unique()
    )
    np.testing.assert_array_equal(np.sort(out), want)


def test_collapse_partials_passthrough_and_exact():
    rows_payload = {"kind": "rows", "data": [1, 2]}
    assert hostmerge.collapse_partials(rows_payload) is rows_payload
    p = {"kind": "partials", "rows": []}
    assert hostmerge.collapse_partials(p) is p
    # exact / zone transforms never touch the payload
    marker = {"kind": "partials", "rows": [1]}
    assert subsume.apply_transform(marker, {"kind": "exact"}) is marker


# ---------------------------------------------------------------------------
# lattice: source choice (cost)
# ---------------------------------------------------------------------------

def test_choose_source_prefers_cheapest_and_refuses_tiny_tables():
    matches = [
        ("rollup:a", {"kind": "exact"}, 5_000),
        ("rollup:b", {"kind": "fold"}, 50),
    ]
    choice = subsume.choose_source(matches, total_rows=1_000_000)
    assert choice is not None and choice[0] == "rollup:b"
    # a table barely bigger than the partials: recompute wins
    assert subsume.choose_source(matches, total_rows=40) is None
    assert subsume.choose_source([], total_rows=1_000_000) is None


# ---------------------------------------------------------------------------
# manager lifecycle
# ---------------------------------------------------------------------------

def _manager_entry(mgr, key="k1", filenames=("a.bcolzs", "b.bcolzs"), now=0.0):
    view = _view(filenames=filenames)
    spec = {"args": [["g"], [["v", "sum", "vs"]], []], "dag_wire": None}
    for _ in range(3):
        mgr.note_query(key, view, spec, now)
    return mgr.start_build(key, now)


def test_heat_threshold_decays():
    mgr = rollupmod.RollupManager()
    view, spec = _view(), {"args": [[], [], []], "dag_wire": None}
    # three instantaneous hits cross the default threshold of 3.0 ...
    assert not mgr.note_query("k", view, spec, 0.0)
    assert not mgr.note_query("k", view, spec, 0.0)
    assert mgr.note_query("k", view, spec, 0.0)
    # ... but spaced hits decay below it (hl 300s: 3 hits over 600s ~= 2.2)
    mgr2 = rollupmod.RollupManager()
    assert not mgr2.note_query("k", view, spec, 0.0)
    assert not mgr2.note_query("k", view, spec, 300.0)
    assert not mgr2.note_query("k", view, spec, 600.0)


def test_entry_lifecycle_ready_stale_refresh():
    mgr = rollupmod.RollupManager()
    entry = _manager_entry(mgr)
    assert entry is not None and entry.state == "building"
    assert mgr.start_build("k1", 0.0) is None  # idempotent
    info = {"data": b"x" * 10, "payload": {}, "base": b"b", "zones": {},
            "groups": 4, "mode": "rebuild"}
    assert mgr.absorb("k1", "a.bcolzs", dict(info), 1.0) == "building"
    assert mgr.absorb("k1", "b.bcolzs", dict(info), 1.0) == "ready"
    assert [e.key for e in mgr.candidates(("a.bcolzs", "b.bcolzs"))] == ["k1"]
    # wrong filename set: no candidates
    assert mgr.candidates(("a.bcolzs",)) == []
    # an append on EITHER file stales the entry out synchronously
    assert mgr.note_append("b.bcolzs", 2.0) == ["k1"]
    assert entry.state == "stale" and mgr.candidates(
        ("a.bcolzs", "b.bcolzs")
    ) == []
    # delta refresh hands back the prior partials and re-arms the epochs
    res = mgr.begin_refresh("k1", 3.0)
    assert res is not None
    refreshed, prior = res
    assert refreshed.state == "building" and set(prior) == {
        "a.bcolzs", "b.bcolzs"
    }
    assert mgr.absorb("k1", "a.bcolzs", dict(info), 4.0) == "building"
    assert mgr.absorb("k1", "b.bcolzs", dict(info), 4.0) == "ready"
    assert [e.key for e in mgr.candidates(("a.bcolzs", "b.bcolzs"))] == ["k1"]


def test_append_racing_a_build_never_serves():
    mgr = rollupmod.RollupManager()
    _manager_entry(mgr)
    info = {"data": b"x", "payload": {}, "base": b"b", "zones": {},
            "groups": 1, "mode": "rebuild"}
    mgr.absorb("k1", "a.bcolzs", dict(info), 1.0)
    # the append dispatch lands between the two shard replies: the epoch
    # snapshot no longer matches, so completion flips to stale, not ready
    assert mgr.note_append("b.bcolzs", 1.5) == []  # building: not "flipped"
    assert mgr.absorb("k1", "b.bcolzs", dict(info), 2.0) == "stale"
    assert mgr.candidates(("a.bcolzs", "b.bcolzs")) == []


def test_sweep_caps_and_build_timeout(monkeypatch):
    monkeypatch.setenv("BQUERYD_TPU_ROLLUP_MAX", "1")
    mgr = rollupmod.RollupManager()
    info = {"data": b"x" * 100, "payload": {}, "base": b"b", "zones": {},
            "groups": 1, "mode": "rebuild"}
    for i, key in enumerate(("cold", "hot")):
        view = _view(filenames=(f"{key}.bcolzs",))
        spec = {"args": [[], [], []], "dag_wire": None}
        for _ in range(3):
            mgr.note_query(key, view, spec, float(i))
        mgr.start_build(key, float(i))
        mgr.absorb(key, f"{key}.bcolzs", dict(info), float(i))
    mgr.note_hit("hot", 10.0)
    dropped = mgr.sweep(11.0)
    assert dropped == [("cold", "count-cap")]
    assert set(mgr.entries) == {"hot"} and mgr.evictions == 1
    # byte cap evicts the same way
    monkeypatch.setenv("BQUERYD_TPU_ROLLUP_MAX", "16")
    monkeypatch.setenv("BQUERYD_TPU_ROLLUP_CACHE_BYTES", "10")
    assert mgr.sweep(12.0) == [("hot", "byte-cap")]
    # a wedged build is abandoned after the timeout
    monkeypatch.delenv("BQUERYD_TPU_ROLLUP_CACHE_BYTES")
    view = _view(filenames=("w.bcolzs",))
    spec = {"args": [[], [], []], "dag_wire": None}
    for _ in range(3):
        mgr.note_query("wedge", view, spec, 100.0)
    mgr.start_build("wedge", 100.0)
    assert mgr.sweep(100.0 + rollupmod.BUILD_TIMEOUT_S + 1) == [
        ("wedge", "build-timeout")
    ]


# ---------------------------------------------------------------------------
# worker: the rollup verb
# ---------------------------------------------------------------------------

def _worker_for(tmp_path, mem_store_url):
    from bqueryd_tpu.worker import WorkerNode

    return WorkerNode(
        coordination_url=mem_store_url,
        data_dir=str(tmp_path),
        loglevel=logging.WARNING,
        restart_check=False,
    )


def _rollup_msg(fname, keys=("g",), aggs=None, where=None,
                prior=None, base=None):
    from bqueryd_tpu.messages import CalcMessage

    msg = CalcMessage({"payload": "rollup", "token": "rollup_test"})
    msg.set_args_kwargs(
        [
            fname, list(keys),
            aggs or [["v", "sum", "vs"], ["f", "mean", "fm"]],
            where or [],
        ],
        {"aggregate": True},
    )
    if prior is not None:
        msg.add_as_binary("rollup_prior", prior)
        msg.add_as_binary("rollup_base", base)
    return msg


def test_worker_rollup_build_census_and_parity(tmp_path, mem_store_url):
    df = _frame(1500, seed=11)
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"), chunklen=256)
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        reply = worker.handle_work(_rollup_msg("t.bcolzs", keys=("g", "g2")))
        assert reply.get("rollup_mode") == "rebuild"
        payload = ResultPayload.from_bytes(reply["data"])
        assert payload["kind"] == "partials"
        got = _sorted(_finalize([payload]), ["g", "g2"])
        expected = _sorted(
            df.groupby(["g", "g2"], as_index=False).agg(
                vs=("v", "sum"), fm=("f", "mean")
            ),
            ["g", "g2"],
        )
        np.testing.assert_array_equal(got["vs"], expected["vs"])
        np.testing.assert_allclose(
            got["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
        )
        # the census carries exactly what the lattice proofs need
        zones = reply.get_from_binary("rollup_zones")
        assert zones["g"]["kind"] == "int" and not zones["g"]["nulls"]
        assert zones["f"]["kind"] == "float" and zones["f"]["nulls"]
        assert zones["s"]["kind"] == "dict" and zones["s"]["zones"] is None
        assert [z[0] for z in zones["seq"]["zones"]][:2] == [0, 256]
        assert reply.get("rollup_base")  # growth fingerprint for refreshes
    finally:
        worker.socket.close()


def test_worker_rollup_refresh_delta_and_fresh(tmp_path, mem_store_url):
    root = str(tmp_path / "t.bcolzs")
    df = _frame(1500, seed=12)
    ctable.fromdataframe(df, root, chunklen=256)
    worker = _worker_for(tmp_path, mem_store_url)
    try:
        first = worker.handle_work(_rollup_msg("t.bcolzs"))
        base = first.get_from_binary("rollup_base")
        # no growth: the prior partials round-trip untouched
        again = worker.handle_work(
            _rollup_msg("t.bcolzs", prior=first["data"], base=base)
        )
        assert again.get("rollup_mode") == "fresh"
        assert again["data"] == first["data"]
        # append, then refresh: only the tail is aggregated and hostmerged
        extra = _frame(300, seed=13, offset=1500)
        ctable(root, mode="a").append_dataframe(extra)
        refreshed = worker.handle_work(
            _rollup_msg("t.bcolzs", prior=first["data"], base=base)
        )
        assert refreshed.get("rollup_mode") == "delta"
        full = pd.concat([df, extra], ignore_index=True)
        got = _sorted(
            _finalize([ResultPayload.from_bytes(refreshed["data"])]), ["g"]
        )
        expected = _sorted(
            full.groupby("g", as_index=False).agg(
                vs=("v", "sum"), fm=("f", "mean")
            ),
            ["g"],
        )
        np.testing.assert_array_equal(got["vs"], expected["vs"])
        np.testing.assert_allclose(
            got["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
        )
        # a stale fingerprint (or rewrite) falls back to a full rebuild
        rebuilt = worker.handle_work(
            _rollup_msg("t.bcolzs", prior=first["data"], base=b"bogus")
        )
        assert rebuilt.get("rollup_mode") == "rebuild"
    finally:
        worker.socket.close()


# ---------------------------------------------------------------------------
# cluster: serving end to end
# ---------------------------------------------------------------------------

def _start(*nodes):
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    return threads


def _stop(nodes, threads):
    for node in nodes:
        node.running = False
    for t in threads:
        t.join(timeout=5)


@pytest.fixture
def serving_cluster(tmp_path, mem_store_url, monkeypatch):
    """Controller + one calc worker, serving enabled with the heat
    threshold lowered to 1 so the FIRST eligible query materializes
    (decay makes spaced repeat counts wall-clock dependent)."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC

    monkeypatch.setenv("BQUERYD_TPU_SERVE", "1")
    monkeypatch.setenv("BQUERYD_TPU_ROLLUP_HEAT_MIN", "1")
    df = _frame(3000, seed=21)
    ctable.fromdataframe(df, str(tmp_path / "t.bcolzs"), chunklen=256)
    controller = ControllerNode(
        coordination_url=mem_store_url,
        loglevel=logging.WARNING,
        runfile_dir=str(tmp_path),
        heartbeat_interval=0.1,
    )
    worker = _worker_for(tmp_path, mem_store_url)
    worker.heartbeat_interval = 0.1
    worker.poll_timeout = 0.05
    threads = _start(controller, worker)

    # the cost model refuses to serve without advertised stats; stats ride
    # the WRM one-shot with a 60s re-send window, so a first WRM that beats
    # the controller's socket would otherwise stall the fixture
    def _stats_known():
        if (controller.shard_stats.get("t.bcolzs") or {}).get("rows") == 3000:
            return True
        worker._stats_sent_ts = 0.0
        return False

    wait_until(_stats_known, desc="shard stats advertisement")
    rpc = RPC(
        coordination_url=mem_store_url, timeout=30, loglevel=logging.WARNING
    )
    yield {
        "rpc": rpc, "controller": controller, "worker": worker,
        "df": df, "tmp_path": tmp_path,
    }
    _stop([controller, worker], threads)


def _ready_keys(controller):
    return [
        e.key for e in controller.serving.manager.entries.values()
        if e.state == "ready"
    ]


Q = (
    ["t.bcolzs"], ["g"],
    [["v", "sum", "vs"], ["f", "mean", "fm"]], [],
)


def _expected(df, keys=("g",)):
    return _sorted(
        df.groupby(list(keys), as_index=False).agg(
            vs=("v", "sum"), fm=("f", "mean")
        ),
        list(keys),
    )


def _assert_parity(got, expected, keys=("g",)):
    got = _sorted(got, list(keys))
    np.testing.assert_array_equal(got["vs"], expected["vs"])
    np.testing.assert_allclose(
        got["fm"].to_numpy(), expected["fm"].to_numpy(), rtol=1e-6
    )


def test_rollup_materializes_and_serves(serving_cluster):
    rpc = serving_cluster["rpc"]
    controller = serving_cluster["controller"]
    df = serving_cluster["df"]
    r1 = rpc.groupby(*Q)
    assert rpc.last_call_answer_source in ("recompute", "cached")
    assert rpc.last_call_subsumed_from is None
    wait_until(lambda: _ready_keys(controller), desc="rollup materialization")
    r2 = rpc.groupby(*Q)
    assert rpc.last_call_answer_source == "rollup"
    assert rpc.last_call_subsumed_from in _ready_keys(controller)
    expected = _expected(df)
    _assert_parity(r1, expected)
    _assert_parity(r2, expected)
    assert controller.counters["rollup_builds"] >= 1
    assert controller.serving.served >= 1


def test_key_fold_and_zone_subsumption_end_to_end(serving_cluster):
    rpc = serving_cluster["rpc"]
    controller = serving_cluster["controller"]
    df = serving_cluster["df"]
    fine = (
        ["t.bcolzs"], ["g", "g2"],
        [["v", "sum", "vs"], ["f", "mean", "fm"]], [],
    )
    rpc.groupby(*fine)
    wait_until(lambda: _ready_keys(controller), desc="fine rollup")
    fine_key = _ready_keys(controller)[0]
    # the coarser groupby folds the finer rollup's partials (g2 is a
    # null-free int column, proven by the build census)
    r = rpc.groupby(*Q)
    assert rpc.last_call_answer_source == "subsume"
    assert rpc.last_call_subsumed_from == fine_key
    _assert_parity(r, _expected(df))
    # a filter the zone maps prove selects every chunk whole serves the
    # stored bytes verbatim
    rz = rpc.groupby(
        ["t.bcolzs"], ["g", "g2"],
        [["v", "sum", "vs"], ["f", "mean", "fm"]], [["seq", ">=", 0]],
    )
    assert rpc.last_call_answer_source == "rollup"
    _assert_parity(rz, _expected(df, keys=("g", "g2")), keys=("g", "g2"))
    # a partial-chunk filter overlap is NEVER subsumed: recompute, exact
    rp = rpc.groupby(
        ["t.bcolzs"], ["g", "g2"],
        [["v", "sum", "vs"], ["f", "mean", "fm"]], [["seq", ">", 1000]],
    )
    assert rpc.last_call_answer_source in ("recompute", "cached")
    _assert_parity(
        rp, _expected(df[df["seq"] > 1000], keys=("g", "g2")),
        keys=("g", "g2"),
    )
    decisions = list(controller.serving.decisions)
    assert any(
        r2[1].startswith("filter-partial")
        for d in decisions for r2 in d["rejected"]
    )


def test_append_invalidates_then_delta_refreshes(serving_cluster):
    rpc = serving_cluster["rpc"]
    controller = serving_cluster["controller"]
    df = serving_cluster["df"]
    rpc.groupby(*Q)
    wait_until(lambda: _ready_keys(controller), desc="rollup materialization")
    extra = _frame(240, seed=22, offset=3000)
    res = rpc.append("t.bcolzs", extra)
    assert res["appended"] == 240
    # the entry staled out the moment the append was dispatched: the
    # repeat query recomputes against the grown table, never serves stale
    full = pd.concat([df, extra], ignore_index=True)
    r = rpc.groupby(*Q)
    assert rpc.last_call_answer_source in ("recompute", "cached", "delta")
    _assert_parity(r, _expected(full))
    # the heartbeat sweep delta-refreshes the entry back to ready
    wait_until(
        lambda: _ready_keys(controller)
        and controller.counters["rollup_refreshes"] >= 1,
        desc="delta refresh",
    )
    entry = controller.serving.manager.entries[_ready_keys(controller)[0]]
    assert entry.per_file["t.bcolzs"]["mode"] == "delta"
    # stats must re-advertise before the cost model will serve again
    wait_until(
        lambda: (controller.shard_stats.get("t.bcolzs") or {}).get("rows")
        == 3240,
        desc="post-append stats re-advertisement",
    )
    r2 = rpc.groupby(*Q)
    assert rpc.last_call_answer_source == "rollup"
    _assert_parity(r2, _expected(full))


def test_kill_switch_restores_dispatch_path(serving_cluster, monkeypatch):
    rpc = serving_cluster["rpc"]
    controller = serving_cluster["controller"]
    df = serving_cluster["df"]
    rpc.groupby(*Q)
    wait_until(lambda: _ready_keys(controller), desc="rollup materialization")
    monkeypatch.setenv("BQUERYD_TPU_SERVE", "0")
    r = rpc.groupby(*Q)
    assert rpc.last_call_answer_source in ("recompute", "cached")
    _assert_parity(r, _expected(df))
    assert controller.serving.snapshot()["enabled"] is False
    # flipping it back re-enables serving from the still-ready entry
    monkeypatch.setenv("BQUERYD_TPU_SERVE", "1")
    r2 = rpc.groupby(*Q)
    assert rpc.last_call_answer_source == "rollup"
    _assert_parity(r2, _expected(df))


def test_mixed_version_worker_degrades_to_recompute(
    serving_cluster, monkeypatch
):
    """A pre-PR-16 worker rejects the rollup verb with its base
    unhandled-payload error: the entry is dropped and serving stays on
    the (always correct) recompute path."""
    worker = serving_cluster["worker"]
    rpc = serving_cluster["rpc"]
    controller = serving_cluster["controller"]
    df = serving_cluster["df"]

    def _old_worker(msg):
        raise ValueError(f"unhandled message payload: {msg.get('payload')}")

    monkeypatch.setattr(worker, "_rollup_build", _old_worker)
    r = rpc.groupby(*Q)
    _assert_parity(r, _expected(df))
    wait_until(
        lambda: any(
            e.get("kind") == "rollup_build_failed"
            and "UnsupportedVerb" in str(e.get("reason"))
            for e in controller.flight.events()
        ),
        desc="rollup build rejection",
    )
    assert controller.serving.manager.entries == {}
    r2 = rpc.groupby(*Q)
    assert rpc.last_call_answer_source in ("recompute", "cached")
    _assert_parity(r2, _expected(df))


def test_debug_bundle_serving_section_and_flight_events(serving_cluster):
    rpc = serving_cluster["rpc"]
    controller = serving_cluster["controller"]
    rpc.groupby(*Q)
    wait_until(lambda: _ready_keys(controller), desc="rollup materialization")
    rpc.groupby(*Q)
    assert rpc.last_call_answer_source == "rollup"
    bundle = rpc.debug_bundle()
    assert bundle["schema"] == "bqueryd_tpu.debug_bundle/4"
    serving = bundle["controller"]["serving"]
    assert serving["enabled"] is True and serving["served"] >= 1
    states = {e["state"] for e in serving["rollups"]["entries"]}
    assert "ready" in states
    assert any(
        d["source"] == "rollup" for d in serving["recent_decisions"]
    )
    kinds = {e["kind"] for e in controller.flight.events()}
    assert {"rollup_dispatch", "rollup_materialized", "serve_decision"} \
        <= kinds
    # provenance counter carries the per-source labels
    metrics = controller.metrics.render()
    assert 'bqueryd_tpu_serve_answers_total{source="rollup"}' in metrics
