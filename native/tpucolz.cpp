// tpucolz — native codec + column decoder for the bqueryd_tpu storage engine.
//
// TPU-native replacement for the role Blosc/bcolz play in the reference
// (external C deps used at reference bqueryd/worker.py:291,319-322): chunked,
// compressed column storage feeding host buffers that are then transferred to
// TPU HBM.  Implements, from scratch:
//
//   * byte-shuffle filter (transpose bytes of fixed-width elements, the same
//     trick Blosc uses to make typed arrays compressible),
//   * an LZ4-block-format compressor/decompressor (format-compatible with the
//     public LZ4 block spec so third-party tooling can read chunks),
//   * a zlib codec path (system zlib) as an alternative codec id,
//   * a multithreaded whole-column decoder (decode all chunks of a column in
//     parallel into one contiguous destination buffer — the hot data-loading
//     path that hides decode latency behind host->device transfers),
//   * an int64 hash factorizer for host-side group-key dictionary building.
//
// Exposed as a plain C API consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <limits>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

// ---------------------------------------------------------------------------
// byte shuffle
// ---------------------------------------------------------------------------

void shuffle_bytes(const uint8_t* src, size_t n, size_t elem, uint8_t* dst) {
  if (elem <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  const size_t nelems = n / elem;
  const size_t tail = n - nelems * elem;
  for (size_t j = 0; j < elem; ++j) {
    const uint8_t* s = src + j;
    uint8_t* d = dst + j * nelems;
    for (size_t k = 0; k < nelems; ++k) {
      d[k] = s[k * elem];
    }
  }
  if (tail) std::memcpy(dst + nelems * elem, src + nelems * elem, tail);
}

void unshuffle_bytes(const uint8_t* src, size_t n, size_t elem, uint8_t* dst) {
  if (elem <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  const size_t nelems = n / elem;
  const size_t tail = n - nelems * elem;
  for (size_t j = 0; j < elem; ++j) {
    const uint8_t* s = src + j * nelems;
    uint8_t* d = dst + j;
    for (size_t k = 0; k < nelems; ++k) {
      d[k * elem] = s[k];
    }
  }
  if (tail) std::memcpy(dst + nelems * elem, src + nelems * elem, tail);
}

// ---------------------------------------------------------------------------
// bit shuffle (c-blosc BITSHUFFLE filter inverse)
// ---------------------------------------------------------------------------
// The shuffled image stores, for each byte position jj of the element and
// each bit kk (LSB first), a plane of nelems/8 bytes; plane byte m, bit i
// is bit kk of byte jj of element 8m+i.  Elements are truncated to a
// multiple of 8 and trailing bytes copied through unshuffled, mirroring
// c-blosc shuffle.c bitshuffle()/bitunshuffle().  Layout pinned against a
// direct port of the bitshuffle library's scalar reference pipeline in
// tests/test_bcolz_v1.py.

// 8x8 bit-matrix transpose (Hacker's Delight transpose8; the same routine
// the bitshuffle library uses as TRANS_BIT_8X8): input byte kk bit i moves
// to output byte i bit kk.
inline uint64_t trans_bit_8x8(uint64_t x) {
  uint64_t t;
  t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAULL;
  x = x ^ t ^ (t << 7);
  t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCULL;
  x = x ^ t ^ (t << 14);
  t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ULL;
  x = x ^ t ^ (t << 28);
  return x;
}

void bitunshuffle_bytes(const uint8_t* src, size_t n, size_t elem,
                        uint8_t* dst) {
  if (elem == 0) {
    std::memcpy(dst, src, n);
    return;
  }
  size_t nelems = (n / elem) & ~static_cast<size_t>(7);
  const size_t cut = nelems * elem;
  if (nelems) {
    const size_t nbr = nelems / 8;  // bytes per bit-plane
    for (size_t jj = 0; jj < elem; ++jj) {
      const uint8_t* planes = src + jj * 8 * nbr;
      for (size_t m = 0; m < nbr; ++m) {
        uint64_t x = 0;
        for (size_t kk = 0; kk < 8; ++kk) {
          x |= static_cast<uint64_t>(planes[kk * nbr + m]) << (8 * kk);
        }
        x = trans_bit_8x8(x);
        for (size_t i = 0; i < 8; ++i) {
          dst[(8 * m + i) * elem + jj] =
              static_cast<uint8_t>((x >> (8 * i)) & 0xFF);
        }
      }
    }
  }
  if (cut < n) std::memcpy(dst + cut, src + cut, n - cut);
}

// ---------------------------------------------------------------------------
// LZ4 block format (https-spec compatible), greedy hash-table compressor
// ---------------------------------------------------------------------------

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t lz4_hash(uint32_t v) { return (v * 2654435761u) >> 18; }  // 14-bit

constexpr size_t kHashSize = 1u << 14;
constexpr size_t kMinMatch = 4;
constexpr size_t kLastLiterals = 5;   // spec: last 5 bytes are literals
constexpr size_t kMfLimit = 12;       // spec: no match within last 12 bytes

size_t lz4_compress(const uint8_t* src, size_t n, uint8_t* dst, size_t cap) {
  std::vector<int64_t> table(kHashSize, -1);
  size_t op = 0, anchor = 0, pos = 0;

  auto emit = [&](size_t lit_len, const uint8_t* lits, size_t match_len,
                  size_t offset) -> bool {
    // token + extended literal lengths + literals + offset + extended matchlen
    size_t need = 1 + lit_len / 255 + 1 + lit_len + 2 + match_len / 255 + 1;
    if (op + need > cap) return false;
    uint8_t* token = dst + op++;
    // literal length
    if (lit_len >= 15) {
      *token = 15 << 4;
      size_t rest = lit_len - 15;
      while (rest >= 255) {
        dst[op++] = 255;
        rest -= 255;
      }
      dst[op++] = static_cast<uint8_t>(rest);
    } else {
      *token = static_cast<uint8_t>(lit_len << 4);
    }
    std::memcpy(dst + op, lits, lit_len);
    op += lit_len;
    if (offset == 0) return true;  // final literals-only sequence
    dst[op++] = static_cast<uint8_t>(offset & 0xff);
    dst[op++] = static_cast<uint8_t>(offset >> 8);
    size_t mlcode = match_len - kMinMatch;
    if (mlcode >= 15) {
      *token |= 15;
      size_t rest = mlcode - 15;
      while (rest >= 255) {
        dst[op++] = 255;
        rest -= 255;
      }
      dst[op++] = static_cast<uint8_t>(rest);
    } else {
      *token |= static_cast<uint8_t>(mlcode);
    }
    return true;
  };

  if (n >= kMfLimit) {
    const size_t match_limit = n - kLastLiterals;
    while (pos + kMfLimit <= n) {
      uint32_t h = lz4_hash(read32(src + pos));
      int64_t cand = table[h];
      table[h] = static_cast<int64_t>(pos);
      if (cand >= 0 && pos - static_cast<size_t>(cand) <= 65535 &&
          read32(src + cand) == read32(src + pos)) {
        size_t ml = kMinMatch;
        while (pos + ml < match_limit && src[cand + ml] == src[pos + ml]) ++ml;
        // Short matches barely compress but cost a whole sequence to decode;
        // keeping them as literals makes near-incompressible byte planes
        // decode at memcpy speed.
        if (ml < 8) {
          ++pos;
          continue;
        }
        if (!emit(pos - anchor, src + anchor, ml, pos - cand)) return 0;
        pos += ml;
        anchor = pos;
      } else {
        ++pos;
      }
    }
  }
  // final literals
  if (!emit(n - anchor, src + anchor, 0, 0)) return 0;
  return op;
}

// Returns bytes written to dst (== expected usize) or 0 on malformed input.
size_t lz4_decompress(const uint8_t* src, size_t csize, uint8_t* dst,
                      size_t usize) {
  size_t ip = 0, op = 0;
  while (ip < csize) {
    uint8_t token = src[ip++];
    size_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= csize) return 0;
        b = src[ip++];
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > csize || op + lit_len > usize) return 0;
    std::memcpy(dst + op, src + ip, lit_len);
    ip += lit_len;
    op += lit_len;
    if (ip >= csize) break;  // last sequence has no match part
    if (ip + 2 > csize) return 0;
    size_t offset = src[ip] | (static_cast<size_t>(src[ip + 1]) << 8);
    ip += 2;
    if (offset == 0 || offset > op) return 0;
    size_t ml = (token & 15);
    if (ml == 15) {
      uint8_t b;
      do {
        if (ip >= csize) return 0;
        b = src[ip++];
        ml += b;
      } while (b == 255);
    }
    ml += kMinMatch;
    if (op + ml > usize) return 0;
    const uint8_t* match = dst + op - offset;
    if (offset >= ml) {
      std::memcpy(dst + op, match, ml);
    } else {
      // Overlapping match = periodic pattern of period `offset`.  Seed one
      // period then double the copied region; O(log(ml/offset)) memcpys
      // instead of byte-at-a-time (hot for RLE-like shuffled columns).
      uint8_t* d = dst + op;
      size_t done = offset;  // offset < ml here
      std::memcpy(d, match, offset);
      while (done < ml) {
        size_t chunk = std::min(done, ml - done);
        std::memcpy(d + done, d, chunk);
        done += chunk;
      }
    }
    op += ml;
  }
  return op == usize ? op : 0;
}

// ---------------------------------------------------------------------------
// BloscLZ decompressor (FastLZ-derived format used by c-blosc v1, the codec
// behind legacy bcolz data).  Implemented from the public on-wire format:
//
//   stream := first_ctrl instr*
//   first byte is masked with 31 (streams open with a literal run)
//   literal run  (ctrl < 32):  copy (ctrl+1) bytes from input
//   match        (ctrl >= 32): len = (ctrl>>5)-1, extended while bytes == 255
//                              when the 3-bit field is 7; ofs = (ctrl&31)<<8
//                              plus one code byte; code==255 with ofs==31<<8
//                              switches to a 16-bit far distance (+8191);
//                              copy len+3 bytes from op-ofs-code-1 (RLE run of
//                              the previous byte when ofs==code==0)
//   every instruction is followed by the next ctrl byte (if input remains)
// ---------------------------------------------------------------------------

constexpr size_t kBloscLZMaxDistance = 8191;

// Returns bytes written (== usize expected by the chunk header) or 0 on
// malformed/overflowing input.
size_t blosclz_decompress(const uint8_t* src, size_t csize, uint8_t* dst,
                          size_t dst_cap) {
  if (csize == 0) return 0;
  size_t ip = 0, op = 0;
  uint32_t ctrl = src[ip++] & 31u;
  for (;;) {
    if (ctrl >= 32) {
      size_t len = (ctrl >> 5) - 1;
      size_t ofs = (ctrl & 31u) << 8;
      if (len == 7 - 1) {  // 3-bit length field saturated: extend
        uint8_t code;
        do {
          if (ip >= csize) return 0;
          code = src[ip++];
          len += code;
        } while (code == 255);
      }
      if (ip >= csize) return 0;
      uint8_t code = src[ip++];
      size_t ref;  // index of first source byte, AFTER the implicit -1
      if (code == 255 && ofs == (31u << 8)) {
        if (ip + 2 > csize) return 0;
        ofs = (static_cast<size_t>(src[ip]) << 8) + src[ip + 1];
        ip += 2;
        if (op < ofs + kBloscLZMaxDistance + 1) return 0;
        ref = op - ofs - kBloscLZMaxDistance - 1;
      } else {
        if (op < ofs + code + 1) return 0;
        ref = op - ofs - code - 1;
      }
      len += 3;
      if (op + len > dst_cap) return 0;
      if (ref + 1 == op) {
        // RLE: run of the previous byte
        std::memset(dst + op, dst[op - 1], len);
      } else {
        // may overlap forward: byte-wise copy is the defined semantics
        for (size_t k = 0; k < len; ++k) dst[op + k] = dst[ref + k];
      }
      op += len;
    } else {
      size_t run = ctrl + 1;
      if (ip + run > csize || op + run > dst_cap) return 0;
      std::memcpy(dst + op, src + ip, run);
      ip += run;
      op += run;
    }
    if (ip >= csize) break;
    ctrl = src[ip++];
  }
  return op;
}

// ---------------------------------------------------------------------------
// Blosc v1 chunk container (the on-disk format of bcolz ".blp" chunk files).
// Public header layout (16 bytes, little-endian):
//   0 version | 1 versionlz | 2 flags | 3 typesize
//   4-7 nbytes | 8-11 blocksize | 12-15 cbytes
// flags: bit0 byte-shuffle, bit1 memcpyed, bit2 bit-shuffle, bit4 dont-split,
//        bits5-7 codec (0 blosclz, 1 lz4/lz4hc, 3 zlib)
// Non-memcpyed chunks: int32 bstarts[nblocks] table follows the header; each
// block holds nsplits sub-streams, each preceded by its int32 csize (a csize
// equal to the uncompressed split size means "stored raw").  Blocks shuffle
// independently; nsplits == typesize for full blocks of splittable codecs
// (mirrors c-blosc's split_block()), else 1.  Because split policy varied
// across c-blosc releases (split-mode was a compressor-side option), each
// block is decoded by trying the inferred split count first and the
// alternative on failure — the int32-prefixed split framing makes a wrong
// guess fail loudly, never decode garbage.
// ---------------------------------------------------------------------------

inline int32_t read_i32le(const uint8_t* p) {
  uint32_t v = static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
               (static_cast<uint32_t>(p[2]) << 16) |
               (static_cast<uint32_t>(p[3]) << 24);
  int32_t out;
  std::memcpy(&out, &v, 4);
  return out;
}

enum BloscFlags : uint8_t {
  kBloscShuffle = 0x1,
  kBloscMemcpyed = 0x2,
  kBloscBitShuffle = 0x4,
};

enum BloscCodec : int32_t {
  kBloscLZCodec = 0,
  kBloscLZ4Codec = 1,
  kBloscZlibCodec = 3,
};

struct BloscHeader {
  uint8_t flags = 0;
  int32_t typesize = 0;
  int32_t nbytes = 0;
  int32_t blocksize = 0;
  int32_t cbytes = 0;
};

bool parse_blosc_header(const uint8_t* src, size_t csize, BloscHeader* h) {
  if (csize < 16) return false;
  h->flags = src[2];
  h->typesize = src[3];
  h->nbytes = read_i32le(src + 4);
  h->blocksize = read_i32le(src + 8);
  h->cbytes = read_i32le(src + 12);
  if (h->nbytes < 0 || h->blocksize <= 0 || h->cbytes < 16 ||
      static_cast<size_t>(h->cbytes) > csize)
    return false;
  return true;
}

bool blosc_split_eligible(int32_t codec, size_t typesize, size_t bsize,
                          bool leftover) {
  if (leftover) return false;
  if (codec != kBloscLZCodec && codec != kBloscLZ4Codec) return false;
  return typesize > 1 && typesize <= 16 && bsize / typesize >= 128 &&
         bsize % typesize == 0;
}

// Decode one block's split streams into block_dst.  Returns true when every
// split's framing and codec stream are consistent.
bool blosc_decode_block(const uint8_t* bp, size_t remain, size_t bsize,
                        size_t nsplits, int32_t codec, uint8_t* block_dst) {
  if (nsplits == 0 || bsize % nsplits != 0) return false;
  const size_t neblock = bsize / nsplits;
  for (size_t s = 0; s < nsplits; ++s) {
    if (remain < 4) return false;
    int32_t sc = read_i32le(bp);
    bp += 4;
    remain -= 4;
    if (sc <= 0 || static_cast<size_t>(sc) > remain) return false;
    const size_t scsize = static_cast<size_t>(sc);
    uint8_t* sdst = block_dst + s * neblock;
    if (scsize == neblock) {
      std::memcpy(sdst, bp, neblock);  // stored raw
    } else {
      switch (codec) {
        case kBloscLZCodec:
          if (blosclz_decompress(bp, scsize, sdst, neblock) != neblock)
            return false;
          break;
        case kBloscLZ4Codec:
          if (lz4_decompress(bp, scsize, sdst, neblock) != neblock)
            return false;
          break;
        case kBloscZlibCodec: {
          uLongf out_len = static_cast<uLongf>(neblock);
          if (uncompress(sdst, &out_len, bp, static_cast<uLong>(scsize)) !=
                  Z_OK ||
              out_len != neblock)
            return false;
          break;
        }
        default:
          return false;
      }
    }
    bp += scsize;
    remain -= scsize;
  }
  return true;
}

// Decode one Blosc v1 chunk into dst (dst_cap >= header nbytes).  Returns
// decoded byte count, or 0 on malformed input / unsupported codec.
size_t blosc_chunk_decode(const uint8_t* src, size_t csize, uint8_t* dst,
                          size_t dst_cap) {
  BloscHeader h;
  if (!parse_blosc_header(src, csize, &h)) return 0;
  const size_t nbytes = static_cast<size_t>(h.nbytes);
  if (nbytes == 0) return 0;
  if (dst_cap < nbytes) return 0;
  if (h.flags & kBloscMemcpyed) {
    if (csize < 16 + nbytes) return 0;
    std::memcpy(dst, src + 16, nbytes);
    return nbytes;
  }
  const int32_t codec = (h.flags >> 5) & 0x7;
  const size_t blocksize = static_cast<size_t>(h.blocksize);
  const size_t nblocks = (nbytes + blocksize - 1) / blocksize;
  if (csize < 16 + 4 * nblocks) return 0;
  const uint8_t* bstarts = src + 16;
  const size_t typesize = static_cast<size_t>(h.typesize);
  std::vector<uint8_t> tmp(blocksize);

  for (size_t b = 0; b < nblocks; ++b) {
    const size_t bsize =
        (b == nblocks - 1) ? nbytes - b * blocksize : blocksize;
    const bool leftover = bsize != blocksize;
    int32_t start = read_i32le(bstarts + 4 * b);
    if (start < 0 || static_cast<size_t>(start) > csize) return 0;
    const uint8_t* bp = src + start;
    size_t remain = csize - static_cast<size_t>(start);
    // filter precedence mirrors c-blosc's blosc_d: byte-shuffle wins, else
    // bit-shuffle (which applies at any typesize — bit-planes are its point
    // for boolean data)
    const bool shuffled = (h.flags & kBloscShuffle) && typesize > 1;
    const bool bitshuffled = !shuffled && (h.flags & kBloscBitShuffle);
    uint8_t* block_dst =
        (shuffled || bitshuffled) ? tmp.data() : dst + b * blocksize;

    size_t primary =
        blosc_split_eligible(codec, typesize, bsize, leftover) ? typesize : 1;
    size_t fallback = primary == 1 ? typesize : 1;
    if (!blosc_decode_block(bp, remain, bsize, primary, codec, block_dst) &&
        (fallback == primary || fallback == 0 ||
         !blosc_decode_block(bp, remain, bsize, fallback, codec, block_dst)))
      return 0;
    if (shuffled) {
      unshuffle_bytes(tmp.data(), bsize, typesize, dst + b * blocksize);
    } else if (bitshuffled) {
      bitunshuffle_bytes(tmp.data(), bsize, typesize, dst + b * blocksize);
    }
  }
  return nbytes;
}

}  // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

// codec ids (stable, part of the on-disk format)
enum TpcCodec : int32_t {
  TPC_RAW = 0,
  TPC_LZ4 = 1,
  TPC_ZLIB = 2,
};

size_t tpc_max_csize(size_t usize) { return usize + usize / 128 + 64; }

// Shuffle (if elem_size > 1) then compress with `codec`.  Returns compressed
// size, or 0 on failure/incompressible-with-cap.
size_t tpc_encode(const uint8_t* src, size_t usize, size_t elem_size,
                  int32_t codec, uint8_t* dst, size_t dst_cap) {
  if (usize == 0) return 0;
  std::vector<uint8_t> shuffled;
  const uint8_t* payload = src;
  if (elem_size > 1) {
    shuffled.resize(usize);
    shuffle_bytes(src, usize, elem_size, shuffled.data());
    payload = shuffled.data();
  }
  switch (codec) {
    case TPC_RAW:
      if (dst_cap < usize) return 0;
      std::memcpy(dst, payload, usize);
      return usize;
    case TPC_LZ4:
      return lz4_compress(payload, usize, dst, dst_cap);
    case TPC_ZLIB: {
      uLongf out_len = static_cast<uLongf>(dst_cap);
      if (compress2(dst, &out_len, payload, static_cast<uLong>(usize), 1) != Z_OK)
        return 0;
      return static_cast<size_t>(out_len);
    }
    default:
      return 0;
  }
}

// Decompress and (if elem_size > 1) unshuffle.  Returns usize on success.
size_t tpc_decode(const uint8_t* src, size_t csize, size_t usize,
                  size_t elem_size, int32_t codec, uint8_t* dst) {
  if (usize == 0) return 0;
  std::vector<uint8_t> tmp;
  uint8_t* payload = dst;
  if (elem_size > 1) {
    tmp.resize(usize);
    payload = tmp.data();
  }
  switch (codec) {
    case TPC_RAW:
      if (csize != usize) return 0;
      std::memcpy(payload, src, usize);
      break;
    case TPC_LZ4:
      if (lz4_decompress(src, csize, payload, usize) != usize) return 0;
      break;
    case TPC_ZLIB: {
      uLongf out_len = static_cast<uLongf>(usize);
      if (uncompress(payload, &out_len, src, static_cast<uLong>(csize)) != Z_OK ||
          out_len != usize)
        return 0;
      break;
    }
    default:
      return 0;
  }
  if (elem_size > 1) unshuffle_bytes(payload, usize, elem_size, dst);
  return usize;
}

// Decode a whole column: `file_buf` holds nchunks chunks back to back; chunk i
// spans [offsets[i], offsets[i+1]) and its decoded payload is `usizes[i]`
// bytes, written at dst + sum(usizes[:i]).  Chunks decode in parallel on up to
// `nthreads` threads (the knob mirroring the reference's Blosc nthreads
// setting, reference bqueryd/worker.py:40).  Returns 1 on success, 0 if any
// chunk fails.
int32_t tpc_decode_column(const uint8_t* file_buf, const uint64_t* offsets,
                          const uint64_t* usizes, size_t nchunks,
                          size_t elem_size, int32_t codec, uint8_t* dst,
                          int32_t nthreads) {
  if (nchunks == 0) return 1;
  std::vector<uint64_t> dst_offsets(nchunks + 1, 0);
  for (size_t i = 0; i < nchunks; ++i)
    dst_offsets[i + 1] = dst_offsets[i] + usizes[i];

  int32_t hw = static_cast<int32_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  if (nthreads <= 0) nthreads = hw;  // 0 = auto
  int32_t workers = std::max(1, std::min({nthreads, hw, static_cast<int32_t>(nchunks)}));

  std::atomic<size_t> next{0};
  std::atomic<int32_t> ok{1};
  auto run = [&]() {
    while (true) {
      size_t i = next.fetch_add(1);
      if (i >= nchunks || !ok.load(std::memory_order_relaxed)) break;
      size_t csize = offsets[i + 1] - offsets[i];
      if (tpc_decode(file_buf + offsets[i], csize, usizes[i], elem_size, codec,
                     dst + dst_offsets[i]) != usizes[i]) {
        ok.store(0, std::memory_order_relaxed);
      }
    }
  };
  if (workers == 1) {
    run();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(workers);
    for (int32_t t = 0; t < workers; ++t) threads.emplace_back(run);
    for (auto& t : threads) t.join();
  }
  return ok.load();
}

// Peek a Blosc v1 chunk header (legacy bcolz ".blp" files): fills
// uncompressed size, typesize and flags.  Returns 1 if the header parses.
int32_t tpc_blosc_info(const uint8_t* src, size_t csize, int64_t* nbytes,
                       int32_t* typesize, int32_t* flags) {
  BloscHeader h;
  if (!parse_blosc_header(src, csize, &h)) return 0;
  if (nbytes) *nbytes = h.nbytes;
  if (typesize) *typesize = h.typesize;
  if (flags) *flags = h.flags;
  return 1;
}

// Decode a Blosc v1 chunk (bcolz migration path).  Returns decoded bytes
// (== header nbytes) or 0 on malformed/unsupported input.
size_t tpc_blosc_decode(const uint8_t* src, size_t csize, uint8_t* dst,
                        size_t dst_cap) {
  return blosc_chunk_decode(src, csize, dst, dst_cap);
}

// Hash-factorize an int64 array: codes[i] = dense id of src[i] in first-seen
// order; uniques gets the dictionary.  Returns number of uniques, or -1 if it
// would exceed uniques_cap.  Host-side equivalent of bquery's factorization
// (the cached factorize used at reference bqueryd/worker.py:291).
int64_t tpc_factorize_i64(const int64_t* src, size_t n, int32_t* codes,
                          int64_t* uniques, size_t uniques_cap) {
  // open-addressing hash map: key -> code
  size_t cap = 16;
  while (cap < n * 2) cap <<= 1;
  std::vector<int64_t> keys(cap);
  std::vector<int32_t> vals(cap, -1);
  std::vector<uint8_t> used(cap, 0);
  const size_t mask = cap - 1;
  int64_t nuniq = 0;
  for (size_t i = 0; i < n; ++i) {
    int64_t k = src[i];
    uint64_t h = static_cast<uint64_t>(k) * 0x9E3779B97F4A7C15ull;
    size_t slot = static_cast<size_t>(h >> 1) & mask;
    while (true) {
      if (!used[slot]) {
        if (static_cast<size_t>(nuniq) >= uniques_cap) return -1;
        used[slot] = 1;
        keys[slot] = k;
        vals[slot] = static_cast<int32_t>(nuniq);
        uniques[nuniq] = k;
        codes[i] = vals[slot];
        ++nuniq;
        break;
      }
      if (keys[slot] == k) {
        codes[i] = vals[slot];
        break;
      }
      slot = (slot + 1) & mask;
    }
  }
  return nuniq;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Host groupby partials — native twin of the hot primitives in
// bqueryd_tpu.ops.groupby.host_partial_tables (the latency-routed host path,
// the role bquery's Cython kernels played at reference bqueryd/worker.py:313).
// Rows stripe across threads with per-thread [n_groups] accumulators merged
// at the end.  Int64 sums accumulate in uint64 (mod 2^64) so they are exact
// for ANY value magnitude and any thread order — the numpy path's 2^53
// float-bincount bound does not apply here.  A row contributes iff its code
// is in [0, n_groups) and its mask byte (when a mask is given) is nonzero.
// ---------------------------------------------------------------------------

namespace {

int32_t plan_workers(size_t n, size_t n_groups, int32_t nthreads) {
  int32_t hw = static_cast<int32_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  if (nthreads <= 0) nthreads = hw;
  int32_t workers = std::max(1, std::min(nthreads, hw));
  // below ~128k rows thread spawn overhead beats the striping win
  if (n < (1u << 17)) workers = 1;
  // each extra worker costs an O(G) zero + merge; keep that amortized by
  // at least 8 G row-operations per worker or the accumulator bookkeeping
  // dwarfs the row scan it parallelizes
  const size_t by_groups = n / (8 * std::max<size_t>(n_groups, 1));
  workers = std::min<int32_t>(
      workers, static_cast<int32_t>(std::max<size_t>(by_groups, 1)));
  return workers;
}

template <typename Body>
void run_striped(size_t n, int32_t workers, const Body& body) {
  if (workers == 1) {
    body(0, 0, n);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(workers);
  for (int32_t t = 0; t < workers; ++t) {
    size_t lo = n * static_cast<size_t>(t) / workers;
    size_t hi = n * static_cast<size_t>(t + 1) / workers;
    threads.emplace_back([&body, t, lo, hi] { body(t, lo, hi); });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// counts[g] = contributing rows; if values && sums: sums[g] += values mod
// 2^64.  Returns 0, or -1 on a bad shape.
int32_t tpc_groupby_i64(const int32_t* codes, const int64_t* values,
                        const uint8_t* mask, size_t n, int64_t n_groups,
                        uint64_t* sums, int64_t* counts, int32_t nthreads) {
  if (n_groups <= 0 || !codes || !counts) return -1;
  const size_t G = static_cast<size_t>(n_groups);
  const bool want_sums = values != nullptr && sums != nullptr;
  const int32_t workers = plan_workers(n, G, nthreads);
  std::vector<std::vector<uint64_t>> tsums(workers);
  std::vector<std::vector<int64_t>> tcounts(workers);
  run_striped(n, workers, [&](int32_t t, size_t lo, size_t hi) {
    auto& c = tcounts[t];
    c.assign(G, 0);
    uint64_t* s = nullptr;
    if (want_sums) {
      tsums[t].assign(G, 0);
      s = tsums[t].data();
    }
    for (size_t i = lo; i < hi; ++i) {
      const int32_t g = codes[i];
      if (g < 0 || static_cast<int64_t>(g) >= n_groups) continue;
      if (mask && !mask[i]) continue;
      c[g] += 1;
      if (s) s[g] += static_cast<uint64_t>(values[i]);
    }
  });
  for (size_t g = 0; g < G; ++g) counts[g] = 0;
  if (want_sums)
    for (size_t g = 0; g < G; ++g) sums[g] = 0;
  for (int32_t t = 0; t < workers; ++t) {
    for (size_t g = 0; g < G; ++g) counts[g] += tcounts[t][g];
    if (want_sums)
      for (size_t g = 0; g < G; ++g) sums[g] += tsums[t][g];
  }
  return 0;
}

// Per-group min+max+presence counts in one pass.  Int64 flavor: every
// contributing row participates; f64 flavor additionally skips NaN.  Empty
// groups report the identity fills (int64 max/min, +/-inf) and count 0 —
// the same convention as the numpy/device paths, masked by count upstream.
int32_t tpc_groupby_minmax_i64(const int32_t* codes, const int64_t* values,
                               const uint8_t* mask, size_t n,
                               int64_t n_groups, int64_t* mins,
                               int64_t* maxs, int64_t* counts,
                               int32_t nthreads) {
  if (n_groups <= 0 || !codes || !values || !mins || !maxs || !counts)
    return -1;
  const size_t G = static_cast<size_t>(n_groups);
  const int64_t kMin = INT64_MIN, kMax = INT64_MAX;
  const int32_t workers = plan_workers(n, G, nthreads);
  std::vector<std::vector<int64_t>> tmins(workers), tmaxs(workers);
  std::vector<std::vector<int64_t>> tcounts(workers);
  run_striped(n, workers, [&](int32_t t, size_t lo, size_t hi) {
    tmins[t].assign(G, kMax);
    tmaxs[t].assign(G, kMin);
    tcounts[t].assign(G, 0);
    int64_t* mn = tmins[t].data();
    int64_t* mx = tmaxs[t].data();
    int64_t* c = tcounts[t].data();
    for (size_t i = lo; i < hi; ++i) {
      const int32_t g = codes[i];
      if (g < 0 || static_cast<int64_t>(g) >= n_groups) continue;
      if (mask && !mask[i]) continue;
      const int64_t v = values[i];
      if (v < mn[g]) mn[g] = v;
      if (v > mx[g]) mx[g] = v;
      c[g] += 1;
    }
  });
  for (size_t g = 0; g < G; ++g) {
    mins[g] = kMax;
    maxs[g] = kMin;
    counts[g] = 0;
  }
  for (int32_t t = 0; t < workers; ++t) {
    for (size_t g = 0; g < G; ++g) {
      if (tmins[t][g] < mins[g]) mins[g] = tmins[t][g];
      if (tmaxs[t][g] > maxs[g]) maxs[g] = tmaxs[t][g];
      counts[g] += tcounts[t][g];
    }
  }
  return 0;
}

int32_t tpc_groupby_minmax_f64(const int32_t* codes, const double* values,
                               const uint8_t* mask, size_t n,
                               int64_t n_groups, double* mins, double* maxs,
                               int64_t* counts, int32_t nthreads) {
  if (n_groups <= 0 || !codes || !values || !mins || !maxs || !counts)
    return -1;
  const size_t G = static_cast<size_t>(n_groups);
  const double kInf = std::numeric_limits<double>::infinity();
  const int32_t workers = plan_workers(n, G, nthreads);
  std::vector<std::vector<double>> tmins(workers), tmaxs(workers);
  std::vector<std::vector<int64_t>> tcounts(workers);
  run_striped(n, workers, [&](int32_t t, size_t lo, size_t hi) {
    tmins[t].assign(G, kInf);
    tmaxs[t].assign(G, -kInf);
    tcounts[t].assign(G, 0);
    double* mn = tmins[t].data();
    double* mx = tmaxs[t].data();
    int64_t* c = tcounts[t].data();
    for (size_t i = lo; i < hi; ++i) {
      const int32_t g = codes[i];
      if (g < 0 || static_cast<int64_t>(g) >= n_groups) continue;
      if (mask && !mask[i]) continue;
      const double v = values[i];
      if (v != v) continue;  // NaN = missing
      if (v < mn[g]) mn[g] = v;
      if (v > mx[g]) mx[g] = v;
      c[g] += 1;
    }
  });
  for (size_t g = 0; g < G; ++g) {
    mins[g] = kInf;
    maxs[g] = -kInf;
    counts[g] = 0;
  }
  for (int32_t t = 0; t < workers; ++t) {
    for (size_t g = 0; g < G; ++g) {
      if (tmins[t][g] < mins[g]) mins[g] = tmins[t][g];
      if (tmaxs[t][g] > maxs[g]) maxs[g] = tmaxs[t][g];
      counts[g] += tcounts[t][g];
    }
  }
  return 0;
}

// f64 sums with NaN skip; counts[g] (when given) = PRESENT (non-NaN)
// contributing rows.  Per-thread partials merge in worker order, so results
// are deterministic for a fixed thread count (float addition is not
// associative; bit-for-bit numpy equality is not promised, matching the
// allclose contract of the float paths).
int32_t tpc_groupby_f64(const int32_t* codes, const double* values,
                        const uint8_t* mask, size_t n, int64_t n_groups,
                        double* sums, int64_t* counts, int32_t nthreads) {
  if (n_groups <= 0 || !codes || !values || !sums) return -1;
  const size_t G = static_cast<size_t>(n_groups);
  const int32_t workers = plan_workers(n, G, nthreads);
  std::vector<std::vector<double>> tsums(workers);
  std::vector<std::vector<int64_t>> tcounts(workers);
  run_striped(n, workers, [&](int32_t t, size_t lo, size_t hi) {
    auto& s = tsums[t];
    s.assign(G, 0.0);
    int64_t* c = nullptr;
    if (counts) {
      tcounts[t].assign(G, 0);
      c = tcounts[t].data();
    }
    for (size_t i = lo; i < hi; ++i) {
      const int32_t g = codes[i];
      if (g < 0 || static_cast<int64_t>(g) >= n_groups) continue;
      if (mask && !mask[i]) continue;
      const double v = values[i];
      if (v != v) continue;  // NaN = missing (pandas skipna)
      s[g] += v;
      if (c) c[g] += 1;
    }
  });
  for (size_t g = 0; g < G; ++g) sums[g] = 0.0;
  if (counts)
    for (size_t g = 0; g < G; ++g) counts[g] = 0;
  for (int32_t t = 0; t < workers; ++t) {
    for (size_t g = 0; g < G; ++g) sums[g] += tsums[t][g];
    if (counts)
      for (size_t g = 0; g < G; ++g) counts[g] += tcounts[t][g];
  }
  return 0;
}

}  // extern "C"
