#!/bin/sh
# Build libtpucolz.so into native/build/.  Falls back from cmake+ninja to a
# direct g++ invocation so the library builds on minimal images.
set -e
cd "$(dirname "$0")"
if command -v cmake >/dev/null 2>&1 && command -v ninja >/dev/null 2>&1; then
    cmake -S . -B build -G Ninja -DCMAKE_BUILD_TYPE=Release >/dev/null
    cmake --build build >/dev/null
else
    mkdir -p build
    g++ -O3 -std=c++17 -shared -fPIC tpucolz.cpp -o build/libtpucolz.so -lz -lpthread
fi
echo "built: $(dirname "$0")/build/libtpucolz.so"
