"""Hardware validation: run the differential-fuzz gates on the DEFAULT
backend (the tunneled TPU) and bound the f64 sort+prefix-diff error.

The test suite pins ``JAX_PLATFORMS=cpu`` (tests/conftest.py), so the float64
sum path it exercises is the direct ``segment_sum`` — NOT the
``_sorted_segment_sum`` prefix-diff path a TPU takes (``ops/groupby.py``
routes f64 sums through the sort path on non-CPU backends, because TPUs have
no native f64 and an emulated-f64 scatter dominates the query).  This script
is the missing gate: it runs the same pandas-differential cases on whatever
backend the machine provides (the axon TPU tunnel under normal env), plus a
dedicated 1M-row f64 sum whose ground truth is ``math.fsum``, and records the
max observed relative error per case.

Usage:  python tpu_validate.py [out.json]
Exit 0 iff every case passes the suite's own tolerances on this backend.
"""

import json
import math
import os
import sys
import tempfile
import time
import traceback

# device kernels only: the point is the TPU path, not the host fallback
os.environ.setdefault("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))

import numpy as np
import pandas as pd

pd.set_option("future.infer_string", False)


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "TPU_VALIDATE.json"
    t0 = time.time()
    import jax

    if os.environ.get("TPU_VALIDATE_FORCE_CPU") == "1":
        # smoke-test mode: the machine's sitecustomize registers the axon
        # tunnel backend unconditionally and a dead tunnel hangs the first
        # device call even under JAX_PLATFORMS=cpu, so drop the factory
        # in-process (same dance as tests/conftest.py)
        import jax._src.xla_bridge as xb

        jax.config.update("jax_platforms", "cpu")
        xb._backend_factories.pop("axon", None)

    report = {
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "cases": {},
        "f64_large": None,
        "ok": False,
    }

    import test_differential_fuzz as fz
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor
    from bqueryd_tpu.storage.ctable import ctable

    frames = fz._dataset(seed=1234)
    root = tempfile.mkdtemp(prefix="tpu_validate_")
    tables = []
    for i, df in enumerate(frames):
        p = os.path.join(root, f"shard_{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))

    engine = QueryEngine()
    failures = 0
    for case_i, (gcols, agg_list, where) in enumerate(fz.CASES):
        expected = fz._expected(frames, gcols, agg_list, where)
        query = GroupByQuery(gcols, agg_list, where, aggregate=True)
        for path in ("engine", "mesh"):
            label = f"case{case_i}:{path}"
            t = time.perf_counter()
            try:
                if path == "engine":
                    payloads = [
                        engine.execute_local(tbl, query) for tbl in tables
                    ]
                    got = hostmerge.payload_to_dataframe(
                        hostmerge.merge_payloads(payloads)
                    )
                else:
                    if not MeshQueryExecutor.supports(query):
                        report["cases"][label] = {"status": "skipped"}
                        continue
                    payload = MeshQueryExecutor().execute(tables, query)
                    got = hostmerge.payload_to_dataframe(
                        hostmerge.merge_payloads([payload])
                    )
                fz._compare(got, expected, gcols, agg_list)
                # max relative error across float outputs, for the record
                max_rel = 0.0
                g2 = got.sort_values(gcols).reset_index(drop=True)
                e2 = expected.sort_values(gcols).reset_index(drop=True)
                for in_col, op, out_col in agg_list:
                    e = np.asarray(e2[out_col])
                    if not np.issubdtype(e.dtype, np.floating):
                        continue
                    g = g2[out_col].to_numpy().astype(np.float64)
                    denom = np.maximum(np.abs(e), 1e-30)
                    with np.errstate(invalid="ignore"):
                        rel = np.abs(g - e.astype(np.float64)) / denom
                    rel = rel[np.isfinite(rel)]
                    if rel.size:
                        max_rel = max(max_rel, float(rel.max()))
                report["cases"][label] = {
                    "status": "pass",
                    "wall_s": round(time.perf_counter() - t, 3),
                    "max_rel_err": max_rel,
                }
            except Exception:
                failures += 1
                report["cases"][label] = {
                    "status": "FAIL",
                    "error": traceback.format_exc(limit=3),
                }
            print(
                f"[tpu_validate] {label}: "
                f"{report['cases'][label]['status']}",
                file=sys.stderr,
                flush=True,
            )

    # dedicated f64 error bound at bench-like scale: 1M rows, 1000 groups,
    # values spanning 12 orders of magnitude; truth = per-group math.fsum
    try:
        from bqueryd_tpu.ops import groupby as gb

        rng = np.random.default_rng(7)
        n, g = 1_000_000, 1_000
        codes = rng.integers(0, g, n).astype(np.int64)
        vals = (rng.random(n) * 2 - 1) * 10.0 ** rng.integers(-6, 6, n)
        truth = np.array(
            [
                math.fsum(vals[codes == i].tolist())
                for i in range(g)
            ]
        )
        tbl = gb.partial_tables(codes, (vals,), ("sum",), g)
        got = np.asarray(tbl["aggs"][0]["sum"])
        denom = np.maximum(np.abs(truth), 1e-30)
        rel = np.abs(got - truth) / denom
        report["f64_large"] = {
            "rows": n,
            "groups": g,
            "max_rel_err": float(rel.max()),
            "max_abs_err": float(np.abs(got - truth).max()),
            "pass": bool(np.allclose(got, truth, rtol=1e-9, atol=1e-6)),
        }
        if not report["f64_large"]["pass"]:
            failures += 1
    except Exception:
        failures += 1
        report["f64_large"] = {"error": traceback.format_exc(limit=3)}

    report["ok"] = failures == 0
    report["failures"] = failures
    report["total_s"] = round(time.time() - t0, 1)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: report[k] for k in ("backend", "ok", "failures")}))
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
