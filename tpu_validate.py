"""Hardware validation: run the differential-fuzz gates on the DEFAULT
backend (the tunneled TPU) and bound the f64 sort+prefix-diff error.

The test suite pins ``JAX_PLATFORMS=cpu`` (tests/conftest.py), so the float64
sum path it exercises is the direct ``segment_sum`` — NOT the
``_sorted_segment_sum`` prefix-diff path a TPU takes (``ops/groupby.py``
routes f64 sums through the sort path on non-CPU backends, because TPUs have
no native f64 and an emulated-f64 scatter dominates the query).  This script
is the missing gate: it runs the same pandas-differential cases on whatever
backend the machine provides (the axon TPU tunnel under normal env), plus a
dedicated 1M-row f64 sum whose ground truth is ``math.fsum``, and records the
max observed relative error per case.

Usage:  python tpu_validate.py [out.json]
Exit 0 iff every phase ran to completion AND passed its tolerances; a
budget-truncated fuzz phase (TPU_VALIDATE_BUDGET_S, measured over the fuzz
loop only) reports ok=false/complete=false even with zero failures among
the cases that did run.
"""

import json
import math
import os
import sys
import tempfile
import time
import traceback

# device kernels only: the point is the TPU path, not the host fallback
os.environ.setdefault("BQUERYD_TPU_HOST_KERNEL_ROWS", "0")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))

import numpy as np
import pandas as pd

pd.set_option("future.infer_string", False)


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "TPU_VALIDATE.json"
    t0 = time.time()
    from bqueryd_tpu.utils import devicehealth

    wedge_start = devicehealth.wedge_marker()
    import jax

    if os.environ.get("TPU_VALIDATE_FORCE_CPU") == "1":
        # smoke-test mode: the machine's sitecustomize registers the axon
        # tunnel backend unconditionally and a dead tunnel hangs the first
        # device call even under JAX_PLATFORMS=cpu, so drop the factory
        # in-process (same dance as tests/conftest.py)
        import jax._src.xla_bridge as xb

        jax.config.update("jax_platforms", "cpu")
        xb._backend_factories.pop("axon", None)

    report = {
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "kernel_bench": {},
        "cases": {},
        "f64_large": None,
        "ok": False,
    }

    def checkpoint():
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)

    # ---- kernel micro-bench FIRST: the scarcest evidence (a tunnel window
    # can be minutes) is per-kernel hardware walls at bench shapes,
    # including one REAL (non-interpret) Pallas run — no cluster needed.
    # Each case is gated on numpy ground truth so a wrong-route or wrong-
    # result kernel can't post a number.
    def kernel_bench():
        import jax.numpy  # noqa: F401  (backend bring-up)

        from bqueryd_tpu.ops import groupby as gb

        rng = np.random.default_rng(0)
        # pre-set route flags would silently re-route the non-pallas cases
        # (flags are read per call in the un-jitted dispatcher); pop them
        # for the whole bench and restore after (same hygiene as bench.py)
        prior_env = {
            flag: os.environ.pop(flag, None)
            for flag in ("BQUERYD_TPU_PALLAS", "BQUERYD_TPU_FORCE_MATMUL")
        }
        shapes = [
            # (name, rows, groups, op, dtype, pallas)
            ("sum_i64_1M_9g", 1_000_000, 9, "sum", np.int64, False),
            ("sum_i64_10M_9g", 10_000_000, 9, "sum", np.int64, False),
            ("mean_f64_10M_9g", 10_000_000, 9, "mean", np.float64, False),
            ("sum_i64_10M_70225g", 10_000_000, 70_225, "sum", np.int64,
             False),
            ("sum_i64_10M_9g_pallas", 10_000_000, 9, "sum", np.int64,
             True),
        ]
        for name, n, g, op, dt, use_pallas in shapes:
            if use_pallas and jax.default_backend() == "cpu":
                # same honesty rule as bench.py: off-TPU the flag would
                # re-measure the scatter path under a pallas label
                report["kernel_bench"][name] = {
                    "skipped": "needs a tpu backend"
                }
                continue
            try:
                codes = rng.integers(0, g, n).astype(np.int64)
                if dt == np.float64:
                    vals = (rng.random(n) * 100 - 50).astype(dt)
                else:
                    vals = rng.integers(-1000, 1000, n).astype(dt)
                if use_pallas:
                    os.environ["BQUERYD_TPU_PALLAS"] = "1"
                try:
                    t_h2d = time.perf_counter()
                    codes_d = jax.device_put(codes)
                    vals_d = jax.device_put(vals)
                    jax.block_until_ready((codes_d, vals_d))
                    h2d_s = time.perf_counter() - t_h2d
                    t_first = time.perf_counter()
                    r = gb.partial_tables(codes_d, (vals_d,), (op,), g)
                    jax.block_until_ready(jax.tree_util.tree_leaves(r))
                    first_s = time.perf_counter() - t_first
                    walls = []
                    for _ in range(3):
                        t0 = time.perf_counter()
                        r = gb.partial_tables(
                            codes_d, (vals_d,), (op,), g
                        )
                        jax.block_until_ready(
                            jax.tree_util.tree_leaves(r)
                        )
                        walls.append(time.perf_counter() - t0)
                finally:
                    if use_pallas:
                        os.environ.pop("BQUERYD_TPU_PALLAS", None)
                got = np.asarray(r["aggs"][0]["sum"])  # mean partials: sum
                truth = np.zeros(g, dtype=np.float64 if dt == np.float64
                                 else np.int64)
                with np.errstate(over="ignore"):
                    np.add.at(truth, codes, vals)
                if dt == np.float64:
                    exact = bool(np.allclose(got, truth, rtol=1e-9))
                else:
                    exact = bool((got == truth).all())
                report["kernel_bench"][name] = {
                    "wall_s": round(min(walls), 5),
                    "rows_per_sec": round(n / min(walls), 1),
                    "h2d_s": round(h2d_s, 3),
                    "compile_plus_first_s": round(first_s, 2),
                    "exact": exact,
                }
            except Exception:
                report["kernel_bench"][name] = {
                    "error": traceback.format_exc(limit=2)
                }
            print(
                f"[tpu_validate] kernel {name}: "
                f"{report['kernel_bench'][name]}",
                file=sys.stderr,
                flush=True,
            )
            # checkpoint after every kernel so a wedging tunnel still
            # leaves the completed entries on disk
            checkpoint()
        # route-tuning data point: the SORT+prefix-diff path at the
        # highcard bench shape.  The dispatcher picks the blocked scatter
        # here (n_blocks*groups fits _MAX_BLOCK_SEGMENTS); measuring the
        # sorted path next to it on hardware tells us whether the 70k-group
        # crossover belongs lower on this chip (pre-fix hardware sample:
        # blocked path 0.583 s at this shape — thin margin vs the 0.833 s
        # baseline).
        name = "sum_i64_10M_70225g_sorted"
        try:
            import jax.numpy as jnp

            n, g = 10_000_000, 70_225
            codes = rng.integers(0, g, n).astype(np.int64)
            vals = rng.integers(-1000, 1000, n).astype(np.int64)

            @jax.jit
            def _sorted(c, v):
                safe = c.astype(jnp.int32)
                return gb._sorted_segment_sum(v, safe, g)

            codes_d = jax.device_put(codes)
            vals_d = jax.device_put(vals)
            jax.block_until_ready((codes_d, vals_d))
            t_first = time.perf_counter()
            r = _sorted(codes_d, vals_d)
            jax.block_until_ready(r)
            first_s = time.perf_counter() - t_first
            walls = []
            for _ in range(3):
                t0 = time.perf_counter()
                r = _sorted(codes_d, vals_d)
                jax.block_until_ready(r)
                walls.append(time.perf_counter() - t0)
            truth = np.zeros(g, dtype=np.int64)
            np.add.at(truth, codes, vals)
            report["kernel_bench"][name] = {
                "wall_s": round(min(walls), 5),
                "rows_per_sec": round(n / min(walls), 1),
                "compile_plus_first_s": round(first_s, 2),
                "exact": bool((np.asarray(r) == truth).all()),
            }
        except Exception:
            report["kernel_bench"][name] = {
                "error": traceback.format_exc(limit=2)
            }
        print(
            f"[tpu_validate] kernel {name}: {report['kernel_bench'][name]}",
            file=sys.stderr,
            flush=True,
        )
        checkpoint()

        # the group-tiled Pallas MXU path at the same highcard shape: the
        # candidate replacement for the 0.583 s blocked scatter (route
        # decision data; gated off by default until this number exists)
        name = "sum_i64_10M_70225g_hicard_pallas"
        if jax.default_backend() != "tpu":
            report["kernel_bench"][name] = {"skipped": "needs a tpu backend"}
        else:
            try:
                import jax.numpy as jnp

                n, g = 10_000_000, 70_225
                codes = rng.integers(0, g, n).astype(np.int64)
                vals = rng.integers(-1000, 1000, n).astype(np.int64)
                os.environ["BQUERYD_TPU_PALLAS"] = "1"
                try:
                    codes_d = jax.device_put(codes)
                    vals_d = jax.device_put(vals)
                    jax.block_until_ready((codes_d, vals_d))
                    assert gb._hicard_matmul_profitable(
                        (vals_d,), ("sum",), n, g
                    ), "hicard gate did not fire"
                    t_first = time.perf_counter()
                    r = gb.partial_tables(codes_d, (vals_d,), ("sum",), g)
                    jax.block_until_ready(jax.tree_util.tree_leaves(r))
                    first_s = time.perf_counter() - t_first
                    walls = []
                    for _ in range(3):
                        t0 = time.perf_counter()
                        r = gb.partial_tables(
                            codes_d, (vals_d,), ("sum",), g
                        )
                        jax.block_until_ready(
                            jax.tree_util.tree_leaves(r)
                        )
                        walls.append(time.perf_counter() - t0)
                finally:
                    os.environ.pop("BQUERYD_TPU_PALLAS", None)
                truth = np.zeros(g, dtype=np.int64)
                np.add.at(truth, codes, vals)
                report["kernel_bench"][name] = {
                    "wall_s": round(min(walls), 5),
                    "rows_per_sec": round(n / min(walls), 1),
                    "compile_plus_first_s": round(first_s, 2),
                    "exact": bool(
                        (np.asarray(r["aggs"][0]["sum"]) == truth).all()
                    ),
                }
            except Exception:
                report["kernel_bench"][name] = {
                    "error": traceback.format_exc(limit=2)
                }
            print(
                f"[tpu_validate] kernel {name}: "
                f"{report['kernel_bench'][name]}",
                file=sys.stderr,
                flush=True,
            )
            checkpoint()

        # one MESH-program data point: the exact serving program (shard_map
        # + psum merge + packed single-buffer fetch) on this backend's
        # devices — distinct from the bare kernel above, which skips the
        # collective and the packed fetch
        name = "mesh_sum_i64_10M_9g"
        try:
            from bqueryd_tpu.parallel import executor as ex_mod

            mesh = ex_mod.make_mesh()
            n_dev = mesh.devices.size
            n, g = 10_000_000, 9
            codes = rng.integers(0, g, n).astype(np.int32)
            vals = rng.integers(-1000, 1000, n).astype(np.int64)
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(mesh, P("shards", None))
            # the serving path narrows codes to _codes_dtype(g) (int8 at 9
            # groups) and the dtype is part of the traced program: match it
            # or this measures a different trace than serving runs
            cdt = ex_mod._codes_dtype(g)
            codes_p = ex_mod.MeshQueryExecutor._pack(
                [codes.astype(cdt)], n_dev, cdt.type(-1), dtype=cdt
            )
            vals_p = ex_mod.MeshQueryExecutor._pack([vals], n_dev, 0)
            codes_d = jax.device_put(codes_p, sharding)
            vals_d = jax.device_put(vals_p, sharding)
            t_first = time.perf_counter()
            merged = ex_mod._mesh_partials(
                mesh, "shards", ("sum",), g, codes_d, (vals_d,)
            )
            first_s = time.perf_counter() - t_first
            walls = []
            for _ in range(3):
                t1 = time.perf_counter()
                merged = ex_mod._mesh_partials(
                    mesh, "shards", ("sum",), g, codes_d, (vals_d,)
                )
                walls.append(time.perf_counter() - t1)
            truth = np.zeros(g, dtype=np.int64)
            with np.errstate(over="ignore"):
                np.add.at(truth, codes, vals)
            exact = bool(
                (np.asarray(merged["aggs"][0]["sum"]) == truth).all()
            )
            report["kernel_bench"][name] = {
                "wall_s": round(min(walls), 5),
                "rows_per_sec": round(n / min(walls), 1),
                "n_devices": int(n_dev),
                "compile_plus_first_s": round(first_s, 2),
                "exact": exact,
            }
        except Exception:
            report["kernel_bench"][name] = {
                "error": traceback.format_exc(limit=2)
            }
        print(
            f"[tpu_validate] kernel {name}: {report['kernel_bench'][name]}",
            file=sys.stderr,
            flush=True,
        )
        checkpoint()
        for flag, prior in prior_env.items():
            if prior is not None:
                os.environ[flag] = prior

    kernel_bench()

    failures = 0

    # ---- dedicated f64 error bound SECOND (it is the round's f64 evidence;
    # the 54 fuzz case-paths behind it compile one program each and can
    # outlast a short tunnel window): 1M rows, 1000 groups, values spanning
    # 12 orders of magnitude; truth = per-group math.fsum
    try:
        from bqueryd_tpu.ops import groupby as gb

        rng = np.random.default_rng(7)
        n, g = 1_000_000, 1_000
        codes = rng.integers(0, g, n).astype(np.int64)
        vals = (rng.random(n) * 2 - 1) * 10.0 ** rng.integers(-6, 6, n)
        truth = np.array(
            [math.fsum(vals[codes == i].tolist()) for i in range(g)]
        )
        tbl = gb.partial_tables(codes, (vals,), ("sum",), g)
        got = np.asarray(tbl["aggs"][0]["sum"])
        denom = np.maximum(np.abs(truth), 1e-30)
        rel = np.abs(got - truth) / denom
        report["f64_large"] = {
            "rows": n,
            "groups": g,
            "max_rel_err": float(rel.max()),
            "max_abs_err": float(np.abs(got - truth).max()),
            "pass": bool(np.allclose(got, truth, rtol=1e-9, atol=1e-6)),
        }
        if not report["f64_large"]["pass"]:
            failures += 1
    except Exception:
        failures += 1
        report["f64_large"] = {"error": traceback.format_exc(limit=3)}
    checkpoint()

    import test_differential_fuzz as fz
    from bqueryd_tpu.models.query import GroupByQuery, QueryEngine
    from bqueryd_tpu.parallel import hostmerge
    from bqueryd_tpu.parallel.executor import MeshQueryExecutor
    from bqueryd_tpu.storage.ctable import ctable

    frames = fz._dataset(seed=1234)
    root = tempfile.mkdtemp(prefix="tpu_validate_")
    tables = []
    for i, df in enumerate(frames):
        p = os.path.join(root, f"shard_{i}.bcolzs")
        ctable.fromdataframe(df, p)
        tables.append(ctable(p, mode="r"))

    engine = QueryEngine()
    # fuzz phase budget: each case-path compiles a fresh program, which on
    # a tunneled backend can outlast the tunnel; unstarted cases are
    # recorded rather than silently missing
    budget_s = float(os.environ.get("TPU_VALIDATE_BUDGET_S", 2400))
    over_budget = False
    t_fuzz = time.time()  # the budget bounds the fuzz loop only
    # a case whose program wedges the tunnel compile-helper blocks the loop
    # from INSIDE a native call (no signal can interrupt it; the round-5
    # window wedged at case20 that way, killing cases 21-26).  The skip
    # list lets a re-run route around a known-wedging case and still bank
    # the rest: TPU_VALIDATE_SKIP_CASES="20,23"
    skip_cases = {
        int(c)
        for c in os.environ.get("TPU_VALIDATE_SKIP_CASES", "").split(",")
        if c.strip()
    }
    for case_i, (gcols, agg_list, where) in enumerate(fz.CASES):
        if case_i in skip_cases:
            report["cases"][f"case{case_i}:engine"] = {"status": "skipped"}
            report["cases"][f"case{case_i}:mesh"] = {"status": "skipped"}
            continue
        if time.time() - t_fuzz > budget_s:
            over_budget = True
            break
        expected = fz._expected(frames, gcols, agg_list, where)
        query = GroupByQuery(gcols, agg_list, where, aggregate=True)
        for path in ("engine", "mesh"):
            label = f"case{case_i}:{path}"
            t = time.perf_counter()
            try:
                if path == "engine":
                    payloads = [
                        engine.execute_local(tbl, query) for tbl in tables
                    ]
                    got = hostmerge.payload_to_dataframe(
                        hostmerge.merge_payloads(payloads)
                    )
                else:
                    if not MeshQueryExecutor.supports(query):
                        report["cases"][label] = {"status": "skipped"}
                        continue
                    payload = MeshQueryExecutor().execute(tables, query)
                    got = hostmerge.payload_to_dataframe(
                        hostmerge.merge_payloads([payload])
                    )
                fz._compare(got, expected, gcols, agg_list)
                # max relative error across float outputs, for the record
                max_rel = 0.0
                g2 = got.sort_values(gcols).reset_index(drop=True)
                e2 = expected.sort_values(gcols).reset_index(drop=True)
                for in_col, op, out_col in agg_list:
                    e = np.asarray(e2[out_col])
                    if not np.issubdtype(e.dtype, np.floating):
                        continue
                    g = g2[out_col].to_numpy().astype(np.float64)
                    denom = np.maximum(np.abs(e), 1e-30)
                    with np.errstate(invalid="ignore"):
                        rel = np.abs(g - e.astype(np.float64)) / denom
                    rel = rel[np.isfinite(rel)]
                    if rel.size:
                        max_rel = max(max_rel, float(rel.max()))
                report["cases"][label] = {
                    "status": "pass",
                    "wall_s": round(time.perf_counter() - t, 3),
                    "max_rel_err": max_rel,
                }
            except Exception:
                failures += 1
                report["cases"][label] = {
                    "status": "FAIL",
                    "error": traceback.format_exc(limit=3),
                }
            print(
                f"[tpu_validate] {label}: "
                f"{report['cases'][label]['status']}",
                file=sys.stderr,
                flush=True,
            )
        # checkpoint after every case so a wedging tunnel keeps the
        # completed entries
        checkpoint()
    if over_budget:
        report["cases_not_run"] = len(fz.CASES) - case_i
        print(
            f"[tpu_validate] budget {budget_s:.0f}s exhausted: "
            f"{report['cases_not_run']} cases not run",
            file=sys.stderr,
            flush=True,
        )

    failures += sum(
        1
        for v in report["kernel_bench"].values()
        if "error" in v or v.get("exact") is False
    )
    # operator-skipped cases are partial validation, same as a budget
    # truncation: the one-line gate must not read as a full pass
    report["cases_skipped"] = len(skip_cases)
    # evidence integrity: engine/mesh cases host-route if the devicehealth
    # latch flipped at ANY point in the run (the window marker catches a
    # transient wedge that recovered before this line) — their walls are
    # then host numbers
    report["backend_wedged_during_run"] = devicehealth.window_dirty(
        wedge_start
    )
    report["complete"] = not over_budget and not skip_cases
    report["ok"] = failures == 0 and report["complete"]
    report["failures"] = failures
    report["total_s"] = round(time.time() - t0, 1)
    checkpoint()
    print(
        json.dumps(
            {
                k: report[k]
                for k in (
                    "backend", "ok", "complete", "failures",
                    "cases_skipped", "backend_wedged_during_run",
                )
            }
        )
    )
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
