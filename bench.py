"""Benchmark: the full framework vs the reference architecture, end to end.

Implements every BASELINE.md config on a 10 M-row NYC-taxi-shaped dataset in
10 ``.bcolzs`` shards, measured through the REAL stack: zmq RPC client ->
controller -> calc worker -> mesh executor (MXU one-hot groupby kernel +
psum merge) -> reply.  The headline line (config 2: 10-shard groupby-sum) is
what the driver records; the other configs ride in ``detail.configs``.

Configs (BASELINE.md "Benchmark configs to implement and measure"):

1. ``single``    single-shard groupby_sum(passenger_count -> fare_amount)
2. ``sharded``   the same over all 10 shards, controller merge  [HEADLINE]
3. ``multikey``  groupby (VendorID, payment_type) with sum+count+mean
4. ``filtered``  where trip_distance > 5.0 pushdown + groupby_sum
5. ``highcard``  groupby (PULocationID x DOLocationID) — ~70k groups,
                 exercises the scatter fallback past the MXU path's limit

``vs_baseline`` is speedup over a faithful CPU re-creation of the reference's
dataflow (the reference publishes no numbers, SURVEY.md §6, so its
architecture is the baseline): per shard, decode the columns single-threaded
(the reference pins Blosc to 1 thread, reference bqueryd/worker.py:40, and
bcolz decompresses per query — no decoded-row cache), aggregate with pandas
(the reference's own ground truth, reference tests/test_simple_rpc.py:139-172;
bquery's Cython kernels are the same class of C loop), tar the per-shard
result (reference bqueryd/worker.py:335-346), tar-of-tars at the controller
(reference bqueryd/controller.py:186-211), then untar + concat + re-groupby
client-side (reference bqueryd/rpc.py:150-173).

Correctness gates: integer aggregates must match the baseline bit-for-bit;
float means within 1e-6 relative.

Prints ONE compact JSON line LAST on stdout: {"metric", "value" (rows/s
through the framework on the headline), "unit", "vs_baseline", "detail"}
— kept under ~1.5 KB so log tails record it intact.  The full per-config
breakdown (phase timings from the min-wall repeat, cold-path walls, the
device round-trip floor) is written to BENCH_DETAIL.json next to this file
(override with BENCH_DETAIL_PATH so probe/smoke runs don't clobber the
committed round artifact).

Timing discipline: each config runs one warmup query, then BENCH_REPEATS
timed repeats; the reported wall is the min and the published phase timings
come from THAT repeat (not the last).  A separate cold run clears the
worker's data caches (alignment + HBM blocks + storage decode cache) first,
so decode/factorize/H2D appear in a recorded number; compiled XLA programs
stay cached — cold means cold data, not cold compiler.

Env knobs: BENCH_ROWS (default 10_000_000), BENCH_SHARDS (10),
BENCH_REPEATS (3), BENCH_DATA_DIR (default /tmp/bqueryd_tpu_bench),
BENCH_CONFIGS (comma list, default all), BENCH_COLD (default 1).
"""

import io
import json
import logging
import os
import pickle
import sys
import tarfile
import threading
import time

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 10_000_000))
SHARDS = int(os.environ.get("BENCH_SHARDS", 10))
REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
DATA_DIR = os.environ.get("BENCH_DATA_DIR", "/tmp/bqueryd_tpu_bench")
CONFIGS = [
    c
    for c in os.environ.get(
        "BENCH_CONFIGS", "single,sharded,multikey,filtered,highcard"
    ).split(",")
    if c
]

HEADLINE = "sharded"
#: set by ensure_backend when the configured backend was unreachable and the
#: run fell back to CPU (recorded in the output so a fallback run is never
#: mistaken for a TPU measurement)
BACKEND_FELL_BACK = False
# Registration + first-call deadlines sized for tunneled-TPU backend
# bring-up, which was measured at >9.5 minutes on this box (round-2 verdict).
# Registration itself is no longer gated on warmup, but keep both generous.
REGISTER_TIMEOUT = float(os.environ.get("BENCH_REGISTER_TIMEOUT_S", 900))
RPC_TIMEOUT = float(os.environ.get("BENCH_RPC_TIMEOUT_S", 3600))
# Per-config wall budget: a tunneled backend can wedge MID-RUN (observed:
# configs 1-2 measured fine, then the next warmup hung >8 minutes with the
# tunnel dead).  Without a bound one wedged query holds the whole benchmark
# hostage for RPC_TIMEOUT and NOTHING gets recorded; with it, the completed
# configs are emitted and the wedged one is marked timed_out.  The first
# config's budget also absorbs backend bring-up (>9.5 min measured), so it
# gets the larger allowance.
CONFIG_TIMEOUT = float(os.environ.get("BENCH_CONFIG_TIMEOUT_S", 900))
FIRST_CONFIG_TIMEOUT = float(
    os.environ.get("BENCH_FIRST_CONFIG_TIMEOUT_S", 2700)
)


def build_dataset():
    """Write the sharded taxi-like dataset once; reuse across runs."""
    from bqueryd_tpu.storage.ctable import ctable

    # v3: adds pickup_ts (datetime64[ns]) for the operators section's
    # window rollups; untouched configs never decode it, so their walls
    # are unaffected
    stamp = os.path.join(DATA_DIR, f"ready_v3_{ROWS}_{SHARDS}")
    names = [f"taxi_{i}.bcolzs" for i in range(SHARDS)]
    if not os.path.exists(stamp):
        import shutil

        import pandas as pd

        shutil.rmtree(DATA_DIR, ignore_errors=True)
        os.makedirs(DATA_DIR, exist_ok=True)
        rng = np.random.RandomState(42)
        per = ROWS // SHARDS
        for i, name in enumerate(names):
            rows = per + (ROWS % SHARDS if i == SHARDS - 1 else 0)
            df = pd.DataFrame(
                {
                    "passenger_count": rng.randint(1, 10, rows).astype(
                        np.int64
                    ),
                    # integer cents: int64 end-to-end, the north-star
                    # bit-exactness axis
                    "fare_amount": rng.randint(250, 20000, rows).astype(
                        np.int64
                    ),
                    "VendorID": rng.randint(1, 3, rows).astype(np.int64),
                    "payment_type": rng.randint(1, 6, rows).astype(np.int64),
                    "PULocationID": rng.randint(1, 266, rows).astype(
                        np.int64
                    ),
                    "DOLocationID": rng.randint(1, 266, rows).astype(
                        np.int64
                    ),
                    "trip_distance": (rng.random(rows) * 30).astype(
                        np.float32
                    ),
                    # one synthetic day of pickups at second granularity
                    # (datetime64[ns]): the operators section's window
                    # rollup axis
                    "pickup_ts": (
                        np.int64(1_700_000_000_000_000_000)
                        + rng.randint(0, 86_400, rows).astype(np.int64)
                        * np.int64(1_000_000_000)
                    ).view("datetime64[ns]"),
                }
            )
            ctable.fromdataframe(df, os.path.join(DATA_DIR, name))
        open(stamp, "w").close()
    return names


# config -> (filenames_slice, groupby_cols, agg_list, where_terms)
def config_query(name, names):
    if name == "single":
        return (
            names[:1],
            ["passenger_count"],
            [["fare_amount", "sum", "fare_amount"]],
            [],
        )
    if name == "sharded":
        return (
            names,
            ["passenger_count"],
            [["fare_amount", "sum", "fare_amount"]],
            [],
        )
    if name == "multikey":
        return (
            names,
            ["VendorID", "payment_type"],
            [
                ["fare_amount", "sum", "fare_sum"],
                ["fare_amount", "count", "n"],
                ["trip_distance", "mean", "dist_mean"],
            ],
            [],
        )
    if name == "filtered":
        return (
            names,
            ["passenger_count"],
            [["fare_amount", "sum", "fare_amount"]],
            [["trip_distance", ">", 5.0]],
        )
    if name == "highcard":
        return (
            names,
            ["PULocationID", "DOLocationID"],
            [["fare_amount", "sum", "fare_amount"]],
            [],
        )
    raise ValueError(name)


def start_cluster():
    """Controller + one calc worker in-process (threads as nodes, the
    reference's own benchmark/test topology) over real zmq sockets.

    The worker's result cache is disabled: repeated identical queries would
    otherwise be served from memory and the benchmark would measure a dict
    lookup, not the engine (the kernel/storage caches stay on — they are the
    steady-state serving path being measured)."""
    os.environ["BQUERYD_TPU_RESULT_CACHE_BYTES"] = "0"
    # Same rationale for semantic serving (PR 16): repeated identical
    # queries would cross the rollup heat threshold and be answered from a
    # materialized rollup — a controller-side lookup, not the engine.  The
    # serving section measures it on its own cluster with SERVE=1.
    os.environ["BQUERYD_TPU_SERVE"] = "0"
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    url = f"mem://bench-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=DATA_DIR,
        heartbeat_interval=0.2,
        dispatch_hard_timeout=RPC_TIMEOUT,
    )
    worker = WorkerNode(
        coordination_url=url,
        data_dir=DATA_DIR,
        loglevel=logging.WARNING,
        restart_check=False,
        heartbeat_interval=0.2,
        poll_timeout=0.1,
    )
    threads = [
        threading.Thread(target=node.go, daemon=True)
        for node in (controller, worker)
    ]
    for t in threads:
        t.start()
    t0 = time.time()
    deadline = t0 + REGISTER_TIMEOUT
    last_log = t0
    while time.time() < deadline:
        if len(controller.files_map) >= SHARDS:
            break
        if not all(t.is_alive() for t in threads):
            raise RuntimeError(
                "a cluster node thread died during startup (see log above)"
            )
        now = time.time()
        if now - last_log >= 15:
            last_log = now
            print(
                f"[bench] waiting for registration: "
                f"{len(controller.files_map)}/{SHARDS} shards after "
                f"{now - t0:.0f}s (deadline {REGISTER_TIMEOUT:.0f}s)",
                file=sys.stderr,
                flush=True,
            )
        time.sleep(0.05)
    else:
        raise RuntimeError(
            f"worker never registered its shards within {REGISTER_TIMEOUT:.0f}s "
            f"({len(controller.files_map)}/{SHARDS} seen)"
        )
    print(
        f"[bench] cluster up: {SHARDS} shards registered in "
        f"{time.time() - t0:.1f}s",
        file=sys.stderr,
        flush=True,
    )
    rpc = RPC(
        coordination_url=url, timeout=RPC_TIMEOUT, loglevel=logging.WARNING
    )
    return rpc, (controller, worker), threads


def _pandas_agg(df, groupby_cols, agg_list):
    named = {}
    for in_col, op, out_col in agg_list:
        pandas_op = {"count": "count", "sum": "sum", "mean": "mean"}[op]
        named[out_col] = (in_col, pandas_op)
    return df.groupby(groupby_cols, as_index=False).agg(**named)


def reference_shaped_baseline(names, groupby_cols, agg_list, where_terms):
    """One query through the reference's dataflow shape on CPU (see module
    docstring); returns (wall_seconds, result_df)."""
    import pandas as pd

    from bqueryd_tpu.storage.ctable import ctable

    in_cols = sorted(
        {c for c, _, _ in agg_list}
        | set(groupby_cols)
        | {t[0] for t in where_terms}
    )
    t0 = time.perf_counter()
    shard_tars = []
    for name in names:
        # per-query single-threaded decode, no decoded cache (bcolz behavior)
        t = ctable(os.path.join(DATA_DIR, name), auto_cache=False, nthreads=1)
        df = pd.DataFrame({c: t.column_raw(c) for c in in_cols})
        for col, op, val in where_terms:
            assert op == ">"
            df = df[df[col] > val]
        # shard partials merge with sum/count partials like the client-side
        # re-groupby does (reference bqueryd/rpc.py:150-173)
        part_aggs = []
        for in_col, op, out_col in agg_list:
            if op == "mean":
                part_aggs.append([in_col, "sum", out_col + "__sum"])
                part_aggs.append([in_col, "count", out_col + "__n"])
            else:
                part_aggs.append([in_col, op, out_col])
        part = _pandas_agg(df, groupby_cols, part_aggs)
        # worker: result table -> tar bytes (reference bqueryd/worker.py:335-346)
        buf = io.BytesIO()
        with tarfile.open(mode="w", fileobj=buf) as tar:
            blob = pickle.dumps(part, protocol=4)
            info = tarfile.TarInfo(name="result")
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
        shard_tars.append(buf.getvalue())
    # controller: tar of tars (reference bqueryd/controller.py:186-211)
    outer = io.BytesIO()
    with tarfile.open(mode="w", fileobj=outer) as tar:
        for i, blob in enumerate(shard_tars):
            info = tarfile.TarInfo(name=f"shard_{i}")
            info.size = len(blob)
            tar.addfile(info, io.BytesIO(blob))
    wire = outer.getvalue()
    # client: untar + untar + concat + re-groupby (reference bqueryd/rpc.py:150-173)
    parts = []
    with tarfile.open(mode="r", fileobj=io.BytesIO(wire)) as tar:
        for member in tar.getmembers():
            inner = tar.extractfile(member).read()
            with tarfile.open(mode="r", fileobj=io.BytesIO(inner)) as shard:
                for m2 in shard.getmembers():
                    parts.append(pickle.loads(shard.extractfile(m2).read()))
    cat = pd.concat(parts, ignore_index=True)
    sums = cat.groupby(groupby_cols, as_index=False).sum()
    merged = sums[groupby_cols].copy()
    for in_col, op, out_col in agg_list:
        if op == "mean":
            merged[out_col] = (
                sums[out_col + "__sum"] / sums[out_col + "__n"]
            )
        else:
            merged[out_col] = sums[out_col]
    return time.perf_counter() - t0, merged


def check_result(result_df, base_df, groupby_cols, agg_list, config):
    """Integer aggregates bit-exact vs the baseline; float means close."""
    import pandas as pd

    r = result_df.sort_values(groupby_cols).reset_index(drop=True)
    b = base_df.sort_values(groupby_cols).reset_index(drop=True)
    assert len(r) == len(b), f"{config}: row count {len(r)} != {len(b)}"
    for col in groupby_cols:
        assert (
            r[col].astype(np.int64) == b[col].astype(np.int64)
        ).all(), f"{config}: key column {col} mismatch"
    for _, op, out_col in agg_list:
        if op in ("sum", "count") and b[out_col].dtype.kind in "iu":
            assert (
                r[out_col].astype(np.int64) == b[out_col].astype(np.int64)
            ).all(), f"{config}: bit-exactness failure in {out_col}"
        else:
            rv = r[out_col].astype(np.float64).to_numpy()
            bv = b[out_col].astype(np.float64).to_numpy()
            # the framework's float32 sum is EXACT (3-limb Dekker split,
            # ops/groupby.py), so the only slack needed is the BASELINE's
            # own f32 pairwise-accumulation error: ~eps32 * log2(n) ≈ 3e-6
            # relative.  rtol=1e-5 keeps margin while catching any limb
            # regression that 1e-4 would have let through.
            atol = 1e-7 * float(np.abs(bv).max(initial=1.0))
            ok = np.allclose(rv, bv, rtol=1e-5, atol=atol)
            assert ok, f"{config}: float mismatch in {out_col}"


def _phase_total(timings):
    """Sum of the worker's per-phase totals across shard-group entries.
    The whole-call wall is the namespaced ``_total`` key (messages.py
    schema); ``total`` is accepted for replies from older workers."""
    if not timings:
        return None
    total = 0.0
    for entry in timings.values():
        if isinstance(entry, dict):
            total += float(entry.get("_total", entry.get("total", 0.0)))
    return round(total, 4)


def device_roundtrip_floor():
    """The per-dispatch latency floor of this backend: wall of a trivial
    jitted kernel dispatch + fetch (one submit + one result round-trip).
    On a tunneled/remote TPU this is tens of ms of pure transport and bounds
    every per-query wall from below — recorded so small-config speedups can
    be attributed (round-3 verdict: the ~65 ms fixed cost was unexplained)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    jax.block_until_ready(f(jnp.zeros(())))
    walls = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(f(jnp.zeros(())))
        walls.append(time.perf_counter() - t0)
    return min(walls)


def _chaos_cluster(n_workers=2):
    """Fresh controller + N replica calc workers over the bench dataset
    (every worker holds every shard — the topology failover needs), with
    failover-scaled timeouts.  One cluster per scenario: a killed or
    wedged worker must not leak into the next scenario's measurement.
    Bootstrap/teardown shared with the ingest section (_ingest_cluster)."""
    return _ingest_cluster(
        DATA_DIR, "chaos", SHARDS, n_workers=n_workers,
        rpc_timeout=60,
        dead_worker_timeout=2.0,
        dispatch_timeout=2.0,
        dispatch_hard_timeout=4.0,
    )


def _chaos_burst(rpc, names, repeats=3):
    """The scenario workload: the headline sum + the multikey float-mean
    query, interleaved ``repeats`` times.  Returns (walls, frames, failed)
    — a query that raises counts as FAILED (the gate's currency) and the
    burst continues."""
    queries = {
        "sharded_sum": config_query(HEADLINE, names),
        "multikey_multiagg": config_query("multikey", names),
    }
    walls, frames, failed = [], {}, 0
    for _ in range(repeats):
        for qname, (f, g, a, w) in queries.items():
            t0 = time.perf_counter()
            try:
                df = rpc.groupby(f, g, a, w)
            except Exception as exc:
                failed += 1
                print(
                    f"[bench] chaos: query {qname} FAILED: {exc!r}",
                    file=sys.stderr, flush=True,
                )
                continue
            walls.append(time.perf_counter() - t0)
            frames.setdefault(qname, []).append(
                df.sort_values(g).reset_index(drop=True)
            )
    return walls, frames, failed


def _chaos_frames_match(frames, reference):
    """Every burst frame vs the fault-free reference: integer columns
    bit-identical, float columns within reassociation ulps (a failover that
    re-splits a device-merge group changes float summation order only).
    Returns (identical, float_max_rel_err)."""
    identical, max_rel = True, 0.0
    for qname, ref in reference.items():
        for df in frames.get(qname, []):
            if len(df) != len(ref) or list(df.columns) != list(ref.columns):
                return False, max_rel
            for col in ref.columns:
                a = df[col].to_numpy()
                b = ref[col].to_numpy()
                if a.dtype.kind in "iub":
                    identical = identical and bool(np.array_equal(a, b))
                else:
                    af = a.astype(np.float64)
                    bf = b.astype(np.float64)
                    identical = identical and bool(
                        np.allclose(af, bf, rtol=1e-9, equal_nan=True)
                    )
                    with np.errstate(all="ignore"):
                        rel = (
                            np.nanmax(
                                np.abs(af - bf)
                                / np.maximum(np.abs(bf), 1e-30)
                            )
                            if len(af) else 0.0
                        )
                    max_rel = max(max_rel, float(rel))
        if not frames.get(qname):
            return False, max_rel  # the whole query family failed
    return identical, max_rel


def _chaos_scenario_plans(workers):
    """The four scripted degradation scenarios over the replica cluster.
    Built AFTER cluster start so the redis-partition rule can target one
    concrete worker id; the others use times=1 (whichever worker draws the
    first dispatch is the victim — deterministic given the plan + seed)."""
    return {
        "kill_worker": {
            "seed": 81,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        },
        "drop_reply": {
            "seed": 82,
            "faults": [{
                "site": "controller.reply",
                "action": "drop",
                "times": 1,
            }],
        },
        "wedge_device": {
            "seed": 83,
            "faults": [{
                "site": "worker.execute",
                "action": "wedge",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        },
        "redis_partition": {
            "seed": 84,
            "faults": [{
                "site": "coordination.store",
                "action": "partition",
                "match": {"node": workers[0].worker_id},
                "window_s": 6.0,
            }],
        },
    }


def _conc_swarm(url, queries_by_client, window_ms):
    """Closed-loop multi-client swarm against a live controller: one thread
    (one REQ socket) per client, a per-round barrier so every round's
    queries land concurrently (the serving pattern the admission window
    exists for), ``window_ms`` pinned for the leg.  Returns
    ``(results[(client, round)], per-query walls, elapsed_s)``."""
    from bqueryd_tpu.rpc import RPC

    n_clients = len(queries_by_client)
    barrier = threading.Barrier(n_clients)
    results = {}
    walls = []
    lock = threading.Lock()
    errors = []
    prior = os.environ.get("BQUERYD_TPU_BATCH_WINDOW_MS")
    if window_ms:
        os.environ["BQUERYD_TPU_BATCH_WINDOW_MS"] = str(window_ms)
    else:
        os.environ.pop("BQUERYD_TPU_BATCH_WINDOW_MS", None)
    try:
        def client(ci):
            try:
                rpc = RPC(
                    coordination_url=url, timeout=RPC_TIMEOUT,
                    loglevel=logging.WARNING,
                )
                for k, query in enumerate(queries_by_client[ci]):
                    barrier.wait(timeout=300)
                    t0 = time.perf_counter()
                    frame = rpc.groupby(*query)
                    wall = time.perf_counter() - t0
                    with lock:
                        walls.append(wall)
                        results[(ci, k)] = frame
            except Exception as exc:  # surfaced to the caller below
                errors.append(exc)
                try:
                    barrier.abort()
                except Exception:
                    pass

        threads = [
            threading.Thread(target=client, args=(ci,), daemon=True)
            for ci in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        elapsed = time.perf_counter() - t0
    finally:
        if prior is None:
            os.environ.pop("BQUERYD_TPU_BATCH_WINDOW_MS", None)
        else:
            os.environ["BQUERYD_TPU_BATCH_WINDOW_MS"] = prior
    if errors:
        raise errors[0]
    return results, walls, elapsed


def _conc_frames_match(a, b, key_cols):
    """(identical, float_max_rel_err): ints bit-exact, floats to
    reassociation ulps — the same contract as the merge parity probes."""
    a = a.sort_values(key_cols).reset_index(drop=True)
    b = b.sort_values(key_cols).reset_index(drop=True)
    if len(a) != len(b):
        return False, float("inf")
    identical = True
    max_rel = 0.0
    for col in a.columns:
        x = a[col].to_numpy()
        y = b[col].to_numpy()
        if x.dtype.kind in "iub":
            identical = identical and bool(np.array_equal(x, y))
        else:
            xf = x.astype(np.float64)
            yf = y.astype(np.float64)
            identical = identical and bool(
                np.allclose(xf, yf, rtol=1e-9, equal_nan=True)
            )
            with np.errstate(all="ignore"):
                rel = (
                    np.nanmax(
                        np.abs(xf - yf) / np.maximum(np.abs(yf), 1e-30)
                    )
                    if len(xf) else 0.0
                )
            max_rel = max(max_rel, float(rel))
    return identical, max_rel


def _pct(values, q):
    """Sorted-index percentile of a wall list (None on empty)."""
    ordered = sorted(values)
    if not ordered:
        return None
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


def _open_loop_swarm(url, make_query, offered_qps, duration_s,
                     n_clients=8):
    """Open-loop load against a live controller: ``n_clients`` REQ threads
    share one global send schedule at ``offered_qps`` (slot k fires at
    t0 + k/offered).  A client whose slot is overdue while it was still
    waiting on a reply sends immediately — lockstep REQ sockets are the
    natural backpressure above saturation, and achieved < offered is
    exactly the knee signal the ramp measures.  Returns
    ``(achieved_qps, walls, n_completed)``."""
    import itertools

    from bqueryd_tpu.rpc import RPC

    lock = threading.Lock()
    walls = []
    errors = []
    slots = itertools.count()
    t0 = [None]
    barrier = threading.Barrier(n_clients)

    def client(ci):
        try:
            rpc = RPC(
                coordination_url=url, timeout=RPC_TIMEOUT,
                loglevel=logging.WARNING,
            )
            barrier.wait(timeout=300)
            with lock:
                if t0[0] is None:
                    t0[0] = time.perf_counter()
            while True:
                k = next(slots)
                due_offset = k / offered_qps
                if due_offset >= duration_s:
                    return
                due = t0[0] + due_offset
                now = time.perf_counter()
                if due > now:
                    time.sleep(due - now)
                q0 = time.perf_counter()
                rpc.groupby(*make_query(k))
                with lock:
                    walls.append(time.perf_counter() - q0)
        except Exception as exc:
            errors.append(exc)
            try:
                barrier.abort()
            except Exception:
                pass

    threads = [
        threading.Thread(target=client, args=(ci,), daemon=True)
        for ci in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    t_end = time.perf_counter()
    if errors:
        raise errors[0]
    # achieved over the post-barrier clock (t0): per-client RPC
    # construction and barrier sync must not dilute the rate — only the
    # in-flight drain tail (bounded by one query wall) remains inside
    elapsed = t_end - t0[0] if t0[0] is not None else 1e-9
    return len(walls) / max(elapsed, 1e-9), walls, len(walls)


def run_capacity_section(names, controller_node, coord_url,
                         slo_combined_pct=None):
    """The capacity gate: an open-loop load ramp against the live bench
    cluster.  Asserts (BENCH_CAPACITY_GATE=0 records without asserting):
    the model measured every worker (coverage), the predicted saturation
    knee brackets the measured QPS plateau within ±25%, the shadow advisor
    recommends scale_up at measured saturation and nothing at low load,
    model-vs-measured queue-delay drift is reported, and the capacity
    evaluation microcost keeps the combined observability overhead under
    the 2% budget (the obs/slo overhead legs already ran with the model's
    taps live)."""
    gate_on = os.environ.get("BENCH_CAPACITY_GATE", "1") == "1"
    detail = {"legs": []}
    knob_env = {
        # a short window so each ramp leg's rate dominates the estimate,
        # and a short (but non-zero: the mechanism stays exercised)
        # hysteresis so a 10 s leg can flip the state machine
        "BQUERYD_TPU_CAPACITY_WINDOW_S": "12",
        "BQUERYD_TPU_CAPACITY_HYSTERESIS_S": "1",
    }
    prior = {k: os.environ.get(k) for k in knob_env}
    os.environ.update(knob_env)
    try:
        duration_s = float(os.environ.get("BENCH_CAPACITY_LEG_S", "10"))
        n_clients = int(os.environ.get("BENCH_CAPACITY_CLIENTS", "6"))

        def make_query(k):
            # distinct filter threshold per slot: the PR-1 identical-work
            # dedup must not fuse concurrent ramp queries (that would
            # measure sharing, not capacity)
            return (
                names,
                ["passenger_count"],
                [["fare_amount", "sum", "fare_sum"]],
                [["trip_distance", ">", round(0.02 + 0.0013 * k, 4)]],
            )

        # closed-loop saturation probe: n_clients hammering back to back
        # approximates the throughput plateau (the measured knee), and
        # warms the model's μ windows
        probe_queries = [
            [make_query(10_000 + ci * 50 + k) for k in range(3)]
            for ci in range(n_clients)
        ]
        _, probe_walls, probe_elapsed = _conc_swarm(
            coord_url, probe_queries, None
        )
        closed_qps = len(probe_walls) / max(probe_elapsed, 1e-9)
        detail["closed_loop_qps"] = round(closed_qps, 4)
        # let the probe's saturation drain out of the rate windows and the
        # busy EWMA before the ramp: the low leg must measure LOW load,
        # not the probe's afterglow
        time.sleep(6)

        measured_knee = closed_qps
        low_recs = sat_recs = None
        for label, factor in (
            ("low", 0.3), ("mid", 0.7), ("overload", 1.4)
        ):
            # the floor only guards a degenerate probe; it must stay WELL
            # below any realistic knee or the 10M low leg (knee ~1 qps)
            # would sit at the warm/saturated boundary instead of at 0.3x
            offered = max(closed_qps * factor, 0.15)
            achieved, leg_walls, n_done = _open_loop_swarm(
                coord_url, make_query, offered, duration_s,
                n_clients=n_clients,
            )
            result = controller_node.capacity.evaluate()
            fleet = result.get("fleet", {})
            actions = [
                r["action"] for r in result.get("recommendations", ())
            ]
            detail["legs"].append({
                "leg": label,
                "offered_qps": round(offered, 4),
                "achieved_qps": round(achieved, 4),
                "completed": n_done,
                "p50_s": round(_pct(leg_walls, 0.50) or 0.0, 4),
                "p99_s": round(_pct(leg_walls, 0.99) or 0.0, 4),
                "fleet_state": fleet.get("state"),
                "fleet_utilization": fleet.get("utilization"),
                "model_knee_qps": fleet.get("knee_qps"),
                "recommendations": actions,
            })
            measured_knee = max(measured_knee, achieved)
            if label == "low":
                low_recs = actions
            if label == "overload":
                sat_recs = actions
        final = controller_node.capacity.evaluate()
        fleet = final.get("fleet", {})
        predicted_knee = fleet.get("knee_qps")
        detail["measured_knee_qps"] = round(measured_knee, 4)
        detail["predicted_knee_qps"] = predicted_knee
        knee_ratio = (
            predicted_knee / measured_knee
            if predicted_knee and measured_knee > 0 else None
        )
        detail["knee_ratio"] = (
            round(knee_ratio, 4) if knee_ratio is not None else None
        )
        detail["knee_within_25pct"] = (
            knee_ratio is not None and 0.75 <= knee_ratio <= 1.25
        )
        detail["model_coverage"] = fleet.get("coverage")
        detail["model_drift"] = fleet.get("model_drift")
        detail["predicted_queue_delay_s"] = fleet.get(
            "predicted_queue_delay_s"
        )
        detail["measured_queue_delay_s"] = fleet.get(
            "measured_queue_delay_s"
        )
        detail["worker_resets"] = controller_node.capacity.worker_resets()
        detail["low_load_recommendations"] = low_recs
        detail["saturated_recommendations"] = sat_recs
        detail["advisor_flipped_to_scale_up"] = bool(
            sat_recs and "scale_up" in sat_recs
        )
        detail["scale_up_advised_total"] = controller_node.counters[
            "capacity_scale_up_advised"
        ]
        detail["shard_heat_top"] = final.get("shard_heat", [])[:4]

        # evaluation microcost: the taps were live through every measured
        # section (the obs/slo overhead legs cover them); what's left is
        # the periodic evaluate, amortized at the bench heartbeat cadence
        # against the headline wall
        K = 200
        t0 = time.perf_counter()
        for _ in range(K):
            controller_node.capacity.evaluate()
        eval_s = (time.perf_counter() - t0) / K
        hb = max(controller_node.heartbeat_interval, 1e-3)
        eval_pct = eval_s / hb * 100.0
        detail["evaluate_cost_ms"] = round(eval_s * 1e3, 4)
        detail["evaluate_overhead_pct"] = round(eval_pct, 4)
        # the whole-path budget: the slo section's combined spans +
        # attribution overhead (measured with the capacity TAPS live —
        # the model is on throughout the bench) plus the periodic
        # evaluate, against the same 2% ceiling
        combined = None
        if slo_combined_pct is not None:
            combined = round(slo_combined_pct + eval_pct, 4)
        detail["combined_overhead_pct_with_capacity"] = combined

        print(
            f"[bench] capacity: measured knee "
            f"{detail['measured_knee_qps']:.2f} qps vs predicted "
            f"{predicted_knee if predicted_knee else float('nan'):.2f} "
            f"(ratio {detail['knee_ratio']}), low-load advice "
            f"{low_recs}, saturated advice {sat_recs}, drift "
            f"{detail['model_drift']}, evaluate "
            f"{detail['evaluate_cost_ms']:.3f} ms",
            file=sys.stderr, flush=True,
        )
        if gate_on:
            assert detail["model_coverage"] == 1.0, (
                f"capacity model coverage {detail['model_coverage']} — "
                "some live worker was never measured"
            )
            assert detail["knee_within_25pct"], (
                f"predicted knee {predicted_knee} vs measured "
                f"{measured_knee:.2f} qps (ratio {detail['knee_ratio']}) "
                "outside the ±25% bracket"
            )
            assert "scale_up" not in (low_recs or []), (
                f"advisor recommended scale_up at 0.3x load: {low_recs}"
            )
            assert detail["advisor_flipped_to_scale_up"], (
                f"advisor never flipped to scale_up at saturation: "
                f"{sat_recs}"
            )
            assert detail["model_drift"] is not None, (
                "model-vs-measured queue-delay drift never computed"
            )
            assert eval_pct < 2.0, (
                f"capacity evaluate costs {eval_pct:.2f}% of a heartbeat "
                "interval (budget: 2%)"
            )
            if combined is not None:
                assert combined <= 2.0, (
                    f"obs + attribution + capacity overhead {combined}% "
                    "of the hot-path wall (budget: 2%)"
                )
        return detail
    finally:
        for key, value in prior.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


def run_chaos_section(names):
    """The chaos gate: each scripted scenario (kill-worker, drop-reply,
    wedge-device, redis-partition) runs the burst over its own fresh
    replica cluster with the fault plan armed, asserting ZERO failed
    queries, results identical to the fault-free run (ints bit-exact,
    floats to reassociation ulps), bounded worst-case wall inflation, and
    — via the summed failover counters — that the failover path actually
    ran (no vacuous pass)."""
    from bqueryd_tpu import chaos as chaos_mod

    detail = {"scenarios": {}}
    # fault-free reference: same burst, same cluster shape, no plan armed
    rpc, controller, workers, nodes, threads = _chaos_cluster()
    try:
        _chaos_burst(rpc, names, repeats=1)  # warm compile/decode caches
        ff_walls, ff_frames, ff_failed = _chaos_burst(rpc, names)
    finally:
        rpc.socket.close(linger=0)
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)
    if ff_failed or not ff_walls:
        raise RuntimeError("chaos fault-free baseline burst failed")
    reference = {
        qname: frames[0] for qname, frames in ff_frames.items()
    }
    ff_max = max(ff_walls)
    detail["fault_free"] = {
        "queries": len(ff_walls),
        "max_wall_s": round(ff_max, 4),
        "mean_wall_s": round(sum(ff_walls) / len(ff_walls), 4),
    }

    failovers_total = 0
    for scenario in ("kill_worker", "drop_reply", "wedge_device",
                     "redis_partition"):
        rpc, controller, workers, nodes, threads = _chaos_cluster()
        injected_before = chaos_mod.injected_total()
        try:
            _chaos_burst(rpc, names, repeats=1)  # warm, pre-fault
            chaos_mod.arm(_chaos_scenario_plans(workers)[scenario])
            walls, frames, failed = _chaos_burst(rpc, names)
        finally:
            chaos_mod.disarm()
            rpc.socket.close(linger=0)
            for node in nodes:
                node.running = False
            for t in threads:
                t.join(timeout=5)
        identical, max_rel = _chaos_frames_match(frames, reference)
        counters = dict(controller.counters)
        failovers = counters.get("failover_dispatches", 0)
        failovers_total += failovers
        max_wall = max(walls) if walls else None
        entry = {
            "queries": len(walls) + failed,
            "failed": failed,
            "max_wall_s": None if max_wall is None else round(max_wall, 4),
            "p99_inflation_x": (
                None if max_wall is None or ff_max <= 0
                else round(max_wall / ff_max, 2)
            ),
            # worst-case inflation bound: one full recovery window
            # (dispatch timeout -> failover backoff -> re-execute) + slack;
            # an unbounded stall means the failover path did NOT recover
            "bounded_p99": (
                max_wall is not None and max_wall <= ff_max + 20.0
            ),
            "identical": identical,
            "float_max_rel_err": max_rel,
            "failover_dispatches": failovers,
            "transient_faults": counters.get("transient_faults", 0),
            "duplicate_replies": counters.get("duplicate_replies", 0),
            "fault_injected": chaos_mod.injected_total() - injected_before,
        }
        detail["scenarios"][scenario] = entry
        print(
            f"[bench] chaos {scenario}: failed={failed} "
            f"max_wall={entry['max_wall_s']}s "
            f"(x{entry['p99_inflation_x']} vs fault-free) "
            f"identical={identical} failovers={failovers} "
            f"injected={entry['fault_injected']}",
            file=sys.stderr, flush=True,
        )

    detail["zero_failed_queries"] = all(
        s["failed"] == 0 for s in detail["scenarios"].values()
    )
    detail["failover_dispatches_total"] = failovers_total
    detail["note"] = (
        "each scenario: fresh 2-replica cluster, fault plan armed "
        "(bqueryd_tpu.chaos), 6-query burst; gate = zero failed queries, "
        "results identical to the fault-free run (ints bit-exact, floats "
        "reassociation-ulp), bounded worst-case wall, and "
        "failover_dispatches > 0 overall (no vacuous pass)"
    )
    if os.environ.get("BENCH_CHAOS_GATE", "1") == "1":
        assert detail["zero_failed_queries"], (
            f"chaos gate: queries failed under fault injection: "
            f"{ {k: v['failed'] for k, v in detail['scenarios'].items()} }"
        )
        for scenario, entry in detail["scenarios"].items():
            assert entry["identical"], (
                f"chaos gate: {scenario} results diverged from the "
                f"fault-free run (float_max_rel_err "
                f"{entry['float_max_rel_err']})"
            )
            assert entry["bounded_p99"], (
                f"chaos gate: {scenario} worst wall {entry['max_wall_s']}s "
                f"blew the bounded-inflation window"
            )
            assert entry["fault_injected"] > 0, (
                f"chaos gate: {scenario} injected no faults — the "
                f"scenario measured nothing"
            )
        assert failovers_total > 0, (
            "chaos gate: failover_dispatches never moved — the failover "
            "path was not exercised (vacuous pass)"
        )
    return detail


def _clear_worker_caches(worker):
    """Cold-path reset: drop the worker's data caches (storage decode,
    alignment, HBM blocks, serialized results).  Compiled XLA programs stay —
    cold means cold data, not a recompile."""
    worker._shed_caches()


def ensure_backend():
    """Probe the default JAX backend in a SUBPROCESS; if it fails or hangs
    (the tunneled TPU backend has been observed down for hours), fall back
    to CPU so the bench completes and records its backend honestly instead
    of dying with rc!=0 and no JSON line.  Subprocess because an in-process
    ``jax.devices()`` on a dead tunnel can block uninterruptibly."""
    import subprocess

    requested = os.environ.get("JAX_PLATFORMS", "")
    if requested and "axon" not in requested and "tpu" not in requested:
        return  # explicitly non-tunnel platform: nothing to probe
    timeout = float(os.environ.get("BENCH_BACKEND_PROBE_TIMEOUT_S", 900))
    # scrub process-local state the parent's jax/axon boot exported —
    # a child seeing _AXON_REGISTERED tries to attach to the parent's
    # relay session and hangs instead of probing cleanly
    env = {
        k: v for k, v in os.environ.items() if k != "_AXON_REGISTERED"
    }
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
            env=env,
        )
        ok = probe.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if ok:
        return
    global BACKEND_FELL_BACK
    BACKEND_FELL_BACK = True
    print(
        "[bench] default backend unavailable; falling back to CPU "
        "(numbers will record backend=cpu)",
        file=sys.stderr,
        flush=True,
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def _autopsy_split(record):
    """Compress an rpc.autopsy record into the dispatch/decode/kernel/merge
    segment split the operators section publishes per wall — so the
    speedup gate can name where remaining time goes instead of recording
    an opaque end-to-end number (the PR-10 machinery, reused)."""
    if not isinstance(record, dict) or not record.get("ok"):
        return None
    buckets = {"dispatch": 0.0, "decode": 0.0, "kernel": 0.0, "merge": 0.0,
               "other": 0.0}
    fold = {
        "admission_wait": "dispatch", "batch_window_wait": "dispatch",
        "plan": "dispatch", "dispatch": "dispatch",
        "retry_backoff": "dispatch", "hedge_dispatch": "dispatch",
        "storage_decode": "decode", "filter": "decode", "align": "decode",
        "join_probe": "decode", "window_rollup": "decode",
        "h2d_transfer": "decode",
        "kernel": "kernel",
        "collective_merge": "merge", "d2h_fetch": "merge",
        "bundle_demux": "merge", "reply_serialization": "merge",
        "client_deserialize": "merge",
    }
    for name, seconds in (record.get("segments") or {}).items():
        buckets[fold.get(name, "other")] += float(seconds)
    out = {k: round(v, 4) for k, v in buckets.items()}
    out["coverage"] = record.get("coverage")
    return out


def _legs_identical(batched, unbatched, sort_cols):
    """Cross-leg parity of the fast path vs the BQUERYD_TPU_DAG_BATCH=0
    per-shard route: ints/datetimes/top-k arrays bit-exact, float columns
    within reassociation tolerance."""
    a = batched.sort_values(sort_cols).reset_index(drop=True)
    b = unbatched.sort_values(sort_cols).reset_index(drop=True)
    if len(a) != len(b) or list(a.columns) != list(b.columns):
        return False
    for col in a.columns:
        va, vb = a[col].to_numpy(), b[col].to_numpy()
        if va.dtype == object and len(va) and isinstance(
            va[0], np.ndarray
        ):
            if not all(
                np.array_equal(np.asarray(x), np.asarray(y))
                for x, y in zip(va, vb)
            ):
                return False
        elif va.dtype.kind == "f":
            if not np.allclose(va, vb, rtol=1e-9, equal_nan=True):
                return False
        elif not np.array_equal(va, vb):
            return False
    return True


def run_operators_section(names, rpc):
    """Operator-DAG executor (plan.dag / parallel.opexec / the PR-15 mesh
    fast path): per-operator sharded walls on the live cluster via
    ``rpc.query``, measured on BOTH legs — the batched fast path (one
    CalcMessage per shard group, device-resident merge) and the
    ``BQUERYD_TPU_DAG_BATCH=0`` per-shard PR-13 route — with gates:
    broadcast-join and top-k parity vs pandas (ints bit-exact), sketch max
    quantile error <= the documented alpha bound, window-rollup parity,
    the plain-DAG bit-identity probe, cross-leg parity (ints bit-exact,
    floats to reassociation), and batched >= 3x unbatched per operator.
    Each batched wall also records its autopsy segment split
    (dispatch/decode/kernel/merge) so the gate names where time goes.
    ``BENCH_OPERATORS_FASTPATH=0`` restores the single-leg PR-13
    measurement (the pre-existing Operator smoke pins it)."""
    import pandas as pd

    from bqueryd_tpu.storage.ctable import ctable

    alpha = 0.01
    fastpath = os.environ.get("BENCH_OPERATORS_FASTPATH", "1") == "1"
    detail = {"alpha": alpha, "fastpath_measured": fastpath,
              "operators": {}}
    cols = [
        "passenger_count", "fare_amount", "PULocationID",
        "trip_distance", "pickup_ts",
    ]
    frames = []
    for name in names:
        t = ctable(os.path.join(DATA_DIR, name), mode="r")
        frames.append(
            pd.DataFrame({c: np.asarray(t.column(c)) for c in cols})
        )
    full = pd.concat(frames, ignore_index=True)

    dim = {
        "PULocationID": np.arange(1, 266, dtype=np.int64),
        "zone": np.array(
            [f"z{i % 5}" for i in range(1, 266)], dtype=object
        ),
    }

    def timed_leg(spec, autopsy=False):
        rpc.query(spec)  # warmup: compile + decode/align caches
        walls = []
        df = None
        for _ in range(2):
            t0 = time.perf_counter()
            df = rpc.query(spec)
            walls.append(time.perf_counter() - t0)
        split = (
            _autopsy_split(rpc.autopsy(rpc.last_trace_id))
            if autopsy else None
        )
        return min(walls), df, split

    def timed(spec, sort_cols=None):
        """Measure the batched leg (+ autopsy split) and, when the fast
        path is under measurement, the BQUERYD_TPU_DAG_BATCH=0 per-shard
        leg — each leg PINNED explicitly and the operator's own env value
        restored after (the PR-7 merge-section precedent)."""
        prev = os.environ.get("BQUERYD_TPU_DAG_BATCH")
        legs = {}
        try:
            if fastpath:
                os.environ["BQUERYD_TPU_DAG_BATCH"] = "0"
                unb_wall, unb_df, _ = timed_leg(spec)
                os.environ["BQUERYD_TPU_DAG_BATCH"] = "1"
                wall, df, split = timed_leg(spec, autopsy=True)
                legs = {
                    "wall_unbatched_s": round(unb_wall, 4),
                    "speedup_vs_unbatched": round(unb_wall / max(wall, 1e-9), 2),
                    "legs_identical": bool(
                        _legs_identical(df, unb_df, sort_cols)
                    ) if sort_cols else None,
                    "merge_modes": dict(rpc.last_call_merge_modes or {}),
                    "autopsy": split,
                }
            else:
                wall, df, _ = timed_leg(spec)
        finally:
            if prev is None:
                os.environ.pop("BQUERYD_TPU_DAG_BATCH", None)
            else:
                os.environ["BQUERYD_TPU_DAG_BATCH"] = prev
        return wall, df, legs

    # -- broadcast hash join ------------------------------------------------
    wall, got, legs = timed({
        "table": list(names), "groupby": ["zone"],
        "aggs": [["fare_amount", "sum", "fare"],
                 ["fare_amount", "count", "n"]],
        "join": {"table": dim, "on": "PULocationID", "select": ["zone"]},
    }, sort_cols=["zone"])
    expj = full.merge(
        pd.DataFrame(dim), on="PULocationID"
    ).groupby("zone")["fare_amount"].agg(["sum", "count"])
    join_ok = (
        dict(zip(got["zone"], got["fare"])) == expj["sum"].to_dict()
        and dict(zip(got["zone"], got["n"])) == expj["count"].to_dict()
    )
    detail["operators"]["join_broadcast"] = {
        "wall_s": round(wall, 4),
        "groups": len(got),
        "dim_rows": len(dim["PULocationID"]),
        "parity_vs_pandas": bool(join_ok),
        **legs,
    }

    # -- per-group top-k ------------------------------------------------------
    wall, got, legs = timed({
        "table": list(names), "groupby": ["passenger_count"],
        "aggs": [["fare_amount", "topk", "top5", {"k": 5}]],
    }, sort_cols=["passenger_count"])
    expk = full.groupby("passenger_count")["fare_amount"].apply(
        lambda s: np.sort(s.to_numpy())[::-1][:5]
    )
    topk_ok = all(
        np.array_equal(np.asarray(got["top5"][i]), expk.loc[g])
        for i, g in enumerate(got["passenger_count"])
    )
    detail["operators"]["topk"] = {
        "wall_s": round(wall, 4),
        "k": 5,
        "groups": len(got),
        "parity_vs_pandas": bool(topk_ok),
        **legs,
    }

    # -- mergeable quantile sketches ----------------------------------------
    wall, got, legs = timed({
        "table": list(names), "groupby": ["passenger_count"],
        "aggs": [
            ["trip_distance", "quantile", "p50",
             {"q": 0.5, "alpha": alpha}],
            ["trip_distance", "quantile", "p99",
             {"q": 0.99, "alpha": alpha}],
        ],
    }, sort_cols=["passenger_count"])
    max_err = 0.0
    for q, col in ((0.5, "p50"), (0.99, "p99")):
        expq = full.groupby("passenger_count")["trip_distance"].quantile(
            q, interpolation="lower"
        )
        for i, g in enumerate(got["passenger_count"]):
            e = float(expq.loc[g])
            rel = abs(float(got[col][i]) - e) / max(abs(e), 1e-9)
            max_err = max(max_err, rel)
    detail["operators"]["quantile_sketch"] = {
        "wall_s": round(wall, 4),
        "quantiles": [0.5, 0.99],
        "groups": len(got),
        "max_rel_err": round(max_err, 6),
        "documented_bound": alpha,
        "within_bound": bool(max_err <= alpha + 1e-9),
        **legs,
    }

    # -- time-window rollup ---------------------------------------------------
    wall, got, legs = timed({
        "table": list(names),
        "groupby": [{"window": {"on": "pickup_ts", "every": "1h",
                                "alias": "hour"}}],
        "aggs": [["fare_amount", "sum", "fare"]],
    }, sort_cols=["hour"])
    exph = full.groupby(
        full["pickup_ts"].dt.floor("1h")
    )["fare_amount"].sum()
    window_ok = (
        dict(zip(pd.to_datetime(got["hour"]), got["fare"]))
        == exph.to_dict()
    )
    detail["operators"]["window_rollup"] = {
        "wall_s": round(wall, 4),
        "every": "1h",
        "windows": len(got),
        "parity_vs_pandas": bool(window_ok),
        **legs,
    }

    # -- plain-DAG bit-identity probe -----------------------------------------
    # the same plain shape through rpc.query (compiles via plan.dag on the
    # worker) and rpc.groupby (classic path): values must be bit-equal —
    # the fuzz corpus proves this per kernel, this probe proves it e2e
    plain_spec = {
        "table": list(names), "groupby": ["passenger_count"],
        "aggs": [["fare_amount", "sum", "fare_amount"]],
    }
    # single-leg measurement: the bit-identity comparison vs rpc.groupby
    # is all this probe needs — the two-leg speedup harness would run
    # three extra full-size rounds whose results are discarded
    _w, via_query, _split = timed_leg(plain_spec)
    via_groupby = rpc.groupby(
        list(names), ["passenger_count"],
        [["fare_amount", "sum", "fare_amount"]], [],
    )
    a = via_query.sort_values("passenger_count").reset_index(drop=True)
    b = via_groupby.sort_values("passenger_count").reset_index(drop=True)
    plain_identical = (
        a["passenger_count"].tolist() == b["passenger_count"].tolist()
        and a["fare_amount"].tolist() == b["fare_amount"].tolist()
    )
    detail["plain_dag_bit_identical"] = bool(plain_identical)
    detail["note"] = (
        "walls are sharded end-to-end rpc.query rounds on the live "
        "cluster (min of 2, warm); wall_s is the batched DAG fast path "
        "(one CalcMessage per shard group + device-resident merge), "
        "wall_unbatched_s the BQUERYD_TPU_DAG_BATCH=0 per-shard PR-13 "
        "route, autopsy the batched wall's attributed segment split; "
        "parity gates: join/topk/window ints bit-exact vs pandas, sketch "
        "max relative quantile error <= alpha vs pandas "
        "interpolation='lower', legs bit-identical (ints) across the "
        "kill switch, plain groupby bit-identical through the DAG path, "
        "and batched >= 3x unbatched per operator"
    )
    speed_line = ""
    if fastpath:
        speed_line = " speedups " + "/".join(
            str(detail["operators"][op].get("speedup_vs_unbatched"))
            for op in ("join_broadcast", "topk", "quantile_sketch",
                       "window_rollup")
        )
    print(
        f"[bench] operators: join {detail['operators']['join_broadcast']['wall_s']}s "
        f"(parity {join_ok}), topk "
        f"{detail['operators']['topk']['wall_s']}s (parity {topk_ok}), "
        f"quantile {detail['operators']['quantile_sketch']['wall_s']}s "
        f"(max_rel_err {max_err:.5f} <= {alpha}), window "
        f"{detail['operators']['window_rollup']['wall_s']}s "
        f"(parity {window_ok}), plain-DAG identical {plain_identical}"
        f"{speed_line}",
        file=sys.stderr, flush=True,
    )
    if os.environ.get("BENCH_OPERATORS_GATE", "1") == "1":
        assert join_ok, "operators gate: broadcast-join parity vs pandas"
        assert topk_ok, "operators gate: top-k parity vs pandas"
        assert detail["operators"]["quantile_sketch"]["within_bound"], (
            f"operators gate: sketch max quantile error {max_err} above "
            f"the documented bound {alpha}"
        )
        assert window_ok, "operators gate: window-rollup parity vs pandas"
        assert plain_identical, (
            "operators gate: plain groupby through the DAG path diverged"
        )
        if fastpath and os.environ.get(
            "BENCH_OPERATORS_SPEEDUP_GATE", "1"
        ) == "1":
            # the >= 3x acceptance floor is stated at the full 10M-row
            # config, where the per-query fixed floor (wire, program
            # dispatch) is negligible; reduced-rows smokes gate at 2x —
            # note the =0 leg runs the CURRENT per-shard code, which
            # shares this PR's faster top-k kernels, so the live-leg
            # ratio understates the gain over the recorded r14 walls
            # (join 8.59s / topk 13.37s / quantile 7.09s / window 9.61s)
            floor = 3.0 if ROWS >= 5_000_000 else 2.0
            # recorded r14 walls at the full 10M sharded config: the
            # acceptance comparator (the pre-fast-path per-shard route
            # WITH its pre-PR-15 kernels)
            r14 = {"join_broadcast": 8.59, "topk": 13.37,
                   "quantile_sketch": 7.09, "window_rollup": 9.61}
            for op in ("join_broadcast", "topk", "quantile_sketch",
                       "window_rollup"):
                entry = detail["operators"][op]
                if ROWS >= 5_000_000:
                    entry["r14_wall_s"] = r14[op]
                    entry["speedup_vs_r14"] = round(
                        r14[op] / max(entry["wall_s"], 1e-9), 2
                    )
                    assert entry["speedup_vs_r14"] >= 3.0, (
                        f"operators gate: {op} fast path "
                        f"{entry['wall_s']}s not 3x faster than the r14 "
                        f"baseline {r14[op]}s"
                    )
                assert entry.get("legs_identical"), (
                    f"operators gate: {op} batched leg diverged from the "
                    f"BQUERYD_TPU_DAG_BATCH=0 per-shard leg"
                )
                assert "device" in (entry.get("merge_modes") or {}).values(), (
                    f"operators gate: {op} batched leg did not device-merge"
                )
                speedup = entry.get("speedup_vs_unbatched") or 0.0
                assert speedup >= floor, (
                    f"operators gate: {op} fast path {speedup}x < {floor}x "
                    f"the per-shard route "
                    f"({entry['wall_s']}s vs {entry['wall_unbatched_s']}s)"
                )
    return detail


def _ingest_cluster(data_dir, coord_tag, n_shards, n_workers=1,
                    worker_dirs=None, rpc_timeout=120, **controller_kw):
    """Fresh controller + N calc workers over a section-owned dataset: the
    shared bootstrap of the chaos scenarios (replica topology over the
    bench dataset) and the ingest section (its own directory — appends
    must never mutate the shared bench data).  Waits until every shard is
    advertised by every worker; a bring-up timeout stops the half-started
    nodes before raising (orphaned daemon threads would keep heartbeating
    under every later section)."""
    from bqueryd_tpu.controller import ControllerNode
    from bqueryd_tpu.rpc import RPC
    from bqueryd_tpu.worker import WorkerNode

    url = f"mem://{coord_tag}-{os.urandom(4).hex()}"
    controller = ControllerNode(
        coordination_url=url,
        loglevel=logging.WARNING,
        runfile_dir=data_dir,
        heartbeat_interval=0.1,
        **controller_kw,
    )
    dirs = worker_dirs or [data_dir] * n_workers
    workers = [
        WorkerNode(
            coordination_url=url,
            data_dir=d,
            loglevel=logging.WARNING,
            restart_check=False,
            heartbeat_interval=0.25,
            poll_timeout=0.05,
        )
        for d in dirs
    ]
    nodes = [controller] + workers
    threads = [
        threading.Thread(target=node.go, daemon=True) for node in nodes
    ]
    for t in threads:
        t.start()
    deadline = time.time() + 120
    while time.time() < deadline:
        # list(): the controller thread mutates files_map during worker
        # registration while this poll iterates it
        if len(controller.files_map) >= n_shards and all(
            len(h) >= n_workers for h in list(controller.files_map.values())
        ):
            break
        time.sleep(0.05)
    else:
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)
        raise RuntimeError(
            f"{coord_tag} cluster never reached its replica topology"
        )
    rpc = RPC(
        coordination_url=url, timeout=rpc_timeout, loglevel=logging.WARNING
    )
    return rpc, controller, workers, nodes, threads


def _ingest_frame(rng, rows, seq_offset):
    import pandas as pd

    return pd.DataFrame(
        {
            "g": rng.randint(0, 7, rows).astype(np.int64),
            "v": rng.randint(-10000, 10000, rows).astype(np.int64),
            "f": rng.random(rows).astype(np.float32),
            # per-shard-monotonic: the zone-map pruning axis (real streams
            # are approximately time-ordered, which is exactly what makes
            # chunk min/max discriminating)
            "seq": np.arange(
                seq_offset, seq_offset + rows, dtype=np.int64
            ),
        }
    )


def _ingest_frames_match(a, b, int_cols, float_cols):
    """(ints_bitexact, floats_bitexact, float_max_rel_err)"""
    ints = all(
        np.array_equal(a[c].to_numpy(), b[c].to_numpy()) for c in int_cols
    ) and np.array_equal(a["g"].to_numpy(), b["g"].to_numpy())
    fbit = all(
        np.array_equal(a[c].to_numpy(), b[c].to_numpy())
        for c in float_cols
    )
    max_rel = 0.0
    for c in float_cols:
        x = a[c].to_numpy(dtype=np.float64)
        y = b[c].to_numpy(dtype=np.float64)
        with np.errstate(all="ignore"):
            rel = (
                np.nanmax(np.abs(x - y) / np.maximum(np.abs(y), 1e-30))
                if len(x) else 0.0
            )
        max_rel = max(max_rel, float(rel))
    return ints, fbit, max_rel


def run_ingest_section():
    """Streaming ingest (PR 14): the three acceptance gates.

    (a) **delta-maintained repeat**: after a <=10% append, the repeat query
        is served by aggregating only the appended chunks and merging the
        delta partial — gated >= 3x faster than the cold full recompute of
        the same post-append data, ints bit-exact / floats within
        reassociation ulps vs that recompute;
    (b) **chunk-granular zone-map pruning**: a filter matching ~8% of the
        per-shard-monotonic ``seq`` axis decodes <= 25% of chunks
        (worker chunk counters), results bit-identical to the
        ``BQUERYD_TPU_CHUNK_PRUNE=0`` path;
    (c) **append-while-querying under chaos**: a 2-replica cluster absorbs
        appends + queries across a die_after_ack worker kill with ZERO
        failed queries and int-bit-exact results vs the expected frame.

    Runs over its own dataset/clusters (appends must not mutate the shared
    bench dataset); gates assert unless BENCH_INGEST_GATE=0.
    """
    import shutil

    import pandas as pd

    gate_on = os.environ.get("BENCH_INGEST_GATE", "1") == "1"
    detail = {}
    rows_ingest = min(ROWS, 2_000_000)
    n_shards = 4
    per = rows_ingest // n_shards
    chunklen = max(4096, per // 24)
    base_dir = os.path.join(DATA_DIR, "ingest")
    shutil.rmtree(base_dir, ignore_errors=True)
    os.makedirs(base_dir, exist_ok=True)
    from bqueryd_tpu.storage.ctable import ctable

    rng = np.random.RandomState(23)
    names = [f"ing_{i}.bcolzs" for i in range(n_shards)]
    frames = {}
    for name in names:
        df = _ingest_frame(rng, per, 0)
        frames[name] = df
        ctable.fromdataframe(
            df, os.path.join(base_dir, name), chunklen=chunklen
        )
    detail["rows"] = rows_ingest
    detail["shards"] = n_shards
    detail["chunklen"] = chunklen

    q = (
        list(names), ["g"],
        [["v", "sum", "vs"], ["f", "mean", "fm"], ["v", "min", "vmin"]],
        [],
    )

    def run_query(rpc, query):
        t0 = time.perf_counter()
        df = rpc.groupby(*query)
        return time.perf_counter() - t0, df.sort_values("g").reset_index(
            drop=True
        )

    rpc, controller, workers, nodes, threads = _ingest_cluster(
        base_dir, "ingest", n_shards
    )
    try:
        worker = workers[0]
        # -- (a) delta-maintained repeat vs cold recompute ----------------
        run_query(rpc, q)  # establishes the delta base
        # two append+refresh cycles: the FIRST delta refresh may compile
        # the tail's program shape (a one-time cost, exactly like the main
        # configs' warmup); the SECOND cycle is the steady-state serving
        # wall the gate measures — still a real refresh over fresh rows
        # (each cycle's append grows the tables again).  Total appended
        # stays <= 10% of the base.
        append_rows = max(per // 24, 1)  # ~4% per shard per cycle
        append_wall = 0.0
        delta_walls = []
        delta_refreshes = 0
        seq_base = per
        for _cycle in range(2):
            t_append = time.perf_counter()
            for name in names:
                extra = _ingest_frame(rng, append_rows, seq_base)
                frames[name] = pd.concat(
                    [frames[name], extra], ignore_index=True
                )
                rpc.append(name, extra)
            seq_base += append_rows
            append_wall += time.perf_counter() - t_append
            refreshes_before = worker.delta_refreshes_total.value
            wall, delta_df = run_query(rpc, q)
            delta_walls.append(wall)
            delta_refreshes += int(
                worker.delta_refreshes_total.value - refreshes_before
            )
        delta_wall = delta_walls[-1]
        routes = set(
            (rpc.last_call_strategies or {}).get("effective", {}).values()
        )
        # cold full recompute of the SAME post-append data
        _clear_worker_caches(worker)
        cold_wall, cold_df = run_query(rpc, q)
        ints_ok, _fbit, max_rel = _ingest_frames_match(
            delta_df, cold_df, ["vs", "vmin"], ["fm"]
        )
        speedup = cold_wall / max(delta_wall, 1e-9)
        detail["delta"] = {
            "append_rows_per_shard": 2 * append_rows,
            "append_fraction": round(2 * append_rows / per, 4),
            "append_wall_s": round(append_wall, 4),
            "delta_walls_s": [round(w, 4) for w in delta_walls],
            "delta_wall_s": round(delta_wall, 4),
            "cold_wall_s": round(cold_wall, 4),
            "speedup": round(speedup, 2),
            "delta_refreshes": delta_refreshes,
            "routes": sorted(routes),
            "ints_bitexact": bool(ints_ok),
            "float_max_rel_err": max_rel,
        }
        print(
            f"[bench] ingest delta: cold {cold_wall:.3f}s vs delta "
            f"{delta_wall:.3f}s ({speedup:.1f}x), refreshes "
            f"{delta_refreshes}, ints_bitexact {ints_ok}",
            flush=True,
        )

        # -- (b) chunk-granular zone-map pruning --------------------------
        total_seq = per + 2 * append_rows
        threshold = int(total_seq * 0.92)  # ~8% of every shard matches
        qf = (
            list(names), ["g"],
            [["v", "sum", "vs"], ["f", "mean", "fm"]],
            [["seq", ">", threshold]],
        )
        dec0 = worker.chunks_decoded_total.value
        skip0 = worker.chunks_skipped_total.value
        pruned_wall, pruned_df = run_query(rpc, qf)
        decoded = worker.chunks_decoded_total.value - dec0
        skipped = worker.chunks_skipped_total.value - skip0
        decode_fraction = decoded / max(decoded + skipped, 1)
        os.environ["BQUERYD_TPU_CHUNK_PRUNE"] = "0"
        try:
            _clear_worker_caches(worker)
            unpruned_wall, unpruned_df = run_query(rpc, qf)
        finally:
            os.environ.pop("BQUERYD_TPU_CHUNK_PRUNE", None)
        p_ints, p_fbit, p_rel = _ingest_frames_match(
            pruned_df, unpruned_df, ["vs"], ["fm"]
        )
        full_frame = pd.concat(frames.values(), ignore_index=True)
        match_fraction = float(
            (full_frame["seq"] > threshold).mean()
        )
        detail["prune"] = {
            "filter_match_fraction": round(match_fraction, 4),
            "chunks_decoded": int(decoded),
            "chunks_skipped": int(skipped),
            "decode_fraction": round(decode_fraction, 4),
            "pruned_wall_s": round(pruned_wall, 4),
            "unpruned_wall_s": round(unpruned_wall, 4),
            "ints_bitexact": bool(p_ints),
            "floats_bitexact": bool(p_fbit),
            "float_max_rel_err": p_rel,
        }
        print(
            f"[bench] ingest prune: decoded {decoded}/{decoded + skipped} "
            f"chunks ({decode_fraction:.2%}) for a "
            f"{match_fraction:.2%}-selective filter; bitexact "
            f"ints={p_ints} floats={p_fbit}",
            flush=True,
        )
    finally:
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)
        try:
            rpc._close_socket()
        except Exception:
            pass

    # -- (c) append-while-querying under the chaos harness ----------------
    from bqueryd_tpu import chaos as chaos_mod

    rows_chaos = max(per // 2, 5000)
    rep_dirs = [os.path.join(base_dir, "rep_a"), os.path.join(base_dir, "rep_b")]
    for d in rep_dirs:
        os.makedirs(d, exist_ok=True)
    rng_c = np.random.RandomState(29)
    chaos_frame = _ingest_frame(rng_c, rows_chaos, 0)
    ctable.fromdataframe(
        chaos_frame, os.path.join(rep_dirs[0], "rep.bcolzs"),
        chunklen=chunklen,
    )
    shutil.copytree(
        os.path.join(rep_dirs[0], "rep.bcolzs"),
        os.path.join(rep_dirs[1], "rep.bcolzs"),
    )
    rpc, controller, workers, nodes, threads = _ingest_cluster(
        rep_dirs[0], "ingest-chaos", 1, n_workers=2,
        worker_dirs=rep_dirs,
        dead_worker_timeout=2.0, dispatch_timeout=2.0,
        dispatch_hard_timeout=8.0,
    )
    qc = (["rep.bcolzs"], ["g"], [["v", "sum", "vs"]], [])
    failed = 0
    parity_ok = True
    try:
        expected = chaos_frame.groupby("g")["v"].sum().to_dict()

        def check(df):
            return dict(zip(df["g"].tolist(), df["vs"].tolist())) == expected

        _w, df0 = run_query(rpc, qc)
        parity_ok = parity_ok and check(df0)
        extra = _ingest_frame(rng_c, rows_chaos // 10, rows_chaos)
        rpc.append("rep.bcolzs", extra)
        chaos_frame = pd.concat([chaos_frame, extra], ignore_index=True)
        expected = chaos_frame.groupby("g")["v"].sum().to_dict()
        chaos_mod.arm({
            "seed": 3,
            "faults": [{
                "site": "worker.execute",
                "action": "die_after_ack",
                "match": {"verb": "groupby"},
                "times": 1,
            }],
        })
        injected0 = chaos_mod.injected_total()
        for _ in range(3):
            try:
                _w, dfc = run_query(rpc, qc)
            except Exception as exc:
                failed += 1
                print(
                    f"[bench] ingest chaos query FAILED: {exc!r}",
                    file=sys.stderr, flush=True,
                )
                continue
            parity_ok = parity_ok and check(dfc)
        chaos_mod.disarm()
        detail["chaos"] = {
            "failed_queries": failed,
            "parity_ok": bool(parity_ok),
            "fault_injected": chaos_mod.injected_total() - injected0,
            "failover_dispatches": int(
                controller.counters["failover_dispatches"]
            ),
        }
        print(
            f"[bench] ingest chaos: {failed} failed queries, parity "
            f"{parity_ok}, failovers "
            f"{detail['chaos']['failover_dispatches']}",
            flush=True,
        )
    finally:
        chaos_mod.disarm()
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)
        try:
            rpc._close_socket()
        except Exception:
            pass

    gates = {
        "delta_speedup_ge_3x": detail["delta"]["speedup"] >= 3.0,
        "delta_ints_bitexact": detail["delta"]["ints_bitexact"],
        "delta_float_ulps": detail["delta"]["float_max_rel_err"] < 1e-9,
        "delta_refreshed": detail["delta"]["delta_refreshes"] >= 1,
        "prune_decode_le_25pct": detail["prune"]["decode_fraction"] <= 0.25,
        "prune_bitexact": (
            detail["prune"]["ints_bitexact"]
            and detail["prune"]["floats_bitexact"]
        ),
        "chaos_zero_failed": detail["chaos"]["failed_queries"] == 0,
        "chaos_parity": detail["chaos"]["parity_ok"],
        "chaos_failover_ran": detail["chaos"]["failover_dispatches"] >= 1,
    }
    detail["gates"] = gates
    if gate_on:
        bad = sorted(k for k, ok in gates.items() if not ok)
        assert not bad, f"ingest gates failed: {bad} — {detail}"
    return detail


def run_serving_section():
    """Semantic serving (PR 16): the acceptance gates.

    An 8-client zipf-weighted swarm over overlapping groupby shapes — one
    hot ANCHOR view keyed finer than every satellite — runs twice over the
    same 400k-row dataset: once with serving enabled (the anchor rollup
    materializes once, the satellites are answered by key-fold /
    agg-projection / zone-proof subsumption from it) and once forced to
    recompute via the documented kill switch (``BQUERYD_TPU_SERVE=0``).
    Gates (``BENCH_SERVING_GATE=0`` records without asserting):

    * both ``rollup`` and ``subsume`` answer sources fire during the
      serving leg;
    * per-shape parity vs the forced-recompute leg — ints bit-exact,
      floats within re-aggregation ulps;
    * serving-leg QPS >= 5x the forced-recompute leg;
    * the kill-switch leg serves zero rollup/subsume answers and repeats
      bit-identically (the exact-signature-only PR-15 behaviour).

    Runs over its own dataset/cluster; main-measurement clusters pin
    ``BQUERYD_TPU_SERVE=0`` (see start_cluster) so rollups can never
    short-circuit the walls the other sections measure.
    """
    import shutil

    import pandas as pd

    gate_on = os.environ.get("BENCH_SERVING_GATE", "1") == "1"
    detail = {}
    rows_serving = min(ROWS, 400_000)
    n_shards = 2
    per = rows_serving // n_shards
    chunklen = max(4096, per // 16)
    base_dir = os.path.join(DATA_DIR, "serving")
    shutil.rmtree(base_dir, ignore_errors=True)
    os.makedirs(base_dir, exist_ok=True)
    from bqueryd_tpu.storage.ctable import ctable

    rng = np.random.RandomState(29)
    names = [f"srv_{i}.bcolzs" for i in range(n_shards)]
    for i, name in enumerate(names):
        df = pd.DataFrame(
            {
                "g": rng.randint(0, 8, per).astype(np.int64),
                "g2": rng.randint(0, 4, per).astype(np.int64),
                "v": rng.randint(-10000, 10000, per).astype(np.int64),
                "f": rng.random(per).astype(np.float32),
                # per-shard-monotonic: the zone-proof axis
                "seq": np.arange(i * per, (i + 1) * per, dtype=np.int64),
            }
        )
        ctable.fromdataframe(
            df, os.path.join(base_dir, name), chunklen=chunklen
        )
    detail["rows"] = rows_serving
    detail["shards"] = n_shards

    aggs = [["v", "sum", "vs"], ["f", "mean", "fm"], ["v", "min", "vmin"]]
    # the anchor is keyed finer than every satellite: ONE materialized
    # rollup provably answers all of them through the lattice
    pool = [
        ("anchor", (list(names), ["g", "g2"], aggs, [])),
        ("coarse", (list(names), ["g"], aggs, [])),
        ("zone", (list(names), ["g", "g2"], aggs, [["seq", ">=", 0]])),
        ("project", (list(names), ["g"], [["v", "sum", "vs"]], [])),
        ("coarse2", (list(names), ["g2"], aggs, [])),
    ]
    weights = np.array([0.4, 0.2, 0.15, 0.15, 0.1])

    def frames_close(sa, sb, keys, agg_list):
        """(ints_bitexact, float_max_rel_err) over one answer pair."""
        ints = all(
            np.array_equal(sa[k].to_numpy(), sb[k].to_numpy()) for k in keys
        )
        rel = 0.0
        for _col, op, out in agg_list:
            x = sa[out].to_numpy()
            y = sb[out].to_numpy()
            if op == "mean":
                with np.errstate(all="ignore"):
                    r = (
                        float(
                            np.nanmax(
                                np.abs(
                                    x.astype(np.float64)
                                    - y.astype(np.float64)
                                )
                                / np.maximum(
                                    np.abs(y.astype(np.float64)), 1e-30
                                )
                            )
                        )
                        if len(x) else 0.0
                    )
                rel = max(rel, r)
            else:
                ints = ints and np.array_equal(x, y)
        return ints, rel

    prior_env = {
        k: os.environ.get(k)
        for k in (
            "BQUERYD_TPU_SERVE",
            "BQUERYD_TPU_ROLLUP_HEAT_MIN",
            "BQUERYD_TPU_RESULT_CACHE_BYTES",
        )
    }
    # the gate compares against FORCED recompute: with the worker's
    # exact-signature result cache on, the kill-switch leg would measure
    # cache lookups (only 5 distinct shapes in the pool), not the engine
    os.environ["BQUERYD_TPU_RESULT_CACHE_BYTES"] = "0"
    rpc, controller, workers, nodes, threads = _ingest_cluster(
        base_dir, "serving", n_shards
    )
    try:
        # the cost model refuses to serve before stats advertise; the
        # one-shot WRM advertisement has a 60s re-send window, so force it
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(
                (controller.shard_stats.get(n) or {}).get("rows") == per
                for n in names
            ):
                break
            for w in workers:
                w._stats_sent_ts = 0.0
            time.sleep(0.05)
        else:
            raise RuntimeError("serving stats never advertised")

        def swarm(n_clients=8, per_client=24, seed=101):
            from bqueryd_tpu.rpc import RPC as _RPC

            walls = [None] * n_clients
            tallies = [None] * n_clients
            frames = [None] * n_clients
            errors = []

            def client(ci):
                r = np.random.RandomState(seed + ci)
                try:
                    cli = _RPC(
                        coordination_url=controller.store.url,
                        timeout=RPC_TIMEOUT, loglevel=logging.WARNING,
                    )
                    tally, got = {}, {}
                    t0 = time.perf_counter()
                    for _ in range(per_client):
                        qname, q = pool[r.choice(len(pool), p=weights)]
                        df = cli.groupby(*q)
                        src = cli.last_call_answer_source or "recompute"
                        tally[src] = tally.get(src, 0) + 1
                        got[qname] = df
                    walls[ci] = time.perf_counter() - t0
                    tallies[ci] = tally
                    frames[ci] = got
                    cli._close_socket()
                except Exception as exc:
                    errors.append(repr(exc))

            ts = [
                threading.Thread(target=client, args=(i,))
                for i in range(n_clients)
            ]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            elapsed = time.perf_counter() - t0
            if errors:
                raise RuntimeError(f"swarm client errors: {errors[:3]}")
            sources, merged = {}, {}
            for t_ in tallies:
                for k, v in (t_ or {}).items():
                    sources[k] = sources.get(k, 0) + v
            for fr in frames:
                for k, v in (fr or {}).items():
                    merged.setdefault(k, v)
            return n_clients * per_client / elapsed, sources, merged

        # -- serving leg: materialize the anchor, then the swarm ----------
        # HEAT_MIN=1: the first anchor query crosses the threshold (EWMA
        # decay puts N spaced hits fractionally under N, so an integer
        # threshold of 2 would need 3 queries)
        os.environ["BQUERYD_TPU_SERVE"] = "1"
        os.environ["BQUERYD_TPU_ROLLUP_HEAT_MIN"] = "1"
        q_anchor = pool[0][1]
        rpc.groupby(*q_anchor)
        deadline = time.time() + 60
        while time.time() < deadline:
            if any(
                e.state == "ready"
                for e in list(controller.serving.manager.entries.values())
            ):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("anchor rollup never materialized")
        # freeze further materialization: the satellites must stay
        # SUBSUMED from the anchor (the lattice is what's measured), not
        # grow their own exact rollups mid-swarm
        os.environ["BQUERYD_TPU_ROLLUP_HEAT_MIN"] = "1e18"
        qps_serving, sources_serving, frames_serving = swarm()

        # -- forced-recompute leg (the documented kill switch) ------------
        os.environ["BQUERYD_TPU_SERVE"] = "0"
        qps_recompute, sources_recompute, frames_recompute = swarm(seed=202)

        # kill-switch determinism probe: the repeat is bit-identical (the
        # exact-signature-only PR-15 path, nothing served)
        ka = rpc.groupby(*q_anchor).sort_values(
            ["g", "g2"]
        ).reset_index(drop=True)
        kb = rpc.groupby(*q_anchor).sort_values(
            ["g", "g2"]
        ).reset_index(drop=True)
        kill_ints, kill_rel = frames_close(ka, kb, ["g", "g2"], aggs)
        kill_identical = kill_ints and kill_rel == 0.0

        ints_ok, fmax = True, 0.0
        for qname, (_n, keys, qa, _w) in pool:
            if qname not in frames_serving or qname not in frames_recompute:
                continue
            sa = frames_serving[qname].sort_values(keys).reset_index(
                drop=True
            )
            sb = frames_recompute[qname].sort_values(keys).reset_index(
                drop=True
            )
            ints, rel = frames_close(sa, sb, keys, qa)
            ints_ok = ints_ok and ints
            fmax = max(fmax, rel)

        detail["swarm"] = {
            "clients": 8,
            "queries_per_client": 24,
            "serving_qps": round(qps_serving, 2),
            "recompute_qps": round(qps_recompute, 2),
            "qps_ratio": round(qps_serving / qps_recompute, 3),
            "sources_serving": sources_serving,
            "sources_recompute": sources_recompute,
        }
        detail["parity"] = {
            "ints_bitexact": ints_ok,
            "float_max_rel_err": fmax,
        }
        detail["kill_switch"] = {
            "bit_identical_repeat": kill_identical,
            "sources": sources_recompute,
        }
        detail["rollup_builds"] = int(controller.counters["rollup_builds"])
        detail["note"] = (
            "8-client zipf swarm over 5 overlapping groupby shapes; one "
            "anchor rollup (keys g,g2) answers the satellites via "
            "key-fold/agg-projection/zone-proof subsumption.  Gates: "
            "rollup+subsume hits > 0, serving QPS >= 5x forced recompute, "
            "ints bit-exact / floats to re-aggregation ulps, "
            "BQUERYD_TPU_SERVE=0 leg serves nothing and repeats "
            "bit-identically"
        )
        print(
            f"[bench] serving: {qps_serving:.1f} qps vs recompute "
            f"{qps_recompute:.1f} qps "
            f"({qps_serving / qps_recompute:.1f}x), sources "
            f"{sources_serving}, parity ints {ints_ok} "
            f"float_rel {fmax:.2e}",
            flush=True,
        )
    finally:
        for k, v in prior_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)
        try:
            rpc._close_socket()
        except Exception:
            pass

    gates = {
        "rollup_hits_gt_0": sources_serving.get("rollup", 0) > 0,
        "subsume_hits_gt_0": sources_serving.get("subsume", 0) > 0,
        "parity_ints_bitexact": ints_ok,
        "parity_float_ulps": fmax < 2e-5,
        "serving_qps_ge_5x": qps_serving >= 5.0 * qps_recompute,
        "kill_switch_no_serving": (
            sources_recompute.get("rollup", 0) == 0
            and sources_recompute.get("subsume", 0) == 0
        ),
        "kill_switch_deterministic": kill_identical,
    }
    detail["gates"] = gates
    if gate_on:
        bad = sorted(k for k, ok in gates.items() if not ok)
        assert not bad, f"serving gates failed: {bad} — {detail}"
    return detail


def main():
    t_start = time.time()
    # arrow-backed string inference (pandas 3 default) intermittently
    # segfaults in libarrow 25.0 on this class of host; the benchmark's
    # data is numeric either way, so measurements are unaffected and the
    # round-end number must never die to a string-Index conversion
    try:
        import pandas as pd

        pd.set_option("future.infer_string", False)
    except Exception:
        pass
    ensure_backend()
    names = build_dataset()
    rpc, nodes, threads = start_cluster()
    worker = nodes[1]
    results = {}
    cold_enabled = os.environ.get("BENCH_COLD", "1") == "1"
    # the main-loop configs measure the default XLA kernel path; a pre-set
    # opt-in flag would silently turn the route-vs-route comparisons below
    # (xla-vs-pallas, scatter-vs-forced-matmul, adaptive-vs-static) into
    # self-comparisons — or, for a pre-set BQUERYD_TPU_PLANNER=0, let the
    # per-repeat pop in the planner section clobber the user's setting and
    # mix routes mid-measurement
    prior_env = {
        flag: os.environ.pop(flag, None)
        for flag in (
            "BQUERYD_TPU_PALLAS",
            "BQUERYD_TPU_FORCE_MATMUL",
            "BQUERYD_TPU_PLANNER",
            # a pre-pinned pool width would turn the pipeline section's
            # serialized-vs-pipelined comparison into a self-comparison
            "BQUERYD_TPU_PIPELINE_THREADS",
            # an armed fault plan would inject into the MAIN measurement
            # clusters; the chaos section arms its own plans per scenario
            "BQUERYD_TPU_FAULT_PLAN",
        )
    }
    base_dfs = {}  # per-config baseline frames for the variant gates
    try:
        import jax

        floor_s = None

        def measure_config(config, out):
            # writes into ``out``, NOT ``results``: a watchdog-abandoned
            # thread that later completes must not mutate the dict the main
            # thread is iterating for emission
            nonlocal floor_s
            from bqueryd_tpu.utils import devicehealth

            wedge_start = devicehealth.wedge_marker()
            files, gcols, aggs, where = config_query(config, names)
            nrows = ROWS * len(files) // SHARDS
            # warmup: storage decode, XLA compile, HBM/alignment caches.
            # The very first of these also absorbs TPU backend bring-up
            # (many minutes on a tunneled backend), so log its duration.
            t_w = time.perf_counter()
            rpc.groupby(files, gcols, aggs, where)
            warm_s = time.perf_counter() - t_w
            print(
                f"[bench] {config}: warmup query took {warm_s:.1f}s",
                file=sys.stderr,
                flush=True,
            )
            if floor_s is None:
                # measured after the first warmup so backend bring-up is done
                floor_s = device_roundtrip_floor()
                print(
                    f"[bench] device dispatch+fetch floor: "
                    f"{floor_s*1e3:.1f} ms",
                    file=sys.stderr,
                    flush=True,
                )
            repeats = []  # (wall, phase timings of THAT repeat)
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                result = rpc.groupby(files, gcols, aggs, where)
                repeats.append(
                    (
                        time.perf_counter() - t0,
                        getattr(rpc, "last_call_timings", None),
                    )
                )
            our_wall, our_timings = min(repeats, key=lambda r: r[0])

            cold_wall = cold_timings = None
            if cold_enabled:
                _clear_worker_caches(worker)
                t0 = time.perf_counter()
                rpc.groupby(files, gcols, aggs, where)
                cold_wall = time.perf_counter() - t0
                cold_timings = getattr(rpc, "last_call_timings", None)

            # symmetric measurement: one warmup (page cache) + REPEATS timed
            # for the baseline, same as the framework side
            reference_shaped_baseline(files, gcols, aggs, where)
            base_walls, base_df = [], None
            for _ in range(REPEATS):
                wall, base_df = reference_shaped_baseline(
                    files, gcols, aggs, where
                )
                base_walls.append(wall)
            base_wall = min(base_walls)
            base_dfs[config] = base_df
            check_result(result, base_df, gcols, aggs, config)
            worker_total = _phase_total(our_timings)
            out[config] = {
                "rows": nrows,
                "groups": len(base_df),
                "framework_wall_s": round(our_wall, 4),
                "warmup_wall_s": round(warm_s, 2),
                "cold_wall_s": (
                    None if cold_wall is None else round(cold_wall, 4)
                ),
                "reference_shaped_wall_s": round(base_wall, 4),
                "rows_per_sec": round(nrows / our_wall, 1),
                "speedup": round(base_wall / our_wall, 3),
                # per-phase breakdown (open/decode/H2D/kernel/collect/...)
                # measured on the worker, from the SAME repeat as the
                # reported min wall (round-3 verdict: last-repeat timings
                # against min-repeat walls made the data self-contradictory)
                "phase_timings": our_timings,
                "cold_phase_timings": cold_timings,
                # evidence integrity: if a wedge OVERLAPPED this config's
                # window (even one that recovered before this line), the
                # devicehealth latch may have served HOST kernels — a wall
                # recorded with this flag true is not a device number
                "backend_wedged": devicehealth.window_dirty(wedge_start),
                # client wall minus worker phase total = zmq + controller +
                # pickle overhead; compare with device_roundtrip_floor_s
                "worker_phase_total_s": worker_total,
                "dispatch_gap_s": (
                    None
                    if worker_total is None
                    else round(our_wall - worker_total, 4)
                ),
            }
            print(
                f"[bench] {config}: {nrows / our_wall:,.0f} rows/s "
                f"(framework {our_wall:.3f}s vs baseline {base_wall:.3f}s, "
                f"speedup {base_wall / our_wall:.2f}x"
                + (
                    f", cold {cold_wall:.3f}s"
                    if cold_wall is not None
                    else ""
                )
                + ")",
                file=sys.stderr,
                flush=True,
            )

        wedged = False
        for i, config in enumerate(CONFIGS):
            # watchdog: one wedged query (tunnel death mid-run) must not
            # hold the whole benchmark hostage for RPC_TIMEOUT — mark the
            # config timed_out, stop measuring (the worker's calc thread is
            # stuck, so later configs would wedge too) and emit what exists
            budget = FIRST_CONFIG_TIMEOUT if i == 0 else CONFIG_TIMEOUT
            box = {}

            def run_one(config=config):
                try:
                    measure_config(config, box.setdefault("out", {}))
                except BaseException as exc:  # re-raised on the main thread
                    box["exc"] = exc

            th = threading.Thread(target=run_one, daemon=True)
            th.start()
            th.join(budget)
            if th.is_alive():
                results[config] = {"timed_out": True, "budget_s": budget}
                print(
                    f"[bench] {config}: no result within {budget:.0f}s — "
                    f"backend wedged; emitting completed configs only",
                    file=sys.stderr,
                    flush=True,
                )
                wedged = True
                break
            if "exc" in box:
                raise box["exc"]
            results.update(box.get("out", {}))

        # kernel-route variants of the headline config: each re-runs the
        # same query with one route flag flipped (the flags are read per
        # call in the un-jitted dispatcher, so a runtime toggle re-routes
        # the identical query) and applies the same bit-exactness gate.
        #   pallas        — the fused one-hot Pallas kernel (VERDICT r3 #6)
        #   forced_matmul — the MXU limb-matmul path, which auto-disables
        #                   on CPU backends; forcing it here gives the
        #                   exact limb+recombination pipeline bench-scale
        #                   coverage without a TPU (VERDICT r4 weak #1).
        #                   Skipped on TPU where it IS the default route.
        completed = {
            name
            for name, r in results.items()
            if "framework_wall_s" in r
        }
        variants = []
        if os.environ.get("BENCH_PALLAS", "1") == "1":
            if jax.default_backend() == "tpu":
                variants.append((HEADLINE, "pallas", "BQUERYD_TPU_PALLAS"))
                # the group-tiled hicard Pallas kernel vs the blocked
                # scatter at 70k groups (route-decision data: the pre-fix
                # hardware sample for the scatter was 0.583 s)
                variants.append(
                    ("highcard", "pallas", "BQUERYD_TPU_PALLAS")
                )
            else:
                # Pallas rides the matmul route, which auto-disables off-TPU:
                # on a CPU backend the flag would silently re-measure the
                # scatter path and record it as a pallas data point (r4's
                # sharded_pallas entry was exactly that sham)
                print(
                    "[bench] pallas variant skipped: needs a tpu backend",
                    file=sys.stderr,
                    flush=True,
                )
        if (
            os.environ.get("BENCH_FORCED_MATMUL", "1") == "1"
            and jax.default_backend() == "cpu"
        ):
            variants.append(
                (HEADLINE, "forced_matmul", "BQUERYD_TPU_FORCE_MATMUL")
            )
        for vcfg, vname, vflag in (
            variants if not wedged else []
        ):
            if vcfg not in completed:
                continue
            from bqueryd_tpu.utils import devicehealth

            v_wedge_start = devicehealth.wedge_marker()
            files, gcols, aggs, where = config_query(vcfg, names)
            os.environ[vflag] = "1"
            try:
                rpc.groupby(files, gcols, aggs, where)  # compile warmup
                v_repeats = []
                for _ in range(REPEATS):
                    t0 = time.perf_counter()
                    v_result = rpc.groupby(files, gcols, aggs, where)
                    v_repeats.append(
                        (
                            time.perf_counter() - t0,
                            getattr(rpc, "last_call_timings", None),
                        )
                    )
                v_wall, v_timings = min(v_repeats, key=lambda r: r[0])
                check_result(
                    v_result, base_dfs[vcfg], gcols, aggs,
                    f"{vcfg}+{vname}",
                )
                v_rows = results[vcfg]["rows"]
                results[f"{vcfg}_{vname}"] = {
                    "rows": v_rows,
                    "groups": results[vcfg]["groups"],
                    "framework_wall_s": round(v_wall, 4),
                    "cold_wall_s": None,
                    "reference_shaped_wall_s": results[vcfg][
                        "reference_shaped_wall_s"
                    ],
                    "rows_per_sec": round(v_rows / v_wall, 1),
                    "speedup": round(
                        results[vcfg]["reference_shaped_wall_s"]
                        / v_wall,
                        3,
                    ),
                    "phase_timings": v_timings,
                    "backend_wedged": devicehealth.window_dirty(
                        v_wedge_start
                    ),
                }
                print(
                    f"[bench] {vcfg}+{vname}: {v_wall:.3f}s "
                    f"(default route was "
                    f"{results[vcfg]['framework_wall_s']:.3f}s)",
                    file=sys.stderr,
                    flush=True,
                )
            except Exception as exc:
                # route variants are supplementary evidence, never the
                # reason the whole benchmark reports failure
                print(
                    f"[bench] {vname} variant failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )
            finally:
                # clear only — restoring a caller-pre-set flag here would
                # contaminate the LATER variants (e.g. a pre-set PALLAS=1
                # leaking into the forced_matmul measurement); the outer
                # finally restores every prior after the whole loop
                os.environ.pop(vflag, None)

        # planner config: the adaptive (plan-driven, calibration-fed) route
        # vs the static fan-out (BQUERYD_TPU_PLANNER=0) on the headline +
        # highcard configs — the main-loop numbers ARE the adaptive route
        # (planner on by default) — plus per-config regret accounting
        # (adaptive wall minus the best measured static wall, including the
        # forced-matmul route where that route is legal), the strategy the
        # workers actually compiled, and a plan-time pruning probe whose
        # filter no shard can match.
        planner_detail = {}
        if os.environ.get("BENCH_PLANNER", "1") == "1" and not wedged:
            controller_node = nodes[0]
            # the matmul route's backend guard: on CPU backends (no
            # FORCE_MATMUL here — bench pops it) forced-matmul is not a
            # legal static route, so it never enters best-static and the
            # regret gate compares adaptive vs plain static only
            matmul_legal = jax.default_backend() != "cpu"
            for pcfg in ("sharded", "highcard"):
                if pcfg not in completed:
                    continue
                files, gcols, aggs, where = config_query(pcfg, names)
                # adaptive and static measured BACK-TO-BACK, interleaved per
                # repeat: the main-loop adaptive wall was taken minutes
                # earlier under different cache/clock conditions, which made
                # an identical-program comparison read as a route difference
                try:
                    a_walls, s_walls = [], []
                    rpc.groupby(files, gcols, aggs, where)  # warmup
                    # more repeats than the headline configs: adaptive and
                    # static compile to the SAME program on backends that
                    # normalize hints, so the comparison is noise-bounded —
                    # a loose min reads scheduler jitter as a route delta
                    a_strategies = None

                    def one_adaptive():
                        nonlocal a_strategies
                        t0 = time.perf_counter()
                        result = rpc.groupby(files, gcols, aggs, where)
                        a_walls.append(time.perf_counter() - t0)
                        # captured INSIDE the loop: after the interleave the
                        # client's last_call_strategies belongs to the
                        # static (PLANNER=0) run, whose hints are all auto
                        a_strategies = getattr(
                            rpc, "last_call_strategies", None
                        )
                        return result

                    def one_static():
                        os.environ["BQUERYD_TPU_PLANNER"] = "0"
                        try:
                            t0 = time.perf_counter()
                            result = rpc.groupby(files, gcols, aggs, where)
                            s_walls.append(time.perf_counter() - t0)
                        finally:
                            os.environ.pop("BQUERYD_TPU_PLANNER", None)
                        return result

                    # pairs alternate order (adaptive-first / static-first),
                    # same as the obs section: always measuring adaptive
                    # first systematically charged it whatever cost the
                    # previous pair's tail left behind (GC, page cache churn)
                    # — the r8 highcard "regret" of 0.55 s on an
                    # identical-program backend was exactly that bias
                    for i in range(max(REPEATS, 5)):
                        if i % 2 == 0:
                            a_result = one_adaptive()
                            s_result = one_static()
                        else:
                            s_result = one_static()
                            a_result = one_adaptive()
                    import statistics as _stats

                    adaptive_wall = min(a_walls)
                    static_wall = min(s_walls)
                    adaptive_median = _stats.median(a_walls)
                    static_median = _stats.median(s_walls)
                    check_result(
                        a_result, base_dfs[pcfg], gcols, aggs,
                        f"{pcfg}+adaptive",
                    )
                    check_result(
                        s_result, base_dfs[pcfg], gcols, aggs,
                        f"{pcfg}+static",
                    )
                except Exception as exc:
                    print(
                        f"[bench] planner variant {pcfg} failed: {exc!r}",
                        file=sys.stderr,
                        flush=True,
                    )
                    continue
                # what the workers actually compiled for the last adaptive
                # repeat (effective_strategy, satellite: hints used to
                # normalize silently and nothing could tell what ran)
                strategies = a_strategies or {}
                effective = [
                    v for v in (strategies.get("effective") or {}).values()
                ]
                chosen = (
                    max(set(effective), key=effective.count)
                    if effective else None
                )
                from bqueryd_tpu.plan import calibrate as calibrate_mod

                calib_stats = calibrate_mod.store().stats()
                forced_wall = results.get(
                    f"{pcfg}_forced_matmul", {}
                ).get("framework_wall_s")
                # best measured STATIC route: the PLANNER=0 wall always;
                # the forced-matmul wall only where that route is legal
                static_routes = {"static": static_wall}
                if forced_wall is not None and matmul_legal:
                    static_routes["forced_matmul"] = forced_wall
                best_static = min(static_routes.values())
                planner_detail[pcfg] = {
                    "adaptive_wall_s": round(adaptive_wall, 4),
                    "main_loop_wall_s": results[pcfg]["framework_wall_s"],
                    "static_wall_s": round(static_wall, 4),
                    # the forced-matmul variant wall (measured above when the
                    # route flag applies): the regression the planner path
                    # must keep unreachable
                    "forced_matmul_wall_s": forced_wall,
                    "chosen_strategy": chosen,
                    "strategy_hints": dict(strategies.get("hints") or {}),
                    "calibration_samples": calib_stats["samples_total"],
                    "calibration_cells": calib_stats["cells"],
                    # regret: adaptive wall minus the best measured static
                    # wall (negative = the calibrated route beat every
                    # static one); the gate below asserts <= 10% wherever
                    # the matmul route is legal
                    "best_static_wall_s": round(best_static, 4),
                    "regret_s": round(adaptive_wall - best_static, 4),
                    "regret_gate_applies": matmul_legal,
                    "regret_within_10pct": bool(
                        adaptive_wall <= 1.10 * best_static
                    ),
                    # noise-robust twin: paired-alternated medians.  On
                    # hint-normalizing backends (CPU: adaptive and static
                    # run the IDENTICAL program) milli-scale walls are
                    # noise-dominated, so the every-config gate requires
                    # BOTH the min AND the median comparison to exceed 10%
                    # before calling a regression (the r8 highcard regret —
                    # 0.55 s systematic, 45% — fails both; one-sided
                    # scheduler noise fails at most one)
                    "adaptive_median_s": round(adaptive_median, 4),
                    "static_median_s": round(static_median, 4),
                    "regret_median_s": round(
                        adaptive_median - static_median, 4
                    ),
                    "median_regret_within_10pct": bool(
                        adaptive_median <= 1.10 * static_median
                    ),
                    "noise_robust_within_10pct": bool(
                        adaptive_wall <= 1.10 * static_wall
                        or adaptive_median <= 1.10 * static_median
                    ),
                }
                print(
                    f"[bench] planner {pcfg}: adaptive {adaptive_wall:.3f}s "
                    f"vs static {static_wall:.3f}s "
                    f"(best static {best_static:.3f}s, regret "
                    f"{adaptive_wall - best_static:+.3f}s, chosen "
                    f"{chosen}, {calib_stats['samples_total']} calibration "
                    f"samples)",
                    file=sys.stderr,
                    flush=True,
                )
            try:
                before_pruned = controller_node.counters[
                    "plan_pruned_shards"
                ]
                before_disp = controller_node.counters["dispatched_shards"]
                probe = rpc.groupby(
                    names,
                    ["passenger_count"],
                    [["fare_amount", "sum", "fare_amount"]],
                    # PULocationID tops out at 265: every shard's min/max
                    # stats exclude this, so the planner must dispatch NOTHING
                    [["PULocationID", ">", 10_000]],
                )
                planner_detail["prune_probe"] = {
                    "plan_pruned_shards": controller_node.counters[
                        "plan_pruned_shards"
                    ] - before_pruned,
                    "dispatched_shards": controller_node.counters[
                        "dispatched_shards"
                    ] - before_disp,
                    "result_rows": int(len(probe)),
                }
                print(
                    f"[bench] prune probe: "
                    f"{planner_detail['prune_probe']}",
                    file=sys.stderr,
                    flush=True,
                )
            except Exception as exc:
                print(
                    f"[bench] prune probe failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )
            planner_detail["plan_counters"] = dict(controller_node.counters)
            planner_detail["note"] = (
                "adaptive = calibration-fed planner (measured kernel walls "
                "refine the heuristic; matmul promotions bind only inside "
                "the kernel guards).  On CPU backends the matmul route is "
                "not legal (backend guard, forced_matmul excluded from "
                "best_static) and surviving hints normalize to the static "
                "program, so regret there is run-to-run noise; the regret "
                "gate certifies adaptive <= 1.10x best-static wherever the "
                "matmul route IS legal"
            )
            # THE GATE (satellite): adaptive must stay within 10% of the
            # best measured static route on every config where the matmul
            # route is legal — the calibrated planner may never leave the
            # forced-matmul-sized win on the table again.  BENCH_PLANNER_
            # GATE=0 records without asserting (probe runs).
            if os.environ.get("BENCH_PLANNER_GATE", "1") == "1":
                for pcfg, entry in planner_detail.items():
                    if not isinstance(entry, dict):
                        continue
                    if entry.get("regret_gate_applies"):
                        assert entry.get("regret_within_10pct"), (
                            f"planner regret gate: {pcfg} adaptive "
                            f"{entry['adaptive_wall_s']}s exceeds 1.10x best "
                            f"static {entry['best_static_wall_s']}s "
                            f"(regret {entry['regret_s']}s)"
                        )
                    if "noise_robust_within_10pct" in entry:
                        # this gate applies EVERYWHERE (highcard included):
                        # a SYSTEMATIC adaptive regression shows in both
                        # the min and the paired-alternated median; it must
                        # not exceed 10% in both at once
                        assert entry.get("noise_robust_within_10pct"), (
                            f"planner regret gate (all configs): {pcfg} "
                            f"adaptive min {entry['adaptive_wall_s']}s / "
                            f"median {entry['adaptive_median_s']}s both "
                            f"exceed 1.10x static "
                            f"(min {entry['static_wall_s']}s, median "
                            f"{entry['static_median_s']}s)"
                        )

        # observability: registry snapshots bracket a headline groupby wall
        # (perf regressions come with phase attribution for free — the
        # histogram delta IS the phase breakdown of the measured queries),
        # plus the metrics hot-path overhead gate: spans + histogram
        # observes must stay under 2% of the adaptive wall.  Soft by
        # default (recorded + loudly printed; CPU-backend walls are noisy);
        # BENCH_OBS_STRICT=1 hard-asserts.
        obs_detail = {}
        if (
            os.environ.get("BENCH_OBSERVABILITY", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            from bqueryd_tpu import obs as obs_mod

            controller_node, worker_node = nodes[0], nodes[1]
            files, gcols, aggs, where = config_query(HEADLINE, names)
            try:
                obs_detail["registry_before"] = {
                    "counters": dict(controller_node.counters),
                    "controller_histograms":
                        controller_node.metrics.histogram_snapshot(),
                    "worker_histograms":
                        worker_node.metrics.histogram_snapshot(),
                }
                rpc.groupby(files, gcols, aggs, where)  # warmup
                on_walls, off_walls = [], []
                # paired walls are CONTEXT, not the gate: per-pair deltas on
                # this class of shared box swing ±500 ms at a 1.1 s wall
                # (measured), so no wall comparison can resolve the ~0.2 ms
                # true cost.  Pairs alternate order (on-first / off-first)
                # to cancel the measured ordering bias.
                traced_id = None
                for i in range(max(REPEATS, 10)):

                    def one(enabled):
                        obs_mod.set_enabled(enabled)
                        try:
                            t0 = time.perf_counter()
                            rpc.groupby(files, gcols, aggs, where)
                            return time.perf_counter() - t0
                        finally:
                            obs_mod.set_enabled(True)

                    if i % 2 == 0:
                        on_walls.append(one(True))
                        # from an ENABLED call: disabled calls store no
                        # timeline, their last_trace_id resolves to None
                        traced_id = rpc.last_trace_id
                        off_walls.append(one(False))
                    else:
                        off_walls.append(one(False))
                        on_walls.append(one(True))
                        traced_id = rpc.last_trace_id
                import statistics

                on_wall, off_wall = min(on_walls), min(off_walls)
                deltas = [a - b for a, b in zip(on_walls, off_walls)]
                paired_delta_pct = (
                    statistics.median(deltas)
                    / statistics.median(off_walls) * 100.0
                )
                # THE GATE: deterministic microcost of the per-query obs
                # work (span recording sized from the real sample trace,
                # the worker/controller histogram observes + family
                # lookups, timeline assembly), as a fraction of the
                # measured adaptive wall.  This is what "<2% overhead"
                # can actually certify on a noisy box.
                sample = controller_node.trace_store.get(traced_id) or {}
                n_spans = max(len(sample.get("spans", [])), 8)
                scratch = obs_mod.MetricsRegistry()
                K = 2000
                t0 = time.perf_counter()
                for _ in range(K):
                    rec = obs_mod.SpanRecorder(
                        trace_id="bench" * 6, node="bench"
                    )
                    for _s in range(n_spans - 1):
                        rec.record("phase", time.time(), 0.01)
                    exported = rec.export()
                    sorted(exported, key=lambda s: s["start_ts"])
                    for name in ("a", "b", "c"):
                        scratch.histogram(
                            "bqueryd_tpu_scratch_seconds", "x",
                            labels={"phase": name},
                        ).observe(0.01)
                    scratch.histogram(
                        "bqueryd_tpu_scratch_total_seconds", "x"
                    ).observe(0.01)
                per_query_obs_s = (time.perf_counter() - t0) / K
                hot_path_pct = (
                    per_query_obs_s / statistics.median(on_walls) * 100.0
                )
                obs_detail["registry_after"] = {
                    "counters": dict(controller_node.counters),
                    "controller_histograms":
                        controller_node.metrics.histogram_snapshot(),
                    "worker_histograms":
                        worker_node.metrics.histogram_snapshot(),
                }
                # one assembled waterfall as evidence the trace path is live
                obs_detail["sample_trace"] = controller_node.trace_store.get(
                    traced_id
                )
                obs_detail["metrics_on_wall_s"] = round(on_wall, 4)
                obs_detail["metrics_off_wall_s"] = round(off_wall, 4)
                obs_detail["paired_wall_delta_pct"] = round(
                    paired_delta_pct, 2
                )
                obs_detail["hot_path_cost_ms"] = round(
                    per_query_obs_s * 1e3, 3
                )
                obs_detail["overhead_pct"] = round(hot_path_pct, 3)
                within = hot_path_pct <= 2.0
                obs_detail["overhead_within_2pct"] = within
                print(
                    f"[bench] observability overhead: hot path "
                    f"{per_query_obs_s*1e3:.2f} ms/query = "
                    f"{hot_path_pct:.3f}% of the adaptive wall "
                    f"(paired wall delta {paired_delta_pct:+.2f}%, "
                    f"noise context)"
                    + ("" if within else "  ** OVER THE 2% BUDGET **"),
                    file=sys.stderr,
                    flush=True,
                )
                assert within, (
                    f"metrics hot path costs {per_query_obs_s*1e3:.2f} ms "
                    f"per query = {hot_path_pct:.2f}% of the adaptive "
                    f"wall (budget: 2%)"
                )
            except Exception as exc:
                obs_mod.set_enabled(True)
                if isinstance(exc, AssertionError):
                    raise  # the hot-path budget gate is deterministic: fail
                print(
                    f"[bench] observability section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # slo/autopsy: per-query critical-path attribution on the sharded
        # config — coverage >= 95% of the wall (BENCH_SLO_GATE=0 records
        # without asserting), an rpc.autopsy round trip including the
        # client_deserialize fold, the deadline-margin histogram, the
        # combined spans+attribution overhead vs the 2% budget, and one run
        # under the PR-8 kill-worker chaos plan whose autopsy must carry
        # retry/backoff segments that sum consistently with the wall
        slo_detail = {}
        if (
            os.environ.get("BENCH_SLO", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            from bqueryd_tpu import chaos as chaos_mod
            from bqueryd_tpu import obs as obs_mod
            from bqueryd_tpu.obs import slo as slo_mod

            gate_on = os.environ.get("BENCH_SLO_GATE", "1") == "1"
            try:
                controller_node = nodes[0]
                files, gcols, aggs, where = config_query(HEADLINE, names)
                coverages, sample = [], None
                for _ in range(max(REPEATS, 3)):
                    rpc.groupby(files, gcols, aggs, where, deadline=120)
                    record = rpc.autopsy(rpc.last_trace_id)
                    assert record is not None, "autopsy round trip failed"
                    # the client fold extended the record with its own
                    # deserialize wall
                    assert "client_deserialize" in record["segments"]
                    total = (
                        sum(record["segments"].values())
                        + record["unattributed_s"]
                    )
                    assert abs(total - record["wall_s"]) < 1e-3, (
                        "attribution segments must sum to the wall"
                    )
                    coverages.append(record["coverage"])
                    sample = record
                slo_detail["coverage_per_run"] = [
                    round(c, 4) for c in coverages
                ]
                slo_detail["coverage_min"] = round(min(coverages), 4)
                slo_detail["sample_autopsy"] = sample
                # deadline-margin histogram: the deadline=120 queries above
                # landed in the default class with positive margins
                margin_hist = controller_node.slo._hist[
                    slo_mod.DEFAULT_CLASS
                ]
                slo_detail["margin_histogram"] = margin_hist.snapshot()
                slo_detail["margin_observations"] = margin_hist.count
                slo_detail["slo_snapshot"] = controller_node.slo.snapshot()
                slo_detail["timeline_entries"] = len(
                    controller_node.timeline_ring
                )

                # attribution microcost on the REAL sample timeline (same
                # method as the obs gate: deterministic per-query work as a
                # fraction of the measured wall), combined with the span/
                # histogram cost already measured above — the 2% budget now
                # covers the whole enabled path, attribution included
                sample_timeline = controller_node.trace_store.get(
                    sample["trace_id"]
                ) or {"spans": []}
                scratch_slo = slo_mod.SLOTracker(obs_mod.MetricsRegistry())
                K = 2000
                t0 = time.perf_counter()
                for _ in range(K):
                    slo_mod.attribute(sample_timeline)
                    scratch_slo.record("default", 0.5)
                attrib_s = (time.perf_counter() - t0) / K
                headline_wall = (
                    obs_detail.get("metrics_on_wall_s") or sample["wall_s"]
                )
                attrib_pct = attrib_s / headline_wall * 100.0
                combined_pct = attrib_pct + (
                    obs_detail.get("overhead_pct") or 0.0
                )
                slo_detail["attribution_cost_ms"] = round(attrib_s * 1e3, 3)
                slo_detail["attribution_overhead_pct"] = round(attrib_pct, 3)
                slo_detail["combined_overhead_pct"] = round(combined_pct, 3)
                slo_detail["combined_within_2pct"] = combined_pct <= 2.0

                # chaos leg: kill-worker over a fresh 2-replica cluster —
                # the recovery (failed attempt wait + backoff + failover
                # dispatch) must be ATTRIBUTED, not mystery wall
                chaos_rpc = controller2 = None
                nodes2, threads2 = [], []
                try:
                    (
                        chaos_rpc, controller2, _workers2, nodes2, threads2,
                    ) = _chaos_cluster(n_workers=2)
                    chaos_mod.arm({
                        "seed": 81,
                        "faults": [{
                            "site": "worker.execute",
                            "action": "die_after_ack",
                            "match": {"verb": "groupby"},
                            "times": 1,
                        }],
                    })
                    chaos_rpc.groupby(files, gcols, aggs, where)
                    chaos_record = chaos_rpc.autopsy(
                        chaos_rpc.last_trace_id
                    )
                    chaos_mod.disarm()
                    assert chaos_record is not None, (
                        "chaos-leg autopsy round trip failed"
                    )
                    total = (
                        sum(chaos_record["segments"].values())
                        + chaos_record["unattributed_s"]
                    )
                    slo_detail["chaos_kill_worker"] = {
                        "ok": chaos_record["ok"],
                        "wall_s": chaos_record["wall_s"],
                        "coverage": chaos_record["coverage"],
                        "segments": chaos_record["segments"],
                        "attempts": len(chaos_record["attempts"]),
                        "retry_backoff_s": chaos_record["segments"].get(
                            "retry_backoff", 0.0
                        ),
                        "sum_consistent": abs(
                            total - chaos_record["wall_s"]
                        ) < 1e-3,
                        "failover_dispatches": controller2.counters[
                            "failover_dispatches"
                        ],
                    }
                finally:
                    chaos_mod.disarm()
                    for node in nodes2:
                        node.running = False
                    for t in threads2:
                        t.join(timeout=5)
                    if chaos_rpc is not None:
                        chaos_rpc._close_socket()

                print(
                    f"[bench] slo: coverage min "
                    f"{slo_detail['coverage_min']:.3f}, attribution "
                    f"{attrib_s * 1e3:.2f} ms/query "
                    f"(combined {combined_pct:.3f}% of wall), chaos "
                    f"kill-worker coverage "
                    f"{slo_detail['chaos_kill_worker']['coverage']:.3f} "
                    f"with {slo_detail['chaos_kill_worker']['attempts']} "
                    "attempts",
                    file=sys.stderr, flush=True,
                )
                if gate_on:
                    assert slo_detail["coverage_min"] >= 0.95, (
                        f"attribution coverage {slo_detail['coverage_min']} "
                        "below the 0.95 contract on the sharded config"
                    )
                    assert slo_detail["margin_observations"] > 0, (
                        "deadline-margin histogram never populated"
                    )
                    assert combined_pct <= 2.0, (
                        f"obs + attribution cost {combined_pct:.2f}% of the "
                        "wall (budget: 2%)"
                    )
                    ck = slo_detail["chaos_kill_worker"]
                    assert ck["ok"], "chaos-leg query failed"
                    assert ck["sum_consistent"], (
                        "chaos autopsy segments do not sum to the wall"
                    )
                    assert ck["retry_backoff_s"] > 0, (
                        "kill-worker recovery shows no retry_backoff "
                        "segment"
                    )
                    assert ck["attempts"] >= 2, (
                        "kill-worker autopsy lists no failover attempt"
                    )
            except Exception as exc:
                if gate_on:
                    # same contract as the armed chaos gate: a setup crash
                    # (cluster bring-up, malformed autopsy) must fail the
                    # armed gate, not record slo={} and read as green
                    raise
                print(
                    f"[bench] slo section failed: {exc!r}",
                    file=sys.stderr, flush=True,
                )

        # profiling: the compile-side story of the whole bench run — the
        # program registry (per-shape compiles, jit-cache reuse, HLO
        # cost_analysis FLOPs/bytes) plus the persistent compile cache's
        # hit rate, so a "this round got slower" diff can distinguish
        # kernel regressions from cold-cache compile walls
        profiling_detail = {}
        if os.environ.get("BENCH_PROFILING", "1") == "1":
            try:
                from bqueryd_tpu.obs import profile as profile_mod

                snap = profile_mod.profiler().snapshot(max_programs=16)
                jit_total = snap["jit_cache_hits"] + snap["jit_cache_misses"]
                persist_total = (
                    snap["persistent_cache_hits"]
                    + snap["persistent_cache_misses"]
                )
                profiling_detail = {
                    "jit_cache_hits": snap["jit_cache_hits"],
                    "jit_cache_misses": snap["jit_cache_misses"],
                    "jit_cache_hit_rate": (
                        round(snap["jit_cache_hits"] / jit_total, 4)
                        if jit_total else None
                    ),
                    "persistent_cache_hits": snap["persistent_cache_hits"],
                    "persistent_cache_misses":
                        snap["persistent_cache_misses"],
                    "persistent_cache_hit_rate": (
                        round(
                            snap["persistent_cache_hits"] / persist_total, 4
                        )
                        if persist_total else None
                    ),
                    "compile_count": sum(
                        snap["compile_seconds"]["counts"]
                    ),
                    "compile_seconds_sum": round(
                        snap["compile_seconds"]["sum"], 4
                    ),
                    "total_flops": sum(
                        p["flops"] or 0 for p in snap["programs"]
                    ),
                    "programs_tracked": snap["programs_tracked"],
                    # the registry itself: per-shape compiles/calls/costs
                    "programs": snap["programs"],
                    "compile_cache": profile_mod.compile_cache_info(),
                    "runtime": profile_mod.runtime_versions(),
                }
                print(
                    f"[bench] profiling: {profiling_detail['compile_count']} "
                    f"compiles ({profiling_detail['compile_seconds_sum']:.2f}s"
                    f" total), jit hit rate "
                    f"{profiling_detail['jit_cache_hit_rate']}, persistent "
                    f"cache hit rate "
                    f"{profiling_detail['persistent_cache_hit_rate']}",
                    file=sys.stderr,
                    flush=True,
                )
            except Exception as exc:
                print(
                    f"[bench] profiling section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # pipeline: the staged shard pipeline + working-set cache story —
        # (1) serialized-stage baseline (BQUERYD_TPU_PIPELINE_THREADS=1) vs
        # the default pipelined wall on the multi-shard headline with COLD
        # data caches (warm compiled programs: the pipeline overlaps
        # decode/align/H2D, which warm data caches would skip entirely),
        # interleaved per repeat; (2) the decode+align+H2D-vs-kernel
        # overlap ratio from the stage busy clocks bracketing one cold
        # query; (3) working-set / result / storage-decode cache hit rates;
        # (4) the codes-cache probe: a warm repeat with a DIFFERENT measure
        # column must run ZERO factorize calls (align+codes segment hits).
        pipeline_detail = {}
        if (
            os.environ.get("BENCH_PIPELINE", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            from bqueryd_tpu.parallel import pipeline as pipeline_mod

            files, gcols, aggs, where = config_query(HEADLINE, names)
            try:
                rpc.groupby(files, gcols, aggs, where)  # warmup
                ser_walls, pipe_walls = [], []
                for _ in range(max(REPEATS, 3)):
                    os.environ["BQUERYD_TPU_PIPELINE_THREADS"] = "1"
                    try:
                        _clear_worker_caches(worker)
                        t0 = time.perf_counter()
                        rpc.groupby(files, gcols, aggs, where)
                        ser_walls.append(time.perf_counter() - t0)
                    finally:
                        os.environ.pop("BQUERYD_TPU_PIPELINE_THREADS", None)
                    _clear_worker_caches(worker)
                    t0 = time.perf_counter()
                    rpc.groupby(files, gcols, aggs, where)
                    pipe_walls.append(time.perf_counter() - t0)
                serialized_wall = min(ser_walls)
                pipelined_wall = min(pipe_walls)

                # (2) overlap ratio measured over one cold pipelined query
                pipeline_mod.clock().reset()
                _clear_worker_caches(worker)
                t0 = time.perf_counter()
                rpc.groupby(files, gcols, aggs, where)
                overlap_wall = time.perf_counter() - t0
                stages = pipeline_mod.clock().snapshot()
                busy = stages["busy_seconds"]
                host_busy = sum(
                    busy.get(s, 0.0) for s in ("decode", "align", "h2d")
                )
                pipeline_detail.update(
                    {
                        # honest labeling: THREADS=1 serializes EVERY host
                        # stage, including the per-shard alignment fan-out
                        # and depth-2 column overlap that predate the
                        # unified pipeline — this is the fully-serialized-
                        # stages baseline (the ISSUE's methodology), not a
                        # strict before/after of PR 4 alone
                        "baseline_note": (
                            "serialized = BQUERYD_TPU_PIPELINE_THREADS=1 "
                            "(all host stages serial, incl. pre-existing "
                            "align fan-out)"
                        ),
                        "threads_default": pipeline_mod.pipeline_threads(),
                        "serialized_wall_s": round(serialized_wall, 4),
                        "pipelined_wall_s": round(pipelined_wall, 4),
                        "pipeline_speedup": round(
                            serialized_wall / pipelined_wall, 3
                        ),
                        "overlap_wall_s": round(overlap_wall, 4),
                        "host_stage_busy_s": round(host_busy, 4),
                        "kernel_busy_s": round(
                            busy.get("kernel", 0.0), 4
                        ),
                        # host-stage busy / wall (the ISSUE's definition).
                        # Busy sums across pool threads, so a high ratio
                        # proves CONCURRENT host-stage execution (intra-
                        # stage fan-out and cross-stage overlap both
                        # count); the serialized-vs-pipelined walls above
                        # are what isolate the pipeline's net win.
                        "overlap_ratio": round(
                            host_busy / overlap_wall, 4
                        ) if overlap_wall > 0 else None,
                        "stage_busy_seconds": {
                            k: round(v, 4) for k, v in busy.items()
                        },
                        "stage_calls": stages["calls"],
                    }
                )

                # (4) codes-cache probe: warm repeat, different measure
                rpc.groupby(files, gcols, aggs, where)  # re-warm caches
                executor = worker._mesh_executor
                ws_before = (
                    executor.workingset.stats() if executor else None
                )
                import bqueryd_tpu.ops as ops_mod

                fact_calls = {"n": 0}
                real_factorize = ops_mod.factorize

                def counting_factorize(*a, **k):
                    fact_calls["n"] += 1
                    return real_factorize(*a, **k)

                ops_mod.factorize = counting_factorize
                try:
                    t0 = time.perf_counter()
                    rpc.groupby(
                        files, gcols,
                        [["trip_distance", "sum", "dist_sum"]], where,
                    )
                    probe_wall = time.perf_counter() - t0
                finally:
                    ops_mod.factorize = real_factorize
                ws_after = (
                    executor.workingset.stats() if executor else None
                )
                pipeline_detail["codes_probe"] = {
                    "factorize_calls": fact_calls["n"],
                    "wall_s": round(probe_wall, 4),
                    "codes_hit": (
                        ws_after["codes"]["hits"]
                        - ws_before["codes"]["hits"]
                        if ws_before else None
                    ),
                    "align_hit": (
                        ws_after["align"]["hits"]
                        - ws_before["align"]["hits"]
                        if ws_before else None
                    ),
                }

                # (3) cache hit rates at end of run
                def rates(stats):
                    total = stats["hits"] + stats["misses"]
                    return {
                        **stats,
                        "hit_rate": (
                            round(stats["hits"] / total, 4) if total else None
                        ),
                    }

                from bqueryd_tpu.storage.ctable import column_cache_stats

                pipeline_detail["caches"] = {
                    "workingset": (
                        {
                            seg: rates(s)
                            for seg, s in ws_after.items()
                            if isinstance(s, dict)
                        }
                        if ws_after else None
                    ),
                    "pressure_evictions": (
                        ws_after.get("pressure_evictions")
                        if ws_after else None
                    ),
                    "storage_decode": rates(column_cache_stats()),
                    # the worker result cache is disabled for the bench
                    # (start_cluster) so repeats measure the engine; its
                    # counters are recorded anyway for completeness
                    # (identity check: an EMPTY BytesCappedCache is
                    # len()-falsy, and False means env-disabled)
                    "results": (
                        rates(worker._result_cache.stats())
                        if worker._result_cache not in (None, False)
                        else None
                    ),
                }
                print(
                    f"[bench] pipeline: serialized {serialized_wall:.3f}s "
                    f"vs pipelined {pipelined_wall:.3f}s "
                    f"({serialized_wall / pipelined_wall:.2f}x), overlap "
                    f"ratio {pipeline_detail['overlap_ratio']}, codes "
                    f"probe {pipeline_detail['codes_probe']}",
                    file=sys.stderr,
                    flush=True,
                )
            except Exception as exc:
                print(
                    f"[bench] pipeline section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # merge: the device-resident distributed merge story — (1) D2H bytes
        # of the span-owned collective merge (devicemerge counters) vs the
        # BQUERYD_TPU_DEVICE_MERGE=0 host-gather baseline's payload bytes
        # over ZeroMQ (the controller's reply_payload_bytes counter — proved
        # from metrics, not instrumentation); (2) THE GATE: device-merge
        # final-table D2H bytes <= 10% of the host-merge payload bytes on
        # the sharded config; (3) parity probes across the fuzz-shaped
        # query mix (int sum, multi-agg incl. float mean, count_distinct):
        # =1 vs =0 must agree bit-identically on integer aggregates and to
        # reassociation ulps on float ones.
        merge_detail = {}
        if (
            os.environ.get("BENCH_MERGE", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            from bqueryd_tpu.parallel import devicemerge as dm_mod

            controller_node = nodes[0]
            files, gcols, aggs, where = config_query(HEADLINE, names)
            # Pin the switch explicitly for each leg (a pre-set =0 in the
            # operator's environment must not turn the device leg into a
            # second host leg that trivially passes the gate), and restore
            # whatever the operator had afterwards.
            prior_dm = os.environ.get("BQUERYD_TPU_DEVICE_MERGE")
            try:
                # (1a) device-mode bytes: counter delta across one query on
                # the device-merge route
                os.environ["BQUERYD_TPU_DEVICE_MERGE"] = "1"
                rpc.groupby(files, gcols, aggs, where)  # warm
                before = dm_mod.stats().snapshot()
                headline_dev = rpc.groupby(files, gcols, aggs, where)
                after = dm_mod.stats().snapshot()
                device_fetched = (
                    after["bytes_fetched"]["device"]
                    - before["bytes_fetched"]["device"]
                )
                d2h_saved = (
                    after["d2h_bytes_saved"] - before["d2h_bytes_saved"]
                )
                device_modes = dict(rpc.last_call_merge_modes or {})

                # (1b) host-gather baseline: kill switch off => per-shard
                # dispatch, partial payloads over zmq, client-side hostmerge;
                # payload bytes from the controller counter
                os.environ["BQUERYD_TPU_DEVICE_MERGE"] = "0"
                rpc.groupby(files, gcols, aggs, where)  # warm the route
                c0 = controller_node.counters["reply_payload_bytes"]
                t0 = time.perf_counter()
                headline_host = rpc.groupby(files, gcols, aggs, where)
                host_wall = time.perf_counter() - t0
                host_payload_bytes = (
                    controller_node.counters["reply_payload_bytes"] - c0
                )
                host_modes = dict(rpc.last_call_merge_modes or {})
                os.environ["BQUERYD_TPU_DEVICE_MERGE"] = "1"
                t0 = time.perf_counter()
                rpc.groupby(files, gcols, aggs, where)
                device_wall = time.perf_counter() - t0

                # (3) parity probes: =1 vs =0 across the query mix.  The
                # count_distinct probe is ROUTE COVERAGE, not a mesh-merge
                # parity check: count_distinct is not in MERGEABLE_OPS, so
                # both legs take the per-shard host route — it proves the
                # kill switch leaves non-mergeable queries undisturbed.
                probes = {
                    "sharded_sum": (files, gcols, aggs, where),
                    "multikey_multiagg": config_query("multikey", names),
                    "count_distinct": (
                        files,
                        ["passenger_count"],
                        [["payment_type", "count_distinct", "nd"]],
                        [],
                    ),
                }
                parity = {}
                for pname, (pf, pg, pa, pw) in probes.items():
                    if pname == "sharded_sum":
                        # the byte-measurement legs above already ran this
                        # exact query on both routes — reuse their results
                        r_dev, r_host = headline_dev, headline_host
                    else:
                        os.environ["BQUERYD_TPU_DEVICE_MERGE"] = "1"
                        r_dev = rpc.groupby(pf, pg, pa, pw)
                        os.environ["BQUERYD_TPU_DEVICE_MERGE"] = "0"
                        r_host = rpc.groupby(pf, pg, pa, pw)
                    r_dev = r_dev.sort_values(pg).reset_index(drop=True)
                    r_host = r_host.sort_values(pg).reset_index(drop=True)
                    identical = len(r_dev) == len(r_host)
                    max_rel = 0.0
                    # a row-count mismatch is already a parity failure; the
                    # per-column compare must not run on mismatched shapes
                    # (np.allclose would raise, and the generic except would
                    # swallow THE GATE instead of failing it)
                    for col in (r_dev.columns if identical else ()):
                        a = r_dev[col].to_numpy()
                        b = r_host[col].to_numpy()
                        if a.dtype.kind in "iub":
                            identical = identical and bool(
                                np.array_equal(a, b)
                            )
                        else:
                            af = a.astype(np.float64)
                            bf = b.astype(np.float64)
                            identical = identical and bool(
                                np.allclose(af, bf, rtol=1e-9,
                                            equal_nan=True)
                            )
                            with np.errstate(all="ignore"):
                                rel = np.nanmax(
                                    np.abs(af - bf)
                                    / np.maximum(np.abs(bf), 1e-30)
                                ) if len(af) else 0.0
                            max_rel = max(max_rel, float(rel))
                    parity[pname] = {
                        "rows": int(len(r_dev)),
                        "identical": bool(identical),
                        "float_max_rel_err": max_rel,
                    }

                ratio = (
                    device_fetched / host_payload_bytes
                    if host_payload_bytes else None
                )
                merge_detail = {
                    "device_bytes_fetched": int(device_fetched),
                    "d2h_bytes_saved": int(d2h_saved),
                    "host_payload_bytes": int(host_payload_bytes),
                    "d2h_ratio": (
                        None if ratio is None else round(ratio, 4)
                    ),
                    "within_10pct": (
                        None if ratio is None else bool(ratio <= 0.10)
                    ),
                    "device_wall_s": round(device_wall, 4),
                    "host_gather_wall_s": round(host_wall, 4),
                    "device_merge_modes": device_modes,
                    "host_merge_modes": host_modes,
                    "parity": parity,
                    "note": (
                        "device = span-owned reduce-scatter merge, final "
                        "table only fetched; host = DEVICE_MERGE=0 "
                        "host-gather (per-shard payloads over zmq, "
                        "hostmerge client-side).  Gate: device D2H <= 10% "
                        "of host payload bytes; integer aggregates "
                        "bit-identical across modes, floats to "
                        "reassociation ulps"
                    ),
                }
                print(
                    f"[bench] merge: device D2H {device_fetched} B vs "
                    f"host-gather payloads {host_payload_bytes} B "
                    f"(ratio {merge_detail['d2h_ratio']}, saved "
                    f"{d2h_saved} B), parity "
                    f"{ {k: v['identical'] for k, v in parity.items()} }",
                    file=sys.stderr,
                    flush=True,
                )
                # THE GATE (BENCH_MERGE_GATE=0 records without asserting)
                if os.environ.get("BENCH_MERGE_GATE", "1") == "1":
                    # zero device bytes means the headline query never rode
                    # the mesh-merge path at all — a 0-byte "pass" measures
                    # nothing (same sanity assert as the CI smoke)
                    assert device_fetched > 0, (
                        "device-merge leg recorded no merge bytes: the "
                        "headline query did not take the device-merge path"
                    )
                    assert merge_detail["within_10pct"], (
                        f"device-merge D2H bytes {device_fetched} exceed "
                        f"10% of host-merge payload bytes "
                        f"{host_payload_bytes}"
                    )
                    for pname, entry in parity.items():
                        assert entry["identical"], (
                            f"merge parity failed on {pname}: {entry}"
                        )
            except AssertionError:
                raise  # the merge gate is deterministic: fail the bench
            except Exception as exc:
                print(
                    f"[bench] merge section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )
            finally:
                if prior_dm is None:
                    os.environ.pop("BQUERYD_TPU_DEVICE_MERGE", None)
                else:
                    os.environ["BQUERYD_TPU_DEVICE_MERGE"] = prior_dm

        # concurrency: shared-scan multi-query fusion — a closed-loop
        # multi-client swarm of DISTINCT-but-compatible queries (same shard
        # set + group keys; every query carries its own never-repeated
        # filter threshold, the traffic shape PR-1's bit-identical dedup
        # can never fuse) measured with the admission window ON (compatible
        # queries fuse into shared-scan bundles: one decode/align/H2D pass,
        # one mesh program per micro-batch) vs OFF (every query pays its
        # own scan).  Gates: fused QPS >= 1.3x unfused, per-query results
        # bit-identical to window-0 execution (ints exact, floats to
        # reassociation ulps), plan_shared_dispatches > 0, and the PR-1
        # identical-query dedup probe actually firing.
        concurrency_detail = {}
        if (
            os.environ.get("BENCH_CONCURRENCY", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            controller_node, worker_node = nodes[0], nodes[1]
            coord_url = controller_node.store.url
            n_clients = int(os.environ.get("BENCH_CONC_CLIENTS", "8"))
            rounds = int(os.environ.get("BENCH_CONC_ROUNDS", "4"))
            window_ms = os.environ.get("BENCH_CONC_WINDOW_MS", "40")
            try:
                import statistics as _stats

                import bqueryd_tpu.ops as ops_mod
                from bqueryd_tpu.storage.ctable import column_cache_stats

                def swarm_queries(base, step=0.013):
                    """n_clients x rounds distinct-but-compatible queries:
                    same shards + group key, unique filter threshold each —
                    no two queries identical, so nothing short of
                    shared-scan fusion can share their work."""
                    return [
                        [
                            (
                                names,
                                ["passenger_count"],
                                [["fare_amount", "sum", "fare_sum"]],
                                [[
                                    "trip_distance", ">",
                                    round(
                                        base + step * (ci * rounds + k), 4
                                    ),
                                ]],
                            )
                            for k in range(rounds)
                        ]
                        for ci in range(n_clients)
                    ]

                # (0) PR-1 identical-dedup probe: two concurrent IDENTICAL
                # calls at window 0 must fuse into one dispatch — the
                # sharing path that predates bundles, proven live here
                # (plan_shared_dispatches sat at 0 in every bench round
                # because the main loop is single-client sequential)
                c_before = dict(controller_node.counters)
                probe_q = [
                    [(
                        names, ["passenger_count"],
                        [["fare_amount", "sum", "fare_amount"]],
                        [["trip_distance", ">", 9.37]],
                    )]
                ] * 2
                _conc_swarm(coord_url, probe_q, None)
                identical_probe = {
                    "shared_dispatches": (
                        controller_node.counters["plan_shared_dispatches"]
                        - c_before["plan_shared_dispatches"]
                    ),
                    "dispatched_shards": (
                        controller_node.counters["dispatched_shards"]
                        - c_before["dispatched_shards"]
                    ),
                }

                counting = {"n": 0}
                real_factorize = ops_mod.factorize

                def counting_factorize(*a, **k):
                    counting["n"] += 1
                    return real_factorize(*a, **k)

                def leg_stats_before():
                    ws = (
                        worker_node._mesh_executor.workingset.stats()
                        if worker_node._mesh_executor else None
                    )
                    return {
                        "counters": dict(controller_node.counters),
                        "decode_misses": column_cache_stats()["misses"],
                        "factorize": counting["n"],
                        "codes_misses": (
                            ws["codes"]["misses"] if ws else 0
                        ),
                    }

                def leg_stats_delta(before, n_queries):
                    ws = (
                        worker_node._mesh_executor.workingset.stats()
                        if worker_node._mesh_executor else None
                    )
                    counters = controller_node.counters
                    return {
                        "decode_misses_per_query": round(
                            (
                                column_cache_stats()["misses"]
                                - before["decode_misses"]
                            ) / n_queries, 3,
                        ),
                        "factorize_calls_per_query": round(
                            (counting["n"] - before["factorize"])
                            / n_queries, 3,
                        ),
                        "codes_misses_per_query": round(
                            (
                                (ws["codes"]["misses"] if ws else 0)
                                - before["codes_misses"]
                            ) / n_queries, 3,
                        ),
                        "shared_dispatches": (
                            counters["plan_shared_dispatches"]
                            - before["counters"]["plan_shared_dispatches"]
                        ),
                        "bundles": (
                            counters["plan_bundles"]
                            - before["counters"]["plan_bundles"]
                        ),
                        "bundled_queries": (
                            counters["plan_bundled_queries"]
                            - before["counters"]["plan_bundled_queries"]
                        ),
                        "dispatched_shards": (
                            counters["dispatched_shards"]
                            - before["counters"]["dispatched_shards"]
                        ),
                    }

                # warmup (disjoint thresholds): compiles the bundle program
                # for the swarm's member count — cold compile walls belong
                # to warmup, not the measured legs
                _conc_swarm(
                    coord_url,
                    [
                        [q] for q in [
                            c[0] for c in swarm_queries(base=20.0)
                        ]
                    ],
                    window_ms,
                )

                ops_mod.factorize = counting_factorize
                try:
                    queries = swarm_queries(base=0.5)
                    n_queries = n_clients * rounds

                    # (1) fused leg: window ON — compatible queries bundle
                    before_f = leg_stats_before()
                    fused_results, fused_walls, fused_elapsed = _conc_swarm(
                        coord_url, queries, window_ms
                    )
                    fused_delta = leg_stats_delta(before_f, n_queries)

                    # (2) unfused leg: window 0 on the SAME query set —
                    # bit-identical PR-8 behaviour, every query its own
                    # scan (codes folds stay cold: the fused leg shares the
                    # UNMASKED codes entry and creates no per-query folds)
                    before_u = leg_stats_before()
                    unfused_results, unfused_walls, unfused_elapsed = (
                        _conc_swarm(coord_url, queries, None)
                    )
                    unfused_delta = leg_stats_delta(before_u, n_queries)
                finally:
                    ops_mod.factorize = real_factorize

                parity_bad = []
                max_rel = 0.0
                for qkey, fused_frame in fused_results.items():
                    identical, rel = _conc_frames_match(
                        fused_frame, unfused_results[qkey],
                        ["passenger_count"],
                    )
                    max_rel = max(max_rel, rel)
                    if not identical:
                        parity_bad.append(qkey)

                pct = _pct  # module-level helper, shared with the capacity ramp

                qps_fused = n_queries / fused_elapsed
                qps_unfused = n_queries / unfused_elapsed
                concurrency_detail = {
                    "clients": n_clients,
                    "rounds": rounds,
                    "queries_per_leg": n_queries,
                    "window_ms": float(window_ms),
                    "fused_qps": round(qps_fused, 2),
                    "unfused_qps": round(qps_unfused, 2),
                    "qps_ratio": round(qps_fused / qps_unfused, 3),
                    "fused_p50_s": round(pct(fused_walls, 0.50), 4),
                    "fused_p99_s": round(pct(fused_walls, 0.99), 4),
                    "unfused_p50_s": round(pct(unfused_walls, 0.50), 4),
                    "unfused_p99_s": round(pct(unfused_walls, 0.99), 4),
                    "fused": fused_delta,
                    "unfused": unfused_delta,
                    "identical_probe": identical_probe,
                    "parity_identical": not parity_bad,
                    "parity_float_max_rel_err": max_rel,
                    "note": (
                        "fused = BQUERYD_TPU_BATCH_WINDOW_MS window on: "
                        "compatible concurrent queries share one "
                        "decode/align/H2D pass and one mesh program per "
                        "micro-batch; unfused = window 0 (PR-8 behaviour). "
                        "Same distinct-query set both legs; gate: fused "
                        "QPS >= 1.3x unfused, per-query parity ints "
                        "bit-exact / floats to reassociation ulps, "
                        "shared_dispatches > 0"
                    ),
                }
                print(
                    f"[bench] concurrency: fused {qps_fused:.1f} qps vs "
                    f"unfused {qps_unfused:.1f} qps "
                    f"({qps_fused / qps_unfused:.2f}x), "
                    f"bundles {fused_delta['bundles']}, shared "
                    f"{fused_delta['shared_dispatches']}, parity "
                    f"{not parity_bad}, identical probe {identical_probe}",
                    file=sys.stderr,
                    flush=True,
                )
                # THE GATE (BENCH_CONCURRENCY_GATE=0 records without
                # asserting — probe runs on noisy boxes)
                if os.environ.get("BENCH_CONCURRENCY_GATE", "1") == "1":
                    assert not parity_bad, (
                        f"shared-scan parity failed for {parity_bad[:4]} "
                        f"(float_max_rel_err {max_rel})"
                    )
                    assert fused_delta["shared_dispatches"] > 0, (
                        "fused leg recorded no shared dispatches: the "
                        "window never formed a bundle"
                    )
                    assert identical_probe["shared_dispatches"] > 0, (
                        f"PR-1 identical-query dedup never fired: "
                        f"{identical_probe}"
                    )
                    assert qps_fused >= 1.3 * qps_unfused, (
                        f"fused QPS {qps_fused:.1f} < 1.3x unfused "
                        f"{qps_unfused:.1f}"
                    )
            except AssertionError:
                raise  # the concurrency gate is deterministic: fail the bench
            except Exception as exc:
                if os.environ.get("BENCH_CONCURRENCY_GATE", "1") == "1":
                    raise
                print(
                    f"[bench] concurrency section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # operators: the operator-DAG executor's per-operator sharded
        # walls + correctness gates (join/topk/window parity vs pandas,
        # sketch error <= the documented alpha, plain-DAG bit-identity)
        operators_detail = {}
        if (
            os.environ.get("BENCH_OPERATORS", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            try:
                operators_detail = run_operators_section(names, rpc)
            except AssertionError:
                raise  # the operators gate is deterministic: fail the bench
            except Exception as exc:
                if os.environ.get("BENCH_OPERATORS_GATE", "1") == "1":
                    # same contract as the chaos/slo/capacity gates: a
                    # setup crash must fail the armed gate, not record
                    # operators={} and read as green
                    raise
                print(
                    f"[bench] operators section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # ingest: streaming append + delta maintenance + chunk pruning —
        # the PR-14 acceptance gates (delta >= 3x cold with parity, filter
        # decode <= 25% of chunks bit-identical, append-while-querying
        # chaos zero-failed) over the section's OWN dataset/clusters
        ingest_detail = {}
        if (
            os.environ.get("BENCH_INGEST", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            try:
                ingest_detail = run_ingest_section()
            except AssertionError:
                raise  # the ingest gate is deterministic: fail the bench
            except Exception as exc:
                if os.environ.get("BENCH_INGEST_GATE", "1") == "1":
                    # same contract as the chaos/slo/capacity gates: a
                    # setup crash must fail the armed gate, not record
                    # ingest={} and read as green
                    raise
                print(
                    f"[bench] ingest section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # serving: semantic serving layer (PR 16) — zipf swarm QPS with
        # rollup + subsumption answers vs the forced-recompute kill
        # switch, parity and bit-identical kill-switch gates, over the
        # section's OWN dataset/cluster (the main clusters pin SERVE=0)
        serving_detail = {}
        if (
            os.environ.get("BENCH_SERVING", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            try:
                serving_detail = run_serving_section()
            except AssertionError:
                raise  # the serving gate is deterministic: fail the bench
            except Exception as exc:
                if os.environ.get("BENCH_SERVING_GATE", "1") == "1":
                    raise
                print(
                    f"[bench] serving section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # chaos: the zero-failed-query degradation gate — scripted
        # kill-worker / drop-reply / wedge-device / redis-partition
        # scenarios over fresh 2-replica clusters of the same dataset,
        # results diffed against a fault-free run (ints bit-exact, floats
        # reassociation-ulp), failover counters proving the path ran.
        # With BQUERYD_TPU_FAULT_PLAN unset (popped above), every
        # injection site in the MAIN measurements above was a no-op.
        chaos_detail = {}
        if (
            os.environ.get("BENCH_CHAOS", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            try:
                chaos_detail = run_chaos_section(names)
            except AssertionError:
                raise  # the chaos gate is deterministic: fail the bench
            except Exception as exc:
                if os.environ.get("BENCH_CHAOS_GATE", "1") == "1":
                    # the gate's assertions live inside run_chaos_section —
                    # a setup crash (cluster bring-up timeout, baseline
                    # burst failure) must fail the armed gate, not record
                    # chaos={} and read as green
                    raise
                print(
                    f"[bench] chaos section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # capacity: the fleet capacity model's load ramp — an open-loop
        # offered-QPS sweep on the live cluster gating the predicted
        # saturation knee against the measured throughput plateau (±25%),
        # the shadow advisor's flip to scale_up at saturation (and
        # silence at low load), model coverage/drift, and the combined
        # observability overhead budget with the model enabled
        capacity_detail = {}
        if (
            os.environ.get("BENCH_CAPACITY", "1") == "1"
            and not wedged
            and HEADLINE in completed
        ):
            try:
                capacity_detail = run_capacity_section(
                    names, nodes[0], nodes[0].store.url,
                    slo_combined_pct=slo_detail.get(
                        "combined_overhead_pct"
                    ),
                )
            except AssertionError:
                raise  # the capacity gate's assertions are deliberate
            except Exception as exc:
                if os.environ.get("BENCH_CAPACITY_GATE", "1") == "1":
                    # same contract as the chaos/slo gates: a setup crash
                    # must fail the armed gate, not record capacity={}
                    # and read as green
                    raise
                print(
                    f"[bench] capacity section failed: {exc!r}",
                    file=sys.stderr,
                    flush=True,
                )

        # -- static-analysis guard: suite runtime + per-family finding
        # counts (proves the full pass stays interactive — a few seconds —
        # and that the tree the bench measured was lint-clean)
        static_analysis_detail = {}
        try:
            from bqueryd_tpu.analysis import run_suite as _analysis_suite

            _ar = _analysis_suite(
                root=os.path.dirname(os.path.abspath(__file__))
            )
            static_analysis_detail = {
                "duration_s": round(_ar.duration_s, 4),
                "files_scanned": _ar.files_scanned,
                "findings_new": len(_ar.new),
                "findings_suppressed": len(_ar.suppressed),
                "findings_baselined": len(_ar.baselined),
                "counts_by_analyzer": dict(_ar.per_analyzer),
                "under_5s": _ar.duration_s < 5.0,
            }
            print(
                f"[bench] static_analysis: {len(_ar.new)} new findings, "
                f"{_ar.files_scanned} files in {_ar.duration_s:.2f}s",
                flush=True,
            )
        except Exception as exc:
            print(
                f"[bench] static_analysis section failed: {exc!r}",
                file=sys.stderr,
                flush=True,
            )

        if HEADLINE in completed:
            head_name = HEADLINE
        elif completed:
            head_name = next(c for c in CONFIGS if c in completed)
        else:
            head_name = None
        head = results.get(head_name, {})
        metric = (
            "taxi_groupby_sum_10shard_e2e_rows_per_sec"
            if head_name == HEADLINE
            else f"taxi_groupby_{head_name}_e2e_rows_per_sec"
            if head_name
            else "taxi_groupby_none_completed"
        )
        # overridable so probe-loop / smoke runs don't clobber the committed
        # round artifact in place (two artifacts fighting over one path will
        # eventually lose the good one)
        detail_path = os.environ.get("BENCH_DETAIL_PATH") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"
        )
        if completed:
            backend_name, n_devices = jax.default_backend(), len(jax.devices())
        else:
            # nothing ever completed — the backend may be wedged mid-init,
            # and jax.default_backend()/jax.devices() on a dead tunnel can
            # block uninterruptibly (see ensure_backend), which would hang
            # the very emission the watchdog exists to protect
            backend_name = os.environ.get("JAX_PLATFORMS") or "uninitialized"
            n_devices = None
        full_detail = {
            "rows": ROWS,
            "shards": SHARDS,
            "backend": backend_name,
            "backend_fell_back": BACKEND_FELL_BACK,
            # true if ANY config saw the wedged latch (its walls are host
            # numbers regardless of the backend label)
            "backend_wedged_any": any(
                r.get("backend_wedged") for r in results.values()
            ),
            "n_devices": n_devices,
            "device_roundtrip_floor_s": (
                None if floor_s is None else round(floor_s, 4)
            ),
            "configs": results,
            # adaptive-vs-static route walls + the plan_pruned_shards /
            # shared-dispatch / admission counters from the controller
            "planner": planner_detail,
            # registry snapshots bracketing the headline walls + the
            # metrics-hot-path overhead gate + a sample trace waterfall
            "observability": obs_detail,
            # critical-path attribution coverage (>=95% gate), the sample
            # autopsy, deadline-margin histogram, combined spans +
            # attribution overhead, and the kill-worker chaos autopsy
            "slo": slo_detail,
            # compile-cache hit rates + the per-shape program registry with
            # cost_analysis FLOPs (obs.profile)
            "profiling": profiling_detail,
            # serialized-vs-pipelined walls, stage busy clocks + overlap
            # ratio, working-set / storage / result cache hit rates, and
            # the zero-factorize codes-cache probe
            "pipeline": pipeline_detail,
            # device-resident merge: span-merge D2H bytes vs the
            # DEVICE_MERGE=0 host-gather payload bytes, the <=10% gate,
            # and the =1 vs =0 parity probes
            "merge": merge_detail,
            # shared-scan multi-query fusion: closed-loop swarm QPS window
            # on vs off, per-query parity, amortization counters, and the
            # PR-1 identical-dedup probe
            "concurrency": concurrency_detail,
            # operator-DAG executor: per-operator sharded walls, pandas
            # parity (ints bit-exact), sketch quantile error <= alpha,
            # and the plain-DAG bit-identity probe
            "operators": operators_detail,
            # streaming ingest: delta-refresh speedup vs cold recompute,
            # zone-map chunk-decode fraction + bit-identity, and the
            # append-while-querying chaos parity gate
            "ingest": ingest_detail,
            # semantic serving: zipf-swarm QPS vs forced recompute,
            # rollup/subsume hit mix, parity, kill-switch bit-identity
            "serving": serving_detail,
            # fault-injection scenarios: zero-failed-query gate, result
            # parity vs the fault-free run, failover/hedge counters
            "chaos": chaos_detail,
            # fleet capacity model: load-ramp knee bracket (±25%), shadow
            # advisor flip at saturation, model coverage/drift, and the
            # evaluate microcost inside the observability budget
            "capacity": capacity_detail,
            # suite runtime + per-family finding counts (the bench guard
            # proving the full static pass stays under a few seconds)
            "static_analysis": static_analysis_detail,
            "total_s": round(time.time() - t_start, 1),
        }
        with open(detail_path, "w") as f:
            json.dump(full_detail, f, indent=1)
        print(f"[bench] full detail -> {detail_path}", file=sys.stderr,
              flush=True)
        # the ONE machine-read line: compact (no phase timings — those live
        # in BENCH_DETAIL.json), backend/n_devices up front, printed LAST
        compact_configs = {
            name: (
                {
                    "wall_s": r["framework_wall_s"],
                    "cold_s": r["cold_wall_s"],
                    "base_s": r["reference_shaped_wall_s"],
                    "speedup": r["speedup"],
                }
                if "framework_wall_s" in r
                else r  # timed_out marker
            )
            for name, r in results.items()
        }
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": head.get("rows_per_sec", 0),
                    "unit": "rows/s",
                    "vs_baseline": head.get("speedup", 0),
                    "detail": {
                        "backend": full_detail["backend"],
                        "backend_fell_back": BACKEND_FELL_BACK,
                        "backend_wedged_any": full_detail[
                            "backend_wedged_any"
                        ],
                        "n_devices": full_detail["n_devices"],
                        "rows": ROWS,
                        "shards": SHARDS,
                        "roundtrip_floor_ms": (
                            None
                            if floor_s is None
                            else round(floor_s * 1e3, 1)
                        ),
                        "configs": compact_configs,
                        "plan_pruned_shards": planner_detail.get(
                            "plan_counters", {}
                        ).get("plan_pruned_shards"),
                        "planner_regret_s": (
                            planner_detail.get(HEADLINE) or {}
                        ).get("regret_s"),
                        "chosen_strategy": (
                            planner_detail.get(HEADLINE) or {}
                        ).get("chosen_strategy"),
                        "obs_overhead_pct": obs_detail.get("overhead_pct"),
                        "slo_coverage_min": slo_detail.get("coverage_min"),
                        "slo_combined_overhead_pct": slo_detail.get(
                            "combined_overhead_pct"
                        ),
                        "pipeline_speedup": pipeline_detail.get(
                            "pipeline_speedup"
                        ),
                        "pipeline_overlap_ratio": pipeline_detail.get(
                            "overlap_ratio"
                        ),
                        "merge_d2h_ratio": merge_detail.get("d2h_ratio"),
                        # working-set / storage-decode hit-rate panel: the
                        # cache posture behind the shared-scan economics
                        "workingset_hit_rates": {
                            seg: (stats or {}).get("hit_rate")
                            for seg, stats in {
                                **(
                                    pipeline_detail.get("caches", {}).get(
                                        "workingset"
                                    ) or {}
                                ),
                                "storage_decode": pipeline_detail.get(
                                    "caches", {}
                                ).get("storage_decode"),
                            }.items()
                        } if pipeline_detail.get("caches") else None,
                        "conc_qps_ratio": concurrency_detail.get(
                            "qps_ratio"
                        ),
                        "conc_shared_dispatches": (
                            concurrency_detail.get("fused") or {}
                        ).get("shared_dispatches"),
                        "conc_parity": concurrency_detail.get(
                            "parity_identical"
                        ),
                        "ingest_delta_speedup": (
                            ingest_detail.get("delta") or {}
                        ).get("speedup"),
                        "ingest_decode_fraction": (
                            ingest_detail.get("prune") or {}
                        ).get("decode_fraction"),
                        "ingest_chaos_zero_failed": (
                            (ingest_detail.get("chaos") or {}).get(
                                "failed_queries"
                            ) == 0
                            if ingest_detail.get("chaos") else None
                        ),
                        "chaos_zero_failed": chaos_detail.get(
                            "zero_failed_queries"
                        ),
                        "chaos_failovers": chaos_detail.get(
                            "failover_dispatches_total"
                        ),
                        "capacity_knee_ratio": capacity_detail.get(
                            "knee_ratio"
                        ),
                        "capacity_advisor_flipped": capacity_detail.get(
                            "advisor_flipped_to_scale_up"
                        ),
                        "jit_cache_hit_rate": profiling_detail.get(
                            "jit_cache_hit_rate"
                        ),
                        "compile_seconds_sum": profiling_detail.get(
                            "compile_seconds_sum"
                        ),
                        "total_s": full_detail["total_s"],
                    },
                }
            ),
            flush=True,
        )
    finally:
        # restore the caller's opt-ins even when the variant loop was skipped
        for flag, prior in prior_env.items():
            if prior is not None and flag not in os.environ:
                os.environ[flag] = prior
        for node in nodes:
            node.running = False
        for t in threads:
            t.join(timeout=5)


if __name__ == "__main__":
    sys.exit(main())
